"""Forced splits JSON (ref: serial_tree_learner.cpp:455 ForceSplits)."""
import json

import numpy as np

import lightgbm_tpu as lgb


def test_forced_splits_shape_tree(tmp_path):
    rng = np.random.RandomState(0)
    X = rng.rand(2000, 3).astype(np.float32)
    y = (X[:, 2] > 0.5).astype(np.float32)  # signal on feature 2 only
    fs = {"feature": 0, "threshold": 0.5,
          "left": {"feature": 1, "threshold": 0.3}}
    path = str(tmp_path / "forced.json")
    json.dump(fs, open(path, "w"))
    ds = lgb.Dataset(X, label=y, params={"verbose": -1})
    bst = lgb.train({"objective": "binary", "num_leaves": 8, "verbose": -1,
                     "min_data_in_leaf": 5, "forcedsplits_filename": path},
                    ds, num_boost_round=1)
    t = bst.models[0]
    # node 0 must split feature 0 at ~0.5; node 1 feature 1 at ~0.3 —
    # neither would be chosen by gain (the signal is feature 2)
    assert int(t.split_feature[0]) == 0
    assert abs(float(t.threshold[0]) - 0.5) < 0.05
    assert int(t.split_feature[1]) == 1
    assert abs(float(t.threshold[1]) - 0.3) < 0.05
    # remaining splits are free and find the signal
    used = set(t.split_feature[:t.num_internal].tolist())
    assert 2 in used
    # leaf stats stay consistent with the partition
    total = int(t.leaf_count.sum())
    assert total == 2000
