"""Exclusive Feature Bundling foundations (ref: src/io/dataset.cpp
FindGroups/FastFeatureBundling + dataset.cpp:1265 FixHistogram)."""
import numpy as np

from lightgbm_tpu.ops.efb import (BundleLayout, encode_bundles,
                                  find_bundles, logical_histograms)


def _sparse_data(R=4000, seed=0):
    """Three mutually-exclusive sparse features + one dense feature."""
    rng = np.random.RandomState(seed)
    owner = rng.randint(0, 4, R)       # 3 = no sparse feature active
    bins = np.zeros((R, 4), np.int64)
    for f in range(3):
        m = owner == f
        bins[m, f] = rng.randint(1, 8, int(m.sum()))
    bins[:, 3] = rng.randint(0, 16, R)  # dense
    nb = [8, 8, 8, 16]
    db = [0, 0, 0, 0]
    return bins, nb, db


def test_find_bundles_groups_exclusive_features():
    bins, nb, db = _sparse_data()
    masks = [bins[:, f] != db[f] for f in range(4)]
    bundles = find_bundles(masks, len(bins))
    # the three exclusive sparse features share one bundle; the dense
    # feature stays alone
    sizes = sorted(len(b) for b in bundles)
    assert sizes == [1, 3]
    dense_bundle = [b for b in bundles if 3 in b][0]
    assert dense_bundle == [3]


def test_encode_and_reconstruct_exact():
    bins, nb, db = _sparse_data()
    masks = [bins[:, f] != db[f] for f in range(4)]
    bundles = find_bundles(masks, len(bins))
    layout = BundleLayout(bundles, nb)
    assert layout.num_columns == 2
    enc = encode_bundles(bins, db, layout)

    # histograms over encoded columns with unit weights
    S = 1
    ch = 1
    Bc = max(layout.col_num_bin)
    bh = np.zeros((S, layout.num_columns, Bc, ch))
    for ci in range(layout.num_columns):
        np.add.at(bh[0, ci, :, 0], enc[:, ci], 1.0)
    totals = np.array([[len(bins)]], np.float64)
    logical = logical_histograms(bh, totals, layout, nb, db, 16)

    # must equal the direct per-feature histograms exactly (no conflicts
    # in mutually-exclusive data)
    for f in range(4):
        want = np.zeros(16)
        np.add.at(want, bins[:, f], 1.0)
        np.testing.assert_allclose(logical[0, f, :, 0], want)


def test_conflict_budget_respected():
    rng = np.random.RandomState(1)
    R = 1000
    # two sparse features with ~5% overlap: too many conflicts to bundle
    # at a tight budget
    a = rng.rand(R) < 0.3
    b = rng.rand(R) < 0.3
    masks = [a, b]
    tight = find_bundles(masks, R, max_conflict_rate=0.0001)
    assert sorted(len(x) for x in tight) == [1, 1]
    loose = find_bundles(masks, R, max_conflict_rate=0.2)
    assert sorted(len(x) for x in loose) == [2]


def test_bundled_training_end_to_end():
    """tpu_enable_bundle trains on sparse exclusive features with the
    same quality as the unbundled path."""
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(3)
    R = 4000
    owner = rng.randint(0, 4, R)
    X = np.zeros((R, 4), np.float32)
    for f in range(3):
        m = owner == f
        X[m, f] = rng.rand(int(m.sum())) + 0.5
    X[:, 3] = rng.rand(R)
    y = ((X[:, 0] > 1.0) | (X[:, 1] > 1.2) | (X[:, 3] > 0.8)) \
        .astype(np.float32)
    from sklearn.metrics import roc_auc_score
    aucs = {}
    for bundle in (False, True):
        ds = lgb.Dataset(X, label=y, params={"verbose": -1})
        bst = lgb.train({"objective": "binary", "num_leaves": 15,
                         "verbose": -1, "min_data_in_leaf": 5,
                         "grow_policy": "depthwise", "tpu_engine": "xla",
                         "tpu_enable_bundle": bundle},
                        ds, num_boost_round=10)
        aucs[bundle] = roc_auc_score(y, bst.predict(X))
    assert aucs[True] > 0.97, aucs
    assert abs(aucs[True] - aucs[False]) < 0.01, aucs


def test_bundled_nonzero_mode_routing():
    """A bundled feature whose MOST FREQUENT value is nonzero: routing's
    out-of-window fallback must use the most-frequent bin (where the
    FixHistogram residual lives), not the zero bin."""
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(9)
    R = 4000
    # feature 0: 80% of rows at value 5.0 (nonzero mode), 20% spread
    x0 = np.full(R, 5.0, np.float32)
    spread = rng.rand(R) < 0.2
    x0[spread] = rng.rand(int(spread.sum())).astype(np.float32) * 10
    # feature 1: sparse, exclusive with feature 0's spread region
    x1 = np.zeros(R, np.float32)
    m1 = (~spread) & (rng.rand(R) < 0.2)
    x1[m1] = rng.rand(int(m1.sum())).astype(np.float32) + 1
    X = np.stack([x0, x1], 1)
    y = ((x0 > 5.0) | (x1 > 1.5)).astype(np.float32)
    from sklearn.metrics import roc_auc_score
    aucs = {}
    for bundle in (False, True):
        ds = lgb.Dataset(X, label=y, params={"verbose": -1})
        bst = lgb.train({"objective": "binary", "num_leaves": 15,
                         "verbose": -1, "min_data_in_leaf": 5,
                         "grow_policy": "depthwise", "tpu_engine": "xla",
                         "tpu_enable_bundle": bundle},
                        ds, num_boost_round=10)
        aucs[bundle] = roc_auc_score(y, bst.predict(X))
    assert aucs[True] > 0.95, aucs
    assert abs(aucs[True] - aucs[False]) < 0.02, aucs


def test_fused_engine_with_bundles_matches_unbundled():
    """EFB on the FUSED engine: conflict-free bundling must reproduce the
    unbundled fused trees exactly (routing via bundle-decoded W tables,
    histograms via logical-view reconstruction)."""
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(21)
    n = 3000
    # mutually exclusive sparse features: each row non-default in at most
    # one of the first 6 features
    X = np.zeros((n, 8), np.float32)
    owner = rng.randint(0, 6, n)
    vals = rng.rand(n).astype(np.float32) + 0.5
    X[np.arange(n), owner] = vals
    X[:, 6] = rng.rand(n)          # dense
    X[:, 7] = rng.rand(n)          # dense
    y = ((X[:, 6] + X[:, 0] - X[:, 1] > 0.6)).astype(np.float32)

    common = {"objective": "binary", "num_leaves": 15, "learning_rate": 0.2,
              "verbose": -1, "min_data_in_leaf": 5, "max_bin": 63,
              "tpu_engine": "fused"}
    p_bundled = dict(common, tpu_enable_bundle=True)
    b1 = lgb.Booster(params=p_bundled,
                     train_set=lgb.Dataset(X, label=y))
    assert b1._gbdt.use_bundles and b1._gbdt.use_fused
    assert b1._gbdt.fused_bundle_cols > 0
    b2 = lgb.Booster(params=dict(common, tpu_enable_bundle=False),
                     train_set=lgb.Dataset(X, label=y))
    assert not b2._gbdt.use_bundles
    for _ in range(10):
        b1.update()
        b2.update()
    assert b1.num_trees() == b2.num_trees() == 10
    # FixHistogram computes each feature's most-frequent bin as
    # total - window_sum; the different f32 rounding can flip near-tie
    # splits exactly like the reference's enable_bundle on/off, so the
    # contract is same-quality models, and the count channel (exact
    # integer sums) must agree on the first split
    assert b1.models[0].split_feature[0] == b2.models[0].split_feature[0]
    assert int(b1.models[0].internal_count[0]) == \
        int(b2.models[0].internal_count[0])
    p1, p2 = b1.predict(X), b2.predict(X)
    assert np.abs(p1 - p2).max() < 0.05
    from sklearn.metrics import roc_auc_score
    a1, a2 = roc_auc_score(y, p1), roc_auc_score(y, p2)
    assert abs(a1 - a2) < 0.005 and a1 > 0.9


def test_fused_bundles_with_missing_values():
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(5)
    n = 2000
    X = np.zeros((n, 6), np.float32)
    owner = rng.randint(0, 4, n)
    X[np.arange(n), owner] = rng.rand(n).astype(np.float32) + 0.5
    X[:, 4] = rng.rand(n)
    X[:, 4][::9] = np.nan          # NaN routing through the dense feature
    X[:, 5] = rng.rand(n)
    y = (X[:, 4] > 0.5).astype(np.float32)
    y[np.isnan(X[:, 4])] = 1.0
    common = {"objective": "binary", "num_leaves": 15, "verbose": -1,
              "min_data_in_leaf": 5, "tpu_engine": "fused"}
    b1 = lgb.Booster(params=dict(common, tpu_enable_bundle=True),
                     train_set=lgb.Dataset(X, label=y))
    assert b1._gbdt.use_bundles and b1._gbdt.fused_bundle_cols > 0
    b2 = lgb.Booster(params=dict(common, tpu_enable_bundle=False),
                     train_set=lgb.Dataset(X, label=y))
    for _ in range(8):
        b1.update()
        b2.update()
    p1, p2 = b1.predict(X), b2.predict(X)
    assert np.abs(p1 - p2).max() < 0.05
    from sklearn.metrics import roc_auc_score
    assert roc_auc_score(y, p1) > 0.95


def test_bundle_plane_views_matches_numpy_oracle():
    """ops/fused_level.bundle_plane_views vs the numpy logical-view
    reconstruction (ops/efb.logical_histograms) on random histograms."""
    from lightgbm_tpu.ops.efb import BundleLayout, logical_histograms
    from lightgbm_tpu.ops.fused_level import bundle_plane_views
    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    nb = [4, 3, 5, 6]                      # logical bins per feature
    layout = BundleLayout([[0, 2], [1, 3]], nb)
    Bc = max(layout.col_num_bin)
    Bc_p = 16                              # padded kernel stride
    S, B = 3, 8
    F = 4
    # random bundle histogram [S, C, Bc_p] with zero padding bins
    bh = np.zeros((S, 2, Bc_p), np.float32)
    for c in range(2):
        bh[:, c, :layout.col_num_bin[c]] = rng.rand(
            S, layout.col_num_bin[c]).astype(np.float32)
    # equalize column totals (every row lands in every column)
    tot = bh[:, 0].sum(axis=1)
    bh[:, 1, 0] += tot - bh[:, 1].sum(axis=1)
    mfb = [1, 0, 2, 3]
    flat_idx = np.zeros((F, B), np.int32)
    valid = np.zeros((F, B), bool)
    for f in range(F):
        ci, off = int(layout.col_of_feat[f]), int(layout.offset_of_feat[f])
        for b in range(nb[f]):
            flat_idx[f, b] = ci * Bc_p + off + b
            valid[f, b] = True
    got = np.asarray(bundle_plane_views(
        jnp.asarray(bh), jnp.asarray(flat_idx), jnp.asarray(valid),
        jnp.asarray(mfb, np.int32)))
    # oracle works on the unpadded [S, C, Bc, 1] layout
    want = logical_histograms(bh[:, :, :Bc, None], tot[:, None], layout,
                              nb, mfb, B)[..., 0]
    assert np.allclose(got, want, atol=1e-5), np.abs(got - want).max()


def test_tolerated_conflicts_reference_semantics():
    """VERDICT r3 #7: the reference bundles with TOLERATED conflicts —
    single_val_max_conflict_cnt = rows/10000, and a feature may join only
    while its own conflicts stay under half its non-zero count (ref:
    dataset.cpp:108-176). A strictly-zero-conflict policy bundles less."""
    rng = np.random.RandomState(3)
    R = 50_000
    # two NEAR-exclusive sparse features: 3 overlapping rows (< R/1e4=5)
    f0 = np.zeros(R, bool)
    f1 = np.zeros(R, bool)
    f0[rng.choice(R, 400, replace=False)] = True
    free = np.where(~f0)[0]
    f1[rng.choice(free, 397, replace=False)] = True
    f1[np.where(f0)[0][:3]] = True     # 3 conflicts
    masks = [f0, f1]
    assert int((f0 & f1).sum()) == 3

    strict = find_bundles(masks, R, max_conflict_rate=0.0)
    tolerant = find_bundles(masks, R, max_conflict_rate=1e-4)
    assert sorted(len(b) for b in strict) == [1, 1]
    assert sorted(len(b) for b in tolerant) == [2]

    # the cnt <= nnz/2 guard: a tiny feature fully inside another's
    # support must NOT be bundled even under a huge budget — its whole
    # signal would be eaten by first-writer-wins encoding
    tiny = np.zeros(R, bool)
    tiny[np.where(f0)[0][:40]] = True  # 40 nnz, all conflicting
    b3 = find_bundles([f0, tiny], R, max_conflict_rate=1.0)
    assert sorted(len(b) for b in b3) == [1, 1]


def test_dense_path_bundle_count_near_ideal():
    """Synthetic sparse-dense mix with a KNOWN exclusivity structure:
    k groups of mutually exclusive features must collapse to ~k columns
    (within 10% of ideal — the FindGroups parity target), despite a few
    tolerated conflicts, through the PRODUCT dense-path setup."""
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(5)
    R, groups, per_group = 30_000, 10, 8
    F = groups * per_group + 2
    X = np.zeros((R, F), np.float32)
    for g in range(groups):
        owner = rng.randint(0, per_group + 3, R)  # some rows empty
        for j in range(per_group):
            m = owner == j
            X[m, g * per_group + j] = rng.rand(int(m.sum())) + 0.5
    X[:, -2:] = rng.rand(R, 2)                    # dense pair
    y = (X[:, 0] + X[:, -1] > 0.8).astype(np.float32)

    ds = lgb.Dataset(X, label=y, params={"verbose": -1})
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbose": -1, "tpu_engine": "fused",
                     "num_iterations": 5}, ds)
    g = bst._gbdt
    assert g.use_bundles
    n_cols = int(np.asarray(g.bundle_cfg.col_of_feat).max()) + 1
    ideal = groups + 2
    assert n_cols <= int(np.ceil(1.1 * ideal)), (n_cols, ideal)

    # quality unchanged: same model surface with bundling disabled
    ds2 = lgb.Dataset(X, label=y, params={"verbose": -1})
    bst2 = lgb.train({"objective": "binary", "num_leaves": 15,
                      "verbose": -1, "tpu_engine": "fused",
                      "enable_bundle": False, "num_iterations": 5}, ds2)
    p1, p2 = bst.predict(X[:2000]), bst2.predict(X[:2000])
    assert float(np.mean((p1 - p2) ** 2)) < 1e-4


def test_bundled_categorical_matches_unbundled():
    """VERDICT r3 #7: categorical features bundle like any feature (the
    reference's FindGroups is dtype-agnostic); routing tests the DECODED
    bin's membership in the categorical bitset on every engine."""
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(9)
    R = 8000
    owner = rng.randint(0, 3, R)
    X = np.zeros((R, 4), np.float64)
    # two mutually exclusive SPARSE features: one numerical, one categorical
    m0 = owner == 0
    X[m0, 0] = rng.rand(int(m0.sum())) + 0.5
    m1 = owner == 1
    X[m1, 1] = rng.randint(1, 6, int(m1.sum()))
    X[:, 2] = rng.rand(R)                      # dense numerical
    X[:, 3] = rng.randint(0, 8, R)             # dense categorical
    y = ((X[:, 0] > 0.9) | (X[:, 1] == 3.0)
         | ((X[:, 3] >= 5) & (X[:, 2] > 0.6))).astype(np.float32)

    def tr(engine, bundle):
        ds = lgb.Dataset(X, label=y, categorical_feature=[1, 3],
                         params={"verbose": -1})
        return lgb.train({"objective": "binary", "num_leaves": 15,
                          "verbose": -1, "tpu_engine": engine,
                          "tpu_enable_bundle": bundle,
                          "enable_bundle": bundle,
                          "num_iterations": 8}, ds)

    for engine in ("fused", "xla"):
        bst_b = tr(engine, True)
        bst_u = tr(engine, False)
        g = bst_b._gbdt
        assert g.use_bundles, engine
        assert g.has_cat
        pb, pu = bst_b.predict(X), bst_u.predict(X)
        # same logical bins + same scans; the FixHistogram residual
        # (default-bin mass = total - window sum) reorders f32 additions
        # vs direct histogramming, so allow float-level drift only
        np.testing.assert_allclose(pb, pu, rtol=1e-3, atol=1e-4), engine
