"""Roofline plane: trace parsing, cost joins, the perf database.

Covers obs/kernelstats.py (malformed Chrome-trace inputs must degrade
to error entries, never exceptions; synthetic traces must attribute
kernel time to anchor spans and join the cost ledger), obs/perfdb.py
(atomic append, schema-gated load, cross-run accumulation), the report
integration (roofline section, decrease-only join-coverage gate,
measured device-time regressions), and one end-to-end CPU train that
closes a real ``profile_dir`` window into joined executables and a
populated perf database row.
"""
import gzip
import json
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs import kernelstats, perfdb
from lightgbm_tpu.obs.report import build_report, compare_reports

_FUSED = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
          "learning_rate": 0.2, "min_data_in_leaf": 5, "verbose": -1,
          "metric": "None", "tpu_engine": "fused", "tpu_megastep": True}


def _data(n=600, f=6, seed=3):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f).astype(np.float32)
    y = (X @ rng.randn(f).astype(np.float32) > 0).astype(np.float32)
    return X, y


def _ds(X, y):
    return lgb.Dataset(X, label=y, params={"max_bin": 63, "verbose": -1})


def _write_trace(root, payload, name="host.trace.json.gz"):
    d = os.path.join(root, "plugins", "profile", "2026_01_01_00_00_00")
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, name)
    if isinstance(payload, bytes):
        with open(path, "wb") as fh:
            fh.write(payload)
    else:
        with gzip.open(path, "wb") as fh:
            fh.write(json.dumps(payload).encode())
    return path


def _synthetic_events():
    """One megastep anchor (0..1000us) with two overlapping kernels
    inside it, one kernel outside it, runtime noise, and python
    frames."""
    return [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        {"ph": "M", "pid": 1, "tid": 1, "name": "thread_name",
         "args": {"name": "python"}},
        {"ph": "M", "pid": 1, "tid": 2, "name": "thread_name",
         "args": {"name": "tf_XLATfrtCpuClient/2"}},
        # kernels FIRST in the stream: attribution must not depend on
        # event order (the two-pass contract)
        {"ph": "X", "pid": 1, "tid": 2, "name": "dot.3",
         "ts": 100.0, "dur": 200.0},
        {"ph": "X", "pid": 1, "tid": 2, "name": "reduce.8",
         "ts": 250.0, "dur": 100.0},
        {"ph": "X", "pid": 1, "tid": 2, "name": "fusion.1",
         "ts": 2000.0, "dur": 50.0},
        {"ph": "X", "pid": 1, "tid": 2,
         "name": "ThunkExecutor::Execute", "ts": 150.0, "dur": 500.0},
        {"ph": "X", "pid": 1, "tid": 1, "name": "$foo.py:1 bar",
         "ts": 120.0, "dur": 10.0},
        {"ph": "X", "pid": 1, "tid": 1, "name": "megastep",
         "ts": 0.0, "dur": 1000.0},
    ]


_COST = [{"event": "cost_executable", "kind": "megastep",
          "signature": "megastep[chunk=2,k=1,eval=False]", "scale": 2,
          "flops": 1.0e6, "hlo_bytes": 2.0e6, "operand_bytes": 4096}]
_COMPILE = [{"event": "compile_executable",
             "signature": "megastep[chunk=2,k=1,eval=False]",
             "compile_ms": 5.0, "operand_bytes": 4096}]


# ------------------------------------------------------------ parsing
class TestParseMalformed:
    def test_missing_dir(self, tmp_path):
        roof = kernelstats.roofline_from_dir(str(tmp_path / "nope"))
        assert roof["join_coverage"] == 0.0
        assert roof["trace_files"] == 0

    def test_truncated_gzip(self, tmp_path):
        good = gzip.compress(json.dumps(
            {"traceEvents": _synthetic_events()}).encode())
        _write_trace(str(tmp_path), good[:len(good) // 3])
        roof = kernelstats.roofline_from_dir(str(tmp_path))
        assert roof["parse_errors"] == 1
        assert roof["join_coverage"] == 0.0

    def test_empty_file(self, tmp_path):
        _write_trace(str(tmp_path), b"")
        roof = kernelstats.roofline_from_dir(str(tmp_path))
        assert roof["parse_errors"] == 1

    def test_not_json(self, tmp_path):
        _write_trace(str(tmp_path), gzip.compress(b"hello world"))
        roof = kernelstats.roofline_from_dir(str(tmp_path))
        assert roof["parse_errors"] == 1
        assert "not JSON" in roof["errors"][0]

    def test_missing_trace_events(self, tmp_path):
        _write_trace(str(tmp_path), {"metadata": {}})
        roof = kernelstats.roofline_from_dir(str(tmp_path))
        assert roof["parse_errors"] == 1
        assert "traceEvents" in roof["errors"][0]

    def test_bad_mixed_with_good(self, tmp_path):
        _write_trace(str(tmp_path), gzip.compress(b"junk"),
                     name="a.trace.json.gz")
        _write_trace(str(tmp_path),
                     {"traceEvents": _synthetic_events()},
                     name="b.trace.json.gz")
        roof = kernelstats.roofline_from_dir(str(tmp_path),
                                             cost_entries=_COST)
        assert roof["parse_errors"] == 1
        assert roof["join_coverage"] == 1.0


class TestAttribution:
    def test_anchor_kernels_union_overlap(self, tmp_path):
        _write_trace(str(tmp_path), {"traceEvents": _synthetic_events()})
        st = kernelstats.parse_profile_dir(str(tmp_path))
        assert st["anchors"]["megastep"]["dispatches"] == 1
        assert st["anchors"]["megastep"]["host_time_us"] == 1000.0
        bk = st["by_kind"]["megastep"]
        # dot.3 (100..300) + reduce.8 (250..350): sum 300, union 250
        assert bk["kernel_time_us"] == pytest.approx(300.0)
        assert bk["device_time_us"] == pytest.approx(250.0)
        assert bk["overlap_us"] == pytest.approx(50.0)
        # fusion.1 is outside the anchor span
        assert st["unattributed_time_us"] == pytest.approx(50.0)
        # runtime noise and python frames never count as kernels
        assert "ThunkExecutor::Execute" not in st["kernels"]
        assert "$foo.py:1 bar" not in st["kernels"]

    def test_join_rates_and_compile(self, tmp_path):
        _write_trace(str(tmp_path), {"traceEvents": _synthetic_events()})
        roof = kernelstats.roofline_from_dir(
            str(tmp_path), cost_entries=_COST, compile_entries=_COMPILE)
        assert roof["join_coverage"] == 1.0
        assert roof["joined_executables"] == 1
        ex = roof["executables"][0]
        assert ex["joined"] and ex["kind"] == "megastep"
        assert ex["timing_source"] == "kernels"
        assert ex["device_time_us_per_dispatch"] == pytest.approx(250.0)
        assert ex["measured_fraction"] == pytest.approx(0.25)
        # analytic work over measured time: 1e6 flops / 250us
        assert ex["achieved_flops_per_s"] == pytest.approx(4.0e9)
        assert ex["achieved_bytes_per_s"] == pytest.approx(8.0e9)
        assert ex["compile_ms"] == 5.0

    def test_unjoinable_signature_coverage_below_one(self, tmp_path):
        _write_trace(str(tmp_path), {"traceEvents": _synthetic_events()})
        roof = kernelstats.roofline_from_dir(
            str(tmp_path),
            cost_entries=[{"kind": "fast_step", "signature": "f[k=1]"}])
        assert roof["join_coverage"] < 1.0
        ex = roof["executables"][0]
        assert not ex["joined"] and ex["signature"] is None

    def test_host_span_fallback(self, tmp_path):
        # anchor with NO kernel events inside: the CPU runtime shape —
        # per-dispatch timing falls back to the host span, labeled
        evs = [e for e in _synthetic_events()
               if e.get("tid") != 2 or e.get("ph") == "M"]
        _write_trace(str(tmp_path), {"traceEvents": evs})
        roof = kernelstats.roofline_from_dir(str(tmp_path),
                                             cost_entries=_COST)
        ex = roof["executables"][0]
        assert ex["timing_source"] == "host_span"
        assert ex["device_time_us_per_dispatch"] == pytest.approx(1000.0)
        assert ex["device_time_us"] == 0.0

    def test_cost_entries_from_events(self):
        evs = _COST + _COMPILE + [{"event": "roofline"}]
        cost, compiles = kernelstats.cost_entries_from_events(evs)
        assert len(cost) == 1 and len(compiles) == 1


# ------------------------------------------------------------- perfdb
class TestPerfDB:
    def test_key_identity(self):
        k1 = perfdb.make_key("m[c=2]", "megastep", "r1024.f6.b63", "cpu")
        k2 = perfdb.make_key("m[c=2]", "megastep", "r1024.f6.b63", "cpu")
        k3 = perfdb.make_key("m[c=2]", "megastep", "r2048.f6.b63", "cpu")
        assert k1["key_id"] == k2["key_id"] != k3["key_id"]

    def test_append_load_accumulate(self, tmp_path):
        path = str(tmp_path / "perf.jsonl")
        key = perfdb.make_key("m[c=2]", "megastep", "r1024.f6.b63",
                              "cpu")
        db = perfdb.PerfDB(path)
        for i in range(2):   # two "runs" appending to the same file
            n = db.append([perfdb.sample(
                key, dispatches=1,
                device_time_us_per_dispatch=100.0 + i,
                source="test")])
            assert n == 1
        loaded = db.load()
        assert len(loaded["rows"]) == 2 and loaded["skipped"] == 0
        summ = perfdb.summarize(loaded["rows"])
        assert summ[0]["samples"] == 2
        assert summ[0]["device_time_us_per_dispatch"]["mean"] == \
            pytest.approx(100.5)

    def test_load_skips_malformed_and_foreign(self, tmp_path):
        path = str(tmp_path / "perf.jsonl")
        key = perfdb.make_key("m", "megastep", "s", "cpu")
        perfdb.PerfDB(path).append([perfdb.sample(
            key, dispatches=1, device_time_us_per_dispatch=1.0)])
        with open(path, "a") as fh:
            fh.write("{not json\n")
            fh.write(json.dumps({"schema": "other.format/9"}) + "\n")
        loaded = perfdb.PerfDB(path).load()
        assert len(loaded["rows"]) == 1 and loaded["skipped"] == 2

    def test_append_never_raises(self, tmp_path):
        # a directory as the db path: open fails, append returns 0
        assert perfdb.PerfDB(str(tmp_path)).append(
            [{"schema": perfdb.SCHEMA}]) == 0
        assert perfdb.PerfDB(str(tmp_path / "x.jsonl")).append([]) == 0

    def test_query_filters(self, tmp_path):
        path = str(tmp_path / "perf.jsonl")
        db = perfdb.PerfDB(path)
        for sig, kind in (("megastep[chunk=2]", "megastep"),
                          ("serve[stacked,bucket=1]", "serve_bucket")):
            db.append([perfdb.sample(
                perfdb.make_key(sig, kind, "s1", "cpu"), dispatches=1,
                device_time_us_per_dispatch=1.0, source="test")])
        assert len(db.query(kind="megastep")) == 1
        # signature matches the pre-'[' base too
        assert len(db.query(signature="serve")) == 1
        assert len(db.query(signature="megastep[chunk=2]")) == 1
        assert len(db.query(kind="fast_step")) == 0

    def test_samples_from_roofline_skips_unjoined(self):
        roof = {"executables": [
            {"joined": True, "signature": "m[c=2]", "kind": "megastep",
             "dispatches": 2, "device_time_us_per_dispatch": 50.0,
             "timing_source": "kernels"},
            {"joined": False, "signature": None, "kind": "fast_step",
             "dispatches": 1, "device_time_us_per_dispatch": 10.0},
        ]}
        rows = perfdb.samples_from_roofline(
            roof, shape_class="s", backend="cpu", source="test")
        assert len(rows) == 1
        assert rows[0]["key"]["signature"] == "m[c=2]"
        assert rows[0]["timing_source"] == "kernels"


# ---------------------------------------------------- report integration
def _report(cov, per_disp):
    roof = {"join_coverage": cov, "joined_executables": 1,
            "anchor_dispatches": 1, "total_device_time_us": per_disp,
            "executables": [
                {"kind": "megastep", "signature": "m[c=2]",
                 "joined": True, "dispatches": 1,
                 "device_time_us": per_disp,
                 "device_time_us_per_dispatch": per_disp,
                 "measured_fraction": 0.5}],
            "kernels": []}
    return build_report({"counters": {"iterations": 8},
                         "gauges": {}}, roofline=roof)


class TestReportIntegration:
    def test_roofline_section(self):
        rep = _report(1.0, 100.0)
        assert rep["roofline"]["join_coverage"] == 1.0
        assert rep["roofline"]["executables"][0]["signature"] == "m[c=2]"

    def test_identical_reports_compare_clean(self):
        rep = _report(1.0, 100.0)
        cmp = compare_reports(rep, rep)
        assert cmp["status"] == "ok" and not cmp["regressions"]

    def test_coverage_drop_flags_rise_does_not(self):
        cmp = compare_reports(_report(1.0, 100.0), _report(0.5, 100.0))
        assert any(e["name"] == "roofline.join_coverage"
                   for e in cmp["regressions"])
        cmp = compare_reports(_report(0.5, 100.0), _report(1.0, 100.0))
        assert not any(e["name"] == "roofline.join_coverage"
                       for e in cmp["regressions"])

    def test_measured_device_time_regression(self):
        cmp = compare_reports(_report(1.0, 100.0), _report(1.0, 300.0),
                              threshold=0.5)
        assert any(e["name"] == "roofline:m[c=2]"
                   for e in cmp["regressions"])
        cmp = compare_reports(_report(1.0, 100.0), _report(1.0, 101.0),
                              threshold=0.5)
        assert not cmp["regressions"]


# ------------------------------------------------------------------ e2e
def test_profile_window_roofline_e2e(tmp_path):
    """A CPU fused-megastep train with a ``profile_dir`` config window
    and ``perf_db`` set: the window close must parse the trace, join
    >= 1 executable at full coverage, record the trace-size gauges,
    surface the roofline in the run report, and append a measured
    sample to the perf database."""
    X, y = _data()
    prof = str(tmp_path / "prof")
    dbpath = str(tmp_path / "perf.jsonl")
    bst = lgb.train(dict(_FUSED, tpu_megastep_iters=4,
                         telemetry_out=str(tmp_path / "tel.jsonl"),
                         profile_dir=prof, perf_db=dbpath),
                    _ds(X, y), num_boost_round=8)
    snap = bst.telemetry()
    g = snap.get("gauges", {})
    # the satellite fix: a window close records what it captured
    assert g.get("profile.trace_files", 0) >= 1
    assert g.get("profile.trace_bytes", 0) > 0
    assert g.get("roofline.join_coverage") == 1.0
    assert g.get("roofline.joined_executables", 0) >= 1
    assert snap["counters"].get("perfdb.samples_written", 0) >= 1
    roof = bst._gbdt._roofline_last
    ex = [r for r in roof["executables"] if r["joined"]]
    assert ex and ex[0]["kind"] == "megastep"
    assert ex[0]["device_time_us_per_dispatch"] > 0
    assert ex[0]["achieved_flops_per_s"] > 0
    # the roofline event (obs_tail's source) made it to the JSONL sink
    events = [json.loads(line)
              for line in open(str(tmp_path / "tel.jsonl"))]
    roofs = [e for e in events if e.get("event") == "roofline"]
    assert roofs and roofs[-1]["join_coverage"] == 1.0
    # the run report carries the roofline section
    rep = bst._gbdt.build_run_report()
    assert rep["roofline"]["join_coverage"] == 1.0
    # the perf database accumulated a measured sample for this shape
    loaded = perfdb.PerfDB(dbpath).load()
    assert loaded["rows"], "perfdb row missing"
    row = loaded["rows"][-1]
    assert row["key"]["kind"] == "megastep"
    assert row["device_time_us_per_dispatch"] > 0
    assert row["key"]["backend"] == "cpu"
