"""Feature-parallel and voting-parallel learners on the virtual CPU mesh.

Mirrors the reference's distributed test strategy (SURVEY §4: localhost
multi-process mockup replaced by an 8-device virtual mesh)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lightgbm_tpu.models.learner import FeatureMeta, grow_tree_depthwise
from lightgbm_tpu.ops.split import SplitParams
from lightgbm_tpu.parallel import make_mesh, shard_rows
from lightgbm_tpu.parallel.mesh import replicate
from lightgbm_tpu.parallel.tree_parallel import (
    make_feature_parallel_grow_fn, make_voting_parallel_grow_fn)


def _data(R=4096, F=8, B=32, seed=0):
    rng = np.random.RandomState(seed)
    bins = rng.randint(0, B - 1, size=(R, F)).astype(np.int32)
    y = ((bins[:, 0] > 14).astype(np.float32)
         + 0.5 * (bins[:, 3] > 20) + 0.1 * rng.randn(R))
    grad = -(y - y.mean()).astype(np.float32)
    hess = np.ones(R, np.float32)
    gh = np.stack([grad, hess, hess], axis=1)
    meta = FeatureMeta(
        num_bin=jnp.full((F,), B, jnp.int32),
        missing_type=jnp.zeros(F, jnp.int32),
        default_bin=jnp.zeros(F, jnp.int32),
        monotone=jnp.zeros(F, jnp.int32))
    return bins, gh, meta


def _single_device_tree(bins, gh, meta, L=15, B=32):
    t, rl = grow_tree_depthwise(
        jnp.asarray(bins), jnp.asarray(gh), meta, jnp.ones(
            (bins.shape[1],), bool), SplitParams(min_data_in_leaf=5),
        L, B, hist_impl="segment")
    return jax.device_get(t), np.asarray(rl)


def test_feature_parallel_matches_single_device():
    """Feature-sharded growth must produce the SAME tree as single-device
    (identical histograms per feature, merged argmax == global argmax)."""
    bins, gh, meta = _data()
    ref_tree, ref_rl = _single_device_tree(bins, gh, meta)

    mesh = make_mesh(8, axis_name="feature")
    grow = make_feature_parallel_grow_fn(
        mesh, SplitParams(min_data_in_leaf=5), 15, 32,
        axis_name="feature")
    tree, rl = grow(jnp.asarray(bins), jnp.asarray(gh), meta,
                    jnp.ones((8,), bool))
    tree = jax.device_get(tree)
    assert int(tree.num_leaves) == int(ref_tree.num_leaves)
    nl = int(tree.num_leaves)
    np.testing.assert_array_equal(tree.split_feature[:nl - 1],
                                  ref_tree.split_feature[:nl - 1])
    np.testing.assert_array_equal(tree.threshold_bin[:nl - 1],
                                  ref_tree.threshold_bin[:nl - 1])
    # leaf totals are summed over a different feature's bins per shard, so
    # values agree only to float32 summation-order tolerance
    np.testing.assert_allclose(tree.leaf_value[:nl],
                               ref_tree.leaf_value[:nl], rtol=1e-4)
    np.testing.assert_array_equal(np.asarray(rl), ref_rl)


def test_voting_parallel_matches_data_parallel_on_small_f():
    """With top_k >= F the vote always includes every feature, so voting
    must reproduce the data-parallel (= single-device) tree exactly."""
    bins, gh, meta = _data()
    ref_tree, ref_rl = _single_device_tree(bins, gh, meta)

    mesh = make_mesh(8)
    grow = make_voting_parallel_grow_fn(
        mesh, SplitParams(min_data_in_leaf=5), 15, 32, top_k=8)
    bins_s = shard_rows(mesh, bins)
    gh_s = shard_rows(mesh, gh)
    meta_r = jax.tree.map(lambda a: replicate(mesh, a), meta,
                          is_leaf=lambda x: x is None)
    tree, rl = grow(bins_s, gh_s, meta_r,
                    replicate(mesh, np.ones(8, bool)))
    tree = jax.device_get(tree)
    nl = int(tree.num_leaves)
    assert nl == int(ref_tree.num_leaves)
    np.testing.assert_array_equal(tree.split_feature[:nl - 1],
                                  ref_tree.split_feature[:nl - 1])
    # psum reduction order differs from the single-device sum
    np.testing.assert_allclose(tree.leaf_value[:nl],
                               ref_tree.leaf_value[:nl], rtol=1e-4)


def test_voting_parallel_restricted_topk_still_learns():
    """With a tight top_k the exchange payload shrinks (2*top_k columns of
    F) and the tree must still find the dominant splits."""
    bins, gh, meta = _data(F=16)
    mesh = make_mesh(8)
    grow = make_voting_parallel_grow_fn(
        mesh, SplitParams(min_data_in_leaf=5), 15, 32, top_k=2)
    tree, _ = grow(shard_rows(mesh, bins), shard_rows(mesh, gh),
                   jax.tree.map(lambda a: replicate(mesh, a), meta,
                                is_leaf=lambda x: x is None),
                   replicate(mesh, np.ones(16, bool)))
    tree = jax.device_get(tree)
    nl = int(tree.num_leaves)
    # sibling histograms are only valid on (parent winners ∩ level
    # winners), so a tight top_k legitimately limits growth — but the
    # dominant splits must be found
    assert nl >= 6
    used = set(tree.split_feature[:nl - 1].tolist())
    assert 0 in used and 3 in used
