"""Categorical split finder vs a numpy re-implementation of the reference
algorithm (feature_histogram.hpp:278-470)."""
import numpy as np
import pytest

import jax.numpy as jnp

from lightgbm_tpu.ops.split import (SplitParams, best_categorical_split_cm,
                                    best_split_cm)


def _leaf_gain(g, h, l1, l2):
    reg = max(0.0, abs(g) - l1)
    sg = np.sign(g) * reg
    return sg * sg / (h + l2)


def _oracle_cat(grad, hess, cnt, nb, p: SplitParams):
    """Best categorical split for ONE (slot, feature) histogram, numpy."""
    eps = 1e-15
    tot_g = grad.sum()
    tot_h = hess.sum() + 2 * eps
    tot_c = cnt.sum()
    gain_shift = _leaf_gain(tot_g, tot_h, p.lambda_l1, p.lambda_l2)
    min_gain_shift = gain_shift + p.min_gain_to_split
    best = (-np.inf, None)

    if nb <= p.max_cat_to_onehot:
        for t in range(1, nb):
            lg, lh, lc = grad[t], hess[t] + eps, cnt[t]
            rg, rh, rc = tot_g - lg, tot_h - lh - eps, tot_c - lc
            if (lc < p.min_data_in_leaf or lh < p.min_sum_hessian_in_leaf
                    or rc < p.min_data_in_leaf
                    or rh < p.min_sum_hessian_in_leaf):
                continue
            gain = (_leaf_gain(lg, lh, p.lambda_l1, p.lambda_l2)
                    + _leaf_gain(rg, rh, p.lambda_l1, p.lambda_l2))
            if gain > min_gain_shift and gain > best[0]:
                best = (gain, {t})
        return best

    l2 = p.lambda_l2 + p.cat_l2
    idx = [t for t in range(1, nb) if cnt[t] >= p.cat_smooth]
    idx.sort(key=lambda t: grad[t] / (hess[t] + p.cat_smooth))
    used = len(idx)
    max_num_cat = min(p.max_cat_threshold, (used + 1) // 2)
    for dir_, start in ((1, 0), (-1, used - 1)):
        sum_g, sum_h, sum_c, grp = 0.0, eps, 0.0, 0.0
        pos = start
        members = []
        for i in range(min(used, max_num_cat)):
            t = idx[pos]
            pos += dir_
            members.append(t)
            sum_g += grad[t]
            sum_h += hess[t]
            sum_c += cnt[t]
            grp += cnt[t]
            if (sum_c < p.min_data_in_leaf
                    or sum_h < p.min_sum_hessian_in_leaf):
                continue
            rc = tot_c - sum_c
            if rc < p.min_data_in_leaf or rc < p.min_data_per_group:
                break
            rh = tot_h - sum_h
            if rh < p.min_sum_hessian_in_leaf:
                break
            if grp < p.min_data_per_group:
                continue
            grp = 0.0
            rg = tot_g - sum_g
            gain = (_leaf_gain(sum_g, sum_h, p.lambda_l1, l2)
                    + _leaf_gain(rg, rh, p.lambda_l1, l2))
            if gain > min_gain_shift and gain > best[0]:
                best = (gain, set(members))
    return best


def _run(grad, hess, cnt, nb, p, F=1):
    S = grad.shape[0]
    B = grad.shape[-1]
    bs = best_categorical_split_cm(
        jnp.asarray(grad), jnp.asarray(hess), jnp.asarray(cnt),
        jnp.full((F,), nb, jnp.int32), jnp.ones((F,), bool), p,
        jnp.zeros((S,), jnp.float32))
    return bs


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_sorted_subset_matches_oracle(seed):
    rng = np.random.RandomState(seed)
    B, nb = 32, 26
    p = SplitParams(min_data_in_leaf=3, min_data_per_group=5, cat_smooth=2.0,
                    cat_l2=1.0, max_cat_to_onehot=4, max_cat_threshold=16)
    grad = np.zeros((1, 1, B), np.float32)
    hess = np.zeros((1, 1, B), np.float32)
    cnt = np.zeros((1, 1, B), np.float32)
    cnt[0, 0, :nb] = rng.randint(0, 40, nb)
    hess[0, 0] = cnt[0, 0] * (0.5 + 0.1 * rng.rand(B))
    grad[0, 0] = rng.randn(B) * cnt[0, 0]
    want_gain, want_set = _oracle_cat(grad[0, 0], hess[0, 0], cnt[0, 0],
                                      nb, p)
    bs = _run(grad, hess, cnt, nb, p)
    if want_set is None:
        assert not bool(bs.cat_flag[0])
        return
    got_set = set(np.nonzero(np.asarray(bs.cat_mask)[0])[0].tolist())
    got_total = float(bs.gain[0]) + (  # add back the shift for comparison
        _leaf_gain(grad[0, 0].sum(), hess[0, 0].sum() + 2e-15,
                   p.lambda_l1, p.lambda_l2) + p.min_gain_to_split)
    assert got_set == want_set, (got_set, want_set)
    np.testing.assert_allclose(got_total, want_gain, rtol=1e-4)


def test_onehot_mode():
    rng = np.random.RandomState(3)
    B, nb = 8, 4
    p = SplitParams(min_data_in_leaf=2, max_cat_to_onehot=6, cat_smooth=1.0)
    grad = np.zeros((1, 1, B), np.float32)
    hess = np.zeros((1, 1, B), np.float32)
    cnt = np.zeros((1, 1, B), np.float32)
    cnt[0, 0, :nb] = [10, 20, 15, 12]
    hess[0, 0, :nb] = [5, 10, 7, 6]
    grad[0, 0, :nb] = [1.0, -8.0, 3.0, 1.5]
    want_gain, want_set = _oracle_cat(grad[0, 0], hess[0, 0], cnt[0, 0],
                                      nb, p)
    bs = _run(grad, hess, cnt, nb, p)
    got_set = set(np.nonzero(np.asarray(bs.cat_mask)[0])[0].tolist())
    assert got_set == want_set


def test_combined_prefers_higher_gain():
    """best_split_cm picks categorical when its gain beats numerical."""
    rng = np.random.RandomState(5)
    B = 16
    S, F = 1, 2
    grad = rng.randn(S, F, B).astype(np.float32) * 5
    hess = np.abs(rng.randn(S, F, B)).astype(np.float32) * 10 + 5
    cnt = np.full((S, F, B), 20.0, np.float32)
    p = SplitParams(min_data_in_leaf=1, cat_smooth=1.0, max_cat_to_onehot=2,
                    max_cat_threshold=8, min_data_per_group=1)
    bs = best_split_cm(
        jnp.asarray(grad), jnp.asarray(hess), jnp.asarray(cnt),
        jnp.full((F,), B, jnp.int32), jnp.zeros((F,), jnp.int32),
        jnp.zeros((F,), jnp.int32), jnp.ones((F,), bool),
        jnp.asarray([False, True]), jnp.zeros((F,), jnp.int32), p,
        jnp.zeros((S,), jnp.float32), has_cat=True)
    assert np.isfinite(float(bs.gain[0]))
    # feature 1 is categorical; if chosen, cat_flag must be set
    if int(bs.feature[0]) == 1:
        assert bool(bs.cat_flag[0])
    else:
        assert not bool(bs.cat_flag[0])
