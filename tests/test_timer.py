"""Section timer subsystem (utils/timer.py — analog of the reference's
TIMETAG Timer, ref: include/LightGBM/utils/common.h:978)."""
import numpy as np

import lightgbm_tpu as lgb
from lightgbm_tpu.utils import log
from lightgbm_tpu.utils.timer import Timer, global_timer


def test_timer_disabled_is_noop():
    t = Timer(enabled=False)
    t.start("x")
    t.stop("x")
    assert t.stats() == {}


def test_timer_accumulates_sections():
    t = Timer(enabled=True)
    with t.section("a"):
        sum(range(1000))
    with t.section("a"):
        pass
    with t.section("b"):
        pass
    s = t.stats()
    assert set(s) == {"a", "b"}
    assert s["a"].total >= 0.0 and s["a"].count == 2
    assert s["b"].count == 1
    t.reset()
    assert t.stats() == {}


def test_timer_reset_clears_open_starts():
    """A section started before reset() must not pollute the next run
    (reset() bumps the generation that invalidates per-thread start
    stacks)."""
    t = Timer(enabled=True)
    t.start("stale")
    t.reset()
    t.stop("stale")     # stale start discarded: no accumulation
    assert t.stats() == {}
    # and a fresh start/stop after the reset still records normally
    t.start("fresh")
    t.stop("fresh")
    assert set(t.stats()) == {"fresh"}


def test_timer_add_and_print_sorted_by_cost():
    t = Timer(enabled=True)
    t.add("cheap", 0.25)
    t.add("costly", 2.0)
    t.add("mid", 1.0)
    lines = []
    level = log.get_log_level()
    log.set_log_level(log.LogLevel.INFO)
    log.register_logger(lines.append)
    try:
        t.print()
    finally:
        log.register_logger(None)
        log.set_log_level(level)
    order = [name for line in lines
             for name in ("costly", "mid", "cheap") if name in line]
    assert order == ["costly", "mid", "cheap"]


def test_training_sections_recorded():
    rng = np.random.RandomState(0)
    X = rng.rand(500, 5).astype(np.float32)
    y = (X[:, 0] > 0.5).astype(np.float32)
    global_timer.enable()
    global_timer.reset()
    try:
        bst = lgb.train({"objective": "binary", "num_leaves": 7,
                         "verbose": -1}, lgb.Dataset(X, label=y),
                        num_boost_round=3)
        bst.predict(X)
        s = global_timer.stats()
        assert "DatasetLoader::Construct" in s
        assert ("GBDT::TrainOneIter" in s
                or "GBDT::TrainOneIterFast" in s)
        assert "Predictor::Predict" in s
        if "GBDT::TrainOneIter" in s:
            # the synchronous driver also feeds the per-phase sections
            # (the pipelined fast path on TPU intentionally does not —
            # its phases overlap and cannot be attributed honestly)
            assert "GBDT::histogram_split" in s
            assert s["GBDT::histogram_split"].count >= 3
    finally:
        global_timer.disable()
        global_timer.reset()
