"""Section timer subsystem (utils/timer.py — analog of the reference's
TIMETAG Timer, ref: include/LightGBM/utils/common.h:978)."""
import numpy as np

import lightgbm_tpu as lgb
from lightgbm_tpu.utils.timer import Timer, global_timer


def test_timer_disabled_is_noop():
    t = Timer(enabled=False)
    t.start("x")
    t.stop("x")
    assert t.stats() == {}


def test_timer_accumulates_sections():
    t = Timer(enabled=True)
    with t.section("a"):
        sum(range(1000))
    with t.section("a"):
        pass
    with t.section("b"):
        pass
    s = t.stats()
    assert set(s) == {"a", "b"} and s["a"] >= 0.0
    t.reset()
    assert t.stats() == {}


def test_training_sections_recorded():
    rng = np.random.RandomState(0)
    X = rng.rand(500, 5).astype(np.float32)
    y = (X[:, 0] > 0.5).astype(np.float32)
    global_timer.enable()
    global_timer.reset()
    try:
        bst = lgb.train({"objective": "binary", "num_leaves": 7,
                         "verbose": -1}, lgb.Dataset(X, label=y),
                        num_boost_round=3)
        bst.predict(X)
        s = global_timer.stats()
        assert "DatasetLoader::Construct" in s
        assert ("GBDT::TrainOneIter" in s
                or "GBDT::TrainOneIterFast" in s)
        assert "Predictor::Predict" in s
    finally:
        global_timer.disable()
        global_timer.reset()
