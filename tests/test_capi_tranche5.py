"""Round-5 C-ABI tranche: the final 20 symbols to 78/78 c_api.h parity.

Exercises each new symbol through ctypes the way an embedding host
would (ref: include/LightGBM/c_api.h signatures; src/c_api.cpp
semantics).
"""
import ctypes
import json

import numpy as np
import pytest

from lightgbm_tpu.native.loader import build_capi


@pytest.fixture(scope="module")
def lib():
    path = build_capi()
    if path is None:
        pytest.skip("no native toolchain")
    lib = ctypes.CDLL(path)
    lib.LGBM_GetLastError.restype = ctypes.c_char_p
    return lib


def _check(lib, rc):
    assert rc == 0, lib.LGBM_GetLastError().decode()


def _make_ds(lib, X, y, params=b"max_bin=63 verbose=-1"):
    X = np.ascontiguousarray(X, np.float64)
    y = np.ascontiguousarray(y, np.float32)
    ds = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateFromMat(
        X.ctypes.data_as(ctypes.c_void_p), 1, X.shape[0], X.shape[1], 1,
        params, None, ctypes.byref(ds)))
    _check(lib, lib.LGBM_DatasetSetField(
        ds, b"label", y.ctypes.data_as(ctypes.c_void_p), len(y), 0))
    return ds


def _train(lib, ds, iters=8,
           params=b"objective=binary num_leaves=15 verbose=-1"):
    bst = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreate(ds, params, ctypes.byref(bst)))
    fin = ctypes.c_int()
    for _ in range(iters):
        _check(lib, lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)))
    return bst


def _data(n=800, f=6, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0.75).astype(np.float32)
    return X, y


# ------------------------------------------------------------- sampling
def test_sample_count_and_indices(lib):
    out = ctypes.c_int()
    _check(lib, lib.LGBM_GetSampleCount(
        1000, b"bin_construct_sample_cnt=300", ctypes.byref(out)))
    assert out.value == 300
    _check(lib, lib.LGBM_GetSampleCount(
        100, b"bin_construct_sample_cnt=300", ctypes.byref(out)))
    assert out.value == 100

    idx = np.zeros(300, np.int32)
    n_out = ctypes.c_int32()
    _check(lib, lib.LGBM_SampleIndices(
        1000, b"bin_construct_sample_cnt=300 data_random_seed=7",
        idx.ctypes.data_as(ctypes.c_void_p), ctypes.byref(n_out)))
    assert n_out.value == 300
    got = idx[:n_out.value]
    # matches the reference-parity LCG stream (utils/random.py)
    from lightgbm_tpu.utils import random as ref_random
    expect = np.asarray(ref_random.Random(7).sample(1000, 300), np.int32)
    np.testing.assert_array_equal(got, expect)
    assert got.min() >= 0 and got.max() < 1000
    assert np.all(np.diff(got) > 0)     # sorted unique, Sample's contract


def test_dump_param_aliases(lib):
    out_len = ctypes.c_int64()
    _check(lib, lib.LGBM_DumpParamAliases(0, ctypes.byref(out_len), None))
    buf = ctypes.create_string_buffer(out_len.value)
    _check(lib, lib.LGBM_DumpParamAliases(
        out_len.value, ctypes.byref(out_len), buf))
    aliases = json.loads(buf.value.decode())
    assert "num_leaves" in aliases
    assert "num_leaf" in aliases["num_leaves"]
    assert "bagging_fraction" in aliases


# ------------------------------------------------------------- logging
def test_register_log_callback(lib):
    lines = []
    CB = ctypes.CFUNCTYPE(None, ctypes.c_char_p)

    @CB
    def collect(msg):
        lines.append(msg.decode())

    _check(lib, lib.LGBM_RegisterLogCallback(collect))
    try:
        from lightgbm_tpu.utils import log
        log.info("tranche5 log callback line")
        assert any("tranche5 log callback line" in ln for ln in lines)
    finally:
        _check(lib, lib.LGBM_RegisterLogCallback(None))
    n = len(lines)
    from lightgbm_tpu.utils import log
    log.info("after unregister")
    assert len(lines) == n


# ------------------------------------- importance / linear / GetPredict
def test_feature_importance_linear_get_predict(lib):
    X, y = _data()
    ds = _make_ds(lib, X, y)
    bst = _train(lib, ds)

    lin = ctypes.c_int()
    _check(lib, lib.LGBM_BoosterGetLinear(bst, ctypes.byref(lin)))
    assert lin.value == 0

    imp = np.zeros(X.shape[1], np.float64)
    _check(lib, lib.LGBM_BoosterFeatureImportance(
        bst, 0, 0, imp.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    assert imp.sum() > 0
    # split importance concentrates on the two informative features
    assert imp[0] + imp[1] > imp[2:].sum()
    imp_gain = np.zeros(X.shape[1], np.float64)
    _check(lib, lib.LGBM_BoosterFeatureImportance(
        bst, 0, 1,
        imp_gain.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    assert not np.allclose(imp, imp_gain)

    # GetPredict(0) == transformed batch prediction on the training data
    need = ctypes.c_int64()
    _check(lib, lib.LGBM_BoosterGetNumPredict(bst, 0, ctypes.byref(need)))
    assert need.value == len(y)
    inner = np.zeros(need.value, np.float64)
    got_len = ctypes.c_int64()
    _check(lib, lib.LGBM_BoosterGetPredict(
        bst, 0, ctypes.byref(got_len),
        inner.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    assert got_len.value == need.value
    Xc = np.ascontiguousarray(X, np.float64)
    batch = np.zeros(len(y), np.float64)
    out_len = ctypes.c_int64()
    _check(lib, lib.LGBM_BoosterPredictForMat(
        bst, Xc.ctypes.data_as(ctypes.c_void_p), 1, len(y), X.shape[1], 1,
        0, 0, -1, b"", ctypes.byref(out_len),
        batch.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    np.testing.assert_allclose(inner, batch, rtol=1e-5, atol=1e-6)

    _check(lib, lib.LGBM_BoosterFree(bst))
    _check(lib, lib.LGBM_DatasetFree(ds))


# --------------------------------------------------- single-row predicts
def test_single_row_mat_and_csr(lib):
    X, y = _data()
    ds = _make_ds(lib, X, y)
    bst = _train(lib, ds)
    Xc = np.ascontiguousarray(X, np.float64)
    batch = np.zeros(len(y), np.float64)
    out_len = ctypes.c_int64()
    _check(lib, lib.LGBM_BoosterPredictForMat(
        bst, Xc.ctypes.data_as(ctypes.c_void_p), 1, len(y), X.shape[1], 1,
        0, 0, -1, b"", ctypes.byref(out_len),
        batch.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))

    one = np.zeros(1, np.float64)
    row = np.ascontiguousarray(X[5], np.float64)
    _check(lib, lib.LGBM_BoosterPredictForMatSingleRow(
        bst, row.ctypes.data_as(ctypes.c_void_p), 1, X.shape[1], 1, 0, 0,
        -1, b"", ctypes.byref(out_len),
        one.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    assert out_len.value == 1
    np.testing.assert_allclose(one[0], batch[5], rtol=1e-9)

    # CSR single row (sparse encoding of the same row)
    nz = np.nonzero(row)[0].astype(np.int32)
    vals = row[nz]
    indptr = np.asarray([0, len(nz)], np.int32)
    _check(lib, lib.LGBM_BoosterPredictForCSRSingleRow(
        bst, indptr.ctypes.data_as(ctypes.c_void_p), 2,
        nz.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        vals.ctypes.data_as(ctypes.c_void_p), 1, 2, len(nz), X.shape[1],
        0, 0, -1, b"", ctypes.byref(out_len),
        one.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    np.testing.assert_allclose(one[0], batch[5], rtol=1e-9)

    # CSR fast path: init once, score several rows
    fc = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterPredictForCSRSingleRowFastInit(
        bst, 0, 0, -1, 1, X.shape[1], b"", ctypes.byref(fc)))
    for i in (0, 17, 203):
        r = np.ascontiguousarray(X[i], np.float64)
        nz = np.nonzero(r)[0].astype(np.int32)
        vals = r[nz]
        indptr = np.asarray([0, len(nz)], np.int32)
        _check(lib, lib.LGBM_BoosterPredictForCSRSingleRowFast(
            fc, indptr.ctypes.data_as(ctypes.c_void_p), 2,
            nz.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            vals.ctypes.data_as(ctypes.c_void_p), 2, len(nz),
            ctypes.byref(out_len),
            one.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
        np.testing.assert_allclose(one[0], batch[i], rtol=1e-9)
    _check(lib, lib.LGBM_FastConfigFree(fc))

    _check(lib, lib.LGBM_BoosterFree(bst))
    _check(lib, lib.LGBM_DatasetFree(ds))


# ------------------------------------------------------ dataset creation
def test_create_from_mats(lib):
    X, y = _data(600, 5)
    a = np.ascontiguousarray(X[:200], np.float64)
    b = np.ascontiguousarray(X[200:], np.float64)
    ptrs = (ctypes.c_void_p * 2)(
        a.ctypes.data_as(ctypes.c_void_p).value,
        b.ctypes.data_as(ctypes.c_void_p).value)
    nrows = np.asarray([200, 400], np.int32)
    ds = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateFromMats(
        2, ptrs, 1, nrows.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        5, 1, b"max_bin=63 verbose=-1", None, ctypes.byref(ds)))
    yc = np.ascontiguousarray(y, np.float32)
    _check(lib, lib.LGBM_DatasetSetField(
        ds, b"label", yc.ctypes.data_as(ctypes.c_void_p), len(y), 0))
    n = ctypes.c_int32()
    _check(lib, lib.LGBM_DatasetGetNumData(ds, ctypes.byref(n)))
    assert n.value == 600
    # trains identically to the single-matrix dataset
    bst = _train(lib, ds, iters=5)
    ds1 = _make_ds(lib, X, y)
    bst1 = _train(lib, ds1, iters=5)
    for h in (bst, bst1):
        pass
    buf_len = ctypes.c_int64()
    _check(lib, lib.LGBM_BoosterSaveModelToString(
        bst, 0, -1, 0, 0, ctypes.byref(buf_len), None))
    s = ctypes.create_string_buffer(buf_len.value)
    _check(lib, lib.LGBM_BoosterSaveModelToString(
        bst, 0, -1, 0, buf_len.value, ctypes.byref(buf_len), s))
    s1 = ctypes.create_string_buffer(buf_len.value)
    _check(lib, lib.LGBM_BoosterSaveModelToString(
        bst1, 0, -1, 0, buf_len.value, ctypes.byref(buf_len), s1))
    assert s.value == s1.value
    for h in (bst, bst1):
        _check(lib, lib.LGBM_BoosterFree(h))
    for d in (ds, ds1):
        _check(lib, lib.LGBM_DatasetFree(d))


def test_create_from_sampled_column_and_push(lib):
    X, y = _data(500, 4, seed=3)
    ncol = 4
    # per-column samples: first 300 rows (the reference samples row ids
    # via LGBM_SampleIndices; any subset works for mapper construction)
    sample_rows = np.arange(300, dtype=np.int32)
    col_data = [np.ascontiguousarray(X[:300, j], np.float64)
                for j in range(ncol)]
    col_idx = [np.ascontiguousarray(sample_rows, np.int32)
               for _ in range(ncol)]
    data_ptrs = (ctypes.POINTER(ctypes.c_double) * ncol)(
        *[c.ctypes.data_as(ctypes.POINTER(ctypes.c_double))
          for c in col_data])
    idx_ptrs = (ctypes.POINTER(ctypes.c_int) * ncol)(
        *[c.ctypes.data_as(ctypes.POINTER(ctypes.c_int))
          for c in col_idx])
    per_col = np.full(ncol, 300, np.int32)
    ds = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateFromSampledColumn(
        data_ptrs, idx_ptrs, ncol,
        per_col.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
        300, 500, b"max_bin=63 verbose=-1", ctypes.byref(ds)))
    # stream all 500 rows in two chunks
    Xc = np.ascontiguousarray(X, np.float64)
    _check(lib, lib.LGBM_DatasetPushRows(
        ds, Xc[:250].ctypes.data_as(ctypes.c_void_p), 1, 250, ncol, 0))
    _check(lib, lib.LGBM_DatasetPushRows(
        ds, Xc[250:].ctypes.data_as(ctypes.c_void_p), 1, 250, ncol, 250))
    yc = np.ascontiguousarray(y, np.float32)
    _check(lib, lib.LGBM_DatasetSetField(
        ds, b"label", yc.ctypes.data_as(ctypes.c_void_p), 500, 0))
    bst = _train(lib, ds, iters=5)
    out = np.zeros(500, np.float64)
    out_len = ctypes.c_int64()
    _check(lib, lib.LGBM_BoosterPredictForMat(
        bst, Xc.ctypes.data_as(ctypes.c_void_p), 1, 500, ncol, 1, 0, 0,
        -1, b"", ctypes.byref(out_len),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    from sklearn.metrics import roc_auc_score
    assert roc_auc_score(y, out) > 0.9
    _check(lib, lib.LGBM_BoosterFree(bst))
    _check(lib, lib.LGBM_DatasetFree(ds))


def test_push_rows_coverage_check(lib):
    """Never-pushed declared rows must fail loudly at finalize, not train
    as zeros (advisor r4 finding)."""
    X, y = _data(300, 4)
    ds_ref = _make_ds(lib, X, y)
    # force construction so it can act as a push reference
    bst0 = _train(lib, ds_ref, iters=1)
    _check(lib, lib.LGBM_BoosterFree(bst0))
    h = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateByReference(
        ds_ref, 200, ctypes.byref(h)))
    Xc = np.ascontiguousarray(X[:100], np.float64)
    _check(lib, lib.LGBM_DatasetPushRows(
        h, Xc.ctypes.data_as(ctypes.c_void_p), 1, 100, 4, 0))
    yc = np.ascontiguousarray(y[:200], np.float32)
    _check(lib, lib.LGBM_DatasetSetField(
        h, b"label", yc.ctypes.data_as(ctypes.c_void_p), 200, 0))
    bst = ctypes.c_void_p()
    rc = lib.LGBM_BoosterCreate(h, b"objective=binary verbose=-1",
                                ctypes.byref(bst))
    assert rc != 0
    assert b"never pushed" in lib.LGBM_GetLastError()
    _check(lib, lib.LGBM_DatasetFree(h))
    _check(lib, lib.LGBM_DatasetFree(ds_ref))


def test_create_from_csr_func(lib):
    """The C++ std::function row-provider convention (ref: c_api.cpp
    LGBM_DatasetCreateFromCSRFunc — the SWIG embedding path). Built via a
    tiny compiled helper exposing a std::function whose address crosses
    the ABI exactly as SWIG hosts pass it."""
    import subprocess
    import tempfile, os
    src = r"""
#include <functional>
#include <utility>
#include <vector>
using RowFn = std::function<void(int, std::vector<std::pair<int,double>>&)>;
static RowFn g_fn = [](int idx, std::vector<std::pair<int,double>>& out) {
  out.clear();
  out.emplace_back(0, 1.0 * idx);
  out.emplace_back(2, idx % 2 ? 5.0 : -5.0);
};
extern "C" void* get_row_fn() { return (void*)&g_fn; }
"""
    d = tempfile.mkdtemp()
    cpp = os.path.join(d, "rowfn.cpp")
    so = os.path.join(d, "rowfn.so")
    with open(cpp, "w") as fh:
        fh.write(src)
    r = subprocess.run(["g++", "-O1", "-shared", "-fPIC", "-std=c++17",
                        cpp, "-o", so], capture_output=True)
    if r.returncode != 0:
        pytest.skip("helper compile failed: " + r.stderr.decode()[-200:])
    helper = ctypes.CDLL(so)
    helper.get_row_fn.restype = ctypes.c_void_p
    ds = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateFromCSRFunc(
        ctypes.c_void_p(helper.get_row_fn()), 400, 3,
        b"max_bin=63 verbose=-1", None, ctypes.byref(ds)))
    n = ctypes.c_int32()
    _check(lib, lib.LGBM_DatasetGetNumData(ds, ctypes.byref(n)))
    assert n.value == 400
    y = (np.arange(400) % 2).astype(np.float32)
    _check(lib, lib.LGBM_DatasetSetField(
        ds, b"label", y.ctypes.data_as(ctypes.c_void_p), 400, 0))
    bst = _train(lib, ds, iters=3)
    # feature 2 perfectly separates the labels
    X = np.zeros((2, 3))
    X[0, 2], X[1, 2] = 5.0, -5.0
    Xc = np.ascontiguousarray(X, np.float64)
    out = np.zeros(2, np.float64)
    out_len = ctypes.c_int64()
    _check(lib, lib.LGBM_BoosterPredictForMat(
        bst, Xc.ctypes.data_as(ctypes.c_void_p), 1, 2, 3, 1, 0, 0, -1,
        b"", ctypes.byref(out_len),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    assert out[0] > 0.5 > out[1]
    _check(lib, lib.LGBM_BoosterFree(bst))
    _check(lib, lib.LGBM_DatasetFree(ds))


def test_add_features_and_dump_text(lib, tmp_path):
    X, y = _data(300, 4)
    ds_a = _make_ds(lib, X[:, :2], y)
    ds_b = _make_ds(lib, X[:, 2:], y)
    # construct both (AddFeaturesFrom joins constructed datasets)
    for d in (ds_a, ds_b):
        b0 = _train(lib, d, iters=1)
        _check(lib, lib.LGBM_BoosterFree(b0))
    _check(lib, lib.LGBM_DatasetAddFeaturesFrom(ds_a, ds_b))
    n = ctypes.c_int32()
    _check(lib, lib.LGBM_DatasetGetNumFeature(ds_a, ctypes.byref(n)))
    assert n.value == 4
    path = str(tmp_path / "dump.txt").encode()
    _check(lib, lib.LGBM_DatasetDumpText(ds_a, path))
    text = open(path.decode()).read()
    assert "num_data: 300" in text
    assert len(text.splitlines()) > 300
    _check(lib, lib.LGBM_DatasetFree(ds_a))
    _check(lib, lib.LGBM_DatasetFree(ds_b))


# ------------------------------------------------- reset + refit lifecycle
def test_reset_training_data_and_refit(lib, tmp_path):
    X, y = _data(700, 5, seed=11)
    ds = _make_ds(lib, X, y)
    bst = _train(lib, ds, iters=6)
    path = str(tmp_path / "m.txt").encode()
    _check(lib, lib.LGBM_BoosterSaveModel(bst, 0, -1, 0, path))
    _check(lib, lib.LGBM_BoosterFree(bst))

    # reload (no training state), attach NEW data drawn from the same
    # distribution, binned with the same mappers (reference CheckAlign
    # contract) — use the original dataset as binning reference
    it = ctypes.c_int()
    bst2 = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreateFromModelfile(
        path, ctypes.byref(it), ctypes.byref(bst2)))
    assert it.value == 6
    X2, y2 = _data(700, 5, seed=12)
    X2c = np.ascontiguousarray(X2, np.float64)
    ds2 = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateFromMat(
        X2c.ctypes.data_as(ctypes.c_void_p), 1, 700, 5, 1,
        b"max_bin=63 verbose=-1", ds, ctypes.byref(ds2)))
    y2c = np.ascontiguousarray(y2, np.float32)
    _check(lib, lib.LGBM_DatasetSetField(
        ds2, b"label", y2c.ctypes.data_as(ctypes.c_void_p), 700, 0))
    _check(lib, lib.LGBM_BoosterResetTrainingData(bst2, ds2))

    # predictions before refit
    before = np.zeros(700, np.float64)
    out_len = ctypes.c_int64()
    _check(lib, lib.LGBM_BoosterPredictForMat(
        bst2, X2c.ctypes.data_as(ctypes.c_void_p), 1, 700, 5, 1, 0, 0,
        -1, b"", ctypes.byref(out_len),
        before.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))

    # leaf assignments of the new data under the existing trees
    nt = ctypes.c_int()
    _check(lib, lib.LGBM_BoosterNumberOfTotalModel(bst2, ctypes.byref(nt)))
    leaves = np.zeros(700 * nt.value, np.float64)
    _check(lib, lib.LGBM_BoosterPredictForMat(
        bst2, X2c.ctypes.data_as(ctypes.c_void_p), 1, 700, 5, 1,
        2, 0, -1, b"", ctypes.byref(out_len),
        leaves.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    lp = np.ascontiguousarray(
        leaves.reshape(700, nt.value).astype(np.int32))
    _check(lib, lib.LGBM_BoosterRefit(
        bst2, lp.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        700, nt.value))

    after = np.zeros(700, np.float64)
    _check(lib, lib.LGBM_BoosterPredictForMat(
        bst2, X2c.ctypes.data_as(ctypes.c_void_p), 1, 700, 5, 1, 0, 0,
        -1, b"", ctypes.byref(out_len),
        after.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    # refit moved the leaf values (decay 0.9 keeps them close, not equal)
    assert not np.allclose(before, after)
    from sklearn.metrics import roc_auc_score
    auc_b, auc_a = roc_auc_score(y2, before), roc_auc_score(y2, after)
    assert auc_a > 0.8        # refit toward the new labels cannot wreck it
    # ... and training continues from the reset state
    fin = ctypes.c_int()
    _check(lib, lib.LGBM_BoosterUpdateOneIter(bst2, ctypes.byref(fin)))
    _check(lib, lib.LGBM_BoosterNumberOfTotalModel(bst2, ctypes.byref(nt)))
    assert nt.value == 7
    _check(lib, lib.LGBM_BoosterFree(bst2))
    _check(lib, lib.LGBM_DatasetFree(ds2))
    _check(lib, lib.LGBM_DatasetFree(ds))


def test_refit_decay_semantics():
    """Python-level check of the RefitTree decay blend (ref:
    serial_tree_learner.cpp:240: new = decay*old + (1-decay)*refit)."""
    import lightgbm_tpu as lgb
    X, y = _data(400, 4, seed=5)
    ds = lgb.Dataset(X, label=y,
                     params={"max_bin": 63, "verbose": -1})
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbose": -1, "refit_decay_rate": 1.0},
                    ds, num_boost_round=3)
    model_str = bst.model_to_string()
    loaded = lgb.Booster(model_str=model_str)
    ds2 = lgb.Dataset(X, label=y, reference=ds,
                      params={"max_bin": 63, "verbose": -1})
    loaded.params["refit_decay_rate"] = 1.0
    loaded.reset_training_data(ds2)
    lp = bst.predict(X, pred_leaf=True).astype(np.int32)
    vals_before = [t.leaf_value.copy() for t in loaded.models]
    loaded.refit_by_leaf_preds(lp)
    # decay 1.0 => leaf values unchanged
    for t, v in zip(loaded.models, vals_before):
        np.testing.assert_allclose(t.leaf_value, v, rtol=1e-12)


# ----------------------------------------------------- network functions
def test_network_init_with_functions(lib):
    """Marshals the reference's external-collective C convention
    (meta.h:68 typedefs) through the ABI and the extnet wrappers: a fake
    single-process transport implements allgather/reduce-scatter over
    simulated ranks by duplicating blocks, proving pointer/layout
    compatibility end to end."""
    from lightgbm_tpu.parallel import extnet

    i32p = ctypes.POINTER(ctypes.c_int32)
    REDUCE = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_void_p,
                              ctypes.c_int, ctypes.c_int32)
    AG = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_int32, i32p,
                          i32p, ctypes.c_int, ctypes.c_void_p,
                          ctypes.c_int32)
    # the reducer crosses as ReduceFunction& = pointer-to-function-pointer
    RS = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_int32,
                          ctypes.c_int, i32p, i32p, ctypes.c_int,
                          ctypes.c_void_p, ctypes.c_int32,
                          ctypes.POINTER(ctypes.c_void_p))

    sim = {}   # reduce-scatter stashes the full reduced buffer so the
               # follow-up allgather can reproduce the other rank's block

    @AG
    def fake_allgather(inp, in_size, starts, lens, num_block, out,
                       out_size):
        full = sim.pop("full", None)
        if full is not None and len(full) == out_size:
            # allgather of reduce-scattered blocks (the allreduce tail)
            ctypes.memmove(out, full, out_size)
            return
        # plain allgather: every rank contributed the same local block
        src = ctypes.string_at(inp, in_size)
        for b in range(num_block):
            ctypes.memmove(out + starts[b], src, lens[b])

    @RS
    def fake_reduce_scatter(inp, in_size, type_size, starts, lens,
                            num_block, out, out_size, reducer):
        # every simulated rank holds the SAME input, so the reduced
        # buffer is num_block x each block, built through the injected
        # reducer; rank 0's own block goes to out, the rest is stashed
        # for the follow-up allgather
        reduce_fn = REDUCE(reducer[0])
        acc = ctypes.create_string_buffer(in_size)
        src = ctypes.create_string_buffer(
            ctypes.string_at(inp, in_size), in_size)
        for _ in range(num_block):
            reduce_fn(ctypes.cast(src, ctypes.c_void_p),
                      ctypes.cast(acc, ctypes.c_void_p), type_size,
                      in_size)
        sim["full"] = ctypes.string_at(acc, in_size)
        ctypes.memmove(out, ctypes.addressof(acc) + starts[0], lens[0])

    _check(lib, lib.LGBM_NetworkInitWithFunctions(
        2, 0, ctypes.cast(fake_reduce_scatter, ctypes.c_void_p),
        ctypes.cast(fake_allgather, ctypes.c_void_p)))
    try:
        assert extnet.is_active() and extnet.num_machines() == 2 \
            and extnet.rank() == 0
        local = np.asarray([1.5, -2.0, 3.25], np.float64)
        gathered = extnet.allgather(local)
        assert gathered.shape == (6,)
        np.testing.assert_allclose(gathered, np.tile(local, 2))
        summed = extnet.allreduce_sum(local)
        np.testing.assert_allclose(summed, 2.0 * local)
    finally:
        _check(lib, lib.LGBM_NetworkFree())
        extnet.free()
    # invalid rank rejected
    rc = lib.LGBM_NetworkInitWithFunctions(2, 5, None, None)
    assert rc != 0
