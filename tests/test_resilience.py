"""Fault-tolerance subsystem (lightgbm_tpu/resilience/): atomic IO,
checkpoint manager commit/selection/pruning semantics, fault-injection
registry, guarded collectives, and the checkpoint/resume bit-identity
matrix on the synchronous driver (gbdt with bagging + feature fraction
+ early stopping, GOSS, DART, CLI resume). The megastep-driver variant
and the multi-process chaos acceptance live in
test_resilience_chaos.py (chaos/slow marked)."""
import json
import os
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.resilience import atomicio, checkpoint as ckpt_mod
from lightgbm_tpu.resilience import comms, faults, recovery
from lightgbm_tpu.resilience import state as rstate


# ---------------------------------------------------------- atomic IO
def test_atomic_write_roundtrip_and_no_temp_litter(tmp_path):
    p = tmp_path / "out.txt"
    atomicio.atomic_write_text(str(p), "hello")
    assert p.read_text() == "hello"
    atomicio.atomic_write_json(str(tmp_path / "out.json"), {"a": 1})
    assert json.loads((tmp_path / "out.json").read_text()) == {"a": 1}
    leftovers = [f for f in os.listdir(tmp_path) if ".tmp." in f]
    assert leftovers == []


def test_save_model_never_leaves_partial_file(tmp_path, monkeypatch):
    X = np.random.RandomState(0).rand(200, 4)
    y = (X[:, 0] > 0.5).astype(np.float64)
    bst = lgb.train({"objective": "binary", "verbose": -1,
                     "min_data_in_leaf": 5},
                    lgb.Dataset(X, label=y, params={"verbose": -1}),
                    num_boost_round=2)
    out = tmp_path / "model.txt"
    bst.save_model(str(out))
    good = out.read_text()
    assert "tree" in good
    # a serialization failure must leave the existing file untouched
    monkeypatch.setattr(bst, "model_to_string",
                        lambda *a, **k: (_ for _ in ()).throw(
                            RuntimeError("boom")))
    with pytest.raises(RuntimeError):
        bst.save_model(str(out))
    assert out.read_text() == good


# --------------------------------------------------- checkpoint manager
def _mk_manager(tmp_path, keep=2):
    return ckpt_mod.CheckpointManager(str(tmp_path / "ck"), rank=0,
                                      world=1, keep=keep, async_io=False)


def _save(mgr, iteration, h="abc"):
    mgr.save(iteration, {"model_hash": h, "iteration": iteration},
             {"a": np.arange(iteration + 1)})


def test_checkpoint_commit_select_load(tmp_path):
    mgr = _mk_manager(tmp_path)
    _save(mgr, 4)
    _save(mgr, 8)
    root = str(tmp_path / "ck")
    assert [it for it, _ in ckpt_mod.list_checkpoints(root)] == [8, 4]
    sel = ckpt_mod.select_checkpoint(root, world=1)
    assert sel and sel.endswith("ckpt_0000000008")
    payload, arrays = ckpt_mod.load_rank(sel, 0)
    assert payload["iteration"] == 8
    assert np.array_equal(arrays["a"], np.arange(9))
    assert mgr.last_written["iteration"] == 8


def test_torn_npz_and_torn_manifest_are_skipped(tmp_path):
    mgr = _mk_manager(tmp_path, keep=4)
    _save(mgr, 4)
    _save(mgr, 8)
    _save(mgr, 12)
    root = str(tmp_path / "ck")
    # torn npz: truncated mid-write (size no longer matches manifest)
    npz12 = os.path.join(root, "ckpt_0000000012", "rank0.npz")
    with open(npz12, "r+b") as fh:
        fh.truncate(10)
    # torn manifest: half a JSON object
    man8 = os.path.join(root, "ckpt_0000000008", "rank0.json")
    with open(man8, "w") as fh:
        fh.write('{"schema": 1, "rank"')
    sel = ckpt_mod.select_checkpoint(root, world=1)
    assert sel and sel.endswith("ckpt_0000000004")
    with pytest.raises(FileNotFoundError):
        ckpt_mod.load_rank(os.path.join(root, "ckpt_0000000008"), 0)


def test_checkpoint_pruning_keeps_newest_two(tmp_path):
    mgr = _mk_manager(tmp_path, keep=2)
    for it in (2, 4, 6, 8):
        _save(mgr, it)
    root = str(tmp_path / "ck")
    assert [it for it, _ in ckpt_mod.list_checkpoints(root)] == [8, 6]


def test_incomplete_world_checkpoint_not_selected(tmp_path):
    # rank 0 of a 2-rank run committed; rank 1 didn't (crashed first):
    # the launcher must not resume a half-cohort checkpoint
    mgr = _mk_manager(tmp_path)
    _save(mgr, 4)
    root = str(tmp_path / "ck")
    assert ckpt_mod.select_checkpoint(root, world=1) is not None
    assert ckpt_mod.select_checkpoint(root, world=2) is None


# ------------------------------------------------------ fault registry
def test_fault_spec_parse_and_at_most_once(tmp_path):
    fl = faults.parse_faults("crash@5:rank=1, diverge@3 ,junk,hang@2")
    assert [(f.kind, f.iteration, f.rank) for f in fl] == \
        [("crash", 5, 1), ("diverge", 3, -1), ("hang", 2, -1)]
    reg = faults.FaultRegistry(fl, state_dir=str(tmp_path / "fs"))
    assert reg.due("crash", 5, rank=1) is not None
    assert reg.due("crash", 5, rank=1) is None          # fired
    assert reg.due("crash", 5, rank=0) is None          # wrong rank
    # a fresh registry (respawned process) sees the marker file
    reg2 = faults.FaultRegistry(faults.parse_faults("crash@5:rank=1"),
                                state_dir=str(tmp_path / "fs"))
    assert reg2.due("crash", 5, rank=1) is None
    # at_or_after: a megastep that jumped past the iteration still fires
    reg3 = faults.FaultRegistry(faults.parse_faults("crash@5:rank=1"))
    assert reg3.due("crash", 7, rank=1) is None
    assert reg3.due("crash", 7, rank=1, at_or_after=True) is not None


def test_torn_ckpt_fault_produces_unselectable_checkpoint(tmp_path,
                                                          monkeypatch):
    monkeypatch.setenv(faults.FAULTS_ENV, "torn_ckpt@8")
    monkeypatch.setenv(faults.FAULT_STATE_ENV, str(tmp_path / "fs"))
    mgr = _mk_manager(tmp_path, keep=4)
    _save(mgr, 4)
    _save(mgr, 8)        # torn: half npz, no manifest
    root = str(tmp_path / "ck")
    assert mgr.last_written["iteration"] == 4
    sel = ckpt_mod.select_checkpoint(root, world=1)
    assert sel and sel.endswith("ckpt_0000000004")
    _save(mgr, 12)       # the fault fired once; later writes commit
    assert ckpt_mod.select_checkpoint(root, world=1) \
        .endswith("ckpt_0000000012")


# --------------------------------------------------- guarded collectives
def test_guarded_call_timeout_and_retry():
    comms.set_collective_policy(0.2, retries=1)
    try:
        with pytest.raises(comms.CollectiveError,
                           match="timed out"):
            comms.guarded_call(lambda: time.sleep(3), what="unit")
        # transient errors retry; success on the second attempt
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] == 1:
                raise OSError("transport hiccup")
            return 42

        assert comms.guarded_call(flaky, what="unit") == 42
        # persistent errors exhaust the retry budget
        with pytest.raises(comms.CollectiveError, match="failed after"):
            comms.guarded_call(
                lambda: (_ for _ in ()).throw(OSError("down")),
                what="unit")
    finally:
        comms.set_collective_policy(0.0)
    # with no timeout configured, guarded_call is a plain passthrough
    assert comms.guarded_call(lambda: "direct") == "direct"


# --------------------------------------------- recovery building blocks
def test_models_blob_roundtrip_and_diff():
    X = np.random.RandomState(1).rand(300, 5)
    y = (X[:, 0] + X[:, 1] > 1).astype(np.float64)
    bst = lgb.train({"objective": "binary", "verbose": -1,
                     "min_data_in_leaf": 5},
                    lgb.Dataset(X, label=y, params={"verbose": -1}),
                    num_boost_round=3)
    models = bst._gbdt.models
    blob = recovery.serialize_models_blob(models)
    back = recovery.deserialize_models_blob(blob)
    assert len(back) == len(models)
    from lightgbm_tpu.obs.health import model_state_hash
    assert model_state_hash(back, rank=-1) == \
        model_state_hash(models, rank=-1)
    assert not any(recovery._trees_differ(a, b)
                   for a, b in zip(models, back))
    back[1].leaf_value = back[1].leaf_value + 1e-3
    assert recovery._trees_differ(models[1], back[1])


# -------------------------------------- resume bit-identity (sync driver)
def _data(seed=0, n=400, f=8):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 1).astype(np.float32)
    return X, y


def _train(params, X, y, n_rounds, valid=None, cbs=None, resume=None):
    ds = lgb.Dataset(X, label=y, params={"verbose": -1})
    vs = None
    if valid is not None:
        vs = [lgb.Dataset(valid[0], label=valid[1], reference=ds)]
    return lgb.train(dict(params), ds, num_boost_round=n_rounds,
                     valid_sets=vs, callbacks=list(cbs or []),
                     resume_from=resume)


def _assert_resume_identity(tmp_path, params, n1, n2, valid=None,
                            cbs_factory=lambda: []):
    """Core matrix assertion: train n2 rounds straight through vs train
    n1 + resume to n2 — byte-identical serialized models. All runs use
    the SAME params (incl. checkpoint_dir, which is echoed into the
    model's parameters block), with the directory wiped in between."""
    import shutil
    ck = tmp_path / "ck"
    params = dict(params, checkpoint_dir=str(ck), checkpoint_period=3)
    X, y = _data()
    ref = _train(params, X, y, n2, valid=valid, cbs=cbs_factory())
    ref_str = ref.model_to_string(num_iteration=-1)
    shutil.rmtree(ck)
    _train(params, X, y, n1, valid=valid, cbs=cbs_factory())
    resumed = _train(params, X, y, n2, valid=valid, cbs=cbs_factory(),
                     resume=str(ck))
    assert resumed.model_to_string(num_iteration=-1) == ref_str
    assert resumed.num_trees() == ref.num_trees()
    return ref, resumed


def test_resume_identity_gbdt_bagging_ff_early_stop(tmp_path):
    Xv, yv = _data(seed=7, n=150)
    params = {"objective": "binary", "num_leaves": 7, "verbose": -1,
              "bagging_fraction": 0.7, "bagging_freq": 2,
              "feature_fraction": 0.8}
    ref, resumed = _assert_resume_identity(
        tmp_path, params, n1=8, n2=14, valid=(Xv, yv),
        cbs_factory=lambda: [lgb.early_stopping(8, verbose=False)])
    assert resumed.best_iteration == ref.best_iteration


def test_resume_identity_goss(tmp_path):
    # learning_rate 0.2 -> GOSS sampling (and its MT19937 stream)
    # engages from iteration 5, straddling the n1=8 resume point
    params = {"objective": "binary", "boosting": "goss", "num_leaves": 7,
              "learning_rate": 0.2, "verbose": -1}
    _assert_resume_identity(tmp_path, params, n1=8, n2=12)


def test_resume_identity_dart(tmp_path):
    # DART mutates already-materialized trees (normalization) and keeps
    # a drop stream + per-tree weights — all of it must ride the
    # checkpoint for the resumed run to reproduce the drop schedule
    params = {"objective": "regression", "boosting": "dart",
              "num_leaves": 7, "drop_rate": 0.5, "verbose": -1}
    _assert_resume_identity(tmp_path, params, n1=6, n2=10)


def test_resume_records_eval_history(tmp_path):
    import shutil
    ck = tmp_path / "ck"
    Xv, yv = _data(seed=3, n=150)
    params = {"objective": "binary", "num_leaves": 7, "verbose": -1,
              "checkpoint_dir": str(ck), "checkpoint_period": 3}
    X, y = _data()
    rec_ref = {}
    _train(params, X, y, 10, valid=(Xv, yv),
           cbs=[lgb.record_evaluation(rec_ref)])
    shutil.rmtree(ck)
    rec_a = {}
    _train(params, X, y, 6, valid=(Xv, yv),
           cbs=[lgb.record_evaluation(rec_a)])
    rec_b = {}
    _train(params, X, y, 10, valid=(Xv, yv),
           cbs=[lgb.record_evaluation(rec_b)], resume=str(ck))
    # the recorded curve continues across the resume: full history, not
    # just the post-resume tail (checkpoint was written at iteration 6)
    assert rec_b == rec_ref
    assert len(rec_b["valid_0"]["binary_logloss"]) == 10


def test_cli_train_resume_path(tmp_path):
    import shutil

    from lightgbm_tpu import cli
    X, y = _data(n=300, f=5)
    train_csv = tmp_path / "train.csv"
    np.savetxt(train_csv, np.column_stack([y, X]), delimiter=",",
               fmt="%.6f")
    ck = tmp_path / "ck"
    # one shared output path: the configured output_model is echoed in
    # the model's parameters block, so byte-identity needs it equal
    out = tmp_path / "model.txt"
    base = ["task=train", f"data={train_csv}", "objective=binary",
            "num_leaves=7", "verbose=-1", "label_column=0",
            f"checkpoint_dir={ck}", "checkpoint_period=3",
            f"output_model={out}"]
    cli.main(base + ["num_iterations=10"])
    ref_text = out.read_text()
    shutil.rmtree(ck)
    cli.main(base + ["num_iterations=6"])
    cli.main(base + ["num_iterations=10", f"resume={ck}"])
    assert out.read_text() == ref_text


def test_resume_refuses_wrong_boosting(tmp_path):
    from lightgbm_tpu.utils.log import LightGBMError
    ck = tmp_path / "ck"
    params = {"objective": "binary", "num_leaves": 7, "verbose": -1,
              "checkpoint_dir": str(ck), "checkpoint_period": 2}
    X, y = _data()
    _train(params, X, y, 4)
    with pytest.raises((LightGBMError, SystemExit, Exception)):
        _train(dict(params, boosting="dart"), X, y, 8, resume=str(ck))


def test_crash_dump_records_checkpoint_manifest(tmp_path):
    ck = tmp_path / "ck"
    tel = tmp_path / "tel.jsonl"
    params = {"objective": "binary", "num_leaves": 7, "verbose": -1,
              "telemetry_out": str(tel),
              "checkpoint_dir": str(ck), "checkpoint_period": 2}
    X, y = _data()
    bst = _train(params, X, y, 6)
    path = bst._gbdt.dump_crash(RuntimeError("synthetic"))
    assert path == str(tel) + ".crash.json"
    dump = json.loads(open(path).read())
    # the dump names the rank's newest committed checkpoint — the first
    # thing an operator needs to restart with bounded lost work
    assert dump["checkpoint"] is not None
    assert dump["checkpoint"]["iteration"] >= 2
    assert os.path.isdir(dump["checkpoint"]["path"])
    assert dump["checkpoint"]["model_hash"]


def test_engine_snapshots_are_atomic_and_resumable(tmp_path):
    snap_base = tmp_path / "model.txt"
    params = {"objective": "binary", "num_leaves": 7, "verbose": -1,
              "snapshot_freq": 2, "output_model": str(snap_base)}
    X, y = _data()
    _train(params, X, y, 5)
    snaps = sorted(p.name for p in tmp_path.glob("*.snapshot_iter_*"))
    assert snaps == ["model.txt.snapshot_iter_2",
                     "model.txt.snapshot_iter_4"]
    assert not [p for p in os.listdir(tmp_path) if ".tmp." in p]
    # every snapshot parses as a complete model
    for s in snaps:
        b = lgb.Booster(model_file=str(tmp_path / s))
        assert b.num_trees() > 0


def test_checkpoint_counters_survive_resume(tmp_path):
    import shutil
    ck = tmp_path / "ck"
    tel = tmp_path / "tel.jsonl"
    params = {"objective": "binary", "num_leaves": 7, "verbose": -1,
              "telemetry_out": str(tel),
              "checkpoint_dir": str(ck), "checkpoint_period": 3}
    X, y = _data()
    a = _train(params, X, y, 6)
    iters_a = a.telemetry()["counters"]["iterations"]
    assert a.telemetry()["counters"].get("ckpt.written", 0) >= 1
    b = _train(params, X, y, 10, resume=str(ck))
    # resumed counters continue from the checkpoint instead of resetting
    assert b.telemetry()["counters"]["iterations"] > iters_a
    shutil.rmtree(ck)
