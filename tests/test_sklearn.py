"""sklearn estimator API tests, mirroring the reference's
tests/python_package_test/test_sklearn.py basics."""
import numpy as np
import pytest

from lightgbm_tpu import (LGBMClassifier, LGBMRanker, LGBMRegressor,
                          early_stopping)


def _clf_data(R=2000, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(R, 8).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(int)
    return X, y


def test_classifier_binary():
    X, y = _clf_data()
    clf = LGBMClassifier(n_estimators=20, num_leaves=15, verbose=-1,
                         min_child_samples=5)
    clf.fit(X, y)
    assert clf.n_classes_ == 2
    assert set(clf.classes_) == {0, 1}
    proba = clf.predict_proba(X)
    assert proba.shape == (len(y), 2)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-6)
    acc = (clf.predict(X) == y).mean()
    assert acc > 0.97
    assert clf.feature_importances_.shape == (8,)
    assert clf.n_features_ == 8


def test_classifier_string_labels():
    X, y = _clf_data()
    labels = np.where(y > 0, "pos", "neg")
    clf = LGBMClassifier(n_estimators=10, num_leaves=7, verbose=-1,
                         min_child_samples=5)
    clf.fit(X, labels)
    pred = clf.predict(X)
    assert set(pred) <= {"pos", "neg"}
    assert (pred == labels).mean() > 0.95


def test_classifier_multiclass():
    rng = np.random.RandomState(1)
    X = rng.randn(1500, 6).astype(np.float32)
    y = np.argmax(X[:, :3] + 0.2 * rng.randn(1500, 3), axis=1)
    clf = LGBMClassifier(n_estimators=15, num_leaves=15, verbose=-1,
                         min_child_samples=5)
    clf.fit(X, y)
    assert clf.n_classes_ == 3
    proba = clf.predict_proba(X)
    assert proba.shape == (1500, 3)
    assert (clf.predict(X) == y).mean() > 0.9


def test_regressor_and_eval_set_early_stopping():
    rng = np.random.RandomState(2)
    X = rng.rand(3000, 5).astype(np.float32)
    y = (2 * X[:, 0] - X[:, 1] + 0.05 * rng.randn(3000)).astype(np.float32)
    Xt, yt = X[:2400], y[:2400]
    Xv, yv = X[2400:], y[2400:]
    reg = LGBMRegressor(n_estimators=200, num_leaves=15, verbose=-1,
                        min_child_samples=5)
    reg.fit(Xt, yt, eval_set=[(Xv, yv)], eval_metric="l2",
            callbacks=[early_stopping(10, verbose=False)])
    assert reg.best_iteration_ > 0
    assert "valid_0" in reg.evals_result_
    mse = np.mean((reg.predict(Xv) - yv) ** 2)
    assert mse < 0.01


def test_custom_objective_and_metric():
    X, y = _clf_data(seed=3)

    def logloss_obj(y_true, y_pred):
        p = 1.0 / (1.0 + np.exp(-y_pred))
        return (p - y_true).astype(np.float32), \
            (p * (1 - p)).astype(np.float32)

    def err_metric(y_true, y_pred):
        return "err", float(np.mean((y_pred > 0) != y_true)), False

    clf = LGBMClassifier(n_estimators=15, num_leaves=15, verbose=-1,
                         min_child_samples=5, objective=logloss_obj)
    # eval_set sharing the exact train objects is named "training"
    # (reference sklearn semantics)
    clf.fit(X, y, eval_set=[(X, y)], eval_metric=err_metric)
    errs = clf.evals_result_["training"]["err"]
    assert errs[-1] < 0.1
    assert errs[-1] <= errs[0]


def test_class_weight_balanced():
    rng = np.random.RandomState(4)
    X = rng.randn(2000, 4).astype(np.float32)
    y = (X[:, 0] > 1.3).astype(int)   # ~10% positives
    clf = LGBMClassifier(n_estimators=10, num_leaves=7, verbose=-1,
                         min_child_samples=5, class_weight="balanced")
    clf.fit(X, y)
    recall = (clf.predict(X)[y == 1] == 1).mean()
    assert recall > 0.8


def test_ranker():
    rng = np.random.RandomState(5)
    n_q, per_q = 40, 25
    X = rng.rand(n_q * per_q, 5).astype(np.float32)
    rel = (X[:, 0] * 3 + 0.3 * rng.rand(n_q * per_q)).astype(int).clip(0, 3)
    group = np.full(n_q, per_q)
    rk = LGBMRanker(n_estimators=10, num_leaves=7, verbose=-1,
                    min_child_samples=5)
    rk.fit(X, rel, group=group)
    scores = rk.predict(X)
    from scipy.stats import spearmanr
    rho = spearmanr(scores, rel).statistic
    assert rho > 0.6


def test_sklearn_compat_clone_and_gridsearch():
    pytest.importorskip("sklearn")
    from sklearn.base import clone
    from sklearn.model_selection import GridSearchCV
    X, y = _clf_data(R=600)
    clf = LGBMClassifier(n_estimators=5, num_leaves=7, verbose=-1,
                         min_child_samples=5)
    c2 = clone(clf)
    assert c2.get_params()["num_leaves"] == 7
    gs = GridSearchCV(clf, {"num_leaves": [4, 7]}, cv=2, scoring="accuracy")
    gs.fit(X, y)
    assert gs.best_params_["num_leaves"] in (4, 7)


def test_device_predict_matches_host():
    """Large batches route through the device predictor; results must match
    the host float64 walk to f32 tolerance (incl. NaN + categorical)."""
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(9)
    R = 4000
    X = rng.randn(R, 6).astype(np.float32)
    X[:, 5] = rng.randint(0, 9, R)
    X[::11, 2] = np.nan
    y = ((X[:, 0] > 0) ^ np.isin(X[:, 5], [2, 6])).astype(np.float32)
    ds = lgb.Dataset(X, label=y, categorical_feature=[5])
    bst = lgb.train({"objective": "binary", "num_leaves": 15, "verbose": -1,
                     "min_data_in_leaf": 5, "min_data_per_group": 5,
                     "cat_smooth": 2.0}, ds, num_boost_round=10)
    # host path (small slice, below the device threshold)
    host = bst.predict(X[:100])
    # force device path by calling the predictor directly
    from lightgbm_tpu.models.predictor import DevicePredictor
    pred = DevicePredictor(bst.models, bst.train_set._inner, 1)
    assert pred.ok
    raw_dev = pred.predict_raw(np.asarray(X[:100], np.float64), 0,
                               bst.num_trees())
    conv = bst._gbdt.objective.convert_output(raw_dev[0])
    np.testing.assert_allclose(conv, host, rtol=2e-5, atol=2e-6)
