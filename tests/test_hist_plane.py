"""Histogram-plane cuts (ROADMAP item 4 / ISSUE 14): quantized gradient
histograms, EMA-FS gain screening, adaptive per-feature bins.

Contracts under test:
- shared layout source of truth (pad_feature_layout == feature_layout)
  and the packed-layout index maps;
- masked (slot == -1) rows with NONZERO gh contribute nothing in the
  XLA and Pallas formulations (the pallas_histogram docstring fix);
- quantization: stochastic rounding determinism + integer exactness,
  int16/int8 channel encode/decode roundtrip, kernel-level parity
  (exact on an integer grid, bounded error on random grads),
  rerun determinism, and cross-driver statistical parity (cross-driver
  BIT identity is deliberately not claimed — see
  test_quant_deterministic_and_cross_driver_parity);
- adaptive bins: kernel- and model-level BYTE-IDENTITY vs the padded
  layout;
- screening: a feature screened out by an adversarial EMA re-enters
  through an exploration round; statistical parity (slow);
- composition: all three cuts ride the megastep at the same dispatch
  schedule, the analytic byte model halves, the psum payload shrinks
  under the adaptive layout, and the EMA survives a checkpoint
  round-trip bit-identically.
"""
import shutil

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.ops import fused_level as fl
from lightgbm_tpu.ops import quantize
from lightgbm_tpu.ops.histogram import _choose_chunk, build_histograms
from lightgbm_tpu.ops.layout import (feature_layout, hist_plane_bytes,
                                     packed_feature_layout)
from lightgbm_tpu.ops.pallas_histogram import (build_histograms_pallas_cm,
                                               build_histograms_pallas_quant,
                                               pad_feature_layout)

KNOBS = {"tpu_quantized_grad": 16, "tpu_gain_screening": True,
         "tpu_screening_warmup": 2, "tpu_screening_explore_period": 4,
         "tpu_adaptive_bins": True}
BASE = {"objective": "binary", "max_bin": 63, "num_leaves": 7,
        "min_data_in_leaf": 5, "verbose": -1, "metric": "None",
        "tpu_engine": "fused", "num_iterations": 4}


def _mixed_data(seed=0, n=512, f=8):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f).astype(np.float32)
    X[:, f // 2:] = np.floor(X[:, f // 2:] * 8.0) / 8.0   # 8 levels
    y = (X @ rng.randn(f).astype(np.float32) > 0).astype(np.float32)
    return X, y


def _train(X, y, params, n=None, **kw):
    ds = lgb.Dataset(X, label=y, params={"max_bin": params.get(
        "max_bin", 63), "verbose": -1})
    p = dict(params)
    if n is not None:
        p["num_iterations"] = n
    return lgb.train(p, ds, **kw)


def _trees(bst):
    # the saved-parameters block echoes the knob values; the TREES are
    # what the byte-identity contracts cover
    return bst.model_to_string(num_iteration=-1).split("\nparameters:")[0]


# ---------------------------------------------------------------- layout
def test_shared_layout_contract():
    for F in (1, 3, 8, 28, 130):
        for mb in (2, 15, 63, 255, 300):
            assert pad_feature_layout(F, mb) == feature_layout(F, mb)
            Fp, Bp = feature_layout(F, mb)
            assert (Fp * Bp) % 128 == 0 and Fp >= F and Bp >= mb


def test_packed_layout_maps():
    nb = np.array([63, 9, 9, 2, 63, 17, 9, 9, 9, 9, 9, 0], np.int32)
    pk = packed_feature_layout(nb, 63)                   # 0 = padding feat
    assert pk.fb % 128 == 0
    assert set(pk.feat_order) == set(range(11))          # padding dropped
    # widths are pow2 >= num_bin, >= 8
    for j, f in enumerate(pk.feat_order):
        assert pk.widths[j] >= max(8, nb[f])
        assert pk.widths[j] & (pk.widths[j] - 1) == 0
    # round-trip: padded flat -> packed -> padded is identity where valid
    p2p = pk.padded_to_packed
    back = pk.packed_to_padded
    valid = pk.padded_valid
    idx = np.nonzero(valid)[0]
    assert np.array_equal(back[p2p[idx]], idx)
    # every real (feature, bin < num_bin) position is representable
    for f in range(11):
        for b in range(nb[f]):
            assert valid[f * pk.bp + b]
    # byte model shrinks vs padded and shrinks again under quantization
    Fp, Bp = feature_layout(len(nb), 63)
    assert pk.fb < Fp * Bp
    b_f32 = hist_plane_bytes(Fp * Bp, 5, 64, 4096, 1024, 0)
    b_cut = hist_plane_bytes(pk.fb, 5, 64, 4096, 1024, 16)
    assert b_cut < b_f32 / 2


def test_choose_chunk_scales_with_elem_width():
    c4 = _choose_chunk(10 ** 7, 28, 64, elem_bytes=4)
    c2 = _choose_chunk(10 ** 7, 28, 64, elem_bytes=2)
    c1 = _choose_chunk(10 ** 7, 28, 64, elem_bytes=1)
    assert c4 <= c2 <= c1
    assert c1 >= 2 * c4 or c1 == 1 << 15   # capped at the row-chunk max
    # in the scaling regime (between the 256 floor and the 2^15 cap) the
    # chunk grows with the inverse element width
    big = _choose_chunk(10 ** 7, 512, 64, elem_bytes=4)
    assert 256 < big < (1 << 15)
    assert _choose_chunk(10 ** 7, 512, 64, elem_bytes=1) >= 2 * big


# ------------------------------------------------------------ quantize
def test_stochastic_round_deterministic_and_exact_on_integers():
    x = jnp.asarray(np.random.RandomState(0).randn(4096) * 100)
    a = np.asarray(quantize.stochastic_round(x, 7))
    b = np.asarray(quantize.stochastic_round(x, 7))
    c = np.asarray(quantize.stochastic_round(x, 8))
    assert np.array_equal(a, b)           # deterministic given seed
    assert not np.array_equal(a, c)       # seed actually dithers
    assert np.max(np.abs(a - np.asarray(x))) <= 1.0   # floor/ceil only
    xi = jnp.asarray(np.arange(-2000, 2000, dtype=np.float32))
    assert np.array_equal(np.asarray(quantize.stochastic_round(xi, 3)),
                          np.arange(-2000, 2000))     # integers exact


@pytest.mark.parametrize("bits", [8, 16])
def test_quant_encode_decode_roundtrip(bits):
    rng = np.random.RandomState(1)
    qmax = quantize.QMAX[bits]
    q_g = rng.randint(-qmax, qmax + 1, 2048).astype(np.int32)
    q_h = rng.randint(-qmax, qmax + 1, 2048).astype(np.int32)
    w = (rng.rand(2048) < 0.8).astype(np.float32)
    q_g = (q_g * w).astype(np.int32)      # zero-weight rows carry zero
    q_h = (q_h * w).astype(np.int32)
    rows = quantize.encode_channels(jnp.asarray(q_g), jnp.asarray(q_h),
                                    jnp.asarray(w), bits)
    assert len(rows) == quantize.QNCH[bits]
    assert all(r.dtype == jnp.int8 for r in rows)
    # per-row sums through the channel decode == direct integer sums
    planes = [jnp.sum(r.astype(jnp.int32)).reshape(1, 1) for r in rows]
    scales = jnp.asarray([1.0, 1.0], jnp.float32)
    g, h, c = quantize.decode_sums(planes, scales, bits)
    assert int(g[0, 0]) == int(q_g.sum())
    assert int(h[0, 0]) == int(q_h.sum())
    assert int(c[0, 0]) == int(w.sum())


def test_decode_sums_no_int32_overflow_at_scale():
    """A root-level bin holding 200K rows of near-max hessian: the
    16-bit hi/lo recombination must happen in f32 — an int32
    ``256 * hi_sum`` would wrap at ~65K such rows (regression test for
    the review-caught overflow)."""
    n = 200_000
    q = np.full(n, quantize.QMAX[16], np.int32)     # non-canceling
    w = np.ones(n, np.float32)
    rows = quantize.encode_channels(jnp.asarray(q), jnp.asarray(q),
                                    jnp.asarray(w), 16)
    planes = [jnp.sum(r.astype(jnp.int32)).reshape(1, 1) for r in rows]
    scales = jnp.asarray([1.0, 1.0], jnp.float32)
    g, h, c = quantize.decode_sums(planes, scales, 16)
    expect = float(n) * quantize.QMAX[16]
    assert float(h[0, 0]) > 0
    assert abs(float(h[0, 0]) - expect) / expect < 1e-6
    assert abs(float(g[0, 0]) - expect) / expect < 1e-6
    assert float(c[0, 0]) == float(n)


# ---------------------------------------------------- masked-row contract
def _masked_row_inputs():
    rng = np.random.RandomState(2)
    R, F, B, S = 512, 4, 16, 3
    bins = rng.randint(0, B, (R, F)).astype(np.int32)
    gh = rng.randn(R, 3).astype(np.float32)   # NONZERO gh everywhere
    gh[:, 2] = 1.0
    slot = rng.randint(0, S, R).astype(np.int32)
    masked = rng.rand(R) < 0.3
    slot_m = np.where(masked, -1, slot).astype(np.int32)
    return bins, gh, slot, slot_m, masked, (R, F, B, S)


@pytest.mark.parametrize("impl", ["segment", "onehot"])
def test_masked_rows_contribute_nothing_xla(impl):
    bins, gh, slot, slot_m, masked, (R, F, B, S) = _masked_row_inputs()
    h_masked = np.asarray(build_histograms(
        jnp.asarray(bins), jnp.asarray(gh), jnp.asarray(slot_m),
        num_slots=S, num_bins=B, impl=impl))
    gh0 = gh.copy()
    gh0[masked] = 0.0
    h_zeroed = np.asarray(build_histograms(
        jnp.asarray(bins), jnp.asarray(gh0), jnp.asarray(slot_m),
        num_slots=S, num_bins=B, impl=impl))
    assert np.array_equal(h_masked, h_zeroed)
    assert h_masked.sum() != 0.0


def test_masked_rows_contribute_nothing_pallas():
    bins, gh, slot, slot_m, masked, (R, F, B, S) = _masked_row_inputs()
    Fp, Bp = pad_feature_layout(F, B)
    bp = np.zeros((R, Fp), np.int32)
    bp[:, :F] = bins
    g1, h1, c1 = build_histograms_pallas_cm(
        jnp.asarray(bp), jnp.asarray(gh), jnp.asarray(slot_m),
        num_slots=S, num_bins=Bp, interpret=True)
    gh0 = gh.copy()
    gh0[masked] = 0.0
    g2, h2, c2 = build_histograms_pallas_cm(
        jnp.asarray(bp), jnp.asarray(gh0), jnp.asarray(slot_m),
        num_slots=S, num_bins=Bp, interpret=True)
    for a, b in ((g1, g2), (h1, h2), (c1, c2)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    assert float(jnp.sum(jnp.abs(g1))) > 0.0


# -------------------------------------------------- quantized histograms
def test_xla_quantized_exact_on_integer_grid():
    """When grad/hess are integers whose max-abs equals the grid max,
    the scale is 1.0 and stochastic rounding is exact — the quantized
    histogram must equal the f32 one bit-for-bit."""
    rng = np.random.RandomState(3)
    R, F, B, S = 1024, 4, 16, 2
    bins = rng.randint(0, B, (R, F)).astype(np.int32)
    qmax = quantize.QMAX[16]
    g = rng.randint(-qmax, qmax + 1, R).astype(np.float32)
    g[np.argmax(np.abs(g))] = qmax        # pin the scale to exactly 1
    h = np.abs(rng.randint(-qmax, qmax + 1, R)).astype(np.float32)
    h[np.argmax(h)] = qmax
    gh = np.stack([g, h, np.ones(R, np.float32)], axis=1)
    slot = rng.randint(0, S, R).astype(np.int32)
    hq = np.asarray(build_histograms(
        jnp.asarray(bins), jnp.asarray(gh), jnp.asarray(slot),
        num_slots=S, num_bins=B, quant_bits=16))
    hf = np.asarray(build_histograms(
        jnp.asarray(bins), jnp.asarray(gh), jnp.asarray(slot),
        num_slots=S, num_bins=B, impl="segment"))
    assert np.array_equal(hq, hf)


def test_pallas_quant_matches_xla_quant_grid():
    """The fused int8-channel kernel formulation and the XLA int32
    segment formulation accumulate the SAME integer grid — on a
    scale-1 integer grid both equal the exact sums."""
    rng = np.random.RandomState(4)
    R, F, B, S = 512, 4, 16, 2
    bins = rng.randint(0, B, (R, F)).astype(np.int32)
    qmax = quantize.QMAX[16]
    g = rng.randint(-qmax, qmax + 1, R).astype(np.float32)
    g[np.argmax(np.abs(g))] = qmax
    h = np.abs(rng.randint(0, qmax + 1, R)).astype(np.float32)
    h[np.argmax(h)] = qmax
    gh = np.stack([g, h, np.ones(R, np.float32)], axis=1)
    slot = rng.randint(0, S, R).astype(np.int32)
    Fp, Bp = pad_feature_layout(F, B)
    bp = np.zeros((R, Fp), np.int32)
    bp[:, :F] = bins
    gq, hq, cq = build_histograms_pallas_quant(
        jnp.asarray(bp), jnp.asarray(gh), jnp.asarray(slot),
        num_slots=S, num_bins=Bp, quant_bits=16, interpret=True)
    ref = np.asarray(build_histograms(
        jnp.asarray(bins), jnp.asarray(gh), jnp.asarray(slot),
        num_slots=S, num_bins=B, impl="segment"))
    assert np.array_equal(np.asarray(gq)[:, :F, :B], ref[..., 0])
    assert np.array_equal(np.asarray(hq)[:, :F, :B], ref[..., 1])
    assert np.array_equal(np.asarray(cq)[:, :F, :B], ref[..., 2])


def test_level_pass_quant_error_bound():
    """Random f32 grads: the quantized level pass reproduces the f32
    histogram within the quantization error model (|noise per row| <=
    scale, summed over a bin)."""
    rng = np.random.RandomState(5)
    F, R = 4, 2048
    bins = rng.randint(0, 16, (F, R)).astype(np.int8)
    F_oh, Bp = feature_layout(F, 16)
    Fp = max(F_oh, 8)
    bT = np.zeros((Fp, R), np.int8)
    bT[:F] = bins
    g = rng.randn(R).astype(np.float32)
    h = np.abs(rng.randn(R)).astype(np.float32)
    ones = np.ones(R, np.float32)
    leaf = jnp.zeros((1, R), jnp.int32)
    Sp = 8
    tbl = (jnp.zeros((Sp, 128), jnp.int32)
           .at[:, 0].set(-2).at[0, 0].set(0).at[0, 2].set(1))
    W = jnp.zeros((Sp, F_oh * Bp), jnp.bfloat16).at[0, :Bp].set(1)
    gh_T = fl.pack_gh(jnp.asarray(g), jnp.asarray(h), jnp.asarray(ones), 5)
    hist_f, _ = fl.level_pass(jnp.asarray(bT), leaf, gh_T, W, tbl,
                              num_slots=Sp, num_bins=Bp, f_oh=F_oh,
                              nch=5, interpret=True)
    gf, hf, cf = fl.hist_planes(hist_f, 5, Sp, F_oh, Bp)
    gh_q, scales = fl.pack_gh_quant(jnp.asarray(g), jnp.asarray(h),
                                    jnp.asarray(ones), 16, np.uint32(9))
    hist_q, _ = fl.level_pass(jnp.asarray(bT), leaf, gh_q, W, tbl,
                              num_slots=Sp, num_bins=Bp, f_oh=F_oh,
                              nch=5, interpret=True, quant_bits=16)
    gq, hq, cq = fl.hist_planes(hist_q, 5, Sp, F_oh, Bp, quant_bits=16,
                                scales=scales)
    assert np.array_equal(np.asarray(cq), np.asarray(cf))   # counts exact
    sg, sh = float(scales[0]), float(scales[1])
    rows_per_bin = np.asarray(cf)[0].max()
    assert float(jnp.max(jnp.abs(gq - gf))) <= sg * (rows_per_bin + 1)
    assert float(jnp.max(jnp.abs(hq - hf))) <= sh * (rows_per_bin + 1)
    # and the bulk is much tighter (sqrt(n) noise, not n)
    assert float(jnp.mean(jnp.abs(gq - gf))) \
        <= sg * np.sqrt(rows_per_bin) * 3


# -------------------------------------------------------- adaptive bins
def test_level_pass_packed_byte_identity():
    rng = np.random.RandomState(6)
    F, R = 8, 2048
    num_bin = np.array([63, 63, 63, 63, 9, 9, 9, 9], np.int32)
    bins = np.stack([rng.randint(0, nb, R) for nb in num_bin]) \
        .astype(np.int8)
    F_oh, Bp = feature_layout(F, 63)
    pk = packed_feature_layout(num_bin, 63, f_oh=F_oh)
    assert pk.fb < F_oh * Bp
    g = rng.randn(R).astype(np.float32)
    h = np.abs(rng.randn(R)).astype(np.float32)
    ones = np.ones(R, np.float32)
    gh_T = fl.pack_gh(jnp.asarray(g), jnp.asarray(h), jnp.asarray(ones), 5)
    leaf = jnp.zeros((1, R), jnp.int32)
    Sp = 8
    tbl = (jnp.zeros((Sp, 128), jnp.int32)
           .at[:, 0].set(-2).at[0, 0].set(0).at[0, 2].set(1))
    W = jnp.zeros((Sp, F_oh * Bp), jnp.bfloat16).at[0, :Bp].set(1)
    hp, _ = fl.level_pass(jnp.asarray(bins), leaf, gh_T, W, tbl,
                          num_slots=Sp, num_bins=Bp, f_oh=F_oh, nch=5,
                          interpret=True)
    ref = fl.hist_planes(hp, 5, Sp, F_oh, Bp)
    order = np.asarray(pk.feat_order)
    Wp = jnp.zeros((Sp, pk.fb), jnp.bfloat16).at[0, :pk.widths[0]].set(1)
    hk, _ = fl.level_pass(jnp.asarray(bins[order]), leaf, gh_T, Wp, tbl,
                          num_slots=Sp, num_bins=Bp, f_oh=F_oh, nch=5,
                          interpret=True, packed=pk)
    out = fl.hist_planes(hk, 5, Sp, F_oh, Bp, packed=pk)
    for a, b in zip(ref, out):
        assert np.array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_adaptive_bins_byte_identity_e2e(mixed_models):
    m0, m1, *_ = mixed_models
    assert _trees(m0) == _trees(m1)


# ---------------------------------------------------------- e2e fixtures
@pytest.fixture(scope="module")
def mixed_models():
    """One shared training sweep over the knob matrix (module-scoped:
    interpret-mode compiles dominate, so every e2e assertion reads from
    this sweep instead of retraining)."""
    X, y = _mixed_data()
    m_base = _train(X, y, BASE)
    m_adapt = _train(X, y, dict(BASE, tpu_adaptive_bins=True))
    m_q16 = _train(X, y, dict(BASE, tpu_quantized_grad=16))
    m_q16_rep = _train(X, y, dict(BASE, tpu_quantized_grad=16))
    m_q16_sync = _train(X, y, dict(BASE, tpu_quantized_grad=16,
                                   tpu_fast_path=False))
    return m_base, m_adapt, m_q16, m_q16_rep, m_q16_sync, (X, y)


@pytest.mark.slow
def test_quant_deterministic_and_cross_driver_parity(mixed_models):
    """Quantized runs are DETERMINISTIC: the dither streams are keyed on
    (iteration, class tree) alone, so an identical rerun serializes
    byte-identical trees. Across DRIVERS the contract is parity, not
    bit identity: fast-path and sync-driver scores differ at the ulp
    level (f64-vs-f32 shrinkage rounding), the f32 histogram's bf16
    channels absorb that, but quantization divides it by the grid scale
    in the dither-threshold domain — a near-tie split can legitimately
    flip. The exactness half of the A/B lives at the kernel level
    (test_xla_quantized_exact_on_integer_grid and friends), the
    inexact half in the accuracy-curve suite."""
    m_base, _, m_q16, m_q16_rep, m_q16_sync, (X, y) = mixed_models
    assert _trees(m_q16) == _trees(m_q16_rep)
    acc_f = np.mean((m_q16.predict(X) > 0.5) == y)
    acc_s = np.mean((m_q16_sync.predict(X) > 0.5) == y)
    assert abs(acc_f - acc_s) <= 0.04
    assert m_q16_sync.num_trees() == m_q16.num_trees()


@pytest.mark.slow
def test_quant_changes_models_but_not_quality_much(mixed_models):
    m_base, _, m_q16, _, _, (X, y) = mixed_models
    # quantization legitimately changes the model (stochastic rounding)
    assert _trees(m_base) != _trees(m_q16)
    acc0 = np.mean((m_base.predict(X) > 0.5) == y)
    accq = np.mean((m_q16.predict(X) > 0.5) == y)
    assert accq >= acc0 - 0.05


# ------------------------------------------------------------- screening
def test_screening_reentry():
    """A decisive feature adversarially screened out (its EMA pinned to
    the bottom) must re-enter through an exploration round and win
    splits again."""
    rng = np.random.RandomState(8)
    n, f = 512, 6
    X = rng.rand(n, f).astype(np.float32)
    y = (X[:, 0] > 0.5).astype(np.float32)      # feature 0 is everything
    ds = lgb.Dataset(X, label=y, params={"max_bin": 63, "verbose": -1})
    params = dict(BASE, tpu_gain_screening=True, tpu_screening_warmup=0,
                  tpu_screening_keep_ratio=0.34,
                  tpu_screening_explore_period=3, num_iterations=6)
    bst = lgb.Booster(params=params, train_set=ds)
    g = bst._gbdt
    assert g.use_screening
    # adversarial EMA: the decisive feature 0 at the bottom, noise
    # features at the top — the non-exploration mask excludes feature 0
    ema = np.zeros(g.fused_f_oh, np.float32)
    ema[1:f] = 100.0
    g._gain_ema_dev = jnp.asarray(ema)
    for _ in range(6):
        bst.update()
    g.drain_pending()
    used = set()
    for ht in g.models:
        used.update(int(v) for v in np.asarray(ht.split_feature))
    assert 0 in used, "screened-out decisive feature never re-entered"
    # and its realized gains rebuilt the EMA above the noise floor
    ema_after = np.asarray(g._gain_ema_dev)
    assert ema_after[0] > 0.0


@pytest.mark.slow
def test_screening_trains_and_reports_active_features(tmp_path):
    X, y = _mixed_data(seed=9)
    tel = tmp_path / "tel.jsonl"
    params = dict(BASE, tpu_gain_screening=True, tpu_screening_warmup=1,
                  tpu_screening_keep_ratio=0.5, tpu_engine="fused",
                  tpu_megastep=True, telemetry_out=str(tel),
                  num_iterations=6)
    bst = _train(X, y, params)
    snap = bst.telemetry()
    gauges = snap.get("gauges", {})
    F = X.shape[1]
    active = gauges.get("screening.active_features")
    assert active is not None and 1 <= active <= F
    assert active <= int(round(0.5 * F)) + F // 2   # top-k (+ties)


def test_knobs_degrade_off_fused():
    """engine=xla: the cuts degrade with structured events and training
    proceeds unchanged (f32 plane)."""
    X, y = _mixed_data(seed=10)
    m = _train(X, y, dict(BASE, tpu_engine="xla", **KNOBS))
    g = m._gbdt
    assert g.quant_bits == 0 and not g.use_screening \
        and not g.use_adaptive_bins
    assert m.num_trees() == BASE["num_iterations"]


# ----------------------------------------------------------- composition
def test_megastep_all_cuts_dispatch_parity(tmp_path):
    """The acceptance gate: with int16 quantization, screening and
    adaptive bins all on, the megastep still measures the SAME dispatch
    schedule (0.125/iter at 8 iterations = one fused chunk), and the
    analytic histogram byte model drops >= 2x vs the f32 full plane."""
    X, y = _mixed_data(seed=11, n=768, f=10)
    tel0 = tmp_path / "t0.jsonl"
    tel1 = tmp_path / "t1.jsonl"
    p0 = dict(BASE, tpu_megastep=True, telemetry_out=str(tel0),
              num_leaves=15)
    b0 = _train(X, y, p0, n=8)
    c0 = b0.telemetry().get("counters", {})
    g0 = b0.telemetry().get("gauges", {})
    d0 = c0.get("train.dispatches", 0) / max(1, c0.get("iterations", 8))
    p1 = dict(p0, telemetry_out=str(tel1), **KNOBS)
    b1 = _train(X, y, p1, n=8)
    c1 = b1.telemetry().get("counters", {})
    g1 = b1.telemetry().get("gauges", {})
    d1 = c1.get("train.dispatches", 0) / max(1, c1.get("iterations", 8))
    assert d1 == d0 == 0.125
    assert g1.get("hist.quant_bits") == 16.0
    assert g1.get("hist.bytes_per_iter") > 0
    ratio = g0.get("hist.bytes_per_iter") / g1.get("hist.bytes_per_iter")
    assert ratio >= 2.0, f"histogram byte model only dropped {ratio:.2f}x"


def test_collectives_payload_shrinks_with_cuts():
    """The data-parallel per-level psum payload (trace-time recorder,
    ops/collectives.py) shrinks under the adaptive layout — what the
    multi-chip megastep would actually put on the wire."""
    from jax.sharding import Mesh, PartitionSpec as P
    from lightgbm_tpu.ops.collectives import CollectiveTrace
    from lightgbm_tpu.models.frontier2 import grow_tree_fused
    from lightgbm_tpu.models.learner import FeatureMeta
    from lightgbm_tpu.ops.split import SplitParams
    from lightgbm_tpu.parallel.mesh import shard_map as _shard_map

    rng = np.random.RandomState(12)
    F, R = 8, 2048
    num_bin = np.array([63, 63, 63, 63, 9, 9, 9, 9], np.int32)
    bins = np.stack([rng.randint(0, nb, R) for nb in num_bin]) \
        .astype(np.int8)
    F_oh, Bp = feature_layout(F, 63)
    pk = packed_feature_layout(num_bin, 63, f_oh=F_oh)
    meta = FeatureMeta(
        num_bin=jnp.asarray(num_bin), missing_type=jnp.zeros(F, jnp.int32),
        default_bin=jnp.zeros(F, jnp.int32),
        monotone=jnp.zeros(F, jnp.int32), is_cat=jnp.zeros(F, bool))
    g = rng.randn(R).astype(np.float32)
    ones = np.ones(R, np.float32)
    params = SplitParams(min_data_in_leaf=5)
    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    fm = jnp.ones((F_oh,), bool).at[F:].set(False)

    def payload(packed, quant):
        if quant:
            gh_T, scales = fl.pack_gh_quant(
                jnp.asarray(g), jnp.asarray(np.abs(g)), jnp.asarray(ones),
                quant, np.uint32(0))
        else:
            gh_T = fl.pack_gh(jnp.asarray(g), jnp.asarray(np.abs(g)),
                              jnp.asarray(ones), 5)
            scales = None
        bt = bins if packed is None else bins[np.asarray(pk.feat_order)]

        def body(b_T, ghv):
            return grow_tree_fused(
                b_T, ghv, meta, fm, params, 7, Bp, F_oh, num_rows=0,
                nch=5 if not quant else quantize.QNCH[quant],
                interpret=True, psum_axis="data", parallel_mode="data",
                quant_bits=quant or 0, packed=packed, gh_scales=scales)
        fn = jax.jit(_shard_map(
            body, mesh=mesh, in_specs=(P(None, "data"), P(None, "data")),
            out_specs=(P(), P("data")), check_vma=False))
        with CollectiveTrace() as rec:
            fn(jnp.asarray(bt), gh_T)
        return rec.bytes, dict(rec.by_dtype)

    b_f32, d_f32 = payload(None, 0)
    b_cut, d_cut = payload(pk, 8)
    assert b_cut < b_f32
    # the quantized path psums int32 accumulators
    assert any(k.startswith("int32") for k in d_cut)


@pytest.mark.slow
def test_checkpoint_ema_roundtrip(tmp_path):
    """EMA-FS state joins the resilience extra-state: train n1 + resume
    to n2 under screening == train n2 straight through, byte-identical
    (the mask schedule depends on the EMA, so a dropped EMA would
    diverge)."""
    X, y = _mixed_data(seed=13, n=256)
    ck = tmp_path / "ck"
    params = dict(BASE, tpu_gain_screening=True, tpu_screening_warmup=1,
                  tpu_screening_keep_ratio=0.5,
                  tpu_screening_explore_period=3,
                  checkpoint_dir=str(ck), checkpoint_period=2)

    def run(n, resume=None):
        ds = lgb.Dataset(X, label=y, params={"max_bin": 63, "verbose": -1})
        return lgb.train(dict(params), ds, num_boost_round=n,
                         resume_from=resume)

    ref = run(7)
    ref_str = ref.model_to_string(num_iteration=-1)
    ref_ema = np.asarray(ref._gbdt._gain_ema_dev)
    shutil.rmtree(ck)
    run(4)
    resumed = run(7, resume=str(ck))
    assert resumed.model_to_string(num_iteration=-1) == ref_str
    assert np.array_equal(np.asarray(resumed._gbdt._gain_ema_dev),
                          ref_ema)


# -------------------------------------------------- accuracy-curve A/Bs
@pytest.mark.slow
@pytest.mark.parametrize("objective,metric_gate", [
    ("binary", 0.05), ("regression", 0.15), ("multiclass", 0.08)])
def test_quant_accuracy_curves(objective, metric_gate):
    """int16 quantization holds the accuracy curve on binary,
    regression and multiclass; int8 is exercised for binary."""
    rng = np.random.RandomState(14)
    n, f = 1500, 10
    X = rng.rand(n, f).astype(np.float32)
    w = rng.randn(f).astype(np.float32)
    margin = X @ w + 0.5 * X[:, 0] * X[:, 1]
    params = dict(BASE, num_leaves=15, num_iterations=15)
    if objective == "binary":
        y = (margin + 0.3 * rng.randn(n) > np.median(margin)) \
            .astype(np.float32)
    elif objective == "regression":
        y = (margin + 0.1 * rng.randn(n)).astype(np.float32)
        params["objective"] = "regression"
    else:
        y = np.digitize(margin, np.quantile(margin, [0.33, 0.66])) \
            .astype(np.float32)
        params.update(objective="multiclass", num_class=3)

    def score(m):
        p = m.predict(X)
        if objective == "regression":
            return float(np.sqrt(np.mean((p - y) ** 2)))
        if objective == "multiclass":
            return 1.0 - float(np.mean(np.argmax(p, 1) == y))
        return 1.0 - float(np.mean((p > 0.5) == y))

    m_f32 = _train(X, y, params)
    bits = [16, 8] if objective == "binary" else [16]
    for b in bits:
        m_q = _train(X, y, dict(params, tpu_quantized_grad=b))
        assert score(m_q) <= score(m_f32) + metric_gate, \
            f"{objective} int{b} accuracy drifted past the gate"


@pytest.mark.slow
def test_screening_statistical_parity():
    """Screening holds predictive quality on data where half the
    features are noise (the regime it targets)."""
    rng = np.random.RandomState(15)
    n, f = 2000, 12
    X = rng.rand(n, f).astype(np.float32)
    y = ((X[:, 0] + X[:, 1] - X[:, 2]) + 0.3 * rng.randn(n) > 0) \
        .astype(np.float32)
    params = dict(BASE, num_leaves=15, num_iterations=20)
    m0 = _train(X, y, params)
    m1 = _train(X, y, dict(params, tpu_gain_screening=True,
                           tpu_screening_warmup=3,
                           tpu_screening_keep_ratio=0.4,
                           tpu_screening_explore_period=5))
    acc0 = np.mean((m0.predict(X) > 0.5) == y)
    acc1 = np.mean((m1.predict(X) > 0.5) == y)
    assert acc1 >= acc0 - 0.04


@pytest.mark.slow
def test_quant_adaptive_deterministic():
    """Quantization + adaptive bins together: identical reruns on the
    same driver serialize byte-identical trees (shared dither streams,
    exact integer sums, exact layout re-index). Cross-driver bit
    identity is deliberately NOT claimed for quantized runs — see
    test_quant_deterministic_and_cross_driver_parity — and screening's
    cross-driver contract is statistical parity
    (test_screening_statistical_parity)."""
    X, y = _mixed_data(seed=16)
    knobs = {"tpu_quantized_grad": 16, "tpu_adaptive_bins": True}
    m_a = _train(X, y, dict(BASE, **knobs))
    m_b = _train(X, y, dict(BASE, **knobs))
    assert _trees(m_a) == _trees(m_b)
    m_sync = _train(X, y, dict(BASE, tpu_fast_path=False, **knobs))
    a_f = np.mean((m_a.predict(X) > 0.5) == y)
    a_s = np.mean((m_sync.predict(X) > 0.5) == y)
    assert abs(a_f - a_s) <= 0.05


@pytest.mark.slow
def test_all_cuts_statistical_parity():
    """All three knobs on, fast path vs sync driver: same accuracy
    regime (the bit-level contracts are covered per-cut above)."""
    X, y = _mixed_data(seed=17, n=1024)
    m_fast = _train(X, y, dict(BASE, num_iterations=10, **KNOBS))
    m_sync = _train(X, y, dict(BASE, num_iterations=10,
                               tpu_fast_path=False, **KNOBS))
    a_f = np.mean((m_fast.predict(X) > 0.5) == y)
    a_s = np.mean((m_sync.predict(X) > 0.5) == y)
    assert abs(a_f - a_s) <= 0.04
