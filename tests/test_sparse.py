"""scipy CSR/CSC ingestion (round 3, VERDICT r2 missing #3).

The TPU-native storage answer to the reference's sparse bins
(ref: src/io/sparse_bin.hpp:73, c_api.cpp:398-520): mutually-exclusive
sparse features are bundled at INGESTION (EFB) and only the
[R, n_bundles] bundle matrix is materialised; training on it must
reproduce dense-trained models."""
import numpy as np
import pytest

scipy_sparse = pytest.importorskip("scipy.sparse")
import scipy.sparse as sp  # noqa: E402

import lightgbm_tpu as lgb  # noqa: E402


@pytest.fixture(scope="module")
def data():
    rng = np.random.RandomState(0)
    n, F = 8000, 300
    X = sp.random(n, F, density=0.01, format="csr", random_state=rng,
                  data_rvs=lambda k: rng.choice([1.0, 2.0, 3.0], k))
    Xd = X.toarray()
    w = np.zeros(F)
    w[:20] = rng.randn(20) * 2
    y = (Xd @ w + 0.3 * rng.randn(n) > 0).astype(np.float32)
    return X, Xd, y


def test_sparse_matches_dense_leafwise(data):
    X, Xd, y = data
    params = {"objective": "binary", "num_leaves": 31, "verbose": -1}
    bd = lgb.train(dict(params), lgb.Dataset(Xd, label=y),
                   num_boost_round=15)
    bs = lgb.train(dict(params), lgb.Dataset(X, label=y),
                   num_boost_round=15)
    assert bs._gbdt.use_bundles
    assert bs._gbdt.train_data.prebundled is not None
    # the bundled matrix must be much narrower than the logical space
    assert bs._gbdt.train_data.bins.shape[1] < X.shape[1] // 3
    np.testing.assert_allclose(bs.predict(Xd), bd.predict(Xd), atol=1e-6)


def test_sparse_predict_input_matches_dense_input(data):
    X, Xd, y = data
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbose": -1}, lgb.Dataset(X, label=y),
                    num_boost_round=8)
    np.testing.assert_array_equal(bst.predict(X), bst.predict(Xd))


def test_sparse_csc_equals_csr(data):
    X, Xd, y = data
    params = {"objective": "binary", "num_leaves": 15, "verbose": -1}
    b1 = lgb.train(dict(params), lgb.Dataset(X.tocsc(), label=y),
                   num_boost_round=5)
    b2 = lgb.train(dict(params), lgb.Dataset(X, label=y),
                   num_boost_round=5)
    np.testing.assert_array_equal(b1.predict(Xd), b2.predict(Xd))


def test_sparse_valid_set_and_early_stopping(data):
    X, Xd, y = data
    ds = lgb.Dataset(X[:6000], label=y[:6000])
    dv = lgb.Dataset(X[6000:], label=y[6000:], reference=ds)
    rec = {}
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbose": -1, "metric": "auc"}, ds,
                    num_boost_round=15, valid_sets=[dv],
                    callbacks=[lgb.record_evaluation(rec)])
    trace = rec["valid_0"]["auc"]
    assert len(trace) == 15
    from sklearn.metrics import roc_auc_score
    final = roc_auc_score(y[6000:], bst.predict(X[6000:]))
    assert abs(trace[-1] - final) < 1e-5


def test_sparse_fused_engine(data):
    # the fused engine consumes the same bundle layout (interpret mode);
    # quality must track the XLA depthwise grower on the same config
    X, Xd, y = data
    params = {"objective": "binary", "num_leaves": 15, "verbose": -1}
    bf = lgb.train(dict(params, tpu_engine="fused"),
                   lgb.Dataset(X, label=y), num_boost_round=5)
    assert bf._gbdt.use_fused and bf._gbdt.use_bundles
    bx = lgb.train(dict(params, grow_policy="depthwise"),
                   lgb.Dataset(X, label=y), num_boost_round=5)
    from sklearn.metrics import roc_auc_score
    auc_f = roc_auc_score(y, bf.predict(Xd))
    auc_x = roc_auc_score(y, bx.predict(Xd))
    assert abs(auc_f - auc_x) < 0.03 and auc_f > 0.55


def test_sparse_model_io_roundtrip(data):
    X, Xd, y = data
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbose": -1}, lgb.Dataset(X, label=y),
                    num_boost_round=5)
    loaded = lgb.Booster(model_str=bst.model_to_string())
    np.testing.assert_array_equal(loaded.predict(Xd), bst.predict(Xd))


def test_sparse_rejects_categorical_and_linear():
    X = sp.random(100, 10, density=0.2, format="csr",
                  random_state=np.random.RandomState(0))
    y = np.zeros(100, np.float32)
    with pytest.raises(lgb.LightGBMError):
        lgb.Dataset(X, label=y, categorical_feature=[1],
                    params={"verbose": -1}).construct()
    with pytest.raises(lgb.LightGBMError):
        lgb.Dataset(X, label=y,
                    params={"linear_tree": True,
                            "verbose": -1}).construct()


def test_sparse_zero_as_missing(data):
    # zero_as_missing puts implicit zeros in the NaN bin; the dense-
    # expanded member path must reproduce the dense-trained model
    X, Xd, y = data
    params = {"objective": "binary", "num_leaves": 15, "verbose": -1,
              "zero_as_missing": True}
    Xd_nan = Xd.copy()
    bd = lgb.train(dict(params), lgb.Dataset(Xd_nan, label=y),
                   num_boost_round=5)
    bs = lgb.train(dict(params), lgb.Dataset(X, label=y),
                   num_boost_round=5)
    np.testing.assert_allclose(bs.predict(Xd), bd.predict(Xd), atol=1e-6)
