"""Cost-effective gradient boosting penalties
(ref: cost_effective_gradient_boosting.hpp:22)."""
import numpy as np

import lightgbm_tpu as lgb


def _data(R=3000, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(R, 4).astype(np.float32)
    # feature 0 slightly stronger than feature 1; 2,3 noise
    y = (1.0 * X[:, 0] + 0.9 * X[:, 1] + 0.1 * rng.randn(R)) \
        .astype(np.float32)
    return X, y


def test_coupled_penalty_avoids_expensive_feature():
    X, y = _data()
    base = {"objective": "regression", "num_leaves": 15, "verbose": -1,
            "min_data_in_leaf": 5}
    ds1 = lgb.Dataset(X, label=y, params={"verbose": -1})
    bst = lgb.train(dict(base), ds1, num_boost_round=5)
    used_plain = set()
    for t in bst.models:
        used_plain |= set(t.split_feature[:t.num_internal].tolist())
    assert 0 in used_plain

    # make feature 0 prohibitively expensive to acquire
    ds2 = lgb.Dataset(X, label=y, params={"verbose": -1})
    bst2 = lgb.train(dict(base, cegb_tradeoff=1.0,
                          cegb_penalty_feature_coupled=[1e9, 0, 0, 0]),
                     ds2, num_boost_round=5)
    used = set()
    for t in bst2.models:
        used |= set(t.split_feature[:t.num_internal].tolist())
    assert 0 not in used
    # the model still learns from the remaining features
    mse = float(np.mean((bst2.predict(X) - y) ** 2))
    assert mse < np.var(y)


def test_split_penalty_shrinks_trees():
    X, y = _data(seed=1)
    base = {"objective": "regression", "num_leaves": 31, "verbose": -1,
            "min_data_in_leaf": 5}
    ds1 = lgb.Dataset(X, label=y, params={"verbose": -1})
    n_plain = sum(t.num_leaves for t in
                  lgb.train(dict(base), ds1, num_boost_round=3).models)
    ds2 = lgb.Dataset(X, label=y, params={"verbose": -1})
    n_pen = sum(t.num_leaves for t in
                lgb.train(dict(base, cegb_penalty_split=0.5), ds2,
                          num_boost_round=3).models)
    assert n_pen < n_plain


def test_cegb_lazy_penalty_blocks_expensive_feature():
    """cegb_penalty_feature_lazy (ref:
    cost_effective_gradient_boosting.hpp:22): the per-row acquisition
    cost is charged for every data point whose path has not used the
    feature yet — a huge lazy penalty on a feature prices it out
    entirely, while the same data without penalties uses it."""
    rng = np.random.RandomState(0)
    n = 3000
    X = rng.rand(n, 3)
    y = (X[:, 0] + 2.0 * X[:, 1] > 1.4).astype(np.float32)

    def tr(lazy):
        ds = lgb.Dataset(X, label=y, params={"verbose": -1})
        p = {"objective": "binary", "num_leaves": 15, "verbose": -1,
             "num_iterations": 5}
        if lazy is not None:
            p["cegb_penalty_feature_lazy"] = lazy
        return lgb.train(p, ds)

    free = tr(None)
    assert 1 in set(int(f) for ht in free._gbdt.models
                    for f in ht.split_feature)   # f1 is informative
    priced = tr([0.0, 1e6, 0.0])
    used = set(int(f) for ht in priced._gbdt.models
               for f in ht.split_feature if f >= 0)
    assert 1 not in used, used
    g = priced._gbdt
    assert g.use_cegb_lazy
    # the persistent bitmap filled in for the features actually used
    assert float(jnp_sum(g.cegb_used_rf)) > 0


def jnp_sum(x):
    import jax.numpy as jnp
    return jnp.sum(x)


def test_cegb_lazy_bitmap_persists_and_discounts_reuse():
    """The lazy bitmap is the reference's per-(row, feature)
    Get/SetUsedFeature store: rows that routed through a split on f have
    paid f's cost — their unused-count contribution drops to zero, and
    the bitmap persists ACROSS boosting iterations (it is never reset
    per tree)."""
    import jax.numpy as jnp
    from lightgbm_tpu.models.learner import cegb_delta_matrix
    from lightgbm_tpu.ops.split import SplitParams

    # formula: delta[s, f] = tradeoff * lazy[f] * unused_cnt[s, f] (+0)
    p = SplitParams(cegb_tradeoff=0.5)
    lazy = jnp.asarray([2.0, 0.0])
    unused = jnp.asarray([[10.0, 7.0], [0.0, 3.0]])
    delta = cegb_delta_matrix(p, jnp.zeros(2), jnp.zeros(2, bool),
                              jnp.zeros(2), lazy_penalty=lazy,
                              unused_cnt=unused)
    np.testing.assert_allclose(np.asarray(delta),
                               [[10.0, 0.0], [0.0, 0.0]])

    # end-to-end persistence: the bitmap only ever grows across updates
    rng = np.random.RandomState(3)
    n = 2000
    X = rng.rand(n, 3)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0.8).astype(np.float32)
    ds = lgb.Dataset(X, label=y, params={"verbose": -1})
    bst = lgb.Booster(params={"objective": "binary", "num_leaves": 7,
                              "verbose": -1,
                              "cegb_penalty_feature_lazy":
                              [1e-4, 1e-4, 1e-4]},
                      train_set=ds)
    g = bst._gbdt
    assert g.use_cegb_lazy
    covered = 0
    for _ in range(4):
        bst.update()
        now = int(np.asarray(g.cegb_used_rf).sum())
        assert now >= covered      # never resets between iterations
        covered = now
    assert covered > 0
