"""Cost-effective gradient boosting penalties
(ref: cost_effective_gradient_boosting.hpp:22)."""
import numpy as np

import lightgbm_tpu as lgb


def _data(R=3000, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(R, 4).astype(np.float32)
    # feature 0 slightly stronger than feature 1; 2,3 noise
    y = (1.0 * X[:, 0] + 0.9 * X[:, 1] + 0.1 * rng.randn(R)) \
        .astype(np.float32)
    return X, y


def test_coupled_penalty_avoids_expensive_feature():
    X, y = _data()
    base = {"objective": "regression", "num_leaves": 15, "verbose": -1,
            "min_data_in_leaf": 5}
    ds1 = lgb.Dataset(X, label=y, params={"verbose": -1})
    bst = lgb.train(dict(base), ds1, num_boost_round=5)
    used_plain = set()
    for t in bst.models:
        used_plain |= set(t.split_feature[:t.num_internal].tolist())
    assert 0 in used_plain

    # make feature 0 prohibitively expensive to acquire
    ds2 = lgb.Dataset(X, label=y, params={"verbose": -1})
    bst2 = lgb.train(dict(base, cegb_tradeoff=1.0,
                          cegb_penalty_feature_coupled=[1e9, 0, 0, 0]),
                     ds2, num_boost_round=5)
    used = set()
    for t in bst2.models:
        used |= set(t.split_feature[:t.num_internal].tolist())
    assert 0 not in used
    # the model still learns from the remaining features
    mse = float(np.mean((bst2.predict(X) - y) ** 2))
    assert mse < np.var(y)


def test_split_penalty_shrinks_trees():
    X, y = _data(seed=1)
    base = {"objective": "regression", "num_leaves": 31, "verbose": -1,
            "min_data_in_leaf": 5}
    ds1 = lgb.Dataset(X, label=y, params={"verbose": -1})
    n_plain = sum(t.num_leaves for t in
                  lgb.train(dict(base), ds1, num_boost_round=3).models)
    ds2 = lgb.Dataset(X, label=y, params={"verbose": -1})
    n_pen = sum(t.num_leaves for t in
                lgb.train(dict(base, cegb_penalty_split=0.5), ds2,
                          num_boost_round=3).models)
    assert n_pen < n_plain
