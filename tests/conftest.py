import os

# Multi-device testing on a virtual CPU mesh (SURVEY.md §4 implication):
# replaces the reference's localhost-subprocess distributed mockup
# (tests/distributed/_test_distributed.py).  XLA_FLAGS must be set before
# jax initializes its backends; jax.config.update beats the JAX_PLATFORMS
# env var, which the runtime environment may pin to a TPU platform.
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the learner jit varies with static shapes
# (rows, features, num_leaves, max_bins), so repeat suite runs hit the disk
# cache instead of re-tracing (~10-30 s per unique shape on CPU).
jax.config.update("jax_compilation_cache_dir", "/tmp/lgbm_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import pytest  # noqa: E402

# The fused engine runs the Pallas kernels in INTERPRET mode off-TPU
# (gbdt.fused_interpret): pure-Python emulation that costs minutes per
# test on the CPU backend this suite pins above, where a real chip takes
# milliseconds. The heaviest such tests (>= ~15 s each, measured; ~1000 s
# combined) are marked `slow` so the bounded tier-1 sweep (ROADMAP.md:
# `-m 'not slow'` under a timeout) spends its window on broad coverage —
# run them explicitly with `-m slow` (or no -m filter) before touching
# kernel or engine code. test_fused_level.py (the kernel's own unit
# tests) and the fused smoke variants stay in tier-1.
_INTERPRET_HEAVY = {
    ("test_categorical.py", "test_categorical_beats_numerical_coding[fused]"),
    ("test_efb.py", "test_dense_path_bundle_count_near_ideal"),
    ("test_efb.py", "test_bundled_categorical_matches_unbundled"),
    ("test_efb.py", "test_fused_bundles_with_missing_values"),
    ("test_efb.py", "test_fused_engine_with_bundles_matches_unbundled"),
    ("test_epilogue.py", "test_binary_epilogue_identical"),
    ("test_epilogue.py", "test_binary_epilogue_deep_tree_terminal_route"),
    ("test_epilogue.py", "test_epilogue_early_stop_semantics"),
    ("test_epilogue.py", "test_epilogue_with_bagging_lookahead"),
    ("test_epilogue.py", "test_epilogue_feature_fraction"),
    ("test_epilogue.py", "test_l2_epilogue_identical"),
    ("test_fast_pipeline.py", "test_fast_matches_sync_path"),
    ("test_megastep.py", "test_megastep_bit_identical_to_fast_path"),
    ("test_megastep.py", "test_megastep_early_stop_across_boundary"),
    ("test_megastep.py", "test_megastep_valid_and_bagging"),
    ("test_megastep.py",
     "test_telemetry_iteration_granularity_keeps_fast_path"),
    ("test_megastep.py", "test_telemetry_section_granularity_forces_sync"),
    ("test_megastep.py", "test_trace_out_implies_section_granularity"),
    ("test_megastep.py", "test_update_contract_unchanged"),
    ("test_traced_eval.py", "test_multiclass_megastep_eval"),
    ("test_traced_eval.py", "test_first_metric_only_multi_eval_set"),
    ("test_traced_eval.py", "test_nan_features_megastep_eval"),
    ("test_traced_eval.py",
     "test_early_stopped_model_bit_identical_to_sync"),
    ("test_traced_eval.py",
     "test_megastep_stays_on_with_builtin_callbacks"),
    ("test_traced_eval.py", "test_snapshots_written_at_drain"),
    ("test_traced_eval.py",
     "test_megastep_evicted_event_names_feature"),
    ("test_traced_eval.py", "test_chunk_of_one_flows_through_scan"),
    ("test_traced_eval.py",
     "test_booster_trainable_after_drain_replay_stop"),
    ("test_fast_pipeline.py", "test_multiclass_fast_matches_sync"),
    ("test_fast_pipeline.py", "test_multiclass_rare_class_keeps_init_score"),
    ("test_fast_pipeline.py",
     "test_subclassed_objective_not_trained_with_base_gradients"),
    ("test_fast_valid.py", "test_valid_traces_match_unfused_path"),
    ("test_fast_valid.py", "test_fast_path_stays_on_with_valid"),
    ("test_fast_valid.py", "test_device_metrics_match_host_metrics"),
    ("test_fast_valid.py", "test_early_stopping_fires_on_fast_path"),
    ("test_fused_engine.py", "test_fused_engine_trains_binary"),
    ("test_fused_engine.py", "test_reset_parameter_callback_with_fused_engine"),
    ("test_fused_parallel.py",
     "test_fused_feature_parallel_with_interaction_constraints"),
    ("test_fused_parallel.py", "test_fused_feature_parallel_with_efb"),
    ("test_fused_parallel.py", "test_fused_feature_parallel_matches_serial"),
    ("test_fused_parallel.py", "test_fused_voting_small_topk_trains"),
    ("test_fused_parallel.py", "test_fused_voting_multiclass"),
    ("test_fused_parallel.py", "test_fused_voting_full_topk_matches_data"),
    ("test_fused_parallel.py", "test_fused_voting_matches_xla_voting_auc"),
    ("test_monotone.py", "test_intermediate_under_fused_feature_parallel"),
    ("test_monotone.py",
     "test_intermediate_mode_monotone_and_tighter_fit[fused-depthwise]"),
    ("test_monotone.py", "test_no_transitive_violation[fused-depthwise]"),
}


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: interpret-mode fused-engine tests costing "
        "minutes on the CPU backend (run with -m slow)")
    config.addinivalue_line(
        "markers", "chaos: multi-process fault-injection acceptance "
        "tests (the CI chaos-acceptance job runs -m chaos; also part "
        "of the weekly slow pass via the paired slow marker)")


def pytest_collection_modifyitems(config, items):
    matched = set()
    for item in items:
        key = (item.fspath.basename, item.name)
        if key in _INTERPRET_HEAVY:
            item.add_marker(pytest.mark.slow)
            matched.add(key)
    # a renamed/re-parametrized test silently un-marks itself and blows
    # the bounded tier-1 window — surface the stale entry (only for
    # files that WERE collected, so single-file runs don't false-alarm;
    # a warning not an error, since -k/-m filters also shrink `items`)
    collected = {item.fspath.basename for item in items}
    stale = [k for k in _INTERPRET_HEAVY - matched if k[0] in collected]
    for basename, name in sorted(stale):
        import warnings
        warnings.warn(pytest.PytestWarning(
            f"stale _INTERPRET_HEAVY entry (no such test collected): "
            f"{basename}::{name}"))


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """The XLA CPU compiler segfaults after a few hundred compilations
    accumulate in one process (observed at ~85% of the full suite;
    every file passes in isolation). Dropping executable references
    between modules keeps the process well under that ceiling; the disk
    cache above makes any recompiles cheap."""
    yield
    jax.clear_caches()
