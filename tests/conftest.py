import os

# Multi-device testing on a virtual CPU mesh (SURVEY.md §4 implication):
# replaces the reference's localhost-subprocess distributed mockup
# (tests/distributed/_test_distributed.py).  XLA_FLAGS must be set before
# jax initializes its backends; jax.config.update beats the JAX_PLATFORMS
# env var, which the runtime environment may pin to a TPU platform.
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the learner jit varies with static shapes
# (rows, features, num_leaves, max_bins), so repeat suite runs hit the disk
# cache instead of re-tracing (~10-30 s per unique shape on CPU).
jax.config.update("jax_compilation_cache_dir", "/tmp/lgbm_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import pytest  # noqa: E402


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """The XLA CPU compiler segfaults after a few hundred compilations
    accumulate in one process (observed at ~85% of the full suite;
    every file passes in isolation). Dropping executable references
    between modules keeps the process well under that ceiling; the disk
    cache above makes any recompiles cheap."""
    yield
    jax.clear_caches()
