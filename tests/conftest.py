import os

# Multi-device testing on a virtual CPU mesh (SURVEY.md §4 implication):
# replaces the reference's localhost-subprocess distributed mockup
# (tests/distributed/_test_distributed.py).
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")
