"""Interaction constraints + feature_fraction_bynode
(ref: col_sampler.hpp:20 ColSampler)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _data(R=3000, F=6, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(R, F).astype(np.float32)
    y = (X[:, 0] + X[:, 2] + 0.5 * X[:, 4] + 0.1 * rng.randn(R)) \
        .astype(np.float32)
    return X, y


def _paths(bst):
    """Feature sets per root-to-leaf path for every tree."""
    out = []
    for ti in bst.dump_model()["tree_info"]:
        def walk(n, path):
            if "split_feature" in n:
                p2 = path | {n["split_feature"]}
                walk(n["left_child"], p2)
                walk(n["right_child"], p2)
            elif path:
                out.append(path)
        walk(ti["tree_structure"], set())
    return out


@pytest.mark.parametrize("engine,policy", [("xla", "leafwise"),
                                           ("xla", "depthwise"),
                                           ("fused", "depthwise")])
def test_interaction_constraints_respected(engine, policy):
    X, y = _data()
    groups = [[0, 1], [2, 3], [4, 5]]
    ds = lgb.Dataset(X, label=y, params={"verbose": -1})
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "verbose": -1, "min_data_in_leaf": 5,
                     "interaction_constraints": groups,
                     "grow_policy": policy, "tpu_engine": engine},
                    ds, num_boost_round=8)
    for path in _paths(bst):
        assert any(path <= set(g) for g in groups), \
            f"path {path} crosses constraint groups"
    # still learns: each signal feature lives in its own group
    mse = float(np.mean((bst.predict(X) - y) ** 2))
    assert mse < np.var(y)


def test_feature_fraction_bynode_varies_features():
    X, y = _data(F=8)
    ds = lgb.Dataset(X, label=y, params={"verbose": -1})
    bst = lgb.train({"objective": "regression", "num_leaves": 31,
                     "verbose": -1, "min_data_in_leaf": 5,
                     "feature_fraction_bynode": 0.4},
                    ds, num_boost_round=5)
    # trees must still learn and no single node sees all features;
    # with 0.4 sampling, the used-feature pool across nodes stays diverse
    used = set()
    for p in _paths(bst):
        used |= p
    assert len(used) >= 3
    mse = float(np.mean((bst.predict(X) - y) ** 2))
    assert mse < np.var(y)
