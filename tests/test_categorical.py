"""End-to-end categorical feature training (ref categorical pipeline:
bin.cpp:424-491 categorical binning, feature_histogram.hpp:278-470 split
search, tree.h CategoricalDecision predict)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _cat_data(R=4000, n_cats=12, seed=0):
    """Target depends on a scattered subset of categories — a single
    numerical threshold over count-ordered bins cannot separate it."""
    rng = np.random.RandomState(seed)
    cat = rng.randint(0, n_cats, size=R)
    good = {1, 4, 7, 10}
    noise = 0.15 * rng.randn(R)
    y = (np.isin(cat, list(good)).astype(np.float32)
         + noise > 0.5).astype(np.float32)
    num = rng.randn(R).astype(np.float32)
    X = np.stack([cat.astype(np.float32), num], axis=1)
    return X, y, good


@pytest.mark.parametrize("engine", ["xla", "fused"])
def test_categorical_beats_numerical_coding(engine):
    X, y, _ = _cat_data()
    base = {"objective": "binary", "num_leaves": 8, "verbose": -1,
            "min_data_in_leaf": 5, "min_data_per_group": 5,
            "cat_smooth": 1.0, "tpu_engine": engine,
            "grow_policy": "depthwise"}
    from sklearn.metrics import roc_auc_score

    ds_cat = lgb.Dataset(X, label=y, params={"verbose": -1},
                         categorical_feature=[0])
    bst_cat = lgb.train(base, ds_cat, num_boost_round=5)
    auc_cat = roc_auc_score(y, bst_cat.predict(X))

    ds_num = lgb.Dataset(X, label=y, params={"verbose": -1})
    bst_num = lgb.train(dict(base, num_leaves=4), ds_num, num_boost_round=1)
    auc_num = roc_auc_score(y, bst_num.predict(X))

    assert auc_cat > 0.90, auc_cat
    # one categorical tree separates what shallow numerical trees cannot
    assert auc_cat > auc_num


def test_categorical_model_roundtrip(tmp_path):
    X, y, good = _cat_data(seed=2)
    ds = lgb.Dataset(X, label=y, params={"verbose": -1},
                     categorical_feature=[0])
    bst = lgb.train({"objective": "binary", "num_leaves": 8, "verbose": -1,
                     "min_data_in_leaf": 5, "min_data_per_group": 5,
                     "cat_smooth": 1.0, "tpu_engine": "xla"},
                    ds, num_boost_round=4)
    pred = bst.predict(X)
    path = str(tmp_path / "cat_model.txt")
    bst.save_model(path)
    txt = open(path).read()
    assert "cat_boundaries" in txt and "cat_threshold" in txt
    bst2 = lgb.Booster(model_file=path)
    np.testing.assert_allclose(bst2.predict(X), pred, rtol=1e-10)
    # unseen category value routes right (not in any bitset), no crash
    Xu = X.copy()
    Xu[:5, 0] = 99.0
    _ = bst2.predict(Xu)


def test_categorical_valid_eval_matches_predict():
    X, y, _ = _cat_data(seed=3)
    Xv, yv = X[3000:], y[3000:]
    ds = lgb.Dataset(X[:3000], label=y[:3000], params={"verbose": -1},
                     categorical_feature=[0])
    dv = ds.create_valid(Xv, label=yv)
    evals = {}
    bst = lgb.train({"objective": "binary", "num_leaves": 8, "verbose": -1,
                     "metric": "binary_logloss", "min_data_in_leaf": 5,
                     "min_data_per_group": 5, "cat_smooth": 1.0,
                     "tpu_engine": "xla"},
                    ds, num_boost_round=4, valid_sets=[dv],
                    valid_names=["v"],
                    callbacks=[lgb.record_evaluation(evals)])
    from sklearn.metrics import log_loss
    want = log_loss(yv, bst.predict(Xv))
    got = evals["v"]["binary_logloss"][-1]
    assert abs(want - got) < 5e-3, (want, got)


def test_binary_cache_roundtrip_with_categorical(tmp_path):
    """Dataset binary cache preserves categorical vocab + bins
    (ref: dataset_loader.cpp:336 LoadFromBinFile)."""
    import numpy as np
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import TpuDataset
    X, y, _ = _cat_data(R=800, seed=7)
    cfg = Config({"verbose": -1})
    ds = TpuDataset.from_data(np.asarray(X, np.float64), cfg,
                              categorical_feature=[0])
    path = str(tmp_path / "d.bin")
    ds.save_binary(path)
    ds2 = TpuDataset.load_binary(path)
    np.testing.assert_array_equal(np.asarray(ds.bins),
                                  np.asarray(ds2.bins))
    assert ds2.is_categorical[0] and not ds2.is_categorical[1]
    m1, m2 = ds.mappers[0], ds2.mappers[0]
    assert m1.bin_2_categorical == m2.bin_2_categorical


def test_dart_with_categorical():
    """DART dropout + categorical splits: valid-set scoring and dropout
    re-routing must handle categorical device trees."""
    X, y, _ = _cat_data(R=2500, seed=11)
    Xv, yv = X[2000:], y[2000:]
    ds = lgb.Dataset(X[:2000], label=y[:2000], params={"verbose": -1},
                     categorical_feature=[0])
    dv = ds.create_valid(Xv, label=yv)
    bst = lgb.train({"objective": "binary", "boosting": "dart",
                     "num_leaves": 8, "drop_rate": 0.3, "verbose": -1,
                     "min_data_in_leaf": 5, "min_data_per_group": 5,
                     "cat_smooth": 1.0, "metric": "binary_logloss"},
                    ds, num_boost_round=8, valid_sets=[dv])
    from sklearn.metrics import roc_auc_score
    assert roc_auc_score(yv, bst.predict(Xv)) > 0.85


def test_pred_contrib_with_categorical():
    """TreeSHAP contributions sum to the raw prediction, incl.
    categorical splits (ref: tree.h:437 PredictContrib)."""
    X, y, _ = _cat_data(R=1200, seed=13)
    ds = lgb.Dataset(X, label=y, params={"verbose": -1},
                     categorical_feature=[0])
    bst = lgb.train({"objective": "binary", "num_leaves": 8, "verbose": -1,
                     "min_data_in_leaf": 5, "min_data_per_group": 5,
                     "cat_smooth": 1.0}, ds, num_boost_round=4)
    contrib = bst.predict(X[:50], pred_contrib=True)
    raw = bst.predict(X[:50], raw_score=True)
    assert contrib.shape == (50, X.shape[1] + 1)
    np.testing.assert_allclose(contrib.sum(axis=1), raw, rtol=1e-5,
                               atol=1e-6)
