"""Fused Pallas engine x distribution-mode composition (VERDICT r4
missing #2): the reference instantiates its device learner under every
distribution mode ({Data,Voting,Feature}ParallelTreeLearner<GPUTreeLearner>,
ref: src/treelearner/tree_learner.cpp:17-49); round 5 composes the fused
engine with voting- and feature-parallel the same way (data-parallel
composed since round 2). Runs on the 8-virtual-device CPU mesh in
interpret mode through the real lgb.train() driver.
"""
import jax
import numpy as np
import pytest

import lightgbm_tpu as lgb


@pytest.fixture(scope="module")
def data():
    rng = np.random.RandomState(11)
    n = 4096
    X = rng.randn(n, 10)
    X[rng.rand(n, 10) < 0.04] = np.nan
    y = (np.nan_to_num(X[:, 0]) + 0.6 * np.nan_to_num(X[:, 2])
         > 0.3).astype(np.float32)
    return X, y


BASE = {"objective": "binary", "num_leaves": 15, "num_iterations": 4,
        "min_data_in_leaf": 5, "verbose": -1, "tpu_engine": "fused"}


def _model(X, y, params):
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train(dict(params), ds)
    # strip the saved-parameters block: tree_learner/top_k legitimately
    # differ between the runs being compared; the TREES must not
    s = bst.model_to_string(num_iteration=-1)
    return bst, s.split("\nparameters:")[0]


def _auc(bst, X, y):
    from sklearn.metrics import roc_auc_score
    return roc_auc_score(y, bst.predict(X))


def test_fused_voting_full_topk_matches_data(data):
    """top_k >= F: every column wins the vote, which statically takes the
    data-parallel full-exchange path — the tree must equal the
    data-parallel fused tree BIT-FOR-BIT. Both runs pin the synchronous
    driver (tpu_fast_path=false): voting always runs sync, and the
    pipelined fast path's fused epilogue is numerically equivalent but
    not bit-identical to it."""
    X, y = data
    _, m_data = _model(X, y, dict(BASE, tree_learner="data",
                                  tpu_fast_path=False))
    _, m_vote = _model(X, y, dict(BASE, tree_learner="voting",
                                  top_k=X.shape[1]))
    assert m_vote == m_data


def test_fused_voting_small_topk_trains(data):
    """A tight vote (top_k=2 of 10 features) still trains a good model —
    the informative features win the vote (the reference's voting
    accuracy claim, voting_parallel_tree_learner.cpp header)."""
    X, y = data
    bst, m_vote = _model(X, y, dict(BASE, tree_learner="voting", top_k=2))
    assert _auc(bst, X, y) > 0.93
    # and the run genuinely restricted the exchange: trees may differ
    # from the full-exchange model (not asserted equal — just sane)
    bst_d, _ = _model(X, y, dict(BASE, tree_learner="data"))
    assert abs(_auc(bst, X, y) - _auc(bst_d, X, y)) < 0.03


def test_fused_voting_matches_xla_voting_auc(data):
    """Same vote rule as the XLA growers' exchange: model quality must
    agree closely (bit-identity is not expected — the engines accumulate
    histograms in different precisions)."""
    X, y = data
    b_f, _ = _model(X, y, dict(BASE, tree_learner="voting", top_k=3))
    b_x, _ = _model(X, y, dict(BASE, tree_learner="voting", top_k=3,
                               tpu_engine="xla", grow_policy="depthwise"))
    assert abs(_auc(b_f, X, y) - _auc(b_x, X, y)) < 0.02


def test_fused_feature_parallel_matches_serial(data):
    """Feature-parallel fused: replicated rows, per-shard column masks,
    per-level best-split record merge — must reproduce the serial fused
    model bit-for-bit (local histograms are complete; the merge's
    tie-breaking matches the serial scan)."""
    X, y = data
    _, m_serial = _model(X, y, dict(BASE, tpu_fast_path=False))
    _, m_feat = _model(X, y, dict(BASE, tree_learner="feature"))
    assert m_feat == m_serial


def test_fused_feature_parallel_weighted(data):
    X, y = data
    rng = np.random.RandomState(3)
    w = rng.rand(len(y)).astype(np.float64) + 0.5
    ds1 = lgb.Dataset(X, label=y, weight=w)
    m1 = lgb.train(dict(BASE, tpu_fast_path=False), ds1).model_to_string(
        num_iteration=-1).split("\nparameters:")[0]
    ds8 = lgb.Dataset(X, label=y, weight=w)
    m8 = lgb.train(dict(BASE, tree_learner="feature"),
                   ds8).model_to_string(
        num_iteration=-1).split("\nparameters:")[0]
    assert m8 == m1


def test_fused_voting_multiclass(data):
    X, _ = data
    rng = np.random.RandomState(5)
    yc = (np.nan_to_num(X[:, 0]) > 0.5).astype(int) \
        + (np.nan_to_num(X[:, 2]) > 0.0).astype(int)
    params = dict(BASE, objective="multiclass", num_class=3,
                  tree_learner="voting", top_k=4)
    ds = lgb.Dataset(X, label=yc.astype(np.float64))
    bst = lgb.train(params, ds)
    acc = (np.argmax(bst.predict(X), axis=1) == yc).mean()
    assert acc > 0.85


def test_forced_splits_under_voting(tmp_path):
    """VERDICT r4 item 7: forced splits compose with voting-parallel —
    the vote exchange always sums the forced features' columns, so the
    forced schedule executes identically to the serial run even when
    those features would lose the vote."""
    import json
    rng = np.random.RandomState(0)
    X = rng.rand(3000, 6).astype(np.float64)
    y = (X[:, 5] > 0.5).astype(np.float32)       # signal on feature 5
    fs = {"feature": 0, "threshold": 0.5,
          "left": {"feature": 1, "threshold": 0.3}}
    path = str(tmp_path / "forced.json")
    json.dump(fs, open(path, "w"))
    params = {"objective": "binary", "num_leaves": 8, "verbose": -1,
              "min_data_in_leaf": 5, "forcedsplits_filename": path,
              "num_iterations": 2}
    ds_s = lgb.Dataset(X, label=y, params={"verbose": -1})
    m_s = lgb.train(dict(params), ds_s).model_to_string(
        num_iteration=-1).split("\nparameters:")[0]
    # tight vote: top_k=1 of 6 — the forced features 0/1 would never win
    ds_v = lgb.Dataset(X, label=y, params={"verbose": -1})
    bst_v = lgb.train(dict(params, tree_learner="voting", top_k=1), ds_v)
    m_v = bst_v.model_to_string(num_iteration=-1).split("\nparameters:")[0]
    t = bst_v.models[0]
    assert int(t.split_feature[0]) == 0
    assert int(t.split_feature[1]) == 1
    assert bst_v._gbdt.parallel_mode == "voting"
    # with the forced columns always exchanged, the serial schedule is
    # reproduced; the free splits may differ under the tight vote, so
    # only the forced prefix is asserted structurally
    assert m_v.count("Tree=") == m_s.count("Tree=")


def test_fused_feature_parallel_with_efb(data):
    """VERDICT r4 item 7: EFB composes with feature-parallel on the fused
    engine (replicated layout keeps global feature indices through the
    bundle decode) — must match the serial fused EFB model bit-for-bit."""
    rng = np.random.RandomState(9)
    n = 4096
    # near-exclusive sparse block: bundling engages
    Xs = np.zeros((n, 8))
    owner = rng.randint(0, 8, n)
    Xs[np.arange(n), owner] = rng.rand(n) + 0.5
    Xd = rng.rand(n, 2)
    X = np.column_stack([Xd, Xs])
    y = (Xd[:, 0] + Xs[:, 0] > 0.8).astype(np.float32)
    params = dict(BASE, num_iterations=3, enable_bundle=True)
    _, m_serial = _model(X, y, params)
    bst_f, m_feat = _model(X, y, dict(params, tree_learner="feature"))
    assert bst_f._gbdt.parallel_mode == "feature"
    assert getattr(bst_f._gbdt, "use_bundles", False), \
        "bundling did not engage — the composition claim is vacuous"
    assert m_feat == m_serial


def test_fused_feature_parallel_with_interaction_constraints(data):
    """Interaction constraints compose with fused feature-parallel
    (node masks are global under the replicated layout)."""
    X, y = data
    params = dict(BASE, num_iterations=3,
                  interaction_constraints=[[0, 2], [1, 3, 4]])
    bst_s, m_serial = _model(X, y, params)
    bst_f, m_feat = _model(X, y, dict(params, tree_learner="feature"))
    assert bst_f._gbdt.parallel_mode == "feature"
    assert m_feat == m_serial
    # constraints actually bind: every tree's features stay in one group
    for t in bst_f.models:
        used = set(int(f) for f in t.split_feature[:max(0, t.num_leaves - 1)])
        assert used <= {0, 2} or used <= {1, 3, 4}, used
