"""Distributed dataset loading parity (VERDICT r2 missing #5): per-rank
file partitions must produce IDENTICAL bin mappers on every rank — the
TPU-native form of the reference's feature-sharded FindBin + mapper
allgather (ref: src/io/dataset_loader.cpp:1015,1146-1154).

Mirrors the reference's distributed mockup (tests/distributed/
_test_distributed.py): real subprocesses, one per rank, joined through
jax.distributed over localhost."""
import json
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

_WORKER = textwrap.dedent("""
    import json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=sys.argv[1],
        num_processes=int(sys.argv[2]), process_id=int(sys.argv[3]))
    import numpy as np
    import lightgbm_tpu as lgb

    path, out_path = sys.argv[4], sys.argv[5]
    ds = lgb.Dataset(path, params={"label_column": 0, "verbose": -1,
                                   "max_bin": 31})
    ds.construct()
    inner = ds._inner
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "num_iterations": 3, "verbose": -1}, ds)
    report = {
        "rank": jax.process_index(),
        "num_rows": int(inner.num_data),
        "bounds": [[float(b) for b in m.bin_upper_bound]
                   for m in inner.mappers],
        "model": bst.model_to_string(),
    }
    with open(out_path, "w") as fh:
        json.dump(report, fh)
""")


def test_two_process_loading_shares_mappers(tmp_path):
    rng = np.random.RandomState(3)
    n = 3001   # odd: unequal shards exercise the allgather padding
    X = rng.randn(n, 5)
    # rank shards see DIFFERENT distributions (sorted rows) so local-only
    # binning would produce different mappers — the allgather must fix it
    X = X[np.argsort(X[:, 0])]
    y = (X[:, 0] + X[:, 1] > 0).astype(np.float64)
    train = tmp_path / "train.csv"
    np.savetxt(train, np.column_stack([y, X]), delimiter=",", fmt="%.6f")

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    outs = [tmp_path / f"rank{i}.json" for i in range(2)]
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # ONLY the repo on the path: the axon TPU plugin breaks multiprocess
    # CPU backends (process_count stays 1)
    env["PYTHONPATH"] = repo_root
    env.pop("XLA_FLAGS", None)
    procs = [subprocess.Popen(
        [sys.executable, str(script), coord, "2", str(i), str(train),
         str(outs[i])], env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE) for i in range(2)]
    for p in procs:
        out, err = p.communicate(timeout=600)
        assert p.returncode == 0, err.decode()[-2000:]

    reports = [json.loads(o.read_text()) for o in outs]
    # disjoint contiguous shards covering the file
    assert reports[0]["num_rows"] + reports[1]["num_rows"] == n
    assert reports[0]["num_rows"] not in (0, n)
    # IDENTICAL mappers everywhere despite skewed shards
    assert reports[0]["bounds"] == reports[1]["bounds"]
    # tree_learner=serial trains rank-LOCAL models on the skewed shards —
    # they must differ (the joint-model claim lives in
    # test_multiproc_train.py, where tree_learner=data makes every rank
    # emit the identical model)
    assert reports[0]["model"] != reports[1]["model"]
    # single-process local-only binning of one skewed shard must differ —
    # otherwise this test would pass vacuously
    import lightgbm_tpu as lgb
    half = lgb.Dataset(np.ascontiguousarray(X[:n // 2]),
                       params={"verbose": -1, "max_bin": 31})
    half.construct()
    local_bounds = [[float(b) for b in m.bin_upper_bound]
                    for m in half._inner.mappers]
    assert local_bounds != reports[0]["bounds"]
