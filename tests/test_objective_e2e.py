"""End-to-end convergence for the long-tail objective families
(ref: src/objective/regression_objective.hpp, xentropy_objective.hpp)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _metric_value(bst, ds_name="training"):
    return None


@pytest.mark.parametrize("objective,metric,make_y", [
    ("poisson", "poisson", lambda r, mu: r.poisson(mu)),
    ("tweedie", "tweedie", lambda r, mu: np.where(r.rand(len(mu)) < 0.3,
                                                  0.0, mu * r.rand(len(mu))
                                                  * 2)),
    ("huber", "huber", lambda r, mu: mu + 0.1 * r.standard_cauchy(len(mu))),
    ("mape", "mape", lambda r, mu: np.maximum(mu + 0.2 * r.randn(len(mu)),
                                              0.1)),
    ("gamma", "gamma", lambda r, mu: r.gamma(2.0, mu / 2.0) + 1e-3),
    ("fair", "fair", lambda r, mu: mu + 0.2 * r.randn(len(mu))),
    ("cross_entropy", "cross_entropy",
     lambda r, mu: (r.rand(len(mu)) < 1 / (1 + np.exp(-(mu - 1.5)))) * 1.0),
])
def test_objective_converges(objective, metric, make_y):
    rng = np.random.RandomState(0)
    R = 2500
    X = rng.rand(R, 4).astype(np.float32)
    mu = 1.0 + 2.0 * X[:, 0] + X[:, 1]
    y = np.asarray(make_y(rng, mu), np.float32)
    evals = {}
    ds = lgb.Dataset(X, label=y, params={"verbose": -1})
    lgb.train({"objective": objective, "num_leaves": 15, "verbose": -1,
               "min_data_in_leaf": 10, "metric": metric},
              ds, num_boost_round=25, valid_sets=[ds],
              valid_names=["training"],
              callbacks=[lgb.record_evaluation(evals)])
    series = list(evals["training"].values())[0]
    assert series[-1] < series[0], (objective, series[0], series[-1])
    drop = (series[0] - series[-1]) / (abs(series[0]) + 1e-12)
    # log-link deviances (tweedie/gamma) move slowly in relative units
    floor = 0.005 if objective in ("tweedie", "gamma") else 0.05
    assert drop > floor, (objective, drop)
