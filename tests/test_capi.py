"""The LGBM_* C ABI (native/capi.cpp + capi_support.py).

Drives the compiled shared library through ctypes exactly the way the
reference's own python-package drives lib_lightgbm (ref:
python-package/lightgbm/basic.py _LIB usage) — create a dataset from a
raw float matrix, set the label field, train, predict, save, reload.
"""
import ctypes
import os

import numpy as np
import pytest

from lightgbm_tpu.native.loader import build_capi


@pytest.fixture(scope="module")
def lib():
    path = build_capi()
    if path is None:
        pytest.skip("no native toolchain")
    lib = ctypes.CDLL(path)
    lib.LGBM_GetLastError.restype = ctypes.c_char_p
    return lib


def _check(lib, rc):
    assert rc == 0, lib.LGBM_GetLastError().decode()


def test_capi_full_lifecycle(lib, tmp_path):
    rng = np.random.RandomState(0)
    X = rng.rand(1200, 6).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float32)

    ds = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateFromMat(
        X.ctypes.data_as(ctypes.c_void_p), 0, 1200, 6, 1,
        b"max_bin=63 verbose=-1", None, ctypes.byref(ds)))
    _check(lib, lib.LGBM_DatasetSetField(
        ds, b"label", y.ctypes.data_as(ctypes.c_void_p), 1200, 0))

    n = ctypes.c_int32()
    _check(lib, lib.LGBM_DatasetGetNumData(ds, ctypes.byref(n)))
    assert n.value == 1200
    _check(lib, lib.LGBM_DatasetGetNumFeature(ds, ctypes.byref(n)))
    assert n.value == 6

    bst = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreate(
        ds, b"objective=binary num_leaves=15 learning_rate=0.2 verbose=-1",
        ctypes.byref(bst)))
    fin = ctypes.c_int()
    for _ in range(10):
        _check(lib, lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)))
    it = ctypes.c_int()
    _check(lib, lib.LGBM_BoosterGetCurrentIteration(bst, ctypes.byref(it)))
    assert it.value == 10
    nc = ctypes.c_int()
    _check(lib, lib.LGBM_BoosterGetNumClasses(bst, ctypes.byref(nc)))
    assert nc.value == 1

    need = ctypes.c_int64()
    _check(lib, lib.LGBM_BoosterCalcNumPredict(
        bst, 1200, 0, 0, -1, ctypes.byref(need)))
    assert need.value == 1200
    _check(lib, lib.LGBM_BoosterCalcNumPredict(
        bst, 1200, 2, 0, -1, ctypes.byref(need)))
    assert need.value == 1200 * 10      # leaf index: one per tree

    out = np.zeros(1200, np.float64)
    out_len = ctypes.c_int64()
    _check(lib, lib.LGBM_BoosterPredictForMat(
        bst, X.ctypes.data_as(ctypes.c_void_p), 0, 1200, 6, 1, 0, 0, -1,
        b"", ctypes.byref(out_len),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    assert out_len.value == 1200
    from sklearn.metrics import roc_auc_score
    assert roc_auc_score(y, out) > 0.95

    model_path = str(tmp_path / "capi_model.txt").encode()
    _check(lib, lib.LGBM_BoosterSaveModel(bst, 0, -1, 0, model_path))

    bst2 = ctypes.c_void_p()
    iters = ctypes.c_int()
    _check(lib, lib.LGBM_BoosterCreateFromModelfile(
        model_path, ctypes.byref(iters), ctypes.byref(bst2)))
    assert iters.value == 10
    out2 = np.zeros(1200, np.float64)
    _check(lib, lib.LGBM_BoosterPredictForMat(
        bst2, X.ctypes.data_as(ctypes.c_void_p), 0, 1200, 6, 1, 0, 0, -1,
        b"", ctypes.byref(out_len),
        out2.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    assert np.array_equal(out, out2)

    # raw-score path differs from probabilities
    raw = np.zeros(1200, np.float64)
    _check(lib, lib.LGBM_BoosterPredictForMat(
        bst, X.ctypes.data_as(ctypes.c_void_p), 0, 1200, 6, 1, 1, 0, -1,
        b"", ctypes.byref(out_len),
        raw.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    assert not np.allclose(raw, out)

    _check(lib, lib.LGBM_BoosterFree(bst))
    _check(lib, lib.LGBM_BoosterFree(bst2))
    _check(lib, lib.LGBM_DatasetFree(ds))


def test_capi_error_reporting(lib):
    ds = ctypes.c_void_p()
    rc = lib.LGBM_DatasetCreateFromMat(
        None, 0, 0, 0, 1, b"", None, ctypes.byref(ds))
    assert rc != 0
    assert len(lib.LGBM_GetLastError()) > 0


def test_capi_float64_and_colmajor(lib):
    rng = np.random.RandomState(3)
    Xc = np.asfortranarray(rng.rand(300, 4).astype(np.float64))
    y = (Xc[:, 0] > 0.5).astype(np.float32)
    ds = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateFromMat(
        Xc.ctypes.data_as(ctypes.c_void_p), 1, 300, 4, 0,
        b"verbose=-1", None, ctypes.byref(ds)))
    _check(lib, lib.LGBM_DatasetSetField(
        ds, b"label", y.ctypes.data_as(ctypes.c_void_p), 300, 0))
    bst = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreate(
        ds, b"objective=binary num_leaves=7 verbose=-1",
        ctypes.byref(bst)))
    fin = ctypes.c_int()
    _check(lib, lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)))
    _check(lib, lib.LGBM_BoosterFree(bst))
    _check(lib, lib.LGBM_DatasetFree(ds))


def test_capi_pure_c_host(lib, tmp_path):
    """A plain C program (no Python host) linking libcapi + libpython
    trains and predicts through the ABI via the embedded interpreter."""
    import shutil
    import subprocess
    import sys
    import sysconfig
    if shutil.which("gcc") is None:
        pytest.skip("no C toolchain")
    here = os.path.dirname(os.path.abspath(__file__))
    src = os.path.join(here, "fixtures", "capi_host.c")
    exe = str(tmp_path / "capi_host")
    libdir = sysconfig.get_config_var("LIBDIR")
    ver = sysconfig.get_config_var("LDVERSION")
    native = os.path.dirname(build_capi())
    r = subprocess.run(
        ["gcc", "-O2", src, "-o", exe, f"-L{native}", "-l:libcapi.so",
         f"-L{libdir}", f"-lpython{ver}", f"-Wl,-rpath,{native}",
         f"-Wl,-rpath,{libdir}"], capture_output=True, text=True)
    if r.returncode != 0:
        pytest.skip(f"link failed: {r.stderr[-200:]}")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(here)] + sys.path)
    out = subprocess.run([exe], capture_output=True, text=True, env=env,
                         timeout=600)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "C HOST OK" in out.stdout


def test_capi_csr_dataset_and_predict(lib, tmp_path):
    """CSR dataset creation + CSR predict (ref surface:
    c_api.cpp:398-520, exercised the way tests/c_api_test/test_.py
    drives lib_lightgbm)."""
    import scipy.sparse as sp
    rng = np.random.RandomState(1)
    n, F = 2000, 40
    Xs = sp.random(n, F, density=0.05, format="csr", random_state=rng,
                   data_rvs=lambda k: rng.rand(k) + 0.5)
    y = (np.asarray(Xs[:, :5].sum(axis=1)).ravel() > 0.4).astype(np.float32)

    indptr = Xs.indptr.astype(np.int32)
    indices = Xs.indices.astype(np.int32)
    vals = Xs.data.astype(np.float64)
    ds = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateFromCSR(
        indptr.ctypes.data_as(ctypes.c_void_p), 2,
        indices.ctypes.data_as(ctypes.c_void_p),
        vals.ctypes.data_as(ctypes.c_void_p), 1,
        ctypes.c_int64(len(indptr)), ctypes.c_int64(len(vals)),
        ctypes.c_int64(F), b"verbose=-1", None, ctypes.byref(ds)))
    _check(lib, lib.LGBM_DatasetSetField(
        ds, b"label", y.ctypes.data_as(ctypes.c_void_p), n, 0))
    nd = ctypes.c_int32()
    _check(lib, lib.LGBM_DatasetGetNumData(ds, ctypes.byref(nd)))
    assert nd.value == n

    bst = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreate(
        ds, b"objective=binary num_leaves=15 verbose=-1",
        ctypes.byref(bst)))
    fin = ctypes.c_int()
    for _ in range(5):
        _check(lib, lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)))

    out = np.zeros(n, np.float64)
    out_len = ctypes.c_int64()
    _check(lib, lib.LGBM_BoosterPredictForCSR(
        bst, indptr.ctypes.data_as(ctypes.c_void_p), 2,
        indices.ctypes.data_as(ctypes.c_void_p),
        vals.ctypes.data_as(ctypes.c_void_p), 1,
        ctypes.c_int64(len(indptr)), ctypes.c_int64(len(vals)),
        ctypes.c_int64(F), 0, 0, -1, b"", ctypes.byref(out_len),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    assert out_len.value == n
    from sklearn.metrics import roc_auc_score
    assert roc_auc_score(y, out) > 0.9

    # dense predict on the same rows must agree
    Xd = Xs.toarray().astype(np.float64)
    out2 = np.zeros(n, np.float64)
    _check(lib, lib.LGBM_BoosterPredictForMat(
        bst, Xd.ctypes.data_as(ctypes.c_void_p), 1, n, F, 1, 0, 0, -1,
        b"", ctypes.byref(out_len),
        out2.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    np.testing.assert_array_equal(out, out2)
    lib.LGBM_BoosterFree(bst)
    lib.LGBM_DatasetFree(ds)


def test_capi_file_dataset_predict_and_eval(lib, tmp_path):
    rng = np.random.RandomState(2)
    X = rng.rand(1500, 5)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float32)
    train_path = tmp_path / "train.csv"
    rows = np.column_stack([y, X])
    np.savetxt(train_path, rows, delimiter=",", fmt="%.6f")

    ds = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateFromFile(
        str(train_path).encode(), b"verbose=-1 label_column=0", None,
        ctypes.byref(ds)))
    nd = ctypes.c_int32()
    _check(lib, lib.LGBM_DatasetGetNumData(ds, ctypes.byref(nd)))
    assert nd.value == 1500

    bst = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreate(
        ds, b"objective=binary num_leaves=15 metric=auc verbose=-1 "
        b"is_provide_training_metric=true",
        ctypes.byref(bst)))
    fin = ctypes.c_int()
    for _ in range(5):
        _check(lib, lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)))

    # GetEvalCounts / GetEvalNames / GetEval on the training data
    cnt = ctypes.c_int()
    _check(lib, lib.LGBM_BoosterGetEvalCounts(bst, ctypes.byref(cnt)))
    assert cnt.value == 1
    bufs = [ctypes.create_string_buffer(64)]
    arr = (ctypes.c_char_p * 1)(ctypes.addressof(bufs[0]))
    out_n = ctypes.c_int()
    out_blen = ctypes.c_size_t()
    _check(lib, lib.LGBM_BoosterGetEvalNames(
        bst, 1, ctypes.byref(out_n), ctypes.c_size_t(64),
        ctypes.byref(out_blen), arr))
    assert out_n.value == 1 and bufs[0].value == b"auc"
    res = np.zeros(4, np.float64)
    out_n2 = ctypes.c_int()
    _check(lib, lib.LGBM_BoosterGetEval(
        bst, 0, ctypes.byref(out_n2),
        res.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    assert out_n2.value == 1 and 0.9 < res[0] <= 1.0

    # PredictForFile writes one line per row
    pred_in = tmp_path / "pred.csv"
    np.savetxt(pred_in, X[:100], delimiter=",", fmt="%.6f")
    pred_out = tmp_path / "pred.out"
    _check(lib, lib.LGBM_BoosterPredictForFile(
        bst, str(pred_in).encode(), 0, 0, 0, -1, b"",
        str(pred_out).encode()))
    got = np.loadtxt(pred_out)
    assert got.shape == (100,) and np.isfinite(got).all()

    # binary dataset cache round trip
    bin_path = tmp_path / "train.bin"
    _check(lib, lib.LGBM_DatasetSaveBinary(ds, str(bin_path).encode()))
    assert bin_path.exists()

    # leaf accessors
    lv = ctypes.c_double()
    _check(lib, lib.LGBM_BoosterGetLeafValue(bst, 0, 0,
                                             ctypes.byref(lv)))
    lib.LGBM_BoosterSetLeafValue.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_double]
    _check(lib, lib.LGBM_BoosterSetLeafValue(
        bst, 0, 0, ctypes.c_double(lv.value + 1.0)))
    lv2 = ctypes.c_double()
    _check(lib, lib.LGBM_BoosterGetLeafValue(bst, 0, 0,
                                             ctypes.byref(lv2)))
    assert abs(lv2.value - lv.value - 1.0) < 1e-9
    nf = ctypes.c_int()
    _check(lib, lib.LGBM_BoosterGetNumFeature(bst, ctypes.byref(nf)))
    assert nf.value == 5
    lib.LGBM_BoosterFree(bst)
    lib.LGBM_DatasetFree(ds)


def test_capi_fast_single_row(lib):
    """FastInit preallocated single-row predicts
    (ref: c_api.cpp:939-1156)."""
    rng = np.random.RandomState(3)
    X = rng.rand(800, 4).astype(np.float32)
    y = (X[:, 0] > 0.5).astype(np.float32)
    ds = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateFromMat(
        X.ctypes.data_as(ctypes.c_void_p), 0, 800, 4, 1, b"verbose=-1",
        None, ctypes.byref(ds)))
    _check(lib, lib.LGBM_DatasetSetField(
        ds, b"label", y.ctypes.data_as(ctypes.c_void_p), 800, 0))
    bst = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreate(
        ds, b"objective=binary num_leaves=7 verbose=-1",
        ctypes.byref(bst)))
    fin = ctypes.c_int()
    for _ in range(3):
        _check(lib, lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)))

    cfg = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterPredictForMatSingleRowFastInit(
        bst, 0, 0, -1, 1, 4, b"", ctypes.byref(cfg)))
    out = ctypes.c_double()
    out_len = ctypes.c_int64()
    row = X[0].astype(np.float64)
    _check(lib, lib.LGBM_BoosterPredictForMatSingleRowFast(
        cfg, row.ctypes.data_as(ctypes.c_void_p), ctypes.byref(out_len),
        ctypes.byref(out)))
    assert out_len.value == 1
    # must match the batch predict of the same row
    batch = np.zeros(1, np.float64)
    _check(lib, lib.LGBM_BoosterPredictForMat(
        bst, row.ctypes.data_as(ctypes.c_void_p), 1, 1, 4, 1, 0, 0, -1,
        b"", ctypes.byref(out_len),
        batch.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    assert abs(out.value - batch[0]) < 1e-12
    lib.LGBM_FastConfigFree(cfg)
    lib.LGBM_BoosterFree(bst)
    lib.LGBM_DatasetFree(ds)


# ---------------------------------------------------------------------
# round-4 tranche (VERDICT r3 #5): custom-gradient train, JSON dump,
# field/feature-name access, CSC predict, sparse contribs, streaming
# push-rows, booster merge — ref: src/c_api.cpp:430-845
def test_capi_update_one_iter_custom(lib):
    rng = np.random.RandomState(3)
    X = rng.rand(1000, 5).astype(np.float64)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float32)
    ds = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateFromMat(
        X.ctypes.data_as(ctypes.c_void_p), 1, 1000, 5, 1,
        b"max_bin=63 verbose=-1", None, ctypes.byref(ds)))
    _check(lib, lib.LGBM_DatasetSetField(
        ds, b"label", y.ctypes.data_as(ctypes.c_void_p), 1000, 0))
    bst = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreate(
        ds, b"objective=none num_leaves=15 verbose=-1", ctypes.byref(bst)))
    # hand-rolled logloss gradients (what every binding's fobj path sends)
    score = np.zeros(1000, np.float64)
    fin = ctypes.c_int()
    for _ in range(8):
        p = 1.0 / (1.0 + np.exp(-score))
        grad = (p - y).astype(np.float32)
        hess = (p * (1 - p)).astype(np.float32)
        _check(lib, lib.LGBM_BoosterUpdateOneIterCustom(
            bst, grad.ctypes.data_as(ctypes.c_void_p),
            hess.ctypes.data_as(ctypes.c_void_p), ctypes.byref(fin)))
        out = np.zeros(1000, np.float64)
        out_len = ctypes.c_int64()
        _check(lib, lib.LGBM_BoosterPredictForMat(
            bst, X.ctypes.data_as(ctypes.c_void_p), 1, 1000, 5, 1, 1, 0,
            -1, b"", ctypes.byref(out_len),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
        score = out
    # training must separate the classes
    assert score[y > 0].mean() > score[y == 0].mean() + 0.5
    lib.LGBM_BoosterFree(bst)
    lib.LGBM_DatasetFree(ds)


def test_capi_dump_get_field_feature_names(lib):
    import json
    rng = np.random.RandomState(4)
    X = rng.rand(600, 4).astype(np.float64)
    y = (X[:, 0] > 0.5).astype(np.float32)
    w = (1.0 + y).astype(np.float32)
    ds = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateFromMat(
        X.ctypes.data_as(ctypes.c_void_p), 1, 600, 4, 1, b"verbose=-1",
        None, ctypes.byref(ds)))
    _check(lib, lib.LGBM_DatasetSetField(
        ds, b"label", y.ctypes.data_as(ctypes.c_void_p), 600, 0))
    _check(lib, lib.LGBM_DatasetSetField(
        ds, b"weight", w.ctypes.data_as(ctypes.c_void_p), 600, 0))

    # set + get feature names (reference string-array conventions)
    names = [b"f_alpha", b"f_beta", b"f_gamma", b"f_delta"]
    arr = (ctypes.c_char_p * 4)(*names)
    _check(lib, lib.LGBM_DatasetSetFeatureNames(
        ds, ctypes.cast(arr, ctypes.POINTER(ctypes.c_char_p)), 4))
    bufs = [ctypes.create_string_buffer(64) for _ in range(4)]
    ptrs = (ctypes.c_char_p * 4)(*[ctypes.addressof(b) for b in bufs])
    n_names = ctypes.c_int()
    need = ctypes.c_size_t()
    _check(lib, lib.LGBM_DatasetGetFeatureNames(
        ds, 4, ctypes.byref(n_names), 64, ctypes.byref(need),
        ctypes.cast(ptrs, ctypes.POINTER(ctypes.c_char_p))))
    assert n_names.value == 4
    assert [b.value for b in bufs] == names
    assert need.value == len(b"f_alpha") + 1

    # get_field returns pinned pointers into the metadata
    out_ptr = ctypes.c_void_p()
    out_len = ctypes.c_int()
    out_type = ctypes.c_int()
    _check(lib, lib.LGBM_DatasetGetField(
        ds, b"weight", ctypes.byref(out_len), ctypes.byref(out_ptr),
        ctypes.byref(out_type)))
    assert out_len.value == 600 and out_type.value == 0
    got = np.ctypeslib.as_array(
        ctypes.cast(out_ptr, ctypes.POINTER(ctypes.c_float)), (600,))
    np.testing.assert_array_equal(got, w)

    bst = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreate(
        ds, b"objective=binary num_leaves=7 verbose=-1", ctypes.byref(bst)))
    fin = ctypes.c_int()
    for _ in range(3):
        _check(lib, lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)))

    # JSON dump over the ABI, with the two-call buffer-size protocol
    need64 = ctypes.c_int64()
    _check(lib, lib.LGBM_BoosterDumpModel(
        bst, 0, -1, 0, 0, ctypes.byref(need64), None))
    buf = ctypes.create_string_buffer(need64.value)
    _check(lib, lib.LGBM_BoosterDumpModel(
        bst, 0, -1, 0, need64.value, ctypes.byref(need64), buf))
    model = json.loads(buf.value.decode())
    assert model["num_tree_per_iteration"] == 1
    assert len(model["tree_info"]) == 3
    assert model["feature_names"] == [n.decode() for n in names]
    lib.LGBM_BoosterFree(bst)
    lib.LGBM_DatasetFree(ds)


def test_capi_csc_predict_and_sparse_contribs(lib):
    import scipy.sparse as sp
    rng = np.random.RandomState(5)
    n, F = 800, 12
    Xs = sp.random(n, F, density=0.3, format="csr", random_state=rng,
                   data_rvs=lambda k: rng.rand(k) + 0.5)
    y = (np.asarray(Xs[:, :3].sum(axis=1)).ravel() > 0.5).astype(np.float32)
    Xd = np.asarray(Xs.todense())
    ds = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateFromMat(
        Xd.ctypes.data_as(ctypes.c_void_p), 1, n, F, 1,
        b"max_bin=63 verbose=-1", None, ctypes.byref(ds)))
    _check(lib, lib.LGBM_DatasetSetField(
        ds, b"label", y.ctypes.data_as(ctypes.c_void_p), n, 0))
    bst = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreate(
        ds, b"objective=binary num_leaves=15 verbose=-1",
        ctypes.byref(bst)))
    fin = ctypes.c_int()
    for _ in range(5):
        _check(lib, lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)))

    # CSC predict must match dense-mat predict
    dense = np.zeros(n, np.float64)
    out_len = ctypes.c_int64()
    _check(lib, lib.LGBM_BoosterPredictForMat(
        bst, Xd.ctypes.data_as(ctypes.c_void_p), 1, n, F, 1, 0, 0, -1,
        b"", ctypes.byref(out_len),
        dense.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    csc = Xs.tocsc()
    got = np.zeros(n, np.float64)
    _check(lib, lib.LGBM_BoosterPredictForCSC(
        bst, csc.indptr.ctypes.data_as(ctypes.c_void_p), 2,
        csc.indices.ctypes.data_as(ctypes.c_void_p),
        csc.data.ctypes.data_as(ctypes.c_void_p), 1,
        ctypes.c_int64(len(csc.indptr)), ctypes.c_int64(csc.nnz),
        ctypes.c_int64(n), 0, 0, -1, b"", ctypes.byref(out_len),
        got.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    assert out_len.value == n
    np.testing.assert_allclose(got, dense, rtol=1e-12)

    # sparse-output contribs: CSR in, CSR out, freed through the ABI
    out2 = (ctypes.c_int64 * 2)()
    o_indptr = ctypes.c_void_p()
    o_indices = ctypes.POINTER(ctypes.c_int32)()
    o_data = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterPredictSparseOutput(
        bst, Xs.indptr.ctypes.data_as(ctypes.c_void_p), 2,
        Xs.indices.ctypes.data_as(ctypes.c_void_p),
        Xs.data.ctypes.data_as(ctypes.c_void_p), 1,
        ctypes.c_int64(len(Xs.indptr)), ctypes.c_int64(Xs.nnz),
        ctypes.c_int64(F), 3, 0, -1, b"", 0, out2,
        ctypes.byref(o_indptr), ctypes.byref(o_indices),
        ctypes.byref(o_data)))
    nindptr, nnz = out2[0], out2[1]
    assert nindptr == n + 1
    # the output indptr/data use the CALLER's indptr_type/data_type
    # (int32/float64 here) — the reference's FreePredictSparse contract
    indptr = np.ctypeslib.as_array(
        ctypes.cast(o_indptr, ctypes.POINTER(ctypes.c_int32)), (nindptr,))
    indices = np.ctypeslib.as_array(o_indices, (nnz,))
    data = np.ctypeslib.as_array(
        ctypes.cast(o_data, ctypes.POINTER(ctypes.c_double)), (nnz,))
    contrib_sparse = sp.csr_matrix(
        (data.copy(), indices.copy(), indptr.copy()), shape=(n, F + 1))
    # row sums of contribs == raw predictions (the SHAP identity)
    raw = np.zeros(n, np.float64)
    _check(lib, lib.LGBM_BoosterPredictForMat(
        bst, Xd.ctypes.data_as(ctypes.c_void_p), 1, n, F, 1, 1, 0, -1,
        b"", ctypes.byref(out_len),
        raw.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    np.testing.assert_allclose(
        np.asarray(contrib_sparse.sum(axis=1)).ravel(), raw, atol=1e-9)
    _check(lib, lib.LGBM_BoosterFreePredictSparse(o_indptr, o_indices,
                                                  o_data, 3, 1))
    lib.LGBM_BoosterFree(bst)
    lib.LGBM_DatasetFree(ds)


def test_capi_create_by_reference_push_rows(lib):
    rng = np.random.RandomState(6)
    X = rng.rand(900, 5).astype(np.float64)
    y = (X[:, 0] > 0.5).astype(np.float32)
    ref = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateFromMat(
        X[:500].ctypes.data_as(ctypes.c_void_p), 1, 500, 5, 1,
        b"max_bin=63 verbose=-1", None, ctypes.byref(ref)))
    _check(lib, lib.LGBM_DatasetSetField(
        ref, b"label", y.ctypes.data_as(ctypes.c_void_p), 500, 0))

    # stream the SAME 500 rows in 3 chunks into a by-reference dataset
    ds = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateByReference(
        ref, ctypes.c_int64(500), ctypes.byref(ds)))
    for lo, hi in ((0, 200), (200, 350), (350, 500)):
        chunk = np.ascontiguousarray(X[lo:hi])
        _check(lib, lib.LGBM_DatasetPushRows(
            ds, chunk.ctypes.data_as(ctypes.c_void_p), 1, hi - lo, 5, lo))
    _check(lib, lib.LGBM_DatasetSetField(
        ds, b"label", y.ctypes.data_as(ctypes.c_void_p), 500, 0))
    n = ctypes.c_int32()
    _check(lib, lib.LGBM_DatasetGetNumData(ds, ctypes.byref(n)))
    assert n.value == 500

    # identical rows + shared mappers -> identical trained model
    def train(handle):
        bst = ctypes.c_void_p()
        _check(lib, lib.LGBM_BoosterCreate(
            handle, b"objective=binary num_leaves=7 verbose=-1",
            ctypes.byref(bst)))
        fin = ctypes.c_int()
        for _ in range(3):
            _check(lib, lib.LGBM_BoosterUpdateOneIter(bst,
                                                      ctypes.byref(fin)))
        out = np.zeros(100, np.float64)
        out_len = ctypes.c_int64()
        q = np.ascontiguousarray(X[:100])
        _check(lib, lib.LGBM_BoosterPredictForMat(
            bst, q.ctypes.data_as(ctypes.c_void_p), 1, 100, 5, 1, 0, 0,
            -1, b"", ctypes.byref(out_len),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
        lib.LGBM_BoosterFree(bst)
        return out
    np.testing.assert_array_equal(train(ref), train(ds))
    lib.LGBM_DatasetFree(ds)
    lib.LGBM_DatasetFree(ref)


def test_capi_booster_merge(lib, tmp_path):
    rng = np.random.RandomState(7)
    X = rng.rand(500, 4).astype(np.float64)
    y = (X[:, 0] > 0.5).astype(np.float32)

    def trained(rounds, fname):
        ds = ctypes.c_void_p()
        _check(lib, lib.LGBM_DatasetCreateFromMat(
            X.ctypes.data_as(ctypes.c_void_p), 1, 500, 4, 1, b"verbose=-1",
            None, ctypes.byref(ds)))
        _check(lib, lib.LGBM_DatasetSetField(
            ds, b"label", y.ctypes.data_as(ctypes.c_void_p), 500, 0))
        bst = ctypes.c_void_p()
        _check(lib, lib.LGBM_BoosterCreate(
            ds, b"objective=binary num_leaves=7 verbose=-1",
            ctypes.byref(bst)))
        fin = ctypes.c_int()
        for _ in range(rounds):
            _check(lib, lib.LGBM_BoosterUpdateOneIter(bst,
                                                      ctypes.byref(fin)))
        _check(lib, lib.LGBM_BoosterSaveModel(bst, 0, -1, 0,
                                              str(fname).encode()))
        lib.LGBM_BoosterFree(bst)
        lib.LGBM_DatasetFree(ds)

    trained(3, tmp_path / "a.txt")
    trained(2, tmp_path / "b.txt")
    it = ctypes.c_int()
    a = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreateFromModelfile(
        str(tmp_path / "a.txt").encode(), ctypes.byref(it),
        ctypes.byref(a)))
    b = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreateFromModelfile(
        str(tmp_path / "b.txt").encode(), ctypes.byref(it),
        ctypes.byref(b)))
    _check(lib, lib.LGBM_BoosterMerge(a, b))
    # merged predictions = sum of the two models' raw scores
    out = np.zeros(500, np.float64)
    out_len = ctypes.c_int64()
    _check(lib, lib.LGBM_BoosterPredictForMat(
        a, X.ctypes.data_as(ctypes.c_void_p), 1, 500, 4, 1, 1, 0, -1,
        b"", ctypes.byref(out_len),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    import lightgbm_tpu as lgb
    ra = lgb.Booster(model_file=str(tmp_path / "a.txt")) \
        .predict(X, raw_score=True)
    rb = lgb.Booster(model_file=str(tmp_path / "b.txt")) \
        .predict(X, raw_score=True)
    np.testing.assert_allclose(out, ra + rb, rtol=1e-12)


def test_reference_c_api_suite(lib, tmp_path):
    """Run the REFERENCE's own tests/c_api_test/test_.py, unmodified and
    in place, against libcapi.so (VERDICT r3 #5 'Done' criterion). A
    symlink sandbox reproduces the layout its find_lib_path expects —
    no reference code is copied."""
    import subprocess
    import sys
    ref = "/root/reference"
    if not os.path.isdir(os.path.join(ref, "tests", "c_api_test")):
        pytest.skip("reference tree unavailable")
    sandbox = tmp_path / "refbox"
    (sandbox / "tests").mkdir(parents=True)
    os.symlink(os.path.join(ref, "tests", "c_api_test"),
               sandbox / "tests" / "c_api_test")
    os.symlink(os.path.join(ref, "examples"), sandbox / "examples")
    (sandbox / "lib").mkdir()
    os.symlink(build_capi(), sandbox / "lib" / "lib_lightgbm.so")
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=here)
    env.pop("XLA_FLAGS", None)
    run = tmp_path / "run"
    run.mkdir()
    r = subprocess.run(
        [sys.executable, "-m", "pytest", "-x", "-q",
         str(sandbox / "tests" / "c_api_test" / "test_.py")],
        cwd=run, env=env, capture_output=True, text=True, timeout=900)
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-2000:]


def test_capi_tranche4_lifecycle(lib, tmp_path):
    """Round-4 tranche 4: string IO, counters, bounds, reset_parameter,
    shuffle, PredictForMats, GetSubset, UpdateParamChecking (ref:
    c_api.h:313-1310)."""
    rng = np.random.RandomState(8)
    X = rng.rand(800, 4)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float32)
    ds = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateFromMat(
        X.ctypes.data_as(ctypes.c_void_p), 1, 800, 4, 1, b"verbose=-1",
        None, ctypes.byref(ds)))
    _check(lib, lib.LGBM_DatasetSetField(
        ds, b"label", y.ctypes.data_as(ctypes.c_void_p), 800, 0))
    bst = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreate(
        ds, b"objective=binary num_leaves=7 learning_rate=0.1 verbose=-1",
        ctypes.byref(bst)))
    fin = ctypes.c_int()
    for _ in range(4):
        _check(lib, lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)))

    # reset_parameter mid-training (the reference's reset_parameter
    # callback path crosses exactly this symbol)
    _check(lib, lib.LGBM_BoosterResetParameter(bst, b"learning_rate=0.2"))
    _check(lib, lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)))

    n = ctypes.c_int()
    _check(lib, lib.LGBM_BoosterNumModelPerIteration(bst, ctypes.byref(n)))
    assert n.value == 1
    _check(lib, lib.LGBM_BoosterNumberOfTotalModel(bst, ctypes.byref(n)))
    assert n.value == 5

    lo = ctypes.c_double()
    hi = ctypes.c_double()
    _check(lib, lib.LGBM_BoosterGetLowerBoundValue(bst, ctypes.byref(lo)))
    _check(lib, lib.LGBM_BoosterGetUpperBoundValue(bst, ctypes.byref(hi)))
    assert lo.value < hi.value

    # save-to-string -> load-from-string round trip
    need = ctypes.c_int64()
    _check(lib, lib.LGBM_BoosterSaveModelToString(
        bst, 0, -1, 0, 0, ctypes.byref(need), None))
    buf = ctypes.create_string_buffer(need.value)
    _check(lib, lib.LGBM_BoosterSaveModelToString(
        bst, 0, -1, 0, need.value, ctypes.byref(need), buf))
    it = ctypes.c_int()
    bst2 = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterLoadModelFromString(
        buf.value, ctypes.byref(it), ctypes.byref(bst2)))
    assert it.value == 5

    # feature names through the booster
    bufs = [ctypes.create_string_buffer(64) for _ in range(4)]
    ptrs = (ctypes.c_char_p * 4)(*[ctypes.addressof(b) for b in bufs])
    nn = ctypes.c_int()
    blen = ctypes.c_size_t()
    _check(lib, lib.LGBM_BoosterGetFeatureNames(
        bst2, 4, ctypes.byref(nn), 64, ctypes.byref(blen),
        ctypes.cast(ptrs, ctypes.POINTER(ctypes.c_char_p))))
    assert nn.value == 4

    # PredictForMats (row-pointer array) == PredictForMat
    rows = np.ascontiguousarray(X[:16], np.float64)
    rp = (ctypes.c_void_p * 16)(
        *[rows[i].ctypes.data for i in range(16)])
    got = np.zeros(16, np.float64)
    out_len = ctypes.c_int64()
    _check(lib, lib.LGBM_BoosterPredictForMats(
        bst2, ctypes.cast(rp, ctypes.POINTER(ctypes.c_void_p)), 1, 16, 4,
        0, 0, -1, b"", ctypes.byref(out_len),
        got.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    want = np.zeros(16, np.float64)
    _check(lib, lib.LGBM_BoosterPredictForMat(
        bst2, rows.ctypes.data_as(ctypes.c_void_p), 1, 16, 4, 1, 0, 0, -1,
        b"", ctypes.byref(out_len),
        want.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    np.testing.assert_array_equal(got, want)

    # shuffle preserves the prediction (sum over trees is order-free)
    _check(lib, lib.LGBM_BoosterShuffleModels(bst2, 0, -1))
    got2 = np.zeros(16, np.float64)
    _check(lib, lib.LGBM_BoosterPredictForMat(
        bst2, rows.ctypes.data_as(ctypes.c_void_p), 1, 16, 4, 1, 0, 0, -1,
        b"", ctypes.byref(out_len),
        got2.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    np.testing.assert_allclose(got2, want, rtol=1e-12)

    # dataset subset
    idx = np.arange(0, 800, 2, dtype=np.int32)
    sub = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetGetSubset(
        ds, idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), 400, b"",
        ctypes.byref(sub)))
    nd = ctypes.c_int32()
    _check(lib, lib.LGBM_DatasetGetNumData(sub, ctypes.byref(nd)))
    assert nd.value == 400

    # param checking: same ok, changed dataset param rejected
    _check(lib, lib.LGBM_DatasetUpdateParamChecking(
        b"max_bin=255 verbose=-1", b"max_bin=255 learning_rate=0.5"))
    rc = lib.LGBM_DatasetUpdateParamChecking(b"max_bin=255", b"max_bin=63")
    assert rc == -1

    lib.LGBM_DatasetFree(sub)
    lib.LGBM_BoosterFree(bst2)
    lib.LGBM_BoosterFree(bst)
    lib.LGBM_DatasetFree(ds)
