"""The LGBM_* C ABI (native/capi.cpp + capi_support.py).

Drives the compiled shared library through ctypes exactly the way the
reference's own python-package drives lib_lightgbm (ref:
python-package/lightgbm/basic.py _LIB usage) — create a dataset from a
raw float matrix, set the label field, train, predict, save, reload.
"""
import ctypes
import os

import numpy as np
import pytest

from lightgbm_tpu.native.loader import build_capi


@pytest.fixture(scope="module")
def lib():
    path = build_capi()
    if path is None:
        pytest.skip("no native toolchain")
    lib = ctypes.CDLL(path)
    lib.LGBM_GetLastError.restype = ctypes.c_char_p
    return lib


def _check(lib, rc):
    assert rc == 0, lib.LGBM_GetLastError().decode()


def test_capi_full_lifecycle(lib, tmp_path):
    rng = np.random.RandomState(0)
    X = rng.rand(1200, 6).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float32)

    ds = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateFromMat(
        X.ctypes.data_as(ctypes.c_void_p), 0, 1200, 6, 1,
        b"max_bin=63 verbose=-1", None, ctypes.byref(ds)))
    _check(lib, lib.LGBM_DatasetSetField(
        ds, b"label", y.ctypes.data_as(ctypes.c_void_p), 1200, 0))

    n = ctypes.c_int32()
    _check(lib, lib.LGBM_DatasetGetNumData(ds, ctypes.byref(n)))
    assert n.value == 1200
    _check(lib, lib.LGBM_DatasetGetNumFeature(ds, ctypes.byref(n)))
    assert n.value == 6

    bst = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreate(
        ds, b"objective=binary num_leaves=15 learning_rate=0.2 verbose=-1",
        ctypes.byref(bst)))
    fin = ctypes.c_int()
    for _ in range(10):
        _check(lib, lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)))
    it = ctypes.c_int()
    _check(lib, lib.LGBM_BoosterGetCurrentIteration(bst, ctypes.byref(it)))
    assert it.value == 10
    nc = ctypes.c_int()
    _check(lib, lib.LGBM_BoosterGetNumClasses(bst, ctypes.byref(nc)))
    assert nc.value == 1

    need = ctypes.c_int64()
    _check(lib, lib.LGBM_BoosterCalcNumPredict(
        bst, 1200, 0, 0, -1, ctypes.byref(need)))
    assert need.value == 1200
    _check(lib, lib.LGBM_BoosterCalcNumPredict(
        bst, 1200, 2, 0, -1, ctypes.byref(need)))
    assert need.value == 1200 * 10      # leaf index: one per tree

    out = np.zeros(1200, np.float64)
    out_len = ctypes.c_int64()
    _check(lib, lib.LGBM_BoosterPredictForMat(
        bst, X.ctypes.data_as(ctypes.c_void_p), 0, 1200, 6, 1, 0, 0, -1,
        b"", ctypes.byref(out_len),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    assert out_len.value == 1200
    from sklearn.metrics import roc_auc_score
    assert roc_auc_score(y, out) > 0.95

    model_path = str(tmp_path / "capi_model.txt").encode()
    _check(lib, lib.LGBM_BoosterSaveModel(bst, 0, -1, 0, model_path))

    bst2 = ctypes.c_void_p()
    iters = ctypes.c_int()
    _check(lib, lib.LGBM_BoosterCreateFromModelfile(
        model_path, ctypes.byref(iters), ctypes.byref(bst2)))
    assert iters.value == 10
    out2 = np.zeros(1200, np.float64)
    _check(lib, lib.LGBM_BoosterPredictForMat(
        bst2, X.ctypes.data_as(ctypes.c_void_p), 0, 1200, 6, 1, 0, 0, -1,
        b"", ctypes.byref(out_len),
        out2.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    assert np.array_equal(out, out2)

    # raw-score path differs from probabilities
    raw = np.zeros(1200, np.float64)
    _check(lib, lib.LGBM_BoosterPredictForMat(
        bst, X.ctypes.data_as(ctypes.c_void_p), 0, 1200, 6, 1, 1, 0, -1,
        b"", ctypes.byref(out_len),
        raw.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    assert not np.allclose(raw, out)

    _check(lib, lib.LGBM_BoosterFree(bst))
    _check(lib, lib.LGBM_BoosterFree(bst2))
    _check(lib, lib.LGBM_DatasetFree(ds))


def test_capi_error_reporting(lib):
    ds = ctypes.c_void_p()
    rc = lib.LGBM_DatasetCreateFromMat(
        None, 0, 0, 0, 1, b"", None, ctypes.byref(ds))
    assert rc != 0
    assert len(lib.LGBM_GetLastError()) > 0


def test_capi_float64_and_colmajor(lib):
    rng = np.random.RandomState(3)
    Xc = np.asfortranarray(rng.rand(300, 4).astype(np.float64))
    y = (Xc[:, 0] > 0.5).astype(np.float32)
    ds = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateFromMat(
        Xc.ctypes.data_as(ctypes.c_void_p), 1, 300, 4, 0,
        b"verbose=-1", None, ctypes.byref(ds)))
    _check(lib, lib.LGBM_DatasetSetField(
        ds, b"label", y.ctypes.data_as(ctypes.c_void_p), 300, 0))
    bst = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreate(
        ds, b"objective=binary num_leaves=7 verbose=-1",
        ctypes.byref(bst)))
    fin = ctypes.c_int()
    _check(lib, lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)))
    _check(lib, lib.LGBM_BoosterFree(bst))
    _check(lib, lib.LGBM_DatasetFree(ds))


def test_capi_pure_c_host(lib, tmp_path):
    """A plain C program (no Python host) linking libcapi + libpython
    trains and predicts through the ABI via the embedded interpreter."""
    import shutil
    import subprocess
    import sys
    import sysconfig
    if shutil.which("gcc") is None:
        pytest.skip("no C toolchain")
    here = os.path.dirname(os.path.abspath(__file__))
    src = os.path.join(here, "fixtures", "capi_host.c")
    exe = str(tmp_path / "capi_host")
    libdir = sysconfig.get_config_var("LIBDIR")
    ver = sysconfig.get_config_var("LDVERSION")
    native = os.path.dirname(build_capi())
    r = subprocess.run(
        ["gcc", "-O2", src, "-o", exe, f"-L{native}", "-l:libcapi.so",
         f"-L{libdir}", f"-lpython{ver}", f"-Wl,-rpath,{native}",
         f"-Wl,-rpath,{libdir}"], capture_output=True, text=True)
    if r.returncode != 0:
        pytest.skip(f"link failed: {r.stderr[-200:]}")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(here)] + sys.path)
    out = subprocess.run([exe], capture_output=True, text=True, env=env,
                         timeout=600)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "C HOST OK" in out.stdout
