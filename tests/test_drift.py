"""Drift & lineage plane (lightgbm_tpu/obs/drift.py + its hooks).

Covers the four layers of the drift ISSUE and its acceptance contract:

- divergence math on every degenerate shape the monitors meet (empty
  reference bins, single-bin features, all-missing columns, empty
  windows) plus the coarsening step that keeps PSI off sampling noise;
- training DataProfile + provenance capture, embedded in the model
  artifact and resilience checkpoints, byte-stable through round trips;
- the serving DriftMonitor A/B acceptance: a distribution-B feed
  against an A-trained model raises EXACTLY one hysteresis-gated
  ``drift_alert`` while an A-fed control raises none — with the 1.0
  dispatches/request and zero-recompile serving contracts
  counter-asserted in BOTH runs, and a profile-less artifact degrading
  to one ``drift_unavailable`` event, never an exception;
- ingest mapper-drift events, the lineage chain (training run_id ->
  checkpoint -> rollover) and the run-report/diff surfacing.
"""
import json
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs import drift as drift_mod
from lightgbm_tpu.obs.drift import (DriftMonitor, build_profile,
                                    canonical_json, coarsen,
                                    js_divergence, profile_digest, psi)
from lightgbm_tpu.serve import PredictionService

F = 5


def _data(n=800, f=F, seed=0, shift=0.0):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f).astype(np.float32)
    if shift:
        X = np.clip(X + shift, 0.0, 1.0).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float32)
    return X, y


def _train(X, y, rounds=6, **extra):
    params = {"objective": "binary", "num_leaves": 15,
              "learning_rate": 0.2, "verbose": -1, "min_data_in_leaf": 5,
              "max_bin": 63, "metric": "None"}
    params.update(extra)
    return lgb.train(params, lgb.Dataset(
        X, label=y, params={"max_bin": 63, "verbose": -1}),
        num_boost_round=rounds)


@pytest.fixture(scope="module")
def bst():
    X, y = _data()
    return _train(X, y)


# ----------------------------------------------------------- psi / js
def test_psi_js_identical_distributions_near_zero():
    c = np.array([10, 20, 30, 40])
    assert psi(c, 10 * c) == pytest.approx(0.0, abs=1e-9)
    assert js_divergence(c, 10 * c) == pytest.approx(0.0, abs=1e-9)


def test_psi_empty_reference_bins_finite_via_smoothing():
    # reference mass entirely absent from bins the current window
    # fills: the epsilon smoothing keeps every log term finite
    v = psi([0, 0, 0, 0], [5, 5, 5, 5])
    assert np.isfinite(v)
    v2 = psi([100, 0, 0, 0], [0, 0, 0, 100])
    assert np.isfinite(v2) and v2 > 1.0


def test_psi_single_bin_feature_is_zero():
    assert psi([7], [3]) == pytest.approx(0.0, abs=1e-12)


def test_psi_empty_vectors_and_length_mismatch():
    assert psi([], []) == 0.0
    assert js_divergence([], []) == 0.0
    # shorter side is padded with empty bins, not truncated
    long = psi([10, 10, 10, 10], [10, 10])
    assert np.isfinite(long) and long > 0.0
    assert np.isfinite(psi([], [1, 2, 3]))


def test_js_symmetric_and_bounded():
    a, b = [100, 0, 0], [0, 0, 100]
    assert js_divergence(a, b) == pytest.approx(js_divergence(b, a))
    assert 0.0 <= js_divergence(a, b) <= np.log(2) + 1e-9


def test_coarsen_groups_and_preserves_mass():
    c = np.arange(64, dtype=np.float64)
    g = coarsen(c, 8)
    assert g.size == 8 and g.sum() == pytest.approx(c.sum())
    # short vectors pass through untouched
    np.testing.assert_array_equal(coarsen([1, 2, 3], 8), [1.0, 2.0, 3.0])


# ------------------------------------------------- profile + artifact
def test_profile_captured_and_byte_stable(bst):
    p = bst.data_profile
    assert p is not None and p["schema"] == drift_mod.PROFILE_SCHEMA
    assert p["rows"] == 800 and len(p["features"]) >= 1
    assert p["mappers_digest"]
    assert "score" in p          # finalize attached the margin sketch
    # canonical dump of a parsed dump is byte-identical
    s = canonical_json(p)
    assert canonical_json(json.loads(s)) == s
    prov = bst.provenance
    assert prov["schema"] == drift_mod.PROVENANCE_SCHEMA
    assert prov["run_id"] and prov["params_digest"]
    assert prov["profile_digest"] == profile_digest(p)


def test_profile_roundtrip_model_string(bst):
    s = bst.model_to_string()
    assert "\ndata_profile:\n" in s and "\nprovenance:\n" in s
    b2 = lgb.Booster(model_str=s)
    assert canonical_json(b2.data_profile) == canonical_json(
        bst.data_profile)
    assert canonical_json(b2.provenance) == canonical_json(bst.provenance)
    # and the re-serialized artifact carries the identical blocks
    assert canonical_json(lgb.Booster(
        model_str=b2.model_to_string()).data_profile) \
        == canonical_json(bst.data_profile)


def test_profile_roundtrip_checkpoint(tmp_path):
    from lightgbm_tpu.resilience.state import booster_from_checkpoint
    X, y = _data(seed=3)
    a = _train(X, y, rounds=6, checkpoint_dir=str(tmp_path / "ck"),
               checkpoint_period=3)
    b = booster_from_checkpoint(str(tmp_path / "ck"))
    assert canonical_json(b.data_profile) == canonical_json(
        a.data_profile)
    assert b.provenance["run_id"] == a.provenance["run_id"]


def test_resume_chains_parent_checkpoint(tmp_path):
    X, y = _data(seed=4)
    ck = str(tmp_path / "ck")
    a = _train(X, y, rounds=4, checkpoint_dir=ck, checkpoint_period=2)
    assert a.provenance["parent_checkpoint"] == ""
    b = _train(X, y, rounds=8, checkpoint_dir=ck, checkpoint_period=2,
               resume=ck)
    assert b.provenance["parent_checkpoint"] != ""


def test_all_missing_column_profile_and_monitor():
    rng = np.random.RandomState(5)
    X = rng.rand(400, 4).astype(np.float32)
    X[:, 2] = np.nan                      # all-missing column
    y = (X[:, 0] > 0.5).astype(np.float32)
    bst = _train(X, y, rounds=3, use_missing=True)
    prof = bst.data_profile
    assert prof is not None
    # the monitor stays finite when fed the same all-missing shape
    mon = DriftMonitor(prof, eval_rows=1)
    mon.accumulate_raw(np.asarray(X[:64], np.float64))
    mon.accumulate_scores(np.zeros(64))
    res = mon.evaluate(force=True)
    assert res is not None
    assert all(np.isfinite(v) for v in res["psi"].values())


# ----------------------------------------------------- serving monitor
def _serve_counters(bst, feed_shift, requests=20, rows=40):
    svc = PredictionService({"m": bst}, max_batch_rows=256,
                            max_delay_ms=0.5, min_bucket_rows=16,
                            batch_events=False, drift_eval_rows=128,
                            drift_hysteresis=2)
    svc.warmup()
    rng = np.random.RandomState(17)
    s0 = svc.stats()
    for _ in range(requests):
        Xq = rng.rand(rows, F).astype(np.float32)
        if feed_shift:
            Xq = np.clip(Xq + 0.35, 0.0, 1.0).astype(np.float32)
        svc.predict("m", Xq, timeout=60)
    s1 = svc.stats()
    rep = svc.run_report()
    stats = svc.stats()
    svc.close()
    snap = svc.tel.snapshot()
    return {"dispatches": s1["dispatches"] - s0["dispatches"],
            "compiles": s1["compiles"] - s0["compiles"],
            "requests": requests, "snap": snap, "report": rep,
            "stats": stats}


def test_serve_drift_ab_acceptance(bst):
    """The ISSUE acceptance: distribution-B feed vs the A-trained model
    raises exactly one hysteresis-gated alert with nonzero per-feature
    PSI; the A-fed control raises none — dispatches/request == 1.0 and
    zero compiles in BOTH runs."""
    ctrl = _serve_counters(bst, feed_shift=False)
    drifted = _serve_counters(bst, feed_shift=True)
    for r in (ctrl, drifted):
        assert r["dispatches"] == r["requests"]     # exactly 1.0/request
        assert r["compiles"] == 0                   # zero recompiles
    cc = ctrl["snap"]["counters"]
    dc = drifted["snap"]["counters"]
    assert cc.get("drift.alerts", 0) == 0
    assert dc.get("drift.alerts", 0) == 1
    assert dc.get("drift.evaluations", 0) >= 2      # hysteresis had data
    alert = [e for e in drifted["snap"]["events"]
             if e.get("event") == "drift_alert"]
    assert len(alert) == 1
    assert alert[0]["model_id"] == "m"
    assert alert[0]["worst_psi"] > 0.2
    assert alert[0]["worst_feature"] >= 0
    # per-feature gauges exported under drift.psi.f<i>
    gauges = drifted["snap"]["gauges"]
    assert any(k.startswith("drift.psi.f") and v > 0.2
               for k, v in gauges.items())
    assert gauges.get("drift.psi_max", 0) > 0.2
    # the service stats surface the drift block
    assert drifted["stats"]["drift"]["alerts"] == 1
    assert ctrl["stats"]["drift"]["alerts"] == 0


def test_serve_drift_report_sections(bst):
    drifted = _serve_counters(bst, feed_shift=True)
    rep = drifted["report"]
    assert rep["drift"]["alert_count"] == 1
    assert any(a.get("event") == "drift_alert"
               for a in rep["drift"]["alerts"])
    lin = rep["lineage"]["m"]
    assert lin["provenance"]["run_id"] == bst.provenance["run_id"]
    assert lin["model_age_s"] is not None and lin["model_age_s"] >= 0


def test_run_diff_flags_new_drift_alert(bst):
    from lightgbm_tpu.obs.report import compare_reports
    ctrl = _serve_counters(bst, feed_shift=False)
    drifted = _serve_counters(bst, feed_shift=True)
    rep = compare_reports(ctrl["report"], drifted["report"],
                          threshold=9.0)
    names = [e["name"] for e in rep["regressions"]]
    assert any(n.startswith("drift_alert:") for n in names), names
    # same-report diff is clean of drift regressions
    rep2 = compare_reports(drifted["report"], drifted["report"],
                           threshold=9.0)
    assert not any(str(e["name"]).startswith("drift_alert:")
                   for e in rep2["regressions"])


def test_profileless_model_degrades_structurally(bst):
    """A model file without an embedded profile serves with one
    drift_unavailable event — never an exception (satellite f)."""
    s = bst.model_to_string()
    stripped = s.split("\ndata_profile:")[0] + "\n"
    b = lgb.Booster(model_str=stripped)
    assert b.data_profile is None
    svc = PredictionService({"m": b}, max_batch_rows=128,
                            max_delay_ms=0.5, batch_events=False)
    svc.warmup()
    rng = np.random.RandomState(2)
    out = svc.predict("m", rng.rand(16, F).astype(np.float32),
                      timeout=60)
    assert out.shape[0] == 16
    svc.close()
    snap = svc.tel.snapshot()
    unavailable = [e for e in snap["events"]
                   if e.get("event") == "drift_unavailable"]
    assert len(unavailable) == 1
    assert unavailable[0]["reason"] == "no_embedded_profile"
    assert snap["counters"].get("drift.alerts", 0) == 0


def test_rollover_chains_lineage(bst):
    X, y = _data(seed=9)
    cand = _train(X, y, rounds=3)
    svc = PredictionService({"m": bst}, max_batch_rows=128,
                            max_delay_ms=0.5, batch_events=False)
    svc.warmup()
    rep = svc.rollover("m", cand)
    assert rep["promoted"]
    snap = svc.tel.snapshot()
    svc.close()
    ev = [e for e in snap["events"] if e.get("event") == "serve_rollover"]
    assert len(ev) == 1
    assert ev[0]["old_run_id"] == bst.provenance["run_id"]
    assert ev[0]["new_run_id"] == cand.provenance["run_id"]
    assert ev[0]["new_profile_digest"] == \
        cand.provenance["profile_digest"][:16]
    # the promoted model's age gauge restarted
    assert snap["gauges"].get("serve.model_age_s.m", 1e9) < 60.0


def test_drift_monitor_hysteresis_latches_once():
    prof = {"schema": drift_mod.PROFILE_SCHEMA, "rows": 100,
            "features": [{"index": 0, "num_bin": 4,
                          "counts": [100, 0, 0, 0],
                          "missing_rate": 0.0, "categorical": False}]}
    mon = DriftMonitor(prof, psi_threshold=0.2, eval_rows=1,
                       hysteresis=2)
    shifted = np.full((8, 1), 3, np.int64)
    mon.accumulate(shifted)
    assert mon.evaluate(force=True)["alert"] is False   # 1st over: armed
    mon.accumulate(shifted)
    assert mon.evaluate(force=True)["alert"] is True    # 2nd over: fires
    mon.accumulate(shifted)
    assert mon.evaluate(force=True)["alert"] is False   # latched
    assert mon.alerts == 1


# --------------------------------------------------------- ingest drift
def test_ingest_mapper_drift_event(tmp_path):
    from lightgbm_tpu.ingest.prefetch import publish_ingest_stats
    from lightgbm_tpu.obs.registry import Telemetry
    rng = np.random.RandomState(0)
    Xa = rng.rand(400, 4).astype(np.float32)
    ya = (Xa[:, 0] > 0.5).astype(np.float32)
    pa = str(tmp_path / "a.csv")
    with open(pa, "w") as fh:
        for i in range(len(ya)):
            fh.write(",".join([f"{ya[i]:g}"]
                              + [repr(float(v)) for v in Xa[i]]) + "\n")
    dsp = {"max_bin": 63, "verbose": -1, "two_round": True,
           "ingest_chunk_rows": 97}
    ds_a = lgb.Dataset(pa, params=dict(dsp))
    ds_a.construct()
    # the training file diffs clean against its own mappers
    md_a = ds_a._inner.ingest_stats["mapper_drift"]
    assert md_a["flagged_chunks"] == 0
    # a validation file from a SHIFTED distribution, binned against the
    # frozen reference mappers, must flag
    Xb = (Xa + 2.0).astype(np.float32)
    pb = str(tmp_path / "b.csv")
    with open(pb, "w") as fh:
        for i in range(len(ya)):
            fh.write(",".join([f"{ya[i]:g}"]
                              + [repr(float(v)) for v in Xb[i]]) + "\n")
    ds_b = lgb.Dataset(pb, params=dict(dsp), reference=ds_a)
    ds_b.construct()
    md_b = ds_b._inner.ingest_stats["mapper_drift"]
    assert md_b["flagged_chunks"] > 0
    assert md_b["out_of_range"] > 0
    assert md_b["worst_feature"] >= 0
    # publishing the stats lands the structured event + counters
    tel = Telemetry(enabled=True)
    publish_ingest_stats(tel, ds_b._inner.ingest_stats)
    snap = tel.snapshot()
    assert snap["counters"]["ingest.drift_chunks"] == \
        md_b["flagged_chunks"]
    assert snap["counters"]["ingest.out_of_range_values"] == \
        md_b["out_of_range"]
    ev = [e for e in snap["events"] if e.get("event") == "mapper_drift"]
    assert len(ev) == 1 and ev[0]["threshold"] == md_b["threshold"]


def test_chunk_mapper_drift_rates():
    from lightgbm_tpu.obs.drift import chunk_mapper_drift
    rng = np.random.RandomState(1)
    # float32 throughout: the mappers froze on the float32 view, and a
    # float64 value past the rounded max would read as (tiny) drift
    X = rng.rand(300, 3).astype(np.float32).astype(np.float64)
    y = (X[:, 0] > 0.5).astype(np.float32)
    bst = _train(X.astype(np.float32), y, rounds=2)
    ds = bst.train_set._inner
    clean = chunk_mapper_drift(ds.mappers, ds.used_features, X)
    assert clean["out_of_range"] == 0 and clean["new_categories"] == 0
    drifted = chunk_mapper_drift(ds.mappers, ds.used_features, X + 5.0)
    assert drifted["out_of_range_rate"] > 0.5


# --------------------------------------------- training-side lineage
def test_training_run_report_carries_lineage(tmp_path, bst):
    X, y = _data(seed=6)
    rep_path = str(tmp_path / "rep.json")
    b = _train(X, y, rounds=3, run_report_out=rep_path,
               telemetry_out=str(tmp_path / "tel.jsonl"))
    rep = json.load(open(rep_path))
    lin = rep["lineage"]["training"]
    assert lin["run_id"] == b.provenance["run_id"]
    assert lin["profile_digest"] == profile_digest(b.data_profile)
    assert "drift" in rep        # section present even with no alerts
    assert rep["drift"]["alert_count"] == 0


# ------------------------------------------------- export / obs_tail
def test_metrics_renders_empty_dist_without_quantiles():
    from lightgbm_tpu.obs.export import render_openmetrics
    from lightgbm_tpu.obs.registry import Telemetry
    # empty-ring summary: count/sum only, no NaN quantiles
    summ = Telemetry._dist_summary([], (0, 0.0))
    assert summ == {"count": 0, "sum": 0.0}
    snap = {"counters": {}, "gauges": {}, "timings": {},
            "dists": {"serve.latency_ms": {"count": 0, "sum": 0.0}}}
    body = render_openmetrics(snap)
    assert "quantile" not in body
    assert "nan" not in body.lower()
    # a populated ring still renders its quantile series
    snap2 = {"counters": {}, "gauges": {}, "timings": {},
             "dists": {"serve.latency_ms": Telemetry._dist_summary(
                 [1.0, 2.0, 3.0])}}
    assert 'quantile="0.5"' in render_openmetrics(snap2)


def test_obs_tail_summary_drift_line(tmp_path):
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "scripts"))
    from obs_tail import summarize
    records = [
        {"ts": 1.0, "event": "drift", "model_id": "m", "psi_max": 0.41,
         "score_psi": 0.1, "rows": 256, "model_age_s": 12.5},
        {"ts": 2.0, "event": "drift_alert", "model_id": "m",
         "psi_max": 0.41, "worst_feature": 2, "worst_psi": 0.41},
    ]
    out = summarize(records)
    line = next(l for l in out.splitlines() if l.startswith("drift:"))
    assert "psi_max=0.41" in line
    assert "alerts=1" in line
    assert "model_age_s=12.5" in line
    # drift_alert records land in the findings tail too
    assert "findings (1):" in out
