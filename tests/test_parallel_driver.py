"""tree_learner=data|voting|feature through the REAL product API on the
8-virtual-device mesh — the analog of the reference's distributed mockup
driving the actual CLI binary (ref: tests/distributed/_test_distributed.py
trains the full product, not a standalone learner; factory composition
being matched: src/treelearner/tree_learner.cpp:17-49).

Every test trains through lgb.train()/Booster with the full driver
(objective dispatch, bagging, shrinkage, bookkeeping) and compares
against the identical single-device ("serial") run.
"""
import jax
import numpy as np
import pytest

import lightgbm_tpu as lgb


@pytest.fixture(scope="module")
def data():
    rng = np.random.RandomState(7)
    n = 4096
    X = rng.randn(n, 12)
    X[rng.rand(n, 12) < 0.05] = np.nan
    y = (np.nan_to_num(X[:, 0]) + 0.5 * np.nan_to_num(X[:, 1]) ** 2
         > 0.4).astype(np.float32)
    return X, y


def _train(X, y, params):
    ds = lgb.Dataset(X, label=y)
    return lgb.train(dict(params), ds)


BASE = {"objective": "binary", "num_leaves": 15, "num_iterations": 5,
        "min_data_in_leaf": 5, "verbose": -1}


def test_mesh_available():
    assert jax.device_count() >= 8


def test_data_parallel_matches_serial(data):
    X, y = data
    p1 = _train(X, y, BASE).predict(X)
    p8 = _train(X, y, dict(BASE, tree_learner="data")).predict(X)
    np.testing.assert_allclose(p8, p1, atol=1e-6)


def test_data_parallel_with_bagging_matches_serial(data):
    X, y = data
    params = dict(BASE, bagging_fraction=0.7, bagging_freq=1,
                  feature_fraction=0.8)
    p1 = _train(X, y, params).predict(X)
    p8 = _train(X, y, dict(params, tree_learner="data")).predict(X)
    # host-side reference-parity RNG streams are shard-independent, so the
    # in-bag sets are identical and only psum float ordering differs
    np.testing.assert_allclose(p8, p1, atol=1e-6)


def test_data_parallel_multiclass_matches_serial(data):
    X, _ = data
    rng = np.random.RandomState(3)
    y3 = (rng.rand(X.shape[0]) * 3).astype(int)
    params = {"objective": "multiclass", "num_class": 3, "num_leaves": 7,
              "num_iterations": 3, "verbose": -1}
    p1 = _train(X, y3, params).predict(X)
    p8 = _train(X, y3, dict(params, tree_learner="data")).predict(X)
    np.testing.assert_allclose(p8, p1, atol=1e-6)


def test_voting_parallel_full_topk_matches_data_parallel(data):
    # with top_k >= F the vote admits every feature: voting must reproduce
    # data-parallel EXACTLY — identical psum payloads, identical float
    # order (ref: voting_parallel_tree_learner.cpp degenerates the same
    # way). The serial run is only quality-compared: the per-shard
    # summation order differs from the single-device chunked scan in f32,
    # so depth-wise near-tie splits may legitimately flip (the reference's
    # distributed tests assert accuracy, not bit-equality —
    # tests/distributed/_test_distributed.py:170-198).
    X, y = data
    params = dict(BASE, grow_policy="depthwise")
    pd_ = _train(X, y, dict(params, tree_learner="data")).predict(X)
    pv = _train(X, y, dict(params, tree_learner="voting",
                           top_k=X.shape[1])).predict(X)
    np.testing.assert_array_equal(pv, pd_)

    from sklearn.metrics import roc_auc_score
    ps = _train(X, y, params).predict(X)
    assert abs(roc_auc_score(y, pv) - roc_auc_score(y, ps)) < 2e-3


def test_voting_parallel_restricted_topk_trains(data):
    X, y = data
    bst = _train(X, y, dict(BASE, tree_learner="voting", top_k=3))
    assert bst.num_trees() == BASE["num_iterations"]
    from sklearn.metrics import roc_auc_score
    assert roc_auc_score(y, bst.predict(X)) > 0.8


def test_feature_parallel_matches_serial_depthwise(data):
    X, y = data
    params = dict(BASE, grow_policy="depthwise")
    p1 = _train(X, y, params).predict(X)
    pf = _train(X, y, dict(params, tree_learner="feature")).predict(X)
    np.testing.assert_allclose(pf, p1, atol=1e-6)


def test_fused_engine_data_parallel_bitexact(data):
    """VERDICT r2 #2: the fused Pallas engine keeps its per-level psum on
    the mesh; trees must match single-device fused trees bit-for-bit on
    the count channel (leaf counts) and to float tolerance on values."""
    X, y = data
    params = dict(BASE, tpu_engine="fused", num_iterations=3)
    b1 = _train(X, y, params)
    b8 = _train(X, y, dict(params, tree_learner="data"))
    m1, m8 = b1.model_to_string(), b8.model_to_string()
    import re
    counts1 = re.findall(r"leaf_count=([\d ]+)", m1)
    counts8 = re.findall(r"leaf_count=([\d ]+)", m8)
    assert counts1 == counts8 and len(counts1) == 3
    np.testing.assert_allclose(b8.predict(X), b1.predict(X), atol=1e-6)


def test_fused_engine_data_parallel_fast_path_used(data):
    """The pipelined fast path must stay alive under tree_learner=data
    (it is the flagship multi-chip mode)."""
    X, y = data
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train(dict(BASE, tpu_engine="fused", tree_learner="data"), ds)
    gbdt = bst._gbdt
    assert gbdt.parallel_mode == "data"
    assert gbdt._fast_path_ok()
    assert bst.num_trees() == BASE["num_iterations"]


def test_data_parallel_categorical_and_monotone(data):
    """Categorical splits + monotone bounds must survive the psum path
    (none of the round-2 mesh tests exercised them — VERDICT weak #5)."""
    rng = np.random.RandomState(11)
    n = 2048
    Xc = rng.randn(n, 6)
    cat = rng.randint(0, 8, n)
    Xc[:, 2] = cat
    y = ((Xc[:, 0] > 0) ^ (cat % 2 == 0)).astype(np.float32)
    params = {"objective": "binary", "num_leaves": 15, "num_iterations": 4,
              "verbose": -1, "categorical_feature": [2],
              "monotone_constraints": [1, 0, 0, 0, 0, 0]}

    def train(extra):
        ds = lgb.Dataset(Xc, label=y, categorical_feature=[2])
        return lgb.train(dict(params, **extra), ds)

    p1 = train({}).predict(Xc)
    p8 = train({"tree_learner": "data"}).predict(Xc)
    np.testing.assert_allclose(p8, p1, atol=1e-6)


def test_reset_parameter_mode_guards_refire(data):
    """Enabling CEGB mid-train under tree_learner=feature must degrade the
    mode to data-parallel instead of feeding the 3-operand feature-mode
    shard_map a 4th (cegb_used) operand (round-3 review finding)."""
    X, y = data
    ds = lgb.Dataset(X[:1024], label=y[:1024])
    bst = lgb.train(
        dict(BASE, num_iterations=3, tree_learner="feature"), ds,
        callbacks=[lgb.reset_parameter(
            cegb_penalty_split=[0.0, 0.1, 0.1])])
    assert bst.num_trees() == 3
    assert bst._gbdt.parallel_mode == "data"   # degraded, still distributed


def test_serial_fallback_single_device_warning(data, monkeypatch):
    """tree_learner=data on a single visible device trains serially."""
    X, y = data
    monkeypatch.setattr(jax, "device_count", lambda *a, **k: 1)
    bst = _train(X[:512], y[:512], dict(BASE, num_iterations=2,
                                        tree_learner="data"))
    assert bst._gbdt.parallel_mode == "serial"
    assert bst.num_trees() == 2


def test_voting_leafwise_full_topk_matches_serial_leafwise(data):
    """VERDICT r3 #8: voting composes with LEAF-WISE growth (ref:
    voting_parallel_tree_learner.cpp:151-184 runs under the serial
    best-first flow). With top_k >= F every column wins the vote, so the
    voting model must reproduce the serial leaf-wise model — not just
    depthwise data-parallel."""
    X, y = data
    ps = _train(X, y, dict(BASE)).predict(X)                 # leafwise
    bv = _train(X, y, dict(BASE, tree_learner="voting",
                           top_k=X.shape[1]))
    assert bv._gbdt.grow_policy == "leafwise"
    pv = bv.predict(X)
    np.testing.assert_allclose(pv, ps, atol=1e-6)


def test_voting_leafwise_restricted_topk_trains(data):
    X, y = data
    bst = _train(X, y, dict(BASE, tree_learner="voting", top_k=3))
    assert bst._gbdt.grow_policy == "leafwise"
    assert bst.num_trees() == BASE["num_iterations"]
    from sklearn.metrics import roc_auc_score
    assert roc_auc_score(y, bst.predict(X)) > 0.8


def test_voting_ranks_categorical_splits(data):
    """Categorical columns enter the vote (per_feature_gains_cm): a
    dataset whose signal lives in a categorical feature must keep it
    through a restricted vote."""
    rng = np.random.RandomState(11)
    n = 4096
    Xc = rng.randn(n, 6)
    cat = rng.randint(0, 6, n)
    Xc[:, 2] = cat
    yc = ((cat >= 3) ^ (rng.rand(n) < 0.05)).astype(np.float32)
    ds = lgb.Dataset(Xc, label=yc, categorical_feature=[2],
                     params={"verbose": -1})
    bst = lgb.train(dict(BASE, tree_learner="voting", top_k=2), ds)
    assert bst._gbdt.parallel_mode == "voting"
    from sklearn.metrics import roc_auc_score
    assert roc_auc_score(yc, bst.predict(Xc)) > 0.9


def test_fast_path_reasons_distribution_modes(data):
    """Round 12: data AND voting ride the fast path on the fused engine
    (no eviction reason); feature-parallel keeps its serial-bit-equality
    contract on the sync driver and names itself as the reason."""
    X, y = data
    Xs, ys = X[:512], y[:512]

    def reason(extra):
        ds = lgb.Dataset(Xs, label=ys, params={"verbose": -1})
        b = lgb.Booster(params=dict(BASE, tpu_engine="fused", **extra),
                        train_set=ds)
        return b._gbdt._fast_path_reason()

    assert reason({"tree_learner": "data"}) is None
    assert reason({"tree_learner": "voting", "top_k": 3}) is None
    assert reason({"tree_learner": "feature"}) == "tree_learner:feature"
