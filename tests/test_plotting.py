"""Plotting surface (ref: python-package/lightgbm/plotting.py,
test_plotting.py basics)."""
import numpy as np
import pytest

matplotlib = pytest.importorskip("matplotlib")
matplotlib.use("Agg")

import lightgbm_tpu as lgb


@pytest.fixture(scope="module")
def model():
    rng = np.random.RandomState(0)
    X = rng.randn(800, 5).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    ds = lgb.Dataset(X, label=y, params={"verbose": -1})
    evals = {}
    bst = lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1,
                     "min_data_in_leaf": 5, "metric": "binary_logloss"},
                    ds, num_boost_round=8, valid_sets=[ds],
                    valid_names=["training"],
                    callbacks=[lgb.record_evaluation(evals)])
    return bst, evals


def test_plot_importance(model):
    bst, _ = model
    ax = lgb.plot_importance(bst)
    assert len(ax.patches) > 0
    ax2 = lgb.plot_importance(bst, importance_type="gain", precision=2)
    assert len(ax2.patches) > 0


def test_plot_metric(model):
    _, evals = model
    ax = lgb.plot_metric(evals, metric="binary_logloss")
    assert len(ax.lines) == 1


def test_plot_split_value_histogram(model):
    bst, _ = model
    ax = lgb.plot_split_value_histogram(bst, feature=0)
    assert len(ax.patches) > 0
    with pytest.raises(ValueError):
        lgb.plot_split_value_histogram(bst, feature=4)  # likely unused
