"""set_network parameter mapping (single-host parse logic only —
jax.distributed.initialize itself needs a real multi-process pod)."""
import socket

import pytest

from lightgbm_tpu.parallel import distributed


def test_empty_machines_raises():
    with pytest.raises(ValueError, match="machines"):
        distributed.set_network("")


def test_unmatched_host_raises():
    with pytest.raises(ValueError, match="none of the machines"):
        distributed.set_network("surely-not-this-host-1:1234,"
                                "surely-not-this-host-2:1234")


def test_rank_and_coordinator_parse(monkeypatch):
    captured = {}

    def fake_init(coordinator_address=None, num_processes=None,
                  process_id=None, local_device_ids=None):
        captured.update(coord=coordinator_address, n=num_processes,
                        rank=process_id)

    monkeypatch.setattr(distributed, "init_distributed",
                        lambda *a, **k: fake_init(*a, **k))
    me = socket.gethostname()
    distributed.set_network(f"otherhost:5000,{me}:5001",
                            local_listen_port=5001, num_machines=2)
    assert captured["rank"] == 1
    assert captured["coord"] == "otherhost:5000"  # entry-0 port wins
    assert captured["n"] == 2


def test_multiprocess_per_host(monkeypatch):
    captured = {}
    monkeypatch.setattr(
        distributed, "init_distributed",
        lambda coord, n, rank: captured.update(rank=rank))
    me = socket.gethostname()
    distributed.set_network(f"{me}:6000,{me}:6001",
                            local_listen_port=6001)
    assert captured["rank"] == 1
