"""Objective gradient tests: analytic grad/hess vs finite differences of the
corresponding loss (the reference encodes the same closed forms,
src/objective/*.hpp)."""
import jax.numpy as jnp
import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.dataset import Metadata
from lightgbm_tpu.objective import create_objective, create_objective_from_string


def setup_obj(name, label, params=None, weight=None, group=None):
    cfg = Config(dict({"objective": name}, **(params or {})))
    obj = create_objective(cfg)
    md = Metadata(len(label))
    md.set_label(np.asarray(label, np.float32))
    if weight is not None:
        md.set_weight(weight)
    if group is not None:
        md.set_group(group)
    obj.init(md, len(label))
    return obj


def numeric_grad(loss_fn, score, eps=1e-4):
    g = np.zeros_like(score)
    for i in range(len(score)):
        sp = score.copy()
        sp[i] += eps
        sm = score.copy()
        sm[i] -= eps
        g[i] = (loss_fn(sp) - loss_fn(sm)) / (2 * eps)
    return g


@pytest.mark.parametrize("name,loss", [
    ("regression", lambda y, s: 0.5 * np.sum((s - y) ** 2)),
    ("binary", lambda y, s: np.sum(np.log1p(np.exp(-(2 * y - 1) * s)))),
    ("poisson", lambda y, s: np.sum(np.exp(s) - y * s)),
    ("gamma", lambda y, s: np.sum(y * np.exp(-s) + s)),
    ("cross_entropy",
     lambda y, s: -np.sum(y * np.log(1 / (1 + np.exp(-s)))
                          + (1 - y) * np.log(1 - 1 / (1 + np.exp(-s))))),
])
def test_gradient_matches_finite_difference(name, loss):
    rng = np.random.RandomState(0)
    n = 20
    if name in ("poisson", "gamma"):
        label = rng.rand(n).astype(np.float32) + 0.5
    elif name in ("binary",):
        label = (rng.rand(n) > 0.5).astype(np.float32)
    elif name == "cross_entropy":
        label = rng.rand(n).astype(np.float32)
    else:
        label = rng.randn(n).astype(np.float32)
    obj = setup_obj(name, label)
    score = rng.randn(n).astype(np.float64) * 0.5
    g, h = obj.get_gradients(jnp.asarray(score[None, :], jnp.float32))
    g_num = numeric_grad(lambda s: loss(label.astype(np.float64), s), score)
    np.testing.assert_allclose(np.asarray(g)[0], g_num, rtol=2e-2, atol=2e-3)
    assert (np.asarray(h)[0] >= 0).all()


def test_l2_boost_from_score_is_mean():
    label = np.array([1.0, 2.0, 3.0, 4.0])
    obj = setup_obj("regression", label)
    assert obj.boost_from_score(0) == pytest.approx(2.5)
    w = np.array([1.0, 0.0, 0.0, 1.0], np.float32)
    obj = setup_obj("regression", label, weight=w)
    assert obj.boost_from_score(0) == pytest.approx(2.5)


def test_binary_boost_from_score_logit():
    label = np.array([1.0] * 30 + [0.0] * 10)
    obj = setup_obj("binary", label)
    assert obj.boost_from_score(0) == pytest.approx(np.log(0.75 / 0.25))


def test_l1_renew_is_median():
    label = np.zeros(5, np.float32)
    obj = setup_obj("regression_l1", label)
    res = np.array([1.0, 5.0, 2.0, 8.0, 3.0])
    assert obj.is_renew_tree_output
    out = obj.renew_tree_output(0.0, res, np.arange(5))
    # the reference PercentileFun interpolates between the 2nd and 3rd
    # largest: 5 - (5-3)*0.5 = 4 (ref: regression_objective.hpp:18-47)
    assert out == pytest.approx(4.0)
    # when float_pos lands on an integer, bias=0 picks the pos-1 largest
    out2 = obj.renew_tree_output(0.0, np.array([1.0, 2.0, 3.0, 4.0]),
                                 np.arange(4))
    assert out2 == pytest.approx(3.0)


def test_quantile_renew_is_percentile():
    label = np.zeros(101, np.float32)
    obj = setup_obj("quantile", label, {"alpha": 0.9})
    res = np.arange(101, dtype=np.float64)
    out = obj.renew_tree_output(0.0, res, np.arange(101))
    assert 88 <= out <= 92


def test_multiclass_gradients_sum_zero():
    rng = np.random.RandomState(1)
    label = rng.randint(0, 3, 30)
    obj = setup_obj("multiclass", label, {"num_class": 3})
    score = jnp.asarray(rng.randn(3, 30), jnp.float32)
    g, h = obj.get_gradients(score)
    np.testing.assert_allclose(np.asarray(g).sum(axis=0), 0.0, atol=1e-5)
    assert (np.asarray(h) > 0).all()


def test_lambdarank_zero_gradient_when_perfect_separation_saturates():
    # lambdas push high-label docs up: with equal scores, gradient of the
    # top-label doc must be negative (boosting subtracts gradients)
    label = np.array([2, 1, 0, 0], np.float32)
    obj = setup_obj("lambdarank", label, group=[4])
    g, h = obj.get_gradients(jnp.zeros((1, 4), jnp.float32))
    g = np.asarray(g)[0]
    assert g[0] < 0          # top doc pushed up
    assert g[2] > 0 or g[3] > 0  # low docs pushed down
    assert abs(g.sum()) < 1e-5


def test_rank_xendcg_gradients_finite():
    rng = np.random.RandomState(2)
    label = rng.randint(0, 4, 20).astype(np.float32)
    obj = setup_obj("rank_xendcg", label, group=[10, 10])
    g, h = obj.get_gradients(jnp.asarray(rng.randn(1, 20), jnp.float32))
    assert np.isfinite(np.asarray(g)).all()
    assert np.isfinite(np.asarray(h)).all()


def test_objective_tostring_roundtrip():
    label = (np.arange(20) % 2).astype(np.float32)
    obj = setup_obj("binary", label, {"sigmoid": 2.0})
    s = obj.to_string()
    obj2 = create_objective_from_string(s)
    assert obj2.name == "binary"
    assert obj2.sigmoid == pytest.approx(2.0)


def test_unbalance_weights():
    label = np.array([1.0] * 10 + [0.0] * 90, np.float32)
    obj = setup_obj("binary", label, {"is_unbalance": True})
    g, h = obj.get_gradients(jnp.zeros((1, 100), jnp.float32))
    g = np.asarray(g)[0]
    # positive-class gradient magnified by 9x
    assert abs(g[0]) == pytest.approx(9 * abs(g[-1]), rel=1e-5)
