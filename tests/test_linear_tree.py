"""Linear trees: per-leaf ridge on path features
(ref: linear_tree_learner.cpp CalculateLinear, arXiv:1802.05640 Eq 3)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _data(R=4000, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.rand(R, 3).astype(np.float32)
    # piecewise-LINEAR target: constant leaves need many splits, linear
    # leaves capture it with few
    y = (np.where(X[:, 0] > 0.5, 2.0 * X[:, 1], -1.5 * X[:, 1])
         + 0.02 * rng.randn(R)).astype(np.float32)
    return X, y


def test_linear_beats_constant_leaves():
    X, y = _data()
    p_base = {"objective": "regression", "num_leaves": 4, "verbose": -1,
              "min_data_in_leaf": 20, "learning_rate": 0.2}
    ds1 = lgb.Dataset(X, label=y, params={"verbose": -1})
    bst_c = lgb.train(dict(p_base), ds1, num_boost_round=30)
    mse_c = float(np.mean((bst_c.predict(X) - y) ** 2))

    ds2 = lgb.Dataset(X, label=y, params={"verbose": -1,
                                          "linear_tree": True})
    bst_l = lgb.train(dict(p_base, linear_tree=True), ds2,
                      num_boost_round=30)
    mse_l = float(np.mean((bst_l.predict(X) - y) ** 2))
    # stock LightGBM on this exact data: const 0.0052249, linear 0.0035681
    # (a 1.46x improvement); ours matches both to ~1e-6 relative
    assert mse_l < mse_c * 0.75, (mse_l, mse_c)
    assert abs(mse_l - 0.0035681) < 2e-4


def test_linear_tree_model_roundtrip(tmp_path):
    X, y = _data(seed=1)
    ds = lgb.Dataset(X, label=y, params={"verbose": -1, "linear_tree": True})
    bst = lgb.train({"objective": "regression", "num_leaves": 8,
                     "verbose": -1, "min_data_in_leaf": 20,
                     "linear_tree": True}, ds, num_boost_round=5)
    pred = bst.predict(X)
    path = str(tmp_path / "lin.txt")
    bst.save_model(path)
    assert "leaf_coeff" in open(path).read()
    b2 = lgb.Booster(model_file=path)
    np.testing.assert_allclose(b2.predict(X), pred, rtol=1e-8)


def test_linear_nan_falls_back_to_constant():
    X, y = _data(seed=2)
    ds = lgb.Dataset(X, label=y, params={"verbose": -1, "linear_tree": True})
    bst = lgb.train({"objective": "regression", "num_leaves": 8,
                     "verbose": -1, "min_data_in_leaf": 20,
                     "linear_tree": True}, ds, num_boost_round=5)
    Xn = X[:50].copy()
    Xn[:, 1] = np.nan
    p = bst.predict(Xn)
    assert np.isfinite(p).all()
