"""SLO plane (obs/slo.py + wiring).

Tier-1 coverage of the declarative-objective engine: fire/resolve
lifecycle with burn-rate + hysteresis semantics, the slow-window burn
guard (a breach streak alone must not page), config-file overlay over
the built-in catalog (merge / disable / reject), plane filtering,
bounded incident capture, the ``GET /alerts`` endpoint, ``/readyz``
gating behind ``slo_readyz_gating``, the run-report ``alerts`` section
and its run_diff regression gate, the dispatch-neutral training
integration, and the obs_tail ``alerts:`` summary line.

Every engine in here runs with ``tick_period_s=0`` (no daemon thread)
and an injected ``now`` so the burn windows are exact.
"""
import importlib.util
import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs import MetricsExporter, Telemetry
from lightgbm_tpu.obs.report import (build_report, compare_reports,
                                     render_markdown)
from lightgbm_tpu.obs.slo import (BUILTIN_OBJECTIVES, INCIDENT_SCHEMA,
                                  SloEngine, SloSpec, load_slo_config)
from lightgbm_tpu.serve import PredictionService

_SCRIPTS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts")


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_SCRIPTS, name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _lat_spec(**kw):
    base = dict(id="lat", kind="latency_p99", target=50.0,
                comparison="above", severity="page", hysteresis=2,
                resolve_hysteresis=2, plane="serve")
    base.update(kw)
    return SloSpec(**base)


def _counters(tel):
    return tel.snapshot().get("counters", {})


def _events(tel, name):
    return [e for e in tel.snapshot().get("events", [])
            if e.get("event") == name]


# ---------------------------------------------------------------- core
def test_fire_resolve_lifecycle_and_incident(tmp_path):
    tel = Telemetry(enabled=True)
    base = str(tmp_path / "tel.jsonl")
    eng = SloEngine(tel, source="serve", specs=[_lat_spec()],
                    tick_period_s=0.0, incident_base=base,
                    context_fn=lambda: {"who": "test"})
    try:
        tel.dist("serve.latency_ms", 400.0)
        assert eng.step(now=100.0, force=True)
        assert eng.active_alerts() == []          # hysteresis=2: not yet
        assert eng.step(now=130.0, force=True)

        active = eng.active_alerts()
        assert len(active) == 1
        a = active[0]
        assert a["objective"] == "lat"
        assert a["alert_id"] == "lat#1"
        assert a["severity"] == "page"
        assert a["burn_fast"] == 1.0 and a["burn_slow"] == 1.0
        assert eng.gating_reason() == "lat"

        c = _counters(tel)
        assert c.get("slo.alerts_fired") == 1
        assert c.get("slo.alerts_page") == 1
        assert c.get("slo.incidents") == 1
        assert c.get("slo.ticks") == 2

        # the transition is a finding event: it survives the whole run
        alerts = [e for e in tel.snapshot().get("findings", [])
                  if e.get("event") == "alert"]
        assert [e["state"] for e in alerts] == ["firing"]
        assert alerts[0]["measured"] == 400.0
        assert alerts[0]["target"] == 50.0

        # incident artifact: bounded, schema-versioned, context attached
        inc_path = base + ".incident.lat-1.json"
        assert os.path.exists(inc_path)
        with open(inc_path) as fh:
            inc = json.load(fh)
        assert inc["schema"] == INCIDENT_SCHEMA
        assert inc["source"] == "serve"
        assert inc["alert"]["objective"] == "lat"
        assert inc["context"] == {"who": "test"}
        assert "lat#1" in inc["active_alerts"]
        assert isinstance(inc["telemetry"], dict)
        assert len(_events(tel, "incident_captured")) == 1

        # drown the slow sample: p99 of the ring drops under target
        for _ in range(300):
            tel.dist("serve.latency_ms", 1.0)
        assert eng.step(now=160.0, force=True)
        assert eng.active_alerts(), "one clean tick must not resolve"
        assert eng.step(now=190.0, force=True)
        assert eng.active_alerts() == []
        assert eng.gating_reason() is None

        c = _counters(tel)
        assert c.get("slo.alerts_resolved") == 1
        pay = eng.alerts_payload()
        assert pay["fired"] == 1 and pay["resolved"] == 1
        assert pay["source"] == "serve"
        assert [h["state"] for h in pay["history"]] == ["firing", "resolved"]
        resolved = pay["history"][-1]
        assert resolved["alert_id"] == "lat#1"
        assert resolved["duration_s"] == pytest.approx(60.0, abs=1e-6)
        assert pay["incidents"] == [inc_path]
    finally:
        eng.stop()
        tel.close()


def test_hysteresis_blocks_short_breach():
    tel = Telemetry(enabled=True)
    eng = SloEngine(tel, source="serve", specs=[_lat_spec(hysteresis=3)],
                    tick_period_s=0.0)
    try:
        tel.dist("serve.latency_ms", 400.0)
        eng.step(now=10.0, force=True)
        eng.step(now=20.0, force=True)
        assert eng.active_alerts() == []          # 2 of 3 breaches
        eng.step(now=30.0, force=True)
        assert len(eng.active_alerts()) == 1      # third fires
    finally:
        eng.stop()
        tel.close()


def test_slow_burn_window_blocks_premature_page():
    """A fresh breach streak satisfies hysteresis and the fast window,
    but the slow-window burn rate must also cross the threshold: a long
    clean history keeps the page from firing until the breach has
    consumed enough of the slow window."""
    tel = Telemetry(enabled=True)
    spec = SloSpec(id="div", kind="shadow_divergence", target=0.1,
                   severity="page", hysteresis=2, fast_window_s=60.0,
                   slow_window_s=600.0, burn_threshold=0.5, plane="serve")
    eng = SloEngine(tel, source="serve", specs=[spec], tick_period_s=0.0)
    try:
        tel.gauge("serve.shadow_divergence", 0.0)
        for k in range(20):                       # clean history, 30 s ticks
            eng.step(now=30.0 * k, force=True)    # t = 0 .. 570
        tel.gauge("serve.shadow_divergence", 0.9)
        fired_at = None
        for k in range(1, 21):                    # breaches at t = 600, 630, ..
            eng.step(now=570.0 + 30.0 * k, force=True)
            if eng.active_alerts():
                fired_at = k
                break
        # over-streak and fast burn are satisfied from breach #2 on, but
        # slow burn is k/21 — it crosses 0.5 only at the 11th breach
        assert fired_at == 11
        obj = eng.alerts_payload()["objectives"][0]
        assert obj["burn_slow"] >= spec.burn_threshold
        assert _counters(tel).get("slo.alerts_fired") == 1
    finally:
        eng.stop()
        tel.close()


# -------------------------------------------------------------- config
def test_config_overlay_merge_disable_reject(tmp_path):
    cfg = tmp_path / "slo.json"
    cfg.write_text(json.dumps({"objectives": [
        {"id": "serve.latency_p99", "target": 123.0},
        {"id": "serve.shed_ratio", "disabled": True},
        {"id": "custom.div", "kind": "shadow_divergence", "target": 0.9,
         "severity": "page"},
        {"id": "bogus.new"},                       # new id without a kind
        {"id": "bad.kind", "kind": "nope"},        # unknown kind
    ]}))
    tel = Telemetry(enabled=True)
    eng = SloEngine(tel, source="serve", config_path=str(cfg),
                    tick_period_s=0.0)
    try:
        objs = {o["id"]: o for o in eng.alerts_payload()["objectives"]}
        assert objs["serve.latency_p99"]["target"] == 123.0
        assert objs["serve.latency_p99"]["severity"] == "page"  # kept
        assert "serve.shed_ratio" not in objs                   # disabled
        assert objs["custom.div"]["kind"] == "shadow_divergence"
        assert "bogus.new" not in objs
        assert "bad.kind" not in objs
        errs = _events(tel, "slo_config_error")
        assert {e.get("objective") for e in errs} == {"bogus.new",
                                                      "bad.kind"}
        loaded = _events(tel, "slo_config_loaded")
        assert len(loaded) == 1 and loaded[0]["path"] == str(cfg)
        assert tel.snapshot()["gauges"].get("slo.objectives") == float(
            len(objs))
    finally:
        eng.stop()
        tel.close()


def test_malformed_config_falls_back_to_catalog(tmp_path):
    cfg = tmp_path / "broken.json"
    cfg.write_text("{not json")
    with pytest.raises(ValueError):
        load_slo_config(str(cfg))
    tel = Telemetry(enabled=True)
    eng = SloEngine(tel, source="serve", config_path=str(cfg),
                    tick_period_s=0.0)
    try:
        errs = _events(tel, "slo_config_error")
        assert len(errs) == 1 and errs[0]["path"] == str(cfg)
        serve_catalog = [s for s in BUILTIN_OBJECTIVES
                         if s.plane in ("any", "serve")]
        assert len(eng.alerts_payload()["objectives"]) == len(serve_catalog)
        assert eng.step(force=True)               # catalog still evaluates
    finally:
        eng.stop()
        tel.close()


def test_plane_filter_selects_source_objectives():
    tel = Telemetry(enabled=True)
    serve_eng = SloEngine(tel, source="serve", tick_period_s=0.0)
    train_eng = SloEngine(tel, source="train", tick_period_s=0.0)
    try:
        serve_ids = {o["id"] for o in serve_eng.alerts_payload()["objectives"]}
        train_ids = {o["id"] for o in train_eng.alerts_payload()["objectives"]}
        assert "serve.latency_p99" in serve_ids
        assert not any(i.startswith("train.") for i in serve_ids)
        assert "train.liveness" in train_ids
        assert "serve.latency_p99" not in train_ids
        # plane="any" objectives run on both engines (the drift ceiling
        # watches ingest-side PSI during training and serving alike)
        assert "obs.scrape_staleness" in serve_ids & train_ids
        assert "serve.drift_score" in serve_ids & train_ids
    finally:
        serve_eng.stop()
        train_eng.stop()
        tel.close()


def test_incident_capture_is_bounded(tmp_path):
    from lightgbm_tpu.obs import slo as slo_mod
    tel = Telemetry(enabled=True)
    specs = [_lat_spec(id=f"lat{i}", hysteresis=1)
             for i in range(slo_mod._MAX_INCIDENTS + 3)]
    eng = SloEngine(tel, source="serve", specs=specs, tick_period_s=0.0,
                    incident_base=str(tmp_path / "t.jsonl"))
    try:
        tel.dist("serve.latency_ms", 400.0)
        eng.step(now=10.0, force=True)            # every objective fires
        c = _counters(tel)
        assert c.get("slo.alerts_fired") == len(specs)
        assert c.get("slo.incidents") == slo_mod._MAX_INCIDENTS
        assert c.get("slo.incidents_dropped") == 3
        assert len(eng.alerts_payload()["incidents"]) == slo_mod._MAX_INCIDENTS
    finally:
        eng.stop()
        tel.close()


# ----------------------------------------------------------- endpoints
def test_alerts_endpoint_serves_payload_and_404s_without_engine():
    tel = Telemetry(enabled=True)
    eng = SloEngine(tel, source="serve", specs=[_lat_spec()],
                    tick_period_s=0.0)
    exp = MetricsExporter(tel, 0, alerts_fn=eng.alerts_payload)
    port = exp.start()
    try:
        tel.dist("serve.latency_ms", 400.0)
        eng.step(now=1.0, force=True)
        eng.step(now=2.0, force=True)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/alerts", timeout=10) as resp:
            pay = json.loads(resp.read().decode("utf-8"))
        assert pay["fired"] == 1
        assert pay["active"][0]["objective"] == "lat"
        assert pay["objectives"][0]["firing"] is True
    finally:
        exp.stop()
        eng.stop()
        tel.close()

    tel2 = Telemetry(enabled=True)
    exp2 = MetricsExporter(tel2, 0)               # no engine armed
    port2 = exp2.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port2}/alerts", timeout=10)
        assert ei.value.code == 404
    finally:
        exp2.stop()
        tel2.close()


def _svc_model():
    rng = np.random.RandomState(0)
    X = rng.rand(300, 6).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float32)
    return lgb.train({"objective": "binary", "num_leaves": 7,
                      "verbose": -1, "min_data_in_leaf": 5},
                     lgb.Dataset(X, label=y), num_boost_round=3)


def test_readyz_gating_on_page_alert(tmp_path):
    cfg = tmp_path / "slo.json"
    cfg.write_text(json.dumps({"objectives": [
        {"id": "serve.latency_p99", "target": 50.0,
         "hysteresis": 2, "resolve_hysteresis": 2}]}))
    bst = _svc_model()
    svc = PredictionService({"m": bst}, max_batch_rows=64,
                            batch_events=False, slo_config=str(cfg),
                            slo_tick_period_s=0.0, slo_readyz_gating=True)
    try:
        assert svc.slo is not None
        svc.warmup(buckets=[64])
        ok, _reason = svc._readiness()
        assert ok
        svc.tel.dist("serve.latency_ms", 400.0)
        svc.slo.step(now=10.0, force=True)
        svc.slo.step(now=20.0, force=True)
        ok, reason = svc._readiness()
        assert not ok
        assert reason == "slo_alert:serve.latency_p99"
    finally:
        svc.close()

    # gating off (the default): the same firing alert must NOT drop
    # readiness — alerting observes, gating is an explicit opt-in
    svc2 = PredictionService({"m": bst}, max_batch_rows=64,
                             batch_events=False, slo_config=str(cfg),
                             slo_tick_period_s=0.0)
    try:
        svc2.warmup(buckets=[64])
        svc2.tel.dist("serve.latency_ms", 400.0)
        svc2.slo.step(now=10.0, force=True)
        svc2.slo.step(now=20.0, force=True)
        assert svc2.slo.gating_reason() == "serve.latency_p99"
        ok, _reason = svc2._readiness()
        assert ok
    finally:
        svc2.close()


# ------------------------------------------------------ report / diff
def _fired_snapshot(tmp_path):
    tel = Telemetry(enabled=True)
    eng = SloEngine(tel, source="serve", specs=[_lat_spec()],
                    tick_period_s=0.0,
                    incident_base=str(tmp_path / "tel.jsonl"))
    tel.dist("serve.latency_ms", 400.0)
    eng.step(now=10.0, force=True)
    eng.step(now=20.0, force=True)
    snap = tel.snapshot()
    eng.stop()
    tel.close()
    return snap


def test_report_alerts_section_and_markdown(tmp_path):
    snap = _fired_snapshot(tmp_path)
    rep = build_report(snap, run_id="r1")
    al = rep["alerts"]
    assert al["fired"] == 1 and al["resolved"] == 0
    assert al["incidents"] == 1
    assert al["active"] == ["lat"]
    assert al["transitions"][-1]["state"] == "firing"
    assert al["transitions"][-1]["objective"] == "lat"
    md = render_markdown(rep)
    assert "## SLO alerts" in md
    assert "lat" in md


def test_run_diff_flags_newly_firing_alert(tmp_path):
    clean_tel = Telemetry(enabled=True)
    clean = build_report(clean_tel.snapshot(), run_id="base")
    clean_tel.close()
    fired = build_report(_fired_snapshot(tmp_path), run_id="cand")

    cmp_rep = compare_reports(clean, fired)
    names = [r["name"] for r in cmp_rep.get("regressions", [])]
    assert "slo_alert:lat" in names

    # identical runs compare clean — the alert gate must not misfire
    cmp_same = compare_reports(fired, fired)
    assert not any(r["name"].startswith("slo_alert:")
                   for r in cmp_same.get("regressions", []))

    base_p = tmp_path / "base.json"
    cand_p = tmp_path / "cand.json"
    base_p.write_text(json.dumps(clean))
    cand_p.write_text(json.dumps(fired))
    run_diff = _load_script("run_diff")
    assert run_diff.main([str(base_p), str(cand_p),
                          "--fail-on-regress"]) == 1
    assert run_diff.main([str(cand_p), str(cand_p),
                          "--fail-on-regress"]) == 0


# ------------------------------------------------- training integration
def test_training_with_slo_enabled_is_clean_and_ticks(tmp_path):
    rng = np.random.RandomState(7)
    X = rng.rand(500, 6).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float32)
    out = str(tmp_path / "tel.jsonl")
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbose": -1, "min_data_in_leaf": 5,
                     "telemetry_out": out, "slo_enabled": True},
                    lgb.Dataset(X, label=y), num_boost_round=5)
    snap = bst.telemetry()
    c = snap.get("counters", {})
    assert c.get("slo.ticks", 0) >= 1
    assert c.get("slo.alerts_fired", 0) == 0      # clean run: no alerts
    assert snap.get("gauges", {}).get("slo.objectives", 0) > 0
    # the final forced step at finalize lands in the sink too
    with open(out) as fh:
        recs = [json.loads(ln) for ln in fh if ln.strip()]
    assert not any(r.get("event") == "alert" for r in recs)


# ------------------------------------------------------------ obs_tail
def test_obs_tail_summary_alerts_line():
    obs_tail = _load_script("obs_tail")
    recs = [
        {"event": "alert", "state": "firing", "objective": "a",
         "severity": "page", "ts": 1.0},
        {"event": "alert", "state": "resolved", "objective": "a",
         "severity": "page", "ts": 2.0},
        {"event": "alert", "state": "firing", "objective": "b",
         "severity": "ticket", "ts": 3.0},
    ]
    out = obs_tail.summarize(recs)
    line = next(ln for ln in out.splitlines() if ln.startswith("alerts:"))
    assert "fired=2" in line
    assert "resolved=1" in line
    assert "b" in line and "'a'" not in line      # only b still active
