"""Serving chaos acceptance: overload and rollover under live traffic.

The ISSUE's two acceptance contracts, exercised end to end against a
real ``PredictionService`` (real engines, real device dispatches on the
CPU backend):

1. **Overload acceptance** — open-loop offered load > capacity: the
   queue depth stays within the configured bound, refused requests get
   STRUCTURED errors (``ServeRejected`` with a retry-after hint /
   ``ServeDeadlineExceeded`` shed at dequeue), accepted-request p99
   stays bounded (shedding absorbs the excess — latency does not
   diverge with offered load), and ZERO futures are left unresolved.

2. **Rollover under load** — continuous traffic across ``rollover()``:
   zero dropped/failed requests, every response attributable to exactly
   one model version (the ``serve_access`` ``model_version`` field over
   the full JSONL sink, not the bounded event ring), the
   ``serve_rollover`` event carries old/new hashes, and a resilience
   CHECKPOINT source round-trips into residency.

Marked ``chaos`` (the serve-chaos CI job runs
``tests/test_serve_chaos.py -m chaos``) and ``slow`` (seconds of
deliberate overload; the weekly slow pass includes them, tier-1's
``-m 'not slow'`` does not).

Capacity throttling is a wrapped ``batcher._dispatch`` adding a fixed
per-batch floor — the offered/capacity ratio is then deterministic on
any runner speed.
"""
import json
import threading
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.serve import (PredictionService, ServeDeadlineExceeded,
                                ServeError, ServeRejected)

pytestmark = pytest.mark.slow

F = 8


def _train(seed=0, n=500, rounds=6, **extra):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, F).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float32)
    params = {"objective": "binary", "num_leaves": 15,
              "learning_rate": 0.2, "verbose": -1, "min_data_in_leaf": 5}
    params.update(extra)
    return lgb.train(params, lgb.Dataset(X, label=y),
                     num_boost_round=rounds)


@pytest.mark.chaos
def test_overload_acceptance_bounded_queue_and_p99(tmp_path):
    """Open-loop submit rate >> capacity -> bounded queue depth,
    structured rejects, bounded accepted p99, zero unresolved."""
    bst = _train(seed=0)
    q_bound = 16
    svc = PredictionService(
        {"m": bst}, max_batch_rows=64, max_delay_ms=0.5,
        min_bucket_rows=16, batch_events=False, serve_devices=1,
        max_queue_requests=q_bound, default_deadline_ms=300.0,
        telemetry_out=str(tmp_path / "overload.jsonl"))
    svc.warmup()
    # throttle: ~4 ms per batch floor => capacity ~ hundreds of
    # requests/s; the submit loop below offers thousands/s
    real = svc.batcher._dispatch

    def throttled(mid, X):
        time.sleep(0.004)
        return real(mid, X)
    svc.batcher._dispatch = throttled

    n_offered = 400
    rng = np.random.RandomState(1)
    done_at = {}
    futs, rejects = [], []
    t_start = time.perf_counter()
    for i in range(n_offered):
        Xq = rng.rand(2, F).astype(np.float32)
        try:
            fut = svc.submit("m", Xq)
            t_sub = time.perf_counter()
            fut.add_done_callback(
                lambda f, t=t_sub, k=len(futs):
                done_at.__setitem__(k, time.perf_counter() - t))
            futs.append(fut)
        except ServeRejected as exc:
            rejects.append(exc)
            assert exc.retry_after_ms > 0
            assert exc.reason in ("queue_requests", "queue_rows")
    offered_wall = time.perf_counter() - t_start

    served = shed = unresolved = other = 0
    for f in futs:
        try:
            f.result(timeout=60)
            served += 1
        except ServeDeadlineExceeded:
            shed += 1
        except ServeError:
            other += 1
        except Exception:
            unresolved += 1
    # every single future resolved (result() above would have raised
    # TimeoutError into `unresolved` otherwise) with a structured
    # outcome — nothing hangs, nothing leaks
    assert unresolved == 0 and other == 0
    assert served + shed == len(futs)
    assert rejects, "offered >> capacity must trip admission control"
    assert served > 0, "admitted requests must still be served"

    s = svc.stats()
    # the queue bound held the whole time (peak watermark gauge)
    assert s["queue_peak_requests"] <= q_bound
    assert s["rejected"] == len(rejects)
    assert s["shed"] == shed
    # accepted-request p99 is bounded by queue_bound/capacity + the
    # deadline, NOT by the offered load: with ~4ms batches and a
    # 16-deep queue it sits well under 2s even on a slow runner
    lat = sorted(done_at.values())
    if lat:
        p99 = lat[min(len(lat) - 1, int(0.99 * (len(lat) - 1) + 0.5))]
        assert p99 < 5.0, f"accepted p99 diverged: {p99:.3f}s"
    svc.close(drain_timeout_s=10)

    # structured rejection telemetry made it to the JSONL sink
    recs = [json.loads(ln) for ln in
            open(tmp_path / "overload.jsonl") if ln.strip()]
    assert any(r.get("event") == "serve_rejected" for r in recs)
    print(f"overload: offered {n_offered} in {offered_wall:.2f}s, "
          f"served {served}, shed {shed}, rejected {len(rejects)}")


@pytest.mark.chaos
def test_rollover_under_continuous_traffic_zero_drops(tmp_path):
    """Continuous traffic across rollover(): zero dropped requests,
    serve_rollover carries old/new hashes, every response attributable
    to exactly one model version, checkpoint source round-trips."""
    ckdir = str(tmp_path / "ck")
    b_old = _train(seed=1, rounds=6, checkpoint_dir=ckdir,
                   checkpoint_period=3)
    b_new = _train(seed=1, rounds=8, learning_rate=0.35)
    sink = str(tmp_path / "rollover.jsonl")
    svc = PredictionService(
        {"m": b_old}, max_batch_rows=64, max_delay_ms=0.5,
        min_bucket_rows=16, batch_events=False, telemetry_out=sink)
    svc.warmup()
    h_old = svc.residency.get("m").model_hash[:16]

    stop = threading.Event()
    failures, outcomes = [], []

    def traffic(seed):
        r = np.random.RandomState(seed)
        while not stop.is_set():
            Xq = r.rand(int(r.randint(1, 5)), F).astype(np.float32)
            try:
                fut = svc.submit("m", Xq)
                fut.result(timeout=60)
                outcomes.append(fut.trace_id)
            except Exception as e:
                failures.append(repr(e))
    threads = [threading.Thread(target=traffic, args=(7 + i,),
                                daemon=True) for i in range(3)]
    for t in threads:
        t.start()
    time.sleep(0.3)
    rep = svc.rollover("m", b_new)            # booster source
    assert rep["promoted"]
    time.sleep(0.3)
    rep2 = svc.rollover("m", ckdir)           # checkpoint source
    assert rep2["promoted"]
    h_ck = svc.residency.get("m").model_hash[:16]
    time.sleep(0.3)
    stop.set()
    for t in threads:
        t.join(timeout=30)
    svc.close(drain_timeout_s=30)

    # THE acceptance number: zero dropped requests across two swaps
    assert failures == [], failures[:5]
    assert len(outcomes) > 50, "traffic generator barely ran"

    # checkpoint source restored the ORIGINAL model bit-exactly: its
    # residency hash equals the pre-rollover engine's
    assert h_ck == h_old
    X = np.random.RandomState(3).rand(30, F).astype(np.float32)
    b_ck = lgb.serve.service._as_booster(ckdir)
    np.testing.assert_allclose(b_ck.predict(X), b_old.predict(X),
                               rtol=1e-5, atol=1e-6)

    recs = [json.loads(ln) for ln in open(sink) if ln.strip()]
    rolls = [r for r in recs if r.get("event") == "serve_rollover"]
    assert len(rolls) == 2
    assert rolls[0]["old_hash"] == h_old
    assert rolls[0]["new_hash"] == rep["new_hash"]
    assert rolls[1]["source"] == "checkpoint"

    # every successful response is attributable to EXACTLY one model
    # version: its trace_id appears in exactly one serve_access record,
    # carrying exactly one of the three version hashes
    acc = {}
    for r in recs:
        if r.get("event") == "serve_access" and not r.get("error"):
            assert r["trace_id"] not in acc, "duplicate access record"
            acc[r["trace_id"]] = r.get("model_version")
    valid_hashes = {h_old, rep["new_hash"], rep2["new_hash"]}
    for tid in outcomes:
        assert tid in acc, f"response {tid} has no access record"
        assert acc[tid] in valid_hashes, acc[tid]
    versions_seen = {acc[tid] for tid in outcomes}
    assert len(versions_seen) >= 2, "traffic never spanned the swap"
    print(f"rollover: {len(outcomes)} responses across 2 swaps, "
          f"0 dropped, versions {versions_seen}")


@pytest.mark.chaos
def test_slow_dispatch_fault_absorbed_by_shedding(monkeypatch, tmp_path):
    """Injected serve_slow_dispatch spike: deadline shedding absorbs it
    (bounded latency for later requests), nothing wedges, worker
    recovers to normal service."""
    from lightgbm_tpu.resilience import faults as faults_mod
    monkeypatch.setenv(faults_mod.FAULTS_ENV,
                       "serve_slow_dispatch@2:ms=600")
    faults_mod._CACHE.clear()
    bst = _train(seed=4)
    svc = PredictionService(
        {"m": bst}, max_batch_rows=32, max_delay_ms=0.5,
        min_bucket_rows=16, batch_events=False, serve_devices=1,
        default_deadline_ms=250.0,
        telemetry_out=str(tmp_path / "slow.jsonl"))
    svc.warmup()
    svc.predict("m", np.zeros((1, F), np.float32))    # batch 1: normal
    # batch 2 hits the 600ms sleep; requests submitted DURING the spike
    # queue behind it, age past their 250ms deadline and must be shed
    # at dequeue, not served stale
    trigger = svc.submit("m", np.zeros((1, F), np.float32))
    time.sleep(0.05)                   # batch 2 is now inside the sleep
    futs = [svc.submit("m", np.zeros((1, F), np.float32))
            for _ in range(6)]
    served = shed = 0
    for f in futs:
        try:
            f.result(timeout=30)
            served += 1
        except ServeDeadlineExceeded:
            shed += 1
    trigger.result(timeout=30)         # the spiked batch itself serves
    assert served + shed == 6
    assert shed > 0, "the spike must shed aged requests"
    # recovered: a fresh request serves promptly
    t0 = time.perf_counter()
    svc.predict("m", np.zeros((1, F), np.float32))
    assert time.perf_counter() - t0 < 5.0
    recs = [json.loads(ln) for ln in
            open(tmp_path / "slow.jsonl") if ln.strip()]
    assert any(r.get("event") == "fault_injected"
               and r.get("kind") == "serve_slow_dispatch" for r in recs)
    svc.close()


@pytest.mark.chaos
def test_fleet_rollover_atomic_one_version_per_device(tmp_path):
    """Multi-replica rollover under live fleet traffic: the all-replica
    swap is ONE critical section, so each device's response stream
    flips old->new at most once and never back — no mixed-version
    window on any lane.  Every successful response attributes to
    exactly one of the two hashes over the full serve_access JSONL,
    and every fleet access record carries its routed device."""
    import jax
    if len(jax.local_devices()) < 2:
        pytest.skip("needs >= 2 local devices "
                    "(tests/conftest.py forces 8 on CPU)")
    b_old = _train(seed=2, rounds=6)
    b_new = _train(seed=2, rounds=8, learning_rate=0.35)
    sink = str(tmp_path / "fleet_rollover.jsonl")
    svc = PredictionService(
        {"m": b_old}, max_batch_rows=64, max_delay_ms=0.5,
        min_bucket_rows=16, batch_events=False, telemetry_out=sink)
    svc.warmup()
    n_dev = svc.n_devices
    assert n_dev >= 2
    h_old = svc.residency.get("m").model_hash[:16]

    stop = threading.Event()
    failures, outcomes = [], []

    def traffic(seed):
        r = np.random.RandomState(seed)
        while not stop.is_set():
            Xq = r.rand(int(r.randint(1, 5)), F).astype(np.float32)
            try:
                fut = svc.submit("m", Xq)
                fut.result(timeout=60)
                outcomes.append(fut.trace_id)
            except Exception as e:
                failures.append(repr(e))
    threads = [threading.Thread(target=traffic, args=(31 + i,),
                                daemon=True) for i in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.3)
    # shadow-score the candidate on mirrored fleet traffic, then swap
    rep = svc.rollover("m", b_new, shadow_requests=5)
    assert rep["promoted"]
    assert rep["shadow"]["completed"]
    time.sleep(0.3)
    stop.set()
    for t in threads:
        t.join(timeout=30)
    svc.close(drain_timeout_s=30)

    assert failures == [], failures[:5]
    assert len(outcomes) > 50, "traffic generator barely ran"
    h_new = rep["new_hash"]

    recs = [json.loads(ln) for ln in open(sink) if ln.strip()]
    rolls = [r for r in recs if r.get("event") == "serve_rollover"]
    assert len(rolls) == 1
    assert rolls[0]["devices"] == n_dev     # the FULL replica set swapped

    acc, per_dev = {}, {}
    for r in recs:
        if r.get("event") == "serve_access" and not r.get("error"):
            assert r["trace_id"] not in acc, "duplicate access record"
            assert "device" in r, "fleet access record must carry device"
            acc[r["trace_id"]] = r.get("model_version")
            per_dev.setdefault(int(r["device"]), []).append(
                r.get("model_version"))
    for tid in outcomes:
        assert tid in acc, f"response {tid} has no access record"
        assert acc[tid] in {h_old, h_new}, acc[tid]
    assert len(per_dev) >= 2, "traffic reached only one device"
    # per-lane atomicity: batches dispatch serially on a lane and each
    # resolves against residency at dispatch time, so the per-device
    # version sequence (JSONL order = lane completion order) is
    # old...old, new...new — one transition, never a flap back
    for d, seq in sorted(per_dev.items()):
        flips = sum(1 for a, b in zip(seq, seq[1:]) if a != b)
        assert flips <= 1, f"device {d} mixed versions: {seq}"
        if flips == 1:
            assert seq[0] == h_old and seq[-1] == h_new, (d, seq)
