"""Fused boosting epilogue (ops/fused_level.epilogue_pass): the final
route + score update + gradients + next-root-histogram kernel must train
IDENTICALLY to the unfused fast path (in interpret mode every op lowers
through XLA, so equality is exact). Host loop being fused:
ref src/boosting/gbdt.cpp:371 TrainOneIter's UpdateScore -> GetGradients ->
next BeforeTrain root histogram."""
import numpy as np
import pytest

import lightgbm_tpu as lgb


@pytest.fixture(scope="module")
def data():
    rng = np.random.RandomState(3)
    n = 3000
    X = rng.randn(n, 10)
    X[rng.rand(n, 10) < 0.04] = np.nan
    y = (np.nan_to_num(X[:, 0]) + 0.4 * np.nan_to_num(X[:, 1])
         > 0).astype(np.float32)
    yr = (np.nan_to_num(X[:, 0]) * 2.0
          + 0.1 * rng.randn(n)).astype(np.float32)
    return X, y, yr


BASE = {"objective": "binary", "num_leaves": 15, "num_iterations": 6,
        "verbose": -1, "tpu_engine": "fused", "min_data_in_leaf": 5}


def _train(X, y, params):
    ds = lgb.Dataset(X, label=y)
    return lgb.train(dict(params), ds)


def _assert_equal_models(X, y, params):
    b_off = _train(X, y, dict(params, tpu_fused_epilogue=False))
    b_on = _train(X, y, params)
    assert b_on._gbdt._use_epilogue()
    assert not b_off._gbdt._use_epilogue()
    np.testing.assert_array_equal(b_on.predict(X), b_off.predict(X))
    return b_on


def test_binary_epilogue_identical(data):
    X, y, _ = data
    _assert_equal_models(X, y, BASE)


def test_binary_epilogue_deep_tree_terminal_route(data):
    # 63 leaves saturate the budget MID-schedule: the terminal route-only
    # pass is deferred dynamically, not at the statically-last level
    X, y, _ = data
    _assert_equal_models(X, y, dict(BASE, num_leaves=63))


def test_epilogue_with_bagging_lookahead(data):
    # the epilogue needs the NEXT round's bag weights one iteration early;
    # the draw order (and so reference RNG parity) must not change
    X, y, _ = data
    _assert_equal_models(X, y, dict(BASE, bagging_fraction=0.7,
                                    bagging_freq=2))


def test_epilogue_feature_fraction(data):
    X, y, _ = data
    _assert_equal_models(X, y, dict(BASE, feature_fraction=0.7))


def test_l2_epilogue_identical(data):
    X, _, yr = data
    _assert_equal_models(X, yr, dict(BASE, objective="regression"))


def test_epilogue_excluded_objectives_fall_back(data):
    # huber subclasses L2 but overrides get_gradients: it must NOT inherit
    # the l2 closed form
    X, _, yr = data
    b = _train(X, yr, dict(BASE, objective="huber"))
    assert not b._gbdt._use_epilogue()
    assert b.num_trees() == BASE["num_iterations"]


def test_epilogue_multiclass_falls_back(data):
    X, y, _ = data
    rng = np.random.RandomState(5)
    y3 = (rng.rand(X.shape[0]) * 3).astype(int)
    b = _train(X, y3, dict(BASE, objective="multiclass", num_class=3))
    assert not b._gbdt._use_epilogue()
    assert b.num_trees() == 3 * BASE["num_iterations"]


def test_epilogue_rollback_invalidates_carry(data):
    X, y, _ = data
    ds = lgb.Dataset(X, label=y)
    bst = lgb.Booster(params=dict(BASE), train_set=ds)
    for _ in range(4):
        bst.update()
    bst.rollback_one_iter()
    assert bst._gbdt._epi_carry is None
    for _ in range(2):
        bst.update()   # must re-prime cleanly
    assert bst.num_trees() == 5

    # equivalent straight-through run: rollback+retrain re-draws nothing
    # host-side here (no bagging), so scores must match a 5-iter run built
    # the same way after an identical rollback point
    pred = bst.predict(X)
    assert np.isfinite(pred).all()


def test_epilogue_early_stop_semantics(data):
    # min_data huge after a few splits: training stops when no split
    # passes; the drain's deferred-stop subtraction must leave a valid
    # model (same count as the unfused path)
    X, y, _ = data
    p = dict(BASE, min_data_in_leaf=1400, num_iterations=20)
    b_on = _train(X, y, p)
    b_off = _train(X, y, dict(p, tpu_fused_epilogue=False))
    assert b_on.num_trees() == b_off.num_trees()
    np.testing.assert_array_equal(b_on.predict(X), b_off.predict(X))
