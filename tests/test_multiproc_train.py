"""Joint multi-process training: N processes, per-rank row shards, ONE
model (VERDICT r3 missing #1 — the analog of the reference's
tests/distributed/_test_distributed.py:170-198, where N CLI processes
train jointly with tree_learner=data and the test asserts the accuracy
of the SHARED model).

Two processes x 4 virtual CPU devices each form one global 8-device
mesh (jax.distributed + gloo); each rank loads its disjoint file shard
(identical bin mappers via the loader's allgather), trains through the
product `lgb.train(tree_learner=data)` driver, and must emit the
BIT-IDENTICAL model string — plus accuracy comparable to a single-
process model on the full data."""
import json
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

_WORKER = textwrap.dedent("""
    import json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=sys.argv[1],
        num_processes=int(sys.argv[2]), process_id=int(sys.argv[3]))
    assert jax.device_count() == 4 * int(sys.argv[2])
    import numpy as np
    import lightgbm_tpu as lgb

    path, test_path, out_path = sys.argv[4], sys.argv[5], sys.argv[6]
    params = json.loads(sys.argv[7])
    test_mode = params.pop("__test_mode", None)
    rounds = params.pop("num_iterations", None) or 10
    # __evict: one opaque user callback — the DOCUMENTED megastep
    # eviction that keeps the serialized parameter block byte-identical
    # (same pairing as tests/test_traced_eval._train_pair)
    evict = params.pop("__evict", False)
    # __tel: telemetry to a cwd-RELATIVE path (the launcher gives every
    # rank its own cwd, so the serialized telemetry_out strings — and
    # hence the model strings — stay byte-comparable across ranks)
    tel = params.pop("__tel", None)
    if tel:
        params["telemetry_out"] = tel
    ds = lgb.Dataset(path, params={"label_column": 0, "verbose": -1,
                                   "max_bin": 63})
    valid_path = params.pop("__valid", None)
    es_rounds = params.pop("__early_stopping", None)
    if test_mode == "custom":
        # rank-local custom gradients: fobj sees THIS rank's rows only
        # (the reference's distributed custom-objective contract)
        def fobj(preds, dtrain):
            y = np.asarray(dtrain.label, np.float64)
            p = 1.0 / (1.0 + np.exp(-np.asarray(preds, np.float64)))
            return p - y, p * (1.0 - p)
        params = dict(params, objective="none")
        bst = lgb.Booster(params=params, train_set=ds)
        for _ in range(rounds):
            bst.update(fobj=fobj)
    else:
        kw = {}
        if valid_path is not None:
            # IDENTICAL valid set on every rank (pre_partition keeps the
            # whole file): host-side valid eval stays SPMD-consistent
            vds = lgb.Dataset(valid_path,
                              params={"label_column": 0, "verbose": -1,
                                      "pre_partition": True},
                              reference=ds)
            kw["valid_sets"] = [vds]
        if es_rounds:
            params = dict(params, early_stopping_round=es_rounds)
        cbs = [(lambda env: None)] if evict else []
        bst = lgb.train(dict(params, num_iterations=rounds), ds,
                        callbacks=cbs, **kw)
        if test_mode == "rollback":
            bst.rollback_one_iter()
    g = bst._gbdt
    test = np.loadtxt(test_path, delimiter=",")
    pred = bst.predict(test[:, 1:])
    evals = [(d, nm, float(v)) for (d, nm, v, _)
             in (g.eval_metrics() if g.training_metrics else [])]
    dpi = None
    megasteps = 0
    evictions = []
    health_checks = []
    if tel:
        c = bst.telemetry().get("counters", {})
        iters = max(1, int(c.get("iterations", rounds)))
        dpi = float(c.get("train.dispatches", 0)) / iters
        rank = jax.process_index()
        tel_file = tel if rank == 0 else tel + ".rank%d" % rank
        for line in open(tel_file):
            r = json.loads(line)
            if r.get("event") == "megastep":
                megasteps += 1
            elif r.get("event") == "megastep_evicted":
                evictions.append(r.get("feature"))
            elif r.get("event") == "health_check":
                health_checks.append((r.get("iter"), r.get("ok")))
    report = {
        "rank": jax.process_index(),
        "evals": evals,
        "num_local_rows": int(ds._inner.num_data),
        "parallel_mode": g.parallel_mode,
        "use_fused": bool(getattr(g, "use_fused", False)),
        "fast_path": bool(g._fast_path_ok()),
        "mp_active": g.mp is not None,
        "total_real": int(g.mp.total_real) if g.mp is not None else -1,
        "num_trees": bst.num_trees(),
        "best_iteration": bst.best_iteration,
        "dispatches_per_iter": dpi,
        "megastep_batches": megasteps,
        "evictions": evictions,
        "health_checks": health_checks,
        "model": bst.model_to_string(),
        "pred": [float(v) for v in pred],
    }
    with open(out_path, "w") as fh:
        json.dump(report, fh)
""")


def _launch(tmp_path, train, test_file, params, nproc=2):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    outs = [tmp_path / f"rank{i}.json" for i in range(nproc)]
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # ONLY the repo on the path: the axon TPU plugin breaks multiprocess
    # CPU backends (process_count stays 1)
    env["PYTHONPATH"] = repo_root
    env.pop("XLA_FLAGS", None)
    # per-rank working directories: cwd-relative telemetry paths stay
    # byte-identical in the serialized params while each rank writes its
    # own file (a shared path would race)
    cwds = []
    for i in range(nproc):
        d = tmp_path / f"rank{i}_cwd"
        d.mkdir(exist_ok=True)
        cwds.append(str(d))
    procs = [subprocess.Popen(
        [sys.executable, str(script), coord, str(nproc), str(i),
         str(train), str(test_file), str(outs[i]), json.dumps(params)],
        env=env, cwd=cwds[i], stdout=subprocess.PIPE,
        stderr=subprocess.PIPE)
        for i in range(nproc)]
    for p in procs:
        out, err = p.communicate(timeout=1200)
        assert p.returncode == 0, err.decode()[-3000:]
    return [json.loads(o.read_text()) for o in outs]


def _auc(y, s):
    order = np.argsort(s)
    r = np.empty(len(y))
    r[order] = np.arange(1, len(y) + 1)
    pos = y > 0
    n1, n0 = pos.sum(), (~pos).sum()
    return (r[pos].sum() - n1 * (n1 + 1) / 2) / (n1 * n0)


def test_two_process_joint_training(tmp_path):
    rng = np.random.RandomState(11)
    n, F = 4000, 8
    X = rng.rand(n + 1000, F)
    margin = (X[:, 0] + 2.0 * X[:, 1] * X[:, 2] - 1.5 * X[:, 3]
              + 0.5 * rng.randn(len(X)))
    y = (margin > np.median(margin)).astype(np.float64)
    # SKEWED shards: sorted rows make rank-local training diverge hard
    order = np.argsort(X[:n, 0])
    Xtr, ytr = X[:n][order], y[:n][order]
    Xte, yte = X[n:], y[n:]
    train = tmp_path / "train.csv"
    test_f = tmp_path / "test.csv"
    np.savetxt(train, np.column_stack([ytr, Xtr]), delimiter=",",
               fmt="%.6f")
    np.savetxt(test_f, np.column_stack([yte, Xte]), delimiter=",",
               fmt="%.6f")

    params = {"objective": "binary", "num_leaves": 15,
              "num_iterations": 10, "learning_rate": 0.2,
              "tree_learner": "data", "verbose": -1}
    reports = _launch(tmp_path, train, test_f, params)

    # the mesh actually spanned both processes and sharded the file
    assert all(r["mp_active"] for r in reports)
    assert all(r["parallel_mode"] == "data" for r in reports)
    assert (reports[0]["num_local_rows"] + reports[1]["num_local_rows"]
            == n)
    assert reports[0]["num_local_rows"] not in (0, n)
    assert all(r["total_real"] == n for r in reports)
    assert reports[0]["num_trees"] == 10

    # THE joint-training claim: every rank emits the identical model
    assert reports[0]["model"] == reports[1]["model"]
    assert np.allclose(reports[0]["pred"], reports[1]["pred"])

    # reference-comparable accuracy: a single-process model on the FULL
    # data must not beat the joint model by more than float-level drift
    import lightgbm_tpu as lgb
    ds = lgb.Dataset(np.ascontiguousarray(Xtr), label=ytr,
                     params={"max_bin": 63, "verbose": -1})
    bst = lgb.train({k: v for k, v in params.items()
                     if k != "tree_learner"}, ds)
    auc_serial = _auc(yte, bst.predict(Xte))
    auc_mp = _auc(yte, np.asarray(reports[0]["pred"]))
    assert auc_mp > 0.75, auc_mp
    assert auc_serial - auc_mp < 0.01, (auc_serial, auc_mp)

    # vacuity check: one rank's shard alone trains a DIFFERENT model
    half = reports[0]["num_local_rows"]
    ds_half = lgb.Dataset(np.ascontiguousarray(Xtr[:half]),
                          label=ytr[:half],
                          params={"max_bin": 63, "verbose": -1})
    bst_half = lgb.train({k: v for k, v in params.items()
                          if k != "tree_learner"}, ds_half)
    assert bst_half.model_to_string() != reports[0]["model"]


def _regression_files(tmp_path, n=3000, F=6, seed=23):
    rng = np.random.RandomState(seed)
    X = rng.rand(n + 800, F)
    y = X[:, 0] + 0.5 * X[:, 1] * X[:, 2] + 0.1 * rng.randn(len(X))
    train = tmp_path / "train.csv"
    test_f = tmp_path / "test.csv"
    np.savetxt(train, np.column_stack([y[:n], X[:n]]), delimiter=",",
               fmt="%.6f")
    np.savetxt(test_f, np.column_stack([y[n:], X[n:]]), delimiter=",",
               fmt="%.6f")
    return train, test_f, X, y, n


@pytest.mark.parametrize("case", [
    # (a) leaf-renewing objective: rank-local percentiles averaged over
    # contributing workers (serial_tree_learner.cpp:744-755 semantics)
    {"objective": "regression_l1", "metric": "l1"},
    # quantile renews too and exercises the weighted path
    {"objective": "quantile", "alpha": 0.7},
    # (c) GOSS: rank-local resampling (goss.hpp:103)
    {"objective": "regression", "boosting": "goss",
     "learning_rate": 0.5, "top_rate": 0.3, "other_rate": 0.3},
    # (c) DART: synced drop-seed stream, sharded score replay
    {"objective": "regression", "boosting": "dart", "drop_rate": 0.3,
     "drop_seed": 7},
    # (c) RF: bagging streams synced, averaged output
    {"objective": "regression", "boosting": "rf",
     "bagging_freq": 1, "bagging_fraction": 0.7,
     "feature_fraction": 0.9},
])
def test_two_process_feature_matrix(tmp_path, case):
    """VERDICT r4 missing #3: the multi-process feature matrix — renew
    objectives, GOSS, DART, RF train jointly: both ranks emit the
    bit-identical model with accuracy comparable to the single-process
    run."""
    train, test_f, X, y, n = _regression_files(tmp_path)
    params = dict({"num_leaves": 15, "num_iterations": 8,
                   "learning_rate": 0.2, "tree_learner": "data",
                   "verbose": -1}, **case)
    reports = _launch(tmp_path, train, test_f, params)
    assert all(r["mp_active"] for r in reports)
    assert reports[0]["model"] == reports[1]["model"]
    assert np.allclose(reports[0]["pred"], reports[1]["pred"])

    import lightgbm_tpu as lgb
    ds = lgb.Dataset(np.ascontiguousarray(X[:n]), label=y[:n],
                     params={"max_bin": 63, "verbose": -1})
    serial = lgb.train({k: v for k, v in params.items()
                        if k != "tree_learner"}, ds)
    mse_mp = float(np.mean((np.asarray(reports[0]["pred"])
                            - y[n:]) ** 2))
    mse_s = float(np.mean((serial.predict(X[n:]) - y[n:]) ** 2))
    base = float(np.var(y[n:]))
    assert mse_mp < 0.5 * base, (mse_mp, base)
    assert mse_mp < mse_s * 1.5 + 1e-3, (mse_mp, mse_s)


def test_two_process_custom_gradients_and_rollback(tmp_path):
    """(d) custom gradients are rank-local (fobj sees this rank's rows);
    (e) rollback replays on the row-sharded matrix."""
    rng = np.random.RandomState(31)
    n, F = 3000, 6
    X = rng.rand(n + 500, F)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float64)
    train = tmp_path / "train.csv"
    test_f = tmp_path / "test.csv"
    np.savetxt(train, np.column_stack([y[:n], X[:n]]), delimiter=",",
               fmt="%.6f")
    np.savetxt(test_f, np.column_stack([y[n:], X[n:]]), delimiter=",",
               fmt="%.6f")
    base = {"num_leaves": 15, "num_iterations": 6, "learning_rate": 0.2,
            "tree_learner": "data", "verbose": -1}
    # custom binary-logloss gradients reproduce the built-in objective's
    # joint model to float drift
    rep_c = _launch(tmp_path, train, test_f,
                    dict(base, __test_mode="custom"))
    assert rep_c[0]["model"] == rep_c[1]["model"]
    assert rep_c[0]["num_trees"] == 6
    auc_c = _auc(y[n:], np.asarray(rep_c[0]["pred"]))
    assert auc_c > 0.85, auc_c
    # rollback: one fewer tree, ranks agree
    rep_r = _launch(tmp_path, train, test_f,
                    dict(base, objective="binary",
                         __test_mode="rollback"))
    assert rep_r[0]["model"] == rep_r[1]["model"]
    assert rep_r[0]["num_trees"] == 5
    auc_r = _auc(y[n:], np.asarray(rep_r[0]["pred"]))
    assert auc_r > 0.85, auc_r


def test_two_process_ranking(tmp_path):
    """(b) ranking: the loader's rank slices align to query boundaries,
    global query structure rides GlobalMetadata.query_row_map, and both
    ranks emit the identical lambdarank model."""
    rng = np.random.RandomState(41)
    n_q, docs = 120, 10
    n = n_q * docs
    X = rng.rand(n, 5)
    rel = (X[:, 0] * 2 + rng.rand(n)).astype(np.float64)
    y = np.digitize(rel, np.percentile(rel, [50, 75, 90])).astype(float)
    train = tmp_path / "train.csv"
    np.savetxt(train, np.column_stack([y, X]), delimiter=",", fmt="%.6f")
    # variable query sizes so the query-aligned cut is non-trivial
    sizes = rng.randint(5, 16, size=200)
    sizes = sizes[np.cumsum(sizes) <= n]
    rem = n - sizes.sum()
    if rem > 0:
        sizes = np.append(sizes, rem)
    np.savetxt(str(train) + ".query", sizes, fmt="%d")
    test_f = tmp_path / "test.csv"
    np.savetxt(test_f, np.column_stack([y[:500], X[:500]]),
               delimiter=",", fmt="%.6f")
    params = {"objective": "lambdarank", "num_leaves": 15,
              "num_iterations": 8, "learning_rate": 0.1,
              "tree_learner": "data", "metric": "ndcg",
              "is_provide_training_metric": True,
              "label_gain": ",".join(
                  str(2 ** i - 1) for i in range(32)), "verbose": -1}
    reports = _launch(tmp_path, train, test_f, params)
    assert all(r["mp_active"] for r in reports)
    assert reports[0]["model"] == reports[1]["model"]
    assert reports[0]["num_trees"] == 8
    # distributed NDCG: both ranks agree on the global training metric
    # and it is non-trivial (rank-local sums + allreduce)
    ev0 = {nm: v for d, nm, v in reports[0]["evals"] if d == "training"}
    ev1 = {nm: v for d, nm, v in reports[1]["evals"] if d == "training"}
    assert any(nm.startswith("ndcg") for nm in ev0), ev0
    for nm in ev0:
        assert abs(ev0[nm] - ev1[nm]) < 1e-9
        assert 0.5 < ev0[nm] <= 1.0, (nm, ev0[nm])
    # the joint model ranks: higher-label docs score higher on average
    pred = np.asarray(reports[0]["pred"])
    hi = pred[y[:500] >= 2].mean()
    lo = pred[y[:500] == 0].mean()
    assert hi > lo + 0.1, (hi, lo)


def test_two_process_fused_engine(tmp_path):
    """The pod path runs the FLAGSHIP kernel (VERDICT r4 missing #2 /
    weak #3): 2 processes x 4 virtual devices, tree_learner=data with
    tpu_engine=fused — the fused per-level psum spans the global gloo
    mesh (interpret mode on CPU), both ranks emit the bit-identical
    model, and the result matches the XLA growers' joint model to float
    drift."""
    rng = np.random.RandomState(17)
    n, F = 3000, 6
    X = rng.rand(n + 800, F)
    y = (X[:, 0] + X[:, 1] * 1.5 > 1.0).astype(np.float64)
    train = tmp_path / "train.csv"
    test_f = tmp_path / "test.csv"
    np.savetxt(train, np.column_stack([y[:n], X[:n]]), delimiter=",",
               fmt="%.6f")
    np.savetxt(test_f, np.column_stack([y[n:], X[n:]]), delimiter=",",
               fmt="%.6f")
    params = {"objective": "binary", "num_leaves": 15,
              "num_iterations": 5, "learning_rate": 0.2,
              "tree_learner": "data", "tpu_engine": "fused",
              "verbose": -1}
    reports = _launch(tmp_path, train, test_f, params)
    assert all(r["mp_active"] for r in reports)
    assert all(r["use_fused"] for r in reports), \
        "multi-process run fell off the fused engine"
    assert reports[0]["model"] == reports[1]["model"]
    assert reports[0]["num_trees"] == 5
    # consistency with the XLA growers on the same shards
    xla_reports = _launch(tmp_path, train, test_f,
                          dict(params, tpu_engine="xla"))
    auc_fused = _auc(y[n:], np.asarray(reports[0]["pred"]))
    auc_xla = _auc(y[n:], np.asarray(xla_reports[0]["pred"]))
    assert auc_fused > 0.8, auc_fused
    assert abs(auc_fused - auc_xla) < 0.02, (auc_fused, auc_xla)


def test_train_distributed_launcher(tmp_path):
    """The orchestration analog of the reference's dask.py _train: the
    launcher spawns the worker fleet, each rank loads its shard, ONE
    model comes back (rank 0's), and it matches a manual single-process
    model on the full data to reference-comparable accuracy."""
    from lightgbm_tpu.parallel import train_distributed
    rng = np.random.RandomState(21)
    n, F = 3000, 6
    X = rng.rand(n + 800, F)
    y = ((X[:, 0] + X[:, 1] * X[:, 2] > 0.9)
         ^ (rng.rand(len(X)) < 0.05)).astype(np.float64)
    train = tmp_path / "train.csv"
    np.savetxt(train, np.column_stack([y[:n], X[:n]]), delimiter=",",
               fmt="%.6f")

    params = {"objective": "binary", "num_leaves": 15,
              "learning_rate": 0.2, "verbose": -1}
    bst = train_distributed(params, str(train), num_processes=2,
                            num_boost_round=8, devices_per_process=2,
                            dataset_params={"label_column": 0,
                                            "verbose": -1},
                            timeout=600)
    auc_mp = _auc(y[n:], bst.predict(X[n:]))

    import lightgbm_tpu as lgb
    ds = lgb.Dataset(np.ascontiguousarray(X[:n]), label=y[:n],
                     params={"verbose": -1})
    serial = lgb.train(dict(params, num_iterations=8), ds)
    auc_s = _auc(y[n:], serial.predict(X[n:]))
    assert auc_mp > 0.75, auc_mp
    assert auc_s - auc_mp < 0.02, (auc_s, auc_mp)


def test_two_process_efb(tmp_path):
    """Dense EFB composes with multi-process training: the bundle layout
    is derived from the ALLGATHERED binning sample (identical on every
    rank, like the reference's sampled FindGroups), local rows encode
    with the shared layout, and both ranks emit the identical model."""
    rng = np.random.RandomState(53)
    n, F = 3000, 12
    # near-exclusive block: bundling engages
    X = np.zeros((n + 600, F))
    X[:, 0] = rng.rand(n + 600)
    owner = rng.randint(2, F, n + 600)
    X[np.arange(n + 600), owner] = rng.rand(n + 600) + 0.5
    y = (X[:, 0] + X[:, 2] > 0.8).astype(np.float64)
    train = tmp_path / "train.csv"
    test_f = tmp_path / "test.csv"
    np.savetxt(train, np.column_stack([y[:n], X[:n]]), delimiter=",",
               fmt="%.6f")
    np.savetxt(test_f, np.column_stack([y[n:], X[n:]]), delimiter=",",
               fmt="%.6f")
    params = {"objective": "binary", "num_leaves": 15,
              "num_iterations": 6, "learning_rate": 0.2,
              "tree_learner": "data", "enable_bundle": True,
              "tpu_enable_bundle": True, "verbose": -1}
    reports = _launch(tmp_path, train, test_f, params)
    assert all(r["mp_active"] for r in reports)
    assert reports[0]["model"] == reports[1]["model"]
    auc = _auc(y[n:], np.asarray(reports[0]["pred"]))
    assert auc > 0.85, auc


def _megastep_files(tmp_path, n=2000, F=6, seed=7):
    rng = np.random.RandomState(seed)
    X = rng.rand(n + 500, F)
    y = (X[:, 0] + X[:, 1] * 1.5 > 1.0).astype(np.float64)
    train = tmp_path / "train.csv"
    valid = tmp_path / "valid.csv"
    np.savetxt(train, np.column_stack([y[:n], X[:n]]), delimiter=",",
               fmt="%.6f")
    np.savetxt(valid, np.column_stack([y[n:], X[n:]]), delimiter=",",
               fmt="%.6f")
    return train, valid


def _megastep_params(valid, tree_learner="data", **extra):
    """The ISSUE 12 acceptance config: fused megastep, bagging +
    feature_fraction + early stopping + a valid set, multi-process."""
    p = {"objective": "binary", "num_leaves": 15, "num_iterations": 20,
         "learning_rate": 0.2, "tree_learner": tree_learner,
         "tpu_engine": "fused", "tpu_megastep": True, "verbose": -1,
         "bagging_fraction": 0.8, "bagging_freq": 2,
         "feature_fraction": 0.8, "metric": "binary_logloss",
         # training metric: its traced reduction runs over the ROW-
         # SHARDED score carry inside the scan (GSPMD finishes the sum
         # across chips), the strongest sharded-eval composition
         "is_provide_training_metric": True,
         "__valid": str(valid), "__early_stopping": 3,
         "__tel": "tel.jsonl"}
    p.update(extra)
    return p


@pytest.mark.slow
@pytest.mark.parametrize("learner", ["data", "voting"])
def test_two_process_megastep_bit_identity(tmp_path, learner):
    """ISSUE 12 acceptance: the 2-process multi-chip megastep (shard_map
    growers inside the scan, in-trace collectives, on-device eval +
    scan-native early stop) serializes BYTE-EQUAL to the per-iteration
    driver — the same documented pairing every fast-path PR has held
    (an opaque user callback evicts the megastep while keeping the
    serialized parameter block identical), under bagging +
    feature_fraction + early stopping, for data AND voting modes."""
    train, valid = _megastep_files(tmp_path)
    extra = {"top_k": 3} if learner == "voting" else {}
    params = _megastep_params(valid, tree_learner=learner, **extra)
    mega = _launch(tmp_path, train, valid, params)
    evicted = _launch(tmp_path, train, valid, dict(params, __evict=True))

    for r in mega + evicted:
        assert r["mp_active"] and r["use_fused"] and r["fast_path"]
        assert r["parallel_mode"] == learner
    # the megastep actually engaged and amortized dispatches (one
    # dispatch per bagging-bounded chunk, NOT >=3 per iteration)
    assert mega[0]["megastep_batches"] >= 1, mega[0]
    assert mega[0]["dispatches_per_iter"] < 1.0, mega[0]
    # SPMD: every rank emits the identical model in both runs
    assert mega[0]["model"] == mega[1]["model"]
    assert evicted[0]["model"] == evicted[1]["model"]
    # THE contract: fused chunk == per-iteration trajectory, byte-equal,
    # including where early stopping latched
    assert mega[0]["best_iteration"] == evicted[0]["best_iteration"]
    assert mega[0]["model"] == evicted[0]["model"]
    assert np.allclose(mega[0]["pred"], evicted[0]["pred"])
    # final host-side training metrics agree across ranks and runs
    # (byte-equal models => identical evals)
    assert mega[0]["evals"] == mega[1]["evals"] == evicted[0]["evals"]
    assert mega[0]["evals"], "training metric did not evaluate"


@pytest.mark.slow
def test_two_process_megastep_health_audit_at_drain(tmp_path):
    """Tentpole (d): under the multi-chip megastep the HealthAuditor
    moves to drain boundaries instead of evicting to the sync driver
    (its hash allgather pairs with the drain's host sync, costing zero
    extra dispatches). health_check_period=2 with one 8-iteration chunk
    -> the run stays on the fast path and exactly ONE audit fires at
    the drain (iteration 7), healthy on both ranks."""
    train, valid = _megastep_files(tmp_path, n=1500)
    params = {"objective": "binary", "num_leaves": 15,
              "num_iterations": 8, "learning_rate": 0.2,
              "tree_learner": "data", "tpu_engine": "fused",
              "tpu_megastep": True, "verbose": -1,
              "health_check_period": 2, "__tel": "tel.jsonl"}
    reports = _launch(tmp_path, train, valid, params)
    for r in reports:
        assert r["mp_active"] and r["use_fused"] and r["fast_path"]
        assert r["megastep_batches"] >= 1
        assert r["dispatches_per_iter"] < 1.0, r
        # one drain-boundary audit, healthy, identical on both ranks
        assert r["health_checks"] == [[7, True]], r["health_checks"]
    assert reports[0]["model"] == reports[1]["model"]


@pytest.mark.slow
def test_two_process_mp_megastep_off_evicts_to_sync_driver(tmp_path):
    """The A/B switch: tpu_mp_megastep=false restores the pre-round-12
    sync eviction — a structured `megastep_evicted` event names the
    config key, the run pays per-iteration dispatches, and the model
    matches the megastep run's tree structure with float-level score
    drift only (the documented f32-vs-f64 shrinkage rounding between
    the in-jit and host score updates, test_fast_pipeline contract)."""
    train, valid = _megastep_files(tmp_path)
    # 8 iterations: long enough for several bagging-bounded chunks,
    # short enough that the ulp-level score drift between the two
    # drivers cannot flip a split choice (structure equality holds)
    params = _megastep_params(valid, num_iterations=8)
    mega = _launch(tmp_path, train, valid, params)
    sync = _launch(tmp_path, train, valid,
                   dict(params, tpu_mp_megastep=False))
    assert not sync[0]["fast_path"]
    assert "config:tpu_mp_megastep=false" in sync[0]["evictions"], \
        sync[0]["evictions"]
    assert sync[0]["megastep_batches"] == 0
    # per-iteration sync driver: gradients + grow + score update + valid
    assert sync[0]["dispatches_per_iter"] >= 3.0, sync[0]
    assert mega[0]["dispatches_per_iter"] < 1.0, mega[0]
    # both drivers run the SAME shard_map grower: identical tree
    # structure, score trajectories differ only by shrinkage rounding
    assert sync[0]["model"] == sync[1]["model"]
    import re
    counts_m = re.findall(r"leaf_count=([\d ]+)", mega[0]["model"])
    counts_s = re.findall(r"leaf_count=([\d ]+)", sync[0]["model"])
    assert counts_m == counts_s and len(counts_m) > 0
    assert np.abs(np.asarray(mega[0]["pred"])
                  - np.asarray(sync[0]["pred"])).max() < 1e-4


def test_two_process_valid_early_stop_weights_large_leaves(tmp_path):
    """VERDICT r4 weak #4: multi-process with a larger leaf count, a
    real valid set, early stopping, and row weights — both ranks agree
    bit-for-bit and early stopping fires identically."""
    rng = np.random.RandomState(61)
    n, F = 6000, 8
    X = rng.rand(n + 1500, F)
    y = (X[:, 0] + 0.8 * X[:, 1] * X[:, 2] > 0.9).astype(np.float64)
    w = (rng.rand(n) + 0.5)
    train = tmp_path / "train.csv"
    np.savetxt(train, np.column_stack([y[:n], X[:n]]), delimiter=",",
               fmt="%.6f")
    np.savetxt(str(train) + ".weight", w, fmt="%.6f")
    valid = tmp_path / "valid.csv"
    np.savetxt(valid, np.column_stack([y[n:], X[n:]]), delimiter=",",
               fmt="%.6f")
    test_f = valid
    params = {"objective": "binary", "num_leaves": 63,
              "num_iterations": 30, "learning_rate": 0.3,
              "tree_learner": "data", "metric": "binary_logloss",
              "verbose": -1, "__valid": str(valid),
              "__early_stopping": 3}
    reports = _launch(tmp_path, train, test_f, params)
    assert all(r["mp_active"] for r in reports)
    assert reports[0]["model"] == reports[1]["model"]
    assert reports[0]["num_trees"] == reports[1]["num_trees"]
    auc = _auc(y[n:], np.asarray(reports[0]["pred"]))
    assert auc > 0.85, auc
