"""Joint multi-process training: N processes, per-rank row shards, ONE
model (VERDICT r3 missing #1 — the analog of the reference's
tests/distributed/_test_distributed.py:170-198, where N CLI processes
train jointly with tree_learner=data and the test asserts the accuracy
of the SHARED model).

Two processes x 4 virtual CPU devices each form one global 8-device
mesh (jax.distributed + gloo); each rank loads its disjoint file shard
(identical bin mappers via the loader's allgather), trains through the
product `lgb.train(tree_learner=data)` driver, and must emit the
BIT-IDENTICAL model string — plus accuracy comparable to a single-
process model on the full data."""
import json
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

_WORKER = textwrap.dedent("""
    import json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=sys.argv[1],
        num_processes=int(sys.argv[2]), process_id=int(sys.argv[3]))
    assert jax.device_count() == 4 * int(sys.argv[2])
    import numpy as np
    import lightgbm_tpu as lgb

    path, test_path, out_path = sys.argv[4], sys.argv[5], sys.argv[6]
    params = json.loads(sys.argv[7])
    ds = lgb.Dataset(path, params={"label_column": 0, "verbose": -1,
                                   "max_bin": 63})
    bst = lgb.train(params, ds)
    g = bst._gbdt
    test = np.loadtxt(test_path, delimiter=",")
    pred = bst.predict(test[:, 1:])
    report = {
        "rank": jax.process_index(),
        "num_local_rows": int(ds._inner.num_data),
        "parallel_mode": g.parallel_mode,
        "mp_active": g.mp is not None,
        "total_real": int(g.mp.total_real) if g.mp is not None else -1,
        "num_trees": len(g.models),
        "model": bst.model_to_string(),
        "pred": [float(v) for v in pred],
    }
    with open(out_path, "w") as fh:
        json.dump(report, fh)
""")


def _launch(tmp_path, train, test_file, params, nproc=2):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    outs = [tmp_path / f"rank{i}.json" for i in range(nproc)]
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # ONLY the repo on the path: the axon TPU plugin breaks multiprocess
    # CPU backends (process_count stays 1)
    env["PYTHONPATH"] = repo_root
    env.pop("XLA_FLAGS", None)
    procs = [subprocess.Popen(
        [sys.executable, str(script), coord, str(nproc), str(i),
         str(train), str(test_file), str(outs[i]), json.dumps(params)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        for i in range(nproc)]
    for p in procs:
        out, err = p.communicate(timeout=600)
        assert p.returncode == 0, err.decode()[-3000:]
    return [json.loads(o.read_text()) for o in outs]


def _auc(y, s):
    order = np.argsort(s)
    r = np.empty(len(y))
    r[order] = np.arange(1, len(y) + 1)
    pos = y > 0
    n1, n0 = pos.sum(), (~pos).sum()
    return (r[pos].sum() - n1 * (n1 + 1) / 2) / (n1 * n0)


def test_two_process_joint_training(tmp_path):
    rng = np.random.RandomState(11)
    n, F = 4000, 8
    X = rng.rand(n + 1000, F)
    margin = (X[:, 0] + 2.0 * X[:, 1] * X[:, 2] - 1.5 * X[:, 3]
              + 0.5 * rng.randn(len(X)))
    y = (margin > np.median(margin)).astype(np.float64)
    # SKEWED shards: sorted rows make rank-local training diverge hard
    order = np.argsort(X[:n, 0])
    Xtr, ytr = X[:n][order], y[:n][order]
    Xte, yte = X[n:], y[n:]
    train = tmp_path / "train.csv"
    test_f = tmp_path / "test.csv"
    np.savetxt(train, np.column_stack([ytr, Xtr]), delimiter=",",
               fmt="%.6f")
    np.savetxt(test_f, np.column_stack([yte, Xte]), delimiter=",",
               fmt="%.6f")

    params = {"objective": "binary", "num_leaves": 15,
              "num_iterations": 10, "learning_rate": 0.2,
              "tree_learner": "data", "verbose": -1}
    reports = _launch(tmp_path, train, test_f, params)

    # the mesh actually spanned both processes and sharded the file
    assert all(r["mp_active"] for r in reports)
    assert all(r["parallel_mode"] == "data" for r in reports)
    assert (reports[0]["num_local_rows"] + reports[1]["num_local_rows"]
            == n)
    assert reports[0]["num_local_rows"] not in (0, n)
    assert all(r["total_real"] == n for r in reports)
    assert reports[0]["num_trees"] == 10

    # THE joint-training claim: every rank emits the identical model
    assert reports[0]["model"] == reports[1]["model"]
    assert np.allclose(reports[0]["pred"], reports[1]["pred"])

    # reference-comparable accuracy: a single-process model on the FULL
    # data must not beat the joint model by more than float-level drift
    import lightgbm_tpu as lgb
    ds = lgb.Dataset(np.ascontiguousarray(Xtr), label=ytr,
                     params={"max_bin": 63, "verbose": -1})
    bst = lgb.train({k: v for k, v in params.items()
                     if k != "tree_learner"}, ds)
    auc_serial = _auc(yte, bst.predict(Xte))
    auc_mp = _auc(yte, np.asarray(reports[0]["pred"]))
    assert auc_mp > 0.75, auc_mp
    assert auc_serial - auc_mp < 0.01, (auc_serial, auc_mp)

    # vacuity check: one rank's shard alone trains a DIFFERENT model
    half = reports[0]["num_local_rows"]
    ds_half = lgb.Dataset(np.ascontiguousarray(Xtr[:half]),
                          label=ytr[:half],
                          params={"max_bin": 63, "verbose": -1})
    bst_half = lgb.train({k: v for k, v in params.items()
                          if k != "tree_learner"}, ds_half)
    assert bst_half.model_to_string() != reports[0]["model"]


def test_train_distributed_launcher(tmp_path):
    """The orchestration analog of the reference's dask.py _train: the
    launcher spawns the worker fleet, each rank loads its shard, ONE
    model comes back (rank 0's), and it matches a manual single-process
    model on the full data to reference-comparable accuracy."""
    from lightgbm_tpu.parallel import train_distributed
    rng = np.random.RandomState(21)
    n, F = 3000, 6
    X = rng.rand(n + 800, F)
    y = ((X[:, 0] + X[:, 1] * X[:, 2] > 0.9)
         ^ (rng.rand(len(X)) < 0.05)).astype(np.float64)
    train = tmp_path / "train.csv"
    np.savetxt(train, np.column_stack([y[:n], X[:n]]), delimiter=",",
               fmt="%.6f")

    params = {"objective": "binary", "num_leaves": 15,
              "learning_rate": 0.2, "verbose": -1}
    bst = train_distributed(params, str(train), num_processes=2,
                            num_boost_round=8, devices_per_process=2,
                            dataset_params={"label_column": 0,
                                            "verbose": -1},
                            timeout=600)
    auc_mp = _auc(y[n:], bst.predict(X[n:]))

    import lightgbm_tpu as lgb
    ds = lgb.Dataset(np.ascontiguousarray(X[:n]), label=y[:n],
                     params={"verbose": -1})
    serial = lgb.train(dict(params, num_iterations=8), ds)
    auc_s = _auc(y[n:], serial.predict(X[n:]))
    assert auc_mp > 0.75, auc_mp
    assert auc_s - auc_mp < 0.02, (auc_s, auc_mp)
