"""Logging subsystem (utils/log.py — analog of the reference logger,
ref: include/LightGBM/utils/log.h:71-170 + python register_logger)."""
import pytest

from lightgbm_tpu.utils import log


@pytest.fixture(autouse=True)
def _restore_log_state():
    """Pin a known level (earlier tests' verbose=-1 params lower the
    module-global threshold) and leave the logger state as found."""
    level = log.get_log_level()
    log.set_log_level(log.LogLevel.INFO)
    yield
    log.register_logger(None)
    log.set_log_level(level)


def test_register_logger_none_restores_stderr(capsys):
    lines = []
    log.register_logger(lines.append)
    log.info("captured %d", 1)
    assert lines and "captured 1" in lines[0]
    log.register_logger(None)
    log.info("back to stderr")
    captured = capsys.readouterr()
    assert "back to stderr" in captured.err
    assert len(lines) == 1   # the callback no longer receives messages


def test_callback_receives_levels_per_threshold():
    lines = []
    log.register_logger(lines.append)
    log.set_log_level(log.LogLevel.DEBUG)
    log.warning("w")
    log.info("i")
    log.debug("d")
    assert [ln.rsplit("] ", 1)[1] for ln in lines] == ["w", "i", "d"]
    assert "[Warning]" in lines[0]
    assert "[Info]" in lines[1]
    assert "[Debug]" in lines[2]

    # raising the threshold filters info/debug but keeps warnings
    lines.clear()
    log.set_log_level(log.LogLevel.WARNING)
    log.warning("w2")
    log.info("i2")
    log.debug("d2")
    assert len(lines) == 1 and "w2" in lines[0]

    # INFO level: warnings + info pass, debug filtered
    lines.clear()
    log.set_log_level(log.LogLevel.INFO)
    log.warning("w3")
    log.info("i3")
    log.debug("d3")
    assert len(lines) == 2


def test_fatal_and_check_raise_lightgbm_error():
    with pytest.raises(log.LightGBMError, match="boom 7"):
        log.fatal("boom %d", 7)
    with pytest.raises(log.LightGBMError, match="check failed"):
        log.check(False)
    with pytest.raises(log.LightGBMError, match="custom message"):
        log.check(1 > 2, "custom message")
    # a passing check is silent
    log.check(True)
