"""Structured training telemetry (obs/ + telemetry_out + profile_dir).

Tier-1 coverage of the observability subsystem: JSONL schema under the
single-device and multi-process drivers, the record_telemetry callback,
degradation-event routing, profiler wiring, and the disabled-path
overhead contract.
"""
import json
import os
import socket
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs import Telemetry


def _data(n=600, f=6, seed=3):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float32)
    return X, y


def _validate_jsonl(path, expect_rank=None):
    """Schema contract from docs/Observability.md: every line parses,
    carries ts/rank/event; iteration records carry strictly monotone
    iteration numbers, sections and collectives."""
    with open(path) as fh:
        recs = [json.loads(line) for line in fh]
    assert recs, f"empty telemetry file {path}"
    for r in recs:
        assert isinstance(r["ts"], float) and r["ts"] > 0
        assert isinstance(r["rank"], int)
        assert isinstance(r["event"], str) and r["event"]
        if expect_rank is not None:
            assert r["rank"] == expect_rank
    iters = [r for r in recs if r["event"] == "iteration"]
    nums = [r["iter"] for r in iters]
    assert nums == sorted(nums) and len(set(nums)) == len(nums), nums
    for r in iters:
        assert isinstance(r["sections"], dict)
        assert "histogram_split" in r["sections"]
        assert "score_update" in r["sections"]
        assert all(v >= 0.0 for v in r["sections"].values())
        assert isinstance(r["collectives"], dict)
        assert isinstance(r["compile"], dict)
        assert isinstance(r["num_leaves"], list) and r["num_leaves"]
    return recs, iters


def test_telemetry_jsonl_schema(tmp_path):
    out = tmp_path / "tel.jsonl"
    X, y = _data()
    bst = lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1,
                     "telemetry_out": str(out)},
                    lgb.Dataset(X, label=y), num_boost_round=5)
    recs, iters = _validate_jsonl(out, expect_rank=0)
    assert [r["iter"] for r in iters] == [0, 1, 2, 3, 4]
    # gradient work is attributed too, and compile events were captured
    assert "boosting" in iters[0]["sections"]
    assert iters[0]["compile"]["count"] > 0   # first iter compiles
    # end-of-training summary (engine.train finalize)
    summaries = [r for r in recs if r["event"] == "summary"]
    assert summaries and summaries[-1]["counters"]["iterations"] == 5

    # the live snapshot agrees
    snap = bst.telemetry()
    assert snap["enabled"] and snap["rank"] == 0
    assert snap["counters"]["iterations"] == 5
    assert "section.histogram_split" in snap["timings"]
    assert snap["timings"]["section.histogram_split"]["count"] == 5
    assert any(k.startswith("compile.") for k in snap["timings"])


def test_record_telemetry_callback():
    X, y = _data()
    result = {}
    lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1},
              lgb.Dataset(X, label=y), num_boost_round=4,
              callbacks=[lgb.record_telemetry(result)])
    recs = result["iterations"]
    assert [r["iter"] for r in recs] == [0, 1, 2, 3]
    assert all("sections" in r for r in recs)
    assert result["summary"]["counters"]["iterations"] == 4


def test_record_telemetry_rejects_non_dict():
    with pytest.raises(TypeError):
        lgb.record_telemetry([])


def test_degradation_events_routed_through_registry(tmp_path):
    """The driver's mode-degradation warnings carry structured reasons:
    tree_learner=feature + interaction constraints cannot run on the
    sliced XLA feature grower and must fall back to data-parallel."""
    out = tmp_path / "tel.jsonl"
    X, y = _data(n=400)
    bst = lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1,
                     "tree_learner": "feature",
                     "interaction_constraints": [[0, 1], [2, 3, 4, 5]],
                     "telemetry_out": str(out)},
                    lgb.Dataset(X, label=y), num_boost_round=2)
    assert bst._gbdt.parallel_mode == "data"
    with open(out) as fh:
        recs = [json.loads(line) for line in fh]
    degrades = [r for r in recs if r["event"] == "degrade"]
    assert any(r["reason"] == "feature_parallel_xla_constraints"
               and r.get("to") == "data" for r in degrades), degrades
    snap = bst.telemetry()
    assert snap["counters"].get(
        "degrade.feature_parallel_xla_constraints") == 1
    # distributed growth estimated its collective traffic
    iters = [r for r in recs if r["event"] == "iteration"]
    assert any(c.startswith("psum_data")
               for r in iters for c in r["collectives"]), iters
    assert snap["counters"].get("collectives.bytes", 0) > 0


def test_telemetry_disabled_no_overhead_and_no_records():
    # plain training leaves the registry untouched (no records, no
    # counters, no sink) — the train loop must not pay for snapshots
    X, y = _data(n=300)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbose": -1}, lgb.Dataset(X, label=y),
                    num_boost_round=3)
    snap = bst.telemetry()
    assert snap["enabled"] is False
    assert snap["counters"] == {} and snap["events"] == []
    assert bst._gbdt.telemetry.drain_records() == []

    # disabled registry ops are attribute-check no-ops: 3e5 calls in the
    # hot-loop style must be far below any per-iteration budget
    tel = Telemetry()
    t0 = time.perf_counter()
    for _ in range(100_000):
        tel.inc("x")
        tel.section("s", 0.0)
        tel.event("e", iteration=0, a=1)
    dt = time.perf_counter() - t0
    assert dt < 1.0, f"disabled-path overhead too high: {dt:.3f}s/300k ops"
    assert tel.snapshot()["counters"] == {}


def test_profile_dir_writes_trace(tmp_path):
    prof = tmp_path / "prof"
    X, y = _data(n=300)
    lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1,
               "profile_dir": str(prof), "profile_start_iteration": 1,
               "profile_num_iterations": 2},
              lgb.Dataset(X, label=y), num_boost_round=4)
    files = [os.path.join(r, f) for r, _, fs in os.walk(prof) for f in fs]
    assert files, "profiler trace produced no files"


_MP_WORKER = textwrap.dedent("""
    import json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=sys.argv[1],
        num_processes=int(sys.argv[2]), process_id=int(sys.argv[3]))
    import numpy as np
    import lightgbm_tpu as lgb

    path, tel_path, out_path = sys.argv[4], sys.argv[5], sys.argv[6]
    ds = lgb.Dataset(path, params={"label_column": 0, "verbose": -1,
                                   "max_bin": 63})
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "learning_rate": 0.2, "tree_learner": "data",
                     "verbose": -1, "telemetry_out": tel_path},
                    ds, num_boost_round=4)
    snap = bst.telemetry()
    with open(out_path, "w") as fh:
        json.dump({"rank": jax.process_index(),
                   "counters": snap["counters"],
                   "iterations": snap["counters"].get("iterations", 0)},
                  fh)
""")


def test_multiproc_telemetry_jsonl(tmp_path):
    """Multi-process driver: every rank streams its own rank-tagged
    JSONL (rank 0 the bare path, rank r <path>.rank<r>), host-plane
    allgathers are counted for real, and rank 0's summary aggregates
    per-rank counters."""
    rng = np.random.RandomState(5)
    n, F = 2000, 6
    X = rng.rand(n, F)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float64)
    train = tmp_path / "train.csv"
    np.savetxt(train, np.column_stack([y, X]), delimiter=",", fmt="%.6f")

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    script = tmp_path / "worker.py"
    script.write_text(_MP_WORKER)
    tel_path = tmp_path / "tel.jsonl"
    outs = [tmp_path / f"rank{i}.json" for i in range(2)]
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = repo_root
    env.pop("XLA_FLAGS", None)
    procs = [subprocess.Popen(
        [sys.executable, str(script), coord, "2", str(i), str(train),
         str(tel_path), str(outs[i])],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        for i in range(2)]
    for p in procs:
        out, err = p.communicate(timeout=600)
        assert p.returncode == 0, err.decode()[-3000:]

    rank_files = [tel_path, tmp_path / "tel.jsonl.rank1"]
    for rank, path in enumerate(rank_files):
        assert path.exists(), f"rank {rank} wrote no telemetry file"
        recs, iters = _validate_jsonl(path, expect_rank=rank)
        assert [r["iter"] for r in iters] == [0, 1, 2, 3]
        # distributed traffic: estimated psums + REAL host allgathers
        assert any("psum_data" in r["collectives"] for r in iters)

    reports = [json.loads(o.read_text()) for o in outs]
    for rep in reports:
        assert rep["iterations"] == 4
        assert rep["counters"].get("collectives.count", 0) > 0
        # the loader/layout's process_allgathers were counted for real
        assert any(k == "collectives.bytes" for k in rep["counters"])

    # rank 0's summary aggregates every rank's counters
    with open(tel_path) as fh:
        recs = [json.loads(line) for line in fh]
    summaries = [r for r in recs if r["event"] == "summary"]
    assert summaries, "rank 0 wrote no summary"
    ranks = summaries[-1].get("ranks")
    assert isinstance(ranks, list) and len(ranks) == 2
    assert sorted(x["rank"] for x in ranks) == [0, 1]
    # rank 1's file carries no aggregate (only rank 0 owns the summary)
    with open(rank_files[1]) as fh:
        recs1 = [json.loads(line) for line in fh]
    assert not any(r["event"] == "summary" for r in recs1)


def test_collective_traffic_measured_not_estimated(tmp_path):
    """Round 12 (ISSUE satellite): the distributed growers' collective
    records come from trace-time MEASUREMENT (ops/collectives.py
    records every psum/pmax payload while the fresh grower jit traces),
    not from the per-learner analytic estimates — the iteration
    records' psum traffic must agree exactly with the recorded
    per-grow profile."""
    out = tmp_path / "tel.jsonl"
    X, y = _data(n=1200)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbose": -1, "tree_learner": "data",
                     "telemetry_out": str(out)},
                    lgb.Dataset(X, label=y), num_boost_round=3)
    g = bst._gbdt
    assert g.parallel_mode == "data"
    # the first grow traced under an active CollectiveTrace recorder
    assert g._coll_per_grow is not None
    cnt, nbytes = g._coll_per_grow
    assert cnt > 0 and nbytes > 0
    with open(out) as fh:
        recs = [json.loads(line) for line in fh]
    iters = [r for r in recs if r["event"] == "iteration"]
    assert iters
    for r in iters:
        c = r["collectives"].get("psum_data")
        assert c is not None, r["collectives"]
        # one tree per iteration: the record IS the measured profile
        assert c["count"] == cnt and c["bytes"] == nbytes, (c, cnt, nbytes)
