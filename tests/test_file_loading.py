"""File-based dataset ingestion: CSV/TSV/LibSVM + sidecars (ref:
dataset_loader.cpp LoadFromFile, parser.cpp auto-detection)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.io.file_loader import load_text_file


def _write_csv(path, X, y, header=False, sep=","):
    with open(path, "w") as f:
        if header:
            cols = ["label"] + [f"f{i}" for i in range(X.shape[1])]
            f.write(sep.join(cols) + "\n")
        for i in range(len(y)):
            vals = [f"{y[i]:g}"] + [
                "" if np.isnan(v) else f"{v:.6g}" for v in X[i]]
            f.write(sep.join(vals) + "\n")


def _data(R=500, F=5, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(R, F).astype(np.float32)
    X[::7, 2] = np.nan
    y = (X[:, 0] > 0).astype(np.float32)
    return X, y


@pytest.mark.parametrize("sep,header", [(",", False), (",", True),
                                        ("\t", True)])
def test_csv_tsv_roundtrip(tmp_path, sep, header):
    X, y = _data()
    p = str(tmp_path / "d.csv")
    _write_csv(p, X, y, header=header, sep=sep)
    Xl, yl, side = load_text_file(p, label_column=0)
    np.testing.assert_allclose(yl, y)
    np.testing.assert_allclose(Xl, X, rtol=1e-5, atol=1e-6)


def test_libsvm(tmp_path):
    X, y = _data()
    p = str(tmp_path / "d.svm")
    with open(p, "w") as f:
        for i in range(len(y)):
            toks = [f"{y[i]:g}"]
            for j, v in enumerate(X[i]):
                if not np.isnan(v) and v != 0:
                    toks.append(f"{j}:{v:.6g}")
            f.write(" ".join(toks) + "\n")
    Xl, yl, _ = load_text_file(p)
    np.testing.assert_allclose(yl, y)
    Xz = np.where(np.isnan(X), 0.0, X)  # libsvm has no NaN: absent == 0
    np.testing.assert_allclose(Xl, Xz, rtol=1e-5, atol=1e-6)


def test_train_from_file_with_sidecars(tmp_path):
    X, y = _data(R=800)
    p = str(tmp_path / "train.csv")
    _write_csv(p, X, y, header=True)
    w = np.ones(len(y))
    np.savetxt(p + ".weight", w)
    ds = lgb.Dataset(p, params={"verbose": -1, "label_column": 0})
    bst = lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1,
                     "min_data_in_leaf": 5}, ds, num_boost_round=5)
    from sklearn.metrics import roc_auc_score
    Xn = np.where(np.isnan(X), np.nan, X)
    auc = roc_auc_score(y, bst.predict(Xn))
    assert auc > 0.9


def test_rank_sharded_loading(tmp_path):
    X, y = _data(R=100)
    p = str(tmp_path / "d.csv")
    _write_csv(p, X, y)
    x0, y0, _ = load_text_file(p, label_column=0, rank=0, num_machines=4)
    x3, y3, _ = load_text_file(p, label_column=0, rank=3, num_machines=4)
    assert len(y0) == 25 and len(y3) == 25
    np.testing.assert_allclose(x0, X[:25], rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(x3, X[75:], rtol=1e-5, atol=1e-6)


def test_cli_train_and_predict(tmp_path):
    """CLI train -> model file -> CLI predict (ref: application.cpp tasks)."""
    import subprocess, sys, os
    X, y = _data(R=600)
    train_p = str(tmp_path / "train.csv")
    _write_csv(train_p, X, y)
    model_p = str(tmp_path / "model.txt")
    out_p = str(tmp_path / "preds.tsv")
    conf_p = str(tmp_path / "train.conf")
    with open(conf_p, "w") as f:
        f.write("task = train\n# comment line\nobjective = binary\n"
                f"data = {train_p}\nnum_leaves = 7\nnum_iterations = 5\n"
                f"min_data_in_leaf = 5\nverbose = -1\n"
                f"output_model = {model_p}\n")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"  # never grab a TPU from a test subprocess
    r = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu", f"config={conf_p}"],
        cwd="/root/repo", env=env, capture_output=True, text=True,
        timeout=300)
    assert r.returncode == 0, r.stderr[-800:]
    assert os.path.exists(model_p)
    r2 = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu", "task=predict",
         f"data={train_p}", f"input_model={model_p}",
         f"output_result={out_p}", "verbose=-1"],
        cwd="/root/repo", env=env, capture_output=True, text=True,
        timeout=300)
    assert r2.returncode == 0, r2.stderr[-800:]
    preds = np.loadtxt(out_p)
    assert preds.shape == (600,)
    from sklearn.metrics import roc_auc_score
    assert roc_auc_score(y, preds) > 0.9


def test_cli_python_consistency(tmp_path):
    """CLI training and Python-API training on the same file produce the
    same model (ref: tests/python_package_test/test_consistency.py)."""
    import os
    import subprocess
    import sys
    import lightgbm_tpu as lgb
    X, y = _data(R=700, seed=9)
    train_p = str(tmp_path / "c.csv")
    _write_csv(train_p, X, y)
    model_p = str(tmp_path / "cli_model.txt")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    args = ["objective=binary", "num_leaves=7", "num_iterations=4",
            "min_data_in_leaf=5", "verbose=-1", "seed=3",
            "deterministic=true"]
    r = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu", "task=train",
         f"data={train_p}", f"output_model={model_p}"] + args,
        cwd="/root/repo", env=env, capture_output=True, text=True,
        timeout=300)
    assert r.returncode == 0, r.stderr[-500:]

    ds = lgb.Dataset(train_p, params={"verbose": -1})
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "min_data_in_leaf": 5, "verbose": -1, "seed": 3,
                     "deterministic": True}, ds, num_boost_round=4)
    cli_bst = lgb.Booster(model_file=model_p)
    import numpy as np
    Xq = np.where(np.isnan(X), np.nan, X)
    np.testing.assert_allclose(cli_bst.predict(Xq), bst.predict(Xq),
                               rtol=1e-9)


def test_cli_refit(tmp_path):
    """CLI refit task re-fits leaf values on new data
    (ref: application.cpp task=refit)."""
    import os
    import subprocess
    import sys
    X, y = _data(R=500, seed=4)
    train_p = str(tmp_path / "r.csv")
    _write_csv(train_p, X, y)
    model_p = str(tmp_path / "m.txt")
    refit_p = str(tmp_path / "m2.txt")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    base = ["objective=binary", "num_leaves=7", "min_data_in_leaf=5",
            "verbose=-1"]
    r = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu", "task=train",
         f"data={train_p}", "num_iterations=3",
         f"output_model={model_p}"] + base,
        cwd="/root/repo", env=env, capture_output=True, text=True,
        timeout=300)
    assert r.returncode == 0, r.stderr[-500:]
    r2 = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu", "task=refit",
         f"data={train_p}", f"input_model={model_p}",
         f"output_model={refit_p}", "verbose=-1"],
        cwd="/root/repo", env=env, capture_output=True, text=True,
        timeout=300)
    assert r2.returncode == 0, r2.stderr[-500:]
    import lightgbm_tpu as lgb
    b = lgb.Booster(model_file=refit_p)
    assert b.num_trees() == 3


def test_sequence_dataset():
    """Chunked Sequence ingestion (ref: basic.py:605 Sequence)."""
    import lightgbm_tpu as lgb

    class NpSeq(lgb.Sequence):
        batch_size = 128

        def __init__(self, arr):
            self.arr = arr

        def __len__(self):
            return len(self.arr)

        def __getitem__(self, idx):
            return self.arr[idx]

    X, y = _data(R=600, seed=6)
    Xc = np.where(np.isnan(X), 0.0, X)
    ds = lgb.Dataset([NpSeq(Xc[:300]), NpSeq(Xc[300:])], label=y,
                     params={"verbose": -1})
    bst = lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1,
                     "min_data_in_leaf": 5}, ds, num_boost_round=5)
    from sklearn.metrics import roc_auc_score
    assert roc_auc_score(y, bst.predict(Xc)) > 0.9


def test_add_features_from():
    """(ref: dataset.h AddFeaturesFrom)"""
    import lightgbm_tpu as lgb
    X, y = _data(R=800, seed=12)
    d1 = lgb.Dataset(X[:, :3], label=y, params={"verbose": -1})
    d2 = lgb.Dataset(X[:, 3:], params={"verbose": -1})
    d1.add_features_from(d2)
    assert d1.num_feature() >= 5
    bst = lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1,
                     "min_data_in_leaf": 5}, d1, num_boost_round=5)
    from sklearn.metrics import roc_auc_score
    Xq = np.where(np.isnan(X), np.nan, X)
    assert roc_auc_score(y, bst.predict(Xq)) > 0.9
