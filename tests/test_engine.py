"""End-to-end behavioral tests (the analog of the reference's
tests/python_package_test/test_engine.py tier)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.utils.log import LightGBMError


@pytest.fixture(scope="module")
def binary_data():
    rng = np.random.RandomState(42)
    X = rng.randn(3000, 20)
    logit = X[:, 0] * 2 + X[:, 1] ** 2 - X[:, 2] + rng.randn(3000) * 0.3
    y = (logit > 0.5).astype(np.float64)
    return X[:2400], y[:2400], X[2400:], y[2400:]


def test_binary_auc(binary_data):
    from sklearn.metrics import roc_auc_score
    Xtr, ytr, Xte, yte = binary_data
    bst = lgb.train({"objective": "binary", "verbose": -1},
                    lgb.Dataset(Xtr, label=ytr), 50)
    auc = roc_auc_score(yte, bst.predict(Xte))
    assert auc > 0.98


def test_regression_vs_sklearn():
    rng = np.random.RandomState(0)
    X = rng.randn(3000, 10)
    y = X[:, 0] * 3 + np.sin(X[:, 1] * 2) + rng.randn(3000) * 0.1
    bst = lgb.train({"objective": "regression", "verbose": -1},
                    lgb.Dataset(X[:2400], label=y[:2400]), 100)
    mse = np.mean((bst.predict(X[2400:]) - y[2400:]) ** 2)
    from sklearn.ensemble import HistGradientBoostingRegressor
    sk = HistGradientBoostingRegressor(max_iter=100).fit(X[:2400], y[:2400])
    sk_mse = np.mean((sk.predict(X[2400:]) - y[2400:]) ** 2)
    assert mse < sk_mse * 1.5


def test_missing_values_routed(binary_data):
    from sklearn.metrics import roc_auc_score
    rng = np.random.RandomState(1)
    Xtr, ytr, Xte, yte = binary_data
    Xtr = Xtr.copy()
    Xte = Xte.copy()
    Xtr[rng.rand(*Xtr.shape) < 0.2] = np.nan
    Xte[rng.rand(*Xte.shape) < 0.2] = np.nan
    bst = lgb.train({"objective": "binary", "verbose": -1},
                    lgb.Dataset(Xtr, label=ytr), 50)
    assert roc_auc_score(yte, bst.predict(Xte)) > 0.9


def test_multiclass_softmax_and_ova():
    rng = np.random.RandomState(2)
    X = rng.randn(2000, 10)
    y = (X[:, 0] > 0.5).astype(int) + (X[:, 1] > 0).astype(int)
    for obj in ("multiclass", "multiclassova"):
        bst = lgb.train({"objective": obj, "num_class": 3, "verbose": -1},
                        lgb.Dataset(X, label=y), 30)
        pred = bst.predict(X)
        assert pred.shape == (2000, 3)
        assert (pred.argmax(1) == y).mean() > 0.9


def test_early_stopping_fires():
    rng = np.random.RandomState(3)
    X = rng.randn(2000, 5)
    y = X[:, 0] + rng.randn(2000)
    dtrain = lgb.Dataset(X[:1500], label=y[:1500])
    dvalid = lgb.Dataset(X[1500:], label=y[1500:], reference=dtrain)
    bst = lgb.train({"objective": "regression", "verbose": -1}, dtrain, 500,
                    valid_sets=[dvalid],
                    callbacks=[lgb.early_stopping(10, verbose=False)])
    assert 0 < bst.best_iteration < 500


def test_model_io_bit_identical(binary_data, tmp_path):
    Xtr, ytr, Xte, _ = binary_data
    bst = lgb.train({"objective": "binary", "verbose": -1},
                    lgb.Dataset(Xtr, label=ytr), 20)
    path = str(tmp_path / "model.txt")
    bst.save_model(path)
    bst2 = lgb.Booster(model_file=path)
    np.testing.assert_array_equal(bst.predict(Xte), bst2.predict(Xte))
    # raw score path too
    np.testing.assert_array_equal(bst.predict(Xte, raw_score=True),
                                  bst2.predict(Xte, raw_score=True))


def test_continued_training(binary_data):
    # reference semantics: the continued booster holds only the NEW trees;
    # the init model enters through init_score (ref: engine.py:174-185
    # _set_predictor -> _set_init_score_by_predictor)
    from sklearn.metrics import log_loss
    Xtr, ytr, Xte, yte = binary_data
    b1 = lgb.train({"objective": "binary", "verbose": -1},
                   lgb.Dataset(Xtr, label=ytr), 10)
    l1 = log_loss(yte, b1.predict(Xte))
    b2 = lgb.train({"objective": "binary", "verbose": -1},
                   lgb.Dataset(Xtr, label=ytr), 10, init_model=b1)
    combined_raw = b1.predict(Xte, raw_score=True) \
        + b2.predict(Xte, raw_score=True)
    l2 = log_loss(yte, 1.0 / (1.0 + np.exp(-combined_raw)))
    assert l2 < l1


def test_custom_objective(binary_data):
    from sklearn.metrics import roc_auc_score
    Xtr, ytr, Xte, yte = binary_data

    def logloss_obj(preds, dataset):
        y = dataset.get_label()
        p = 1.0 / (1.0 + np.exp(-preds))
        return p - y, p * (1 - p)

    # custom objective via update loop
    ds2 = lgb.Dataset(Xtr, label=ytr)
    bst2 = lgb.Booster(params={"objective": "none", "verbose": -1},
                       train_set=ds2)
    for _ in range(30):
        bst2.update(fobj=logloss_obj)
    auc = roc_auc_score(yte, bst2.predict(Xte, raw_score=True))
    assert auc > 0.97


def test_custom_feval(binary_data):
    Xtr, ytr, Xte, yte = binary_data
    dtrain = lgb.Dataset(Xtr, label=ytr)
    dvalid = lgb.Dataset(Xte, label=yte, reference=dtrain)
    seen = {}

    def my_metric(preds, dataset):
        return ("my_err", float(np.mean((preds > 0) != dataset.get_label())),
                False)

    record = {}
    lgb.train({"objective": "binary", "verbose": -1, "metric": "None"},
              dtrain, 5, valid_sets=[dvalid], feval=my_metric,
              callbacks=[lgb.record_evaluation(record)])
    assert "my_err" in record["valid_0"]
    assert len(record["valid_0"]["my_err"]) == 5


def test_weights_change_model():
    rng = np.random.RandomState(4)
    X = rng.randn(1000, 5)
    y = X[:, 0] + rng.randn(1000) * 0.1
    w = np.abs(rng.randn(1000)) + 0.01
    b1 = lgb.train({"objective": "regression", "verbose": -1},
                   lgb.Dataset(X, label=y), 10)
    b2 = lgb.train({"objective": "regression", "verbose": -1},
                   lgb.Dataset(X, label=y, weight=w), 10)
    assert np.abs(b1.predict(X) - b2.predict(X)).max() > 1e-6


def test_bagging_and_feature_fraction():
    rng = np.random.RandomState(5)
    X = rng.randn(2000, 20)
    y = (X[:, 0] + X[:, 1] > 0).astype(float)
    bst = lgb.train({"objective": "binary", "bagging_freq": 1,
                     "bagging_fraction": 0.5, "feature_fraction": 0.5,
                     "verbose": -1}, lgb.Dataset(X, label=y), 30)
    from sklearn.metrics import roc_auc_score
    assert roc_auc_score(y, bst.predict(X)) > 0.9


def test_goss_dart_rf_run():
    from sklearn.metrics import roc_auc_score
    rng = np.random.RandomState(6)
    X = rng.randn(2000, 10)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(float)
    for boosting, extra in [("goss", {}), ("dart", {}),
                            ("rf", {"bagging_freq": 1,
                                    "bagging_fraction": 0.7})]:
        params = {"objective": "binary", "boosting": boosting,
                  "verbose": -1, **extra}
        bst = lgb.train(params, lgb.Dataset(X, label=y), 30)
        auc = roc_auc_score(y, bst.predict(X))
        assert auc > 0.9, f"{boosting} AUC {auc}"


def test_lambdarank_improves_ndcg():
    rng = np.random.RandomState(7)
    n_q = 100
    sizes = rng.randint(5, 20, n_q)
    n = sizes.sum()
    X = rng.randn(n, 10)
    y = np.clip((X[:, 0] * 2 + rng.randn(n) * 0.5).astype(int), 0, 4)
    ds = lgb.Dataset(X, label=y, group=sizes)
    record = {}
    lgb.train({"objective": "lambdarank", "metric": "ndcg", "eval_at": [5],
               "verbose": -1}, ds, 30,
              valid_sets=[ds], valid_names=["training"],
              callbacks=[lgb.record_evaluation(record)])
    ndcgs = record["training"]["ndcg@5"]
    assert ndcgs[-1] > ndcgs[0]
    assert ndcgs[-1] > 0.75


def test_cv_returns_results():
    rng = np.random.RandomState(8)
    X = rng.randn(1000, 5)
    y = (X[:, 0] > 0).astype(float)
    res = lgb.cv({"objective": "binary", "verbose": -1,
                  "metric": "binary_logloss"},
                 lgb.Dataset(X, label=y), num_boost_round=10, nfold=3)
    assert len(res["valid binary_logloss-mean"]) == 10
    assert res["valid binary_logloss-mean"][-1] < \
        res["valid binary_logloss-mean"][0]


def test_invalid_params_raise():
    X = np.random.RandomState(9).randn(100, 3)
    y = X[:, 0]
    with pytest.raises(LightGBMError):
        lgb.train({"objective": "binary", "num_leaves": 1, "verbose": -1},
                  lgb.Dataset(X, label=y), 1)


def test_reset_parameter_callback():
    rng = np.random.RandomState(10)
    X = rng.randn(500, 5)
    y = X[:, 0] + rng.randn(500) * 0.1
    lrs = []

    def spy(env):
        lrs.append(env.model.config.learning_rate)
    spy.order = 100
    lgb.train({"objective": "regression", "verbose": -1},
              lgb.Dataset(X, label=y), 5,
              callbacks=[lgb.reset_parameter(
                  learning_rate=lambda i: 0.1 * (0.5 ** i)), spy])
    assert lrs[0] == pytest.approx(0.1)
    assert lrs[-1] == pytest.approx(0.1 * 0.5 ** 4)


def test_feature_importance(binary_data):
    Xtr, ytr, _, _ = binary_data
    bst = lgb.train({"objective": "binary", "verbose": -1},
                    lgb.Dataset(Xtr, label=ytr), 20)
    imp_split = bst.feature_importance("split")
    imp_gain = bst.feature_importance("gain")
    assert imp_split.sum() > 0
    # informative features dominate
    assert imp_gain[:3].sum() > imp_gain[3:].sum()


def test_pred_leaf_and_contrib(binary_data):
    Xtr, ytr, Xte, _ = binary_data
    bst = lgb.train({"objective": "binary", "verbose": -1, "num_leaves": 7},
                    lgb.Dataset(Xtr, label=ytr), 5)
    leaves = bst.predict(Xte[:50], pred_leaf=True)
    assert leaves.shape == (50, 5)
    assert leaves.max() < 7
    contrib = bst.predict(Xte[:10], pred_contrib=True)
    assert contrib.shape == (10, Xtr.shape[1] + 1)
    raw = bst.predict(Xte[:10], raw_score=True)
    np.testing.assert_allclose(contrib.sum(axis=1), raw, rtol=1e-5, atol=1e-5)


def test_depthwise_policy_quality(binary_data):
    from sklearn.metrics import roc_auc_score
    Xtr, ytr, Xte, yte = binary_data
    bst = lgb.train({"objective": "binary", "grow_policy": "depthwise",
                     "verbose": -1}, lgb.Dataset(Xtr, label=ytr), 30)
    assert roc_auc_score(yte, bst.predict(Xte)) > 0.97


def test_snapshot_freq(tmp_path):
    """snapshot_freq writes periodic checkpoints that load as boosters
    (ref: gbdt.cpp:279-283)."""
    import numpy as np
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(0)
    X = rng.randn(500, 4).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    out = str(tmp_path / "m.txt")
    ds = lgb.Dataset(X, label=y, params={"verbose": -1})
    lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1,
               "min_data_in_leaf": 5, "snapshot_freq": 2,
               "output_model": out}, ds, num_boost_round=5)
    import os
    snaps = [p for p in os.listdir(tmp_path) if "snapshot_iter_" in p]
    assert sorted(snaps) == ["m.txt.snapshot_iter_2", "m.txt.snapshot_iter_4"]
    b = lgb.Booster(model_file=str(tmp_path / "m.txt.snapshot_iter_4"))
    assert b.num_trees() == 4


def test_first_metric_only_checks_all_valid_sets():
    """With first_metric_only, the FIRST metric is tracked on every valid
    set; other metrics are ignored (ADVICE round-1 item)."""
    import numpy as np
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(1)
    X = rng.randn(800, 4).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    ds = lgb.Dataset(X[:400], label=y[:400], params={"verbose": -1})
    v1 = ds.create_valid(X[400:600], label=y[400:600])
    v2 = ds.create_valid(X[600:], label=y[600:])
    evals = {}
    bst = lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1,
                     "min_data_in_leaf": 5,
                     "metric": ["binary_logloss", "auc"],
                     "early_stopping_round": 3, "first_metric_only": True},
                    ds, num_boost_round=30, valid_sets=[v1, v2],
                    valid_names=["v1", "v2"],
                    callbacks=[lgb.record_evaluation(evals)])
    # both valid sets were evaluated on the first metric
    assert "binary_logloss" in evals["v1"] and "binary_logloss" in evals["v2"]
    # and the CLI-path early stopper tracks the first metric on BOTH valid
    # sets (GBDT.output_metric, ref: gbdt.cpp:560)
    g = bst._gbdt
    g.best_score.clear()
    g.best_iter.clear()
    g.output_metric(1)
    tracked = {k for k in g.best_score}
    assert ("v1", "binary_logloss") in tracked
    assert ("v2", "binary_logloss") in tracked
    assert not any(name == "auc" for _, name in tracked)


def test_prediction_early_stop():
    """pred_early_stop skips remaining trees for confident rows with
    bounded output change (ref: prediction_early_stop.cpp)."""
    import numpy as np
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(3)
    X = rng.randn(1000, 4).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    ds = lgb.Dataset(X, label=y, params={"verbose": -1})
    bst = lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1,
                     "min_data_in_leaf": 5}, ds, num_boost_round=40)
    full = bst.predict(X, raw_score=True)
    es = bst.predict(X, raw_score=True, pred_early_stop=True,
                     pred_early_stop_freq=5, pred_early_stop_margin=2.0)
    # stopped rows must already be on the right side with margin >= 2
    moved = np.abs(full - es) > 1e-12
    assert np.all(np.abs(es[moved]) >= 2.0)
    assert np.sign(es[moved]).astype(int).tolist() == \
        np.sign(full[moved]).astype(int).tolist()


def test_cv_with_query_groups():
    """Ranking CV keeps whole queries per fold (ref: engine.py:323
    _make_n_folds group handling)."""
    import numpy as np
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(8)
    n_q, per_q = 24, 20
    X = rng.rand(n_q * per_q, 4).astype(np.float32)
    rel = (3 * X[:, 0] + 0.2 * rng.rand(n_q * per_q)).astype(int).clip(0, 3)
    ds = lgb.Dataset(X, label=rel, group=np.full(n_q, per_q),
                     params={"verbose": -1})
    res = lgb.cv({"objective": "lambdarank", "num_leaves": 7,
                  "verbose": -1, "min_data_in_leaf": 5,
                  "metric": "ndcg", "ndcg_eval_at": [5]},
                 ds, num_boost_round=4, nfold=3, stratified=False)
    key = [k for k in res if k.startswith("valid")][0]
    assert len(res[key]) == 4
    assert res[key][-1] > 0.5


def test_predict_iteration_slicing():
    """start_iteration/num_iteration slicing (ref: basic.py predict)."""
    import numpy as np
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(10)
    X = rng.randn(500, 4).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    ds = lgb.Dataset(X, label=y, params={"verbose": -1})
    bst = lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1,
                     "min_data_in_leaf": 5}, ds, num_boost_round=6)
    full = bst.predict(X, raw_score=True)
    head = bst.predict(X, raw_score=True, num_iteration=2)
    tail = bst.predict(X, raw_score=True, start_iteration=2)
    # head uses trees [0,2), tail trees [2,6); raw scores add up (minus
    # the double-counted boost-from-average constant folded into tree 0)
    np.testing.assert_allclose(head + tail, full, rtol=1e-9, atol=1e-9)
    assert not np.allclose(head, full)
