#include <stdio.h>
#include <stdint.h>
extern const char* LGBM_GetLastError(void);
extern int LGBM_DatasetCreateFromMat(const void*, int, int32_t, int32_t,
                                     int, const char*, void*, void**);
extern int LGBM_DatasetSetField(void*, const char*, const void*, int32_t, int);
extern int LGBM_BoosterCreate(void*, const char*, void**);
extern int LGBM_BoosterUpdateOneIter(void*, int*);
extern int LGBM_BoosterPredictForMat(void*, const void*, int, int32_t,
                                     int32_t, int, int, int, int,
                                     const char*, int64_t*, double*);
int main(void) {
  enum { N = 400, F = 4 };
  static float X[N * F], y[N];
  unsigned s = 12345;
  for (int i = 0; i < N * F; ++i) {
    s = 1103515245u * s + 12345u;
    X[i] = (float)((s >> 16) & 0x7FFF) / 32768.0f;
  }
  for (int i = 0; i < N; ++i) y[i] = X[i * F] > 0.5f ? 1.0f : 0.0f;
  void* ds = 0; void* bst = 0; int fin = 0;
  if (LGBM_DatasetCreateFromMat(X, 0, N, F, 1, "verbose=-1", 0, &ds)) {
    printf("ds err: %s\n", LGBM_GetLastError()); return 1;
  }
  if (LGBM_DatasetSetField(ds, "label", y, N, 0)) {
    printf("field err: %s\n", LGBM_GetLastError()); return 1;
  }
  if (LGBM_BoosterCreate(ds, "objective=binary num_leaves=7 verbose=-1",
                         &bst)) {
    printf("bst err: %s\n", LGBM_GetLastError()); return 1;
  }
  for (int i = 0; i < 3; ++i)
    if (LGBM_BoosterUpdateOneIter(bst, &fin)) {
      printf("update err: %s\n", LGBM_GetLastError()); return 1;
    }
  static double out[N]; int64_t out_len = 0;
  if (LGBM_BoosterPredictForMat(bst, X, 0, N, F, 1, 0, 0, -1, "",
                                &out_len, out)) {
    printf("pred err: %s\n", LGBM_GetLastError()); return 1;
  }
  int ok = 0;
  for (int i = 0; i < N; ++i)
    ok += ((out[i] > 0.5) == (y[i] > 0.5f));
  printf("C HOST OK: %lld preds, acc %.3f\n", (long long)out_len,
         (double)ok / N);
  return 0;
}
