"""Fused route+histogram level kernel vs numpy oracle (interpret mode).

Covers the round-2 hot path (ops/fused_level.py): root histogram, mid-tree
routing + smaller-child histograms with missing-bin routing, categorical
route tables, hi/lo bf16 precision recombination, and the table_lookup
score-update kernel. Oracle is plain numpy over the same tables
(ref semantics: src/io/dense_bin.hpp Split + ConstructHistogram).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lightgbm_tpu.ops.fused_level import (NCH_FAST, NCH_PRECISE,
                                          build_route_table, feature_layout,
                                          hist_planes, level_pass, pack_gh,
                                          table_lookup)


def _np_route_left(b, thr, dl, nb, mt, db):
    missing = ((mt == 1) & (b == db)) | ((mt == 2) & (b == nb - 1))
    return np.where(missing, dl, b <= thr)


def _oracle(bins, leaf, grad, hess, w, slots, meta, F, B):
    """Per-slot smaller-child histograms + new leaf ids, in numpy."""
    nb, mt, db = meta
    R = bins.shape[0]
    Sp = len(slots)
    hist = np.zeros((Sp, F, B, 3), np.float64)
    new_leaf = leaf.copy()
    for k, (lf, feat, thr, dl, delta, small_left) in enumerate(slots):
        if lf < 0:
            continue
        on = leaf == lf
        b = bins[:, feat]
        left = _np_route_left(b, thr, dl, nb[feat], mt[feat], db[feat])
        go_right = on & ~left
        new_leaf = np.where(go_right, leaf + delta, new_leaf)
        in_small = on & (left == bool(small_left))
        for f in range(F):
            np.add.at(hist[k, f, :, 0], bins[in_small, f], grad[in_small])
            np.add.at(hist[k, f, :, 1], bins[in_small, f], hess[in_small])
            np.add.at(hist[k, f, :, 2], bins[in_small, f], w[in_small])
    return hist, new_leaf


def _setup(R=1024, F=5, B=16, seed=0):
    rng = np.random.RandomState(seed)
    nb = np.array([B, B - 3, B, 7, B], np.int32)[:F]
    mt = np.array([0, 1, 2, 0, 2], np.int32)[:F]
    db = np.array([0, 4, 0, 0, 0], np.int32)[:F]
    bins = np.stack([rng.randint(0, nb[f], size=R) for f in range(F)],
                    axis=1).astype(np.int8)
    grad = rng.randn(R).astype(np.float32)
    hess = np.abs(rng.randn(R)).astype(np.float32) + 0.1
    w = np.ones(R, np.float32)
    return bins, grad, hess, w, (nb, mt, db)


def _run_level(bins, leaf, grad, hess, w, slots, meta, F, B, nch):
    nb, mt, db = meta
    F_oh, Bp = feature_layout(F, B)
    assert Bp == B
    R = bins.shape[0]
    C = 256
    Rp = ((R + C - 1) // C) * C
    Fp = max(F_oh, 8)
    bins_T = np.zeros((Fp, Rp), np.int8)
    bins_T[:F, :R] = bins.T
    leaf_T = np.full((1, Rp), -1, np.int32)
    leaf_T[0, :R] = leaf
    gpad = np.zeros(Rp, np.float32)
    gpad[:R] = grad
    hpad = np.zeros(Rp, np.float32)
    hpad[:R] = hess
    wpad = np.zeros(Rp, np.float32)
    wpad[:R] = w

    Sp = len(slots)
    feat = jnp.asarray([s[1] if s[0] >= 0 else -1 for s in slots], jnp.int32)
    thr = jnp.asarray([s[2] for s in slots], jnp.int32)
    dl = jnp.asarray([bool(s[3]) for s in slots])
    W = build_route_table(feat, thr, dl, jnp.asarray(nb), jnp.asarray(mt),
                          jnp.asarray(db), Sp, F_oh, B)
    tbl = np.zeros((Sp, 128), np.int32)
    for k, (lf, _, _, _, delta, small_left) in enumerate(slots):
        tbl[k, 0] = lf
        tbl[k, 1] = delta
        tbl[k, 2] = int(small_left)

    gh_T = pack_gh(jnp.asarray(gpad), jnp.asarray(hpad), jnp.asarray(wpad),
                   nch)
    hist, new_leaf = level_pass(
        jnp.asarray(bins_T), jnp.asarray(leaf_T), gh_T, W,
        jnp.asarray(tbl), num_slots=Sp, num_bins=B, f_oh=F_oh, nch=nch,
        tile_rows=C, interpret=True)
    g, h, c = hist_planes(hist, nch, Sp, F_oh, B)
    got = np.stack([np.asarray(g), np.asarray(h), np.asarray(c)],
                   axis=-1)[:, :F]
    return got, np.asarray(new_leaf)[0, :R]


def test_root_histogram():
    bins, grad, hess, w, meta = _setup()
    F, B = 5, 16
    leaf = np.zeros(bins.shape[0], np.int32)
    # root: slot 0 collects everything (W row routes all rows left)
    slots = [(0, 0, B - 1, True, 0, 1)] + [(-1, 0, 0, 0, 0, 0)] * 7
    got, new_leaf = _run_level(bins, leaf, grad, hess, w, slots, meta, F, B,
                               NCH_PRECISE)
    want, want_leaf = _oracle(bins, leaf, grad, hess, w, slots, meta, F, B)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-4)
    np.testing.assert_array_equal(new_leaf, want_leaf)


@pytest.mark.parametrize("nch", [NCH_PRECISE, NCH_FAST])
def test_mid_level_route_and_hist(nch):
    bins, grad, hess, w, meta = _setup(R=2048)
    F, B = 5, 16
    rng = np.random.RandomState(1)
    leaf = rng.randint(0, 3, size=bins.shape[0]).astype(np.int32)
    # three active slots splitting leaves 0,1,2 on different features,
    # exercising zero- and nan-missing routing + both small sides
    slots = [
        (0, 1, 5, True, 3, 1),    # feature 1: zero-missing, default left
        (1, 2, 7, False, 3, 0),   # feature 2: nan-missing, default right
        (2, 3, 2, True, 3, 1),    # feature 3: 7 bins
    ] + [(-1, 0, 0, 0, 0, 0)] * 5
    got, new_leaf = _run_level(bins, leaf, grad, hess, w, slots, meta, F, B,
                               nch)
    want, want_leaf = _oracle(bins, leaf, grad, hess, w, slots, meta, F, B)
    tol = 1e-4 if nch == NCH_PRECISE else 2e-2
    np.testing.assert_allclose(got, want, rtol=tol, atol=float(tol))
    np.testing.assert_array_equal(new_leaf, want_leaf)


def test_precision_hi_lo_beats_bf16():
    """The hi/lo split must recover ~fp32 sums where raw bf16 drifts."""
    bins, grad, hess, w, meta = _setup(R=4096, seed=3)
    F, B = 5, 16
    leaf = np.zeros(bins.shape[0], np.int32)
    slots = [(0, 0, B - 1, True, 0, 1)] + [(-1, 0, 0, 0, 0, 0)] * 7
    want, _ = _oracle(bins, leaf, grad, hess, w, slots, meta, F, B)
    got5, _ = _run_level(bins, leaf, grad, hess, w, slots, meta, F, B,
                         NCH_PRECISE)
    got3, _ = _run_level(bins, leaf, grad, hess, w, slots, meta, F, B,
                         NCH_FAST)
    err5 = np.abs(got5[..., 0] - want[..., 0]).max()
    err3 = np.abs(got3[..., 0] - want[..., 0]).max()
    assert err5 < 1e-3
    assert err5 < err3 / 4


def test_categorical_route_table():
    bins, grad, hess, w, meta = _setup(R=2048, seed=5)
    F, B = 5, 16
    rng = np.random.RandomState(2)
    leaf = rng.randint(0, 2, size=bins.shape[0]).astype(np.int32)
    nb, mt, db = meta
    cat_mask = np.zeros((8, B), bool)
    cat_mask[0, [1, 3, 4]] = True       # bins {1,3,4} of feature 0 go left
    slots = [(0, 0, 0, False, 2, 1)] + [(-1, 0, 0, 0, 0, 0)] * 7
    F_oh, _ = feature_layout(F, B)
    feat = jnp.asarray([0] + [-1] * 7, jnp.int32)
    W = build_route_table(
        feat, jnp.zeros(8, jnp.int32), jnp.zeros(8, bool),
        jnp.asarray(nb), jnp.asarray(mt), jnp.asarray(db), 8, F_oh, B,
        cat_flag=jnp.asarray([True] + [False] * 7),
        cat_mask=jnp.asarray(cat_mask))
    # numpy oracle with explicit membership
    on = leaf == 0
    left = cat_mask[0][bins[:, 0]]
    want_leaf = np.where(on & ~left, leaf + 2, leaf)

    C = 256
    R = bins.shape[0]
    Fp = max(F_oh, 8)
    bins_T = np.zeros((Fp, R), np.int8)
    bins_T[:F] = bins.T
    leaf_T = leaf[None, :].astype(np.int32)
    tbl = np.zeros((8, 128), np.int32)
    tbl[0] = 0
    tbl[0, 1] = 2
    tbl[0, 2] = 1
    tbl[1:, 0] = -1
    gh_T = pack_gh(jnp.asarray(grad), jnp.asarray(hess), jnp.asarray(w),
                   NCH_FAST)
    hist, new_leaf = level_pass(
        jnp.asarray(bins_T), jnp.asarray(leaf_T), gh_T, W, jnp.asarray(tbl),
        num_slots=8, num_bins=B, f_oh=F_oh, nch=NCH_FAST, tile_rows=C,
        interpret=True)
    np.testing.assert_array_equal(np.asarray(new_leaf)[0], want_leaf)
    # smaller-child (left side here) grad histogram of feature 0
    in_small = on & left
    want_g = np.zeros(B)
    np.add.at(want_g, bins[in_small, 0], grad[in_small])
    g, _, _ = hist_planes(hist, NCH_FAST, 8, F_oh, B)
    np.testing.assert_allclose(np.asarray(g)[0, 0], want_g, rtol=2e-2,
                               atol=2e-2)


def test_table_lookup():
    rng = np.random.RandomState(0)
    R, L = 4096, 37
    idx = rng.randint(-1, L, size=R).astype(np.int32)
    table = rng.randn(L).astype(np.float32)
    out = table_lookup(jnp.asarray(idx[None, :]), jnp.asarray(table),
                       tile_rows=1024, interpret=True)
    want = np.where(idx >= 0, table[np.clip(idx, 0, L - 1)], 0.0)
    np.testing.assert_allclose(np.asarray(out)[0], want, rtol=1e-6)
