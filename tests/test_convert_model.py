"""convert_model if-else codegen + save_binary CLI task (VERDICT r3 #5;
ref: src/io/tree.cpp:562 ToIfElse, application.cpp task dispatch)."""
import ctypes
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _compile(code, tmp_path):
    if shutil.which("g++") is None:
        pytest.skip("no C++ toolchain")
    src = tmp_path / "pred.cpp"
    # export the entry points for ctypes
    src.write_text(code + '\nextern "C" void PredictC(const double* a, '
                   'double* o) { Predict(a, o); }\n'
                   'extern "C" void PredictRawC(const double* a, '
                   'double* o) { PredictRaw(a, o); }\n')
    so = tmp_path / "pred.so"
    r = subprocess.run(["g++", "-O2", "-shared", "-fPIC", str(src),
                        "-o", str(so)], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr[-2000:]
    return ctypes.CDLL(str(so))


def _check_codegen(bst, X, k, tmp_path):
    from lightgbm_tpu.io.model_io import model_to_if_else
    lib = _compile(model_to_if_else(bst), tmp_path)
    got = np.empty((len(X), k))
    raw = np.empty((len(X), k))
    out = (ctypes.c_double * k)()
    for i, row in enumerate(np.ascontiguousarray(X, np.float64)):
        lib.PredictC(row.ctypes.data_as(ctypes.c_void_p), out)
        got[i] = list(out)
        lib.PredictRawC(row.ctypes.data_as(ctypes.c_void_p), out)
        raw[i] = list(out)
    want = np.asarray(bst.predict(X)).reshape(len(X), -1)
    want_raw = np.asarray(bst.predict(X, raw_score=True)) \
        .reshape(len(X), -1)
    np.testing.assert_allclose(raw, want_raw, rtol=1e-10, atol=1e-12)
    np.testing.assert_allclose(got, want, rtol=1e-8, atol=1e-10)


def test_if_else_codegen_binary_with_missing_and_categorical(tmp_path):
    rng = np.random.RandomState(0)
    n = 3000
    X = rng.rand(n, 5)
    X[rng.rand(n) < 0.1, 0] = np.nan              # NaN missing on f0
    X[:, 3] = rng.randint(0, 40, n)               # categorical, wide
    y = ((np.nan_to_num(X[:, 0]) + X[:, 1] > 0.9)
         | (X[:, 3] % 7 == 3)).astype(np.float32)
    ds = lgb.Dataset(X, label=y, categorical_feature=[3],
                     params={"verbose": -1})
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbose": -1, "num_iterations": 8}, ds)
    Xq = X[:400].copy()
    _check_codegen(bst, Xq, 1, tmp_path)


def test_if_else_codegen_multiclass(tmp_path):
    rng = np.random.RandomState(1)
    n = 2000
    X = rng.rand(n, 4)
    y = (X[:, 0] * 3).astype(int)
    ds = lgb.Dataset(X, label=y, params={"verbose": -1})
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "num_leaves": 7, "verbose": -1,
                     "num_iterations": 5}, ds)
    _check_codegen(bst, X[:200], 3, tmp_path)


def test_cli_convert_model_and_save_binary(tmp_path):
    rng = np.random.RandomState(2)
    X = rng.rand(1200, 4)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(float)
    train = tmp_path / "t.csv"
    np.savetxt(train, np.column_stack([y, X]), delimiter=",", fmt="%.6f")
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=here)
    env.pop("XLA_FLAGS", None)

    model = tmp_path / "m.txt"
    r = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu.cli", "task=train",
         f"data={train}", "label_column=0", "objective=binary",
         "num_iterations=5", "num_leaves=7", f"output_model={model}",
         "verbose=-1"], env=env, capture_output=True, text=True,
        timeout=600, cwd=tmp_path)
    assert r.returncode == 0, r.stderr[-2000:]

    cpp = tmp_path / "model.cpp"
    r = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu.cli", "task=convert_model",
         f"input_model={model}", f"convert_model={cpp}"],
        env=env, capture_output=True, text=True, timeout=600,
        cwd=tmp_path)
    assert r.returncode == 0, r.stderr[-2000:]
    code = cpp.read_text()
    assert "PredictTree0" in code and "void Predict(" in code

    r = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu.cli", "task=save_binary",
         f"data={train}", "label_column=0", "verbose=-1"],
        env=env, capture_output=True, text=True, timeout=600,
        cwd=tmp_path)
    assert r.returncode == 0, r.stderr[-2000:]
    binfile = str(train) + ".bin"
    assert os.path.exists(binfile)
    # the binary cache round-trips as a Dataset
    ds2 = lgb.Dataset(binfile, params={"verbose": -1})
    ds2.construct()
    assert ds2._inner.num_data == 1200
