"""Metric tests vs sklearn/numpy oracles (ref: src/metric/)."""
import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.dataset import Metadata
from lightgbm_tpu.metric import create_metric


def setup_metric(name, label, params=None, weight=None, group=None):
    cfg = Config(dict(params or {}))
    m = create_metric(name, cfg)
    md = Metadata(len(label))
    md.set_label(np.asarray(label, np.float32))
    if weight is not None:
        md.set_weight(weight)
    if group is not None:
        md.set_group(group)
    m.init(md, len(label))
    return m


def test_l2_rmse_l1():
    rng = np.random.RandomState(0)
    y = rng.randn(100)
    s = rng.randn(1, 100)
    assert setup_metric("l2", y).eval(s, None)[0] == \
        pytest.approx(np.mean((s[0] - y) ** 2))
    assert setup_metric("rmse", y).eval(s, None)[0] == \
        pytest.approx(np.sqrt(np.mean((s[0] - y) ** 2)))
    assert setup_metric("l1", y).eval(s, None)[0] == \
        pytest.approx(np.mean(np.abs(s[0] - y)))


def test_weighted_l2():
    y = np.array([0.0, 0.0])
    s = np.array([[1.0, 2.0]])
    w = np.array([3.0, 1.0], np.float32)
    assert setup_metric("l2", y, weight=w).eval(s, None)[0] == \
        pytest.approx((3 * 1 + 1 * 4) / 4)


def test_auc_vs_sklearn():
    from sklearn.metrics import roc_auc_score
    rng = np.random.RandomState(1)
    y = (rng.rand(500) > 0.5).astype(float)
    s = rng.randn(1, 500) + y * 0.8
    ours = setup_metric("auc", y).eval(s, None)[0]
    assert ours == pytest.approx(roc_auc_score(y, s[0]), abs=1e-9)


def test_weighted_auc_vs_sklearn():
    from sklearn.metrics import roc_auc_score
    rng = np.random.RandomState(2)
    y = (rng.rand(300) > 0.4).astype(float)
    s = rng.randn(1, 300) + y
    w = rng.rand(300).astype(np.float32) + 0.1
    ours = setup_metric("auc", y, weight=w).eval(s, None)[0]
    assert ours == pytest.approx(
        roc_auc_score(y, s[0], sample_weight=w), abs=1e-6)


def test_auc_with_ties():
    y = np.array([1.0, 0.0, 1.0, 0.0])
    s = np.array([[0.5, 0.5, 0.5, 0.5]])
    assert setup_metric("auc", y).eval(s, None)[0] == pytest.approx(0.5)


def test_binary_logloss():
    from sklearn.metrics import log_loss
    rng = np.random.RandomState(3)
    y = (rng.rand(200) > 0.5).astype(float)
    raw = rng.randn(1, 200)

    class FakeObj:
        @staticmethod
        def convert_output(r):
            return 1 / (1 + np.exp(-r))
    ours = setup_metric("binary_logloss", y).eval(raw, FakeObj)[0]
    assert ours == pytest.approx(log_loss(y, 1 / (1 + np.exp(-raw[0]))),
                                 rel=1e-6)


def test_multi_error_ties_count_as_errors():
    y = np.array([0.0, 1.0])
    s = np.array([[0.5, 0.5], [0.5, 0.5]])  # all tied
    cfg_err = setup_metric("multi_error", y, {"num_class": 2})
    assert cfg_err.eval(s, None)[0] == pytest.approx(1.0)


def test_average_precision_vs_sklearn():
    from sklearn.metrics import average_precision_score
    rng = np.random.RandomState(4)
    y = (rng.rand(300) > 0.6).astype(float)
    s = rng.randn(1, 300) + y * 0.7
    ours = setup_metric("average_precision", y).eval(s, None)[0]
    assert ours == pytest.approx(average_precision_score(y, s[0]), abs=1e-6)


def test_ndcg_perfect_ranking_is_one():
    y = np.array([3.0, 2.0, 1.0, 0.0] * 3)
    s = np.tile(np.array([4.0, 3.0, 2.0, 1.0]), 3)[None, :]
    m = setup_metric("ndcg", y, {"eval_at": [4]}, group=[4, 4, 4])
    assert m.eval(s, None)[0] == pytest.approx(1.0)


def test_ndcg_at_k_form():
    m = setup_metric("ndcg@2", np.array([1.0, 0.0]), group=[2])
    assert m.names == ["ndcg@2"]


def test_map_simple():
    # one query: relevant docs ranked 1st and 3rd -> AP@3 = (1 + 2/3)/2
    y = np.array([1.0, 0.0, 1.0])
    s = np.array([[3.0, 2.0, 1.0]])
    m = setup_metric("map", y, {"eval_at": [3]}, group=[3])
    assert m.eval(s, None)[0] == pytest.approx((1.0 + 2.0 / 3.0) / 2.0)


def test_kullback_leibler_zero_for_perfect():
    y = np.array([1.0, 0.0, 1.0])
    s = np.array([[100.0, -100.0, 100.0]])
    m = setup_metric("kullback_leibler", y)
    assert m.eval(s, None)[0] == pytest.approx(0.0, abs=1e-6)


def test_metric_aliases():
    y = np.random.RandomState(5).randn(50)
    s = np.random.RandomState(6).randn(1, 50)
    assert setup_metric("mse", y).eval(s, None) == \
        setup_metric("l2", y).eval(s, None)
    assert setup_metric("mae", y).eval(s, None) == \
        setup_metric("l1", y).eval(s, None)
