"""Frontier-v2 grower: structural invariants + agreement with the round-1
growers on the same data (CPU interpret mode)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lightgbm_tpu.models.frontier2 import (add_leaf_values_to_score,
                                           grow_tree_fused, level_caps)
from lightgbm_tpu.models.learner import FeatureMeta, grow_tree_leafwise
from lightgbm_tpu.ops.fused_level import feature_layout, pack_gh
from lightgbm_tpu.ops.split import SplitParams


def _data(R=2048, F=6, B=32, seed=0):
    rng = np.random.RandomState(seed)
    bins = rng.randint(0, B - 1, size=(R, F)).astype(np.int8)
    y = ((bins[:, 0] > 12).astype(np.float32)
         + 0.5 * (bins[:, 1] > 20) + 0.1 * rng.randn(R))
    grad = (y - y.mean()).astype(np.float32) * -1.0
    hess = np.ones(R, np.float32)
    return bins, grad, hess


def _grow(bins, grad, hess, num_leaves=15, B=32, max_depth=-1):
    R, F = bins.shape
    F_oh, Bp = feature_layout(F, B)
    Rp = ((R + 1023) // 1024) * 1024
    Fp = max(F_oh, 8)
    bins_T = np.zeros((Fp, Rp), np.int8)
    bins_T[:F, :R] = bins.T
    gpad = np.zeros(Rp, np.float32)
    gpad[:R] = grad
    hpad = np.zeros(Rp, np.float32)
    hpad[:R] = hess
    wpad = np.zeros(Rp, np.float32)
    wpad[:R] = 1.0
    gh_T = pack_gh(jnp.asarray(gpad), jnp.asarray(hpad), jnp.asarray(wpad), 5)

    nb = np.zeros(F_oh, np.int32)
    nb[:F] = B
    meta = FeatureMeta(
        num_bin=jnp.asarray(nb),
        missing_type=jnp.zeros(F_oh, jnp.int32),
        default_bin=jnp.zeros(F_oh, jnp.int32),
        monotone=jnp.zeros(F_oh, jnp.int32))
    fmask = jnp.asarray(np.arange(F_oh) < F)
    params = SplitParams(min_data_in_leaf=5)
    tree, row_leaf = grow_tree_fused(
        jnp.asarray(bins_T), gh_T, meta, fmask, params, num_leaves, B,
        F_oh, nch=5, max_depth=max_depth, extra_levels=2, interpret=True)
    return jax.device_get(tree), np.asarray(row_leaf)[:R]


def _route_rows_np(tree, bins):
    """Walk TreeArrays on host to recompute row->leaf."""
    R = bins.shape[0]
    out = np.zeros(R, np.int32)
    nl = int(tree.num_leaves)
    if nl == 1:
        return out
    for r in range(R):
        node = 0
        for _ in range(nl):
            f = tree.split_feature[node]
            go_left = bins[r, f] <= tree.threshold_bin[node]
            nxt = tree.left_child[node] if go_left else tree.right_child[node]
            if nxt < 0:
                out[r] = -nxt - 1
                break
            node = nxt
    return out


def test_level_caps():
    assert level_caps(255, -1, 3) == (1, 2, 4, 8, 16, 32, 64, 128,
                                      64, 64, 64)
    # extras survive a positive max_depth: the runtime depth/gain masks
    # skip them when nothing can split
    assert level_caps(31, 4, 3) == (1, 2, 4, 8, 30, 30, 30)
    assert level_caps(2, -1, 0) == (1,)


def test_structure_and_routing():
    bins, grad, hess = _data()
    tree, row_leaf = _grow(bins, grad, hess, num_leaves=15)
    nl = int(tree.num_leaves)
    assert nl > 8  # separable data must split plenty
    want = _route_rows_np(tree, bins)
    np.testing.assert_array_equal(row_leaf, want)
    # leaf counts match the actual partition
    counts = np.bincount(row_leaf, minlength=15)
    np.testing.assert_allclose(tree.leaf_count[:nl], counts[:nl], atol=0.5)
    # every internal node has valid children
    for i in range(nl - 1):
        assert tree.left_child[i] != tree.right_child[i]


def test_loss_reduction_close_to_leafwise():
    bins, grad, hess = _data(R=4096)
    tree, row_leaf = _grow(bins, grad, hess, num_leaves=15)
    nl = int(tree.num_leaves)
    # training L2 proxy: sum over leaves of -G^2/H after vs before
    def tree_gain(t, rl, nleaf):
        g = 0.0
        for l in range(nleaf):
            m = rl == l
            if m.sum():
                g += (grad[m].sum() ** 2) / (hess[m].sum() + 1e-9)
        return g

    gain_fused = tree_gain(tree, row_leaf, nl)

    R, F = bins.shape
    meta = FeatureMeta(
        num_bin=jnp.full((F,), 32, jnp.int32),
        missing_type=jnp.zeros(F, jnp.int32),
        default_bin=jnp.zeros(F, jnp.int32),
        monotone=jnp.zeros(F, jnp.int32))
    t2, rl2 = grow_tree_leafwise(
        jnp.asarray(bins.astype(np.int32)),
        jnp.asarray(np.stack([grad, hess, np.ones_like(grad)], 1)),
        meta, jnp.ones((F,), bool), SplitParams(min_data_in_leaf=5),
        15, 32, hist_impl="onehot")
    gain_leaf = tree_gain(jax.device_get(t2), np.asarray(rl2),
                          int(t2.num_leaves))
    assert gain_fused >= 0.9 * gain_leaf


def test_max_depth_respected():
    bins, grad, hess = _data()
    tree, _ = _grow(bins, grad, hess, num_leaves=31, max_depth=3)
    nl = int(tree.num_leaves)
    assert nl <= 8
    assert int(tree.leaf_depth[:nl].max()) <= 3


def test_score_update():
    bins, grad, hess = _data(R=1024)
    tree, row_leaf = _grow(bins, grad, hess, num_leaves=7)
    Rp = 1024
    score = jnp.zeros((Rp,), jnp.float32)
    s2 = add_leaf_values_to_score(
        score, jnp.asarray(row_leaf), jnp.asarray(tree.leaf_value), 0.1,
        interpret=True)
    want = 0.1 * tree.leaf_value[row_leaf]
    np.testing.assert_allclose(np.asarray(s2), want, rtol=1e-6)
