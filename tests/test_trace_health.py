"""Observability tier 2: trace export, health auditing, numerical
guards, crash flight recorder (ISSUE 4).

Covers the Chrome-trace exporter (valid JSON, per-rank tracks, span
nesting), the cross-rank health auditor (unit-level divergence /
straggler detection plus a forced divergence on the two-process
driver), NaN/Inf guard anomaly events, the crash dump, the JsonlSink
re-open lifecycle, and scripts/bench_compare.py.
"""
import json
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs import Telemetry, chrome_trace_events
from lightgbm_tpu.obs.health import HealthAuditor, model_state_hash

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _data(n=500, f=6, seed=9):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float32)
    return X, y


def _load_trace(path):
    """A trace_out file must be a loadable Chrome-trace JSON object with
    a traceEvents list (the contract chrome://tracing / ui.perfetto.dev
    relies on)."""
    with open(path) as fh:
        doc = json.load(fh)
    assert isinstance(doc, dict) and isinstance(doc["traceEvents"], list)
    return doc["traceEvents"]


# ---------------------------------------------------------------- trace
def test_chrome_trace_events_unit():
    """Exporter pure function: rank -> pid, track -> named tid, X spans
    in microseconds, zero-duration records as instants."""
    spans = [
        [{"name": "iteration", "ts": 10.0, "dur": 0.5, "rank": 0,
          "track": "train", "iter": 0},
         {"name": "histogram_split", "ts": 10.1, "dur": 0.2, "rank": 0,
          "track": "train", "iter": 0},
         {"name": "psum_data", "ts": 10.2, "dur": 0.0, "rank": 0,
          "track": "collectives", "args": {"bytes": 64}}],
        [{"name": "iteration", "ts": 10.0, "dur": 0.6, "rank": 1,
          "track": "train", "iter": 0}],
    ]
    events = chrome_trace_events(spans)
    meta = [e for e in events if e["ph"] == "M"]
    names = {(e["pid"], e["name"], json.dumps(e["args"])) for e in meta}
    assert any(e["name"] == "process_name"
               and e["args"]["name"] == "rank 0" and e["pid"] == 0
               for e in meta)
    assert any(e["name"] == "process_name"
               and e["args"]["name"] == "rank 1" and e["pid"] == 1
               for e in meta), names
    xs = [e for e in events if e["ph"] == "X"]
    assert {e["pid"] for e in xs} == {0, 1}
    it0 = next(e for e in xs if e["pid"] == 0 and e["name"] == "iteration")
    assert it0["ts"] == pytest.approx(10.0 * 1e6)
    assert it0["dur"] == pytest.approx(0.5 * 1e6)
    assert it0["args"]["iter"] == 0
    # the zero-duration collective renders as an instant, on its own tid
    inst = next(e for e in events if e["ph"] == "i")
    assert inst["name"] == "psum_data" and inst["args"]["bytes"] == 64
    assert inst["tid"] != it0["tid"]


def test_trace_out_writes_loadable_timeline(tmp_path):
    trace = tmp_path / "run.trace.json"
    X, y = _data()
    lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1,
               "trace_out": str(trace)},
              lgb.Dataset(X, label=y), num_boost_round=4)
    events = _load_trace(trace)
    xs = [e for e in events if e["ph"] == "X"]
    iters = [e for e in xs if e["name"] == "iteration"]
    assert [e["args"]["iter"] for e in iters] == [0, 1, 2, 3]
    # driver sections nest inside their iteration span: same pid/tid,
    # start at/after the iteration start, end at/before its end (1ms
    # slack: section edges use perf_counter durations on a time.time
    # base)
    slack = 1e3  # µs
    for sec_name in ("histogram_split", "score_update", "boosting"):
        secs = [e for e in xs if e["name"] == sec_name]
        assert secs, f"no {sec_name} spans in trace"
        for s in secs:
            it = next(e for e in iters
                      if e["args"]["iter"] == s["args"]["iter"])
            assert s["pid"] == it["pid"] and s["tid"] == it["tid"]
            assert s["ts"] >= it["ts"] - slack
            assert s["ts"] + s["dur"] <= it["ts"] + it["dur"] + slack
    # iteration 0 compiles: the compile track carries back-dated spans
    compiles = [e for e in xs if str(e["name"]).startswith("compile:")]
    assert compiles, "no compile spans on the compile track"
    assert {e["cat"] for e in compiles} == {"compile"}


def test_trace_without_telemetry_out_needs_no_jsonl(tmp_path):
    """trace_out alone enables the registry sink-less — no JSONL file
    appears, the trace still does."""
    trace = tmp_path / "t.json"
    X, y = _data(n=300)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbose": -1, "trace_out": str(trace)},
                    lgb.Dataset(X, label=y), num_boost_round=2)
    assert trace.exists()
    assert bst.telemetry()["enabled"]
    assert not list(tmp_path.glob("*.jsonl"))


# --------------------------------------------------------------- health
def test_model_state_hash_detects_model_change_and_fault(monkeypatch):
    X, y = _data(n=400)
    b1 = lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1},
                   lgb.Dataset(X, label=y), num_boost_round=2)
    models = b1._gbdt.models
    assert model_state_hash(models) == model_state_hash(models)
    b2 = lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1,
                    "learning_rate": 0.31},
                   lgb.Dataset(X, label=y), num_boost_round=2)
    assert model_state_hash(models) != model_state_hash(b2._gbdt.models)
    # fault injection salts exactly the matching rank's digest
    clean = model_state_hash(models, rank=1)
    monkeypatch.setenv("LIGHTGBM_TPU_HEALTH_FAULT_RANK", "1")
    assert model_state_hash(models, rank=1) != clean
    assert model_state_hash(models, rank=0) == model_state_hash(models)


def test_health_auditor_unit_divergence_and_straggler(monkeypatch):
    """Unit-level audit round against a faked 3-rank gather: a diverging
    hash yields rank_divergence, a 4x slow section yields a straggler
    event naming the slowest rank."""
    import lightgbm_tpu.obs.registry as registry

    tel = Telemetry()
    tel.enable()
    tel._rank = 0

    def fake_gather(local):
        others = [dict(local, rank=1),
                  dict(local, rank=2,
                       hash="deadbeef" * 8,
                       sections={"histogram_split": 0.4,
                                 "score_update": 0.01})]
        return [local] + others

    monkeypatch.setattr(registry, "allgather_json", fake_gather)
    aud = HealthAuditor(tel, period=2, skew_threshold=2.0)
    assert not aud.due(0) and aud.due(1)
    ok = aud.check(1, [], sections={"histogram_split": 0.1,
                                    "score_update": 0.01})
    assert ok is False
    snap = tel.snapshot()
    assert snap["counters"]["health.checks"] == 1
    assert snap["counters"]["health.rank_divergence"] == 1
    assert snap["counters"]["health.straggler"] >= 1
    events = {e["event"]: e for e in snap["events"]}
    assert events["health_check"]["ok"] is False
    assert set(events["rank_divergence"]["hashes"]) == {"0", "1", "2"}
    strag = [e for e in snap["events"] if e["event"] == "straggler"]
    assert any(e["section"] == "histogram_split"
               and e["slowest_rank"] == 2 and e["skew"] >= 2.0
               for e in strag), strag
    assert snap["gauges"]["health.skew.histogram_split"] >= 2.0


def test_health_check_period_single_process(tmp_path):
    """End-to-end single process: checks fire on the configured period
    and agree (one rank can't diverge from itself)."""
    out = tmp_path / "tel.jsonl"
    X, y = _data()
    bst = lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1,
                     "telemetry_out": str(out), "health_check_period": 2},
                    lgb.Dataset(X, label=y), num_boost_round=6)
    with open(out) as fh:
        recs = [json.loads(line) for line in fh]
    checks = [r for r in recs if r["event"] == "health_check"]
    assert [c["iter"] for c in checks] == [1, 3, 5]
    assert all(c["ok"] for c in checks)
    assert not any(r["event"] in ("rank_divergence", "straggler")
                   for r in recs)
    assert bst.telemetry()["counters"]["health.checks"] == 3


# ------------------------------------------------------ numerical guards
def test_nan_gradient_guard_emits_anomaly(tmp_path):
    """A custom objective injecting NaN gradients at iteration 1 must
    produce a structured anomaly event (and training must survive)."""
    out = tmp_path / "tel.jsonl"
    X, y = _data()
    calls = {"n": 0}

    def bad_fobj(preds, ds):
        grad = preds - ds.get_label()
        hess = np.ones_like(grad)
        if calls["n"] == 1:
            grad = grad.copy()
            grad[:7] = np.nan
        calls["n"] += 1
        return grad, hess

    result = {}
    lgb.train({"objective": "none", "num_leaves": 7, "verbose": -1,
               "telemetry_out": str(out)},
              lgb.Dataset(X, label=y), num_boost_round=3, fobj=bad_fobj,
              callbacks=[lgb.record_telemetry(result)])
    with open(out) as fh:
        recs = [json.loads(line) for line in fh]
    anomalies = [r for r in recs if r["event"] == "anomaly"
                 and r["kind"] == "nonfinite_grad_hess"]
    assert anomalies and anomalies[0]["iter"] == 1
    assert anomalies[0]["grad"] == 7 and anomalies[0]["hess"] == 0
    # record_telemetry surfaces the findings as a first-class list
    assert any(a["kind"] == "nonfinite_grad_hess"
               for a in result["anomalies"])


def test_split_gain_stats_in_iteration_records(tmp_path):
    out = tmp_path / "tel.jsonl"
    X, y = _data()
    lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1,
               "telemetry_out": str(out)},
              lgb.Dataset(X, label=y), num_boost_round=3)
    with open(out) as fh:
        iters = [json.loads(l) for l in fh
                 if '"iteration"' in l]
    iters = [r for r in iters if r["event"] == "iteration"]
    assert iters
    for r in iters:
        sg = r["split_gain"]
        assert sg["count"] > 0
        assert sg["min"] <= sg["mean"] <= sg["max"]


# --------------------------------------------------- crash flight recorder
def test_crash_flight_recorder(tmp_path):
    """An exception unwinding out of the train loop dumps
    <telemetry_out>.crash.json (ring buffer + section stack + config)
    before re-raising."""
    out = tmp_path / "tel.jsonl"
    X, y = _data()

    def exploding_fobj(preds, ds):
        if exploding_fobj.calls == 2:
            raise RuntimeError("injected-mid-train-failure")
        exploding_fobj.calls += 1
        grad = preds - ds.get_label()
        return grad, np.ones_like(grad)

    exploding_fobj.calls = 0
    with pytest.raises(RuntimeError, match="injected-mid-train-failure"):
        lgb.train({"objective": "none", "num_leaves": 7, "verbose": -1,
                   "telemetry_out": str(out)},
                  lgb.Dataset(X, label=y), num_boost_round=5,
                  fobj=exploding_fobj)
    crash = tmp_path / "tel.jsonl.crash.json"
    assert crash.exists(), "flight recorder wrote no crash dump"
    with open(crash) as fh:
        payload = json.load(fh)
    assert payload["rank"] == 0 and payload["iteration"] == 2
    exc = payload["exception"]
    assert exc["type"] == "RuntimeError"
    assert "injected-mid-train-failure" in exc["message"]
    assert any("exploding_fobj" in ln for ln in exc["traceback"])
    # the custom objective runs BEFORE the driver's sections, so the
    # stack is empty here (test_crash_dump_records_active_section covers
    # the in-section case)
    assert payload["telemetry"]["section_stack"] == []
    assert payload["config"]["telemetry_out"] == str(out)
    assert payload["config"]["num_iterations"] == 5
    # the ring buffer preserved the pre-crash iteration records
    events = payload["telemetry"]["events"]
    assert sum(1 for e in events if e["event"] == "iteration") == 2
    # and the JSONL stream was flushed, so both views agree
    with open(out) as fh:
        recs = [json.loads(line) for line in fh]
    assert sum(1 for r in recs if r["event"] == "iteration") == 2


def test_crash_dump_records_active_section(tmp_path, monkeypatch):
    """An exception INSIDE a driver section leaves that section on the
    dumped stack — the flight recorder's 'where training was'."""
    import lightgbm_tpu.boosting.gbdt as gbdt_mod

    out = tmp_path / "tel.jsonl"
    X, y = _data()
    orig = gbdt_mod.GBDT._to_host_tree

    def boom(self, tree, shrinkage):
        if self.iter == 1:
            raise RuntimeError("injected-materialize-failure")
        return orig(self, tree, shrinkage)

    monkeypatch.setattr(gbdt_mod.GBDT, "_to_host_tree", boom)
    with pytest.raises(RuntimeError, match="injected-materialize"):
        lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1,
                   "telemetry_out": str(out)},
                  lgb.Dataset(X, label=y), num_boost_round=3)
    with open(tmp_path / "tel.jsonl.crash.json") as fh:
        payload = json.load(fh)
    assert payload["iteration"] == 1
    assert payload["telemetry"]["section_stack"] == ["tree_materialize"]


def test_no_crash_dump_without_telemetry(tmp_path):
    X, y = _data(n=300)

    def bad_fobj(preds, ds):
        raise ValueError("boom")

    with pytest.raises(ValueError):
        lgb.train({"objective": "none", "num_leaves": 7, "verbose": -1},
                  lgb.Dataset(X, label=y), num_boost_round=2,
                  fobj=bad_fobj)
    assert not list(tmp_path.glob("*.crash.json*"))


# ------------------------------------------------------- sink lifecycle
def test_jsonl_sink_reopen_appends(tmp_path):
    from lightgbm_tpu.obs.events import JsonlSink

    path = str(tmp_path / "s.jsonl")
    s1 = JsonlSink(path)
    s1.write({"event": "first"})
    s1.close()
    # a later sink on the SAME path in this process appends — the
    # established stream is never clobbered (ISSUE 4 satellite)
    s2 = JsonlSink(path)
    s2.write({"event": "second"})
    s2.close()
    with open(path) as fh:
        events = [json.loads(l)["event"] for l in fh]
    assert events == ["first", "second"]


def test_enable_reenable_same_path_is_noop(tmp_path):
    path = str(tmp_path / "t.jsonl")
    tel = Telemetry()
    assert tel.enable(sink_path=path) is True
    sink = tel._sink
    # re-enable with the same path: same sink object, nothing re-attached
    assert tel.enable(sink_path=path) is False
    assert tel._sink is sink
    # a different path is a genuine re-target: old sink closed, new one on
    other = str(tmp_path / "u.jsonl")
    assert tel.enable(sink_path=other) is True
    assert tel.sink_path == other
    tel.event("after_retarget")
    tel.disable()
    with open(other) as fh:
        assert [json.loads(l)["event"] for l in fh] == ["after_retarget"]


def test_reset_parameter_reenable_preserves_stream(tmp_path):
    """The end-to-end lifecycle bug from the satellite: train, then
    reset_parameter(telemetry_out=<same path>) and keep training — the
    earlier records must survive."""
    out = tmp_path / "tel.jsonl"
    X, y = _data()
    bst = lgb.Booster(params={"objective": "binary", "num_leaves": 7,
                              "verbose": -1, "telemetry_out": str(out)},
                      train_set=lgb.Dataset(X, label=y))
    bst.update()
    bst.reset_parameter({"telemetry_out": str(out), "verbose": -1})
    bst.update()
    with open(out) as fh:
        recs = [json.loads(line) for line in fh]
    iters = [r["iter"] for r in recs if r["event"] == "iteration"]
    assert iters == [0, 1], f"re-enable clobbered the stream: {iters}"


# -------------------------------------------------------- bench compare
def _bench_compare(tmp_path, records, *extra):
    traj = tmp_path / "traj.jsonl"
    with open(traj, "w") as fh:
        for r in records:
            fh.write(json.dumps(r) + "\n")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "scripts",
                                      "bench_compare.py"),
         "--trajectory", str(traj), *extra],
        capture_output=True, text=True)
    return r, json.loads(r.stdout.strip().splitlines()[-1])


def test_bench_compare_flags_regression(tmp_path):
    prev = {"run_id": "a", "metric": "m", "value": 1.0,
            "phase_timings": {"GBDT::histogram_split":
                              {"total": 1.0, "count": 10},
                              "tiny": {"total": 1e-4, "count": 10}}}
    cur = {"run_id": "b", "metric": "m", "value": 1.30,
           "phase_timings": {"GBDT::histogram_split":
                             {"total": 2.0, "count": 10},
                             "tiny": {"total": 1e-2, "count": 10}}}
    r, rep = _bench_compare(tmp_path, [prev, cur], "--fail-on-regress")
    assert r.returncode == 1, r.stderr
    assert rep["status"] == "ok"
    names = {e["name"] for e in rep["regressions"]}
    assert names == {"m", "GBDT::histogram_split"}  # headline + phase
    assert rep["headline"]["ratio"] == pytest.approx(1.3)
    # sub-threshold / sub-min-seconds phases are not flagged
    assert "tiny" not in {e["name"] for e in rep["phases"]}


def test_bench_compare_ok_and_insufficient(tmp_path):
    rec = {"run_id": "a", "metric": "m", "value": 1.0,
           "phase_timings": {"p": {"total": 1.0, "count": 10}}}
    r, rep = _bench_compare(tmp_path, [rec], "--fail-on-regress")
    assert r.returncode == 0 and rep["status"] == "insufficient_history"
    faster = dict(rec, run_id="b", value=0.9)
    r, rep = _bench_compare(tmp_path, [rec, faster], "--fail-on-regress")
    assert r.returncode == 0 and rep["regressions"] == []


# ------------------------------------------------- two-process driver
_MP_WORKER = textwrap.dedent("""
    import json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=sys.argv[1],
        num_processes=int(sys.argv[2]), process_id=int(sys.argv[3]))
    import numpy as np
    import lightgbm_tpu as lgb

    path, tel_path, trace_path = sys.argv[4], sys.argv[5], sys.argv[6]
    ds = lgb.Dataset(path, params={"label_column": 0, "verbose": -1,
                                   "max_bin": 63})
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "learning_rate": 0.2, "tree_learner": "data",
                     "verbose": -1, "telemetry_out": tel_path,
                     "trace_out": trace_path,
                     "health_check_period": 2},
                    ds, num_boost_round=4)
""")


def test_multiproc_trace_and_forced_divergence(tmp_path):
    """Acceptance run: two-process driver with trace_out +
    health_check_period, rank 1's model hash salted via the fault env —
    rank 0's merged trace carries both ranks' tracks and every rank
    records the rank_divergence."""
    rng = np.random.RandomState(11)
    n, F = 2000, 6
    X = rng.rand(n, F)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float64)
    train = tmp_path / "train.csv"
    np.savetxt(train, np.column_stack([y, X]), delimiter=",", fmt="%.6f")

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    script = tmp_path / "worker.py"
    script.write_text(_MP_WORKER)
    tel_path = tmp_path / "tel.jsonl"
    trace_path = tmp_path / "run.trace.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO_ROOT,
               LIGHTGBM_TPU_HEALTH_FAULT_RANK="1")
    env.pop("XLA_FLAGS", None)
    procs = [subprocess.Popen(
        [sys.executable, str(script), coord, "2", str(i), str(train),
         str(tel_path), str(trace_path)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        for i in range(2)]
    for p in procs:
        out, err = p.communicate(timeout=600)
        assert p.returncode == 0, err.decode()[-3000:]

    # every rank holds the divergence evidence in its own stream
    for rank, path in enumerate([tel_path,
                                 tmp_path / "tel.jsonl.rank1"]):
        with open(path) as fh:
            recs = [json.loads(line) for line in fh]
        checks = [r for r in recs if r["event"] == "health_check"]
        assert [c["iter"] for c in checks] == [1, 3]
        assert all(c["ok"] is False and c["ranks"] == 2 for c in checks)
        divs = [r for r in recs if r["event"] == "rank_divergence"]
        assert divs, f"rank {rank} recorded no divergence"
        hashes = divs[0]["hashes"]
        assert set(hashes) == {"0", "1"} and hashes["0"] != hashes["1"]

    # rank 0 merged both ranks' spans into one timeline
    events = _load_trace(trace_path)
    assert not trace_path.with_name(trace_path.name + ".rank1").exists()
    proc_names = {e["args"]["name"] for e in events
                  if e.get("name") == "process_name"}
    assert proc_names == {"rank 0", "rank 1"}
    xs = [e for e in events if e["ph"] == "X"]
    for pid in (0, 1):
        names = {e["name"] for e in xs if e["pid"] == pid}
        assert "iteration" in names and "histogram_split" in names
        assert "health_check" in names
    # the REAL host-plane collectives of the multiproc layout show up as
    # timed spans on the collectives track
    assert any(e["cat"] == "collectives" and e["name"] == "host_allgather"
               for e in xs)
