"""Streaming out-of-core ingest (lightgbm_tpu/ingest/): chunked
bin-and-pack pipeline, sharded binary dataset cache, double-buffered
host->device prefetch.

The load-bearing contract: a model trained from the streamed and/or
cached path serializes BYTE-EQUAL to one trained from the monolithic
text load, while peak host-side chunk residency stays bounded
(max_live_chunks <= 2)."""
import json
import os
import pickle
import shutil
import struct

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.ingest.cache import (CacheError, cache_shard_path,
                                       load_dataset_cache, read_manifest)
from lightgbm_tpu.ingest.chunker import iter_chunks, scan_layout
from lightgbm_tpu.ingest.prefetch import stream_to_device


def _write_csv(path, X, y, header=False, sep=","):
    with open(path, "w") as f:
        if header:
            cols = ["label"] + [f"f{i}" for i in range(X.shape[1])]
            f.write(sep.join(cols) + "\n")
        for i in range(len(y)):
            vals = [f"{y[i]:g}"] + [
                "" if np.isnan(v) else repr(float(v)) for v in X[i]]
            f.write(sep.join(vals) + "\n")


def _data(R=700, F=6, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(R, F).astype(np.float32)
    X[::7, 2] = np.nan
    X[:, 4] = rng.randint(0, 4, R)      # low-cardinality column
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    return X, y


@pytest.fixture()
def csv_file(tmp_path):
    X, y = _data()
    p = str(tmp_path / "train.csv")
    _write_csv(p, X, y)
    return p, X, y


_PARAMS = {"objective": "binary", "max_bin": 63, "num_leaves": 15,
           "verbose": -1, "metric": "None", "bagging_fraction": 0.8,
           "bagging_freq": 2, "feature_fraction": 0.7,
           "min_data_in_leaf": 5}
_DS = {"max_bin": 63, "verbose": -1}
_STREAM = dict(_DS, two_round=True, ingest_chunk_rows=97)


def _model_str(bst):
    return bst.model_to_string(num_iteration=-1)


# ------------------------------------------------------------- chunker
@pytest.mark.parametrize("sep,header", [(",", False), ("\t", True)])
def test_chunker_matches_monolithic_dense(tmp_path, sep, header):
    from lightgbm_tpu.io.file_loader import load_text_file
    X, y = _data(R=311)
    p = str(tmp_path / "d.csv")
    _write_csv(p, X, y, header=header, sep=sep)
    Xm, ym, _ = load_text_file(p, label_column=0)
    layout = scan_layout(p)
    parts = [Xc for _, Xc, _ in iter_chunks(layout, 64)]
    whole = np.concatenate(parts, axis=0)
    # column 0 is the label in the raw parse
    np.testing.assert_array_equal(whole[:, 0], ym)
    np.testing.assert_array_equal(whole[:, 1:], Xm)


def test_chunker_matches_monolithic_libsvm(tmp_path):
    from lightgbm_tpu.io.file_loader import load_text_file
    X, y = _data(R=200)
    p = str(tmp_path / "d.svm")
    with open(p, "w") as f:
        for i in range(len(y)):
            toks = [f"{y[i]:g}"]
            for j, v in enumerate(X[i]):
                if not np.isnan(v) and v != 0:
                    toks.append(f"{j}:{v!r}")
            f.write(" ".join(toks) + "\n")
    Xm, ym, _ = load_text_file(p)
    layout = scan_layout(p)
    assert layout.is_libsvm
    Xs, ys = [], []
    for _, Xc, yc in iter_chunks(layout, 77):
        Xs.append(Xc)
        ys.append(yc)
    np.testing.assert_array_equal(np.concatenate(Xs), Xm)
    np.testing.assert_array_equal(np.concatenate(ys), ym)


def test_chunker_slice_with_whitespace_and_comment_lines(tmp_path):
    # a whitespace-only line is a DATA row (all-NaN) to the scan and
    # both parsers; the slice skipper must count it identically or
    # every rank>0 slice shifts (and indented '#' still means comment
    # only when '#' is the FIRST char)
    from lightgbm_tpu.io.file_loader import load_text_file
    p = str(tmp_path / "w.csv")
    with open(p, "w") as f:
        f.write("1,10\n# c\n2,20\n   \n3,30\n\n4,40\n")
    Xm, ym, _ = load_text_file(p, label_column=0)
    assert Xm.shape[0] == 5          # 4 numeric + 1 whitespace NaN row
    parts = [load_text_file(p, label_column=0, rank=r, num_machines=2)
             for r in range(2)]
    yall = np.concatenate([y for _, y, _ in parts])
    np.testing.assert_array_equal(np.nan_to_num(yall, nan=-9),
                                  np.nan_to_num(ym, nan=-9))
    layout = scan_layout(p)
    tail = np.concatenate([c for _, c, _ in iter_chunks(layout, 2, 3, 5)])
    np.testing.assert_array_equal(tail[:, 0], [3.0, 4.0])


def test_chunker_rank_slice(tmp_path):
    X, y = _data(R=250)
    p = str(tmp_path / "d.csv")
    _write_csv(p, X, y)
    layout = scan_layout(p)
    parts = [Xc for _, Xc, _ in iter_chunks(layout, 50, start_row=90,
                                            stop_row=201)]
    whole = np.concatenate(parts, axis=0)
    assert whole.shape[0] == 111
    np.testing.assert_array_equal(whole[:, 0], y[90:201])


# ------------------------------------------------- streamed bin parity
def test_streamed_bins_and_mappers_bit_identical(csv_file):
    from lightgbm_tpu.binning import mappers_digest
    p, X, y = csv_file
    mono = lgb.Dataset(p, params=dict(_DS)).construct()._inner
    streamed = lgb.Dataset(p, params=dict(_STREAM)).construct()._inner
    assert streamed.streamed
    assert mappers_digest(mono.mappers) == mappers_digest(streamed.mappers)
    np.testing.assert_array_equal(np.asarray(mono.bins),
                                  np.asarray(streamed.bins))
    np.testing.assert_array_equal(mono.metadata.label,
                                  streamed.metadata.label)
    stats = streamed.ingest_stats
    assert stats["chunks"] > 2 and stats["rows"] == 2 * 700
    assert stats["max_live_chunks"] <= 2


def test_streamed_categorical_matches_monolithic(csv_file):
    p, X, y = csv_file
    mono = lgb.Dataset(p, params=dict(_DS),
                       categorical_feature=[4]).construct()._inner
    st = lgb.Dataset(p, params=dict(_STREAM),
                     categorical_feature=[4]).construct()._inner
    np.testing.assert_array_equal(np.asarray(mono.bins),
                                  np.asarray(st.bins))
    assert bool(st.is_categorical[st.used_features.index(4)
                                  if 4 in st.used_features else 0]) == \
        bool(mono.is_categorical[mono.used_features.index(4)
                                 if 4 in mono.used_features else 0])


def test_streamed_sidecars(tmp_path):
    X, y = _data(R=300)
    p = str(tmp_path / "t.csv")
    _write_csv(p, X, y)
    rng = np.random.RandomState(3)
    w = rng.rand(300).astype(np.float64)
    np.savetxt(p + ".weight", w)
    mono = lgb.Dataset(p, params=dict(_DS)).construct()._inner
    st = lgb.Dataset(p, params=dict(_STREAM)).construct()._inner
    np.testing.assert_array_equal(mono.metadata.weight,
                                  st.metadata.weight)


# ------------------------------------------------- model bit-identity
def test_streamed_model_bit_identical_sync_driver(csv_file):
    p, _, _ = csv_file
    params = dict(_PARAMS, tpu_fast_path=False)
    m1 = lgb.train(dict(params), lgb.Dataset(p, params=dict(_DS)),
                   num_boost_round=10)
    m2 = lgb.train(dict(params), lgb.Dataset(p, params=dict(_STREAM)),
                   num_boost_round=10)
    assert _model_str(m1) == _model_str(m2)


def test_streamed_model_bit_identical_fast_path(csv_file):
    p, _, _ = csv_file
    m1 = lgb.train(dict(_PARAMS), lgb.Dataset(p, params=dict(_DS)),
                   num_boost_round=10)
    m2 = lgb.train(dict(_PARAMS), lgb.Dataset(p, params=dict(_STREAM)),
                   num_boost_round=10)
    assert _model_str(m1) == _model_str(m2)


def test_streamed_model_bit_identical_megastep(csv_file):
    # the megastep consumer (interpret-mode fused engine, explicit
    # opt-in off-TPU) must drain the same model whether the bins came
    # from the monolithic load or the chunked/cached ingest
    p, _, _ = csv_file
    params = dict(_PARAMS, tpu_engine="fused", tpu_megastep=True,
                  num_leaves=7)
    m1 = lgb.train(dict(params), lgb.Dataset(p, params=dict(_DS)),
                   num_boost_round=6)
    m2 = lgb.train(dict(params), lgb.Dataset(
        p, params=dict(_STREAM, save_binary=True)), num_boost_round=6)
    assert _model_str(m1) == _model_str(m2)


# ------------------------------------------------------------- cache
def test_cache_roundtrip_fields(tmp_path, csv_file):
    p, X, y = csv_file
    rng = np.random.RandomState(5)
    w = rng.rand(700)
    ds = lgb.Dataset(p, params=dict(_DS), weight=w)
    cp = str(tmp_path / "c.bin")
    ds.save_binary(cp)
    mono = ds._inner
    back = load_dataset_cache(cp)
    assert back.streamed and back.ingest_stats["cache_hit"] == 1
    assert isinstance(back.bins, np.memmap)
    np.testing.assert_array_equal(np.asarray(back.bins),
                                  np.asarray(mono.bins))
    np.testing.assert_array_equal(back.metadata.label, mono.metadata.label)
    np.testing.assert_array_equal(back.metadata.weight,
                                  mono.metadata.weight)
    assert back.feature_names == mono.feature_names
    assert back.used_features == mono.used_features
    m = read_manifest(cp)
    assert m["num_data"] == 700 and m["format_version"] == 2


def test_cache_hit_skips_text_parsing(tmp_path, csv_file, monkeypatch):
    p, _, _ = csv_file
    cp = str(tmp_path / "c.bin")
    lgb.Dataset(p, params=dict(_DS)).save_binary(cp)

    import lightgbm_tpu.io.file_loader as fl
    import lightgbm_tpu.native.loader as nl

    def _boom(*a, **k):
        raise AssertionError("text parser invoked on a cache hit")
    monkeypatch.setattr(fl, "load_text_file", _boom)
    monkeypatch.setattr(nl, "scan", _boom)
    ds = lgb.Dataset(cp, params={"verbose": -1})
    ds.construct()
    assert ds._inner.num_data == 700


def test_cache_model_bit_identity(tmp_path, csv_file):
    p, _, _ = csv_file
    cp = str(tmp_path / "c.bin")
    lgb.Dataset(p, params=dict(_DS)).save_binary(cp)
    m1 = lgb.train(dict(_PARAMS), lgb.Dataset(p, params=dict(_DS)),
                   num_boost_round=10)
    m2 = lgb.train(dict(_PARAMS), lgb.Dataset(cp, params={"verbose": -1}),
                   num_boost_round=10)
    assert _model_str(m1) == _model_str(m2)


def test_cache_corrupt_byte_detected(tmp_path, csv_file):
    p, _, _ = csv_file
    cp = str(tmp_path / "c.bin")
    lgb.Dataset(p, params=dict(_DS)).save_binary(cp)
    with open(cp, "r+b") as fh:
        fh.seek(64)
        b = fh.read(1)
        fh.seek(64)
        fh.write(bytes([b[0] ^ 0xFF]))
    with pytest.raises(CacheError, match="hash mismatch"):
        load_dataset_cache(cp)


def test_cache_truncation_detected(tmp_path, csv_file):
    p, _, _ = csv_file
    cp = str(tmp_path / "c.bin")
    lgb.Dataset(p, params=dict(_DS)).save_binary(cp)
    with open(cp, "r+b") as fh:
        fh.truncate(os.path.getsize(cp) - 33)
    with pytest.raises(CacheError):
        load_dataset_cache(cp)


def test_cache_version_mismatch_refused(tmp_path, csv_file):
    p, _, _ = csv_file
    cp = str(tmp_path / "c.bin")
    lgb.Dataset(p, params=dict(_DS)).save_binary(cp)
    with open(cp, "rb") as fh:
        data = fh.read()
    mf_len, magic = struct.unpack("<Q8s", data[-16:])
    mf = json.loads(data[-16 - mf_len:-16])
    mf["format_version"] = 99
    mfb = json.dumps(mf, sort_keys=True).encode()
    with open(cp, "wb") as fh:
        fh.write(data[:-16 - mf_len])
        fh.write(mfb)
        fh.write(struct.pack("<Q8s", len(mfb), magic))
    with pytest.raises(CacheError, match="version"):
        load_dataset_cache(cp)


def test_cache_rank_layout_refused(tmp_path, csv_file):
    p, _, _ = csv_file
    cp = str(tmp_path / "c.bin")
    lgb.Dataset(p, params=dict(_DS)).save_binary(cp)
    with pytest.raises(CacheError, match="world"):
        load_dataset_cache(cp, expect_world=4)
    assert cache_shard_path("x.bin", 1, 4) == "x.bin.rank1of4"
    assert cache_shard_path("x.bin", 0, 1) == "x.bin"


def test_legacy_v1_cache_still_loads(tmp_path, csv_file):
    p, _, _ = csv_file
    mono = lgb.Dataset(p, params=dict(_DS)).construct()._inner
    payload = {
        "version": 1, "bins": np.asarray(mono.bins),
        "mappers": [m.to_dict() for m in mono.mappers],
        "used_features": mono.used_features,
        "num_data": mono.num_data,
        "num_total_features": mono.num_total_features,
        "feature_names": mono.feature_names,
        "label": mono.metadata.label, "weight": None,
        "query_boundaries": None, "init_score": None,
        "monotone_constraints": None,
    }
    cp = str(tmp_path / "legacy.bin")
    with open(cp, "wb") as fh:
        fh.write(b"LGBMTPU1")
        pickle.dump(payload, fh, protocol=4)
    ds = lgb.Dataset(cp, params={"verbose": -1})
    ds.construct()
    np.testing.assert_array_equal(np.asarray(ds._inner.bins),
                                  np.asarray(mono.bins))


def test_auto_cache_hit_and_staleness(tmp_path):
    X, y = _data(R=400)
    p = str(tmp_path / "a.csv")
    _write_csv(p, X, y)
    params = dict(_DS, save_binary=True)
    ds1 = lgb.Dataset(p, params=dict(params))
    ds1.construct()
    cache = p + ".bin"
    assert os.path.exists(cache)
    # second construct with identical params/source: HIT
    ds2 = lgb.Dataset(p, params=dict(params))
    ds2.construct()
    assert ds2._inner.ingest_stats["cache_hit"] == 1
    np.testing.assert_array_equal(np.asarray(ds1._inner.bins),
                                  np.asarray(ds2._inner.bins))
    # a dataset-defining param change must MISS and rebuild (the
    # rebuild re-caches under the NEW params digest)
    ds3 = lgb.Dataset(p, params=dict(params, max_bin=31))
    ds3.construct()
    assert ds3._inner.ingest_stats is None \
        or ds3._inner.ingest_stats.get("cache_hit") != 1
    assert read_manifest(cache)["source"] is not None
    ds4 = lgb.Dataset(p, params=dict(params, max_bin=31))
    ds4.construct()
    assert ds4._inner.ingest_stats["cache_hit"] == 1
    # source edit must MISS too
    with open(p, "a") as fh:
        fh.write(",".join(["1"] + ["0.5"] * X.shape[1]) + "\n")
    ds5 = lgb.Dataset(p, params=dict(params, max_bin=31))
    ds5.construct()
    assert ds5._inner.num_data == 401     # rebuilt from the new text


# ----------------------------------------------------------- prefetch
def test_prefetch_identical_to_one_shot(csv_file):
    import jax.numpy as jnp
    p, _, _ = csv_file
    inner = lgb.Dataset(p, params=dict(_DS)).construct()._inner
    bins = np.asarray(inner.bins)
    out = stream_to_device(bins, 53)
    np.testing.assert_array_equal(np.asarray(out),
                                  np.asarray(jnp.asarray(bins)))
    assert out.dtype == jnp.asarray(bins).dtype


def test_prefetch_bounded_residency_counters(tmp_path, csv_file):
    p, _, _ = csv_file
    tel_path = str(tmp_path / "tel.jsonl")
    params = dict(_PARAMS, telemetry_out=tel_path)
    bst = lgb.train(dict(params),
                    lgb.Dataset(p, params=dict(_STREAM)),
                    num_boost_round=4)
    snap = bst.telemetry()
    c = snap.get("counters", {})
    g = snap.get("gauges", {})
    assert c.get("ingest.chunks", 0) > 0
    assert c.get("ingest.rows", 0) == 2 * 700    # two streaming passes
    assert c.get("prefetch.chunks", 0) > 1
    assert "prefetch.host_wait_ms" in c
    assert 0 < g.get("ingest.max_live_chunks", 99) <= 2
    events = [json.loads(line) for line in open(tel_path)]
    ing = [e for e in events if e.get("event") == "ingest"]
    assert ing and ing[0]["max_live_chunks"] <= 2


def test_prefetch_disabled_falls_back(csv_file):
    p, _, _ = csv_file
    m1 = lgb.train(dict(_PARAMS),
                   lgb.Dataset(p, params=dict(_STREAM)),
                   num_boost_round=5)
    m2 = lgb.train(dict(_PARAMS),
                   lgb.Dataset(p, params=dict(_STREAM,
                                              ingest_prefetch=False)),
                   num_boost_round=5)
    assert _model_str(m1) == _model_str(m2)


def test_cache_as_valid_set_requires_aligned_mappers(tmp_path, csv_file):
    from lightgbm_tpu import LightGBMError
    p, X, y = csv_file
    train_ds = lgb.Dataset(p, params=dict(_DS))
    # a cache built with reference= the training data aligns and works
    good = str(tmp_path / "valid_good.bin")
    lgb.Dataset(p, params=dict(_DS), reference=train_ds) \
        .construct()._inner.save_binary(good)
    bst = lgb.train(dict(_PARAMS), train_ds, num_boost_round=3,
                    valid_sets=[lgb.Dataset(good, reference=train_ds)])
    assert bst.num_trees() == 3
    # a cache binned standalone under DIFFERENT params must be refused
    bad = str(tmp_path / "valid_bad.bin")
    lgb.Dataset(p, params=dict(_DS, max_bin=17)).save_binary(bad)
    with pytest.raises(LightGBMError, match="different mappers"):
        lgb.Dataset(bad, reference=train_ds).construct()
    # ... and a REFERENCE-BINNED cache must never train standalone (its
    # bins follow another dataset's boundaries)
    with pytest.raises(LightGBMError, match="reference"):
        lgb.Dataset(good, params={"verbose": -1}).construct()


def test_auto_cache_provenance_mismatch_rebuilds(tmp_path):
    from lightgbm_tpu.ingest.cache import read_manifest as rm
    X, y = _data(R=300)
    p = str(tmp_path / "v.csv")
    _write_csv(p, X, y)
    train_ds = lgb.Dataset(p, params=dict(_DS))
    params = dict(_DS, save_binary=True)
    # sidecar written by a VALIDATION (reference-binned) construct...
    lgb.Dataset(p, params=dict(params), reference=train_ds).construct()
    assert rm(p + ".bin")["reference_binned"] is True
    # ...must MISS for a standalone construct of the same file (which
    # then re-caches with standalone provenance), never hit-and-raise
    ds = lgb.Dataset(p, params=dict(params))
    ds.construct()
    assert not ds._inner.reference_binned
    assert ds._inner.ingest_stats is None \
        or ds._inner.ingest_stats.get("cache_hit") != 1
    assert rm(p + ".bin")["reference_binned"] is False


def test_auto_cache_misses_on_categorical_change(tmp_path):
    # constructor-passed categoricals never reach the config key, so
    # the fingerprint hashes the RESOLVED index list — changing it must
    # MISS, not silently serve bins where the feature was (or was not)
    # categorical
    X, y = _data(R=300)
    p = str(tmp_path / "c.csv")
    _write_csv(p, X, y)
    params = dict(_DS, save_binary=True)
    lgb.Dataset(p, params=dict(params),
                categorical_feature=[4]).construct()
    ds2 = lgb.Dataset(p, params=dict(params))     # no categoricals now
    ds2.construct()
    assert ds2._inner.ingest_stats is None \
        or ds2._inner.ingest_stats.get("cache_hit") != 1
    ds3 = lgb.Dataset(p, params=dict(params))     # same resolution: HIT
    ds3.construct()
    assert ds3._inner.ingest_stats["cache_hit"] == 1


def test_auto_cache_stale_reference_miss_not_error(tmp_path):
    # a validation sidecar whose reference was rebuilt with different
    # binning must rebuild (best-effort path), never abort training
    X, y = _data(R=300)
    p = str(tmp_path / "v2.csv")
    _write_csv(p, X, y)
    params = dict(_DS, save_binary=True)
    t1 = lgb.Dataset(p, params=dict(_DS))
    lgb.Dataset(p, params=dict(params), reference=t1).construct()
    # reference rebuilt under different binning -> valid cache stale
    t2 = lgb.Dataset(p, params=dict(_DS, max_bin=17))
    v2 = lgb.Dataset(p, params=dict(params, max_bin=17), reference=t2)
    v2.construct()                                 # no raise
    assert v2._inner.num_data == 300


def test_rank_slice_clamped_when_machines_exceed_rows(tmp_path):
    from lightgbm_tpu.io.file_loader import (compute_rank_slice,
                                             load_text_file)
    X, y = _data(R=9)
    p = str(tmp_path / "tiny.csv")
    _write_csv(p, X, y)
    total = 0
    for r in range(8):
        sl = compute_rank_slice(p, 9, r, 8)
        assert sl.stop >= sl.start >= 0
        total += sl.stop - sl.start
        Xr, yr, _ = load_text_file(p, label_column=0, rank=r,
                                   num_machines=8)
        assert Xr.shape[0] == sl.stop - sl.start
    assert total == 9


def test_cache_write_failure_is_best_effort(tmp_path, monkeypatch,
                                            csv_file):
    from lightgbm_tpu.ingest.cache import CacheWriter
    p, _, _ = csv_file

    def _boom(self, packed):
        raise OSError(28, "No space left on device")
    monkeypatch.setattr(CacheWriter, "append_rows", _boom)
    # streamed build with a failing cache writer: warns and re-streams
    # into memory
    ds = lgb.Dataset(p, params=dict(_STREAM, save_binary=True))
    ds.construct()
    assert ds._inner.num_data == 700
    assert not os.path.exists(p + ".bin")
    # monolithic build with a failing post-hoc cache write: warns only
    ds2 = lgb.Dataset(p, params=dict(_DS, save_binary=True))
    ds2.construct()
    assert ds2._inner.num_data == 700


# ----------------------------------------------------------- multiproc
def test_launcher_sharded_cache_roundtrip(tmp_path):
    """The multiproc launcher routes through per-rank cache shards:
    run 1 (save_binary + two_round) writes <data>.bin.rank<r>of2 per
    rank; run 2 cache-HITS both shards and trains the identical
    model."""
    from lightgbm_tpu.parallel import train_distributed
    rng = np.random.RandomState(21)
    n, F = 1200, 5
    X = rng.rand(n, F)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float64)
    train = tmp_path / "train.csv"
    np.savetxt(train, np.column_stack([y, X]), delimiter=",",
               fmt="%.6f")
    params = {"objective": "binary", "num_leaves": 15,
              "learning_rate": 0.2, "verbose": -1}
    dsp = {"label_column": 0, "verbose": -1, "two_round": True,
           "ingest_chunk_rows": 256, "save_binary": True}
    bst1 = train_distributed(params, str(train), num_processes=2,
                             num_boost_round=5, devices_per_process=2,
                             dataset_params=dict(dsp), timeout=600)
    shards = [str(train) + f".bin.rank{r}of2" for r in range(2)]
    for s in shards:
        assert os.path.exists(s), s
        assert read_manifest(s)["world"] == 2
    mtimes = [os.path.getmtime(s) for s in shards]
    bst2 = train_distributed(params, str(train), num_processes=2,
                             num_boost_round=5, devices_per_process=2,
                             dataset_params=dict(dsp), timeout=600)
    # the caches were HIT, not rewritten
    assert [os.path.getmtime(s) for s in shards] == mtimes
    assert bst1.model_to_string(num_iteration=-1) \
        == bst2.model_to_string(num_iteration=-1)


# ----------------------------------------------------------- eligibility
def test_linear_tree_falls_back_to_monolithic(csv_file):
    p, _, _ = csv_file
    ds = lgb.Dataset(p, params=dict(_STREAM, linear_tree=True))
    ds.construct()
    # fell back: raw data retained for the ridge fits, not streamed
    assert not ds._inner.streamed
    assert ds._inner.raw_data is not None
