"""Monotone constraint enforcement with per-leaf bound propagation.

The adversarial case from VERDICT round 1: transitive violations across
the tree that a local left/right check provably misses (ref:
monotone_constraints.hpp BasicLeafConstraints + split-time clipping)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _adversarial(R=6000, seed=0):
    """y rises then falls in x0 (non-monotone), plus a confounder."""
    rng = np.random.RandomState(seed)
    x0 = rng.rand(R).astype(np.float32)
    x1 = rng.rand(R).astype(np.float32)
    y = (np.sin(3.0 * x0) + 0.3 * x1 + 0.05 * rng.randn(R)) \
        .astype(np.float32)
    return np.stack([x0, x1], 1), y


def _check_monotone(bst, n_grid=200):
    """Predictions must be non-decreasing in x0 for any fixed x1."""
    grid = np.linspace(0.01, 0.99, n_grid).astype(np.float32)
    worst = 0.0
    for x1 in (0.1, 0.5, 0.9):
        X = np.stack([grid, np.full(n_grid, x1, np.float32)], 1)
        p = bst.predict(X)
        worst = min(worst, float(np.min(np.diff(p))))
    return worst


@pytest.mark.parametrize("engine,policy", [("xla", "leafwise"),
                                           ("xla", "depthwise"),
                                           ("fused", "depthwise")])
def test_no_transitive_violation(engine, policy):
    X, y = _adversarial()
    ds = lgb.Dataset(X, label=y, params={"verbose": -1})
    bst = lgb.train({"objective": "regression", "num_leaves": 31,
                     "verbose": -1, "min_data_in_leaf": 10,
                     "monotone_constraints": [1, 0],
                     "grow_policy": policy, "tpu_engine": engine},
                    ds, num_boost_round=20)
    worst = _check_monotone(bst)
    assert worst >= -1e-6, f"monotone violation: {worst}"


def test_unconstrained_is_nonmonotone():
    """Sanity: without the constraint the same data must violate (the test
    above is vacuous otherwise)."""
    X, y = _adversarial()
    ds = lgb.Dataset(X, label=y, params={"verbose": -1})
    bst = lgb.train({"objective": "regression", "num_leaves": 31,
                     "verbose": -1, "min_data_in_leaf": 10},
                    ds, num_boost_round=20)
    assert _check_monotone(bst) < -1e-3


def test_monotone_penalty_discourages_root_split():
    X, y = _adversarial()
    # huge penalty: monotone feature splits near the root get ~zeroed
    ds = lgb.Dataset(X, label=y, params={"verbose": -1})
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "verbose": -1, "min_data_in_leaf": 10,
                     "monotone_constraints": [1, 0],
                     "monotone_penalty": 2.0},
                    ds, num_boost_round=1)
    root_feature = bst.dump_model()["tree_info"][0]["tree_structure"] \
        .get("split_feature")
    assert root_feature == 1  # x1 (unconstrained) wins the root


def _sweep_worst(bst, n_feat, rng, sweeps=200, pts=64):
    worst = 0.0
    for _ in range(sweeps):
        ctx = rng.rand(1, n_feat).repeat(pts, axis=0)
        ctx[:, 0] = np.linspace(0, 1, pts)
        worst = min(worst, float(np.diff(bst.predict(ctx)).min()))
    return worst


def test_basic_mode_is_globally_monotone():
    """The reference's basic rule fences BOTH children at
    mid=(l+r)/2 (BasicLeafConstraints::Update,
    monotone_constraints.hpp:488) — raw-output fences permit
    cross-subtree violations (round-3 fix)."""
    rng = np.random.RandomState(0)
    n = 6000
    X = rng.rand(n, 4)
    y = (2 * X[:, 0] + np.sin(6 * X[:, 1]) + 3 * X[:, 0] * X[:, 2]
         + 0.1 * rng.randn(n)).astype(np.float32)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "regression", "num_leaves": 31,
                     "verbose": -1, "monotone_constraints": [1, 0, 0, 0]},
                    ds, num_boost_round=30)
    assert _sweep_worst(bst, 4, rng) >= -1e-9


@pytest.mark.parametrize("engine,policy", [("xla", "leafwise"),
                                           ("xla", "depthwise"),
                                           ("fused", "depthwise")])
def test_intermediate_mode_monotone_and_tighter_fit(engine, policy):
    """VERDICT r2 #8 / r3 #6: intermediate mode — raw-output fences +
    region-aware cross-tree tightening + stale-leaf best-split recompute
    (ref: monotone_constraints.hpp:514 IntermediateLeafConstraints,
    serial_tree_learner.cpp:706-714) — on EVERY grower, including the
    flagship fused engine (level-synchronous bookkeeping via
    mono_inter_level_update). Must stay globally monotone while fitting
    BETTER than basic (less over-constraint)."""
    rng = np.random.RandomState(0)
    n = 6000
    X = rng.rand(n, 4)
    y = (2 * X[:, 0] + np.sin(6 * X[:, 1]) + 3 * X[:, 0] * X[:, 2]
         + 0.1 * rng.randn(n)).astype(np.float32)

    def tr(method):
        ds = lgb.Dataset(X, label=y)
        return lgb.train(
            {"objective": "regression", "num_leaves": 31, "verbose": -1,
             "monotone_constraints": [1, 0, 0, 0],
             "grow_policy": policy, "tpu_engine": engine,
             "monotone_constraints_method": method}, ds,
            num_boost_round=30)

    bb, bi = tr("basic"), tr("intermediate")
    assert _sweep_worst(bi, 4, rng) >= -1e-9
    mse_b = float(np.mean((bb.predict(X) - y) ** 2))
    mse_i = float(np.mean((bi.predict(X) - y) ** 2))
    assert mse_i < mse_b      # intermediate = strictly less over-constraint
    # models must actually differ (the recompute machinery engaged)
    assert not np.allclose(bb.predict(X), bi.predict(X))


def test_intermediate_stale_leaf_recompute_adversarial():
    """The seed-7 adversarial case from round 3's forensics: a leaf whose
    region a later split becomes strictly adjacent to must constrain that
    split's child outputs (the round-3 region bug left the fresh slot's
    upper region at the init placeholder, silently skipping the clip)."""
    rng = np.random.RandomState(7)
    n = 400
    X = rng.rand(n, 2)
    y = (2 * X[:, 0] + np.sin(8 * X[:, 1])
         + 2.5 * X[:, 0] * (X[:, 1] > .5)
         + .1 * rng.randn(n)).astype(np.float32)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "regression", "num_leaves": 8,
                     "verbose": -1, "monotone_constraints": [1, 0],
                     "monotone_constraints_method": "intermediate",
                     "min_data_in_leaf": 5}, ds, num_boost_round=3)
    assert _sweep_worst(bst, 2, rng, sweeps=300) >= -1e-9


def test_advanced_mode_monotone_and_tighter_than_intermediate():
    """VERDICT r3 #6: advanced mode — per-(feature, bin-segment) bound
    planes (ref: monotone_constraints.hpp:856 AdvancedLeafConstraints).
    A child split away from the constraining neighbor's shadow escapes
    the bound, so advanced must stay globally monotone while fitting at
    least as well as intermediate — and strictly better here, where the
    signal needs exactly that escape (y jumps with x1 only where x1's
    neighbor region does not shadow)."""
    rng = np.random.RandomState(2)
    n = 6000
    X = rng.rand(n, 3)
    y = (1.5 * X[:, 0]
         + np.where(X[:, 1] > 0.5, 2.0 * X[:, 0] * X[:, 2], 0.0)
         + 0.05 * rng.randn(n)).astype(np.float32)

    def tr(method):
        ds = lgb.Dataset(X, label=y)
        return lgb.train(
            {"objective": "regression", "num_leaves": 31, "verbose": -1,
             "monotone_constraints": [1, 0, 0],
             "monotone_constraints_method": method}, ds,
            num_boost_round=30)

    bi, ba = tr("intermediate"), tr("advanced")
    assert ba._gbdt.mono_mode == "advanced"
    assert _sweep_worst(ba, 3, rng) >= -1e-9
    mse_i = float(np.mean((bi.predict(X) - y) ** 2))
    mse_a = float(np.mean((ba.predict(X) - y) ** 2))
    assert mse_a <= mse_i * 1.0001, (mse_a, mse_i)
    # the segment machinery must actually engage
    assert not np.allclose(ba.predict(X), bi.predict(X))


def test_advanced_mode_degrades_gracefully_on_depthwise():
    X, y = _adversarial()
    ds = lgb.Dataset(X, label=y, params={"verbose": -1})
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "verbose": -1, "monotone_constraints": [1, 0],
                     "grow_policy": "depthwise",
                     "monotone_constraints_method": "advanced"},
                    ds, num_boost_round=10)
    assert bst._gbdt.mono_mode == "intermediate"
    assert _check_monotone(bst) >= -1e-6


def test_intermediate_under_voting_parallel():
    """VERDICT r4 item 6: the intermediate recompute composes with
    voting-parallel — the stale-leaf rescan reads only globally-summed
    (vote-winner) pool columns via the validity plane; monotonicity must
    hold and the mode must not silently degrade to basic."""
    rng = np.random.RandomState(7)
    n = 4000
    X = rng.rand(n, 6)
    y = (2 * X[:, 0] + np.sin(8 * X[:, 1])
         + 2.5 * X[:, 0] * (X[:, 1] > .5)
         + .1 * rng.randn(n)).astype(np.float32)
    mono = [1, 0, 0, 0, 0, 0]
    params = {"objective": "regression", "num_leaves": 8, "verbose": -1,
              "monotone_constraints": mono,
              "monotone_constraints_method": "intermediate",
              "min_data_in_leaf": 5, "tree_learner": "voting", "top_k": 2}
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train(params, ds, num_boost_round=3)
    assert bst._gbdt.mono_mode == "intermediate"
    assert bst._gbdt.parallel_mode == "voting"
    assert _sweep_worst(bst, 6, rng, sweeps=300) >= -1e-9


def test_advanced_under_voting_parallel():
    """Advanced (bound planes) rides the leaf-wise grower, which voting
    composes with — monotone under a tight vote."""
    rng = np.random.RandomState(3)
    n = 4000
    X = rng.rand(n, 5)
    y = (1.5 * X[:, 0]
         + np.where(X[:, 1] > 0.5, 2.0 * X[:, 0] * X[:, 2], 0.0)
         + 0.05 * rng.randn(n)).astype(np.float32)
    params = {"objective": "regression", "num_leaves": 15, "verbose": -1,
              "monotone_constraints": [1, 0, 0, 0, 0],
              "monotone_constraints_method": "advanced",
              "tree_learner": "voting", "top_k": 2}
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train(params, ds, num_boost_round=10)
    assert bst._gbdt.mono_mode == "advanced"
    assert bst._gbdt.parallel_mode == "voting"
    assert _sweep_worst(bst, 5, rng) >= -1e-9


def test_intermediate_under_fused_feature_parallel():
    """Intermediate composes with fused feature-parallel (replicated
    layout keeps global per-feature leaf regions)."""
    rng = np.random.RandomState(11)
    n = 4096
    X = rng.rand(n, 6)
    y = (2 * X[:, 0] + np.sin(8 * X[:, 1])
         + .1 * rng.randn(n)).astype(np.float32)
    params = {"objective": "regression", "num_leaves": 8, "verbose": -1,
              "monotone_constraints": [1, 0, 0, 0, 0, 0],
              "monotone_constraints_method": "intermediate",
              "min_data_in_leaf": 5, "tree_learner": "feature",
              "tpu_engine": "fused"}
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train(params, ds, num_boost_round=3)
    assert bst._gbdt.mono_mode == "intermediate"
    assert bst._gbdt.parallel_mode == "feature"
    assert bst._gbdt.use_fused
    assert _sweep_worst(bst, 6, rng, sweeps=300) >= -1e-9
