"""Monotone constraint enforcement with per-leaf bound propagation.

The adversarial case from VERDICT round 1: transitive violations across
the tree that a local left/right check provably misses (ref:
monotone_constraints.hpp BasicLeafConstraints + split-time clipping)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _adversarial(R=6000, seed=0):
    """y rises then falls in x0 (non-monotone), plus a confounder."""
    rng = np.random.RandomState(seed)
    x0 = rng.rand(R).astype(np.float32)
    x1 = rng.rand(R).astype(np.float32)
    y = (np.sin(3.0 * x0) + 0.3 * x1 + 0.05 * rng.randn(R)) \
        .astype(np.float32)
    return np.stack([x0, x1], 1), y


def _check_monotone(bst, n_grid=200):
    """Predictions must be non-decreasing in x0 for any fixed x1."""
    grid = np.linspace(0.01, 0.99, n_grid).astype(np.float32)
    worst = 0.0
    for x1 in (0.1, 0.5, 0.9):
        X = np.stack([grid, np.full(n_grid, x1, np.float32)], 1)
        p = bst.predict(X)
        worst = min(worst, float(np.min(np.diff(p))))
    return worst


@pytest.mark.parametrize("engine,policy", [("xla", "leafwise"),
                                           ("xla", "depthwise"),
                                           ("fused", "depthwise")])
def test_no_transitive_violation(engine, policy):
    X, y = _adversarial()
    ds = lgb.Dataset(X, label=y, params={"verbose": -1})
    bst = lgb.train({"objective": "regression", "num_leaves": 31,
                     "verbose": -1, "min_data_in_leaf": 10,
                     "monotone_constraints": [1, 0],
                     "grow_policy": policy, "tpu_engine": engine},
                    ds, num_boost_round=20)
    worst = _check_monotone(bst)
    assert worst >= -1e-6, f"monotone violation: {worst}"


def test_unconstrained_is_nonmonotone():
    """Sanity: without the constraint the same data must violate (the test
    above is vacuous otherwise)."""
    X, y = _adversarial()
    ds = lgb.Dataset(X, label=y, params={"verbose": -1})
    bst = lgb.train({"objective": "regression", "num_leaves": 31,
                     "verbose": -1, "min_data_in_leaf": 10},
                    ds, num_boost_round=20)
    assert _check_monotone(bst) < -1e-3


def test_monotone_penalty_discourages_root_split():
    X, y = _adversarial()
    # huge penalty: monotone feature splits near the root get ~zeroed
    ds = lgb.Dataset(X, label=y, params={"verbose": -1})
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "verbose": -1, "min_data_in_leaf": 10,
                     "monotone_constraints": [1, 0],
                     "monotone_penalty": 2.0},
                    ds, num_boost_round=1)
    root_feature = bst.dump_model()["tree_info"][0]["tree_structure"] \
        .get("split_feature")
    assert root_feature == 1  # x1 (unconstrained) wins the root
