"""End-to-end GBDT training through the fused engine (tpu_engine=fused,
interpret mode on CPU) vs the default XLA engine."""
import numpy as np

import lightgbm_tpu as lgb


def _data(R=3000, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(R, 8).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] - 0.3 * X[:, 2] > 0).astype(np.float32)
    X[::23, 4] = np.nan
    return X, y


def _auc(y, p):
    from sklearn.metrics import roc_auc_score
    return roc_auc_score(y, p)


def test_fused_engine_trains_binary():
    X, y = _data()
    params = {"objective": "binary", "num_leaves": 15, "verbose": -1,
              "min_data_in_leaf": 5, "tpu_engine": "fused"}
    ds = lgb.Dataset(X, label=y, params={"verbose": -1})
    bst = lgb.train(params, ds, num_boost_round=15)
    auc_fused = _auc(y, bst.predict(X))

    params_ref = dict(params)
    params_ref["tpu_engine"] = "xla"
    ds2 = lgb.Dataset(X, label=y, params={"verbose": -1})
    bst2 = lgb.train(params_ref, ds2, num_boost_round=15)
    auc_ref = _auc(y, bst2.predict(X))

    assert auc_fused > 0.97
    assert auc_fused > auc_ref - 0.01


def test_fused_engine_regression_l2():
    rng = np.random.RandomState(1)
    X = rng.rand(2000, 6).astype(np.float32)
    y = (3 * X[:, 0] - 2 * X[:, 1] + 0.1 * rng.randn(2000)).astype(np.float32)
    ds = lgb.Dataset(X, label=y, params={"verbose": -1})
    bst = lgb.train({"objective": "regression", "num_leaves": 31,
                     "verbose": -1, "min_data_in_leaf": 5,
                     "tpu_engine": "fused"}, ds, num_boost_round=20)
    pred = bst.predict(X)
    mse = float(np.mean((pred - y) ** 2))
    assert mse < 0.05, mse


def test_fused_matches_xla_trees_first_iter():
    """First tree of fused vs xla depthwise engines must pick the same root
    split on clean data (same histograms -> same gain scan)."""
    X, y = _data(R=2000, seed=3)
    base = {"objective": "binary", "num_leaves": 7, "verbose": -1,
            "min_data_in_leaf": 5, "grow_policy": "depthwise"}
    models = {}
    for eng in ("fused", "xla"):
        p = dict(base)
        p["tpu_engine"] = eng
        ds = lgb.Dataset(X, label=y, params={"verbose": -1})
        bst = lgb.train(p, ds, num_boost_round=1)
        models[eng] = bst.dump_model()["tree_info"][0]["tree_structure"]

    def root(m):
        return (m["split_feature"], round(m["threshold"], 6))
    assert root(models["fused"]) == root(models["xla"])


def test_fused_engine_goss_and_rf():
    """GOSS sampling and random-forest mode run through the fused engine
    (host-driven sampling feeding the fused grower)."""
    rng = np.random.RandomState(5)
    X = rng.randn(3000, 6).astype(np.float32)
    y = (X[:, 0] - X[:, 1] > 0).astype(np.float32)
    from sklearn.metrics import roc_auc_score
    for boosting, extra in (("goss", {}),
                            ("rf", {"bagging_freq": 1,
                                    "bagging_fraction": 0.7})):
        ds = lgb.Dataset(X, label=y, params={"verbose": -1})
        bst = lgb.train(dict({"objective": "binary", "boosting": boosting,
                              "num_leaves": 15, "verbose": -1,
                              "min_data_in_leaf": 5,
                              "tpu_engine": "fused"}, **extra),
                        ds, num_boost_round=8)
        auc = roc_auc_score(y, bst.predict(X))
        assert auc > 0.9, (boosting, auc)


def test_fused_engine_multiclass_and_weights():
    rng = np.random.RandomState(6)
    X = rng.randn(2000, 5).astype(np.float32)
    y = np.argmax(X[:, :3] + 0.3 * rng.randn(2000, 3), axis=1)
    w = np.abs(rng.randn(2000)).astype(np.float32) + 0.1
    ds = lgb.Dataset(X, label=y, weight=w, params={"verbose": -1})
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "num_leaves": 15, "verbose": -1,
                     "min_data_in_leaf": 5, "tpu_engine": "fused"},
                    ds, num_boost_round=8)
    acc = (np.argmax(bst.predict(X), 1) == y).mean()
    assert acc > 0.85, acc


def test_fused_engine_quantile_renew():
    """Quantile objective's leaf renewal (host path) composes with the
    fused grower's device row_leaf."""
    rng = np.random.RandomState(7)
    X = rng.rand(2000, 4).astype(np.float32)
    y = (2 * X[:, 0] + rng.standard_exponential(2000) * 0.3) \
        .astype(np.float32)
    ds = lgb.Dataset(X, label=y, params={"verbose": -1})
    bst = lgb.train({"objective": "quantile", "alpha": 0.8,
                     "num_leaves": 15, "verbose": -1,
                     "min_data_in_leaf": 10, "tpu_engine": "fused"},
                    ds, num_boost_round=20)
    cover = float((y <= bst.predict(X)).mean())
    assert 0.7 < cover < 0.9, cover


def test_reset_parameter_callback_with_fused_engine():
    """Learning-rate schedules via reset_parameter recompile cleanly
    against the fused engine's cached jits."""
    rng = np.random.RandomState(8)
    X = rng.randn(1500, 4).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    ds = lgb.Dataset(X, label=y, params={"verbose": -1})
    bst = lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1,
                     "min_data_in_leaf": 5, "tpu_engine": "fused"},
                    ds, num_boost_round=6,
                    callbacks=[lgb.reset_parameter(
                        learning_rate=lambda i: 0.2 * (0.9 ** i))])
    from sklearn.metrics import roc_auc_score
    assert roc_auc_score(y, bst.predict(X)) > 0.95
