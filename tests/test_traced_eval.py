"""On-device eval inside the megastep (metric/traced.py + the
boosting/gbdt.py drain-replay path).

Two layers of coverage:

1. Metric parity — every traced metric evaluated directly (jit, no
   training) must match its f64 host implementation within float32
   tolerance, across regression / binary / multiclass / ranking shapes
   with weights and NaN-containing features.

2. Driver semantics — `lgb.train` with eval sets + the built-in
   callback set (early_stopping / log_evaluation / record_evaluation)
   stays on the megastep, replays callbacks at drain, and the
   early-stopped model is BIT-IDENTICAL to the synchronous driver's
   (identical params; the sync run is evicted by an extra opaque user
   callback, which is exactly the documented eviction rule).
"""
import json
import types

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import callback as cbm
from lightgbm_tpu.config import Config
from lightgbm_tpu.metric import create_metric
from lightgbm_tpu.metric.traced import build_traced_metric


def _metadata(label, weight=None, query_boundaries=None):
    return types.SimpleNamespace(label=np.asarray(label),
                                 weight=(np.asarray(weight)
                                         if weight is not None else None),
                                 query_boundaries=query_boundaries,
                                 query_row_map=None)


def _host_vs_traced(name, label, score, objective=None, weight=None,
                    query_boundaries=None, params=None, rtol=2e-5,
                    atol=1e-6):
    cfg = Config(dict(params or {}, verbose=-1))
    m = create_metric(name, cfg)
    m.init(_metadata(label, weight, query_boundaries), len(label))
    host = m.eval(np.asarray(score, np.float64), objective)
    tm = build_traced_metric(m, objective)
    assert tm is not None, f"{name} has no traced form"
    assert list(tm.names) == list(m.names)
    import jax
    traced = jax.jit(tm.fn)(np.asarray(score, np.float32), tm.ops)
    traced = [float(v) for v in jax.device_get(traced)]
    np.testing.assert_allclose(traced, host, rtol=rtol, atol=atol)
    return traced


def _binary_objective():
    from lightgbm_tpu.objective import create_objective
    cfg = Config({"objective": "binary", "verbose": -1})
    obj = create_objective(cfg)
    return obj, cfg


RNG = np.random.RandomState(7)
N = 500


# ---------------------------------------------------------------------------
# 1. metric parity: traced vs host, one metric at a time
# ---------------------------------------------------------------------------
def test_regression_metrics_parity():
    label = RNG.randn(N).astype(np.float32) * 3
    weight = RNG.rand(N).astype(np.float32) + 0.1
    score = (label + RNG.randn(N) * 0.5).astype(np.float32)[None, :]
    for name in ("l2", "rmse", "l1", "quantile", "huber", "mape"):
        _host_vs_traced(name, label, score, weight=weight)
        _host_vs_traced(name, label, score)   # unweighted


def test_binary_metrics_parity():
    obj, cfg = _binary_objective()
    label = (RNG.rand(N) > 0.4).astype(np.float32)
    obj.init(_metadata(label), N)
    weight = RNG.rand(N).astype(np.float32) + 0.1
    score = RNG.randn(1, N).astype(np.float32) * 2
    for name in ("binary_logloss", "binary_error", "auc"):
        _host_vs_traced(name, label, score, objective=obj, weight=weight)
        _host_vs_traced(name, label, score, objective=obj)


def test_auc_tie_handling_parity():
    label = (RNG.rand(N) > 0.5).astype(np.float32)
    score = RNG.randint(0, 5, N).astype(np.float32)[None, :]  # heavy ties
    _host_vs_traced("auc", label, score)


def test_multiclass_metrics_parity():
    from lightgbm_tpu.objective import create_objective
    nc = 4
    cfg = Config({"objective": "multiclass", "num_class": nc,
                  "verbose": -1})
    obj = create_objective(cfg)
    label = RNG.randint(0, nc, N).astype(np.float32)
    obj.init(_metadata(label), N)
    weight = RNG.rand(N).astype(np.float32) + 0.1
    score = RNG.randn(nc, N).astype(np.float32)
    for name in ("multi_logloss", "multi_error"):
        _host_vs_traced(name, label, score, objective=obj, weight=weight,
                        params={"num_class": nc})
    _host_vs_traced("multi_error", label, score, objective=obj,
                    params={"num_class": nc, "multi_error_top_k": 2})


def test_ndcg_parity():
    n_q = 40
    sizes = RNG.randint(1, 30, n_q)
    qb = np.concatenate([[0], np.cumsum(sizes)])
    n = int(qb[-1])
    label = RNG.randint(0, 4, n).astype(np.float32)
    # one all-zero-label query exercises the degenerate counts-as-1 path
    label[qb[0]:qb[1]] = 0.0
    score = RNG.randn(1, n).astype(np.float32)
    _host_vs_traced("ndcg", label, score, query_boundaries=qb,
                    params={"eval_at": [1, 3, 5]})


def test_untraceable_metric_rejected():
    cfg = Config({"verbose": -1})
    m = create_metric("gamma", cfg)   # no loss_jnp: host-only
    m.init(_metadata(np.ones(8, np.float32) + 1.0), 8)
    assert build_traced_metric(m, None) is None


# ---------------------------------------------------------------------------
# 2. driver semantics on the megastep
# ---------------------------------------------------------------------------
def _data(n=1200, f=8, seed=3, nan_frac=0.0):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float32)
    if nan_frac > 0:
        mask = rng.rand(n, f) < nan_frac
        mask[:, :2] &= rng.rand(n, 2) < 0.5   # keep signal columns usable
        X[mask] = np.nan
    return X, y


FUSED = {"objective": "binary", "num_leaves": 15, "learning_rate": 0.2,
         "verbose": -1, "min_data_in_leaf": 5, "tpu_engine": "fused",
         "tpu_megastep": True, "metric": ["binary_logloss", "auc"]}


def _train_pair(params, rounds, callbacks_extra=(), n_valid=2,
                nan_frac=0.0, seed=3):
    """(megastep booster, sync booster, megastep record, sync record):
    identical params both runs; the sync run carries one extra opaque
    callback, which is the documented megastep eviction and keeps the
    serialized parameter block byte-identical."""
    X, y = _data(seed=seed, nan_frac=nan_frac)
    valids = [_data(seed=11 + i, nan_frac=nan_frac) for i in range(n_valid)]

    def run(evict):
        d = lgb.Dataset(X, label=y)
        rec = {}
        cbs = [cbm.record_evaluation(rec)] + list(callbacks_extra)
        if evict:
            cbs.append(lambda env: None)    # opaque user callback
        b = lgb.train(dict(params), d, num_boost_round=rounds,
                      valid_sets=[lgb.Dataset(Xv, label=yv, reference=d)
                                  for Xv, yv in valids],
                      callbacks=cbs)
        return b, rec
    b1, r1 = run(False)
    b2, r2 = run(True)
    return b1, b2, r1, r2


def test_early_stopped_model_bit_identical_to_sync():
    params = dict(FUSED, early_stopping_round=5)
    b1, b2, r1, r2 = _train_pair(params, rounds=40)
    assert b1.best_iteration == b2.best_iteration > 0
    assert b1.num_trees() == b2.num_trees() < 40
    # the acceptance contract: serialized models (full AND
    # best-iteration-sliced) are byte-identical
    assert b1.model_to_string(num_iteration=-1) == \
        b2.model_to_string(num_iteration=-1)
    assert b1.model_to_string() == b2.model_to_string()
    # recorded curves: same length, f32-tolerance equal values
    for ds in r2:
        for m in r2[ds]:
            a, b = np.asarray(r1[ds][m]), np.asarray(r2[ds][m])
            assert len(a) == len(b)
            np.testing.assert_allclose(a, b, rtol=3e-5, atol=3e-7)


def test_first_metric_only_multi_eval_set():
    params = dict(FUSED, early_stopping_round=4, first_metric_only=True)
    b1, b2, r1, r2 = _train_pair(params, rounds=40)
    assert b1.best_iteration == b2.best_iteration > 0
    assert b1.model_to_string(num_iteration=-1) == \
        b2.model_to_string(num_iteration=-1)


def test_nan_features_megastep_eval():
    params = dict(FUSED, early_stopping_round=5)
    b1, b2, r1, r2 = _train_pair(params, rounds=30, nan_frac=0.25)
    assert b1.best_iteration == b2.best_iteration
    assert b1.model_to_string(num_iteration=-1) == \
        b2.model_to_string(num_iteration=-1)


def test_multiclass_megastep_eval():
    rng = np.random.RandomState(5)
    n, f, nc = 900, 6, 3
    X = rng.rand(n, f).astype(np.float32)
    y = (X[:, 0] * 3).astype(np.int32).clip(0, nc - 1).astype(np.float32)
    Xv = rng.rand(400, f).astype(np.float32)
    yv = (Xv[:, 0] * 3).astype(np.int32).clip(0, nc - 1) \
        .astype(np.float32)
    params = {"objective": "multiclass", "num_class": nc,
              "num_leaves": 7, "verbose": -1, "min_data_in_leaf": 5,
              "tpu_engine": "fused", "tpu_megastep": True,
              "metric": ["multi_logloss", "multi_error"],
              "early_stopping_round": 4}

    def run(evict):
        d = lgb.Dataset(X, label=y)
        rec = {}
        cbs = [cbm.record_evaluation(rec)]
        if evict:
            cbs.append(lambda env: None)
        b = lgb.train(dict(params), d, num_boost_round=12,
                      valid_sets=[lgb.Dataset(Xv, label=yv, reference=d)],
                      callbacks=cbs)
        return b, rec
    b1, r1 = run(False)
    b2, r2 = run(True)
    assert b1.best_iteration == b2.best_iteration
    assert b1.model_to_string(num_iteration=-1) == \
        b2.model_to_string(num_iteration=-1)
    np.testing.assert_allclose(r1["valid_0"]["multi_logloss"],
                               r2["valid_0"]["multi_logloss"],
                               rtol=3e-5, atol=3e-7)


def test_megastep_stays_on_with_builtin_callbacks(tmp_path):
    # the headline eligibility claim: eval sets + early_stopping +
    # log_evaluation + record_evaluation keep the megastep (dispatch
    # budget far under the sync driver's >= 3/iter)
    out = tmp_path / "tel.jsonl"
    X, y = _data()
    Xv, yv = _data(seed=11)
    Xv2, yv2 = _data(seed=12)
    d = lgb.Dataset(X, label=y)
    rec = {}
    b = lgb.train(dict(FUSED, early_stopping_round=25,
                       telemetry_out=str(out)),
                  d, num_boost_round=10,
                  valid_sets=[lgb.Dataset(Xv, label=yv, reference=d),
                              lgb.Dataset(Xv2, label=yv2, reference=d)],
                  callbacks=[cbm.log_evaluation(1),
                             cbm.record_evaluation(rec)])
    snap = b.telemetry()
    c = snap["counters"]
    assert c["iterations"] == 10
    assert c["train.dispatches"] / c["iterations"] <= 0.2
    assert len(rec["valid_0"]["binary_logloss"]) == 10
    assert b.best_iteration > 0   # "did not meet" still records best
    recs = [json.loads(line) for line in open(out)]
    evs = {r["event"] for r in recs}
    assert "megastep" in evs and "eval_batch" in evs
    # the run COMPLETED (stopping_rounds never hit): the callback's
    # final-iteration raise must not masquerade as a real early stop
    assert "early_stopping" not in evs
    eb = [r for r in recs if r["event"] == "eval_batch"]
    assert all(not r["stopped"] for r in eb)
    assert eb[0]["slots"] == ["valid_0/binary_logloss", "valid_0/auc",
                              "valid_1/binary_logloss", "valid_1/auc"]
    assert len(eb[0]["last"]) == 4
    # host-recomputed parity for the final iteration's logged values
    host = dict(
        (f"{ds}/{m}", v) for ds, m, v, _ in
        b.eval_valid())
    for slot, v in zip(eb[-1]["slots"], eb[-1]["last"]):
        np.testing.assert_allclose(v, host[slot], rtol=3e-5, atol=3e-7)


def test_chunk_of_one_flows_through_scan():
    # horizon tails force a length-1 megastep when a consumer is armed
    # (every iteration must flow through the scan for its metric row);
    # the drained [B=1, k, ...] entry must unstack its batch axis, not
    # be mistaken for a pipelined [k, ...] entry
    X, y = _data(n=400)
    Xv, yv = _data(n=300, seed=11)

    def run(evict):
        d = lgb.Dataset(X, label=y)
        rec = {}
        cbs = [cbm.record_evaluation(rec)]
        if evict:
            cbs.append(lambda env: None)
        b = lgb.train(dict(FUSED, tpu_megastep_iters=4), d,
                      num_boost_round=5,
                      valid_sets=[lgb.Dataset(Xv, label=yv,
                                              reference=d)],
                      callbacks=cbs)
        return b, rec
    b1, r1 = run(False)
    b2, r2 = run(True)
    assert b1.num_trees() == 5
    assert len(r1["valid_0"]["binary_logloss"]) == 5
    assert b1.model_to_string(num_iteration=-1) == \
        b2.model_to_string(num_iteration=-1)


def test_megastep_evicted_event_names_feature(tmp_path):
    out = tmp_path / "tel.jsonl"
    X, y = _data(n=600)
    Xv, yv = _data(n=400, seed=11)
    d = lgb.Dataset(X, label=y)
    lgb.train(dict(FUSED, telemetry_out=str(out)), d, num_boost_round=2,
              valid_sets=[lgb.Dataset(Xv, label=yv, reference=d)],
              callbacks=[lambda env: None])
    recs = [json.loads(line) for line in open(out)]
    ev = [r for r in recs if r["event"] == "megastep_evicted"]
    assert ev, recs
    assert ev[0]["feature"].startswith("callback:")


def test_megastep_evicted_event_names_feval(tmp_path):
    out = tmp_path / "tel.jsonl"
    X, y = _data(n=600)
    Xv, yv = _data(n=400, seed=11)
    d = lgb.Dataset(X, label=y)
    lgb.train(dict(FUSED, telemetry_out=str(out)), d, num_boost_round=2,
              valid_sets=[lgb.Dataset(Xv, label=yv, reference=d)],
              feval=lambda preds, ds: ("const", 1.0, True))
    recs = [json.loads(line) for line in open(out)]
    ev = [r for r in recs if r["event"] == "megastep_evicted"]
    assert any(r["feature"] == "feval" for r in ev), recs


def test_snapshots_written_at_drain(tmp_path):
    X, y = _data(n=600)
    Xv, yv = _data(n=400, seed=11)
    base = tmp_path / "model.txt"
    d = lgb.Dataset(X, label=y)
    b = lgb.train(dict(FUSED, snapshot_freq=3,
                       output_model=str(base)),
                  d, num_boost_round=7,
                  valid_sets=[lgb.Dataset(Xv, label=yv, reference=d)])
    assert b.num_trees() == 7
    for it in (3, 6):
        snap = tmp_path / f"model.txt.snapshot_iter_{it}"
        assert snap.exists(), f"missing snapshot at iteration {it}"
        bs = lgb.Booster(model_file=str(snap))
        assert bs.num_trees() == it


def test_booster_trainable_after_drain_replay_stop():
    # a drain-replayed early stop must leave the kept booster on the
    # normal one-iteration-per-update contract (the sync early-stop
    # path does); the internal stop latch is cleared at disarm
    X, y = _data(n=400)
    Xv, yv = _data(n=300, seed=11)
    d = lgb.Dataset(X, label=y)
    b = lgb.train(dict(FUSED, early_stopping_round=3,
                       min_sum_hessian_in_leaf=0.1), d,
                  num_boost_round=25,
                  valid_sets=[lgb.Dataset(Xv, label=yv, reference=d)],
                  keep_training_booster=True)
    n0 = b.num_trees()
    assert b.best_iteration > 0 and n0 < 25
    b.update()
    assert b.num_trees() == n0 + 1


def test_min_delta_evicts(tmp_path):
    out = tmp_path / "tel.jsonl"
    X, y = _data(n=600)
    Xv, yv = _data(n=400, seed=11)
    d = lgb.Dataset(X, label=y)
    b = lgb.train(dict(FUSED, telemetry_out=str(out)), d,
                  num_boost_round=6,
                  valid_sets=[lgb.Dataset(Xv, label=yv, reference=d)],
                  callbacks=[cbm.early_stopping(30, verbose=False,
                                                min_delta=0.01)])
    assert b.num_trees() == 6
    recs = [json.loads(line) for line in open(out)]
    ev = [r for r in recs if r["event"] == "megastep_evicted"]
    assert any("min_delta" in r["feature"] for r in ev), recs
