"""Live observability plane (obs/export.py + obs/reqtrace.py).

Tier-1 coverage of the OpenMetrics exporter (scrape-during-training,
scrape-during-serving, full-registry coverage, port-in-use fallback,
rank-distinct endpoints + rank-0 fleet aggregate under the two-process
driver), the request-scoped serving traces (exactly one ``serve_access``
record per request, trace_id threading into the Perfetto serve track),
per-device memory accounting, and the obs_tail operator tool.
"""
import importlib.util
import json
import os
import socket
import subprocess
import sys
import textwrap
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs import MetricsExporter, Telemetry
from lightgbm_tpu.obs.export import (CONTENT_TYPE, _metric_name,
                                     render_openmetrics)


def _data(n=600, f=6, seed=3):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float32)
    return X, y


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _scrape(port, path="/metrics", timeout=10):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout) as r:
        return r.headers.get("Content-Type", ""), r.read().decode()


def _parse_exposition(body):
    """Minimal OpenMetrics reader: {family: type} from # TYPE lines and
    {sample_name+labels: value} from sample lines; asserts basic
    well-formedness on the way."""
    types, samples = {}, {}
    lines = body.splitlines()
    assert lines[-1] == "# EOF", "exposition must end with # EOF"
    for line in lines[:-1]:
        assert line, "no blank lines inside the exposition"
        if line.startswith("# TYPE "):
            _, _, fam, mtype = line.split(" ", 3)
            types[fam] = mtype
        elif not line.startswith("#"):
            name_labels, value = line.rsplit(" ", 1)
            samples[name_labels] = float(value)
    return types, samples


# ---------------------------------------------------------------- unit
def test_render_openmetrics_unit():
    tel = Telemetry(enabled=True)
    tel.inc("serve.requests", 3)
    tel.gauge("mem.d0.bytes_in_use", 12345)
    tel.observe("section.boosting", 0.25)
    for v in (1.0, 2.0, 100.0):
        tel.dist("serve.latency_ms", v)
    body = render_openmetrics(tel.snapshot(),
                              {"rank": 0, "run_id": "r1"})
    types, samples = _parse_exposition(body)
    assert types["lgbm_serve_requests"] == "counter"
    assert samples['lgbm_serve_requests_total{rank="0",run_id="r1"}'] == 3
    assert types["lgbm_mem_d0_bytes_in_use"] == "gauge"
    assert types["lgbm_section_boosting_seconds"] == "summary"
    assert samples[
        'lgbm_section_boosting_seconds_count{rank="0",run_id="r1"}'] == 1
    assert types["lgbm_serve_latency_ms"] == "summary"
    assert samples['lgbm_serve_latency_ms{quantile="0.5",rank="0",'
                   'run_id="r1"}'] == 2.0
    assert samples[
        'lgbm_serve_latency_ms_count{rank="0",run_id="r1"}'] == 3
    assert samples['lgbm_serve_latency_ms_sum{rank="0",run_id="r1"}'] \
        == 103.0

    # fleet entries render under the same family with their own rank
    # label (and no run_id — the peers' run ids are not ours)
    body = render_openmetrics(
        tel.snapshot(), {"rank": 0, "run_id": "r1"},
        fleet=[{"rank": 1, "counters": {"serve.requests": 7}},
               {"rank": 0, "counters": {"serve.requests": 3}}])
    _, samples = _parse_exposition(body)
    assert samples['lgbm_serve_requests_total{rank="1"}'] == 7
    # the local rank's own series stays the live one, not the stale
    # allgathered copy
    assert samples['lgbm_serve_requests_total{rank="0",run_id="r1"}'] == 3


def test_render_sanitizes_names():
    assert _metric_name("events.megastep") == "lgbm_events_megastep"
    assert _metric_name("mem.d0.bytes_in_use") == \
        "lgbm_mem_d0_bytes_in_use"
    assert _metric_name("weird name-1!") == "lgbm_weird_name_1_"


# ---------------------------------------------------- training scrapes
def test_exporter_scrape_during_training(tmp_path):
    port = _free_port()
    X, y = _data()
    mid = {}

    def scrape_cb(env):
        if env.iteration == 2 and not mid:
            mid["ctype"], mid["body"] = _scrape(port)

    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbose": -1, "metrics_port": port,
                     "telemetry_out": str(tmp_path / "t.jsonl")},
                    lgb.Dataset(X, label=y), num_boost_round=5,
                    callbacks=[scrape_cb])
    try:
        # the mid-run scrape answered with valid, live OpenMetrics
        assert mid, "callback never scraped"
        assert mid["ctype"] == CONTENT_TYPE
        types, samples = _parse_exposition(mid["body"])
        assert types["lgbm_iterations"] == "counter"

        # post-train the endpoint is still live and the exposition
        # carries EVERY registry counter, gauge, timing and dist with
        # the rank/run_id labels (TTL cache off: the mid-train scrape
        # above may still be inside the ~1 s cache window, and this
        # assertion needs the LIVE body — the cache itself is covered
        # by test_control_plane.py)
        bst._gbdt._metrics.cache_ttl = 0.0
        _, body = _scrape(port)
        _parse_exposition(body)
        snap = bst.telemetry()
        labels = f'rank="0",run_id="{bst._gbdt.telemetry.run_id}"'
        for name, v in snap["counters"].items():
            line = f"{_metric_name(name)}_total{{{labels}}}"
            assert any(l.startswith(line) for l in body.splitlines()), \
                f"counter {name} missing from exposition"
        for name in snap["gauges"]:
            assert f"{_metric_name(name)}{{{labels}}}" in body, \
                f"gauge {name} missing"
        for name in snap["timings"]:
            assert f"{_metric_name(name)}_seconds_count{{{labels}}}" \
                in body, f"timing {name} missing"
        for name in snap["dists"]:
            assert f'{_metric_name(name)}{{quantile="0.5",{labels}}}' \
                in body, f"dist {name} missing"
        # the scraped counter values agree with the registry snapshot
        _, samples = _parse_exposition(body)
        assert samples[f"lgbm_iterations_total{{{labels}}}"] == \
            snap["counters"]["iterations"]
        # a structured metrics_exporter event recorded the bind
        evs = [e for e in snap["events"]
               if e["event"] == "metrics_exporter"]
        assert evs and evs[0]["port"] == port \
            and evs[0]["fallback"] is False
        # liveness endpoint answers too
        ctype, ok = _scrape(port, "/healthz")
        assert ok == "ok\n"
    finally:
        bst._gbdt._metrics.stop()


def test_exporter_port_in_use_falls_back(tmp_path):
    port = _free_port()
    blocker = socket.socket()
    blocker.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    blocker.bind(("127.0.0.1", port))
    blocker.listen(1)
    try:
        X, y = _data(n=300)
        bst = lgb.train({"objective": "binary", "num_leaves": 7,
                         "verbose": -1, "metrics_port": port},
                        lgb.Dataset(X, label=y), num_boost_round=2)
        try:
            exp = bst._gbdt._metrics
            # training survived, the exporter fell back to an ephemeral
            # port and said so with a structured event
            assert exp.port is not None and exp.port != port
            evs = [e for e in bst.telemetry()["events"]
                   if e["event"] == "metrics_exporter"]
            assert evs and evs[0]["fallback"] is True \
                and evs[0]["requested_port"] == port \
                and evs[0]["port"] == exp.port
            _, body = _scrape(exp.port)
            _parse_exposition(body)
        finally:
            bst._gbdt._metrics.stop()
    finally:
        blocker.close()


def test_exporter_lifecycle_on_reset(tmp_path):
    """reset_parameter clearing metrics_port stops the endpoint; an
    unchanged port keeps the same running server."""
    port = _free_port()
    X, y = _data(n=300)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbose": -1, "metrics_port": port},
                    lgb.Dataset(X, label=y), num_boost_round=2)
    exp = bst._gbdt._metrics
    assert exp is not None and exp.port == port
    bst.reset_parameter({"metrics_port": port, "learning_rate": 0.05})
    assert bst._gbdt._metrics is exp        # same server kept
    bst.reset_parameter({"metrics_port": 0})
    assert bst._gbdt._metrics is None
    with pytest.raises(Exception):
        _scrape(port, timeout=2)


# ----------------------------------------------------- serving traces
def test_serve_access_records_and_trace_spans(tmp_path):
    from lightgbm_tpu.serve import PredictionService
    X, y = _data(n=500)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbose": -1, "max_bin": 63},
                    lgb.Dataset(X, label=y,
                                params={"max_bin": 63, "verbose": -1}),
                    num_boost_round=10)
    port = _free_port()
    tel_path = tmp_path / "serve.jsonl"
    trace_path = tmp_path / "serve_trace.json"
    rng = np.random.RandomState(7)
    svc = PredictionService(
        {"m": bst}, max_batch_rows=256, min_bucket_rows=16,
        max_delay_ms=1.0, telemetry_out=str(tel_path),
        trace_out=str(trace_path), metrics_port=port)
    svc.warmup()
    sizes = [1, 3, 17, 120, 256, 5]
    futs = [svc.submit("m", rng.rand(s, X.shape[1]).astype(np.float32))
            for s in sizes]
    # every future carries its minted trace id
    tids = [f.trace_id for f in futs]
    assert len(set(tids)) == len(tids)
    assert all(len(t) == 16 for t in tids)
    for f in futs:
        f.result(timeout=120)

    # live scrape while the service is up: registry dists exposed as
    # summaries with quantiles, request counter correct
    ctype, body = _scrape(port)
    assert ctype == CONTENT_TYPE
    types, samples = _parse_exposition(body)
    labels = f'rank="0",run_id="{svc.tel.run_id}"'
    assert samples[f"lgbm_serve_requests_total{{{labels}}}"] == \
        len(sizes)
    assert types["lgbm_serve_latency_ms"] == "summary"
    assert f'lgbm_serve_latency_ms{{quantile="0.5",{labels}}}' in body
    svc.close()
    assert svc.metrics_url is None           # closed: exporter stopped

    recs = [json.loads(line) for line in open(tel_path)]
    access = [r for r in recs if r["event"] == "serve_access"]
    # exactly ONE serve_access per request, schema complete
    assert sorted(r["trace_id"] for r in access) == sorted(tids)
    for r in access:
        assert r["model_id"] == "m"
        assert r["rows"] in sizes
        for key in ("queue_ms", "batch_ms", "dispatch_ms"):
            assert isinstance(r[key], (int, float)) and r[key] >= 0.0
        assert r["degraded"] is False
        assert isinstance(r["bucket"], int) and r["bucket"] >= 16

    # Perfetto: one serve-track span per request, trace_id matching its
    # serve_access record
    doc = json.load(open(trace_path))
    spans = [e for e in doc["traceEvents"]
             if e.get("cat") == "serve" and e.get("ph") == "X"]
    assert sorted(e["args"]["trace_id"] for e in spans) == sorted(tids)
    by_tid = {r["trace_id"]: r for r in access}
    for e in spans:
        rec = by_tid[e["args"]["trace_id"]]
        assert e["args"]["rows"] == rec["rows"]
        assert e["args"]["bucket"] == rec["bucket"]
        # the span covers at least the queue wait
        assert e["dur"] >= rec["queue_ms"] * 1000.0 * 0.5


def test_serve_access_on_degraded_host_walk(tmp_path):
    """A model the device path cannot represent (linear_tree) still
    yields its serve_access record — flagged degraded."""
    from lightgbm_tpu.serve import PredictionService
    X, y = _data(n=400)
    bst = lgb.train({"objective": "regression", "num_leaves": 7,
                     "verbose": -1, "linear_tree": True},
                    lgb.Dataset(X, label=y), num_boost_round=3)
    tel_path = tmp_path / "serve.jsonl"
    svc = PredictionService({"m": bst}, telemetry_out=str(tel_path))
    fut = svc.submit("m", X[:5])
    fut.result(timeout=120)
    svc.close()
    recs = [json.loads(line) for line in open(tel_path)]
    access = [r for r in recs if r["event"] == "serve_access"]
    assert len(access) == 1
    assert access[0]["trace_id"] == fut.trace_id
    assert access[0]["degraded"] is True
    assert access[0]["bucket"] is None


def test_serve_access_on_closed_batcher():
    """Even a request rejected at submit (batcher already stopped)
    yields its serve_access record — the exactly-one-per-request
    contract covers the failure paths an operator debugs."""
    from lightgbm_tpu.serve.batcher import MicroBatcher
    tel = Telemetry(enabled=True)
    b = MicroBatcher(lambda m, X: np.zeros((1, X.shape[0])),
                     telemetry=tel)
    b.close()
    fut = b.submit("m", np.zeros((2, 3), np.float32))
    assert isinstance(fut.exception(timeout=5), RuntimeError)
    acc = [e for e in tel.snapshot()["events"]
           if e["event"] == "serve_access"]
    assert len(acc) == 1
    assert acc[0]["trace_id"] == fut.trace_id
    # since the overload hardening the submit-after-close failure is
    # the structured ServeClosed (a ServeError subclass of the
    # RuntimeError asserted above)
    assert acc[0]["error"] == "ServeClosed"


# ------------------------------------------------------- build info
def test_build_info_series(tmp_path):
    """The exporter carries one constant ``lgbm_build_info{...} 1``
    info-series so scrapes are joinable across deploys: package
    version, jax version, active backend, plus the exporter's own
    rank/run_id labels."""
    from lightgbm_tpu.obs.export import build_info_labels
    info = build_info_labels()
    assert set(info) == {"version", "jax_version", "backend"}
    assert all(isinstance(v, str) and v for v in info.values())
    assert info["version"] == lgb.__version__

    port = _free_port()
    X, y = _data(n=300)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbose": -1, "metrics_port": port},
                    lgb.Dataset(X, label=y), num_boost_round=2)
    try:
        _, body = _scrape(port)
        types, samples = _parse_exposition(body)
        assert types["lgbm_build_info"] == "gauge"
        key = next(k for k in samples
                   if k.startswith("lgbm_build_info{"))
        assert samples[key] == 1.0
        run_id = bst._gbdt.telemetry.run_id
        for frag in (f'version="{info["version"]}"',
                     f'jax_version="{info["jax_version"]}"',
                     f'backend="{info["backend"]}"',
                     'rank="0"', f'run_id="{run_id}"'):
            assert frag in key, (frag, key)
    finally:
        bst._gbdt._metrics.stop()


# --------------------------------------------- per-device memory stats
def test_device_memory_stats_cpu_degrades_to_none():
    from lightgbm_tpu.obs.jaxmon import (device_memory_stats,
                                         memory_watermarks)
    stats = device_memory_stats()
    # CPU backends report no allocator stats → clean None; on a real
    # accelerator the contract is per-device keyed dicts
    if stats is not None:
        assert all(isinstance(k, int) for k in stats)
        assert all("bytes_in_use" in v for v in stats.values())
    tel = Telemetry(enabled=True)
    out = memory_watermarks(tel, where="drain")
    if out is None:
        assert not any(k.startswith("mem.d")
                       for k in tel.snapshot()["gauges"])
    else:
        gauges = tel.snapshot()["gauges"]
        assert any(k.startswith("mem.d") and k.endswith("bytes_in_use")
                   for k in gauges)
        assert tel.snapshot()["counters"]["mem.watermarks.drain"] == 1


# ------------------------------------------------------------ obs_tail
def _load_obs_tail():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "obs_tail.py")
    spec = importlib.util.spec_from_file_location("obs_tail", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_obs_tail_summary_and_filter(tmp_path, capsys):
    out = tmp_path / "t.jsonl"
    X, y = _data(n=300)
    lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1,
               "telemetry_out": str(out)},
              lgb.Dataset(X, label=y), num_boost_round=3)
    obs_tail = _load_obs_tail()
    assert obs_tail.main([str(out), "--summary"]) == 0
    text = capsys.readouterr().out
    assert "iteration" in text and "records:" in text

    assert obs_tail.main([str(out), "--event", "iteration",
                          "--rank", "0", "--last", "2"]) == 0
    lines = [l for l in capsys.readouterr().out.splitlines() if l]
    assert len(lines) == 2
    assert all("event=iteration" in l for l in lines)

    # corrupt lines are skipped, not fatal
    with open(out, "a") as fh:
        fh.write("{not json\n")
    assert obs_tail.main([str(out), "--summary"]) == 0


def test_obs_tail_dedups_bench_runs(tmp_path, capsys):
    traj = tmp_path / "traj.jsonl"
    with open(traj, "w") as fh:
        fh.write(json.dumps({"run_id": "a", "value": 1.0,
                             "event": "bench"}) + "\n")
        fh.write(json.dumps({"run_id": "a", "value": 2.0,
                             "event": "bench"}) + "\n")
        fh.write(json.dumps({"run_id": "b", "value": 3.0,
                             "event": "bench"}) + "\n")
    obs_tail = _load_obs_tail()
    recs = obs_tail.load_records(str(traj), dedup_runs=True)
    # last-wins per run_id, bench_compare semantics
    assert [r["value"] for r in recs] == [2.0, 3.0]


def _readline_or_die(stream, timeout=60):
    """Blocking-readline with a deadline so a broken --follow hangs the
    TEST, not the whole tier-1 sweep."""
    import queue as _q
    import threading
    q = _q.Queue()
    threading.Thread(target=lambda: q.put(stream.readline()),
                     daemon=True).start()
    try:
        return q.get(timeout=timeout)
    except _q.Empty:
        raise AssertionError("--follow produced no output in time")


def test_obs_tail_follow_survives_rotation(tmp_path):
    """`obs_tail --follow` across the two sink-recycle shapes: a rename
    rotation (new inode) and a truncate-in-place rewrite (size below
    the read offset). Both must reopen and keep printing — the old
    behavior tailed a dead offset forever."""
    path = tmp_path / "t.jsonl"
    path.write_text(json.dumps({"event": "a", "ts": 1.0}) + "\n")
    script = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "obs_tail.py")
    proc = subprocess.Popen(
        [sys.executable, script, str(path), "--follow"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    try:
        assert "event=a" in _readline_or_die(proc.stdout)

        # rotation: a NEW file renamed over the path (fresh inode)
        side = tmp_path / "t.jsonl.new"
        side.write_text(json.dumps({"event": "b", "ts": 2.0}) + "\n")
        os.replace(side, path)
        assert "event=b" in _readline_or_die(proc.stdout)

        # grow the offset well past the next rewrite's size so the
        # shrink check (size < offset) is unambiguous
        with open(path, "a") as fh:
            for i in range(5):
                fh.write(json.dumps({"event": "pad", "ts": 3.0 + i,
                                     "fill": "x" * 64}) + "\n")
        for _ in range(5):
            assert "event=pad" in _readline_or_die(proc.stdout)

        # truncate-in-place mid-follow: same inode, shrunk content
        path.write_text(json.dumps({"event": "c", "ts": 9.0}) + "\n")
        assert "event=c" in _readline_or_die(proc.stdout)
    finally:
        proc.kill()
        proc.communicate(timeout=30)


# ------------------------------------------------- two-process cohort
_MP_WORKER = textwrap.dedent("""
    import json, os, sys, urllib.request
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=sys.argv[1],
        num_processes=int(sys.argv[2]), process_id=int(sys.argv[3]))
    import numpy as np
    import lightgbm_tpu as lgb

    path, base_port, out_path = sys.argv[4], int(sys.argv[5]), sys.argv[6]
    rank = jax.process_index()
    result = {"rank": rank}

    def scrape(port):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            return r.read().decode()

    def cb(env):
        # after iteration 3 both health checks ((it+1) % 2) have run,
        # so rank 0's fleet view is populated; scrape OWN endpoint live
        if env.iteration == 3 and "self_body" not in result:
            result["self_body"] = scrape(base_port + rank)

    ds = lgb.Dataset(path, params={"label_column": 0, "verbose": -1,
                                   "max_bin": 63})
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "learning_rate": 0.2, "tree_learner": "data",
                     "verbose": -1, "metrics_port": base_port,
                     "health_check_period": 2},
                    ds, num_boost_round=5, callbacks=[cb])
    with open(out_path, "w") as fh:
        json.dump(result, fh)
""")


def test_multiproc_rank_endpoints_and_fleet_aggregate(tmp_path):
    """Two-process driver: rank r serves metrics_port + r, every rank's
    exposition is self-labelled, and rank 0's endpoint additionally
    carries the fleet counter series (rank=\"1\" labels) fed by the
    health auditor's existing allgather."""
    rng = np.random.RandomState(5)
    n, F = 2000, 6
    X = rng.rand(n, F)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float64)
    train = tmp_path / "train.csv"
    np.savetxt(train, np.column_stack([y, X]), delimiter=",", fmt="%.6f")

    coord_port = _free_port()
    base_port = _free_port()
    if base_port + 1 == coord_port:
        base_port = _free_port()
    coord = f"127.0.0.1:{coord_port}"
    script = tmp_path / "worker.py"
    script.write_text(_MP_WORKER)
    outs = [tmp_path / f"rank{i}.json" for i in range(2)]
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = repo_root
    env.pop("XLA_FLAGS", None)
    procs = [subprocess.Popen(
        [sys.executable, str(script), coord, "2", str(i), str(train),
         str(base_port), str(outs[i])],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        for i in range(2)]
    for p in procs:
        out, err = p.communicate(timeout=600)
        assert p.returncode == 0, err.decode()[-3000:]

    bodies = {}
    for rank in range(2):
        res = json.loads(outs[rank].read_text())
        assert res["rank"] == rank
        body = res["self_body"]
        bodies[rank] = body
        types, samples = _parse_exposition(body)
        assert types["lgbm_iterations"] == "counter"
        # self series carries the scraping rank's own label
        own = [k for k in samples
               if k.startswith("lgbm_iterations_total")
               and f'rank="{rank}"' in k]
        assert own, f"rank {rank} exposition lacks its own series"
        # the health collectives were counted on both ranks
        assert any(k.startswith("lgbm_health_checks_total")
                   for k in samples)
    # rank 0 aggregates the fleet: a rank="1" counter series without
    # run_id (the peer's counters arrived via the audit allgather)
    _, samples0 = _parse_exposition(bodies[0])
    assert 'lgbm_iterations_total{rank="1"}' in samples0, \
        sorted(k for k in samples0 if "iterations_total" in k)
    # rank 1 serves only itself (no fleet series for rank 0)
    _, samples1 = _parse_exposition(bodies[1])
    assert 'lgbm_iterations_total{rank="0"}' not in samples1
