"""Distributed-mode tests on a virtual 8-device CPU mesh — the analog of the
reference's localhost-subprocess distributed mockup
(ref: tests/distributed/_test_distributed.py)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lightgbm_tpu.boosting.gbdt import (feature_meta_from_dataset,
                                        split_params_from_config)
from lightgbm_tpu.config import Config
from lightgbm_tpu.dataset import TpuDataset
from lightgbm_tpu.models.learner import grow_tree_leafwise
from lightgbm_tpu.parallel import (make_mesh, make_sharded_grow_fn,
                                   shard_rows)
from lightgbm_tpu.parallel.mesh import replicate


@pytest.fixture(scope="module")
def setup():
    rng = np.random.RandomState(0)
    n = 4096  # divisible by 8 shards
    X = rng.randn(n, 12)
    y = (X[:, 0] + 0.5 * X[:, 1] ** 2 > 0.4).astype(np.float32)
    cfg = Config({"max_bin": 63, "verbose": -1})
    ds = TpuDataset.from_data(X, cfg)
    ds.metadata.set_label(y)
    meta = feature_meta_from_dataset(ds)
    params = split_params_from_config(cfg)
    p = 0.5
    grad = (p - y).astype(np.float32)
    hess = np.full_like(grad, p * (1 - p))
    gh = np.stack([grad, hess, np.ones_like(grad)], axis=1)
    return ds, meta, params, gh, y, X


def test_eight_virtual_devices_available():
    assert len(jax.devices()) >= 8


def test_data_parallel_tree_matches_single_device(setup):
    ds, meta, params, gh, _, _X = setup
    B = int(ds.max_num_bin)
    F = ds.num_features

    # single device reference
    tree1, row_leaf1 = grow_tree_leafwise(
        jnp.asarray(ds.bins), jnp.asarray(gh), meta, jnp.ones(F, bool),
        params, 31, B)

    # 8-way data parallel
    mesh = make_mesh(8)
    grow = make_sharded_grow_fn(mesh, params, 31, B)
    bins_s = shard_rows(mesh, ds.bins)
    gh_s = shard_rows(mesh, gh)
    tree8, row_leaf8 = grow(bins_s, gh_s,
                            jax.tree.map(lambda a: replicate(mesh, a), meta),
                            replicate(mesh, np.ones(F, bool)))

    assert int(tree8.num_leaves) == int(tree1.num_leaves)
    np.testing.assert_array_equal(np.asarray(tree8.split_feature),
                                  np.asarray(tree1.split_feature))
    np.testing.assert_array_equal(np.asarray(tree8.threshold_bin),
                                  np.asarray(tree1.threshold_bin))
    np.testing.assert_allclose(np.asarray(tree8.leaf_value),
                               np.asarray(tree1.leaf_value), rtol=2e-4,
                               atol=1e-5)
    np.testing.assert_array_equal(np.asarray(row_leaf8),
                                  np.asarray(row_leaf1))


def test_full_training_step_runs_sharded(setup):
    """Two sharded boosting steps through the PRODUCT driver decrease
    the loss (the round-2-flagged standalone demo step with hardcoded
    gradients was deleted; the real path is lgb.train with
    tree_learner=data — see tests/test_parallel_driver.py for the full
    matrix)."""
    ds, meta, params, gh, y, X = setup
    import lightgbm_tpu as lgb
    d = lgb.Dataset(X, label=y, params={"max_bin": 63, "verbose": -1})
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbose": -1, "tree_learner": "data",
                     "num_iterations": 2}, d)
    s0 = np.zeros_like(y, np.float64)
    s2 = bst.predict(X, raw_score=True)

    def logloss(s):
        return np.mean(np.log1p(np.exp(-(2 * y - 1) * np.asarray(s))))
    assert logloss(s2) < logloss(s0)


def test_uneven_rows_padding():
    mesh = make_mesh(8)
    arr = np.arange(100, dtype=np.float32)  # not divisible by 8
    sharded = shard_rows(mesh, arr)
    assert sharded.shape[0] == 104
    np.testing.assert_array_equal(np.asarray(sharded)[:100], arr)
    assert np.asarray(sharded)[100:].sum() == 0
