"""Live control plane (ISSUE 15): on-demand profiling, the device-time
cost ledger, and comparable run reports.

Tier-1 coverage of the exporter's control endpoints (/snapshot during
live training matching the registry, the /profile round trip with
overlap refusal and dispatch neutrality, /report), the cost ledger's
self-consistency against the compile_executable records and the hist.*
analytic plane model, the run_report.json schema + scripts/run_diff.py
on identical and doctored reports, the /metrics TTL cache under
scrape-storm concurrency, and the bytes_reserved/fragmentation memory
satellites.  The two-process rank-0 report aggregation runs in the
weekly slow pass.
"""
import importlib.util
import json
import os
import socket
import subprocess
import sys
import textwrap
import threading
import time
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs import jaxmon
from lightgbm_tpu.obs.export import MetricsExporter, post, scrape
from lightgbm_tpu.obs.registry import Telemetry


def _data(n=600, f=6, seed=3):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float32)
    return X, y


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _load_script(name):
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", name + ".py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_FUSED = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
          "learning_rate": 0.2, "min_data_in_leaf": 5, "verbose": -1,
          "metric": "None", "tpu_engine": "fused", "tpu_megastep": True}


def _ds(X, y):
    return lgb.Dataset(X, label=y, params={"max_bin": 63, "verbose": -1})


# ------------------------------------------------------------ /snapshot
def test_snapshot_during_live_training_matches_registry(tmp_path):
    port = _free_port()
    X, y = _data()
    mid = {}

    def snap_cb(env):
        if env.iteration == 2 and not mid:
            _, body = scrape(f"http://127.0.0.1:{port}/snapshot")
            mid["snap"] = json.loads(body)

    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbose": -1, "metrics_port": port,
                     "telemetry_out": str(tmp_path / "t.jsonl")},
                    _ds(X, y), num_boost_round=5, callbacks=[snap_cb])
    try:
        tel = bst._gbdt.telemetry
        # mid-run: the live /snapshot answered with the deep registry
        # view (events + findings, which /metrics never carries)
        assert mid, "callback never hit /snapshot"
        assert "events" in mid["snap"] and "counters" in mid["snap"]
        assert mid["snap"]["run_id"] == tel.run_id
        assert 0 < mid["snap"]["counters"]["iterations"] \
            <= tel.snapshot()["counters"]["iterations"]
        # settled: /snapshot is the registry snapshot, verbatim
        _, body = scrape(f"http://127.0.0.1:{port}/snapshot")
        live = json.loads(body)
        ref = tel.snapshot()
        assert live["counters"] == ref["counters"]
        assert live["gauges"] == ref["gauges"]
        assert [e["event"] for e in live["events"]] == \
            [e["event"] for e in ref["events"]]
        # the profile handoff state rides along
        assert live["profile"] == {"armed": None, "open": False}
    finally:
        bst._gbdt._metrics.stop()


# ------------------------------------------------------------- /profile
def test_profile_round_trip_refusal_and_dispatch_neutrality(tmp_path):
    """POST /profile arms; a second POST refuses with 409; the window
    opens at an iteration edge (the sync-driver leg of the contract),
    closes after >= iters iterations, produces a non-empty trace
    directory, and the dispatch count matches the sync driver's usual
    per-iteration schedule (profiling adds none).  The megastep
    drain-boundary leg is covered below on the fused engine."""
    X, y = _data()
    prof_dir = tmp_path / "prof"
    port = _free_port()
    # the XLA sync driver: cheap off-TPU, and exactly the "iteration
    # edge" arm of the window contract
    params = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
              "verbose": -1, "metric": "None", "tpu_engine": "xla",
              "metrics_port": port,
              "telemetry_out": str(tmp_path / "t.jsonl")}
    bst = lgb.Booster(params=params, train_set=_ds(X, y))
    url = f"http://127.0.0.1:{port}"
    code, body = post(f"{url}/profile?iters=2&dir={prof_dir}")
    assert code == 200 and body["armed"] is True
    code2, body2 = post(f"{url}/profile?iters=9")
    assert code2 == 409 and body2["armed"] is False
    assert "already armed" in body2["reason"]
    disp_per_iter = None
    for i in range(4):
        bst.update()
        if i == 0:
            disp_per_iter = bst._gbdt.telemetry.snapshot()[
                "counters"]["train.dispatches"]
    bst._gbdt.drain_pending()
    snap = bst._gbdt.telemetry.snapshot()
    bst._gbdt._metrics.stop()

    states = [e["state"] for e in snap["events"]
              if e["event"] == "profile_window"]
    assert states == ["armed", "refused", "open", "closed"]
    closed = [e for e in snap["events"]
              if e["event"] == "profile_window"
              and e["state"] == "closed"]
    assert closed[0]["covered"] >= 2
    files = [os.path.join(r, f)
             for r, _, fs in os.walk(prof_dir) for f in fs]
    assert files, "on-demand profiler window produced no trace"
    # dispatch neutrality: iterations 2-4 ran under/after the window
    # and paid exactly the same per-iteration dispatch schedule as
    # iteration 1
    assert snap["counters"]["train.dispatches"] == 4 * disp_per_iter


def test_profile_fires_at_megastep_drain_boundary(tmp_path):
    """Against an engine-armed megastep run the window opens and closes
    at drain boundaries (chunk-multiple iterations), and the dispatch
    schedule is unchanged: one dispatch per fused chunk, exactly."""
    X, y = _data()
    prof_dir = tmp_path / "prof_ms"
    chunk = 3
    port = _free_port()
    stop = threading.Event()

    def _arm():
        url = (f"http://127.0.0.1:{port}/profile?iters=1"
               f"&dir={prof_dir}")
        while not stop.is_set():
            try:
                code, _ = post(url, timeout=2)
                if code == 200:
                    return
            except Exception:
                pass
            time.sleep(0.01)

    th = threading.Thread(target=_arm, daemon=True)
    th.start()
    bst = lgb.train(
        dict(_FUSED, metrics_port=port, tpu_megastep_iters=chunk,
             telemetry_out=str(tmp_path / "ms.jsonl")),
        _ds(X, y), num_boost_round=2 * chunk)
    stop.set()
    th.join(timeout=5)
    snap = bst._gbdt.telemetry.snapshot()
    bst._gbdt._metrics.stop()

    closed = [e for e in snap["events"]
              if e["event"] == "profile_window"
              and e["state"] in ("closed", "closed_at_finalize")]
    assert closed, ("no profile window closed: "
                    + str([e for e in snap["events"]
                           if e["event"] == "profile_window"]))
    # boundary alignment: open/close iterations are chunk multiples
    opened = [e for e in snap["events"]
              if e["event"] == "profile_window"
              and e["state"] == "open"]
    assert opened and opened[0]["iter"] % chunk == 0
    assert closed[0]["iter"] % chunk == 0
    files = [os.path.join(r, f)
             for r, _, fs in os.walk(prof_dir) for f in fs]
    assert files
    # dispatch neutrality, absolutely: one dispatch per fused chunk —
    # the armed/open/closed window added none
    assert snap["counters"]["train.dispatches"] == 2


def test_profile_refuses_while_config_window_pending(tmp_path):
    """A profile_dir config window owns the profiler: POST /profile
    answers 409 until it completes."""
    port = _free_port()
    X, y = _data(n=400)
    params = dict(_FUSED, metrics_port=port,
                  profile_dir=str(tmp_path / "cfg_prof"),
                  profile_start_iteration=0, profile_num_iterations=2)
    ds = _ds(X, y)
    bst = lgb.Booster(params=params, train_set=ds)
    try:
        code, body = post(f"http://127.0.0.1:{port}/profile?iters=1")
        assert code == 409
        assert "profile_dir" in body["reason"]
    finally:
        bst._gbdt._metrics.stop()


# ---------------------------------------------------------- cost ledger
def test_cost_ledger_gauges_and_compile_executable_consistency(tmp_path):
    X, y = _data()
    bst = lgb.train(dict(_FUSED,
                         telemetry_out=str(tmp_path / "c.jsonl")),
                    _ds(X, y), num_boost_round=4)
    snap = bst._gbdt.telemetry.snapshot()
    g = snap["gauges"]
    assert g.get("cost.flops_per_iter", 0) > 0
    assert g.get("cost.hlo_bytes_per_iter", 0) > 0
    # achieved_fraction is the hist analytic model over the HLO bytes
    assert 0 < g.get("cost.achieved_fraction", 0) <= 1.0
    assert abs(g["cost.achieved_fraction"]
               - g["hist.bytes_per_iter"] / g["cost.hlo_bytes_per_iter"]) \
        < 1e-9
    evs = snap["events"]
    compiles = {e["signature"]: e for e in evs
                if e["event"] == "compile_executable"}
    costs = {e["signature"]: e for e in evs
             if e["event"] == "cost_executable"}
    assert costs, "no cost_executable records"
    for sig, ce in costs.items():
        # the ledger joins the compile record by signature, and both
        # quote the SAME operand-byte estimate
        assert sig in compiles, (sig, sorted(compiles))
        assert ce["operand_bytes"] == compiles[sig]["operand_bytes"]
        assert ce["flops"] > 0 and ce["hlo_bytes"] > 0
    ledgers = [e for e in evs if e["event"] == "cost_ledger"]
    assert ledgers, "no cost_ledger record at the drain"
    led = ledgers[-1]
    ent = costs[led["signature"]]
    assert led["flops_per_iter"] == ent["flops"] / ent["scale"]
    assert led["hlo_bytes_per_iter"] == ent["hlo_bytes"] / ent["scale"]
    assert led["kind"] in ("megastep", "fast_step")


def test_cost_ledger_compiled_mode_and_off(tmp_path):
    X, y = _data()
    bst = lgb.train(dict(_FUSED, cost_ledger="compiled",
                         telemetry_out=str(tmp_path / "cc.jsonl")),
                    _ds(X, y), num_boost_round=4)
    snap = bst._gbdt.telemetry.snapshot()
    assert snap["gauges"].get("cost.flops_per_iter", 0) > 0
    ce = [e for e in snap["events"] if e["event"] == "cost_executable"]
    assert ce and ce[0]["mode"] == "compiled"

    bst2 = lgb.train(dict(_FUSED, cost_ledger="off",
                          telemetry_out=str(tmp_path / "co.jsonl")),
                     _ds(X, y), num_boost_round=4)
    snap2 = bst2._gbdt.telemetry.snapshot()
    assert "cost.flops_per_iter" not in snap2["gauges"]
    assert not [e for e in snap2["events"]
                if e["event"].startswith("cost")]


def test_serve_cost_gauges(tmp_path):
    from lightgbm_tpu.serve import PredictionService
    X, y = _data()
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbose": -1, "max_bin": 63}, _ds(X, y),
                    num_boost_round=5)
    svc = PredictionService({"m": bst}, max_batch_rows=128,
                            min_bucket_rows=16, batch_events=False)
    try:
        svc.warmup()
        svc.predict("m", X[:40])
        snap = svc.tel.snapshot()
        assert snap["gauges"].get("cost.serve.flops_per_row", 0) > 0
        assert snap["gauges"].get("cost.serve.hlo_bytes_per_row", 0) > 0
        sigs = {e["signature"] for e in snap["events"]
                if e["event"] == "cost_executable"}
        warmed = {e["signature"] for e in snap["events"]
                  if e["event"] == "compile_executable"}
        assert sigs == warmed and sigs
    finally:
        svc.close()


# ----------------------------------------------------------- run report
def _train_with_report(tmp_path, name, rounds=4, **extra):
    X, y = _data()
    out = tmp_path / name
    lgb.train(dict(_FUSED, run_report_out=str(out),
                   telemetry_out=str(tmp_path / (name + ".jsonl")),
                   **extra),
              _ds(X, y), num_boost_round=rounds)
    return out


def test_run_report_schema_and_run_diff(tmp_path):
    from lightgbm_tpu.obs.report import SCHEMA, load_report
    a = _train_with_report(tmp_path, "a.json")
    b = _train_with_report(tmp_path, "b.json")
    rep = load_report(str(a))
    assert rep["schema"] == SCHEMA
    assert rep["derived"]["dispatches_per_iter"] > 0
    assert rep["derived"]["iterations"] == 4
    assert rep["cost"]["flops_per_iter"] > 0
    assert rep["reasons"]["megastep_evicted"] == []
    assert os.path.exists(str(a) + ".md")
    md = open(str(a) + ".md").read()
    assert "Cost ledger" in md and "dispatches/iter" in md

    run_diff = _load_script("run_diff")
    # identical runs (same params, same data, same seed): exit 0
    assert run_diff.main([str(a), str(b), "--fail-on-regress"]) == 0

    # doctored regression #1: dispatches/iter grew (fast-path eviction)
    bad = json.loads(open(b).read())
    bad["derived"]["dispatches_per_iter"] *= 4
    (tmp_path / "bad1.json").write_text(json.dumps(bad))
    assert run_diff.main([str(a), str(tmp_path / "bad1.json"),
                          "--fail-on-regress"]) == 1
    # doctored regression #2: a NEW eviction reason fired
    bad2 = json.loads(open(b).read())
    bad2["reasons"]["megastep_evicted"] = ["callback:user_cb"]
    (tmp_path / "bad2.json").write_text(json.dumps(bad2))
    assert run_diff.main([str(a), str(tmp_path / "bad2.json"),
                          "--fail-on-regress"]) == 1
    # doctored regression #3: the candidate LOST its cost ledger (every
    # analysis failed -> the gauges never appeared) — a silently
    # missing deterministic counter must flag, not skip
    bad_lost = json.loads(open(b).read())
    bad_lost["cost"]["flops_per_iter"] = None
    bad_lost["cost"]["hlo_bytes_per_iter"] = None
    bad_lost["cost"]["achieved_fraction"] = None
    (tmp_path / "bad_lost.json").write_text(json.dumps(bad_lost))
    assert run_diff.main([str(a), str(tmp_path / "bad_lost.json"),
                          "--fail-on-regress"]) == 1
    # ... but a counter the BASELINE predates is informational only
    old_base = json.loads(open(a).read())
    old_base["cost"]["achieved_fraction"] = None
    (tmp_path / "old_base.json").write_text(json.dumps(old_base))
    assert run_diff.main([str(tmp_path / "old_base.json"), str(b),
                          "--fail-on-regress"]) == 0
    # schema mismatch is not comparable: exit 2
    bad3 = json.loads(open(b).read())
    bad3["schema"] = "lightgbm_tpu.run_report/999"
    (tmp_path / "bad3.json").write_text(json.dumps(bad3))
    assert run_diff.main([str(a), str(tmp_path / "bad3.json")]) == 2


def test_run_report_records_evictions(tmp_path):
    """A run that evicts off the megastep (user callback) must name the
    reason in the report."""
    from lightgbm_tpu.obs.report import load_report
    X, y = _data()
    rep_path = tmp_path / "ev.json"
    lgb.train(dict(_FUSED, run_report_out=str(rep_path)),
              _ds(X, y), num_boost_round=3,
              callbacks=[lambda env: None])
    rep = load_report(str(rep_path))
    assert rep["reasons"]["megastep_evicted"], rep["reasons"]


def test_report_endpoint_matches_artifact(tmp_path):
    port = _free_port()
    X, y = _data()
    rep_path = tmp_path / "live.json"
    bst = lgb.train(dict(_FUSED, metrics_port=port,
                         run_report_out=str(rep_path)),
                    _ds(X, y), num_boost_round=4)
    try:
        _, body = scrape(f"http://127.0.0.1:{port}/report")
        live = json.loads(body)
        disk = json.loads(open(rep_path).read())
        assert live["schema"] == disk["schema"]
        assert live["derived"] == disk["derived"]
        assert live["cost"]["flops_per_iter"] == \
            disk["cost"]["flops_per_iter"]
    finally:
        bst._gbdt._metrics.stop()


def test_obs_tail_summary_and_report_mode(tmp_path, capsys):
    _train_with_report(tmp_path, "ot.json")
    obs_tail = _load_script("obs_tail")
    assert obs_tail.main([str(tmp_path / "ot.json.jsonl"),
                          "--summary"]) == 0
    out = capsys.readouterr().out
    assert "cost:" in out and "flops/iter=" in out
    assert "hist:" in out and "achieved_fraction=" in out
    assert obs_tail.main(["--report", str(tmp_path / "ot.json")]) == 0
    out = capsys.readouterr().out
    assert "# Run report" in out and "Cost ledger" in out


# ------------------------------------------------------ scrape TTL cache
def test_metrics_ttl_cache_under_scrape_storm():
    tel = Telemetry(enabled=True)
    tel.inc("x", 5)
    exp = MetricsExporter(tel, 0, cache_ttl=0.5)
    try:
        port = exp.start()
        assert port > 0
        url = f"http://127.0.0.1:{port}/metrics"
        errors = []
        bodies = []

        def storm():
            try:
                for _ in range(40):
                    with urllib.request.urlopen(url, timeout=10) as r:
                        bodies.append(r.read().decode())
            except Exception as e:      # pragma: no cover
                errors.append(repr(e))

        threads = [threading.Thread(target=storm) for _ in range(8)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        # mutate the registry while the storm runs — the cache bounds
        # how often the renderer touches the registry lock
        while any(t.is_alive() for t in threads):
            tel.inc("x")
            time.sleep(0.001)
        for t in threads:
            t.join(timeout=30)
        wall = time.perf_counter() - t0
        assert not errors, errors
        assert len(bodies) == 8 * 40
        # the storm was served mostly from cache: distinct bodies are
        # bounded by elapsed ttl windows, not by request count
        distinct = len(set(bodies))
        assert distinct <= int(wall / 0.5) + 2, (distinct, wall)
        assert exp.cache_hits > 0
        # after the TTL expires a scrape sees fresh values again
        time.sleep(0.6)
        tel.inc("x", 1000)
        time.sleep(0.6)
        with urllib.request.urlopen(url, timeout=10) as r:
            fresh = r.read().decode()
        line = next(l for l in fresh.splitlines()
                    if l.startswith("lgbm_x_total"))
        assert float(line.rsplit(" ", 1)[1]) == \
            tel.snapshot()["counters"]["x"]
    finally:
        exp.stop()


# ----------------------------------------------- memory stat satellites
def test_memory_watermarks_reserved_and_fragmentation(monkeypatch):
    tel = Telemetry(enabled=True)
    fake = {0: {"bytes_in_use": 400, "peak_bytes_in_use": 500,
                "bytes_limit": 1000, "bytes_reserved": 600,
                "peak_bytes_reserved": 700,
                "largest_free_block_bytes": 150}}
    monkeypatch.setattr(jaxmon, "device_memory_stats", lambda: fake)
    stats = jaxmon.memory_watermarks(tel, where="test")
    g = tel.snapshot()["gauges"]
    assert g["mem.d0.bytes_reserved"] == 600
    assert g["mem.d0.peak_bytes_reserved"] == 700
    # free pool = reserved 600 - in_use 400 = 200 (NOT limit - in_use:
    # the largest-free-block stat describes the reserved pool); largest
    # block 150 -> 25% of the pool's free space is shattered
    assert abs(g["mem.d0.fragmentation"] - 0.25) < 1e-9
    assert stats[0]["fragmentation"] == g["mem.d0.fragmentation"]


def test_memory_watermarks_gracefully_absent_without_stats():
    # CPU backend: no allocator stats — no reserved/fragmentation
    # gauges, no exception (the graceful-absence half of the satellite)
    tel = Telemetry(enabled=True)
    jaxmon.memory_watermarks(tel, where="cpu")
    g = tel.snapshot()["gauges"]
    assert not any("bytes_reserved" in k or "fragmentation" in k
                   for k in g)


def test_fragmentation_edge_cases():
    assert jaxmon.fragmentation({}) is None
    assert jaxmon.fragmentation(
        {"bytes_limit": 100, "bytes_in_use": 100,
         "largest_free_block_bytes": 0}) == 0.0
    assert jaxmon.fragmentation(
        {"bytes_limit": 100, "bytes_in_use": 0,
         "largest_free_block_bytes": 100}) == 0.0


# ------------------------------------------------- two-process rank-0
_MP_WORKER = textwrap.dedent("""
    import json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=sys.argv[1],
        num_processes=int(sys.argv[2]), process_id=int(sys.argv[3]))
    import numpy as np
    import lightgbm_tpu as lgb

    path, report_out, out_path = sys.argv[4], sys.argv[5], sys.argv[6]
    ds = lgb.Dataset(path, params={"label_column": 0, "verbose": -1,
                                   "max_bin": 63})
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "learning_rate": 0.2, "tree_learner": "data",
                     "verbose": -1, "run_report_out": report_out},
                    ds, num_boost_round=4)
    c = bst.telemetry().get("counters", {})
    with open(out_path, "w") as fh:
        json.dump({"rank": jax.process_index(),
                   "iterations": int(c.get("iterations", 0))}, fh)
""")


@pytest.mark.slow
def test_multiproc_rank0_report_aggregates_sections(tmp_path):
    """Two-process run with run_report_out: rank 0 writes ONE report
    whose ``ranks`` section carries both ranks' counters (riding the
    finalize allgather), rank 1 writes nothing."""
    rng = np.random.RandomState(5)
    n, F = 2000, 6
    X = rng.rand(n, F)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float64)
    train = tmp_path / "train.csv"
    np.savetxt(train, np.column_stack([y, X]), delimiter=",",
               fmt="%.6f")
    coord = f"127.0.0.1:{_free_port()}"
    script = tmp_path / "worker.py"
    script.write_text(_MP_WORKER)
    report_path = tmp_path / "mp_report.json"
    outs = [tmp_path / f"rank{i}.json" for i in range(2)]
    repo_root = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = repo_root
    env.pop("XLA_FLAGS", None)
    procs = [subprocess.Popen(
        [sys.executable, str(script), coord, "2", str(i), str(train),
         str(report_path), str(outs[i])],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        for i in range(2)]
    for p in procs:
        out, err = p.communicate(timeout=600)
        assert p.returncode == 0, err.decode()[-3000:]
    rep = json.loads(report_path.read_text())
    assert rep["rank"] == 0 and rep["world_size"] == 2
    ranks = rep["ranks"]
    assert sorted(s["rank"] for s in ranks) == [0, 1]
    for sec in ranks:
        assert sec["counters"].get("iterations", 0) > 0
    # exactly one artifact: rank 1 wrote nothing else into tmp
    assert not (tmp_path / "mp_report.json.rank1").exists()
