"""Prediction serving subsystem (lightgbm_tpu/serve/).

Covers the three layers and the acceptance contract of the serving
ISSUE: after ``warmup``, a stream of mixed-size requests incurs ZERO
recompiles (counter-asserted) and at most one host dispatch per
micro-batch, and served outputs match ``Booster.predict()`` within the
documented float32 tolerance — including for a booster loaded from a
model file with no training dataset attached.

Boosters are trained once per module (fixtures); engines pack cheaply
off them, and the module-scope jitted runners mean bucket compiles are
shared across same-shape tests.
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.serve import (MicroBatcher, PredictionService,
                                ResidencyManager, ServingEngine)

TOL = dict(rtol=1e-5, atol=1e-6)   # f32 device accumulation vs f64 host
F = 8


def _train(seed=0, n=400, f=F, rounds=6, **extra):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float32)
    params = {"objective": "binary", "num_leaves": 15,
              "learning_rate": 0.2, "verbose": -1, "min_data_in_leaf": 5}
    params.update(extra)
    return lgb.train(params, lgb.Dataset(X, label=y),
                     num_boost_round=rounds)


@pytest.fixture(scope="module")
def bst():
    return _train(seed=0)


@pytest.fixture(scope="module")
def file_model(bst, tmp_path_factory):
    path = str(tmp_path_factory.mktemp("serve") / "m.txt")
    bst.save_model(path)
    return path, lgb.Booster(model_file=path)


def _queries(rng, sizes, f=F):
    return [rng.rand(int(s), f).astype(np.float32) for s in sizes]


# ---------------------------------------------------------------- engine
def test_engine_binned_parity(bst):
    eng = ServingEngine(bst, max_batch_rows=128, min_bucket_rows=32)
    assert eng.variant == "binned" and eng.device_ok
    rng = np.random.RandomState(1)
    for Xq in _queries(rng, [1, 33, 150]):
        np.testing.assert_allclose(eng.predict(Xq), bst.predict(Xq),
                                   **TOL)


def test_engine_raw_parity_file_loaded(file_model):
    _, loaded = file_model
    assert loaded.train_set is None
    eng = ServingEngine(loaded, max_batch_rows=128, min_bucket_rows=32)
    assert eng.variant == "raw" and eng.device_ok, eng.degraded_reason
    rng = np.random.RandomState(3)
    for Xq in _queries(rng, [1, 19, 140]):
        np.testing.assert_allclose(eng.predict(Xq), loaded.predict(Xq),
                                   **TOL)


def test_engine_raw_leaf_routing_bit_identical(file_model):
    """Per-tree leaf ROUTING (not just the f32 score sum) must match
    the host walk exactly for float32-representable inputs — each
    single-tree device output equals leaf_value[host_leaf] cast f32."""
    _, loaded = file_model
    rng = np.random.RandomState(5)
    Xq = rng.rand(128, F).astype(np.float32)
    for ti, tree in enumerate(loaded.models[:3]):
        eng = ServingEngine(loaded, max_batch_rows=128,
                            min_bucket_rows=128, start_iteration=ti,
                            num_iteration=1)   # one tree at a time
        dev = eng.predict_raw(Xq)[0]
        host_leaves = tree.predict_leaf_index(Xq)
        expect = tree.leaf_value[host_leaves].astype(np.float32)
        np.testing.assert_array_equal(dev.astype(np.float32), expect)


def test_engine_zero_recompiles_after_warmup(bst):
    eng = ServingEngine(bst, max_batch_rows=128, min_bucket_rows=32)
    warm = eng.warmup()
    assert warm["warmed"] == [32, 64, 128]
    c0, d0 = eng.compiles, eng.dispatches
    rng = np.random.RandomState(7)
    sizes = [1, 3, 32, 33, 100, 128, 200, 5]
    for Xq in _queries(rng, sizes):
        eng.predict(Xq)
    assert eng.compiles == c0, "mixed-size stream recompiled after warmup"
    # one dispatch per <=128-row request; the 200-row one chunks into 2
    assert eng.dispatches - d0 == len(sizes) + 1


def test_engine_degrades_linear_tree_to_host_walk():
    rng = np.random.RandomState(8)
    X = rng.rand(300, 4)
    y = X @ np.array([1.0, 2.0, -1.0, 0.5]) + 0.05 * rng.randn(300)
    blin = lgb.train({"objective": "regression", "num_leaves": 5,
                      "verbose": -1, "linear_tree": True,
                      "min_data_in_leaf": 10},
                     lgb.Dataset(X, label=y), num_boost_round=2)
    from lightgbm_tpu.obs import Telemetry
    tel = Telemetry(enabled=True)
    eng = ServingEngine(blin, telemetry=tel)
    assert not eng.device_ok and eng.degraded_reason == "linear_tree"
    Xq = rng.rand(9, 4)
    np.testing.assert_allclose(eng.predict(Xq), blin.predict(Xq),
                               rtol=1e-9, atol=1e-12)
    snap = tel.snapshot()
    reasons = [e for e in snap["events"]
               if e["event"] == "serve_degradation"]
    assert reasons and reasons[0]["reason"] == "linear_tree"
    assert snap["counters"].get("serve.host_rows", 0) == 9


def test_engine_sparse_request():
    sp = pytest.importorskip("scipy.sparse")
    Xs = sp.random(400, 20, density=0.1, random_state=9, format="csr")
    ys = (np.asarray(Xs.sum(axis=1)).ravel() > 1.0).astype(np.float32)
    bsp = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbose": -1, "min_data_in_leaf": 5},
                    lgb.Dataset(Xs, label=ys), num_boost_round=3)
    Xq = sp.random(40, 20, density=0.1, random_state=10, format="csr")
    eng = ServingEngine(bsp, max_batch_rows=128, min_bucket_rows=32)
    np.testing.assert_allclose(eng.predict(Xq), bsp.predict(Xq), **TOL)


# --------------------------------------------------------------- batcher
def test_batcher_coalesces_slices_and_caps():
    calls = []

    def dispatch(mid, X):
        calls.append(X.shape[0])
        return X.sum(axis=1)

    b = MicroBatcher(dispatch, max_batch_rows=12, max_delay_ms=30.0)
    try:
        rng = np.random.RandomState(0)
        reqs = [rng.rand(3, 4) for _ in range(10)]
        futs = [b.submit("m", X) for X in reqs]
        outs = [f.result(timeout=10) for f in futs]
        for X, out in zip(reqs, outs):
            np.testing.assert_allclose(out, X.sum(axis=1))
        assert len(calls) < len(reqs)        # coalescing happened
        assert sum(calls) == 30
        assert all(c <= 12 for c in calls)   # strict row cap
    finally:
        b.close()


def test_batcher_isolates_models_and_errors():
    def dispatch(mid, X):
        if mid == "bad":
            raise ValueError("boom")
        return np.full(X.shape[0], 7.0)

    b = MicroBatcher(dispatch, max_batch_rows=64, max_delay_ms=5.0)
    try:
        ok = b.submit("good", np.zeros((2, 2)))
        bad = b.submit("bad", np.zeros((2, 2)))
        np.testing.assert_allclose(ok.result(timeout=10), [7.0, 7.0])
        with pytest.raises(ValueError, match="boom"):
            bad.result(timeout=10)
        # the queue survives the poisoned request
        again = b.submit("good", np.zeros((1, 2)))
        np.testing.assert_allclose(again.result(timeout=10), [7.0])
    finally:
        b.close()


def test_batcher_groups_by_column_count():
    widths = []

    def dispatch(mid, X):
        widths.append(X.shape[1])
        return np.zeros(X.shape[0])

    b = MicroBatcher(dispatch, max_batch_rows=64, max_delay_ms=30.0)
    try:
        f1 = b.submit("m", np.zeros((2, 4)))
        f2 = b.submit("m", np.zeros((2, 5)))   # different width
        f3 = b.submit("m", np.zeros((2, 4)))
        for f in (f1, f2, f3):
            f.result(timeout=10)
        # width-4 requests coalesced; the width-5 one dispatched alone
        # (np.concatenate across widths would have failed all three)
        assert sorted(widths) == [4, 5]
    finally:
        b.close()


def test_batcher_cancelled_future_does_not_wedge_worker():
    import threading
    import time as _t
    block = threading.Event()

    def dispatch(mid, X):
        block.wait(2)
        return np.zeros(X.shape[0])

    b = MicroBatcher(dispatch, max_batch_rows=1, max_delay_ms=1.0)
    try:
        f1 = b.submit("a", np.zeros((1, 2)))   # worker blocks in here
        _t.sleep(0.05)
        f2 = b.submit("a", np.zeros((1, 2)))   # still queued
        assert f2.cancel()                     # cancelled while pending
        block.set()
        f1.result(timeout=5)
        # the worker survived serving the cancelled request's batch
        f3 = b.submit("a", np.zeros((1, 2)))
        f3.result(timeout=5)
    finally:
        block.set()
        b.close()


def test_batcher_close_rejects_new_submits():
    b = MicroBatcher(lambda mid, X: np.zeros(X.shape[0]))
    b.close()
    fut = b.submit("m", np.zeros((1, 2)))
    with pytest.raises(RuntimeError):
        fut.result(timeout=5)


# ------------------------------------------------------------- residency
def test_residency_lru_eviction_and_pin(bst):
    from lightgbm_tpu.obs import Telemetry
    tel = Telemetry(enabled=True)
    # three model ids over the SAME booster: identical packed bytes and
    # jit signatures (no extra compiles), distinct resident engines
    one = ServingEngine(bst, max_batch_rows=128,
                        min_bucket_rows=32).packed_nbytes
    assert one > 0
    mgr = ResidencyManager(budget_bytes=int(one * 2.5), telemetry=tel,
                           max_batch_rows=128, min_bucket_rows=32)
    for i in range(3):
        mgr.register(f"m{i}", bst)
    mgr.get("m0")
    mgr.get("m1")
    assert set(mgr.resident()) == {"m0", "m1"}
    mgr.get("m2")                      # over budget: m0 is LRU
    assert set(mgr.resident()) == {"m1", "m2"}
    snap = tel.snapshot()
    assert snap["counters"]["serve.evictions"] == 1
    ev = [e for e in snap["events"] if e["event"] == "serve_eviction"]
    assert ev and ev[0]["model_id"] == "m0"
    # re-use rebuilds m0 (and evicts the new LRU, m1)
    mgr.get("m0")
    assert "m0" in mgr.resident() and "m1" not in mgr.resident()
    assert tel.snapshot()["counters"]["serve.rebuilds"] == 1
    # pinned models are never evicted
    mgr.pin("m2")
    mgr.get("m1")
    assert "m2" in mgr.resident()
    with pytest.raises(KeyError):
        mgr.get("nope")


# --------------------------------------------------------------- service
def test_service_acceptance_mixed_sizes_zero_recompiles(bst, file_model):
    """The ISSUE acceptance test: warmup, then a mixed-size request
    stream over a live AND a file-loaded model shows (counter-asserted)
    zero recompiles and <=1 device dispatch per micro-batch, with
    outputs matching Booster.predict within the f32 tolerance."""
    path, loaded = file_model
    svc = PredictionService({"live": bst, "file": path},
                            max_batch_rows=128, max_delay_ms=1.0,
                            min_bucket_rows=32, batch_events=False)
    try:
        svc.warmup()
        s0 = svc.stats()
        rng = np.random.RandomState(31)
        sizes = [1, 2, 17, 40, 100, 128, 9, 33]
        for i, Xq in enumerate(_queries(rng, sizes)):
            mid = ("live", "file")[i % 2]
            got = svc.predict(mid, Xq)
            want = (bst if mid == "live" else loaded).predict(Xq)
            np.testing.assert_allclose(got, want, **TOL)
        s1 = svc.stats()
        assert s1["compiles"] == s0["compiles"], \
            "request stream compiled after warmup"
        batches = s1["batches"] - s0["batches"]
        dispatches = s1["dispatches"] - s0["dispatches"]
        assert batches == len(sizes)          # sequential: no coalescing
        assert dispatches <= batches          # <=1 dispatch per batch
    finally:
        svc.close()


def test_service_concurrent_submits_coalesce(bst):
    svc = PredictionService({"m": bst}, max_batch_rows=128,
                            max_delay_ms=20.0, min_bucket_rows=32,
                            batch_events=False)
    try:
        svc.warmup()
        s0 = svc.stats()
        rng = np.random.RandomState(33)
        reqs = [rng.rand(4, F).astype(np.float32) for _ in range(16)]
        futs = [svc.submit("m", X) for X in reqs]
        outs = [f.result(timeout=30) for f in futs]
        for X, out in zip(reqs, outs):
            np.testing.assert_allclose(out, bst.predict(X), **TOL)
        s1 = svc.stats()
        batches = s1["batches"] - s0["batches"]
        assert batches < len(reqs), "no coalescing happened"
        assert s1["dispatches"] - s0["dispatches"] <= batches
        assert s1["latency_ms"] and s1["latency_ms"]["count"] >= 16
    finally:
        svc.close()


def test_service_telemetry_jsonl_events(bst, tmp_path):
    out = str(tmp_path / "serve.jsonl")
    svc = PredictionService({"m": bst}, telemetry_out=out,
                            max_delay_ms=1.0, max_batch_rows=128,
                            min_bucket_rows=32)
    try:
        svc.warmup()
        svc.predict("m", np.random.RandomState(35).rand(5, F))
    finally:
        svc.close()
    import json
    events = [json.loads(line) for line in open(out)]
    names = {e["event"] for e in events}
    assert {"serve_start", "serve_model_loaded", "serve_warmup",
            "serve_batch", "serve_stats"} <= names
    batch = next(e for e in events if e["event"] == "serve_batch")
    assert batch["rows"] == 5 and batch["requests"] == 1
    stats = next(e for e in events if e["event"] == "serve_stats")
    assert stats["requests"] == 1 and stats["dispatches_per_request"] >= 1


def test_service_specs_raw_score_num_iteration(bst, tmp_path):
    svc = PredictionService([bst], max_delay_ms=1.0, max_batch_rows=128,
                            min_bucket_rows=32, raw_score=True,
                            num_iteration=3)
    try:
        assert svc.model_ids() == ["0"]
        Xq = np.random.RandomState(38).rand(21, F).astype(np.float32)
        np.testing.assert_allclose(
            svc.predict("0", Xq),
            bst.predict(Xq, raw_score=True, num_iteration=3), **TOL)
        with pytest.raises(KeyError):
            svc.submit("1", np.zeros((1, F)))
    finally:
        svc.close()
    with pytest.raises(FileNotFoundError):
        PredictionService({"x": str(tmp_path / "missing.txt")})
