"""Valid sets on the pipelined fast path (round 3, VERDICT r2 weak #3):
valid-score updates run in-jit from device TreeArrays and metric eval
pulls scalars — the fast path must no longer be disabled by valid sets,
and results must match the synchronous path exactly (interpret mode)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb


@pytest.fixture(scope="module")
def data():
    rng = np.random.RandomState(9)
    n = 4000
    X = rng.randn(n, 10)
    X[rng.rand(n, 10) < 0.04] = np.nan
    y = (np.nan_to_num(X[:, 0]) + 0.4 * np.nan_to_num(X[:, 1]) ** 2
         > 0.3).astype(np.float32)
    return X[:3000], y[:3000], X[3000:], y[3000:]


BASE = {"objective": "binary", "num_leaves": 15, "verbose": -1,
        "tpu_engine": "fused", "metric": ["auc", "binary_logloss"]}


def _run(data, extra, rounds=25, es=None):
    Xt, yt, Xv, yv = data
    ds = lgb.Dataset(Xt, label=yt)
    dv = lgb.Dataset(Xv, label=yv, reference=ds)
    rec = {}
    cbs = [lgb.record_evaluation(rec)]
    if es:
        cbs.append(lgb.early_stopping(es, verbose=False))
    bst = lgb.train(dict(BASE, **extra), ds, num_boost_round=rounds,
                    valid_sets=[dv], valid_names=["v"], callbacks=cbs)
    return bst, rec


def test_fast_path_stays_on_with_valid(data):
    bst, _ = _run(data, {})
    assert bst._gbdt._fast_path_ok()
    assert bst._gbdt._use_epilogue()


def test_valid_traces_match_unfused_path(data):
    _, rec_fast = _run(data, {})
    _, rec_off = _run(data, {"tpu_fused_epilogue": False})
    np.testing.assert_allclose(rec_fast["v"]["auc"], rec_off["v"]["auc"],
                               atol=2e-6)
    np.testing.assert_allclose(rec_fast["v"]["binary_logloss"],
                               rec_off["v"]["binary_logloss"], atol=2e-6)


def test_device_metrics_match_host_metrics(data):
    Xt, yt, Xv, yv = data
    bst, rec = _run(data, {})
    from sklearn.metrics import log_loss, roc_auc_score
    p = bst.predict(Xv)
    assert abs(rec["v"]["auc"][-1] - roc_auc_score(yv, p)) < 1e-5
    assert abs(rec["v"]["binary_logloss"][-1] - log_loss(yv, p)) < 1e-5


def test_early_stopping_fires_on_fast_path(data):
    # flip 35% of the valid labels so the valid metric degrades and ES
    # actually fires (the pop path needs drained host trees)
    Xt, yt, Xv, yv = data
    rng = np.random.RandomState(0)
    yv2 = yv.copy()
    flip = rng.rand(len(yv2)) < 0.35
    yv2[flip] = 1 - yv2[flip]
    bst, rec = _run((Xt, yt, Xv, yv2), {"learning_rate": 0.3}, rounds=60,
                    es=3)
    assert 0 < bst.best_iteration < 60
    # stock LightGBM keeps the overrun trees; predict defaults to
    # best_iteration
    assert bst.num_trees() >= bst.best_iteration
    b_off, rec_off = _run((Xt, yt, Xv, yv2),
                          {"learning_rate": 0.3,
                           "tpu_fused_epilogue": False}, rounds=60, es=3)
    assert bst.best_iteration == b_off.best_iteration


def test_multiclass_valid_on_fast_path(data):
    Xt, yt, Xv, yv = data
    rng = np.random.RandomState(4)
    y3t = (rng.rand(len(yt)) * 3).astype(int)
    y3v = (rng.rand(len(yv)) * 3).astype(int)
    params = {"objective": "multiclass", "num_class": 3, "num_leaves": 7,
              "verbose": -1, "tpu_engine": "fused",
              "metric": "multi_logloss"}
    ds = lgb.Dataset(Xt, label=y3t)
    dv = lgb.Dataset(Xv, label=y3v, reference=ds)
    rec = {}
    bst = lgb.train(params, ds, num_boost_round=5, valid_sets=[dv],
                    callbacks=[lgb.record_evaluation(rec)])
    assert bst._gbdt._fast_path_ok()   # multiclass: fast path, no epilogue
    assert not bst._gbdt._use_epilogue()
    # the recorded (device-evaluated) final metric must match the metric
    # computed from a fresh host predict of the same model
    from sklearn.metrics import log_loss
    p = bst.predict(Xv)
    assert abs(rec["valid_0"]["multi_logloss"][-1]
               - log_loss(y3v, p, labels=[0, 1, 2])) < 1e-5
    # cross-engine (bf16-hi/lo fused vs f32 XLA) only agrees to ~1e-4
    ds2 = lgb.Dataset(Xt, label=y3t)
    dv2 = lgb.Dataset(Xv, label=y3v, reference=ds2)
    rec2 = {}
    lgb.train(dict(params, tpu_engine="xla", grow_policy="depthwise"),
              ds2, num_boost_round=5, valid_sets=[dv2],
              callbacks=[lgb.record_evaluation(rec2)])
    np.testing.assert_allclose(rec["valid_0"]["multi_logloss"],
                               rec2["valid_0"]["multi_logloss"], atol=5e-4)


def test_no_split_stop_rolls_back_valid_scores(data):
    # min_data so large that training dries up mid-batch: the deferred
    # stop must subtract the discarded iterations from VALID scores too
    Xt, yt, Xv, yv = data
    ds = lgb.Dataset(Xt, label=yt)
    dv = lgb.Dataset(Xv, label=yv, reference=ds)
    rec = {}
    bst = lgb.train(dict(BASE, min_gain_to_split=60.0, learning_rate=0.3),
                    ds, num_boost_round=40, valid_sets=[dv],
                    valid_names=["v"],
                    callbacks=[lgb.record_evaluation(rec)])
    n_kept = bst.num_trees()
    assert n_kept < 40
    # the final valid score must equal a fresh replay of the kept model
    import jax.numpy as jnp
    g = bst._gbdt
    replay = np.asarray(bst.predict(Xv, raw_score=True))
    np.testing.assert_allclose(np.asarray(g.valid_scores[0][0]), replay,
                               atol=1e-4)
