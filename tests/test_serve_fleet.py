"""Serving fleet (docs/Serving.md "Serving fleet").

Covers the multi-device serving plane: per-device replication of the
packed tree tensors (one host-side pack, N committed placements),
honest per-device byte accounting audited against the live device
buffers, least-loaded lane routing with the per-device deterministic
contract (dispatches_per_request == 1.0, compiles_per_1k == 0 on every
routed device), admission spill to the coldest lane before a shed,
queue-depth gauges published on submit (a stalled worker's backlog is
visible between drains), atomic all-replica rollover, and row-sharded
``predict_bulk`` numerical identity with the single-device dispatch.

tests/conftest.py forces ``--xla_force_host_platform_device_count=8``,
so the whole suite runs these paths on a real multi-device topology;
tests that NEED more than one device skip gracefully elsewhere.
"""
import gc
import threading
import time

import numpy as np
import pytest

import jax

import lightgbm_tpu as lgb
from lightgbm_tpu.obs import Telemetry
from lightgbm_tpu.serve import (MicroBatcher, PredictionService,
                                ResidencyManager, ServingEngine)
from lightgbm_tpu.serve.errors import ServeRejected

TOL = dict(rtol=1e-5, atol=1e-6)   # f32 device accumulation vs f64 host
F = 8
NDEV = len(jax.local_devices())
fleet = pytest.mark.skipif(
    NDEV < 2, reason="needs >= 2 local devices (tests/conftest.py "
    "forces 8 on the CPU backend)")


def _train(seed=0, n=400, f=F, rounds=6, **extra):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float32)
    params = {"objective": "binary", "num_leaves": 15,
              "learning_rate": 0.2, "verbose": -1, "min_data_in_leaf": 5}
    params.update(extra)
    return lgb.train(params, lgb.Dataset(X, label=y),
                     num_boost_round=rounds)


@pytest.fixture(scope="module")
def bst():
    return _train(seed=0)


@pytest.fixture(scope="module")
def bst_multi():
    rng = np.random.RandomState(7)
    X = rng.rand(300, F).astype(np.float32)
    y = rng.randint(0, 3, 300).astype(np.float32)
    return lgb.train({"objective": "multiclass", "num_class": 3,
                      "num_leaves": 15, "verbose": -1,
                      "min_data_in_leaf": 5},
                     lgb.Dataset(X, label=y), num_boost_round=5)


def _svc(models, **kw):
    kw.setdefault("max_batch_rows", 64)
    kw.setdefault("min_bucket_rows", 16)
    kw.setdefault("max_delay_ms", 1.0)
    kw.setdefault("batch_events", False)
    return PredictionService(models, **kw)


# ----------------------------------------------------------- accounting
@fleet
def test_residency_bytes_match_live_device_buffers(bst):
    """The budget accounting charges what the device actually holds:
    per device, ``resident_bytes_on(d)`` must land within 10% of the
    bytes of the live jax buffers the build placed there (the old
    estimate summed the base packing per replica and missed the
    slice/copy operands entirely)."""
    devices = jax.local_devices()[:2]
    gc.collect()
    # keep the baseline arrays alive so their ids cannot be recycled
    baseline = list(jax.live_arrays())
    before = {id(a) for a in baseline}
    rm = ResidencyManager(devices=devices, max_batch_rows=128,
                          min_bucket_rows=32)
    rm.register("m", bst)
    rm.get("m", 0)
    rm.get("m", 1)
    gc.collect()
    fresh = [a for a in jax.live_arrays() if id(a) not in before]
    for d, dev in enumerate(devices):
        actual = sum(int(a.nbytes) for a in fresh
                     if a.devices() == {dev})
        est = rm.resident_bytes_on(d)
        assert est > 0 and actual > 0
        assert abs(actual - est) <= 0.10 * actual, \
            f"device {d}: actual={actual} est={est}"
    del baseline


def test_full_range_engine_aliases_packed_no_copy(bst):
    """A full-tree-range engine hands the packed arrays straight to the
    runner — run_args must NOT materialize slice copies (which would
    double true residency), and the charge is the owned packing plus
    only the small derived operands (tree-id vector)."""
    eng = ServingEngine(bst, max_batch_rows=128, min_bucket_rows=32)
    packed = [x for x in eng.pred._packed if x is not None]
    packed_ids = {id(x) for x in packed}
    aliased = [a for a in eng._operands
               if a is not None and id(a) in packed_ids]
    assert len(aliased) == len(packed)
    assert eng.packed_nbytes < 1.10 * eng.pred.packed_nbytes


def test_sub_range_engine_charges_its_slices(bst):
    """num_iteration < total forces real slice copies — the accounting
    must charge them on top of the base packing, not pretend the engine
    costs the same as the full-range one."""
    eng = ServingEngine(bst, max_batch_rows=128, min_bucket_rows=32,
                        num_iteration=3)
    assert eng.num_iteration == 3
    assert eng.packed_nbytes > eng.pred.packed_nbytes


@fleet
def test_replica_shares_packing_and_charges_copies(bst):
    """A replica on another device reuses the base engine's host-side
    packing (one pack per model) but its committed operand copies are
    its own bytes — charged to ITS device."""
    devices = jax.local_devices()[:2]
    rm = ResidencyManager(devices=devices, max_batch_rows=128,
                          min_bucket_rows=32)
    rm.register("m", bst)
    base = rm.get("m", 0)
    rep = rm.get("m", 1)
    assert rep.pred is base.pred          # shared packing, no re-pack
    assert rep.model_hash == base.model_hash
    assert base._owns_pred and not rep._owns_pred
    assert rep.packed_nbytes > 0          # the replica copies are real
    assert rm.resident_bytes_on(1) == rep.packed_nbytes
    # and every replica operand actually lives on its device
    for a in rep._operands:
        if a is not None and hasattr(a, "devices"):
            assert a.devices() == {devices[1]}


# -------------------------------------------------------------- routing
@fleet
def test_fleet_routes_every_device_with_per_device_contract(bst):
    """A sequential closed loop must still exercise EVERY device (idle
    ties rotate), and after warmup every routed device honors the
    deterministic contract: exactly 1.0 dispatches/request, 0
    steady-state recompiles."""
    svc = _svc({"m": bst})
    try:
        assert svc.n_devices == NDEV
        svc.warmup()
        rng = np.random.RandomState(3)
        n_req = 4 * NDEV
        for _ in range(n_req):
            Xq = rng.rand(16, F).astype(np.float32)
            np.testing.assert_allclose(svc.predict("m", Xq),
                                       bst.predict(Xq), **TOL)
        st = svc.stats()
        fl = st["fleet"]
        assert fl["devices"] == NDEV
        assert fl["routed_devices"] == NDEV
        per = fl["per_device"]
        assert sum(e["requests"] for e in per) == n_req
        for e in per:
            assert e["requests"] > 0
            assert e["dispatches_per_request"] == 1.0, e
            assert e["compiles_per_1k_requests"] == 0.0, e
        # the aggregate contract holds too
        assert st["dispatches_per_request"] == 1.0
        assert st["compiles_per_1k_requests"] == 0.0
    finally:
        svc.close()


@fleet
def test_round_robin_routing_spreads_exactly(bst):
    svc = _svc({"m": bst}, routing="round_robin")
    try:
        svc.warmup()
        rng = np.random.RandomState(5)
        for _ in range(3 * NDEV):
            svc.predict("m", rng.rand(8, F).astype(np.float32))
        fl = svc.stats()["fleet"]
        assert fl["routing"] == "round_robin"
        assert [e["requests"] for e in fl["per_device"]] == [3] * NDEV
    finally:
        svc.close()


def test_single_device_plane_has_no_fleet_surface(bst):
    """serve_devices=1 is the pre-fleet plane: one lane, two-argument
    dispatch callback, no fleet stats section."""
    svc = _svc({"m": bst}, serve_devices=1)
    try:
        assert svc.devices is None and svc.n_devices == 1
        assert svc.batcher.n_lanes == 1
        svc.warmup()
        rng = np.random.RandomState(9)
        Xq = rng.rand(10, F).astype(np.float32)
        np.testing.assert_allclose(svc.predict("m", Xq),
                                   bst.predict(Xq), **TOL)
        assert "fleet" not in svc.stats()
    finally:
        svc.close()


# ---------------------------------------------------- spill & admission
def _wedge_lanes(batcher, n, gate, rows=1):
    """Occupy every lane's worker inside a gated dispatch and wait
    until all of them are busy."""
    futs = [batcher.submit("m", np.zeros((rows, F), np.float32))
            for _ in range(n)]
    deadline = time.time() + 10.0
    while any(lane.busy_rows == 0 for lane in batcher._lanes):
        assert time.time() < deadline, "workers never picked up"
        time.sleep(0.005)
    return futs


def test_spill_to_coldest_lane_before_shed():
    """A submit its routed lane must reject goes to the coldest lane
    with room (counted, evented) — only when EVERY lane is full does
    admission control shed."""
    tel = Telemetry(enabled=True)
    gate = threading.Event()

    def dispatch(model_id, X, device):
        gate.wait(10.0)
        return np.zeros((X.shape[0],))

    b = MicroBatcher(dispatch, max_batch_rows=8, max_delay_ms=1.0,
                     telemetry=tel, max_queue_rows=4, n_lanes=2)
    try:
        busy = _wedge_lanes(b, 2, gate)
        # pin routing to lane 0: the spill mechanics, not the routing
        # policy, are under test here
        b._pick_lane = lambda: b._lanes[0]
        f1 = b.submit("m", np.zeros((2, F), np.float32))
        assert b._lanes[0].q_rows == 2       # lane cap = ceil(4/2) = 2
        f2 = b.submit("m", np.zeros((2, F), np.float32))
        assert b._lanes[1].q_rows == 2       # spilled, not shed
        c = tel.snapshot()["counters"]
        assert c.get("serve.spills") == 1
        assert c.get("serve.d1.spills") == 1
        with pytest.raises(ServeRejected):   # both lanes full now
            b.submit("m", np.zeros((2, F), np.float32))
        gate.set()
        for f in busy + [f1, f2]:
            f.result(timeout=10.0)
        events = [e for e in tel.snapshot()["events"]
                  if e["event"] == "serve_spill"]
        assert events and events[0]["to_device"] == 1
    finally:
        gate.set()
        b.close(drain_timeout_s=5.0)
        tel.close()


def test_queue_gauges_published_on_submit_while_worker_stalled():
    """The backlog behind a stalled worker must be visible WITHOUT a
    drain: submit itself refreshes the aggregate and per-lane
    queue-depth/rows gauges."""
    tel = Telemetry(enabled=True)
    gate = threading.Event()

    def dispatch(model_id, X, device):
        gate.wait(10.0)
        return np.zeros((X.shape[0],))

    b = MicroBatcher(dispatch, max_batch_rows=4, max_delay_ms=1.0,
                     telemetry=tel, n_lanes=2)
    try:
        busy = _wedge_lanes(b, 2, gate)
        b._pick_lane = lambda: b._lanes[0]
        queued = [b.submit("m", np.zeros((2, F), np.float32))
                  for _ in range(3)]
        g = tel.snapshot()["gauges"]
        assert g["serve.queue_depth"] == 3
        assert g["serve.queue_rows"] == 6
        assert g["serve.d0.queue_depth"] == 3
        assert g["serve.d0.queue_rows"] == 6
        gate.set()
        for f in busy + queued:
            f.result(timeout=10.0)
    finally:
        gate.set()
        b.close(drain_timeout_s=5.0)
        tel.close()


def test_sustained_imbalance_per_lane_skew_and_spill_sums():
    """Per-lane queue gauges + spill counters under SUSTAINED imbalance:
    every worker is wedged inside a gated dispatch (lane 0 plays the
    slow-faulted lane traffic keeps targeting), the flood pins to lane
    0 until it fills, and the excess spills toward the colder lanes.
    The skew must be visible in the per-lane gauges, the spill-to-
    coldest counters must advance on the receiving lanes only, and the
    aggregate gauges/counters must equal the per-lane sums exactly (no
    double or lost accounting)."""
    tel = Telemetry(enabled=True)
    gates = {d: threading.Event() for d in range(4)}

    def dispatch(model_id, X, device):
        gates[device].wait(10.0)
        return np.zeros((X.shape[0],))

    b = MicroBatcher(dispatch, max_batch_rows=4, max_delay_ms=1.0,
                     telemetry=tel, max_queue_rows=32, n_lanes=4)
    try:
        busy = _wedge_lanes(b, 4, None)
        # pin routing to the faulted lane: spill mechanics under test
        b._pick_lane = lambda: b._lanes[0]
        # lane cap = ceil(32/4) = 8 rows: 4 submits fill lane 0, the
        # next 6 must spill (12 rows spread over lanes 1-3)
        futs = [b.submit("m", np.zeros((2, F), np.float32))
                for _ in range(10)]
        g = tel.snapshot()["gauges"]
        assert g["serve.d0.queue_depth"] == 4
        assert g["serve.d0.queue_rows"] == 8
        for d in (1, 2, 3):
            assert g[f"serve.d{d}.queue_rows"] > 0
            assert g["serve.d0.queue_depth"] > \
                g[f"serve.d{d}.queue_depth"]
        assert sum(g[f"serve.d{d}.queue_rows"] for d in (1, 2, 3)) == 12
        # aggregates are EXACTLY the per-lane sums
        assert g["serve.queue_depth"] == sum(
            g[f"serve.d{d}.queue_depth"] for d in range(4))
        assert g["serve.queue_rows"] == sum(
            g[f"serve.d{d}.queue_rows"] for d in range(4))
        c = tel.snapshot()["counters"]
        assert c.get("serve.spills") == 6
        assert sum(c.get(f"serve.d{d}.spills", 0)
                   for d in range(4)) == c["serve.spills"]
        assert c.get("serve.d0.spills", 0) == 0   # full lane never gains
        for d in (1, 2, 3):
            assert c.get(f"serve.d{d}.spills", 0) >= 1
        for gate in gates.values():
            gate.set()
        for f in busy + futs:
            f.result(timeout=10.0)
    finally:
        for gate in gates.values():
            gate.set()
        b.close(drain_timeout_s=5.0)
        tel.close()


# ------------------------------------------------------------- rollover
@fleet
def test_fleet_rollover_swaps_every_replica_atomically(bst):
    b2 = _train(seed=1, rounds=8)
    svc = _svc({"m": bst})
    try:
        svc.warmup()
        rng = np.random.RandomState(13)
        X = rng.rand(200, F).astype(np.float32)
        old_hash = svc.residency.get("m", 0).model_hash
        rep = svc.rollover("m", b2)
        assert rep["promoted"]
        hashes = {svc.residency.get("m", d).model_hash
                  for d in range(svc.n_devices)}
        assert len(hashes) == 1 and old_hash not in hashes
        np.testing.assert_allclose(svc.predict("m", X), b2.predict(X),
                                   **TOL)
        # the cached bulk scorer rebuilt from the promoted replica
        np.testing.assert_allclose(svc.predict_bulk("m", X),
                                   b2.predict(X), **TOL)
    finally:
        svc.close()


# ----------------------------------------------------------------- bulk
@fleet
def test_predict_bulk_identical_to_single_device_dispatch(bst):
    svc = _svc({"m": bst}, max_batch_rows=256, min_bucket_rows=32)
    try:
        svc.warmup()
        rng = np.random.RandomState(11)
        X = rng.rand(1000, F).astype(np.float32)
        single = svc.residency.get("m", 0).predict(X)
        bulk = svc.predict_bulk("m", X)
        assert bulk.shape == single.shape
        np.testing.assert_allclose(bulk, single, **TOL)
        np.testing.assert_allclose(bulk, bst.predict(X), **TOL)
        sp = pytest.importorskip("scipy.sparse")
        np.testing.assert_allclose(
            svc.predict_bulk("m", sp.csr_matrix(X)), single, **TOL)
        fl = svc.stats()["fleet"]
        assert fl["bulk_rows"] == 2 * X.shape[0]
        assert fl["bulk_dispatches"] >= 2
    finally:
        svc.close()


@fleet
def test_predict_bulk_multiclass_and_raw_score(bst_multi):
    svc = _svc({"mc": bst_multi}, max_batch_rows=128)
    try:
        svc.warmup()
        rng = np.random.RandomState(17)
        X = rng.rand(500, F).astype(np.float32)
        eng = svc.residency.get("mc", 0)
        np.testing.assert_allclose(svc.predict_bulk("mc", X),
                                   eng.predict(X), **TOL)
        np.testing.assert_allclose(
            svc.predict_bulk("mc", X, raw_score=True),
            eng.predict(X, raw_score=True), **TOL)
    finally:
        svc.close()


@fleet
def test_predict_bulk_degraded_model_falls_back_to_host_walk():
    """A model the device path cannot represent (linear trees) must
    serve predict_bulk through the exact host walk — never a sharded
    dispatch, never an error."""
    rng = np.random.RandomState(8)
    X = rng.rand(300, 4)
    y = X @ np.array([1.0, 2.0, -1.0, 0.5]) + 0.05 * rng.randn(300)
    blin = lgb.train({"objective": "regression", "num_leaves": 5,
                      "verbose": -1, "linear_tree": True,
                      "min_data_in_leaf": 10},
                     lgb.Dataset(X, label=y), num_boost_round=2)
    svc = _svc({"lin": blin})
    try:
        Xq = rng.rand(50, 4)
        np.testing.assert_allclose(svc.predict_bulk("lin", Xq),
                                   blin.predict(Xq),
                                   rtol=1e-9, atol=1e-12)
        assert svc.stats()["fleet"]["bulk_rows"] == 0
    finally:
        svc.close()


@fleet
def test_bulk_steady_stream_recompiles_nothing(bst):
    """Repeat bulk calls with the same shard bucket must be pure cache
    hits — the bulk signatures live in the same process-wide registry
    the online engines gate on."""
    svc = _svc({"m": bst}, max_batch_rows=128)
    try:
        svc.warmup()
        rng = np.random.RandomState(19)
        X = rng.rand(800, F).astype(np.float32)
        svc.predict_bulk("m", X)
        c0 = svc.stats()["fleet"]["bulk_compiles"]
        for _ in range(3):
            svc.predict_bulk("m", X)
        fl = svc.stats()["fleet"]
        assert fl["bulk_compiles"] == c0
        assert fl["bulk_dispatches"] >= 4
    finally:
        svc.close()
