"""Split finder unit tests vs a numpy oracle
(behavior mirrors ref: src/treelearner/feature_histogram.hpp)."""
import jax.numpy as jnp
import numpy as np
import pytest

from lightgbm_tpu.ops.split import (SplitParams, best_numerical_split,
                                    calculate_leaf_output, leaf_gain)


def brute_force_best(hist, num_bin, missing_type, default_bin, p):
    """Oracle: try every (feature, threshold, direction) by direct partition."""
    S, F, B, _ = hist.shape
    best = []
    for s in range(S):
        best_gain, best_f, best_t = -np.inf, -1, -1
        tot_g = hist[s, 0, :, 0].sum()
        tot_h = hist[s, 0, :, 1].sum()
        tot_c = hist[s, 0, :, 2].sum()
        shift = (max(abs(tot_g) - p.lambda_l1, 0.0) * np.sign(tot_g)) ** 2 \
            / (tot_h + p.lambda_l2)
        for f in range(F):
            nb = num_bin[f]
            mt = missing_type[f]
            db = default_bin[f]
            for t in range(nb - 1):
                for miss_left in ([True, False] if mt else [True]):
                    g = hist[s, f, :nb, 0].copy()
                    h = hist[s, f, :nb, 1].copy()
                    c = hist[s, f, :nb, 2].copy()
                    left = np.arange(nb) <= t
                    if mt == 2:  # NaN rides the missing direction (last bin)
                        left[nb - 1] = miss_left
                    if mt == 1:  # zero bin rides the missing direction
                        left[db] = miss_left
                    lg, lh, lc = g[left].sum(), h[left].sum(), c[left].sum()
                    rg, rh, rc = g[~left].sum(), h[~left].sum(), c[~left].sum()
                    if lc < p.min_data_in_leaf or rc < p.min_data_in_leaf:
                        continue
                    if lh < p.min_sum_hessian_in_leaf \
                            or rh < p.min_sum_hessian_in_leaf:
                        continue
                    def lgain(sg, sh):
                        tg = max(abs(sg) - p.lambda_l1, 0.0) * np.sign(sg)
                        return tg * tg / (sh + p.lambda_l2)
                    gain = lgain(lg, lh) + lgain(rg, rh)
                    if gain > best_gain + 1e-10 and gain > shift \
                            + p.min_gain_to_split:
                        best_gain, best_f, best_t = gain, f, t
        best.append((best_f, best_t, best_gain - shift))
    return best


def make_hist(rng, S=1, F=4, B=16, num_bin=None):
    hist = rng.rand(S, F, B, 3).astype(np.float32)
    hist[..., 1] += 0.1
    hist[..., 2] = (hist[..., 2] * 30).astype(np.int32)
    nb = num_bin if num_bin is not None else np.full(F, B, np.int32)
    for f in range(F):
        hist[:, f, nb[f]:, :] = 0.0
    # all features must share per-slot totals (they bin the same rows);
    # rescale feature 0's totals onto the others
    for s in range(S):
        tg = hist[s, 0, :, 0].sum()
        th = hist[s, 0, :, 1].sum()
        tc = hist[s, 0, :, 2].sum()
        for f in range(1, F):
            cg = hist[s, f, :nb[f], 0].sum()
            hist[s, f, :nb[f], 0] *= tg / cg if cg != 0 else 0
            hist[s, f, :nb[f], 1] *= th / hist[s, f, :nb[f], 1].sum()
            c = hist[s, f, :nb[f], 2]
            # adjust counts to match total by dumping remainder in bin 0
            diff = tc - c.sum()
            c[0] += diff
    return hist, nb


def test_matches_bruteforce_no_missing():
    rng = np.random.RandomState(0)
    p = SplitParams(min_data_in_leaf=1, min_sum_hessian_in_leaf=0.0)
    hist, nb = make_hist(rng, S=2, F=4, B=16)
    mt = np.zeros(4, np.int32)
    db = np.zeros(4, np.int32)
    res = best_numerical_split(
        jnp.asarray(hist), jnp.asarray(nb), jnp.asarray(mt), jnp.asarray(db),
        jnp.ones(4, bool), jnp.zeros(4, jnp.int32), p, jnp.zeros(2))
    oracle = brute_force_best(hist.astype(np.float64), nb, mt, db, p)
    for s in range(2):
        of, ot, og = oracle[s]
        assert int(res.feature[s]) == of
        assert int(res.threshold[s]) == ot
        assert float(res.gain[s]) == pytest.approx(og, rel=1e-4)


def test_l1_l2_regularization_gains():
    rng = np.random.RandomState(1)
    p = SplitParams(lambda_l1=0.5, lambda_l2=2.0, min_data_in_leaf=1,
                    min_sum_hessian_in_leaf=0.0)
    hist, nb = make_hist(rng, S=1, F=3, B=8)
    mt = np.zeros(3, np.int32)
    db = np.zeros(3, np.int32)
    res = best_numerical_split(
        jnp.asarray(hist), jnp.asarray(nb), jnp.asarray(mt), jnp.asarray(db),
        jnp.ones(3, bool), jnp.zeros(3, jnp.int32), p, jnp.zeros(1))
    oracle = brute_force_best(hist.astype(np.float64), nb, mt, db, p)
    assert int(res.feature[0]) == oracle[0][0]
    assert float(res.gain[0]) == pytest.approx(oracle[0][2], rel=1e-4)


def test_min_data_in_leaf_blocks_splits():
    rng = np.random.RandomState(2)
    hist, nb = make_hist(rng, S=1, F=2, B=4)
    hist[..., 2] = 1.0  # 4 data per feature total
    p = SplitParams(min_data_in_leaf=100)
    res = best_numerical_split(
        jnp.asarray(hist), jnp.asarray(nb), jnp.zeros(2, jnp.int32),
        jnp.zeros(2, jnp.int32), jnp.ones(2, bool), jnp.zeros(2, jnp.int32),
        p, jnp.zeros(1))
    assert int(res.feature[0]) == -1
    assert not np.isfinite(float(res.gain[0]))


def test_feature_mask_excludes():
    rng = np.random.RandomState(3)
    p = SplitParams(min_data_in_leaf=1, min_sum_hessian_in_leaf=0.0)
    hist, nb = make_hist(rng, S=1, F=3, B=8)
    mt = np.zeros(3, np.int32)
    db = np.zeros(3, np.int32)
    full = best_numerical_split(
        jnp.asarray(hist), jnp.asarray(nb), jnp.asarray(mt), jnp.asarray(db),
        jnp.ones(3, bool), jnp.zeros(3, jnp.int32), p, jnp.zeros(1))
    f0 = int(full.feature[0])
    mask = np.ones(3, bool)
    mask[f0] = False
    res = best_numerical_split(
        jnp.asarray(hist), jnp.asarray(nb), jnp.asarray(mt), jnp.asarray(db),
        jnp.asarray(mask), jnp.zeros(3, jnp.int32), p, jnp.zeros(1))
    assert int(res.feature[0]) != f0


def test_leaf_output_formula():
    p = SplitParams(lambda_l1=0.0, lambda_l2=1.0)
    out = calculate_leaf_output(jnp.float32(10.0), jnp.float32(4.0), p)
    assert float(out) == pytest.approx(-10.0 / 5.0)
    p1 = SplitParams(lambda_l1=2.0, lambda_l2=0.0)
    out = calculate_leaf_output(jnp.float32(10.0), jnp.float32(4.0), p1)
    assert float(out) == pytest.approx(-8.0 / 4.0)


def test_max_delta_step_clips():
    p = SplitParams(max_delta_step=0.5)
    out = calculate_leaf_output(jnp.float32(100.0), jnp.float32(1.0), p)
    assert float(out) == pytest.approx(-0.5)
