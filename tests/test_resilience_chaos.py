"""Chaos acceptance for the resilience subsystem (ISSUE 9 criteria):

- a fault-injected rank crash mid-run -> the launcher kills the cohort,
  selects the newest all-rank-consistent checkpoint, respawns, and the
  final serialized model is BYTE-IDENTICAL to an uninterrupted run;
- a fault-injected rank divergence -> auto-repaired (re-sync event with
  repaired=true, post-repair hashes equal on every rank), not merely
  logged;
- the megastep driver (fused interpret + drain-replay eval consumer)
  resumes bit-identically from a drain-boundary checkpoint.

Marked ``chaos`` (the CI chaos-acceptance job runs ``-m chaos``) and
``slow`` (multi-process spawns / interpret mode are minutes-scale, so
tier-1's ``-m 'not slow'`` skips them; the weekly slow pass includes
them).
"""
import json
import os
import shutil
import socket
import subprocess
import sys
import tempfile
import textwrap

import numpy as np
import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.slow


def _csv(tmp_path, n=1200, f=6, seed=11):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float64)
    path = tmp_path / "train.csv"
    np.savetxt(path, np.column_stack([y, X]), delimiter=",", fmt="%.6f")
    return path


@pytest.mark.chaos
def test_launcher_crash_auto_resume_byte_identity(tmp_path):
    """ISSUE acceptance: rank 1 os._exit()s at iteration 5; the
    launcher respawns from the newest consistent checkpoint (period 2
    -> at most 2 iterations of lost work) and the final model is
    byte-identical to an uninjected run with the same params/seed."""
    from lightgbm_tpu.parallel import train_distributed
    train = _csv(tmp_path)
    ck = tmp_path / "ck"
    params = {"objective": "binary", "num_leaves": 15,
              "learning_rate": 0.2, "tree_learner": "data",
              "verbose": -1,
              "checkpoint_dir": str(ck), "checkpoint_period": 2}
    dsp = {"label_column": 0, "verbose": -1, "max_bin": 63}
    ref = train_distributed(dict(params), str(train), num_processes=2,
                            num_boost_round=8, dataset_params=dsp,
                            timeout=500)
    ref_str = ref.model_to_string(num_iteration=-1)
    shutil.rmtree(ck)
    bst = train_distributed(
        dict(params), str(train), num_processes=2, num_boost_round=8,
        dataset_params=dsp, timeout=500,
        fault_env={"LIGHTGBM_TPU_FAULTS": "crash@5:rank=1"})
    assert bst.model_to_string(num_iteration=-1) == ref_str


@pytest.mark.chaos
def test_launcher_gives_up_after_max_restarts(tmp_path):
    """Capped retries: with no checkpointing and a crash that re-fires
    every attempt (fresh fault-state dir each spawn is NOT used — the
    launcher shares one, so force re-firing via three distinct
    iteration triggers), the launcher fails loudly instead of looping."""
    from lightgbm_tpu.parallel import train_distributed
    from lightgbm_tpu.utils.log import LightGBMError
    train = _csv(tmp_path, n=600)
    params = {"objective": "binary", "num_leaves": 7, "verbose": -1,
              "tree_learner": "data"}
    dsp = {"label_column": 0, "verbose": -1, "max_bin": 63}
    with pytest.raises((LightGBMError, Exception)):
        train_distributed(
            dict(params), str(train), num_processes=2, num_boost_round=8,
            dataset_params=dsp, timeout=400, max_restarts=2,
            restart_backoff=0.1,
            fault_env={"LIGHTGBM_TPU_FAULTS":
                       "crash@1:rank=1,crash@2:rank=1,crash@3:rank=1"})


_DIV_WORKER = textwrap.dedent("""
    import json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=sys.argv[1],
        num_processes=int(sys.argv[2]), process_id=int(sys.argv[3]))
    import lightgbm_tpu as lgb
    path, tel_path = sys.argv[4], sys.argv[5]
    ds = lgb.Dataset(path, params={"label_column": 0, "verbose": -1,
                                   "max_bin": 63})
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "learning_rate": 0.2, "tree_learner": "data",
                     "verbose": -1, "telemetry_out": tel_path,
                     "health_check_period": 2}, ds, num_boost_round=8)
""")


@pytest.mark.chaos
def test_divergence_auto_repaired_not_just_logged(tmp_path):
    """ISSUE acceptance: a real injected divergence (rank 1's newest
    tree corrupted, its score rows consistently perturbed) is detected
    by the health auditor and AUTO-REPAIRED: a structured `recovery`
    event with repaired=true, one tree replaced on the diverged rank
    only, and every later health check's hashes agree again."""
    train = _csv(tmp_path, n=1500)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    script = tmp_path / "worker.py"
    script.write_text(_DIV_WORKER)
    tel = tmp_path / "tel.jsonl"
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO_ROOT,
               LIGHTGBM_TPU_FAULTS="diverge@2:rank=1")
    env.pop("XLA_FLAGS", None)
    procs = [subprocess.Popen(
        [sys.executable, str(script), coord, "2", str(i), str(train),
         str(tel)], env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE) for i in range(2)]
    for p in procs:
        out, err = p.communicate(timeout=500)
        assert p.returncode == 0, err.decode()[-3000:]

    replaced = {}
    for rank, path in enumerate([tel, tmp_path / "tel.jsonl.rank1"]):
        recs = [json.loads(line) for line in open(path)]
        events = {}
        for r in recs:
            events.setdefault(r["event"], []).append(r)
        # injected on rank 1 at iteration 2 -> detected at the it=3 audit
        divs = events.get("rank_divergence", [])
        assert [d["iter"] for d in divs] == [3], divs
        rec = [r for r in events.get("recovery", [])
               if r.get("action") == "resync"]
        assert len(rec) == 1 and rec[0]["repaired"] is True, rec
        # post-repair hashes in the recovery event agree across ranks
        assert len(set(rec[0]["hashes"].values())) == 1
        replaced[rank] = rec[0]["replaced_trees"]
        checks = [(c["iter"], c["ok"])
                  for c in events.get("health_check", [])]
        # healthy before, diverged at 3, healthy after the repair
        assert checks == [(1, True), (3, False), (5, True), (7, True)], \
            checks
    assert replaced == {0: 0, 1: 1}
    fault_marks = [r for r in
                   [json.loads(line) for line in
                    open(tmp_path / "tel.jsonl.rank1")]
                   if r["event"] == "fault_injected"]
    assert fault_marks and fault_marks[0]["kind"] == "diverge"


@pytest.mark.chaos
def test_multiproc_megastep_mid_chunk_crash_resume_byte_identity(tmp_path):
    """ISSUE 12 chaos leg: a 2-process MULTI-CHIP MEGASTEP run (fused
    interpret, shard_map growers inside the scan, bagging-bounded
    chunks of 2, drain-boundary checkpoints every 4 iterations) with a
    rank crash whose trigger iteration (5) lands MID-chunk — not on a
    drain/checkpoint boundary. The launcher must respawn from the
    newest consistent drain-boundary checkpoint (iteration 4), replay
    the chunk interior deterministically (bagging streams restored from
    the checkpoint), and emit the BYTE-IDENTICAL model of an uninjected
    run."""
    from lightgbm_tpu.parallel import train_distributed
    train = _csv(tmp_path)
    ck = tmp_path / "ck"
    tel = tmp_path / "tel.jsonl"
    params = {"objective": "binary", "num_leaves": 15,
              "learning_rate": 0.2, "tree_learner": "data",
              "tpu_engine": "fused", "tpu_megastep": True, "verbose": -1,
              "bagging_fraction": 0.8, "bagging_freq": 2,
              "telemetry_out": str(tel),
              "checkpoint_dir": str(ck), "checkpoint_period": 4}
    dsp = {"label_column": 0, "verbose": -1, "max_bin": 63}
    ref = train_distributed(dict(params), str(train), num_processes=2,
                            num_boost_round=12, dataset_params=dsp,
                            timeout=900)
    ref_str = ref.model_to_string(num_iteration=-1)
    # the reference run actually rode the megastep (vacuity guard)
    recs = [json.loads(line) for line in open(tel)]
    assert any(r["event"] == "megastep" for r in recs), \
        sorted({r["event"] for r in recs})
    shutil.rmtree(ck)
    tel.unlink()
    bst = train_distributed(
        dict(params), str(train), num_processes=2, num_boost_round=12,
        dataset_params=dsp, timeout=900,
        fault_env={"LIGHTGBM_TPU_FAULTS": "crash@5:rank=1"})
    assert bst.model_to_string(num_iteration=-1) == ref_str


def test_megastep_resume_bit_identity(tmp_path):
    """Drain-boundary checkpoints on the fused interpret megastep with
    the on-device-eval consumer (valid set + early stopping + logging):
    resumed run == uninterrupted run, byte for byte."""
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(0)
    X = rng.rand(300, 6).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 1).astype(np.float32)
    Xv = rng.rand(120, 6).astype(np.float32)
    yv = (Xv[:, 0] + Xv[:, 1] > 1).astype(np.float32)
    ck = tmp_path / "ck"
    params = {"objective": "binary", "num_leaves": 7, "verbose": -1,
              "tpu_engine": "fused", "tpu_megastep": True, "max_bin": 31,
              "metric": ["binary_logloss"],
              "bagging_fraction": 0.8, "bagging_freq": 3,
              "checkpoint_dir": str(ck), "checkpoint_period": 4}

    def run(n, resume=None):
        ds = lgb.Dataset(X, label=y,
                         params={"max_bin": 31, "verbose": -1})
        vs = lgb.Dataset(Xv, label=yv, reference=ds)
        return lgb.train(dict(params), ds, num_boost_round=n,
                         valid_sets=[vs],
                         callbacks=[lgb.early_stopping(20, verbose=False),
                                    lgb.log_evaluation(100)],
                         resume_from=resume)

    ref = run(12)
    ref_str = ref.model_to_string(num_iteration=-1)
    # the run stayed on the megastep (consumer armed, no eviction)
    shutil.rmtree(ck)
    a = run(8)
    assert a._gbdt._megastep_armed is False  # disarmed after train
    resumed = run(12, resume=str(ck))
    assert resumed.model_to_string(num_iteration=-1) == ref_str
