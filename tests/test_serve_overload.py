"""Overload-hardened serving: admission control, deadline shedding,
retry policy, zero-downtime rollover, wedge detection, /readyz.

Tier-1 (fast) coverage of the serving plane's overload features
(lightgbm_tpu/serve/): every knob defaults OFF, so the companion
contract — the pre-hardening behavior of an un-configured service —
stays covered by tests/test_serve.py unchanged.  The open-loop
acceptance runs (offered load > capacity, rollover under continuous
traffic) live in tests/test_serve_chaos.py (``-m chaos``).

Dispatch throttling in these tests is a wrapped ``batcher._dispatch``
holding a gate/sleep — deterministic on any runner, no reliance on the
CPU being slow.
"""
import threading
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.resilience import faults as faults_mod
from lightgbm_tpu.serve import (PredictionService, RetryPolicy,
                                ServeClosed, ServeDeadlineExceeded,
                                ServeRejected, ServeWorkerWedged)
from lightgbm_tpu.serve import batcher as batcher_mod
from lightgbm_tpu.serve.admission import AdmissionController

TOL = dict(rtol=1e-5, atol=1e-6)
F = 8


def _train(seed=0, n=400, rounds=5, **extra):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, F).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float32)
    params = {"objective": "binary", "num_leaves": 15,
              "learning_rate": 0.2, "verbose": -1, "min_data_in_leaf": 5}
    params.update(extra)
    return lgb.train(params, lgb.Dataset(X, label=y),
                     num_boost_round=rounds)


@pytest.fixture(scope="module")
def bst():
    return _train(seed=0)


@pytest.fixture(scope="module")
def bst2():
    return _train(seed=0, rounds=7, learning_rate=0.35)


def _svc(bst, **kw):
    kw.setdefault("max_batch_rows", 64)
    kw.setdefault("min_bucket_rows", 16)
    kw.setdefault("max_delay_ms", 0.5)
    kw.setdefault("batch_events", False)
    # single lane: these tests specify the overload semantics of ONE
    # bounded queue (gated-dispatch backlogs, watermark math, wedge
    # sequencing); fleet admission/spill is tests/test_serve_fleet.py
    kw.setdefault("serve_devices", 1)
    return PredictionService({"m": bst}, **kw)


def _gate_dispatch(svc, hold_s=2.0):
    """Replace the service's dispatch with one that blocks on a gate —
    the deterministic way to pile up a backlog."""
    real = svc.batcher._dispatch
    gate = threading.Event()

    def slow(mid, X):
        gate.wait(hold_s)
        return real(mid, X)
    svc.batcher._dispatch = slow
    return gate, real


def _events(svc, name):
    return [e for e in svc.tel._events if e.get("event") == name]


# ------------------------------------------------------ admission
def test_reject_structured_and_queue_bounded(bst):
    svc = _svc(bst, max_queue_requests=4)
    svc.warmup()
    gate, _ = _gate_dispatch(svc)
    futs, rejects = [], []
    try:
        for _ in range(25):
            try:
                futs.append(svc.submit("m", np.zeros((2, F), np.float32)))
            except ServeRejected as exc:
                rejects.append(exc)
        # the queue never grew past the bound (first batch in flight
        # holds up to the coalesce budget; the QUEUE stays <= 4)
        assert len(svc.batcher._q) <= 4
        assert rejects, "open-loop burst over a 4-deep queue must reject"
        exc = rejects[0]
        assert exc.reason in ("queue_requests", "queue_rows")
        assert exc.retry_after_ms > 0
        d = exc.details()
        assert d["error"] == "ServeRejected" and "queue_requests" in d
    finally:
        gate.set()
    for f in futs:
        f.result(timeout=30)          # everything admitted is served
    s = svc.stats()
    assert s["rejected"] == len(rejects)
    assert s["queue_peak_requests"] <= 4
    assert _events(svc, "serve_rejected"), "structured reject event"
    svc.close()


def test_oversized_single_request_admits_when_queue_empty(bst):
    # a request larger than the row bound must still serve (the engine
    # chunks it) — admission only refuses it when it would pile onto an
    # existing backlog
    svc = _svc(bst, max_queue_rows=8)
    svc.warmup()
    out = svc.predict("m", np.random.RandomState(3)
                      .rand(32, F).astype(np.float32))
    assert out.shape == (32,)
    assert svc.stats()["rejected"] == 0
    svc.close()


def test_deadline_shed_at_dequeue_before_device_work(bst):
    svc = _svc(bst)
    svc.warmup()
    d0 = svc.stats()["dispatches"]
    gate, _ = _gate_dispatch(svc)
    # first request occupies the worker; the rest queue behind it with
    # a deadline shorter than the gate hold
    f0 = svc.submit("m", np.zeros((1, F), np.float32))
    time.sleep(0.05)
    late = [svc.submit("m", np.zeros((1, F), np.float32),
                       deadline_ms=100.0) for _ in range(3)]
    time.sleep(0.3)                    # all three expire while queued
    gate.set()
    f0.result(timeout=30)
    sheds = 0
    for f in late:
        with pytest.raises(ServeDeadlineExceeded) as ei:
            f.result(timeout=30)
        sheds += 1
        assert ei.value.fields["waited_ms"] >= 100.0
        assert ei.value.fields["deadline_ms"] == pytest.approx(100.0)
    s = svc.stats()
    assert s["shed"] == sheds == 3
    # shed BEFORE dispatch: no device work was spent on them
    assert s["dispatches"] - d0 == 1
    errs = [e for e in _events(svc, "serve_access")
            if e.get("error") == "ServeDeadlineExceeded"]
    assert len(errs) == 3              # shed requests trace too
    svc.close()


def test_service_default_deadline_applies(bst):
    svc = _svc(bst, default_deadline_ms=80.0)
    svc.warmup()
    gate, _ = _gate_dispatch(svc)
    svc.submit("m", np.zeros((1, F), np.float32))
    time.sleep(0.05)
    f = svc.submit("m", np.zeros((1, F), np.float32))   # inherits 80ms
    time.sleep(0.2)
    gate.set()
    with pytest.raises(ServeDeadlineExceeded):
        f.result(timeout=30)
    svc.close()


# -------------------------------------------------------- retry
def test_retry_policy_retries_shed_and_reject_only(bst):
    svc = _svc(bst, max_queue_requests=1)
    svc.warmup()
    gate, real = _gate_dispatch(svc)
    # saturate: one in flight + full queue
    svc.submit("m", np.zeros((1, F), np.float32))
    time.sleep(0.05)
    svc.submit("m", np.zeros((1, F), np.float32))
    t = threading.Timer(0.3, gate.set)
    t.start()
    # the retried predict keeps hitting ServeRejected until the gate
    # opens and the backlog drains, then succeeds
    pol = RetryPolicy(max_attempts=40, base_backoff_ms=25,
                      max_backoff_ms=100)
    out = svc.predict("m", np.zeros((2, F), np.float32), retry=pol)
    assert out.shape == (2,)
    assert svc.stats()["retries"] > 0
    t.cancel()

    # compute errors are NEVER retried: a poisoned dispatch raises
    # through predict once, with no retry counter movement
    calls = []

    def boom(mid, X):
        calls.append(1)
        raise ValueError("poisoned")
    svc.batcher._dispatch = boom
    r0 = svc.stats()["retries"]
    with pytest.raises(ValueError):
        svc.predict("m", np.zeros((1, F), np.float32), retry=pol)
    assert len(calls) == 1
    assert svc.stats()["retries"] == r0
    svc.batcher._dispatch = real
    svc.close()


def test_retry_policy_backoff_honors_server_hint():
    pol = RetryPolicy(max_attempts=3, base_backoff_ms=10,
                      backoff_multiplier=2.0, max_backoff_ms=500)
    assert pol.backoff_ms(0) == 10
    assert pol.backoff_ms(1) == 20
    hint = ServeRejected("x", reason="queue_rows", retry_after_ms=120.0)
    assert pol.backoff_ms(0, hint) == 120.0     # server knows better
    big = ServeRejected("x", reason="queue_rows", retry_after_ms=9000.0)
    assert pol.backoff_ms(0, big) == 500        # but capped
    assert pol.should_retry(hint, 0) and not pol.should_retry(hint, 2)
    assert not pol.should_retry(ValueError("compute"), 0)


# --------------------------------------------- adaptive controller
def test_admission_controller_hysteresis_no_flap(bst):
    svc = _svc(bst, target_p99_ms=50.0, max_queue_rows=1024)
    try:
        ctl = svc.admission
        assert ctl is not None and ctl.level == 0
        b = svc.batcher
        base_delay, base_rows = b.max_delay_s, b.max_batch_rows
        # a single spike (or an alternating signal) must NOT move it
        ctl.step(force=True, p99_ms=500.0)
        ctl.step(force=True, p99_ms=10.0)
        ctl.step(force=True, p99_ms=500.0)
        ctl.step(force=True, p99_ms=60.0)   # dead band resets streaks
        assert ctl.level == 0 and b.shed_watermark_rows is None
        # three CONSECUTIVE over-target evaluations escalate
        for _ in range(3):
            ctl.step(force=True, p99_ms=500.0)
        assert ctl.level == 1
        assert b.max_delay_s == pytest.approx(base_delay / 2)
        assert b.max_batch_rows == base_rows // 2
        assert b.shed_watermark_rows == 512
        for _ in range(3):
            ctl.step(force=True, p99_ms=500.0)
        assert ctl.level == 2 and b.shed_watermark_rows == 256
        # recovery needs consecutive UNDER recover_ratio*target evals
        for _ in range(3):
            ctl.step(force=True, p99_ms=10.0)
        assert ctl.level == 1
        for _ in range(3):
            ctl.step(force=True, p99_ms=10.0)
        assert ctl.level == 0
        assert b.max_delay_s == pytest.approx(base_delay)
        assert b.max_batch_rows == base_rows
        assert b.shed_watermark_rows is None
        evs = _events(svc, "serve_admission")
        assert len(evs) == 4 and {e["direction"] for e in evs} == \
            {"shed", "recover"}
    finally:
        svc.close()


def test_admission_watermark_rejects_under_hard_cap(bst):
    svc = _svc(bst, target_p99_ms=50.0, max_queue_rows=1024)
    svc.warmup()
    gate, _ = _gate_dispatch(svc)
    try:
        for _ in range(3):
            svc.admission.step(force=True, p99_ms=500.0)
        assert svc.batcher.shed_watermark_rows == 512
        svc.submit("m", np.zeros((1, F), np.float32))
        time.sleep(0.05)               # in flight, holds the worker
        svc.submit("m", np.zeros((1, F), np.float32))   # queued
        with pytest.raises(ServeRejected) as ei:
            # 600 rows onto the backlog clears the 1024 hard cap but
            # not the level-1 watermark (512)
            svc.submit("m", np.zeros((600, F), np.float32))
        assert ei.value.reason == "shed_watermark"
    finally:
        gate.set()
        svc.close()


# -------------------------------------------- bounded drain / wedge
def test_close_drain_timeout_sheds_structured(bst):
    svc = _svc(bst)
    svc.warmup()
    gate, _ = _gate_dispatch(svc, hold_s=1.5)
    f0 = svc.submit("m", np.zeros((1, F), np.float32))
    time.sleep(0.05)
    queued = [svc.submit("m", np.zeros((1, F), np.float32))
              for _ in range(4)]
    t0 = time.perf_counter()
    svc.close(drain_timeout_s=0.2)     # cannot drain through the gate
    assert time.perf_counter() - t0 < 10.0
    gate.set()
    f0.result(timeout=30)              # the in-flight batch completed
    for f in queued:                   # the backlog was shed, not leaked
        with pytest.raises(ServeClosed):
            f.result(timeout=30)


def test_wedged_worker_detected_and_reported(bst, monkeypatch):
    monkeypatch.setenv(faults_mod.FAULTS_ENV, "serve_wedge_worker@1")
    monkeypatch.setattr(batcher_mod, "_WEDGE_GRACE_S", 0.3)
    faults_mod._CACHE.clear()
    svc = _svc(bst)
    svc.warmup()
    f1 = svc.submit("m", np.zeros((1, F), np.float32))
    time.sleep(0.2)                    # worker wedges inside batch 1
    f2 = svc.submit("m", np.zeros((1, F), np.float32))
    svc.close(drain_timeout_s=0.2)
    for f in (f1, f2):                 # in-flight AND queued both fail
        with pytest.raises(ServeWorkerWedged):
            f.result(timeout=5)
    ev = _events(svc, "serve_worker_wedged")
    assert ev and ev[0]["queued"] == 1 and ev[0]["inflight"] == 1


def test_dispatch_error_fault_resolves_batch_and_recovers(bst,
                                                          monkeypatch):
    monkeypatch.setenv(faults_mod.FAULTS_ENV, "serve_dispatch_error@1")
    faults_mod._CACHE.clear()
    svc = _svc(bst)
    svc.warmup()
    with pytest.raises(faults_mod.ServeFaultError):
        svc.predict("m", np.zeros((1, F), np.float32))
    # the worker survived: the NEXT request serves normally
    out = svc.predict("m", np.zeros((1, F), np.float32))
    assert out.shape == (1,)
    assert svc.stats()["batches"] >= 1
    svc.close()


# ------------------------------------------------------- rollover
def test_rollover_swaps_atomically_with_hashes(bst, bst2):
    svc = _svc(bst)
    svc.warmup()
    before = svc.predict("m", np.zeros((3, F), np.float32))
    rep = svc.rollover("m", bst2)
    assert rep["promoted"] and rep["old_hash"] != rep["new_hash"]
    after = svc.predict("m", np.zeros((3, F), np.float32))
    np.testing.assert_allclose(
        after, bst2.predict(np.zeros((3, F), np.float64)), **TOL)
    assert not np.allclose(before, after)
    ev = _events(svc, "serve_rollover")
    assert ev and ev[0]["old_hash"] == rep["old_hash"] \
        and ev[0]["new_hash"] == rep["new_hash"]
    assert svc.stats()["rollovers"] == 1
    svc.close()


def test_rollover_from_resilience_checkpoint(tmp_path):
    ckdir = str(tmp_path / "ck")
    b = _train(seed=2, rounds=6, checkpoint_dir=ckdir,
               checkpoint_period=2)
    other = _train(seed=3, rounds=4)
    svc = _svc(other)
    svc.warmup()
    rep = svc.rollover("m", ckdir)     # checkpoint root -> residency
    assert rep["promoted"]
    X = np.random.RandomState(5).rand(40, F).astype(np.float32)
    np.testing.assert_allclose(svc.predict("m", X), b.predict(X), **TOL)
    assert _events(svc, "serve_rollover")[0]["source"] == "checkpoint"
    svc.close()


def test_rollover_shadow_reports_divergence_and_abort(bst, bst2):
    svc = _svc(bst)
    svc.warmup()
    stop = threading.Event()
    fails = []

    def traffic():
        r = np.random.RandomState(11)
        while not stop.is_set():
            try:
                svc.predict("m", r.rand(2, F).astype(np.float32))
            except Exception as e:     # pragma: no cover
                fails.append(repr(e))
    th = threading.Thread(target=traffic, daemon=True)
    th.start()
    try:
        rep = svc.rollover("m", bst2, shadow_requests=4,
                           shadow_timeout_s=15.0)
        assert rep["promoted"] and rep["shadow"]["completed"]
        assert rep["shadow"]["requests"] >= 4
        assert rep["shadow"]["max_divergence"] > 0
        assert _events(svc, "serve_shadow")
        # abort path: a zero tolerance against a diverging candidate
        # keeps the CURRENT model serving
        rep2 = svc.rollover("m", bst, shadow_requests=3,
                            shadow_timeout_s=15.0,
                            shadow_abort_threshold=0.0)
        assert not rep2["promoted"]
        assert _events(svc, "serve_rollover_aborted")
    finally:
        stop.set()
        th.join(timeout=10)
    assert not fails
    X = np.zeros((3, F), np.float32)
    np.testing.assert_allclose(svc.predict("m", X),
                               bst2.predict(np.zeros((3, F))), **TOL)
    svc.close()


def test_rollover_responses_attributable_to_one_version(bst, bst2):
    svc = _svc(bst)
    svc.warmup()
    h_old = svc.residency.get("m").model_hash[:16]
    for _ in range(3):
        svc.predict("m", np.zeros((2, F), np.float32))
    svc.rollover("m", bst2)
    h_new = svc.residency.get("m").model_hash[:16]
    for _ in range(3):
        svc.predict("m", np.zeros((2, F), np.float32))
    acc = [e for e in _events(svc, "serve_access")
           if "model_version" in e]
    assert len(acc) >= 6
    seen = {e["model_version"] for e in acc}
    assert seen == {h_old, h_new}
    svc.close()


# --------------------------------------------------------- readyz
def test_readyz_gates_on_warmup_and_close(bst):
    import urllib.error
    import urllib.request

    from lightgbm_tpu.parallel.launcher import _free_port
    svc = _svc(bst, metrics_port=_free_port())

    def probe():
        url = svc.metrics_url.replace("/metrics", "/readyz")
        try:
            with urllib.request.urlopen(url, timeout=5) as r:
                return r.status, r.read().decode().strip()
        except urllib.error.HTTPError as e:
            return e.code, e.read().decode().strip()
    code, reason = probe()
    assert code == 503 and reason == "warmup_pending"
    svc.warmup()
    code, reason = probe()
    assert code == 200 and reason == "ready"
    # the training-style exporter (no ready_check) stays ready
    from lightgbm_tpu.obs.export import MetricsExporter
    assert MetricsExporter(svc.tel, 0).ready_check is None
    svc.close()


def test_idle_overload_knobs_keep_serving_contract(bst):
    # all knobs off (defaults): the deterministic serving contract the
    # bench gates on must be untouched by the overload machinery
    svc = _svc(bst)
    svc.warmup()
    rng = np.random.RandomState(7)
    for s in (1, 5, 17, 33):
        svc.predict("m", rng.rand(s, F).astype(np.float32))
    s = svc.stats()
    assert s["dispatches_per_request"] == 1.0
    assert s["compiles_per_1k_requests"] == 0.0
    assert s["rejected"] == 0 and s["shed"] == 0
    svc.close()
