"""Device-vs-host prediction path parity, forced deterministically.

``pred_device_min_work`` (new config key; replaces the old hardwired
2M rows x trees literal) forces either path: 0 sends EVERY predict
through the device predictor (binned via the training BinMappers, or
raw-value-threshold routing when the booster has no training dataset);
a huge value forces the exact float64 host walk.  Parity is asserted on
the shapes the ISSUE names: categorical splits, EFB-bundled (sparse-
built) datasets, NaN/missing-heavy inputs, single-leaf trees and a
file-loaded booster.
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import basic

FORCE_DEV = {"pred_device_min_work": 0}
FORCE_HOST = {"pred_device_min_work": 10**15}
TOL = dict(rtol=1e-5, atol=1e-6)


def _paths_agree(bst, Xq, **tol):
    tol = tol or TOL
    bst.params.update(FORCE_HOST)
    if bst.config is not None:
        bst.config.update(FORCE_HOST)
    bst._pred_min_work_cache = None
    host = bst.predict(Xq)
    if bst.config is not None:
        bst.config.update(FORCE_DEV)
    bst.params.update(FORCE_DEV)
    bst._pred_min_work_cache = None
    dev = bst.predict(Xq)
    np.testing.assert_allclose(dev, host, **tol)
    return host


def test_threshold_key_switches_paths():
    rng = np.random.RandomState(0)
    X = rng.rand(250, 6).astype(np.float32)
    y = (X[:, 0] > 0.5).astype(np.float32)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbose": -1, "min_data_in_leaf": 5,
                     **FORCE_DEV},
                    lgb.Dataset(X, label=y), num_boost_round=3)
    assert bst.config.pred_device_min_work == 0
    bst.predict(X[:10])
    # the device predictor was actually built and cached
    assert getattr(bst, "_device_predictor", None) is not None
    bst.config.update(FORCE_HOST)
    assert bst._pred_device_min_work() == 10**15
    # with the default threshold a small predict stays on the host walk
    b2 = lgb.train({"objective": "binary", "num_leaves": 7,
                    "verbose": -1, "min_data_in_leaf": 5},
                   lgb.Dataset(X, label=y), num_boost_round=2)
    assert b2.config.pred_device_min_work == 2_000_000
    b2.predict(X[:10])
    assert getattr(b2, "_device_predictor", None) is None


def test_parity_categorical_splits(tmp_path):
    rng = np.random.RandomState(1)
    n = 600
    X = np.column_stack([rng.rand(n),
                         rng.randint(0, 12, n).astype(float),
                         rng.rand(n),
                         rng.randint(0, 5, n).astype(float)])
    y = (((X[:, 1] % 3) == 1) | (X[:, 3] > 2)).astype(np.float32)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbose": -1, "min_data_in_leaf": 5},
                    lgb.Dataset(X, label=y, categorical_feature=[1, 3]),
                    num_boost_round=4)
    # unseen categories (incl. negatives and a huge value past int32)
    # must route like the host walk
    Xq = np.column_stack([rng.rand(60),
                          rng.randint(-1, 15, 60).astype(float),
                          rng.rand(60),
                          rng.randint(0, 8, 60).astype(float)])
    Xq[0, 1] = 4.0e9        # past int32: out-of-vocab, routes right
    Xq[1, 3] = np.nan
    Xq[2, 1] = -0.5         # truncates toward zero: category 0
    Xq[3, 3] = -1.0         # truncates to -1: out-of-vocab
    Xq[4, 1] = 2.7          # fractional in-vocab: category 2
    _paths_agree(bst, Xq)
    # file-loaded: same queries through the raw-value cat masks
    path = str(tmp_path / "cat.txt")
    bst.save_model(path)
    loaded = lgb.Booster(model_file=path)
    host = _paths_agree(loaded, Xq)
    # bst is still forced onto the (f32-accumulating) device path by
    # the first _paths_agree — f32 tolerance, not exact
    np.testing.assert_allclose(host, bst.predict(Xq), **TOL)


def test_parity_efb_bundled_sparse_dataset():
    sp = pytest.importorskip("scipy.sparse")
    rng = np.random.RandomState(2)
    Xs = sp.random(800, 40, density=0.04, random_state=2, format="csr")
    ys = (np.asarray(Xs.sum(axis=1)).ravel() > 0.8).astype(np.float32)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbose": -1, "min_data_in_leaf": 5},
                    lgb.Dataset(Xs, label=ys), num_boost_round=4)
    Xq = sp.random(80, 40, density=0.04, random_state=3, format="csr")
    _paths_agree(bst, Xq)
    # dense queries against the bundle-built mappers agree too
    _paths_agree(bst, np.asarray(Xq.todense()))


def test_parity_nan_heavy_input():
    rng = np.random.RandomState(4)
    X = rng.rand(500, 8).astype(np.float32)
    X[rng.rand(*X.shape) < 0.35] = np.nan
    y = (np.nan_to_num(X[:, 0]) + np.nan_to_num(X[:, 1]) > 0.9) \
        .astype(np.float32)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbose": -1, "min_data_in_leaf": 5},
                    lgb.Dataset(X, label=y), num_boost_round=4)
    Xq = rng.rand(90, 8).astype(np.float32)
    Xq[rng.rand(*Xq.shape) < 0.5] = np.nan
    Xq[3] = np.nan                      # an all-missing row
    _paths_agree(bst, Xq)


def test_parity_zero_as_missing():
    rng = np.random.RandomState(5)
    X = rng.rand(400, 5).astype(np.float32)
    X[rng.rand(*X.shape) < 0.4] = 0.0
    y = (X[:, 0] > 0.5).astype(np.float32)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbose": -1, "min_data_in_leaf": 5,
                     "zero_as_missing": True},
                    lgb.Dataset(X, label=y,
                                params={"zero_as_missing": True}),
                    num_boost_round=3)
    Xq = rng.rand(50, 5).astype(np.float32)
    Xq[rng.rand(*Xq.shape) < 0.4] = 0.0
    _paths_agree(bst, Xq)


def test_parity_single_leaf_trees(tmp_path):
    rng = np.random.RandomState(6)
    X, y = rng.rand(100, 3), rng.rand(100)
    bst = lgb.train({"objective": "regression", "num_leaves": 4,
                     "verbose": -1, "min_data_in_leaf": 10000},
                    lgb.Dataset(X, label=y), num_boost_round=3)
    assert all(t.num_leaves == 1 for t in bst.models)
    Xq = rng.rand(7, 3)
    # live booster: no usable features -> device packer degrades, host
    # walk answers on both settings
    _paths_agree(bst, Xq)
    # file-loaded: the raw variant serves single-leaf trees on device
    path = str(tmp_path / "sl.txt")
    bst.save_model(path)
    loaded = lgb.Booster(model_file=path)
    host = _paths_agree(loaded, Xq)
    np.testing.assert_allclose(host, bst.predict(Xq), rtol=1e-9)


def test_parity_file_loaded_booster(tmp_path):
    rng = np.random.RandomState(7)
    X = rng.rand(600, 10).astype(np.float32)
    X[rng.rand(*X.shape) < 0.2] = np.nan
    y = (np.nan_to_num(X[:, 0]) - np.nan_to_num(X[:, 2]) > 0) \
        .astype(np.float32)
    bst = lgb.train({"objective": "binary", "num_leaves": 31,
                     "verbose": -1, "min_data_in_leaf": 5},
                    lgb.Dataset(X, label=y), num_boost_round=6)
    path = str(tmp_path / "m.txt")
    bst.save_model(path)
    loaded = lgb.Booster(model_file=path)
    assert loaded.train_set is None
    Xq = rng.rand(120, 10).astype(np.float32)
    Xq[rng.rand(*Xq.shape) < 0.3] = np.nan
    host = _paths_agree(loaded, Xq)
    # and the device path agrees with the ORIGINAL booster's predict
    np.testing.assert_allclose(host, bst.predict(Xq), rtol=1e-9,
                               atol=1e-12)


def test_parity_multiclass_start_num_iteration():
    rng = np.random.RandomState(8)
    X = rng.rand(400, 6)
    y = (X[:, 0] * 3).astype(int) % 3
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "num_leaves": 7, "verbose": -1,
                     "min_data_in_leaf": 5},
                    lgb.Dataset(X, label=y), num_boost_round=4)
    Xq = rng.rand(40, 6)
    for kw in ({}, {"raw_score": True},
               {"start_iteration": 1, "num_iteration": 2}):
        bst.config.update(FORCE_HOST)
        host = bst.predict(Xq, **kw)
        bst.config.update(FORCE_DEV)
        dev = bst.predict(Xq, **kw)
        np.testing.assert_allclose(dev, host, **TOL)


def test_host_sparse_walk_densifies_in_chunks(monkeypatch):
    """The host fallback must densify tall CSR input in bounded row
    chunks (the old whole-matrix todense is an OOM at serving scale) —
    forced here with a tiny chunk size so the loop actually runs."""
    sp = pytest.importorskip("scipy.sparse")
    rng = np.random.RandomState(9)
    Xs = sp.random(300, 15, density=0.1, random_state=9, format="csr")
    ys = (np.asarray(Xs.sum(axis=1)).ravel() > 0.7).astype(np.float32)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbose": -1, "min_data_in_leaf": 5},
                    lgb.Dataset(Xs, label=ys), num_boost_round=3)
    Xq = sp.random(50, 15, density=0.1, random_state=10, format="csr")
    bst.config.update(FORCE_HOST)
    expect = bst.predict(np.asarray(Xq.todense()))
    monkeypatch.setattr(basic, "_HOST_SPARSE_CHUNK_ROWS", 7)
    got = bst.predict(Xq)
    np.testing.assert_allclose(got, expect, rtol=1e-12, atol=1e-15)
