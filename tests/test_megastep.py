"""Dispatch-amortized training megastep (boosting/gbdt.py
_train_one_megastep) and the telemetry granularity that keeps the fast
path.

The megastep chains up to tpu_megastep_iters boosting iterations inside
ONE jit via lax.scan over the fused tree-growing step; the scan body is
the same trace as the per-iteration fast step, so the two paths must be
bit-identical. Telemetry at the default `batch` granularity must keep
the fast path (the pre-round-6 behavior evicted any telemetry-on run to
the synchronous driver) and count host dispatches.
"""
import json
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _data(n=1200, f=8, seed=3):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float32)
    return X, y


# tpu_megastep is set EXPLICITLY: off-TPU the fused engine runs in
# interpret mode, where the megastep is opt-in (no dispatch latency to
# amortize — see GBDT._megastep_ok)
FUSED = {"objective": "binary", "num_leaves": 15, "learning_rate": 0.2,
         "verbose": -1, "min_data_in_leaf": 5, "tpu_engine": "fused",
         "tpu_megastep": True}


def _trees_equal(b1, b2):
    assert b1.num_trees() == b2.num_trees()
    for t1, t2 in zip(b1.models, b2.models):
        assert t1.num_leaves == t2.num_leaves
        assert np.array_equal(t1.split_feature, t2.split_feature)
        assert np.array_equal(t1.threshold_bin, t2.threshold_bin)
        assert np.array_equal(t1.leaf_value, t2.leaf_value)


def test_megastep_engages_in_engine_train():
    # 10 rounds on the same data shape as the telemetry test below, so
    # both share ONE compiled megastep(10) program (tier-1 budget)
    X, y = _data()
    b = lgb.train(dict(FUSED), lgb.Dataset(X, label=y),
                  num_boost_round=10)
    g = b._gbdt
    assert g._megastep_fns, "lgb.train did not build a megastep"
    assert 10 in g._megastep_fns         # one fused chunk covered the run
    assert b.num_trees() == 10
    assert not g._megastep_armed         # disarmed after the loop


def test_update_contract_unchanged():
    # the bare Booster.update contract stays one iteration per call —
    # megasteps are consumed only by loops that armed them
    X, y = _data(n=600)
    b = lgb.Booster(params=dict(FUSED), train_set=lgb.Dataset(X, label=y))
    for i in range(3):
        b.update()
        assert b._gbdt.iter == i + 1
    assert not b._gbdt._megastep_fns
    assert b.num_trees() == 3


def test_megastep_bit_identical_to_fast_path():
    X, y = _data()
    b1 = lgb.train(dict(FUSED, tpu_megastep=True),
                   lgb.Dataset(X, label=y), num_boost_round=8)
    b2 = lgb.train(dict(FUSED, tpu_megastep=False,
                        tpu_fused_epilogue=False),
                   lgb.Dataset(X, label=y), num_boost_round=8)
    _trees_equal(b1, b2)
    # live training scores too, not just the serialized model
    np.testing.assert_array_equal(np.asarray(b1._gbdt.scores),
                                  np.asarray(b2._gbdt.scores))


def test_megastep_early_stop_across_boundary():
    # min_sum_hessian tuned so splits dry up mid-run: the stop fires
    # INSIDE a fused chunk, drain must rewind the tail exactly like the
    # per-iteration pipeline
    X, y = _data(n=400)
    params = dict(FUSED, min_sum_hessian_in_leaf=20.0, learning_rate=0.9)
    b1 = lgb.train(dict(params, tpu_megastep=True),
                   lgb.Dataset(X, label=y), num_boost_round=30)
    b2 = lgb.train(dict(params, tpu_megastep=False,
                        tpu_fused_epilogue=False),
                   lgb.Dataset(X, label=y), num_boost_round=30)
    b2._gbdt.drain_pending()   # the pipeline detects the stop at drain
    assert b1._gbdt._stopped_early and b2._gbdt._stopped_early
    assert 0 < b1.num_trees() < 30
    _trees_equal(b1, b2)


def test_megastep_valid_and_bagging():
    # valid-score updates ride inside the scan; bagging chunks align to
    # the re-bagging boundary so the LCG stream order is untouched
    X, y = _data()
    Xv, yv = _data(seed=11)
    params = dict(FUSED, bagging_fraction=0.6, bagging_freq=4,
                  bagging_seed=7)

    def run(extra):
        d = lgb.Dataset(X, label=y)
        return lgb.train(dict(params, **extra), d, num_boost_round=10,
                         valid_sets=[lgb.Dataset(Xv, label=yv,
                                                 reference=d)])
    b1 = run({"tpu_megastep": True})
    b2 = run({"tpu_megastep": False, "tpu_fused_epilogue": False})
    _trees_equal(b1, b2)
    np.testing.assert_array_equal(np.asarray(b1._gbdt.valid_scores[0]),
                                  np.asarray(b2._gbdt.valid_scores[0]))
    # bagging forced chunking at the 4-iteration window boundary
    assert 4 in b1._gbdt._megastep_fns


def test_telemetry_batch_keeps_fast_path_and_dispatch_budget(tmp_path):
    # ISSUE 5 acceptance: with telemetry_out set and default granularity
    # the fast path stays on and the megastep path pays < 2 host
    # dispatches per boosting iteration (the sync driver pays >= 3)
    out = tmp_path / "tel.jsonl"
    X, y = _data()
    b = lgb.train(dict(FUSED, telemetry_out=str(out)),
                  lgb.Dataset(X, label=y), num_boost_round=10)
    g = b._gbdt
    assert g._fast_path_ok()
    snap = b.telemetry()
    c = snap["counters"]
    assert c["iterations"] == 10
    assert 0 < c["train.dispatches"] / c["iterations"] < 2.0
    assert c.get("train.drains", 0) >= 1

    recs = [json.loads(line) for line in open(out)]
    for r in recs:
        assert isinstance(r["ts"], float) and isinstance(r["rank"], int)
        assert isinstance(r["event"], str) and r["event"]
    batches = [r for r in recs if r["event"] == "megastep"]
    assert batches, recs
    assert sum(r["kept"] for r in batches) == 10
    for r in batches:
        assert r["iterations"] >= r["kept"] > 0
        assert r["fused_iterations"] >= 0
        assert r["sections"]["batch"] >= 0.0
        assert r["engine"] == "fused"
    summaries = [r for r in recs if r["event"] == "summary"]
    assert summaries and summaries[-1]["counters"]["iterations"] == 10


def test_telemetry_iteration_granularity_keeps_fast_path(tmp_path):
    out = tmp_path / "tel_iter.jsonl"
    X, y = _data(n=800)
    b = lgb.train(dict(FUSED, telemetry_out=str(out),
                       telemetry_granularity="iteration"),
                  lgb.Dataset(X, label=y), num_boost_round=5)
    assert b._gbdt._fast_path_ok()
    recs = [json.loads(line) for line in open(out)]
    iters = [r for r in recs if r["event"] == "iteration"]
    assert [r["iter"] for r in iters] == [0, 1, 2, 3, 4]
    for r in iters:
        assert r["sections"]["fast_iteration"] >= 0.0
        assert r["pipelined"] is True
        assert isinstance(r["num_leaves"], list) and r["num_leaves"]


def test_telemetry_section_granularity_forces_sync(tmp_path):
    out = tmp_path / "tel_sec.jsonl"
    X, y = _data(n=800)
    b = lgb.train(dict(FUSED, telemetry_out=str(out),
                       telemetry_granularity="section"),
                  lgb.Dataset(X, label=y), num_boost_round=3)
    assert not b._gbdt._fast_path_ok()
    recs = [json.loads(line) for line in open(out)]
    iters = [r for r in recs if r["event"] == "iteration"]
    assert len(iters) == 3
    for r in iters:
        assert "histogram_split" in r["sections"]
        assert "score_update" in r["sections"]


def test_trace_out_implies_section_granularity(tmp_path):
    # the Chrome-trace exporter needs synced sections; batch granularity
    # must not silently produce an empty timeline
    X, y = _data(n=600)
    b = lgb.train(dict(FUSED, telemetry_out=str(tmp_path / "t.jsonl"),
                       trace_out=str(tmp_path / "trace.json")),
                  lgb.Dataset(X, label=y), num_boost_round=2)
    assert b._gbdt._tel_granularity() == "section"
    assert not b._gbdt._fast_path_ok()
    assert (tmp_path / "trace.json").exists()


def test_compilation_cache_dir_applied(tmp_path):
    import jax
    before = jax.config.jax_compilation_cache_dir
    cache = tmp_path / "xla_cache"
    try:
        X, y = _data(n=300)
        lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1,
                   "compilation_cache_dir": str(cache)},
                  lgb.Dataset(X, label=y), num_boost_round=2)
        assert jax.config.jax_compilation_cache_dir == str(cache)
    finally:
        jax.config.update("jax_compilation_cache_dir", before)


def test_megastep_disabled_for_unarmed_per_iteration_observers():
    # callbacks observe individual iterations -> engine.train must not
    # arm the megastep; training still works on the per-iteration path
    X, y = _data(n=600)
    seen = []
    cb = lambda env: seen.append(env.iteration)   # noqa: E731
    b = lgb.train(dict(FUSED), lgb.Dataset(X, label=y),
                  num_boost_round=4, callbacks=[cb])
    assert seen == [0, 1, 2, 3]
    assert b.num_trees() == 4
    assert not b._gbdt._megastep_fns
