"""Async pipelined training fast path (boosting/gbdt.py
_train_one_iter_fast / drain_pending).

The fast path defers HostTree materialisation: device trees queue up and
drain in batches, removing the 2-3 blocking host syncs per tree that
dominate remote-attached-TPU latency (ref behaviour being replaced:
gbdt.cpp:371 TrainOneIter's synchronous bookkeeping).
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _data(n=3000, f=8, seed=3):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float32)
    return X, y


FUSED = {"objective": "binary", "num_leaves": 15, "learning_rate": 0.2,
         "verbose": -1, "min_data_in_leaf": 5, "tpu_engine": "fused"}


def test_fast_path_engages_and_defers():
    X, y = _data()
    b = lgb.Booster(params=dict(FUSED), train_set=lgb.Dataset(X, label=y))
    for _ in range(10):
        b.update()
    g = b._gbdt
    assert g._fast_path_ok()
    assert len(g._pending) == 10          # nothing materialised yet
    assert b.num_trees() == 10            # num_trees drains
    assert len(g._pending) == 0


def test_fast_matches_sync_path():
    X, y = _data()
    b1 = lgb.Booster(params=dict(FUSED), train_set=lgb.Dataset(X, label=y))
    for _ in range(20):
        b1.update()
    b2 = lgb.Booster(params=dict(FUSED), train_set=lgb.Dataset(X, label=y))
    b2._gbdt._fast_ok_cache = False       # force the synchronous path
    for _ in range(20):
        b2.update()
    assert b1._gbdt._fast_path_ok() and not b2._gbdt._fast_path_ok()
    p1, p2 = b1.predict(X), b2.predict(X)
    # same trees; trajectories differ only by f32-vs-f64 shrinkage rounding
    assert np.abs(p1 - p2).max() < 1e-5
    assert b1.num_trees() == b2.num_trees()
    for t1, t2 in zip(b1.models, b2.models):
        assert t1.num_leaves == t2.num_leaves
        assert np.array_equal(t1.split_feature, t2.split_feature)


def test_stop_condition_detected_at_drain():
    X, y = _data()
    params = dict(FUSED)
    params["min_sum_hessian_in_leaf"] = 1e9   # no split can ever pass
    b = lgb.Booster(params=params, train_set=lgb.Dataset(X, label=y))
    for _ in range(6):
        if b.update():
            break
    b._gbdt.drain_pending()
    assert b._gbdt._stopped_early
    # the reference keeps ONE constant tree carrying the init score when
    # the very first iteration finds no split (gbdt.cpp:421-437)
    assert b.num_trees() == 1
    assert b._gbdt.iter == 0
    assert b.models[0].num_leaves == 1
    # training scores match the reference's double bookkeeping
    # (BoostFromAverage + constant-tree AddScore)
    import math
    init = math.log(y.mean() / (1.0 - y.mean()))
    s = np.asarray(b._gbdt.scores)
    assert np.allclose(s, 2.0 * init, atol=1e-4)
    assert abs(b.models[0].leaf_value[0] - init) < 1e-4


def test_stop_mid_stream_keeps_earlier_trees():
    # min_sum_hessian chosen so a few splits succeed before drying up
    X, y = _data(n=400)
    params = dict(FUSED)
    params["min_sum_hessian_in_leaf"] = 20.0
    params["learning_rate"] = 0.9
    b = lgb.Booster(params=params, train_set=lgb.Dataset(X, label=y))
    for _ in range(30):
        if b.update():
            break
    b._gbdt.drain_pending()
    nt = b.num_trees()
    assert 0 < nt < 30
    # replayed scores must equal a from-scratch prediction of the kept model
    pred = b.predict(X, raw_score=True)
    scores = np.asarray(b._gbdt.scores[0], np.float64)
    base = scores - pred
    assert np.allclose(base, base[0], atol=1e-5)   # constant init offset
    assert np.abs(base[0]) < 10.0


def test_model_io_after_pipelined_training():
    X, y = _data()
    b = lgb.Booster(params=dict(FUSED), train_set=lgb.Dataset(X, label=y))
    for _ in range(8):
        b.update()
    s = b.model_to_string()               # drains internally
    b2 = lgb.Booster(model_str=s)
    assert np.array_equal(b2.predict(X), b.predict(X))


def test_eval_during_pipelined_training():
    X, y = _data()
    params = dict(FUSED)
    params["metric"] = "auc"
    params["is_provide_training_metric"] = True
    b = lgb.Booster(params=params, train_set=lgb.Dataset(X, label=y))
    for _ in range(5):
        b.update()
    res = b.eval_train()
    assert res and res[0][1] == "auc" and res[0][2] > 0.9


def test_valid_set_keeps_fast_path():
    # round 3 (VERDICT r2 weak #3): valid sets no longer force the sync
    # path — their score updates run in-jit from the device TreeArrays
    X, y = _data()
    Xv, yv = _data(seed=11)
    b = lgb.Booster(params=dict(FUSED), train_set=lgb.Dataset(X, label=y))
    for _ in range(4):
        b.update()
    ds_v = lgb.Dataset(Xv, label=yv, reference=lgb.Dataset(X, label=y))
    b.add_valid(ds_v, "v0")               # drains + replays, then fast
    assert b._gbdt._fast_path_ok()
    for _ in range(4):
        b.update()
    assert b.num_trees() == 8
    res = b.eval_valid()
    assert len(res) > 0 and res[0][0] == "v0"
    # the in-jit valid scores must equal a fresh replay of the model
    import numpy as np
    replay = np.asarray(b.predict(Xv, raw_score=True))
    np.testing.assert_allclose(
        np.asarray(b._gbdt.valid_scores[0][0]), replay, atol=1e-4)


def test_bagging_on_fast_path():
    X, y = _data()
    params = dict(FUSED)
    params.update(bagging_fraction=0.6, bagging_freq=2, bagging_seed=7)
    b = lgb.Booster(params=params, train_set=lgb.Dataset(X, label=y))
    for _ in range(10):
        b.update()
    assert b._gbdt._fast_path_ok()
    assert b.num_trees() == 10
    from sklearn.metrics import roc_auc_score
    assert roc_auc_score(y, b.predict(X)) > 0.95


def test_multiclass_rare_class_keeps_init_score():
    # a rare class whose softmax hessian can't clear min_sum_hessian
    # dries up on iteration 0 while the others grow; the constant tree
    # must carry its log-prior exactly like the sync path
    rng = np.random.RandomState(9)
    X = rng.rand(600, 5).astype(np.float32)
    y = (X[:, 0] > 0.5).astype(np.float32)
    y[:12] = 2.0          # 12 rows of class 2
    params = {"objective": "multiclass", "num_class": 3, "num_leaves": 7,
              "verbose": -1, "min_data_in_leaf": 2,
              "min_sum_hessian_in_leaf": 20.0, "tpu_engine": "fused"}
    b1 = lgb.Booster(params=dict(params),
                     train_set=lgb.Dataset(X, label=y))
    b2 = lgb.Booster(params=dict(params),
                     train_set=lgb.Dataset(X, label=y))
    b2._gbdt._fast_ok_cache = False
    for _ in range(3):
        b1.update()
        b2.update()
    r1 = b1.predict(X, raw_score=True)
    r2 = b2.predict(X, raw_score=True)
    # the dried class must carry its log-prior EXACTLY like the sync path
    assert np.abs(r1[:, 2] - r2[:, 2]).max() < 1e-6
    assert abs(b1.models[2].leaf_value[0] - np.log(12 / 600)) < 0.2
    # grown classes: same quality up to near-tie trajectory drift
    assert np.abs(r1 - r2).max() < 0.1


def test_multiclass_fast_matches_sync():
    rng = np.random.RandomState(5)
    X = rng.rand(1500, 6).astype(np.float32)
    y = (X[:, 0] * 3).astype(np.int32).clip(0, 2).astype(np.float32)
    params = {"objective": "multiclass", "num_class": 3, "num_leaves": 7,
              "learning_rate": 0.3, "verbose": -1, "min_data_in_leaf": 5,
              "tpu_engine": "fused"}
    b1 = lgb.Booster(params=dict(params),
                     train_set=lgb.Dataset(X, label=y))
    for _ in range(6):
        b1.update()
    assert b1._gbdt._fast_path_ok()
    b2 = lgb.Booster(params=dict(params),
                     train_set=lgb.Dataset(X, label=y))
    b2._gbdt._fast_ok_cache = False
    for _ in range(6):
        b2.update()
    assert b1.num_trees() == b2.num_trees() == 18
    # trajectories may pick different near-tie splits (f32-vs-f64
    # shrinkage rounding compounded by softmax coupling); both paths must
    # deliver the same quality, like the reference's CPU-vs-GPU drift band
    p1, p2 = b1.predict(X), b2.predict(X)
    assert np.abs(p1 - p2).max() < 5e-3
    acc1 = (p1.argmax(1) == y).mean()
    acc2 = (p2.argmax(1) == y).mean()
    assert acc1 > 0.95 and abs(acc1 - acc2) < 0.01


def test_subclassed_objective_not_trained_with_base_gradients():
    # huber subclasses L2 overriding only get_gradients; the fast path
    # must NOT pair the inherited gradient_operands with L2's
    # gradients_from (it would silently train unclipped L2)
    rng = np.random.RandomState(13)
    X = rng.rand(2000, 6).astype(np.float32)
    y = (X[:, 0] * 3 + 0.1 * rng.randn(2000)).astype(np.float32)
    y[:20] += 50.0    # outliers huber must resist
    params = {"objective": "huber", "alpha": 0.5, "num_leaves": 15,
              "learning_rate": 0.2, "verbose": -1, "min_data_in_leaf": 5,
              "tpu_engine": "fused"}
    b1 = lgb.Booster(params=dict(params),
                     train_set=lgb.Dataset(X, label=y))
    b2 = lgb.Booster(params=dict(params),
                     train_set=lgb.Dataset(X, label=y))
    b2._gbdt._fast_ok_cache = False
    for _ in range(10):
        b1.update()
        b2.update()
    assert np.abs(b1.predict(X) - b2.predict(X)).max() < 1e-4


def test_engine_train_uses_fast_path():
    X, y = _data()
    bst = lgb.train(dict(FUSED), lgb.Dataset(X, label=y),
                    num_boost_round=12)
    assert bst.num_trees() == 12
    from sklearn.metrics import roc_auc_score
    assert roc_auc_score(y, bst.predict(X)) > 0.95
