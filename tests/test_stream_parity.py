"""Parity of the streaming dataset-construction surfaces against the
monolithic build: the C-API push-rows protocol (capi_support._PushBuild
— dense chunks, CSR chunks, SetField-during-build) and the CLI
``task=save_binary`` -> reload round trip."""
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import capi_support as capi


def _data(R=600, F=5, seed=11):
    rng = np.random.RandomState(seed)
    X = rng.rand(R, F)
    X[X < 0.15] = 0.0                    # sparsity for the CSR leg
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float32)
    return np.ascontiguousarray(X), y


def _train_model_str(ds, rounds=8):
    bst = capi.booster_create(
        ds, "objective=binary num_leaves=15 learning_rate=0.2 verbose=-1")
    for _ in range(rounds):
        capi.booster_update(bst)
    return capi.booster_save_model_to_string(bst, 0, -1, 0)


def _mono_ds(X, y):
    ds = capi.dataset_create_from_mat(
        X.ctypes.data, 1, X.shape[0], X.shape[1], 1,
        "max_bin=63 verbose=-1", None)
    yc = np.ascontiguousarray(y, np.float32)
    capi.dataset_set_field(ds, "label", yc.ctypes.data, len(yc), 0)
    return ds


def test_push_rows_dense_matches_monolithic():
    X, y = _data()
    mono = _mono_ds(X, y)
    push = capi.dataset_create_by_reference(mono, X.shape[0])
    for lo in range(0, X.shape[0], 173):
        chunk = np.ascontiguousarray(X[lo:lo + 173])
        capi.dataset_push_rows(push, chunk.ctypes.data, 1,
                               chunk.shape[0], X.shape[1], lo)
    yc = np.ascontiguousarray(y, np.float32)
    capi.dataset_set_field(push, "label", yc.ctypes.data, len(yc), 0)
    assert _train_model_str(mono) == _train_model_str(push)


def test_push_rows_set_field_during_build():
    # SetField BEFORE the final chunk arrives is legal (the reference's
    # streaming protocol): it is applied at finalize and must match
    # setting it after construction
    X, y = _data(R=400)
    mono = _mono_ds(X, y)
    push = capi.dataset_create_by_reference(mono, X.shape[0])
    yc = np.ascontiguousarray(y, np.float32)
    half = X.shape[0] // 2
    first = np.ascontiguousarray(X[:half])
    capi.dataset_push_rows(push, first.ctypes.data, 1, half,
                           X.shape[1], 0)
    # mid-build SetField (the build is not finalized yet)
    capi.dataset_set_field(push, "label", yc.ctypes.data, len(yc), 0)
    assert capi.dataset_num_data(push) == X.shape[0]   # declared size
    rest = np.ascontiguousarray(X[half:])
    capi.dataset_push_rows(push, rest.ctypes.data, 1, X.shape[0] - half,
                           X.shape[1], half)
    assert _train_model_str(mono) == _train_model_str(push)


def test_push_rows_missing_chunk_refused():
    X, y = _data(R=300)
    mono = _mono_ds(X, y)
    push = capi.dataset_create_by_reference(mono, X.shape[0])
    first = np.ascontiguousarray(X[:100])
    capi.dataset_push_rows(push, first.ctypes.data, 1, 100, X.shape[1], 0)
    with pytest.raises(ValueError, match="never pushed"):
        push.finalize()


def test_push_rows_csr_matches_monolithic():
    sp = pytest.importorskip("scipy.sparse")
    X, y = _data()
    mono = _mono_ds(X, y)
    push = capi.dataset_create_by_reference(mono, X.shape[0])
    for lo in range(0, X.shape[0], 211):
        chunk = sp.csr_matrix(X[lo:lo + 211])
        indptr = np.ascontiguousarray(chunk.indptr, np.int32)
        indices = np.ascontiguousarray(chunk.indices, np.int32)
        vals = np.ascontiguousarray(chunk.data, np.float64)
        capi.dataset_push_rows_by_csr(
            push, indptr.ctypes.data, 2, indices.ctypes.data,
            vals.ctypes.data, 1, len(indptr), len(vals), X.shape[1], lo)
    yc = np.ascontiguousarray(y, np.float32)
    capi.dataset_set_field(push, "label", yc.ctypes.data, len(yc), 0)
    assert _train_model_str(mono) == _train_model_str(push)


def test_capi_save_binary_roundtrip(tmp_path):
    X, y = _data()
    mono = _mono_ds(X, y)
    cp = str(tmp_path / "capi.bin")
    capi.dataset_save_binary(mono, cp)
    reloaded = capi.dataset_create_from_file(cp, "verbose=-1", None)
    assert _train_model_str(mono) == _train_model_str(reloaded)


# ------------------------------------------------------------ CLI task
def _write_csv(path, X, y):
    with open(path, "w") as f:
        for i in range(len(y)):
            f.write(",".join([f"{y[i]:g}"]
                             + [repr(float(v)) for v in X[i]]) + "\n")


def test_cli_save_binary_reload_roundtrip(tmp_path):
    from lightgbm_tpu.cli import main as cli_main
    X, y = _data(R=500)
    p = str(tmp_path / "t.csv")
    _write_csv(p, X, y)
    cli_main([f"task=save_binary", f"data={p}", "max_bin=63",
              "verbose=-1"])
    cache = p + ".bin"
    assert os.path.exists(cache)

    params = {"objective": "binary", "max_bin": 63, "num_leaves": 15,
              "verbose": -1, "metric": "None"}
    m_text = lgb.train(dict(params),
                       lgb.Dataset(p, params={"max_bin": 63,
                                              "verbose": -1}),
                       num_boost_round=8)
    m_cache = lgb.train(dict(params),
                        lgb.Dataset(cache, params={"verbose": -1}),
                        num_boost_round=8)
    assert m_text.model_to_string(num_iteration=-1) \
        == m_cache.model_to_string(num_iteration=-1)


def test_cli_save_binary_explicit_output(tmp_path):
    from lightgbm_tpu.cli import main as cli_main
    from lightgbm_tpu.ingest.cache import read_manifest
    X, y = _data(R=300)
    p = str(tmp_path / "t.csv")
    _write_csv(p, X, y)
    out = str(tmp_path / "elsewhere.bin")
    cli_main([f"task=save_binary", f"data={p}", f"output_model={out}",
              "verbose=-1"])
    assert read_manifest(out)["num_data"] == 300
    ds = lgb.Dataset(out, params={"verbose": -1})
    ds.construct()
    assert ds._inner.num_data == 300


def test_cli_train_from_cache(tmp_path):
    # the full CLI train task fed a cache artifact instead of text
    from lightgbm_tpu.cli import main as cli_main
    X, y = _data(R=400)
    p = str(tmp_path / "t.csv")
    _write_csv(p, X, y)
    cli_main([f"task=save_binary", f"data={p}", "max_bin=63",
              "verbose=-1"])
    model_out = str(tmp_path / "model.txt")
    cli_main([f"task=train", f"data={p}.bin", "objective=binary",
              "num_iterations=5", "max_bin=63", "verbose=-1",
              f"output_model={model_out}"])
    assert os.path.exists(model_out)
    bst = lgb.Booster(model_file=model_out)
    assert bst.num_trees() == 5
