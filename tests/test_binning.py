"""BinMapper unit tests (behavior mirrors ref: src/io/bin.cpp FindBin)."""
import numpy as np
import pytest

from lightgbm_tpu.binning import (BIN_CATEGORICAL, BIN_NUMERICAL, MISSING_NAN,
                                  MISSING_NONE, MISSING_ZERO, BinMapper)


def make_mapper(values, total=None, max_bin=255, min_data_in_bin=3,
                bin_type=BIN_NUMERICAL, use_missing=True,
                zero_as_missing=False):
    m = BinMapper()
    values = np.asarray(values, dtype=np.float64)
    nz = values[(np.abs(values) > 1e-35) | np.isnan(values)]
    m.find_bin(nz, total_sample_cnt=total or len(values), max_bin=max_bin,
               min_data_in_bin=min_data_in_bin, min_split_data=0,
               pre_filter=False, bin_type=bin_type, use_missing=use_missing,
               zero_as_missing=zero_as_missing)
    return m


def test_bins_are_order_preserving():
    rng = np.random.RandomState(0)
    vals = rng.randn(5000)
    m = make_mapper(vals, max_bin=63)
    bins = m.value_to_bin(vals)
    order = np.argsort(vals)
    assert (np.diff(bins[order]) >= 0).all()


def test_bin_count_capped():
    rng = np.random.RandomState(1)
    vals = rng.randn(10000)
    m = make_mapper(vals, max_bin=16)
    assert m.num_bin <= 16


def test_distinct_values_get_own_bins():
    vals = np.repeat([1.0, 2.0, 3.0], 100)
    m = make_mapper(vals, min_data_in_bin=1)
    bins = m.value_to_bin(np.array([1.0, 2.0, 3.0]))
    assert len(set(bins.tolist())) == 3


def test_nan_goes_to_last_bin():
    vals = np.concatenate([np.random.RandomState(2).randn(1000),
                           [np.nan] * 50])
    m = make_mapper(vals)
    assert m.missing_type == MISSING_NAN
    assert m.value_to_bin(np.nan) == m.num_bin - 1
    assert m.value_to_bin(0.0) < m.num_bin - 1


def test_no_missing():
    vals = np.random.RandomState(3).randn(500) + 10
    m = make_mapper(vals)
    assert m.missing_type == MISSING_NONE


def test_zero_as_missing():
    vals = np.concatenate([np.random.RandomState(4).randn(500), [0.0] * 400])
    m = make_mapper(vals, zero_as_missing=True)
    assert m.missing_type == MISSING_ZERO


def test_zero_bin_is_default():
    # sparse feature: zeros dominate, default bin holds them
    vals = np.concatenate([np.random.RandomState(5).rand(100) + 1.0,
                           np.zeros(900)])
    m = make_mapper(vals)
    assert m.value_to_bin(0.0) == m.default_bin
    assert m.most_freq_bin == m.default_bin


def test_trivial_constant_feature():
    m = make_mapper(np.ones(100) * 5.0)
    assert not m.is_trivial  # one distinct nonzero value + implicit zero
    m2 = make_mapper(np.zeros(100))
    assert m2.is_trivial


def test_categorical_count_sorted():
    rng = np.random.RandomState(6)
    vals = rng.choice([3, 7, 11], size=1000, p=[0.6, 0.3, 0.1])
    m = make_mapper(vals.astype(float), bin_type=BIN_CATEGORICAL,
                    min_data_in_bin=1)
    # most frequent category gets bin 1 (bin 0 reserved for NaN/other)
    assert m.bin_2_categorical[1] == 3
    assert m.value_to_bin(3.0) == 1
    assert m.value_to_bin(7.0) == 2


def test_serialization_roundtrip():
    vals = np.random.RandomState(7).randn(1000)
    m = make_mapper(vals, max_bin=31)
    m2 = BinMapper.from_dict(m.to_dict())
    x = np.random.RandomState(8).randn(100)
    assert (m.value_to_bin(x) == m2.value_to_bin(x)).all()
    assert m2.num_bin == m.num_bin


def test_min_data_in_bin_respected():
    # with min_data_in_bin=50 over 200 samples, at most 4 numeric bins
    vals = np.random.RandomState(9).rand(200) + 1.0
    m = make_mapper(vals, max_bin=255, min_data_in_bin=50)
    bins = m.value_to_bin(vals)
    counts = np.bincount(bins, minlength=m.num_bin)
    # every non-empty interior bin holds >= min_data_in_bin
    nonzero = counts[counts > 0]
    assert (nonzero >= 40).all()  # greedy packing allows slight undershoot


def test_max_bin_by_feature():
    """(ref: config.h max_bin_by_feature)"""
    import numpy as np
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import TpuDataset
    rng = np.random.RandomState(0)
    X = rng.rand(2000, 3)
    cfg = Config({"max_bin": 255, "max_bin_by_feature": [8, 255, 16],
                  "verbose": -1})
    ds = TpuDataset.from_data(X, cfg)
    assert ds.mappers[0].num_bin <= 8
    assert ds.mappers[1].num_bin > 100
    assert ds.mappers[2].num_bin <= 16
