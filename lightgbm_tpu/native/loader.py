"""ctypes bridge to the native text parser (parser.cpp).

Compiles the shared library on first use with the system toolchain and
caches it next to the source (the image bakes g++ but not pybind11, so the
binding layer is plain ctypes per the C ABI in parser.cpp). A pure-numpy
fallback keeps file loading functional without a compiler.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

from ..utils import log

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO_PATH = os.path.join(_HERE, "libparser.so")
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _compile(src: str, out: str, extra: Tuple[str, ...] = (),
             fallback_extra: Optional[Tuple[str, ...]] = None,
             timeout: int = 180) -> str:
    """Flag-stamped, mtime-cached g++ compile with an atomic publish:
    build to a process-unique temp path, then rename, so a concurrent
    process can never dlopen a half-written .so. A sidecar stamp records
    the flag set that produced the cached .so — a flag or Python-version
    change (or an earlier degraded fallback build) invalidates it instead
    of being pinned forever. Callers serialize same-process builds under
    _LOCK. Raises on failure."""
    stamp_path = out + ".flags"
    want_stamp = " ".join(extra)
    if os.path.exists(out) and \
            os.path.getmtime(out) >= os.path.getmtime(src):
        have = None
        if os.path.exists(stamp_path):
            with open(stamp_path) as fh:
                have = fh.read()
        if have == want_stamp:
            return out
    tmp = f"{out}.{os.getpid()}.{threading.get_ident()}.tmp"
    base = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", src, "-o", tmp]
    built_stamp = want_stamp
    r = subprocess.run(base[:-2] + list(extra) + base[-2:],
                       capture_output=True, timeout=timeout)
    if r.returncode != 0 and fallback_extra is not None:
        subprocess.run(base[:-2] + list(fallback_extra) + base[-2:],
                       check=True, capture_output=True, timeout=timeout)
        built_stamp = " ".join(fallback_extra)
        log.warning("%s built with FALLBACK flags (%s); a pure-C host "
                    "may fail to dlopen it", os.path.basename(out),
                    built_stamp)
    elif r.returncode != 0:
        raise RuntimeError(r.stderr.decode()[-300:])
    os.replace(tmp, out)
    with open(stamp_path, "w") as fh:
        fh.write(built_stamp)
    return out


def _build() -> Optional[str]:
    try:
        return _compile(os.path.join(_HERE, "parser.cpp"), _SO_PATH)
    except Exception as e:  # no toolchain / sandboxed build dir
        log.warning("native parser build failed (%s); using the slower "
                    "numpy text parser", e)
        return None


def get_lib() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        path = _build()
        if path is None:
            return None
        lib = ctypes.CDLL(path)
        lib.lgbt_scan.restype = ctypes.c_int
        lib.lgbt_scan.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_char),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
        lib.lgbt_parse_dense.restype = ctypes.c_int
        lib.lgbt_parse_dense.argtypes = [
            ctypes.c_char_p, ctypes.c_char, ctypes.c_int,
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64, ctypes.c_int64]
        lib.lgbt_parse_libsvm.restype = ctypes.c_int
        lib.lgbt_parse_libsvm.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64, ctypes.c_int64]
        for name in ("lgbt_parse_dense_range", "lgbt_parse_libsvm_range"):
            fn = getattr(lib, name, None)
            if fn is None:
                continue   # stale cached .so predating the range ABI
            fn.restype = ctypes.c_int
        if hasattr(lib, "lgbt_parse_dense_range"):
            lib.lgbt_parse_dense_range.argtypes = [
                ctypes.c_char_p, ctypes.c_char, ctypes.c_int,
                ctypes.c_int64, ctypes.POINTER(ctypes.c_float),
                ctypes.c_int64, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int64)]
        if hasattr(lib, "lgbt_parse_libsvm_range"):
            lib.lgbt_parse_libsvm_range.argtypes = [
                ctypes.c_char_p, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
                ctypes.c_int64, ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int64)]
        _LIB = lib
        return _LIB


def scan(path: str) -> Tuple[str, int, int, bool, bool]:
    """(sep, n_rows, n_cols, is_libsvm, has_header) for a text file."""
    lib = get_lib()
    if lib is not None:
        sep = ctypes.c_char(b",")
        rows = ctypes.c_int64(0)
        cols = ctypes.c_int64(0)
        is_svm = ctypes.c_int(0)
        header = ctypes.c_int(0)
        rc = lib.lgbt_scan(path.encode(), ctypes.byref(sep),
                           ctypes.byref(rows), ctypes.byref(cols),
                           ctypes.byref(is_svm), ctypes.byref(header))
        if rc != 0:
            raise IOError(f"cannot scan {path} (rc={rc})")
        return (sep.value.decode(), rows.value, cols.value,
                bool(is_svm.value), bool(header.value))
    return _scan_numpy(path)


def parse_dense(path: str, sep: str, has_header: bool, n_rows: int,
                n_cols: int) -> np.ndarray:
    lib = get_lib()
    if lib is not None:
        out = np.empty((n_rows, n_cols), np.float32)
        rc = lib.lgbt_parse_dense(
            path.encode(), sep.encode(), int(has_header),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            n_rows, n_cols)
        if rc != 0:
            raise IOError(f"cannot parse {path} (rc={rc})")
        return out
    return _parse_dense_numpy(path, sep, has_header, n_rows, n_cols)


def parse_dense_range(path: str, sep: str, skip_header: bool, offset: int,
                      max_rows: int, n_cols: int):
    """Chunked resumable dense parse -> (X [rows, n_cols] float32,
    next_offset).  Byte ``offset`` 0 starts at the file head (the header
    is skipped only there); pass the returned ``next_offset`` back to
    continue.  Routes through the SAME native field parser as
    ``parse_dense`` so chunked ingest is bit-identical to the monolithic
    load; falls back to the shared numpy line parser without one."""
    lib = get_lib()
    if lib is not None and hasattr(lib, "lgbt_parse_dense_range"):
        out = np.empty((max_rows, n_cols), np.float32)
        rows = ctypes.c_int64(0)
        nxt = ctypes.c_int64(0)
        rc = lib.lgbt_parse_dense_range(
            path.encode(), sep.encode(), int(skip_header), int(offset),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            max_rows, n_cols, ctypes.byref(rows), ctypes.byref(nxt))
        if rc != 0:
            raise IOError(f"cannot parse {path} at {offset} (rc={rc})")
        return out[:rows.value], int(nxt.value)
    return _parse_range_numpy(path, offset, max_rows, skip_header,
                              lambda line, dst: _dense_line_numpy(
                                  line, sep, dst), n_cols)


def parse_libsvm_range(path: str, offset: int, max_rows: int,
                       n_cols: int):
    """Chunked resumable LibSVM parse -> (X [rows, n_cols-1] float32,
    label [rows] float32, next_offset); file column 0 is the label,
    zeros implicit."""
    lib = get_lib()
    n_feat = n_cols - 1
    if lib is not None and hasattr(lib, "lgbt_parse_libsvm_range"):
        out = np.empty((max_rows, n_feat), np.float32)
        lab = np.empty((max_rows,), np.float32)
        rows = ctypes.c_int64(0)
        nxt = ctypes.c_int64(0)
        rc = lib.lgbt_parse_libsvm_range(
            path.encode(), int(offset),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            lab.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            max_rows, n_feat, ctypes.byref(rows), ctypes.byref(nxt))
        if rc != 0:
            raise IOError(f"cannot parse {path} at {offset} (rc={rc})")
        return out[:rows.value], lab[:rows.value], int(nxt.value)
    labels = np.empty((max_rows,), np.float32)

    def _line(line, dst):
        row_idx = _line.i
        _line.i += 1
        dst[:] = 0.0
        labels[row_idx] = _libsvm_line_numpy(line, dst)
    _line.i = 0
    X, nxt = _parse_range_numpy(path, offset, max_rows, False, _line,
                                n_feat, zero_fill=True)
    return X, labels[:len(X)], nxt


def parse_libsvm(path: str, n_rows: int,
                 n_cols: int) -> Tuple[np.ndarray, np.ndarray]:
    """(X [n_rows, n_cols-1], label [n_rows]) — file column 0 is the
    label; zeros are implicit (LibSVM sparse convention)."""
    lib = get_lib()
    n_feat = n_cols - 1
    if lib is not None:
        out = np.empty((n_rows, n_feat), np.float32)
        lab = np.empty((n_rows,), np.float32)
        rc = lib.lgbt_parse_libsvm(
            path.encode(),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            lab.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            n_rows, n_feat)
        if rc != 0:
            raise IOError(f"cannot parse {path} (rc={rc})")
        return out, lab
    return _parse_libsvm_numpy(path, n_rows, n_feat)


# ---------------------------------------------------------------- fallbacks
def _scan_numpy(path: str):
    sep, rows, cols, libsvm, header = ",", 0, 0, False, False
    with open(path) as f:
        first = True
        for line in f:
            # line classification MUST match the C scanner and the
            # (range) parsers: empty after CR/LF strip, or FIRST char
            # '#' — a whole-line strip would skip whitespace-only lines
            # the parsers count as (all-NaN) data rows, desynchronizing
            # n_rows from the parse
            line = line.rstrip("\r\n")
            if not line or line.startswith("#"):
                continue
            if first:
                if "\t" in line:
                    sep = "\t"
                elif "," in line:
                    sep = ","
                else:
                    sep = " "
                toks = line.split() if sep == " " else line.split(sep)
                if len(toks) > 1 and ":" in toks[1] and \
                        toks[1].split(":")[0].isdigit():
                    libsvm, sep = True, " "
                if not libsvm:
                    def num(t):
                        try:
                            float(t or "nan")
                            return True
                        except ValueError:
                            return t.lower() in ("na", "nan", "null", "none",
                                                 "")
                    header = not all(num(t) for t in toks)
                first = False
                if header:
                    continue
            rows += 1
            if libsvm:
                for t in line.split()[1:]:
                    if ":" in t:
                        cols = max(cols, int(t.split(":")[0]) + 1)
            else:
                cols = max(cols, len(line.split(sep)))
    return sep, rows, (cols + 1 if libsvm else cols), libsvm, header


def _dense_line_numpy(line: str, sep: str, dst: np.ndarray) -> None:
    """The ONE numpy-fallback dense row parser (missing/garbage fields
    -> NaN, ragged lines NaN-padded) — the monolithic and chunked
    fallbacks share it so they cannot drift."""
    toks = line.split(sep)
    n = len(dst)
    for col in range(n):
        if col < len(toks):
            t = toks[col].strip()
            try:
                dst[col] = float(t) if t else np.nan
            except ValueError:
                dst[col] = np.nan
        else:
            dst[col] = np.nan


def _libsvm_line_numpy(line: str, dst: np.ndarray) -> float:
    toks = line.split()
    try:
        lab = float(toks[0])
    except (ValueError, IndexError):
        lab = 0.0
    for t in toks[1:]:
        if ":" not in t:
            continue
        k, v = t.split(":", 1)
        try:
            k = int(k)
        except ValueError:
            continue
        if 0 <= k < len(dst):
            try:
                dst[k] = float(v)
            except ValueError:
                pass
    return lab


def _parse_range_numpy(path: str, offset: int, max_rows: int,
                       skip_header: bool, line_fn, n_cols: int,
                       zero_fill: bool = False):
    """Bounded resumable line-at-a-time parse into a preallocated chunk
    buffer -> (X[:rows], next_byte_offset). Reads in binary so byte
    offsets are exact across encodings/newlines."""
    out = np.empty((max_rows, n_cols), np.float32)
    row = 0
    with open(path, "rb") as f:
        if offset > 0:
            f.seek(offset)
        consumed = offset
        first = offset == 0
        while row < max_rows:
            raw = f.readline()
            if not raw:
                break
            line = raw.decode("utf-8", "replace").rstrip("\r\n")
            if not line or line.startswith("#"):
                consumed = f.tell()
                continue
            if first and skip_header:
                first = False
                consumed = f.tell()
                continue
            first = False
            if zero_fill:
                out[row] = 0.0
            line_fn(line, out[row])
            row += 1
            consumed = f.tell()
    return out[:row], consumed


def _parse_dense_numpy(path: str, sep: str, has_header: bool,
                       n_rows: int, n_cols: int) -> np.ndarray:
    """Whole-file fallback parse via the bounded line iterator: one
    preallocated [n_rows, n_cols] float32 output, no per-line Python
    list accumulation (the old form held every field as a boxed float —
    ~25x the array's own RSS on wide files)."""
    out, _ = _parse_range_numpy(
        path, 0, n_rows, has_header,
        lambda line, dst: _dense_line_numpy(line, sep, dst), n_cols)
    if out.shape[0] != n_rows:
        raise IOError(f"{path}: expected {n_rows} data rows, parsed "
                      f"{out.shape[0]}")
    return out


def _parse_libsvm_numpy(path: str, n_rows: int, n_feat: int):
    X = np.zeros((n_rows, n_feat), np.float32)
    y = np.zeros((n_rows,), np.float32)
    i = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            y[i] = _libsvm_line_numpy(line, X[i])
            i += 1
    return X, y


# ---------------------------------------------------------------- C ABI
_CAPI_SO = os.path.join(_HERE, "libcapi.so")


def build_capi() -> Optional[str]:
    """Compile the LGBM_* C ABI library (capi.cpp). Returns the .so path
    or None when no toolchain is available. Loaded into a Python host it
    resolves interpreter symbols from the process; a pure-C host gets
    them from the linked libpython (falls back to not linking it)."""
    import sysconfig
    inc = sysconfig.get_paths()["include"]
    ver = sysconfig.get_config_var("LDVERSION") or \
        sysconfig.get_config_var("VERSION")
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    extra = [f"-I{inc}"]
    if libdir:
        extra += [f"-L{libdir}", f"-Wl,-rpath,{libdir}"]
    extra += [f"-lpython{ver}"]
    try:
        with _LOCK:
            return _compile(os.path.join(_HERE, "capi.cpp"), _CAPI_SO,
                            tuple(extra), fallback_extra=(f"-I{inc}",))
    except Exception as e:
        log.warning("C ABI build failed (%s)", e)
        return None
