// C ABI for lightgbm_tpu — the stable embedding surface.
//
// Behavioral analog of the reference's C API core
// (ref: include/LightGBM/c_api.h, src/c_api.cpp): same symbol names,
// argument conventions, 0/-1 return codes, and LGBM_GetLastError
// contract for the subset that covers the train/predict/save/load
// lifecycle. Where the reference's C API fronts a C++ runtime, this one
// fronts the in-process Python/JAX runtime: each call enters the
// interpreter (initializing an embedded one if the host is a plain C
// program) and delegates to lightgbm_tpu.capi_support, which wraps the
// raw buffers with numpy without copying.
//
// Thread-safety matches the reference's "not thread-safe per handle"
// stance; calls serialize on the GIL.
#include <Python.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <functional>
#include <mutex>
#include <string>
#include <utility>
#include <vector>

#define LGBM_EXPORT extern "C" __attribute__((visibility("default")))

namespace {

thread_local std::string g_last_error = "everything is fine";

std::once_flag g_interp_once;

struct Gil {
  PyGILState_STATE state;
  Gil() {
    // pure-C host: bring up an embedded interpreter exactly once (two
    // host threads making their first concurrent LGBM_* calls must not
    // race Py_InitializeEx), then RELEASE the GIL the init acquired so
    // other host threads can enter
    std::call_once(g_interp_once, [] {
      if (!Py_IsInitialized()) {
        Py_InitializeEx(0);
        PyEval_SaveThread();
      }
    });
    state = PyGILState_Ensure();
  }
  ~Gil() { PyGILState_Release(state); }
};

PyObject* support() {
  static PyObject* mod = nullptr;
  if (mod == nullptr) {
    mod = PyImport_ImportModule("lightgbm_tpu.capi_support");
  }
  return mod;
}

int fail_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      const char* c = PyUnicode_AsUTF8(s);
      g_last_error = c ? c : "unknown python error";
      Py_DECREF(s);
    }
  } else {
    g_last_error = "unknown error";
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  return -1;
}

// call capi_support.<fn>(args...); returns new ref or nullptr
PyObject* call(const char* fn, PyObject* args) {
  PyObject* mod = support();
  if (mod == nullptr) return nullptr;
  PyObject* f = PyObject_GetAttrString(mod, fn);
  if (f == nullptr) return nullptr;
  PyObject* out = PyObject_CallObject(f, args);
  Py_DECREF(f);
  return out;
}

}  // namespace

LGBM_EXPORT const char* LGBM_GetLastError() { return g_last_error.c_str(); }

// data_type: 0 = float32 (C_API_DTYPE_FLOAT32), 1 = float64
LGBM_EXPORT int LGBM_DatasetCreateFromMat(const void* data, int data_type,
                                          int32_t nrow, int32_t ncol,
                                          int is_row_major,
                                          const char* parameters,
                                          void* reference, void** out) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(KiiiisO)", (unsigned long long)(uintptr_t)data, data_type,
      (int)nrow, (int)ncol, is_row_major, parameters ? parameters : "",
      reference ? (PyObject*)reference : Py_None);
  if (args == nullptr) return fail_from_python();
  PyObject* h = call("dataset_create_from_mat", args);
  Py_DECREF(args);
  if (h == nullptr) return fail_from_python();
  *out = (void*)h;  // owned handle
  return 0;
}

// field_data types: 0 float32, 1 float64, 2 int32, 3 int64
LGBM_EXPORT int LGBM_DatasetSetField(void* handle, const char* field_name,
                                     const void* field_data,
                                     int32_t num_element, int type) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(OsKii)", (PyObject*)handle, field_name,
      (unsigned long long)(uintptr_t)field_data, (int)num_element, type);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("dataset_set_field", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_DatasetGetNumData(void* handle, int32_t* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", (PyObject*)handle);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("dataset_num_data", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  *out = (int32_t)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_DatasetGetNumFeature(void* handle, int32_t* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", (PyObject*)handle);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("dataset_num_feature", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  *out = (int32_t)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_DatasetFree(void* handle) {
  Gil gil;
  Py_XDECREF((PyObject*)handle);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterCreate(void* train_data, const char* parameters,
                                   void** out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Os)", (PyObject*)train_data,
                                 parameters ? parameters : "");
  if (args == nullptr) return fail_from_python();
  PyObject* h = call("booster_create", args);
  Py_DECREF(args);
  if (h == nullptr) return fail_from_python();
  *out = (void*)h;
  return 0;
}

LGBM_EXPORT int LGBM_BoosterCreateFromModelfile(const char* filename,
                                                int* out_num_iterations,
                                                void** out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", filename);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("booster_from_modelfile", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  PyObject* h = PyTuple_GetItem(r, 0);
  *out_num_iterations = (int)PyLong_AsLong(PyTuple_GetItem(r, 1));
  Py_INCREF(h);
  Py_DECREF(r);
  *out = (void*)h;
  return 0;
}

LGBM_EXPORT int LGBM_BoosterAddValidData(void* booster, void* valid_data) {
  Gil gil;
  PyObject* args = Py_BuildValue("(OO)", (PyObject*)booster,
                                 (PyObject*)valid_data);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("booster_add_valid", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterUpdateOneIter(void* booster, int* is_finished) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", (PyObject*)booster);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("booster_update", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  *is_finished = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterGetCurrentIteration(void* booster, int* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", (PyObject*)booster);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("booster_current_iteration", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  *out = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

// required out_result length for a prediction call (ref: c_api.h
// LGBM_BoosterCalcNumPredict) — leaf/contrib outputs are larger than
// nrow, so callers MUST size buffers with this
LGBM_EXPORT int LGBM_BoosterCalcNumPredict(void* booster, int num_row,
                                           int predict_type,
                                           int start_iteration,
                                           int num_iteration,
                                           int64_t* out_len) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Oiiii)", (PyObject*)booster, num_row,
                                 predict_type, start_iteration,
                                 num_iteration);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("booster_calc_num_predict", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  *out_len = (int64_t)PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

// predict_type: 0 normal, 1 raw_score, 2 leaf_index, 3 contrib
LGBM_EXPORT int LGBM_BoosterPredictForMat(
    void* booster, const void* data, int data_type, int32_t nrow,
    int32_t ncol, int is_row_major, int predict_type,
    int start_iteration, int num_iteration, const char* parameter,
    int64_t* out_len, double* out_result) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(OKiiiiiiisK)", (PyObject*)booster,
      (unsigned long long)(uintptr_t)data, data_type, (int)nrow, (int)ncol,
      is_row_major, predict_type, start_iteration, num_iteration,
      parameter ? parameter : "",
      (unsigned long long)(uintptr_t)out_result);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("booster_predict_for_mat", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  *out_len = (int64_t)PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterSaveModel(void* booster, int start_iteration,
                                      int num_iteration,
                                      int feature_importance_type,
                                      const char* filename) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Oiiis)", (PyObject*)booster,
                                 start_iteration, num_iteration,
                                 feature_importance_type, filename);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("booster_save_model", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterGetNumClasses(void* booster, int* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", (PyObject*)booster);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("booster_num_classes", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  *out = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterFree(void* handle) {
  Gil gil;
  Py_XDECREF((PyObject*)handle);
  return 0;
}

// ----------------------------------------------------------------------
// round-3 surface growth (ref: src/c_api.cpp:398-520 CSR/CSC/file dataset
// creation, :939-1156 FastInit single-row predicts, c_api.h:1317
// NetworkInit, GetEval family, leaf accessors)

LGBM_EXPORT int LGBM_DatasetCreateFromFile(const char* filename,
                                           const char* parameters,
                                           void* reference, void** out) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(ssO)", filename, parameters ? parameters : "",
      reference ? (PyObject*)reference : Py_None);
  if (args == nullptr) return fail_from_python();
  PyObject* h = call("dataset_create_from_file", args);
  Py_DECREF(args);
  if (h == nullptr) return fail_from_python();
  *out = (void*)h;
  return 0;
}

LGBM_EXPORT int LGBM_DatasetCreateFromCSR(
    const void* indptr, int indptr_type, const int32_t* indices,
    const void* data, int data_type, int64_t nindptr, int64_t nelem,
    int64_t num_col, const char* parameters, void* reference, void** out) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(KiKKiLLLsO)", (unsigned long long)(uintptr_t)indptr, indptr_type,
      (unsigned long long)(uintptr_t)indices,
      (unsigned long long)(uintptr_t)data, data_type,
      (long long)nindptr, (long long)nelem, (long long)num_col,
      parameters ? parameters : "",
      reference ? (PyObject*)reference : Py_None);
  if (args == nullptr) return fail_from_python();
  PyObject* h = call("dataset_create_from_csr", args);
  Py_DECREF(args);
  if (h == nullptr) return fail_from_python();
  *out = (void*)h;
  return 0;
}

LGBM_EXPORT int LGBM_DatasetCreateFromCSC(
    const void* col_ptr, int col_ptr_type, const int32_t* indices,
    const void* data, int data_type, int64_t ncol_ptr, int64_t nelem,
    int64_t num_row, const char* parameters, void* reference, void** out) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(KiKKiLLLsO)", (unsigned long long)(uintptr_t)col_ptr, col_ptr_type,
      (unsigned long long)(uintptr_t)indices,
      (unsigned long long)(uintptr_t)data, data_type,
      (long long)ncol_ptr, (long long)nelem, (long long)num_row,
      parameters ? parameters : "",
      reference ? (PyObject*)reference : Py_None);
  if (args == nullptr) return fail_from_python();
  PyObject* h = call("dataset_create_from_csc", args);
  Py_DECREF(args);
  if (h == nullptr) return fail_from_python();
  *out = (void*)h;
  return 0;
}

LGBM_EXPORT int LGBM_DatasetSaveBinary(void* handle, const char* filename) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Os)", (PyObject*)handle, filename);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("dataset_save_binary", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterGetNumFeature(void* booster, int* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", (PyObject*)booster);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("booster_num_feature", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  *out = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterPredictForFile(
    void* booster, const char* data_filename, int data_has_header,
    int predict_type, int start_iteration, int num_iteration,
    const char* parameter, const char* result_filename) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(Osiiiiss)", (PyObject*)booster, data_filename, data_has_header,
      predict_type, start_iteration, num_iteration,
      parameter ? parameter : "", result_filename);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("booster_predict_for_file", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterPredictForCSR(
    void* booster, const void* indptr, int indptr_type,
    const int32_t* indices, const void* data, int data_type,
    int64_t nindptr, int64_t nelem, int64_t num_col, int predict_type,
    int start_iteration, int num_iteration, const char* parameter,
    int64_t* out_len, double* out_result) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(OKiKKiLLLiiisK)", (PyObject*)booster,
      (unsigned long long)(uintptr_t)indptr, indptr_type,
      (unsigned long long)(uintptr_t)indices,
      (unsigned long long)(uintptr_t)data, data_type,
      (long long)nindptr, (long long)nelem, (long long)num_col,
      predict_type, start_iteration, num_iteration,
      parameter ? parameter : "",
      (unsigned long long)(uintptr_t)out_result);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("booster_predict_for_csr", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  *out_len = (int64_t)PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterGetEvalCounts(void* booster, int* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", (PyObject*)booster);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("booster_get_eval_counts", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  *out = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

// reference string-array convention: caller provides ``len`` buffers of
// ``buffer_len`` bytes; out_buffer_len reports the longest name + NUL
LGBM_EXPORT int LGBM_BoosterGetEvalNames(void* booster, const int len,
                                         int* out_len,
                                         const size_t buffer_len,
                                         size_t* out_buffer_len,
                                         char** out_strs) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", (PyObject*)booster);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("booster_get_eval_names", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  Py_ssize_t n = PyList_Size(r);
  *out_len = (int)n;
  size_t need = 1;
  for (Py_ssize_t i = 0; i < n; ++i) {
    const char* s = PyUnicode_AsUTF8(PyList_GetItem(r, i));
    size_t l = s ? strlen(s) + 1 : 1;
    if (l > need) need = l;
    if (out_strs != nullptr && i < len && s != nullptr) {
      std::snprintf(out_strs[i], buffer_len, "%s", s);
    }
  }
  *out_buffer_len = need;
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterGetEval(void* booster, int data_idx,
                                    int* out_len, double* out_results) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Oi)", (PyObject*)booster, data_idx);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("booster_get_eval", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  Py_ssize_t n = PyList_Size(r);
  *out_len = (int)n;
  for (Py_ssize_t i = 0; i < n; ++i) {
    out_results[i] = PyFloat_AsDouble(PyList_GetItem(r, i));
  }
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterGetLeafValue(void* booster, int tree_idx,
                                         int leaf_idx, double* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Oii)", (PyObject*)booster, tree_idx,
                                 leaf_idx);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("booster_get_leaf_value", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  *out = PyFloat_AsDouble(r);
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterSetLeafValue(void* booster, int tree_idx,
                                         int leaf_idx, double val) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Oiid)", (PyObject*)booster, tree_idx,
                                 leaf_idx, val);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("booster_set_leaf_value", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterRollbackOneIter(void* booster) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", (PyObject*)booster);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("booster_rollback_one_iter", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_NetworkInit(const char* machines, int local_listen_port,
                                 int listen_time_out, int num_machines) {
  Gil gil;
  PyObject* args = Py_BuildValue("(siii)", machines ? machines : "",
                                 local_listen_port, listen_time_out,
                                 num_machines);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("network_init", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_NetworkFree() {
  Gil gil;
  PyObject* args = Py_BuildValue("()");
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("network_free", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

// FastInit single-row predicts (ref: c_api.cpp:939-1156): parse/alloc
// once, then per-call predicts touch only the row buffer
LGBM_EXPORT int LGBM_BoosterPredictForMatSingleRowFastInit(
    void* booster, const int predict_type, const int start_iteration,
    const int num_iteration, const int data_type, const int32_t ncol,
    const char* parameter, void** out_fast_config) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(Oiiiiis)", (PyObject*)booster, predict_type, start_iteration,
      num_iteration, data_type, (int)ncol, parameter ? parameter : "");
  if (args == nullptr) return fail_from_python();
  PyObject* h = call("fast_config_create", args);
  Py_DECREF(args);
  if (h == nullptr) return fail_from_python();
  *out_fast_config = (void*)h;
  return 0;
}

LGBM_EXPORT int LGBM_BoosterPredictForMatSingleRowFast(
    void* fast_config, const void* data, int64_t* out_len,
    double* out_result) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(OKK)", (PyObject*)fast_config,
      (unsigned long long)(uintptr_t)data,
      (unsigned long long)(uintptr_t)out_result);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("predict_single_row_fast", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  *out_len = (int64_t)PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_FastConfigFree(void* fast_config) {
  Gil gil;
  Py_XDECREF((PyObject*)fast_config);
  return 0;
}

// ----------------------------------------------------------------------
// round-4 tranche (ref: src/c_api.cpp:430-845 — custom-gradient train,
// JSON dump, field/feature-name access, CSC predict, sparse contribs,
// streaming dataset push, booster merge)

LGBM_EXPORT int LGBM_BoosterUpdateOneIterCustom(void* booster,
                                                const float* grad,
                                                const float* hess,
                                                int* is_finished) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(OKK)", (PyObject*)booster, (unsigned long long)(uintptr_t)grad,
      (unsigned long long)(uintptr_t)hess);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("booster_update_one_iter_custom", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  *is_finished = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

// reference buffer convention: out_len = bytes needed incl. NUL; the
// string is copied only when it fits in buffer_len
LGBM_EXPORT int LGBM_BoosterDumpModel(void* booster, int start_iteration,
                                      int num_iteration,
                                      int feature_importance_type,
                                      int64_t buffer_len, int64_t* out_len,
                                      char* out_str) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Oiii)", (PyObject*)booster,
                                 start_iteration, num_iteration,
                                 feature_importance_type);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("booster_dump_model", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  Py_ssize_t n = 0;
  const char* s = PyUnicode_AsUTF8AndSize(r, &n);
  if (s == nullptr) {
    Py_DECREF(r);
    return fail_from_python();
  }
  *out_len = (int64_t)n + 1;
  if (out_str != nullptr && buffer_len >= n + 1) {
    std::memcpy(out_str, s, n + 1);
  }
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_DatasetGetField(void* handle, const char* field_name,
                                     int* out_len, const void** out_ptr,
                                     int* out_type) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Os)", (PyObject*)handle, field_name);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("dataset_get_field", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  unsigned long long ptr = 0;
  int n = 0, tc = 0;
  if (!PyArg_ParseTuple(r, "Kii", &ptr, &n, &tc)) {
    Py_DECREF(r);
    return fail_from_python();
  }
  Py_DECREF(r);
  *out_ptr = (const void*)(uintptr_t)ptr;
  *out_len = n;
  *out_type = tc;
  return 0;
}

LGBM_EXPORT int LGBM_DatasetGetFeatureNames(void* handle, const int len,
                                            int* num_feature_names,
                                            const size_t buffer_len,
                                            size_t* out_buffer_len,
                                            char** feature_names) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", (PyObject*)handle);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("dataset_get_feature_names", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  Py_ssize_t n = PyList_Size(r);
  *num_feature_names = (int)n;
  size_t need = 1;
  for (Py_ssize_t i = 0; i < n; ++i) {
    const char* s = PyUnicode_AsUTF8(PyList_GetItem(r, i));
    size_t l = s ? strlen(s) + 1 : 1;
    if (l > need) need = l;
    if (feature_names != nullptr && i < len && s != nullptr) {
      std::snprintf(feature_names[i], buffer_len, "%s", s);
    }
  }
  *out_buffer_len = need;
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_DatasetSetFeatureNames(void* handle,
                                            const char** feature_names,
                                            int num_feature_names) {
  Gil gil;
  PyObject* names = PyList_New(num_feature_names);
  if (names == nullptr) return fail_from_python();
  for (int i = 0; i < num_feature_names; ++i) {
    PyList_SetItem(names, i, PyUnicode_FromString(feature_names[i]));
  }
  PyObject* args = Py_BuildValue("(ON)", (PyObject*)handle, names);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("dataset_set_feature_names", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterPredictForCSC(
    void* booster, const void* col_ptr, int col_ptr_type,
    const int32_t* indices, const void* data, int data_type,
    int64_t ncol_ptr, int64_t nelem, int64_t num_row, int predict_type,
    int start_iteration, int num_iteration, const char* parameter,
    int64_t* out_len, double* out_result) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(OKiKKiLLLiiisK)", (PyObject*)booster,
      (unsigned long long)(uintptr_t)col_ptr, col_ptr_type,
      (unsigned long long)(uintptr_t)indices,
      (unsigned long long)(uintptr_t)data, data_type,
      (long long)ncol_ptr, (long long)nelem, (long long)num_row,
      predict_type, start_iteration, num_iteration,
      parameter ? parameter : "",
      (unsigned long long)(uintptr_t)out_result);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("booster_predict_for_csc", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  *out_len = (int64_t)PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

// Sparse-output SHAP contributions (ref: c_api.cpp:845). Only
// predict_type=3 (contrib) with matrix_type=0 (CSR) is supported; the
// returned buffers live until LGBM_BoosterFreePredictSparse.
LGBM_EXPORT int LGBM_BoosterPredictSparseOutput(
    void* booster, const void* indptr, int indptr_type,
    const int32_t* indices, const void* data, int data_type,
    int64_t nindptr, int64_t nelem, int64_t num_col_or_row,
    int predict_type, int start_iteration, int num_iteration,
    const char* parameter, int matrix_type, int64_t* out_len,
    void** out_indptr, int32_t** out_indices, void** out_data) {
  if (predict_type != 3) {
    g_last_error = "PredictSparseOutput supports predict_type=3 (contrib)";
    return -1;
  }
  if (matrix_type != 0) {
    g_last_error = "PredictSparseOutput supports matrix_type=CSR only";
    return -1;
  }
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(OKiKKiLLLii)", (PyObject*)booster,
      (unsigned long long)(uintptr_t)indptr, indptr_type,
      (unsigned long long)(uintptr_t)indices,
      (unsigned long long)(uintptr_t)data, data_type,
      (long long)nindptr, (long long)nelem, (long long)num_col_or_row,
      start_iteration, num_iteration);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("booster_predict_sparse_contribs", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  long long n_indptr = 0, nnz = 0;
  unsigned long long p_indptr = 0, p_indices = 0, p_data = 0;
  if (!PyArg_ParseTuple(r, "LLKKK", &n_indptr, &nnz, &p_indptr, &p_indices,
                        &p_data)) {
    Py_DECREF(r);
    return fail_from_python();
  }
  Py_DECREF(r);
  out_len[0] = (int64_t)n_indptr;
  out_len[1] = (int64_t)nnz;
  *out_indptr = (void*)(uintptr_t)p_indptr;
  *out_indices = (int32_t*)(uintptr_t)p_indices;
  *out_data = (void*)(uintptr_t)p_data;
  return 0;
}

LGBM_EXPORT int LGBM_BoosterFreePredictSparse(void* indptr,
                                              int32_t* indices, void* data,
                                              int indptr_type,
                                              int data_type) {
  (void)indices;
  (void)data;
  (void)indptr_type;
  (void)data_type;
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(K)", (unsigned long long)(uintptr_t)indptr);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("booster_free_predict_sparse", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_DatasetCreateByReference(void* reference,
                                              int64_t num_total_row,
                                              void** out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(OL)", (PyObject*)reference,
                                 (long long)num_total_row);
  if (args == nullptr) return fail_from_python();
  PyObject* h = call("dataset_create_by_reference", args);
  Py_DECREF(args);
  if (h == nullptr) return fail_from_python();
  *out = (void*)h;
  return 0;
}

LGBM_EXPORT int LGBM_DatasetPushRows(void* handle, const void* data,
                                     int data_type, int32_t nrow,
                                     int32_t ncol, int32_t start_row) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(OKiiii)", (PyObject*)handle,
      (unsigned long long)(uintptr_t)data, data_type, (int)nrow, (int)ncol,
      (int)start_row);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("dataset_push_rows", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_DatasetPushRowsByCSR(
    void* handle, const void* indptr, int indptr_type,
    const int32_t* indices, const void* data, int data_type,
    int64_t nindptr, int64_t nelem, int64_t num_col, int32_t start_row) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(OKiKKiLLLi)", (PyObject*)handle,
      (unsigned long long)(uintptr_t)indptr, indptr_type,
      (unsigned long long)(uintptr_t)indices,
      (unsigned long long)(uintptr_t)data, data_type, (long long)nindptr,
      (long long)nelem, (long long)num_col, (int)start_row);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("dataset_push_rows_by_csr", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterMerge(void* booster, void* other_booster) {
  Gil gil;
  PyObject* args = Py_BuildValue("(OO)", (PyObject*)booster,
                                 (PyObject*)other_booster);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("booster_merge", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

// ----------------------------------------------------------------------
// round-4 tranche 4 (booster lifecycle/string IO breadth —
// ref: include/LightGBM/c_api.h:313-1310)

LGBM_EXPORT int LGBM_BoosterSaveModelToString(void* booster,
                                              int start_iteration,
                                              int num_iteration,
                                              int feature_importance_type,
                                              int64_t buffer_len,
                                              int64_t* out_len,
                                              char* out_str) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Oiii)", (PyObject*)booster,
                                 start_iteration, num_iteration,
                                 feature_importance_type);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("booster_save_model_to_string", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  Py_ssize_t n = 0;
  const char* s = PyUnicode_AsUTF8AndSize(r, &n);
  if (s == nullptr) {
    Py_DECREF(r);
    return fail_from_python();
  }
  *out_len = (int64_t)n + 1;
  if (out_str != nullptr && buffer_len >= n + 1) {
    std::memcpy(out_str, s, n + 1);
  }
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterLoadModelFromString(const char* model_str,
                                                int* out_num_iterations,
                                                void** out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", model_str ? model_str : "");
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("booster_load_model_from_string", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  PyObject* bst = PyTuple_GetItem(r, 0);
  *out_num_iterations = (int)PyLong_AsLong(PyTuple_GetItem(r, 1));
  Py_INCREF(bst);
  *out = (void*)bst;
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterGetFeatureNames(void* booster, const int len,
                                            int* out_len,
                                            const size_t buffer_len,
                                            size_t* out_buffer_len,
                                            char** out_strs) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", (PyObject*)booster);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("booster_get_feature_names", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  Py_ssize_t n = PyList_Size(r);
  *out_len = (int)n;
  size_t need = 1;
  for (Py_ssize_t i = 0; i < n; ++i) {
    const char* s = PyUnicode_AsUTF8(PyList_GetItem(r, i));
    size_t l = s ? strlen(s) + 1 : 1;
    if (l > need) need = l;
    if (out_strs != nullptr && i < len && s != nullptr) {
      std::snprintf(out_strs[i], buffer_len, "%s", s);
    }
  }
  *out_buffer_len = need;
  Py_DECREF(r);
  return 0;
}

static int int_getter(const char* fn, void* handle, int* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", (PyObject*)handle);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call(fn, args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  *out = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterNumModelPerIteration(void* booster,
                                                 int* out_tree_per_it) {
  return int_getter("booster_num_model_per_iteration", booster,
                    out_tree_per_it);
}

LGBM_EXPORT int LGBM_BoosterNumberOfTotalModel(void* booster,
                                               int* out_models) {
  return int_getter("booster_number_of_total_model", booster, out_models);
}

static int double_getter(const char* fn, void* handle, double* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", (PyObject*)handle);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call(fn, args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  *out = PyFloat_AsDouble(r);
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterGetLowerBoundValue(void* booster,
                                               double* out_results) {
  return double_getter("booster_get_lower_bound_value", booster,
                       out_results);
}

LGBM_EXPORT int LGBM_BoosterGetUpperBoundValue(void* booster,
                                               double* out_results) {
  return double_getter("booster_get_upper_bound_value", booster,
                       out_results);
}

LGBM_EXPORT int LGBM_BoosterResetParameter(void* booster,
                                           const char* parameters) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Os)", (PyObject*)booster,
                                 parameters ? parameters : "");
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("booster_reset_parameter", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterShuffleModels(void* booster, int start_iter,
                                          int end_iter) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Oii)", (PyObject*)booster, start_iter,
                                 end_iter);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("booster_shuffle_models", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterPredictForMats(
    void* booster, const void** data, int data_type, int32_t nrow,
    int32_t ncol, int predict_type, int start_iteration, int num_iteration,
    const char* parameter, int64_t* out_len, double* out_result) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(OKiiiiiisK)", (PyObject*)booster,
      (unsigned long long)(uintptr_t)data, data_type, (int)nrow, (int)ncol,
      predict_type, start_iteration, num_iteration,
      parameter ? parameter : "",
      (unsigned long long)(uintptr_t)out_result);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("booster_predict_for_mats", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  *out_len = (int64_t)PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_DatasetGetSubset(const void* handle,
                                      const int32_t* used_row_indices,
                                      int32_t num_used_row_indices,
                                      const char* parameters, void** out) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(OKis)", (PyObject*)handle,
      (unsigned long long)(uintptr_t)used_row_indices,
      (int)num_used_row_indices, parameters ? parameters : "");
  if (args == nullptr) return fail_from_python();
  PyObject* h = call("dataset_get_subset", args);
  Py_DECREF(args);
  if (h == nullptr) return fail_from_python();
  *out = (void*)h;
  return 0;
}

LGBM_EXPORT int LGBM_DatasetUpdateParamChecking(const char* old_parameters,
                                                const char* new_parameters) {
  Gil gil;
  PyObject* args = Py_BuildValue("(ss)",
                                 old_parameters ? old_parameters : "",
                                 new_parameters ? new_parameters : "");
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("dataset_update_param_checking", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

// ----------------------------------------------------------------------
// round-5 tranche: the final 20 symbols to full c_api.h parity
// (ref: include/LightGBM/c_api.h — booster lifecycle Refit/Reset/
// FeatureImportance/GetPredict, sampling helpers, multi-mat and
// sampled-column dataset creation, single-row CSR fast paths, log and
// network injection hooks)

namespace {
// shared two-call string-buffer protocol (out_len = bytes incl. NUL,
// copy only when it fits)
int string_result_to_buffer(PyObject* r, int64_t buffer_len,
                            int64_t* out_len, char* out_str) {
  Py_ssize_t n = 0;
  const char* s = PyUnicode_AsUTF8AndSize(r, &n);
  if (s == nullptr) {
    Py_DECREF(r);
    return fail_from_python();
  }
  *out_len = (int64_t)n + 1;
  if (out_str != nullptr && buffer_len >= n + 1) {
    std::memcpy(out_str, s, n + 1);
  }
  Py_DECREF(r);
  return 0;
}
}  // namespace

LGBM_EXPORT int LGBM_DumpParamAliases(int64_t buffer_len, int64_t* out_len,
                                      char* out_str) {
  Gil gil;
  PyObject* args = Py_BuildValue("()");
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("dump_param_aliases", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  return string_result_to_buffer(r, buffer_len, out_len, out_str);
}

LGBM_EXPORT int LGBM_RegisterLogCallback(void (*callback)(const char*)) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(K)", (unsigned long long)(uintptr_t)callback);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("register_log_callback", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_GetSampleCount(int32_t num_total_row,
                                    const char* parameters, int* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(is)", (int)num_total_row,
                                 parameters ? parameters : "");
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("get_sample_count", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  *out = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_SampleIndices(int32_t num_total_row,
                                   const char* parameters, void* out,
                                   int32_t* out_len) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(isK)", (int)num_total_row, parameters ? parameters : "",
      (unsigned long long)(uintptr_t)out);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("sample_indices", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  *out_len = (int32_t)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_DatasetCreateFromSampledColumn(
    double** sample_data, int** sample_indices, int32_t ncol,
    const int* num_per_col, int32_t num_sample_row, int32_t num_total_row,
    const char* parameters, void** out) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(KKiKiis)", (unsigned long long)(uintptr_t)sample_data,
      (unsigned long long)(uintptr_t)sample_indices, (int)ncol,
      (unsigned long long)(uintptr_t)num_per_col, (int)num_sample_row,
      (int)num_total_row, parameters ? parameters : "");
  if (args == nullptr) return fail_from_python();
  PyObject* h = call("dataset_create_from_sampled_column", args);
  Py_DECREF(args);
  if (h == nullptr) return fail_from_python();
  *out = (void*)h;
  return 0;
}

LGBM_EXPORT int LGBM_DatasetCreateFromMats(int32_t nmat, const void** data,
                                           int data_type, int32_t* nrow,
                                           int32_t ncol, int is_row_major,
                                           const char* parameters,
                                           const void* reference,
                                           void** out) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(iKiKiisO)", (int)nmat, (unsigned long long)(uintptr_t)data,
      data_type, (unsigned long long)(uintptr_t)nrow, (int)ncol,
      is_row_major, parameters ? parameters : "",
      reference ? (PyObject*)reference : Py_None);
  if (args == nullptr) return fail_from_python();
  PyObject* h = call("dataset_create_from_mats", args);
  Py_DECREF(args);
  if (h == nullptr) return fail_from_python();
  *out = (void*)h;
  return 0;
}

// the get-row functor convention is a C++ std::function pointer (ref:
// c_api.cpp LGBM_DatasetCreateFromCSRFunc); rows are materialized here
// and handed to the normal CSR constructor
LGBM_EXPORT int LGBM_DatasetCreateFromCSRFunc(void* get_row_funptr,
                                              int num_rows, int64_t num_col,
                                              const char* parameters,
                                              const void* reference,
                                              void** out) {
  if (get_row_funptr == nullptr) {
    g_last_error = "get_row_funptr is null";
    return -1;
  }
  typedef std::function<void(int idx,
                             std::vector<std::pair<int, double>>&)> RowFn;
  auto& get_row = *static_cast<RowFn*>(get_row_funptr);
  std::vector<int32_t> indptr{0};
  std::vector<int32_t> indices;
  std::vector<double> values;
  std::vector<std::pair<int, double>> row;
  for (int i = 0; i < num_rows; ++i) {
    row.clear();
    get_row(i, row);
    for (const auto& kv : row) {
      indices.push_back(kv.first);
      values.push_back(kv.second);
    }
    indptr.push_back(static_cast<int32_t>(indices.size()));
  }
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(KiKKiKKisO)", (unsigned long long)(uintptr_t)indptr.data(),
      2 /* int32 */, (unsigned long long)(uintptr_t)indices.data(),
      (unsigned long long)(uintptr_t)values.data(), 1 /* float64 */,
      (unsigned long long)(uintptr_t)indptr.size(),
      (unsigned long long)(uintptr_t)values.size(), (int)num_col,
      parameters ? parameters : "",
      reference ? (PyObject*)reference : Py_None);
  if (args == nullptr) return fail_from_python();
  PyObject* h = call("dataset_create_from_csr", args);
  Py_DECREF(args);
  if (h == nullptr) return fail_from_python();
  *out = (void*)h;
  return 0;
}

LGBM_EXPORT int LGBM_DatasetAddFeaturesFrom(void* target, void* source) {
  Gil gil;
  PyObject* args = Py_BuildValue("(OO)", (PyObject*)target,
                                 (PyObject*)source);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("dataset_add_features_from", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_DatasetDumpText(void* handle, const char* filename) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Os)", (PyObject*)handle, filename);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("dataset_dump_text", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterGetLinear(void* booster, int* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", (PyObject*)booster);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("booster_get_linear", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  *out = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterResetTrainingData(void* booster,
                                              const void* train_data) {
  Gil gil;
  PyObject* args = Py_BuildValue("(OO)", (PyObject*)booster,
                                 (PyObject*)train_data);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("booster_reset_training_data", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterRefit(void* booster, const int32_t* leaf_preds,
                                  int32_t nrow, int32_t ncol) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(OKii)", (PyObject*)booster,
      (unsigned long long)(uintptr_t)leaf_preds, (int)nrow, (int)ncol);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("booster_refit", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterFeatureImportance(void* booster,
                                              int num_iteration,
                                              int importance_type,
                                              double* out_results) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(OiiK)", (PyObject*)booster, num_iteration, importance_type,
      (unsigned long long)(uintptr_t)out_results);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("booster_feature_importance", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterGetNumPredict(void* booster, int data_idx,
                                          int64_t* out_len) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Oi)", (PyObject*)booster, data_idx);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("booster_get_num_predict", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  *out_len = (int64_t)PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterGetPredict(void* booster, int data_idx,
                                       int64_t* out_len,
                                       double* out_result) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(OiK)", (PyObject*)booster, data_idx,
      (unsigned long long)(uintptr_t)out_result);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("booster_get_predict", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  *out_len = (int64_t)PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterPredictForMatSingleRow(
    void* booster, const void* data, int data_type, int ncol,
    int is_row_major, int predict_type, int start_iteration,
    int num_iteration, const char* parameter, int64_t* out_len,
    double* out_result) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(OKiiiiiiisK)", (PyObject*)booster,
      (unsigned long long)(uintptr_t)data, data_type, 1 /* nrow */, ncol,
      is_row_major, predict_type, start_iteration, num_iteration,
      parameter ? parameter : "",
      (unsigned long long)(uintptr_t)out_result);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("booster_predict_for_mat", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  *out_len = (int64_t)PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterPredictForCSRSingleRow(
    void* booster, const void* indptr, int indptr_type,
    const int32_t* indices, const void* data, int data_type,
    int64_t nindptr, int64_t nelem, int64_t num_col, int predict_type,
    int start_iteration, int num_iteration, const char* parameter,
    int64_t* out_len, double* out_result) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(OKiKKiKKKiiisK)", (PyObject*)booster,
      (unsigned long long)(uintptr_t)indptr, indptr_type,
      (unsigned long long)(uintptr_t)indices,
      (unsigned long long)(uintptr_t)data, data_type,
      (unsigned long long)nindptr, (unsigned long long)nelem,
      (unsigned long long)num_col, predict_type, start_iteration,
      num_iteration, parameter ? parameter : "",
      (unsigned long long)(uintptr_t)out_result);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("booster_predict_for_csr_single_row", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  *out_len = (int64_t)PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterPredictForCSRSingleRowFastInit(
    void* booster, const int predict_type, const int start_iteration,
    const int num_iteration, const int data_type, const int64_t num_col,
    const char* parameter, void** out_fast_config) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(OiiiiKs)", (PyObject*)booster, predict_type, start_iteration,
      num_iteration, data_type, (unsigned long long)num_col,
      parameter ? parameter : "");
  if (args == nullptr) return fail_from_python();
  PyObject* h = call("fast_config_create_csr", args);
  Py_DECREF(args);
  if (h == nullptr) return fail_from_python();
  *out_fast_config = (void*)h;
  return 0;
}

LGBM_EXPORT int LGBM_BoosterPredictForCSRSingleRowFast(
    void* fast_config, const void* indptr, const int indptr_type,
    const int32_t* indices, const void* data, const int64_t nindptr,
    const int64_t nelem, int64_t* out_len, double* out_result) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(OKiKKKKK)", (PyObject*)fast_config,
      (unsigned long long)(uintptr_t)indptr, indptr_type,
      (unsigned long long)(uintptr_t)indices,
      (unsigned long long)(uintptr_t)data, (unsigned long long)nindptr,
      (unsigned long long)nelem,
      (unsigned long long)(uintptr_t)out_result);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("predict_single_row_fast_csr", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  *out_len = (int64_t)PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_EXPORT int LGBM_NetworkInitWithFunctions(int num_machines, int rank,
                                              void* reduce_scatter_ext_fun,
                                              void* allgather_ext_fun) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(iiKK)", num_machines, rank,
      (unsigned long long)(uintptr_t)reduce_scatter_ext_fun,
      (unsigned long long)(uintptr_t)allgather_ext_fun);
  if (args == nullptr) return fail_from_python();
  PyObject* r = call("network_init_with_functions", args);
  Py_DECREF(args);
  if (r == nullptr) return fail_from_python();
  Py_DECREF(r);
  return 0;
}
