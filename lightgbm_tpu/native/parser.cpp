// Fast text parsers for dataset ingestion: CSV/TSV and LibSVM.
//
// Native analog of the reference's parser layer (ref: src/io/parser.cpp
// CSVParser/TSVParser/LibSVMParser + utils/text_reader.h chunked reads) —
// an original implementation exposed through a minimal C ABI consumed via
// ctypes (no pybind11 in this image).
//
// Contract (all functions return 0 on success, negative on error):
//   lgbt_scan(path, &sep, &n_rows, &n_cols, &is_libsvm, &has_header)
//       one streaming pass: sniffs the separator (',', '\t', ' '),
//       LibSVM-ness ("idx:val" tokens), a non-numeric header line, and
//       counts rows and columns (LibSVM: max feature index + 1).
//   lgbt_parse_dense(path, sep, skip_header, out, n_rows, n_cols)
//       fills a row-major float32 [n_rows, n_cols] buffer; empty fields
//       and "na"/"nan"/"null" become NaN.
//   lgbt_parse_libsvm(path, out, label_out, n_rows, n_cols)
//       fills zeros + sparse values; column 0 of the file is the label.
//   lgbt_parse_dense_range / lgbt_parse_libsvm_range
//       chunked resumable variants (ref: utils/text_reader.h
//       ReadPartAndParse): parse up to max_rows data rows starting at a
//       byte offset, reporting rows parsed and the offset after the last
//       consumed line, so a caller can stream a file in bounded chunks
//       through EXACTLY the same field parser as the monolithic entry
//       points (bit-identical values by construction).
//
// Build: g++ -O3 -shared -fPIC parser.cpp -o libparser.so   (see loader.py)

#include <cctype>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

// Buffered line reader (64 KB chunks, handles \r\n and missing trailing \n).
// Tracks the byte offset of the NEXT unconsumed character so the chunked
// range parsers can resume exactly where a previous call stopped.
class LineReader {
 public:
  explicit LineReader(FILE* f, int64_t base = 0)
      : f_(f), pos_(0), len_(0), eof_(false), base_(base) {}

  bool next(std::string* line) {
    line->clear();
    for (;;) {
      if (pos_ >= len_) {
        if (eof_) return !line->empty();
        base_ += static_cast<int64_t>(len_);
        len_ = fread(buf_, 1, sizeof(buf_), f_);
        pos_ = 0;
        if (len_ == 0) {
          eof_ = true;
          return !line->empty();
        }
      }
      char* nl = static_cast<char*>(
          memchr(buf_ + pos_, '\n', len_ - pos_));
      if (nl == nullptr) {
        line->append(buf_ + pos_, len_ - pos_);
        pos_ = len_;
        continue;
      }
      size_t n = nl - (buf_ + pos_);
      line->append(buf_ + pos_, n);
      pos_ += n + 1;
      if (!line->empty() && line->back() == '\r') line->pop_back();
      return true;
    }
  }

  int64_t offset() const { return base_ + static_cast<int64_t>(pos_); }

 private:
  FILE* f_;
  char buf_[1 << 16];
  size_t pos_, len_;
  bool eof_;
  int64_t base_;
};

inline const char* skip_ws(const char* p) {
  while (*p == ' ' || *p == '\t') ++p;
  return p;
}

inline bool parse_float(const char* s, const char* end, float* out) {
  while (s < end && (*s == ' ')) ++s;
  if (s >= end) {
    *out = NAN;
    return true;
  }
  // common missing markers
  size_t n = end - s;
  if ((n == 2 && strncasecmp(s, "na", 2) == 0) ||
      (n == 3 && strncasecmp(s, "nan", 3) == 0) ||
      (n == 4 && (strncasecmp(s, "null", 4) == 0 ||
                  strncasecmp(s, "none", 4) == 0))) {
    *out = NAN;
    return true;
  }
  char* e = nullptr;
  std::string tmp(s, end);  // strtod needs NUL termination
  double v = strtod(tmp.c_str(), &e);
  if (e == tmp.c_str()) return false;
  *out = static_cast<float>(v);
  return true;
}

bool looks_numeric(const char* s, const char* end) {
  float v;
  return parse_float(s, end, &v);
}

int count_fields(const std::string& line, char sep) {
  int n = 1;
  for (char c : line)
    if (c == sep) ++n;
  return n;
}

bool is_libsvm_token(const char* s, const char* end) {
  const char* colon = static_cast<const char*>(memchr(s, ':', end - s));
  if (colon == nullptr || colon == s) return false;
  for (const char* p = s; p < colon; ++p)
    if (!isdigit(static_cast<unsigned char>(*p))) return false;
  return true;
}

// The ONE dense row parser: the monolithic and range entry points both
// route here, so chunked ingest cannot drift from whole-file parsing.
inline void parse_dense_line(const std::string& line, char sep,
                             float* dst, int64_t n_cols) {
  const char* q = line.c_str();
  const char* endl = q + line.size();
  int64_t col = 0;
  while (q <= endl && col < n_cols) {
    const char* e = static_cast<const char*>(memchr(q, sep, endl - q));
    if (e == nullptr) e = endl;
    if (!parse_float(q, e, &dst[col])) dst[col] = NAN;
    ++col;
    q = e + 1;
  }
  for (; col < n_cols; ++col) dst[col] = NAN;  // ragged line
}

// The ONE LibSVM row parser (dst must be pre-zeroed).
inline void parse_libsvm_line(const std::string& line, float* dst,
                              int64_t n_feat, float* label_out) {
  const char* q = skip_ws(line.c_str());
  const char* endl = line.c_str() + line.size();
  const char* e = q;
  while (e < endl && *e != ' ' && *e != '\t') ++e;
  float lab = 0.0f;
  parse_float(q, e, &lab);
  *label_out = lab;
  q = skip_ws(e);
  while (q < endl) {
    const char* colon = static_cast<const char*>(
        memchr(q, ':', endl - q));
    if (colon == nullptr) break;
    const char* ve = colon + 1;
    while (ve < endl && *ve != ' ' && *ve != '\t') ++ve;
    int64_t idx = strtoll(std::string(q, colon).c_str(), nullptr, 10);
    float v = 0.0f;
    parse_float(colon + 1, ve, &v);
    if (idx >= 0 && idx < n_feat) dst[idx] = v;
    q = skip_ws(ve);
  }
}

}  // namespace

extern "C" {

int lgbt_scan(const char* path, char* sep_out, int64_t* n_rows,
              int64_t* n_cols, int* is_libsvm, int* has_header) {
  FILE* f = fopen(path, "rb");
  if (f == nullptr) return -1;
  LineReader r(f);
  std::string line;
  int64_t rows = 0;
  int64_t maxcol = 0;
  char sep = ',';
  int libsvm = 0;
  int header = 0;
  bool first = true;
  while (r.next(&line)) {
    if (line.empty() || line[0] == '#') continue;
    if (first) {
      // separator sniff: prefer tab, then comma, then space
      int nt = count_fields(line, '\t');
      int nc = count_fields(line, ',');
      if (nt > 1) sep = '\t';
      else if (nc > 1) sep = ',';
      else sep = ' ';
      // LibSVM sniff: second whitespace token shaped like idx:val
      const char* p = skip_ws(line.c_str());
      const char* sp = p;
      while (*sp && *sp != ' ' && *sp != '\t') ++sp;
      const char* tok2 = skip_ws(sp);
      const char* tok2e = tok2;
      while (*tok2e && *tok2e != ' ' && *tok2e != '\t') ++tok2e;
      if (tok2 < tok2e && is_libsvm_token(tok2, tok2e)) {
        libsvm = 1;
        sep = ' ';
      }
      if (!libsvm) {
        // header sniff: any non-numeric field in the first line
        const char* q = line.c_str();
        const char* endl = q + line.size();
        while (q <= endl) {
          const char* e = static_cast<const char*>(
              memchr(q, sep, endl - q));
          if (e == nullptr) e = endl;
          if (q < e && !looks_numeric(q, e)) {
            header = 1;
            break;
          }
          q = e + 1;
        }
      }
      first = false;
      if (header) continue;  // header line is not a data row
    }
    ++rows;
    if (libsvm) {
      const char* q = line.c_str();
      const char* endl = q + line.size();
      while (q < endl) {
        const char* colon = static_cast<const char*>(
            memchr(q, ':', endl - q));
        if (colon == nullptr) break;
        // walk back to the token start
        const char* ts = colon;
        while (ts > q && isdigit(static_cast<unsigned char>(ts[-1]))) --ts;
        if (ts < colon) {
          int64_t idx = strtoll(std::string(ts, colon).c_str(), nullptr,
                                10);
          if (idx + 1 > maxcol) maxcol = idx + 1;
        }
        q = colon + 1;
      }
    } else {
      int nf = count_fields(line, sep);
      if (nf > maxcol) maxcol = nf;
    }
  }
  fclose(f);
  *sep_out = sep;
  *n_rows = rows;
  *n_cols = libsvm ? maxcol + 1 : maxcol;  // +1: label column 0
  *is_libsvm = libsvm;
  *has_header = header;
  return 0;
}

int lgbt_parse_dense(const char* path, char sep, int skip_header,
                     float* out, int64_t n_rows, int64_t n_cols) {
  FILE* f = fopen(path, "rb");
  if (f == nullptr) return -1;
  LineReader r(f);
  std::string line;
  int64_t row = 0;
  bool first = true;
  while (r.next(&line) && row < n_rows) {
    if (line.empty() || line[0] == '#') continue;
    if (first && skip_header) {
      first = false;
      continue;
    }
    first = false;
    parse_dense_line(line, sep, out + row * n_cols, n_cols);
    ++row;
  }
  fclose(f);
  return row == n_rows ? 0 : -2;
}

int lgbt_parse_dense_range(const char* path, char sep, int skip_header,
                           int64_t offset, float* out, int64_t max_rows,
                           int64_t n_cols, int64_t* rows_out,
                           int64_t* next_offset) {
  FILE* f = fopen(path, "rb");
  if (f == nullptr) return -1;
  if (offset > 0 && fseeko(f, offset, SEEK_SET) != 0) {
    fclose(f);
    return -1;
  }
  LineReader r(f, offset);
  std::string line;
  int64_t row = 0;
  int64_t consumed = offset;
  bool first = (offset == 0);  // the header can only sit at the file head
  while (row < max_rows && r.next(&line)) {
    if (line.empty() || line[0] == '#') {
      consumed = r.offset();
      continue;
    }
    if (first && skip_header) {
      first = false;
      consumed = r.offset();
      continue;
    }
    first = false;
    parse_dense_line(line, sep, out + row * n_cols, n_cols);
    ++row;
    consumed = r.offset();
  }
  fclose(f);
  *rows_out = row;
  *next_offset = consumed;
  return 0;
}

int lgbt_parse_libsvm(const char* path, float* out, float* label_out,
                      int64_t n_rows, int64_t n_feat) {
  FILE* f = fopen(path, "rb");
  if (f == nullptr) return -1;
  LineReader r(f);
  std::string line;
  int64_t row = 0;
  memset(out, 0, sizeof(float) * n_rows * n_feat);
  while (r.next(&line) && row < n_rows) {
    if (line.empty() || line[0] == '#') continue;
    parse_libsvm_line(line, out + row * n_feat, n_feat, &label_out[row]);
    ++row;
  }
  fclose(f);
  return row == n_rows ? 0 : -2;
}

int lgbt_parse_libsvm_range(const char* path, int64_t offset, float* out,
                            float* label_out, int64_t max_rows,
                            int64_t n_feat, int64_t* rows_out,
                            int64_t* next_offset) {
  FILE* f = fopen(path, "rb");
  if (f == nullptr) return -1;
  if (offset > 0 && fseeko(f, offset, SEEK_SET) != 0) {
    fclose(f);
    return -1;
  }
  LineReader r(f, offset);
  std::string line;
  int64_t row = 0;
  int64_t consumed = offset;
  memset(out, 0, sizeof(float) * max_rows * n_feat);
  while (row < max_rows && r.next(&line)) {
    if (line.empty() || line[0] == '#') {
      consumed = r.offset();
      continue;
    }
    parse_libsvm_line(line, out + row * n_feat, n_feat, &label_out[row]);
    ++row;
    consumed = r.offset();
  }
  fclose(f);
  *rows_out = row;
  *next_offset = consumed;
  return 0;
}

}  // extern "C"
