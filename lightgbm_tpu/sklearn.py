"""scikit-learn estimator API.

TPU-native analog of the reference sklearn wrappers (ref:
python-package/lightgbm/sklearn.py:358 LGBMModel, :939 LGBMRegressor,
:985 LGBMClassifier, :1120 LGBMRanker) — an original implementation of the
same public surface over this package's ``train()``/``Booster``.

Supported: fit/predict(_proba), eval_set + eval_metric + early stopping
via callbacks, sample/eval weights, init_score, categorical_feature,
sklearn-signature custom objectives and metrics, label encoding and
class_weight for classification, group/eval_at for ranking, get_params/
set_params/clone compatibility, feature_importances_.
"""
from __future__ import annotations

import copy
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from . import callback as _callback
from .basic import Booster, Dataset
from .engine import train as _train
from .utils import log

try:  # sklearn is optional at import time, required to actually fit
    from sklearn.base import BaseEstimator as _SKBase

    class _Base(_SKBase):
        pass
    _HAS_SKLEARN = True
except Exception:  # pragma: no cover
    class _Base:  # minimal stand-in so the module imports without sklearn
        def get_params(self, deep=True):
            out = {}
            import inspect
            for name in inspect.signature(
                    type(self).__init__).parameters:
                if name not in ("self", "kwargs"):
                    out[name] = getattr(self, name, None)
            out.update(getattr(self, "_other_params", {}))
            return out

        def set_params(self, **params):
            for k, v in params.items():
                setattr(self, k, v)
            return self
    _HAS_SKLEARN = False


def _n_positional_args(func: Callable) -> int:
    import inspect
    try:
        sig = inspect.signature(func)
    except (TypeError, ValueError):
        return 2
    return sum(1 for p in sig.parameters.values()
               if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD))


def _objective_adapter(func: Callable):
    """Wrap an sklearn-signature objective ``func(y_true, y_pred[, weight])
    -> (grad, hess)`` into the engine's ``fobj(preds, dataset)``
    (ref: sklearn.py:45 _ObjectiveFunctionWrapper). Arity is inspected
    once — never probed by catching TypeError, which would swallow user
    bugs and double-invoke side-effecting objectives."""
    argc = _n_positional_args(func)

    def fobj(preds, dataset):
        label = dataset.get_label()
        if argc >= 3:
            return func(label, preds, dataset.get_weight())
        return func(label, preds)
    return fobj


def _metric_adapter(func: Callable):
    """Wrap ``func(y_true, y_pred[, weight]) -> (name, value, is_higher
    _better)`` into ``feval(preds, dataset)``
    (ref: sklearn.py:134 _EvalFunctionWrapper)."""
    argc = _n_positional_args(func)

    def feval(preds, dataset):
        label = dataset.get_label()
        if argc >= 3:
            return func(label, preds, dataset.get_weight())
        return func(label, preds)
    return feval


class LGBMModel(_Base):
    """Base estimator (ref: sklearn.py:358)."""

    def __init__(self, boosting_type: str = "gbdt", num_leaves: int = 31,
                 max_depth: int = -1, learning_rate: float = 0.1,
                 n_estimators: int = 100, subsample_for_bin: int = 200000,
                 objective: Optional[Any] = None,
                 class_weight: Optional[Any] = None,
                 min_split_gain: float = 0.0, min_child_weight: float = 1e-3,
                 min_child_samples: int = 20, subsample: float = 1.0,
                 subsample_freq: int = 0, colsample_bytree: float = 1.0,
                 reg_alpha: float = 0.0, reg_lambda: float = 0.0,
                 random_state: Optional[int] = None, n_jobs: int = -1,
                 importance_type: str = "split", **kwargs):
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.subsample_for_bin = subsample_for_bin
        self.objective = objective
        self.class_weight = class_weight
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.importance_type = importance_type
        self._other_params: Dict[str, Any] = dict(kwargs)
        for k, v in kwargs.items():
            setattr(self, k, v)
        self._Booster: Optional[Booster] = None
        self._evals_result: Dict = {}
        self._best_score: Dict = {}
        self._best_iteration: Optional[int] = None
        self._objective = objective
        self._class_weight = class_weight
        self._n_features: Optional[int] = None
        self.fitted_ = False

    # ------------------------------------------------------------------
    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        params = super().get_params(deep=deep)
        params.update(self._other_params)
        return params

    def set_params(self, **params):
        for key, value in params.items():
            setattr(self, key, value)
            if hasattr(self, "_other_params") and key not in \
                    self._sk_constructor_args():
                self._other_params[key] = value
        return self

    @classmethod
    def _sk_constructor_args(cls):
        import inspect
        return [n for n in inspect.signature(LGBMModel.__init__).parameters
                if n not in ("self", "kwargs")]

    # ------------------------------------------------------------------
    def _process_params(self, default_objective: str) -> Dict[str, Any]:
        params = self.get_params()
        params.pop("importance_type", None)
        params.pop("n_estimators", None)
        params.pop("class_weight", None)
        # sklearn-name -> LightGBM-name translation (aliases understood by
        # the Config registry; ref sklearn.py fit parameter mapping)
        rename = {
            "boosting_type": "boosting",
            "min_split_gain": "min_gain_to_split",
            "min_child_weight": "min_sum_hessian_in_leaf",
            "min_child_samples": "min_data_in_leaf",
            "subsample": "bagging_fraction",
            "subsample_freq": "bagging_freq",
            "colsample_bytree": "feature_fraction",
            "reg_alpha": "lambda_l1",
            "reg_lambda": "lambda_l2",
            "subsample_for_bin": "bin_construct_sample_cnt",
            "random_state": "seed",
        }
        for a, b in rename.items():
            if a in params:
                v = params.pop(a)
                if v is not None:
                    params[b] = v
        params.pop("n_jobs", None)
        if callable(self.objective):
            self._objective = self.objective
            params["objective"] = "none"
        elif self.objective is None:
            params["objective"] = default_objective
        else:
            params["objective"] = self.objective
        if params.get("seed") is None:
            params.pop("seed", None)
        params.setdefault("verbose", -1)
        return params

    # ------------------------------------------------------------------
    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_init_score=None, eval_group=None, eval_metric=None,
            feature_name="auto", categorical_feature="auto",
            callbacks=None, init_model=None):
        """(ref: sklearn.py:700 LGBMModel.fit)"""
        params = self._process_params(
            default_objective=self._default_objective())
        n_rounds = int(self.n_estimators)

        X_raw, y_raw = X, y
        X = np.asarray(X, dtype=np.float32)
        self._n_features = X.shape[1]
        y_tr = self._prepare_labels(np.asarray(y))
        w = self._combine_weights(np.asarray(y), sample_weight)

        fobj = None
        if callable(self._objective):
            fobj = _objective_adapter(self._objective)
        feval = None
        if eval_metric is not None:
            if callable(eval_metric):
                feval = _metric_adapter(eval_metric)
            else:
                params["metric"] = eval_metric

        train_set = Dataset(X, label=y_tr, weight=w, group=group,
                            init_score=init_score,
                            params=dict(params),
                            feature_name=feature_name,
                            categorical_feature=categorical_feature)
        valid_sets: List[Dataset] = []
        names: List[str] = []
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            for i, (vx, vy) in enumerate(eval_set):
                vw = (eval_sample_weight[i]
                      if eval_sample_weight is not None else None)
                vis = (eval_init_score[i]
                       if eval_init_score is not None else None)
                vg = eval_group[i] if eval_group is not None else None
                vy_t = self._prepare_labels(np.asarray(vy))
                vw_t = self._combine_weights(np.asarray(vy), vw)
                # the training set itself is recognized by identity only
                # (ref sklearn.py fit: valid_x is X and valid_y is y)
                if vx is X_raw and vy is y_raw:
                    valid_sets.append(train_set)
                else:
                    valid_sets.append(train_set.create_valid(
                        np.asarray(vx, np.float32), label=vy_t, weight=vw_t,
                        group=vg, init_score=vis))
                names.append(eval_names[i] if eval_names else f"valid_{i}")

        callbacks = list(callbacks) if callbacks else []
        self._evals_result = {}
        if valid_sets:
            callbacks.append(
                _callback.record_evaluation(self._evals_result))

        booster = _train(
            params, train_set, num_boost_round=n_rounds,
            valid_sets=valid_sets or None, valid_names=names or None,
            fobj=fobj, feval=feval, init_model=init_model,
            callbacks=callbacks)
        self._Booster = booster
        self._best_iteration = booster.best_iteration
        self._best_score = booster.best_score
        self.fitted_ = True
        return self

    # hooks specialized by subclasses -----------------------------------
    def _default_objective(self) -> str:
        return "regression"

    def _prepare_labels(self, y: np.ndarray) -> np.ndarray:
        return y.astype(np.float32)

    def _combine_weights(self, y: np.ndarray, sample_weight):
        return (None if sample_weight is None
                else np.asarray(sample_weight, np.float32))

    # ------------------------------------------------------------------
    def predict(self, X, raw_score: bool = False, start_iteration: int = 0,
                num_iteration: Optional[int] = None, pred_leaf: bool = False,
                pred_contrib: bool = False, **kwargs):
        self._check_fitted()
        X = np.asarray(X, dtype=np.float32)
        if self._n_features is not None and X.shape[1] != self._n_features:
            raise ValueError(
                f"X has {X.shape[1]} features, expected {self._n_features}")
        return self._Booster.predict(
            X, raw_score=raw_score, start_iteration=start_iteration,
            num_iteration=num_iteration, pred_leaf=pred_leaf,
            pred_contrib=pred_contrib)

    def _check_fitted(self):
        if self._Booster is None:
            raise ValueError(
                "Estimator not fitted, call fit before exploiting the model.")

    # ------------------------------------------------------------------
    @property
    def booster_(self) -> Booster:
        self._check_fitted()
        return self._Booster

    @property
    def evals_result_(self) -> Dict:
        self._check_fitted()
        return self._evals_result

    @property
    def best_iteration_(self):
        self._check_fitted()
        return self._best_iteration

    @property
    def best_score_(self):
        self._check_fitted()
        return self._best_score

    @property
    def n_features_(self) -> int:
        self._check_fitted()
        return self._n_features

    @property
    def n_features_in_(self) -> int:
        return self.n_features_

    @property
    def objective_(self):
        self._check_fitted()
        return (self.objective if self.objective is not None
                else self._default_objective())

    @property
    def feature_importances_(self) -> np.ndarray:
        self._check_fitted()
        return self._Booster.feature_importance(
            importance_type=self.importance_type)

    @property
    def feature_name_(self):
        self._check_fitted()
        return self._Booster.feature_name()


class LGBMRegressor(LGBMModel):
    """(ref: sklearn.py:939)"""

    def _default_objective(self) -> str:
        return "regression"

    def fit(self, X, y, sample_weight=None, init_score=None, eval_set=None,
            eval_names=None, eval_sample_weight=None, eval_init_score=None,
            eval_metric=None, feature_name="auto",
            categorical_feature="auto", callbacks=None, init_model=None):
        return super().fit(
            X, y, sample_weight=sample_weight, init_score=init_score,
            eval_set=eval_set, eval_names=eval_names,
            eval_sample_weight=eval_sample_weight,
            eval_init_score=eval_init_score, eval_metric=eval_metric,
            feature_name=feature_name,
            categorical_feature=categorical_feature, callbacks=callbacks,
            init_model=init_model)


class LGBMClassifier(LGBMModel):
    """(ref: sklearn.py:985)"""

    def _default_objective(self) -> str:
        return "binary" if getattr(self, "_n_classes", 2) <= 2 \
            else "multiclass"

    def fit(self, X, y, sample_weight=None, init_score=None, eval_set=None,
            eval_names=None, eval_sample_weight=None, eval_init_score=None,
            eval_metric=None, feature_name="auto",
            categorical_feature="auto", callbacks=None, init_model=None):
        y_arr = np.asarray(y)
        self._classes = np.unique(y_arr)
        self._n_classes = len(self._classes)
        self._label_map = {c: i for i, c in enumerate(self._classes)}
        if self._n_classes > 2:
            self._other_params["num_class"] = self._n_classes
            setattr(self, "num_class", self._n_classes)
        else:
            # a previous multiclass fit must not leak its num_class
            self._other_params.pop("num_class", None)
            if getattr(self, "num_class", None) is not None:
                self.num_class = None
        return super().fit(
            X, y, sample_weight=sample_weight, init_score=init_score,
            eval_set=eval_set, eval_names=eval_names,
            eval_sample_weight=eval_sample_weight,
            eval_init_score=eval_init_score, eval_metric=eval_metric,
            feature_name=feature_name,
            categorical_feature=categorical_feature, callbacks=callbacks,
            init_model=init_model)

    def _prepare_labels(self, y: np.ndarray) -> np.ndarray:
        return np.asarray([self._label_map[v] for v in y], np.float32)

    def _combine_weights(self, y: np.ndarray, sample_weight):
        w = (np.ones(len(y), np.float32) if sample_weight is None
             else np.asarray(sample_weight, np.float32).copy())
        if self.class_weight is None:
            return None if sample_weight is None else w
        if self.class_weight == "balanced":
            counts = np.array([np.sum(y == c) for c in self._classes],
                              np.float64)
            cw = len(y) / (self._n_classes * np.maximum(counts, 1))
            weights = {c: cw[i] for i, c in enumerate(self._classes)}
        else:
            weights = dict(self.class_weight)
        for c, cwv in weights.items():
            w[y == c] *= cwv
        return w

    def predict(self, X, raw_score: bool = False, start_iteration: int = 0,
                num_iteration: Optional[int] = None, pred_leaf: bool = False,
                pred_contrib: bool = False, **kwargs):
        result = self.predict_proba(
            X, raw_score=raw_score, start_iteration=start_iteration,
            num_iteration=num_iteration, pred_leaf=pred_leaf,
            pred_contrib=pred_contrib, **kwargs)
        if callable(self._objective) or raw_score or pred_leaf \
                or pred_contrib:
            # raw scores pass through untouched for custom objectives
            # (ref: sklearn.py LGBMClassifier.predict)
            return result
        idx = (np.argmax(result, axis=1) if result.ndim > 1
               else (result > 0.5).astype(int))
        return self._classes[idx]

    def predict_proba(self, X, raw_score: bool = False,
                      start_iteration: int = 0,
                      num_iteration: Optional[int] = None,
                      pred_leaf: bool = False, pred_contrib: bool = False,
                      **kwargs):
        self._check_fitted()
        preds = super().predict(
            X, raw_score=raw_score, start_iteration=start_iteration,
            num_iteration=num_iteration, pred_leaf=pred_leaf,
            pred_contrib=pred_contrib, **kwargs)
        if raw_score or pred_leaf or pred_contrib:
            return preds
        if callable(self._objective):
            log.warning("Cannot compute class probabilities due to the "
                        "usage of a customized objective function: "
                        "returning raw scores")
            return preds
        if preds.ndim == 1:
            return np.stack([1.0 - preds, preds], axis=1)
        return preds

    @property
    def classes_(self) -> np.ndarray:
        self._check_fitted()
        return self._classes

    @property
    def n_classes_(self) -> int:
        self._check_fitted()
        return self._n_classes


class LGBMRanker(LGBMModel):
    """(ref: sklearn.py:1120)"""

    def _default_objective(self) -> str:
        return "lambdarank"

    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_init_score=None, eval_group=None, eval_metric=None,
            eval_at=(1, 2, 3, 4, 5), feature_name="auto",
            categorical_feature="auto", callbacks=None, init_model=None):
        if group is None:
            raise ValueError("Should set group for ranking task")
        if eval_set is not None and eval_group is None:
            raise ValueError("Eval_group cannot be None when eval_set is "
                             "not None")
        self._other_params["eval_at"] = list(eval_at)
        setattr(self, "eval_at", list(eval_at))
        return super().fit(
            X, y, sample_weight=sample_weight, init_score=init_score,
            group=group, eval_set=eval_set, eval_names=eval_names,
            eval_sample_weight=eval_sample_weight,
            eval_init_score=eval_init_score, eval_group=eval_group,
            eval_metric=eval_metric, feature_name=feature_name,
            categorical_feature=categorical_feature, callbacks=callbacks,
            init_model=init_model)
