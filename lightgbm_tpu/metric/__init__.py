"""Evaluation metrics.

TPU-native analog of the reference metric layer (ref: src/metric/metric.cpp:17
CreateMetric factory; regression/binary/multiclass/rank/xentropy hpp families).
Scores arrive as host numpy (they're already synced back each eval round, like
the reference); every metric is vectorized numpy, not a row loop.

Each metric exposes: ``init(metadata, num_data)``, ``names`` (list),
``is_bigger_better``, and ``eval(score, objective) -> list[float]`` where
``score`` is ``[k, n]`` raw scores (k = num predictions per row).
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..config import Config
from ..utils import dcg, log

K_EPSILON = 1e-15

# metric-name aliases (ref: config.cpp ParseMetrics + docs/Parameters.rst)
METRIC_ALIASES = {
    "l2": "l2", "mean_squared_error": "l2", "mse": "l2",
    "regression": "l2", "regression_l2": "l2",
    "l2_root": "rmse", "root_mean_squared_error": "rmse", "rmse": "rmse",
    "l1": "l1", "mean_absolute_error": "l1", "mae": "l1",
    "regression_l1": "l1",
    "quantile": "quantile", "huber": "huber", "fair": "fair",
    "poisson": "poisson",
    "mape": "mape", "mean_absolute_percentage_error": "mape",
    "gamma": "gamma", "gamma_deviance": "gamma_deviance",
    "tweedie": "tweedie",
    "binary_logloss": "binary_logloss", "binary": "binary_logloss",
    "binary_error": "binary_error",
    "auc": "auc", "average_precision": "average_precision",
    "auc_mu": "auc_mu",
    "multi_logloss": "multi_logloss", "multiclass": "multi_logloss",
    "softmax": "multi_logloss", "multiclassova": "multi_logloss",
    "multiclass_ova": "multi_logloss", "ova": "multi_logloss",
    "ovr": "multi_logloss",
    "multi_error": "multi_error",
    "cross_entropy": "cross_entropy", "xentropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda",
    "xentlambda": "cross_entropy_lambda",
    "kullback_leibler": "kullback_leibler", "kldiv": "kullback_leibler",
    "ndcg": "ndcg", "lambdarank": "ndcg", "rank_xendcg": "ndcg",
    "xendcg": "ndcg", "xe_ndcg": "ndcg", "xe_ndcg_mart": "ndcg",
    "xendcg_mart": "ndcg",
    "map": "map", "mean_average_precision": "map",
}


class Metric:
    """Base metric (ref: include/LightGBM/metric.h:28)."""

    names: List[str] = []
    is_bigger_better = False

    def __init__(self, config: Config):
        self.config = config

    def init(self, metadata, num_data: int) -> None:
        self.num_data = num_data
        self.label = metadata.label
        self.weight = metadata.weight
        self.query_boundaries = metadata.query_boundaries
        # multi-process ranking: compacted-row -> padded-global-row map
        # (parallel/multiproc.GlobalMetadata)
        self.query_row_map = getattr(metadata, "query_row_map", None)
        if self.weight is not None:
            self.sum_weights = float(np.sum(self.weight))
        else:
            self.sum_weights = float(num_data)
        self._label_dev = None
        self._weight_dev = None

    def eval(self, score: np.ndarray, objective) -> List[float]:
        raise NotImplementedError

    def _query_rows(self, q):
        """Global row indices of compacted query q (identity without a
        row map)."""
        qb = self.query_boundaries
        rows = np.arange(qb[q], qb[q + 1])
        return rows if self.query_row_map is None \
            else self.query_row_map[rows]

    def _eval_mp_ranked(self, score_dev, mp, accum_fn, width):
        """Distributed per-query metric: each rank accumulates over its
        LOCAL whole queries, sums + query counts allreduce — the
        reference's distributed metric contract (its per-query sums ride
        Network::GlobalSum)."""
        qb = self.query_boundaries
        off = mp.process_index * mp.block
        loc = mp.local_block(score_dev, axis=1)
        sums = np.zeros(width, np.float64)
        cnt = 0
        for q in range(len(qb) - 1):
            rows_g = self._query_rows(q)
            if rows_g.size == 0:
                # zero-size query: owned by rank 0 so it is counted
                # exactly once (the single-process eval tolerates them)
                if mp.process_index == 0:
                    accum_fn(q, np.zeros(0), np.zeros(0), sums)
                    cnt += 1
                continue
            if not (off <= rows_g[0] < off + mp.block):
                continue
            lab = np.asarray(self.label)[rows_g]
            sc = np.asarray(loc[0][rows_g - off], np.float64)
            accum_fn(q, lab, sc, sums)
            cnt += 1
        from jax.experimental import multihost_utils
        allg = np.asarray(multihost_utils.process_allgather(
            np.concatenate([sums, [float(cnt)]])))
        allg = allg.reshape(mp.process_count, width + 1)
        tot = allg[:, :width].sum(axis=0)
        n_q = allg[:, width].sum()
        return list(tot / max(1.0, n_q))

    def eval_mp(self, score_dev, objective, mp):
        """Distributed (multi-process) evaluation, or None when this
        metric has no distributed form."""
        return None

    # -- on-device evaluation ------------------------------------------
    # The pipelined driver evaluates per iteration; pulling the full
    # [k, n] score matrix to host numpy each round costs O(n) D2H
    # (VERDICT r2 weak #3). Metrics with a jnp formulation return 0-d
    # device values here — the driver fetches SCALARS only. Precision
    # note: device accumulation is f32 (vs the host path's f64); the ref
    # GPU learner accepts the same class of drift
    # (docs/GPU-Performance.rst:130-160).
    def eval_device(self, score_dev, objective, cache=None):
        """List of 0-d device arrays, or None when this metric has no
        traced formulation (the host numpy eval is used instead).

        ``cache`` is a per-(eval set, iteration) dict shared across the
        metrics of one eval call: the objective-converted score row is
        computed once and reused, instead of every metric re-reading
        (and re-converting) the device valid scores on its own."""
        return None

    def _converted_row(self, score_dev, objective, cache):
        """Objective-converted [n] score row, shared across the eval
        set's metrics through ``cache``."""
        if cache is not None and "converted_row" in cache:
            return cache["converted_row"]
        s = score_dev[0]
        if objective is not None:
            s = objective.convert_output_jnp(s)
        if cache is not None and s is not None:
            cache["converted_row"] = s
        return s

    def _dev_label_weight(self):
        import jax.numpy as jnp
        if self._label_dev is None:
            self._label_dev = jnp.asarray(self.label)
            if self.weight is not None:
                self._weight_dev = jnp.asarray(self.weight)
        return self._label_dev, self._weight_dev


# ---------------------------------------------------------------------------
# Regression metrics (ref: src/metric/regression_metric.hpp)
# ---------------------------------------------------------------------------
class _RegressionMetric(Metric):
    """Weighted pointwise loss averaged over rows
    (ref: regression_metric.hpp:22-113)."""

    convert = True  # run objective.convert_output on scores first

    def loss(self, label, score):
        raise NotImplementedError

    def average(self, sum_loss, sum_weights):
        return sum_loss / sum_weights

    def eval(self, score, objective):
        s = score[0]
        if self.convert and objective is not None:
            s = objective.convert_output(s)
        pt = self.loss(self.label, s)
        if self.weight is not None:
            sum_loss = float(np.sum(pt * self.weight))
        else:
            sum_loss = float(np.sum(pt))
        return [self.average(sum_loss, self.sum_weights)]

    # explicit jnp mirror of `loss` (np ufuncs on device arrays silently
    # fall back to host transfers, defeating the point)
    def loss_jnp(self, label, score):
        return None

    def average_jnp(self, sum_loss, sum_weights):
        """Traced mirror of `average`: keeps the scalar ON DEVICE so the
        caller's batched fetch stays one round trip (RMSE's host
        `average` runs np.sqrt, which would pull the scalar per metric
        mid-eval)."""
        return sum_loss / sum_weights

    def eval_device(self, score_dev, objective, cache=None):
        import jax.numpy as jnp
        if self.convert:
            s = self._converted_row(score_dev, objective, cache)
            if s is None:
                return None
        else:
            s = score_dev[0]
        label, weight = self._dev_label_weight()
        pt = self.loss_jnp(label, s)
        if pt is None:
            return None
        sum_loss = (jnp.sum(pt * weight) if weight is not None
                    else jnp.sum(pt))
        # scalar arithmetic only — the 0-d result rides the caller's
        # batched fetch; nothing crosses to host here
        return [self.average_jnp(sum_loss, self.sum_weights)]


class L2Metric(_RegressionMetric):
    names = ["l2"]

    def loss(self, label, score):
        d = score - label
        return d * d

    def loss_jnp(self, label, score):
        d = score - label
        return d * d


class RMSEMetric(L2Metric):
    names = ["rmse"]

    def average(self, sum_loss, sum_weights):
        return float(np.sqrt(sum_loss / sum_weights))

    def average_jnp(self, sum_loss, sum_weights):
        import jax.numpy as jnp
        return jnp.sqrt(sum_loss / sum_weights)


class L1Metric(_RegressionMetric):
    names = ["l1"]

    def loss(self, label, score):
        return np.abs(score - label)

    def loss_jnp(self, label, score):
        import jax.numpy as jnp
        return jnp.abs(score - label)


class QuantileMetric(_RegressionMetric):
    names = ["quantile"]

    def loss(self, label, score):
        delta = label - score
        a = self.config.alpha
        return np.where(delta < 0, (a - 1.0) * delta, a * delta)

    def loss_jnp(self, label, score):
        import jax.numpy as jnp
        delta = label - score
        a = self.config.alpha
        return jnp.where(delta < 0, (a - 1.0) * delta, a * delta)


class HuberLossMetric(_RegressionMetric):
    names = ["huber"]

    def loss(self, label, score):
        diff = score - label
        a = self.config.alpha
        return np.where(np.abs(diff) <= a, 0.5 * diff * diff,
                        a * (np.abs(diff) - 0.5 * a))

    def loss_jnp(self, label, score):
        import jax.numpy as jnp
        diff = score - label
        a = self.config.alpha
        return jnp.where(jnp.abs(diff) <= a, 0.5 * diff * diff,
                         a * (jnp.abs(diff) - 0.5 * a))


class FairLossMetric(_RegressionMetric):
    names = ["fair"]

    def loss(self, label, score):
        x = np.abs(score - label)
        c = self.config.fair_c
        return c * x - c * c * np.log1p(x / c)


class PoissonMetric(_RegressionMetric):
    names = ["poisson"]

    def loss(self, label, score):
        s = np.maximum(score, 1e-10)
        return s - label * np.log(s)


class MAPEMetric(_RegressionMetric):
    names = ["mape"]

    def loss(self, label, score):
        return np.abs(label - score) / np.maximum(1.0, np.abs(label))

    def loss_jnp(self, label, score):
        import jax.numpy as jnp
        return jnp.abs(label - score) / jnp.maximum(1.0, jnp.abs(label))


class GammaMetric(_RegressionMetric):
    names = ["gamma"]

    def loss(self, label, score):
        # ref: regression_metric.hpp:261-272 (negative gamma log-likelihood)
        psi = 1.0
        theta = -1.0 / np.maximum(score, 1e-300)
        b = -np.log(np.maximum(-theta, 1e-300))
        c = (1.0 / psi * np.log(np.maximum(label / psi, 1e-300))
             - np.log(np.maximum(label, 1e-300)))
        return -((label * theta - b) / psi + c)


class GammaDevianceMetric(_RegressionMetric):
    names = ["gamma_deviance"]

    def loss(self, label, score):
        tmp = label / (score + 1e-9)
        return tmp - np.log(np.maximum(tmp, 1e-300)) - 1.0

    def average(self, sum_loss, sum_weights):
        return sum_loss * 2.0

    def average_jnp(self, sum_loss, sum_weights):
        # no loss_jnp yet, so this is unreachable today — kept in sync
        # with `average` so a future traced loss cannot silently pick up
        # the default mean
        return sum_loss * 2.0


class TweedieMetric(_RegressionMetric):
    names = ["tweedie"]

    def loss(self, label, score):
        rho = self.config.tweedie_variance_power
        s = np.maximum(score, 1e-10)
        a = label * np.exp((1.0 - rho) * np.log(s)) / (1.0 - rho)
        b = np.exp((2.0 - rho) * np.log(s)) / (2.0 - rho)
        return -a + b


# ---------------------------------------------------------------------------
# Binary metrics (ref: src/metric/binary_metric.hpp)
# ---------------------------------------------------------------------------
class _BinaryMetric(Metric):
    def loss(self, label, prob):
        raise NotImplementedError

    def loss_jnp(self, label, prob):
        return None

    def eval(self, score, objective):
        s = score[0]
        if objective is not None:
            s = objective.convert_output(s)
        pt = self.loss(self.label, s)
        if self.weight is not None:
            sum_loss = float(np.sum(pt * self.weight))
        else:
            sum_loss = float(np.sum(pt))
        return [sum_loss / self.sum_weights]

    def eval_device(self, score_dev, objective, cache=None):
        import jax.numpy as jnp
        s = self._converted_row(score_dev, objective, cache)
        if s is None:
            return None
        label, weight = self._dev_label_weight()
        pt = self.loss_jnp(label, s)
        if pt is None:
            return None
        sum_loss = (jnp.sum(pt * weight) if weight is not None
                    else jnp.sum(pt))
        return [sum_loss / self.sum_weights]


class BinaryLoglossMetric(_BinaryMetric):
    names = ["binary_logloss"]

    def loss(self, label, prob):
        # ref: binary_metric.hpp:119-130
        p = np.clip(np.where(label > 0, prob, 1.0 - prob), K_EPSILON, None)
        return -np.log(p)

    def loss_jnp(self, label, prob):
        import jax.numpy as jnp
        p = jnp.clip(jnp.where(label > 0, prob, 1.0 - prob), K_EPSILON,
                     None)
        return -jnp.log(p)


class BinaryErrorMetric(_BinaryMetric):
    names = ["binary_error"]

    def loss(self, label, prob):
        # ref: binary_metric.hpp:143-149
        return np.where(prob <= 0.5, (label > 0), (label <= 0)) \
            .astype(np.float64)

    def loss_jnp(self, label, prob):
        import jax.numpy as jnp
        return jnp.where(prob <= 0.5, label > 0, label <= 0) \
            .astype(jnp.float32)


def _weighted_auc(label: np.ndarray, score: np.ndarray,
                  weight: Optional[np.ndarray]) -> float:
    """AUC with tie handling (ref: binary_metric.hpp:159-268 AUCMetric::Eval
    — trapezoid accumulation over score-sorted groups)."""
    pos = (label > 0).astype(np.float64)
    w = weight.astype(np.float64) if weight is not None else \
        np.ones_like(pos)
    order = np.argsort(-score, kind="stable")
    sp = pos[order]
    sw = w[order]
    ss = score[order]
    # group boundaries at distinct scores
    new_group = np.concatenate([[True], ss[1:] != ss[:-1]])
    gid = np.cumsum(new_group) - 1
    n_groups = gid[-1] + 1 if len(gid) else 0
    g_pos = np.zeros(n_groups)
    g_all = np.zeros(n_groups)
    np.add.at(g_pos, gid, sp * sw)
    np.add.at(g_all, gid, sw)
    g_neg = g_all - g_pos
    cum_pos_before = np.concatenate([[0.0], np.cumsum(g_pos)[:-1]])
    # ties contribute half
    s_area = np.sum(g_neg * (cum_pos_before + 0.5 * g_pos))
    total_pos = float(np.sum(sp * sw))
    total_neg = float(np.sum(sw)) - total_pos
    if total_pos <= 0 or total_neg <= 0:
        log.warning("AUC is undefined with only one class present")
        return 1.0
    return float(s_area / (total_pos * total_neg))


def _weighted_auc_jnp(label, score, weight):
    """jnp mirror of _weighted_auc — same tie-grouped trapezoid, f32
    accumulation, one scalar leaves the device."""
    import jax
    import jax.numpy as jnp
    n = score.shape[0]
    pos = (label > 0).astype(jnp.float32)
    w = weight if weight is not None else jnp.ones_like(pos)
    order = jnp.argsort(-score, stable=True)
    sp = pos[order] * w[order]
    sw = w[order]
    ss = score[order]
    new_group = jnp.concatenate([jnp.ones((1,), bool), ss[1:] != ss[:-1]])
    gid = jnp.cumsum(new_group.astype(jnp.int32)) - 1
    g_pos = jax.ops.segment_sum(sp, gid, num_segments=n)
    g_all = jax.ops.segment_sum(sw, gid, num_segments=n)
    g_neg = g_all - g_pos
    cum_pos_before = jnp.concatenate(
        [jnp.zeros((1,), g_pos.dtype), jnp.cumsum(g_pos)[:-1]])
    s_area = jnp.sum(g_neg * (cum_pos_before + 0.5 * g_pos))
    total_pos = jnp.sum(sp)
    total_neg = jnp.sum(sw) - total_pos
    # one-class degenerate case matches the host path's 1.0
    return jnp.where((total_pos <= 0) | (total_neg <= 0), 1.0,
                     s_area / (total_pos * total_neg))


class AUCMetric(Metric):
    names = ["auc"]
    is_bigger_better = True

    def eval(self, score, objective):
        return [_weighted_auc(self.label, score[0], self.weight)]

    def eval_device(self, score_dev, objective, cache=None):
        label, weight = self._dev_label_weight()
        return [_weighted_auc_jnp(label, score_dev[0], weight)]


class AveragePrecisionMetric(Metric):
    """ref: binary_metric.hpp:270-380 (weighted average precision)."""

    names = ["average_precision"]
    is_bigger_better = True

    def eval(self, score, objective):
        w = (self.weight.astype(np.float64) if self.weight is not None
             else np.ones(self.num_data))
        pos = (self.label > 0).astype(np.float64)
        order = np.argsort(-score[0], kind="stable")
        sp = pos[order] * w[order]
        sw = w[order]
        ss = score[0][order]
        new_group = np.concatenate([[True], ss[1:] != ss[:-1]])
        gid = np.cumsum(new_group) - 1
        n_groups = gid[-1] + 1
        g_pos = np.zeros(n_groups)
        g_all = np.zeros(n_groups)
        np.add.at(g_pos, gid, sp)
        np.add.at(g_all, gid, sw)
        cum_pos = np.cumsum(g_pos)
        cum_all = np.cumsum(g_all)
        total_pos = cum_pos[-1]
        if total_pos <= 0:
            log.warning("Average precision is undefined with no positives")
            return [1.0]
        precision = cum_pos / cum_all
        recall_delta = g_pos / total_pos
        return [float(np.sum(precision * recall_delta))]


# ---------------------------------------------------------------------------
# Multiclass metrics (ref: src/metric/multiclass_metric.hpp)
# ---------------------------------------------------------------------------
class MultiSoftmaxLoglossMetric(Metric):
    names = ["multi_logloss"]

    def eval(self, score, objective):
        # score: [num_class, n]; convert via objective softmax if present
        k, n = score.shape
        if objective is not None:
            probs = objective.convert_output(score.T)  # [n, k]
        else:
            m = score - np.max(score, axis=0, keepdims=True)
            e = np.exp(m)
            probs = (e / np.sum(e, axis=0, keepdims=True)).T
        li = self.label.astype(np.int64)
        p = np.clip(probs[np.arange(n), li], K_EPSILON, None)
        pt = -np.log(p)
        if self.weight is not None:
            return [float(np.sum(pt * self.weight) / self.sum_weights)]
        return [float(np.sum(pt) / self.sum_weights)]


class MultiErrorMetric(Metric):
    names = ["multi_error"]

    def eval(self, score, objective):
        k, n = score.shape
        li = self.label.astype(np.int64)
        top_k = int(self.config.multi_error_top_k)
        # correct if true-class score is within the top k (ties count,
        # ref: multiclass_metric.hpp:143-153)
        true_score = score[li, np.arange(n)]
        # ties count against (ref: multiclass_metric.hpp:142-151 uses >=,
        # self included, error iff num_larger > top_k)
        num_larger = np.sum(score >= true_score[None, :], axis=0)
        err = (num_larger > top_k).astype(np.float64)
        if self.weight is not None:
            return [float(np.sum(err * self.weight) / self.sum_weights)]
        return [float(np.sum(err) / self.sum_weights)]


class AucMuMetric(Metric):
    """AUC-mu for multiclass (ref: multiclass_metric.hpp:183-337).

    Pairwise class separability averaged over all class pairs, using the
    auc_mu_weights decision matrix when provided."""

    names = ["auc_mu"]
    is_bigger_better = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self.num_class = int(self.config.num_class)
        aw = self.config.auc_mu_weights
        nc = self.num_class
        if aw:
            W = np.asarray(aw, dtype=np.float64).reshape(nc, nc)
        else:
            W = np.ones((nc, nc)) - np.eye(nc)
        self.W = W

    def eval(self, score, objective):
        nc, n = score.shape
        li = self.label.astype(np.int64)
        w = (self.weight.astype(np.float64) if self.weight is not None
             else np.ones(n))
        total = 0.0
        cnt = 0
        for i in range(nc):
            for j in range(i + 1, nc):
                mask = (li == i) | (li == j)
                if not mask.any() or not ((li == i).any()
                                          and (li == j).any()):
                    cnt += 1
                    continue
                # partition by decision value v·(a_row) using weight-matrix
                # difference row (ref: :252-276)
                v = self.W[i, j] * score[j, mask] - self.W[j, i] * score[i, mask]
                lab = (li[mask] == i).astype(np.float64)  # class i = "pos"
                # class i should score lower v; AUC of (-v) vs pos
                total += _weighted_auc(lab, -v, w[mask])
                cnt += 1
        return [total / max(cnt, 1)]


# ---------------------------------------------------------------------------
# Rank metrics (ref: src/metric/rank_metric.hpp, map_metric.hpp)
# ---------------------------------------------------------------------------
class NDCGMetric(Metric):
    is_bigger_better = True

    def __init__(self, config):
        super().__init__(config)
        self.eval_at = [int(k) for k in (config.eval_at or [1, 2, 3, 4, 5])]
        self.names = [f"ndcg@{k}" for k in self.eval_at]
        self.label_gain = dcg.default_label_gain(config.label_gain)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.query_boundaries is None:
            log.fatal("The NDCG metric requires query information")
        dcg.check_label(self.label, len(self.label_gain))
        qb = self.query_boundaries
        self.num_queries = len(qb) - 1
        # per-query ideal DCGs
        self.inv_max_dcgs = np.zeros((self.num_queries, len(self.eval_at)))
        for q in range(self.num_queries):
            lab = np.asarray(self.label)[self._query_rows(q)]
            for ki, k in enumerate(self.eval_at):
                m = dcg.max_dcg_at_k(k, lab, self.label_gain)
                self.inv_max_dcgs[q, ki] = 1.0 / m if m > 0 else -1.0

    def eval(self, score, objective):
        qb = self.query_boundaries
        result = np.zeros(len(self.eval_at))
        for q in range(self.num_queries):
            lab = self.label[qb[q]:qb[q + 1]]
            sc = score[0][qb[q]:qb[q + 1]]
            for ki, k in enumerate(self.eval_at):
                if self.inv_max_dcgs[q, ki] <= 0:
                    # all-zero-label query counts as perfect (ref: :88-92)
                    result[ki] += 1.0
                else:
                    d = dcg.dcg_at_k([k], lab, sc, self.label_gain)[0]
                    result[ki] += d * self.inv_max_dcgs[q, ki]
        return list(result / self.num_queries)

    def eval_mp(self, score_dev, objective, mp):
        if self.query_row_map is None:
            return None

        def acc(q, lab, sc, sums):
            for ki, k in enumerate(self.eval_at):
                if self.inv_max_dcgs[q, ki] <= 0:
                    sums[ki] += 1.0
                else:
                    d = dcg.dcg_at_k([k], lab, sc, self.label_gain)[0]
                    sums[ki] += d * self.inv_max_dcgs[q, ki]
        return self._eval_mp_ranked(score_dev, mp, acc,
                                    len(self.eval_at))


class MapMetric(Metric):
    """MAP@k (ref: src/metric/map_metric.hpp)."""

    is_bigger_better = True

    def __init__(self, config):
        super().__init__(config)
        self.eval_at = [int(k) for k in (config.eval_at or [1, 2, 3, 4, 5])]
        self.names = [f"map@{k}" for k in self.eval_at]

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.query_boundaries is None:
            log.fatal("The MAP metric requires query information")
        self.num_queries = len(self.query_boundaries) - 1

    def eval(self, score, objective):
        qb = self.query_boundaries
        result = np.zeros(len(self.eval_at))
        for q in range(self.num_queries):
            lab = (self.label[qb[q]:qb[q + 1]] > 0).astype(np.float64)
            sc = score[0][qb[q]:qb[q + 1]]
            order = np.argsort(-sc, kind="stable")
            rel = lab[order]
            cum_rel = np.cumsum(rel)
            pos = np.arange(1, len(rel) + 1)
            prec = cum_rel / pos
            for ki, k in enumerate(self.eval_at):
                kk = min(k, len(rel))
                n_rel = cum_rel[kk - 1] if kk > 0 else 0
                if n_rel > 0:
                    result[ki] += float(np.sum((prec * rel)[:kk]) / n_rel)
                else:
                    result[ki] += 0.0
        return list(result / self.num_queries)


# ---------------------------------------------------------------------------
# Cross-entropy metrics (ref: src/metric/xentropy_metric.hpp)
# ---------------------------------------------------------------------------
def _xent(label, prob):
    # handles soft labels in [0, 1] (ref: xentropy_metric.hpp:33 XentLoss)
    p = np.clip(prob, K_EPSILON, 1.0 - K_EPSILON)
    return -(label * np.log(p) + (1.0 - label) * np.log(1.0 - p))


def _stable_sigmoid(s):
    # saturated raw scores overflow np.exp and spray RuntimeWarnings
    # (the reference xentropy metric clamps the same way)
    return 1.0 / (1.0 + np.exp(-np.clip(s, -500.0, 500.0)))


class CrossEntropyMetric(Metric):
    names = ["cross_entropy"]

    def eval(self, score, objective):
        pt = _xent(self.label, _stable_sigmoid(score[0]))
        if self.weight is not None:
            return [float(np.sum(pt * self.weight) / self.sum_weights)]
        return [float(np.sum(pt) / self.sum_weights)]


class CrossEntropyLambdaMetric(Metric):
    names = ["cross_entropy_lambda"]

    def eval(self, score, objective):
        # ref: xentropy_metric.hpp:196-226 — loss in the lambda parameterization
        s = score[0]
        w = self.weight if self.weight is not None else 1.0
        hhat = np.logaddexp(0.0, s)   # log(1+e^s) without overflow
        z = 1.0 - np.exp(-w * hhat)
        z = np.clip(z, K_EPSILON, 1.0 - K_EPSILON)
        pt = _xent(self.label, z)
        return [float(np.sum(pt) / self.num_data)]


class KullbackLeiblerDivergence(Metric):
    """KL(label || sigmoid(score)) = xentropy minus label entropy
    (ref: xentropy_metric.hpp:249-320)."""

    names = ["kullback_leibler"]

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        # float64 before the clip: a float32 label rounds 1 - 1e-15 back
        # to exactly 1.0 and log(1 - lab) would emit divide-by-zero
        lab = np.clip(np.asarray(self.label, np.float64), K_EPSILON,
                      1.0 - K_EPSILON)
        ent = -(self.label * np.log(lab)
                + (1.0 - self.label) * np.log(1.0 - lab))
        # entropy is zero for hard 0/1 labels
        ent = np.where((self.label <= 0.0) | (self.label >= 1.0), 0.0, ent)
        if self.weight is not None:
            self.presum_label_entropy = float(np.sum(ent * self.weight)
                                              / self.sum_weights)
        else:
            self.presum_label_entropy = float(np.mean(ent))

    def eval(self, score, objective):
        s = score[0]
        pt = _xent(self.label, _stable_sigmoid(s))
        if self.weight is not None:
            xent = float(np.sum(pt * self.weight) / self.sum_weights)
        else:
            xent = float(np.mean(pt))
        return [xent - self.presum_label_entropy]


# ---------------------------------------------------------------------------
_REGISTRY = {
    "l2": L2Metric, "rmse": RMSEMetric, "l1": L1Metric,
    "quantile": QuantileMetric, "huber": HuberLossMetric,
    "fair": FairLossMetric, "poisson": PoissonMetric, "mape": MAPEMetric,
    "gamma": GammaMetric, "gamma_deviance": GammaDevianceMetric,
    "tweedie": TweedieMetric,
    "binary_logloss": BinaryLoglossMetric, "binary_error": BinaryErrorMetric,
    "auc": AUCMetric, "average_precision": AveragePrecisionMetric,
    "auc_mu": AucMuMetric,
    "multi_logloss": MultiSoftmaxLoglossMetric, "multi_error": MultiErrorMetric,
    "ndcg": NDCGMetric, "map": MapMetric,
    "cross_entropy": CrossEntropyMetric,
    "cross_entropy_lambda": CrossEntropyLambdaMetric,
    "kullback_leibler": KullbackLeiblerDivergence,
}


def create_metric(name: str, config: Config) -> Optional[Metric]:
    """Factory (ref: src/metric/metric.cpp:17 Metric::CreateMetric)."""
    raw = name.strip().lower()
    if raw in ("", "none", "null", "na", "custom"):
        return None
    # "ndcg@5" / "map@3" forms set eval_at inline
    if "@" in raw:
        base, ks = raw.split("@", 1)
        base = METRIC_ALIASES.get(base, base)
        if base in ("ndcg", "map"):
            cfg = Config(dict(config.to_dict()))
            cfg._values["eval_at"] = [int(k) for k in ks.split(",")]
            return _REGISTRY[base](cfg)
    resolved = METRIC_ALIASES.get(raw, raw)
    cls = _REGISTRY.get(resolved)
    if cls is None:
        log.fatal("Unknown metric type name: %s", name)
    return cls(config)


def default_metric_for_objective(objective_name: str) -> str:
    """Objective's eponymous metric (ref: config.cpp objective->metric map)."""
    mapping = {
        "regression": "l2", "regression_l1": "l1", "huber": "huber",
        "fair": "fair", "poisson": "poisson", "quantile": "quantile",
        "mape": "mape", "gamma": "gamma", "tweedie": "tweedie",
        "binary": "binary_logloss",
        "multiclass": "multi_logloss", "multiclassova": "multi_logloss",
        "cross_entropy": "cross_entropy",
        "cross_entropy_lambda": "cross_entropy_lambda",
        "lambdarank": "ndcg", "rank_xendcg": "ndcg",
    }
    return mapping.get(objective_name, "")
