"""Traced (jit-compatible) formulations of the built-in metrics.

The megastep (boosting/gbdt.py `_make_megastep`) chains whole boosting
iterations inside one ``lax.scan``; evaluating metrics per iteration on
host would force a score fetch per iteration and evict the most common
production config (train + eval sets + early stopping + logging) off
the 0.125-dispatch fast path.  This module re-expresses the built-in
metrics as pure reductions over the device-resident score carries the
scan already maintains, so the whole eval loop runs inside the jit and
only the stacked ``[B, n_slots]`` metric matrix leaves the device at
drain time.

Contract per builder: ``(ops, fn)`` where ``ops`` is a pytree of device
arrays (labels, weights, rank tables) passed as jit OPERANDS — an O(n)
array closed over instead would be embedded in the lowered HLO as a
constant (the same rule the fast step applies to the bin matrix) — and
``fn(score, ops) -> [scalar, ...]`` is a pure traced function returning
one 0-d value per metric name.  Values are f32 on device; parity with
the f64 host metrics is tolerance-tested (tests/test_traced_eval.py),
the same accuracy class the reference GPU build accepts
(docs/GPU-Performance.rst:130-160).

Numbers that are static given the dataset (ideal DCGs, discount/gain
tables, per-slot rank positions, sum of weights) are precomputed on
host exactly like the host metrics do, so the traced forms match the
reference semantics bin-for-bin where the math is discrete (error
counts, rank positions) and to float tolerance elsewhere.

Multi-process (multi-chip megastep, round 12): the training-score carry
is ROW-SHARDED over the global mesh, so a training metric's reductions
are partitioned by GSPMD and finished with the compiler's own
cross-chip psum — every rank sees the identical scalar. Valid-set
arrays are REPLICATED per rank and must be identical on every rank
(the driver enforces this with one digest allgather at precheck —
`engine:multiproc_divergent_valid_data`); the metric values, and
therefore the scan-native early-stop latch, are then identical on
every rank by construction, with no per-iteration collective needed.
The metric operands come from objects re-inited with the GLOBAL
metadata (MultiProcLayout.global_metadata), so label statistics and
weight sums are pod-wide, with pad rows carrying zero weight.
"""
from __future__ import annotations

from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import (AUCMetric, BinaryErrorMetric, BinaryLoglossMetric,
               HuberLossMetric, L1Metric, L2Metric, MAPEMetric,
               MultiErrorMetric, MultiSoftmaxLoglossMetric, NDCGMetric,
               QuantileMetric, RMSEMetric, K_EPSILON, _weighted_auc_jnp)
from ..utils import dcg


class TracedMetric(NamedTuple):
    """One metric's traced form: names it produces, its operand pytree,
    and the pure eval function."""

    names: Tuple[str, ...]
    ops: tuple
    fn: Callable


def _label_weight_ops(metric) -> tuple:
    label = jnp.asarray(np.asarray(metric.label), jnp.float32)
    weight = (jnp.asarray(np.asarray(metric.weight), jnp.float32)
              if metric.weight is not None else None)
    return (label, weight)


def _traced_row_converter(objective):
    """Traced analog of the host eval's ``objective.convert_output`` for
    [n] score rows, or None when the objective has no traced form (the
    builder then rejects the metric and the driver evicts with a named
    reason)."""
    if objective is None:
        return lambda s: s
    probe = objective.convert_output_jnp(jnp.zeros((1,), jnp.float32))
    if probe is None:
        return None
    return objective.convert_output_jnp


def _weighted_mean(pt, weight, sum_weights: float):
    s = jnp.sum(pt * weight) if weight is not None else jnp.sum(pt)
    return s / jnp.float32(sum_weights)


def _pointwise_builder(metric, objective) -> Optional[TracedMetric]:
    """Regression/binary pointwise-loss family: weighted sum of the
    metric's own ``loss_jnp`` over converted scores, finished by the
    metric's ``average_jnp`` (the traced mirror of `average` — RMSE's
    sqrt, the default sum/weights) so host and traced forms share one
    final-transform definition."""
    convert = _traced_row_converter(objective) \
        if getattr(metric, "convert", True) else (lambda s: s)
    if convert is None:
        return None
    if metric.loss_jnp(jnp.zeros((1,), jnp.float32),
                       jnp.zeros((1,), jnp.float32)) is None:
        return None
    sum_weights = float(metric.sum_weights)

    def fn(score, ops):
        label, weight = ops
        pt = metric.loss_jnp(label, convert(score[0]))
        sl = jnp.sum(pt * weight) if weight is not None else jnp.sum(pt)
        if hasattr(metric, "average_jnp"):     # regression family
            return [metric.average_jnp(sl, jnp.float32(sum_weights))]
        return [sl / jnp.float32(sum_weights)]  # binary family
    return TracedMetric(tuple(metric.names), _label_weight_ops(metric), fn)


def _auc_builder(metric, objective) -> Optional[TracedMetric]:
    def fn(score, ops):
        label, weight = ops
        return [_weighted_auc_jnp(label, score[0], weight)]
    return TracedMetric(tuple(metric.names), _label_weight_ops(metric), fn)


def _multiclass_probs(objective, score):
    """Traced class-probability conversion matching the host metric's
    ``objective.convert_output(score.T)`` branch; ``score`` is [k, n],
    returns [k, n] probabilities, or None when the objective form is
    unknown."""
    if objective is None or objective.name in ("multiclass", "softmax"):
        m = score - jnp.max(score, axis=0, keepdims=True)
        e = jnp.exp(m)
        return e / jnp.sum(e, axis=0, keepdims=True)
    if objective.name == "multiclassova":
        return 1.0 / (1.0 + jnp.exp(-float(objective.sigmoid) * score))
    return None


def _multi_logloss_builder(metric, objective) -> Optional[TracedMetric]:
    if _multiclass_probs(objective, jnp.zeros((2, 1), jnp.float32)) is None:
        return None
    sum_weights = float(metric.sum_weights)
    li = jnp.asarray(np.asarray(metric.label, np.int32))
    _, weight = _label_weight_ops(metric)

    def fn(score, ops):
        li, weight = ops
        probs = _multiclass_probs(objective, score)
        n = score.shape[1]
        p = jnp.clip(probs[li, jnp.arange(n)], K_EPSILON, None)
        return [_weighted_mean(-jnp.log(p), weight, sum_weights)]
    return TracedMetric(tuple(metric.names), (li, weight), fn)


def _multi_error_builder(metric, objective) -> Optional[TracedMetric]:
    sum_weights = float(metric.sum_weights)
    top_k = int(metric.config.multi_error_top_k)
    li = jnp.asarray(np.asarray(metric.label, np.int32))
    _, weight = _label_weight_ops(metric)

    def fn(score, ops):
        li, weight = ops
        n = score.shape[1]
        true_score = score[li, jnp.arange(n)]
        num_larger = jnp.sum(score >= true_score[None, :], axis=0)
        err = (num_larger > top_k).astype(jnp.float32)
        return [_weighted_mean(err, weight, sum_weights)]
    return TracedMetric(tuple(metric.names), (li, weight), fn)


def _ndcg_builder(metric, objective) -> Optional[TracedMetric]:
    """NDCG@k from the shared utils/dcg gain/discount tables as a
    sort-then-segment-sum reduction: one global stable lexsort by
    (query, -score) groups every query's rows into its static slot
    range, so the per-slot discount*[pos<k] factor and the per-query
    ideal-DCG normalizers are host-precomputed constants and only the
    score ordering is data-dependent."""
    qb = np.asarray(metric.query_boundaries, np.int64)
    if qb is None or len(qb) < 2:
        return None
    n = int(qb[-1])
    if getattr(metric, "query_row_map", None) is not None:
        return None        # multi-process compacted layout: host path
    num_q = len(qb) - 1
    label = np.asarray(metric.label)
    gains = np.asarray(metric.label_gain, np.float64)
    row_gain = gains[label.astype(np.int64)].astype(np.float32)
    qid = np.repeat(np.arange(num_q, dtype=np.int32), np.diff(qb))
    pos = np.arange(n, dtype=np.int64) - qb[qid]       # rank within query
    disc = dcg.discounts(int(np.diff(qb).max()))
    ks = list(metric.eval_at)
    # [n_k, n]: discount at the slot's rank, zeroed past each cutoff
    factor = np.stack([np.where(pos < k, disc[pos], 0.0) for k in ks]) \
        .astype(np.float32)
    inv_max = np.asarray(metric.inv_max_dcgs, np.float64)   # [num_q, n_k]
    degenerate = inv_max <= 0

    ops = (jnp.asarray(row_gain), jnp.asarray(qid),
           jnp.asarray(factor),
           jnp.asarray(np.where(degenerate, 0.0, inv_max)
                       .astype(np.float32).T),             # [n_k, num_q]
           jnp.asarray(degenerate.T))

    def fn(score, ops):
        row_gain, qid, factor, inv_max_t, degen_t = ops
        s = score[0]
        order = jnp.argsort(-s, stable=True)
        order = order[jnp.argsort(qid[order], stable=True)]
        g_sorted = row_gain[order]
        # slot -> query mapping is static after the lexsort (query sizes
        # are fixed), so the original ascending qid vector is reused
        out = []
        for ki in range(len(ks)):
            dcg_q = jax.ops.segment_sum(g_sorted * factor[ki], qid,
                                        num_segments=num_q)
            ndcg_q = jnp.where(degen_t[ki], 1.0, dcg_q * inv_max_t[ki])
            out.append(jnp.sum(ndcg_q) / jnp.float32(num_q))
        return out
    return TracedMetric(tuple(metric.names), ops, fn)


_BUILDERS = {
    L2Metric: _pointwise_builder,
    RMSEMetric: _pointwise_builder,
    L1Metric: _pointwise_builder,
    QuantileMetric: _pointwise_builder,
    HuberLossMetric: _pointwise_builder,
    MAPEMetric: _pointwise_builder,
    BinaryLoglossMetric: _pointwise_builder,
    BinaryErrorMetric: _pointwise_builder,
    AUCMetric: _auc_builder,
    MultiSoftmaxLoglossMetric: _multi_logloss_builder,
    MultiErrorMetric: _multi_error_builder,
    NDCGMetric: _ndcg_builder,
}


def build_traced_metric(metric, objective) -> Optional[TracedMetric]:
    """Traced form of one host metric instance, or None when this
    metric (or its objective conversion) has no traced formulation."""
    builder = _BUILDERS.get(type(metric))
    if builder is None:
        return None
    try:
        return builder(metric, objective)
    except Exception:
        return None


class TracedEvalPlan:
    """The megastep's per-iteration eval program: every (eval set,
    metric) pair flattened into an ordered slot list matching the
    synchronous engine's ``evaluation_result_list`` exactly (training
    slots first when the train set rides in ``valid_sets``, then each
    valid set's metrics in order), plus the operand pytree the scan
    passes through jit."""

    def __init__(self, groups, slots):
        # groups: [(score_index, [TracedMetric, ...])] where score_index
        # is -1 for the training scores, else the valid-set index
        self._groups = groups
        self.slots = slots            # [(ds_name, metric_name, bigger)]

    @property
    def n_slots(self) -> int:
        return len(self.slots)

    def operands(self) -> tuple:
        return tuple(tuple(tm.ops for tm in metrics)
                     for _, metrics in self._groups)

    def eval_in_scan(self, scores, vscores, metric_ops):
        """[n_slots] f32 metric vector for one iteration's updated score
        carries; runs inside the megastep scan trace."""
        vals = []
        for (si, metrics), group_ops in zip(self._groups, metric_ops):
            sc = scores if si < 0 else vscores[si]
            for tm, ops in zip(metrics, group_ops):
                vals.extend(tm.fn(sc, ops))
        if not vals:
            return jnp.zeros((0,), jnp.float32)
        return jnp.stack([jnp.asarray(v, jnp.float32) for v in vals])


def build_plan(gbdt, include_training: bool):
    """(plan, None) when every configured metric has a traced form;
    (None, reason) naming the first untraceable metric otherwise."""
    groups = []
    slots = []

    def add(ds_name, si, metrics):
        traced = []
        for m in metrics:
            tm = build_traced_metric(m, gbdt.objective)
            if tm is None:
                return f"metric:{m.names[0]}"
            traced.append(tm)
            for name in tm.names:
                slots.append((ds_name, name, bool(m.is_bigger_better)))
        groups.append((si, traced))
        return None

    if include_training and gbdt.training_metrics:
        err = add("training", -1, gbdt.training_metrics)
        if err:
            return None, err
    for vi, metrics in enumerate(gbdt.valid_metrics):
        err = add(gbdt.valid_names[vi], vi, metrics)
        if err:
            return None, err
    return TracedEvalPlan(groups, slots), None
