"""Regression objectives.

TPU-native analog of ref: src/objective/regression_objective.hpp.  Gradients
are single fused jnp expressions over the whole score vector (the reference's
OpenMP loops, vectorized).  Formula citations per class below.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..utils import log
from .base import ObjectiveFunction, percentile, weighted_percentile


def _sign(x):
    return jnp.where(x > 0, 1.0, jnp.where(x < 0, -1.0, 0.0))


class RegressionL2Loss(ObjectiveFunction):
    """L2 loss; grad = score - label, hess = 1
    (ref: regression_objective.hpp:127-141)."""

    name = "regression"

    def __init__(self, config):
        super().__init__(config)
        self.sqrt = bool(getattr(config, "reg_sqrt", False))
        self._raw_label = None

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.sqrt:
            self._raw_label = self.label
            self.label = (np.sign(self.label)
                          * np.sqrt(np.abs(self.label))).astype(np.float32)
        self._label_j = jnp.asarray(self.label)
        self._weight_j = (jnp.asarray(self.weight)
                          if self.weight is not None else None)

    def get_gradients(self, score):
        return self.gradients_from(score, self.gradient_operands())

    def gradient_operands(self):
        return (self._label_j, self._weight_j)

    def gradients_from(self, score, operands):
        label, weight = operands
        diff = score - label[None, :]
        if weight is None:
            return diff, jnp.ones_like(diff)
        w = weight[None, :]
        return diff * w, jnp.broadcast_to(w, diff.shape)

    def epilogue_spec(self):
        # exact-class guard: huber/fair/poisson/quantile subclass this and
        # override get_gradients — they must not inherit the L2 closed form
        if type(self) is not RegressionL2Loss:
            return None
        w = (self._weight_j if self._weight_j is not None
             else jnp.ones_like(self._label_j))
        return ("l2", (self._label_j, w), 1.0)

    def boost_from_score(self, class_id):
        # ref: regression_objective.hpp:173 — weighted label mean
        if self.weight is not None:
            return float(np.sum(self.label * self.weight) / np.sum(self.weight))
        return float(np.mean(self.label))

    def convert_output(self, raw):
        if self.sqrt:
            return np.sign(raw) * raw * raw
        return raw

    def convert_output_jnp(self, raw):
        # valid for any subclass whose effective convert_output is the one
        # defined HERE (poisson/gamma/tweedie override it with exp)
        for k in type(self).__mro__:
            if "convert_output" in k.__dict__:
                if k is not RegressionL2Loss:
                    return None
                break
        if self.sqrt:
            return jnp.sign(raw) * raw * raw
        return raw

    def to_string(self):
        return self.name + (" sqrt" if self.sqrt else "")

    @property
    def is_constant_hessian(self):
        return self.weight is None


class RegressionL1Loss(RegressionL2Loss):
    """L1; grad = sign(diff); leaves renewed to weighted median of residuals
    (ref: regression_objective.hpp:217-293)."""

    name = "regression_l1"

    def __init__(self, config):
        super().__init__(config)
        self.sqrt = False

    def get_gradients(self, score):
        diff = score - self._label_j[None, :]
        g = _sign(diff)
        if self._weight_j is None:
            return g, jnp.ones_like(g)
        w = self._weight_j[None, :]
        return g * w, jnp.broadcast_to(w, g.shape)

    def boost_from_score(self, class_id):
        if self.weight is not None:
            return weighted_percentile(self.label, self.weight, 0.5)
        return percentile(self.label, 0.5)

    @property
    def is_renew_tree_output(self):
        return True

    def renew_tree_output(self, leaf_pred, residuals, row_idx):
        if self.weight is not None:
            return weighted_percentile(residuals, self.weight[row_idx], 0.5)
        return percentile(residuals, 0.5)

    @property
    def is_constant_hessian(self):
        return self.weight is None

    def to_string(self):
        return self.name


class RegressionHuberLoss(RegressionL2Loss):
    """Huber; grad clipped at alpha (ref: regression_objective.hpp:313-338)."""

    name = "huber"

    def __init__(self, config):
        super().__init__(config)
        self.sqrt = False
        self.alpha = float(config.alpha)
        if self.alpha <= 0:
            log.fatal("alpha should be greater than 0 in huber loss")

    def get_gradients(self, score):
        diff = score - self._label_j[None, :]
        g = jnp.clip(diff, -self.alpha, self.alpha)
        if self._weight_j is None:
            return g, jnp.ones_like(g)
        w = self._weight_j[None, :]
        return g * w, jnp.broadcast_to(w, g.shape)

    def to_string(self):
        return self.name

    @property
    def is_constant_hessian(self):
        return self.weight is None


class RegressionFairLoss(RegressionL2Loss):
    """Fair loss; grad = c·x/(|x|+c), hess = c²/(|x|+c)²
    (ref: regression_objective.hpp:362-381)."""

    name = "fair"

    def __init__(self, config):
        super().__init__(config)
        self.sqrt = False
        self.c = float(config.fair_c)

    def get_gradients(self, score):
        x = score - self._label_j[None, :]
        ax_c = jnp.abs(x) + self.c
        g = self.c * x / ax_c
        h = self.c * self.c / (ax_c * ax_c)
        if self._weight_j is not None:
            w = self._weight_j[None, :]
            g, h = g * w, h * w
        return g, h

    def to_string(self):
        return self.name

    @property
    def is_constant_hessian(self):
        return False


class RegressionPoissonLoss(RegressionL2Loss):
    """Poisson; grad = exp(s) - y, hess = exp(s + max_delta_step)
    (ref: regression_objective.hpp:440-466)."""

    name = "poisson"

    def __init__(self, config):
        super().__init__(config)
        self.sqrt = False
        self.max_delta_step = float(config.poisson_max_delta_step)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self.check_label()

    def check_label(self):
        if np.min(self.label) < 0.0:
            log.fatal("[%s]: at least one target label is negative", self.name)
        if np.sum(self.label) == 0.0:
            log.fatal("[%s]: sum of labels is zero", self.name)

    def get_gradients(self, score):
        exp_s = jnp.exp(score)
        g = exp_s - self._label_j[None, :]
        h = jnp.exp(score + self.max_delta_step)
        if self._weight_j is not None:
            w = self._weight_j[None, :]
            g, h = g * w, h * w
        return g, h

    def boost_from_score(self, class_id):
        mean = RegressionL2Loss.boost_from_score(self, class_id)
        return float(np.log(max(mean, 1e-300)))

    def convert_output(self, raw):
        return np.exp(raw)

    def to_string(self):
        return self.name

    @property
    def is_constant_hessian(self):
        return False


class RegressionQuantileLoss(RegressionL2Loss):
    """Quantile (pinball); renews leaves to the alpha-quantile of residuals
    (ref: regression_objective.hpp:480-571)."""

    name = "quantile"

    def __init__(self, config):
        super().__init__(config)
        self.sqrt = False
        self.alpha = float(config.alpha)
        if not (0.0 < self.alpha < 1.0):
            log.fatal("alpha should be in (0, 1) for quantile objective")

    def get_gradients(self, score):
        delta = score - self._label_j[None, :]
        g = jnp.where(delta >= 0, 1.0 - self.alpha, -self.alpha)
        if self._weight_j is None:
            return g, jnp.ones_like(g)
        w = self._weight_j[None, :]
        return g * w, jnp.broadcast_to(w, g.shape)

    def boost_from_score(self, class_id):
        if self.weight is not None:
            return weighted_percentile(self.label, self.weight, self.alpha)
        return percentile(self.label, self.alpha)

    @property
    def is_renew_tree_output(self):
        return True

    def renew_tree_output(self, leaf_pred, residuals, row_idx):
        if self.weight is not None:
            return weighted_percentile(residuals, self.weight[row_idx],
                                       self.alpha)
        return percentile(residuals, self.alpha)

    def to_string(self):
        return f"{self.name} alpha:{self.alpha}"

    @property
    def is_constant_hessian(self):
        return self.weight is None


class RegressionMAPELoss(RegressionL1Loss):
    """MAPE; L1 with per-row weight 1/max(1, |label|)
    (ref: regression_objective.hpp:580-668)."""

    name = "mape"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if np.any(np.abs(self.label) < 1):
            log.warning("Some label values are < 1 in absolute value. MAPE is "
                        "unstable with such values, so LightGBM rounds them to "
                        "1.0 when calculating MAPE.")
        lw = 1.0 / np.maximum(1.0, np.abs(self.label))
        if self.weight is not None:
            lw = lw * self.weight
        self.label_weight = lw.astype(np.float32)
        self._label_weight_j = jnp.asarray(self.label_weight)

    def get_gradients(self, score):
        diff = score - self._label_j[None, :]
        g = _sign(diff) * self._label_weight_j[None, :]
        if self._weight_j is None:
            return g, jnp.ones_like(g)
        w = self._weight_j[None, :]
        return g, jnp.broadcast_to(w, g.shape)

    def boost_from_score(self, class_id):
        return weighted_percentile(self.label, self.label_weight, 0.5)

    def renew_tree_output(self, leaf_pred, residuals, row_idx):
        return weighted_percentile(residuals, self.label_weight[row_idx], 0.5)

    @property
    def is_constant_hessian(self):
        return True

    def to_string(self):
        return self.name


class RegressionGammaLoss(RegressionPoissonLoss):
    """Gamma; grad = 1 - y·exp(-s), hess = y·exp(-s)
    (ref: regression_objective.hpp:687-706)."""

    name = "gamma"

    def get_gradients(self, score):
        e = jnp.exp(-score)
        y = self._label_j[None, :]
        g = 1.0 - y * e
        h = y * e
        if self._weight_j is not None:
            w = self._weight_j[None, :]
            g, h = g * w, h * w
        return g, h


class RegressionTweedieLoss(RegressionPoissonLoss):
    """Tweedie with variance power rho
    (ref: regression_objective.hpp:723-744)."""

    name = "tweedie"

    def __init__(self, config):
        super().__init__(config)
        self.rho = float(config.tweedie_variance_power)

    def get_gradients(self, score):
        y = self._label_j[None, :]
        e1 = jnp.exp((1.0 - self.rho) * score)
        e2 = jnp.exp((2.0 - self.rho) * score)
        g = -y * e1 + e2
        h = -y * (1.0 - self.rho) * e1 + (2.0 - self.rho) * e2
        if self._weight_j is not None:
            w = self._weight_j[None, :]
            g, h = g * w, h * w
        return g, h
