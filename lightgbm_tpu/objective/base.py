"""Objective function interface.

TPU-native analog of ref: include/LightGBM/objective_function.h.  The contract
the boosting layer depends on:

- ``init(metadata, num_data)``: bind label/weight/query arrays (host numpy).
- ``get_gradients(score) -> (grad, hess)``: jnp arrays shaped like ``score``
  (``[k, n]`` with k = num_model_per_iteration).
- ``boost_from_score(class_id)``: initial score (host scalar).
- ``convert_output(raw)``: raw score -> output space (sigmoid/softmax/exp...).
- ``renew_tree_output(...)``: optional leaf-value recomputation (L1/quantile/
  MAPE/Huber) — see booster for the call site.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ..utils import log

K_EPSILON = 1e-15


def percentile(data: np.ndarray, alpha: float) -> float:
    """Unweighted percentile with the reference's interpolation
    (ref: src/objective/regression_objective.hpp:18 PercentileFun)."""
    cnt = len(data)
    if cnt <= 1:
        return float(data[0]) if cnt else 0.0
    float_pos = (1.0 - alpha) * cnt
    pos = int(float_pos)
    sorted_desc = np.sort(data)[::-1]
    if pos < 1:
        return float(sorted_desc[0])
    if pos >= cnt:
        return float(sorted_desc[-1])
    bias = float_pos - pos
    v1 = float(sorted_desc[pos - 1])
    v2 = float(sorted_desc[pos])
    return v1 - (v1 - v2) * bias


def weighted_percentile(data: np.ndarray, weight: np.ndarray,
                        alpha: float) -> float:
    """Weighted percentile (ref: regression_objective.hpp:50
    WeightedPercentileFun — including its interpolation quirks)."""
    cnt = len(data)
    if cnt <= 1:
        return float(data[0]) if cnt else 0.0
    order = np.argsort(data, kind="stable")
    sdata = np.asarray(data, dtype=np.float64)[order]
    cdf = np.cumsum(np.asarray(weight, dtype=np.float64)[order])
    threshold = cdf[-1] * alpha
    pos = int(np.searchsorted(cdf, threshold, side="right"))
    pos = min(pos, cnt - 1)
    if pos == 0 or pos == cnt - 1:
        return float(sdata[pos])
    v1, v2 = float(sdata[pos - 1]), float(sdata[pos])
    if cdf[pos + 1] - cdf[pos] >= 1.0:
        return (threshold - cdf[pos]) / (cdf[pos + 1] - cdf[pos]) * (v2 - v1) + v1
    return v2


class ObjectiveFunction:
    """Base objective (ref: include/LightGBM/objective_function.h:22)."""

    name = "base"

    def __init__(self, config):
        self.config = config
        self.num_data = 0
        self.label: Optional[np.ndarray] = None
        self.weight: Optional[np.ndarray] = None
        self._traced_ok: Optional[bool] = None

    # ------------------------------------------------------------------
    def init(self, metadata, num_data: int) -> None:
        self.num_data = num_data
        self.label = metadata.label
        self.weight = metadata.weight
        self._traced_ok = None   # operands are rebuilt from the new data

    def get_gradients(self, score) -> Tuple:
        raise NotImplementedError

    # -- in-jit gradient protocol -------------------------------------
    # The boosting fast path traces gradients into its per-iteration jit.
    # O(num_data) arrays must enter that jit as ARGUMENTS (closed-over
    # device arrays embed into the lowered program as constants — 100s of
    # MB of HLO at Higgs scale). Objectives that support this return
    # their large arrays from gradient_operands() and compute from them
    # in gradients_from(); get_gradients stays the eager entry point.
    def gradient_operands(self):
        """Pytree of device arrays for gradients_from, or None if this
        objective's gradients cannot be traced (host state, RNG)."""
        return None

    def gradients_from(self, score, operands) -> Tuple:
        raise NotImplementedError

    def convert_output_jnp(self, raw):
        """Traced (jnp) analog of convert_output for on-device metric
        evaluation, or None when no device form exists (those metrics
        fall back to the host numpy path)."""
        return None

    def epilogue_spec(self):
        """(kind, (row0, row1), sigmoid) for the fused boosting-epilogue
        kernel (ops/fused_level.epilogue_pass), which re-derives the
        gradients INSIDE the route+score+root-histogram pass, or None when
        this objective has no per-row closed form the kernel implements.
        ``kind`` selects the formula ('binary' | 'l2'); row0/row1 are [R]
        f32 device arrays (binary: ±1 label and label weight; l2: label
        and row weight)."""
        return None

    def supports_traced_gradients(self) -> bool:
        """True only when the class providing the most-derived
        get_gradients ALSO provides its own gradients_from — a subclass
        overriding just get_gradients (huber/fair/poisson/... on top of
        L2) must not inherit the base pair, or the traced path would
        silently train with the base objective's gradients. Cached per
        data binding: the fast path and the megastep chunker consult
        this every iteration."""
        if self._traced_ok is None:
            self._traced_ok = False
            for k in type(self).__mro__:
                if "get_gradients" in k.__dict__:
                    self._traced_ok = (
                        "gradients_from" in k.__dict__
                        and self.gradient_operands() is not None)
                    break
        return self._traced_ok

    def boost_from_score(self, class_id: int) -> float:
        return 0.0

    def convert_output(self, raw):
        return raw

    def to_string(self) -> str:
        return self.name

    # ------------------------------------------------------------------
    @property
    def num_model_per_iteration(self) -> int:
        return 1

    @property
    def num_prediction_per_row(self) -> int:
        return 1

    @property
    def is_constant_hessian(self) -> bool:
        return False

    @property
    def is_renew_tree_output(self) -> bool:
        return False

    def renew_tree_output(self, leaf_pred: float, residuals: np.ndarray,
                          row_idx: np.ndarray) -> float:
        """New output for one leaf given residuals (label-score) of its rows
        (ref: objective_function.h RenewTreeOutput)."""
        return leaf_pred

    @property
    def need_accurate_prediction(self) -> bool:
        return True

    def class_need_train(self, class_id: int) -> bool:
        return True

    def check_label(self) -> None:
        pass

    def _weights_or_ones(self) -> np.ndarray:
        if self.weight is not None:
            return self.weight
        return np.ones(self.num_data, dtype=np.float32)
