"""Objective functions (gradient/hessian producers).

TPU-native analog of the reference objective layer
(ref: src/objective/objective_function.cpp:17-47 factory and the
regression/binary/multiclass/xentropy/rank hpp families).  Each objective
computes per-row (grad, hess) as a vectorized jnp program over the full score
array — one fused XLA kernel instead of the reference's OpenMP row loops.
"""
from __future__ import annotations

from typing import Optional

from ..config import Config
from ..utils import log
from .base import ObjectiveFunction
from .binary import BinaryLogloss
from .multiclass import MulticlassOVA, MulticlassSoftmax
from .rank import LambdarankNDCG, RankXENDCG
from .regression import (RegressionFairLoss, RegressionGammaLoss,
                         RegressionHuberLoss, RegressionL1Loss,
                         RegressionL2Loss, RegressionMAPELoss,
                         RegressionPoissonLoss, RegressionQuantileLoss,
                         RegressionTweedieLoss)
from .xentropy import CrossEntropy, CrossEntropyLambda

_REGISTRY = {
    "regression": RegressionL2Loss,
    "regression_l1": RegressionL1Loss,
    "huber": RegressionHuberLoss,
    "fair": RegressionFairLoss,
    "poisson": RegressionPoissonLoss,
    "quantile": RegressionQuantileLoss,
    "mape": RegressionMAPELoss,
    "gamma": RegressionGammaLoss,
    "tweedie": RegressionTweedieLoss,
    "binary": BinaryLogloss,
    "multiclass": MulticlassSoftmax,
    "multiclassova": MulticlassOVA,
    "cross_entropy": CrossEntropy,
    "cross_entropy_lambda": CrossEntropyLambda,
    "lambdarank": LambdarankNDCG,
    "rank_xendcg": RankXENDCG,
}


def create_objective(config: Config) -> Optional[ObjectiveFunction]:
    """Factory (ref: src/objective/objective_function.cpp:17
    CreateObjectiveFunction).  Returns None for objective="none" (custom)."""
    name = config.objective
    if name in ("none", ""):
        return None
    cls = _REGISTRY.get(name)
    if cls is None:
        log.fatal("Unknown objective type name: %s", name)
    return cls(config)


def create_objective_from_string(s: str) -> Optional[ObjectiveFunction]:
    """Rebuild an objective from its model-file ToString form
    (ref: objective_function.cpp:49 CreateObjectiveFunction(str))."""
    tokens = s.strip().split(" ")
    if not tokens or tokens[0] in ("none", ""):
        return None
    name = tokens[0]
    cls = _REGISTRY.get(name)
    if cls is None:
        log.fatal("Unknown objective type name: %s", name)
    params = {}
    for tok in tokens[1:]:
        if ":" in tok:
            k, v = tok.split(":", 1)
            params[k] = v
        elif tok == "sqrt":
            params["reg_sqrt"] = True
    cfg = Config(params)
    cfg._values["objective"] = name  # keep resolved name
    return cls(cfg)
