"""Cross-entropy objectives for probabilistic labels in [0, 1].

TPU-native analog of ref: src/objective/xentropy_objective.hpp
(CrossEntropy, CrossEntropyLambda).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..utils import log
from .base import K_EPSILON, ObjectiveFunction


class CrossEntropy(ObjectiveFunction):
    """Cross-entropy; grad = sigmoid(s) - y (ref: xentropy_objective.hpp:77-95)."""

    name = "cross_entropy"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.label.min() < 0.0 or self.label.max() > 1.0:
            log.fatal("[%s]: label should be in [0, 1] interval", self.name)
        if self.weight is not None:
            if self.weight.min() < 0.0:
                log.fatal("[%s]: at least one weight is negative", self.name)
            if self.weight.sum() == 0.0:
                log.fatal("[%s]: sum of weights is zero", self.name)
        self._label_j = jnp.asarray(self.label)
        self._weight_j = (jnp.asarray(self.weight)
                          if self.weight is not None else None)

    def get_gradients(self, score):
        z = 1.0 / (1.0 + jnp.exp(-score))
        g = z - self._label_j[None, :]
        h = z * (1.0 - z)
        if self._weight_j is not None:
            w = self._weight_j[None, :]
            g, h = g * w, h * w
        return g, h

    def boost_from_score(self, class_id):
        # ref: xentropy_objective.hpp:113-137
        if self.weight is not None:
            pavg = float(np.sum(self.label * self.weight)
                         / np.sum(self.weight))
        else:
            pavg = float(np.mean(self.label))
        pavg = min(max(pavg, K_EPSILON), 1.0 - K_EPSILON)
        initscore = float(np.log(pavg / (1.0 - pavg)))
        log.info("[%s:BoostFromScore]: pavg = %f -> initscore = %f",
                 self.name, pavg, initscore)
        return initscore

    def convert_output(self, raw):
        return 1.0 / (1.0 + np.exp(-raw))

    @property
    def need_accurate_prediction(self):
        return False


class CrossEntropyLambda(ObjectiveFunction):
    """Weighted cross-entropy via the lambda parameterization
    (ref: xentropy_objective.hpp:157-266)."""

    name = "cross_entropy_lambda"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.label.min() < 0.0 or self.label.max() > 1.0:
            log.fatal("[%s]: label should be in [0, 1] interval", self.name)
        if self.weight is not None and self.weight.min() <= 0.0:
            log.fatal("[%s]: at least one weight is non-positive", self.name)
        self._label_j = jnp.asarray(self.label)
        self._weight_j = (jnp.asarray(self.weight)
                          if self.weight is not None else None)

    def get_gradients(self, score):
        # ref: xentropy_objective.hpp:190-217
        y = self._label_j[None, :]
        if self._weight_j is None:
            z = 1.0 / (1.0 + jnp.exp(-score))
            return z - y, z * (1.0 - z)
        w = self._weight_j[None, :]
        epf = jnp.exp(score)
        hhat = jnp.log1p(epf)
        z = 1.0 - jnp.exp(-w * hhat)
        enf = 1.0 / epf
        g = (1.0 - y / z) * w / (1.0 + enf)
        c = 1.0 / (1.0 - z)
        d = 1.0 + epf
        a = w * epf / (d * d)
        d2 = c - 1.0
        b = (c / (d2 * d2)) * (1.0 + w * epf - c)
        h = a * (1.0 + y * b)
        return g, h

    def boost_from_score(self, class_id):
        # ref: xentropy_objective.hpp:243-265
        if self.weight is not None:
            havg = float(np.sum(self.label * self.weight)
                         / np.sum(self.weight))
        else:
            havg = float(np.mean(self.label))
        initscore = float(np.log(max(np.expm1(havg), K_EPSILON)))
        log.info("[%s:BoostFromScore]: havg = %f -> initscore = %f",
                 self.name, havg, initscore)
        return initscore

    def convert_output(self, raw):
        # output is the exponential parameter lambda, NOT a probability
        # (ref: xentropy_objective.hpp:233)
        return np.log1p(np.exp(raw))

    @property
    def need_accurate_prediction(self):
        return False
