"""Multiclass objectives.

TPU-native analog of ref: src/objective/multiclass_objective.hpp
(MulticlassSoftmax, MulticlassOVA).  Scores are ``[num_class, n]``; softmax
runs across axis 0 in one fused kernel.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..utils import log
from .base import K_EPSILON, ObjectiveFunction
from .binary import BinaryLogloss


class MulticlassSoftmax(ObjectiveFunction):
    """Softmax with the K/(K-1) hessian rescale factor
    (ref: multiclass_objective.hpp:24-167)."""

    name = "multiclass"

    def __init__(self, config):
        super().__init__(config)
        self.num_class = int(config.num_class)
        if self.num_class < 2:
            log.fatal("num_class should be greater than 1 for multiclass")
        self.factor = self.num_class / (self.num_class - 1.0)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        li = self.label.astype(np.int32)
        if li.min() < 0 or li.max() >= self.num_class:
            log.fatal("Label must be in [0, %d), but found %d in label",
                      self.num_class, int(li.min() if li.min() < 0
                                          else li.max()))
        # per-class init probabilities (ref: multiclass_objective.hpp:58-83)
        w = self.weight if self.weight is not None else np.ones(num_data,
                                                                np.float32)
        probs = np.zeros(self.num_class)
        np.add.at(probs, li, w)
        self.class_init_probs = probs / w.sum()
        self._onehot = jnp.asarray(
            (li[None, :] == np.arange(self.num_class)[:, None])
            .astype(np.float32))
        self._weight_j = (jnp.asarray(self.weight)
                          if self.weight is not None else None)

    def get_gradients(self, score):
        return self.gradients_from(score, self.gradient_operands())

    def gradient_operands(self):
        return (self._onehot, self._weight_j)

    def gradients_from(self, score, operands):
        # ref: multiclass_objective.hpp:86-130
        onehot, weight = operands
        p = jnp.exp(score - jnp.max(score, axis=0, keepdims=True))
        p = p / jnp.sum(p, axis=0, keepdims=True)
        grad = p - onehot
        hess = self.factor * p * (1.0 - p)
        if weight is not None:
            w = weight[None, :]
            grad, hess = grad * w, hess * w
        return grad, hess

    def boost_from_score(self, class_id):
        # ref: multiclass_objective.hpp:142-148 — log of class prior, with
        # the average subtracted by the caller convention (reference returns
        # std::log(class_init_probs_[class_id]) guarded against 0)
        p = max(self.class_init_probs[class_id], K_EPSILON)
        return float(np.log(p))

    def convert_output(self, raw):
        """Softmax over class axis; ``raw`` is [n, num_class] host array."""
        m = raw - np.max(raw, axis=-1, keepdims=True)
        e = np.exp(m)
        return e / np.sum(e, axis=-1, keepdims=True)

    def to_string(self):
        return f"{self.name} num_class:{self.num_class}"

    @property
    def num_model_per_iteration(self):
        return self.num_class

    @property
    def num_prediction_per_row(self):
        return self.num_class

    @property
    def need_accurate_prediction(self):
        return False


class MulticlassOVA(ObjectiveFunction):
    """One-vs-all: num_class independent binary objectives
    (ref: multiclass_objective.hpp:172-263)."""

    name = "multiclassova"

    def __init__(self, config):
        super().__init__(config)
        self.num_class = int(config.num_class)
        if self.num_class < 2:
            log.fatal("num_class should be greater than 1 for multiclassova")
        self.sigmoid = float(config.sigmoid)
        self._binaries = [BinaryLogloss(config) for _ in range(self.num_class)]

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        for k, b in enumerate(self._binaries):
            # is_pos = label == k (ref: multiclass_objective.hpp:186)
            sub = _ClassView(metadata, k)
            b.init(sub, num_data)

    def get_gradients(self, score):
        gs, hs = [], []
        for k, b in enumerate(self._binaries):
            g, h = b.get_gradients(score[k:k + 1])
            gs.append(g)
            hs.append(h)
        return jnp.concatenate(gs, axis=0), jnp.concatenate(hs, axis=0)

    def boost_from_score(self, class_id):
        return self._binaries[class_id].boost_from_score(0)

    def convert_output(self, raw):
        return 1.0 / (1.0 + np.exp(-self.sigmoid * raw))

    def class_need_train(self, class_id):
        return self._binaries[class_id].need_train

    def to_string(self):
        return f"{self.name} num_class:{self.num_class} sigmoid:{self.sigmoid:g}"

    @property
    def num_model_per_iteration(self):
        return self.num_class

    @property
    def num_prediction_per_row(self):
        return self.num_class

    @property
    def need_accurate_prediction(self):
        return False


class _ClassView:
    """Metadata view with label = (label == k) for the OVA sub-objectives."""

    def __init__(self, metadata, k):
        self.label = (metadata.label.astype(np.int32) == k).astype(np.float32)
        self.weight = metadata.weight
        self.query_boundaries = metadata.query_boundaries
        self.init_score = metadata.init_score
