"""Learning-to-rank objectives.

TPU-native analog of ref: src/objective/rank_objective.hpp (LambdarankNDCG,
RankXENDCG).  The reference iterates pairs per query on the host with OpenMP;
here queries are padded into a ``[num_queries, max_docs]`` matrix and the
pairwise lambda accumulation is one batched ``[Q, D, D]`` tensor program,
chunked over queries to bound memory.  The reference's sigmoid lookup table
(a CPU speed hack, rank_objective.hpp:240) is replaced by the exact sigmoid —
fused on the VPU it costs nothing.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import dcg, log
from .base import K_EPSILON, ObjectiveFunction


class RankingObjective(ObjectiveFunction):
    """Shared query handling (ref: rank_objective.hpp:25-93)."""

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            log.fatal("Ranking tasks require query information")
        self.query_boundaries = metadata.query_boundaries
        self.num_queries = len(self.query_boundaries) - 1
        qb = self.query_boundaries.astype(np.int64)
        sizes = np.diff(qb)
        self.max_docs = int(sizes.max())
        Q, D = self.num_queries, self.max_docs
        # padded [Q, D] gather indices + validity mask. Multi-process:
        # boundaries are over COMPACTED real rows; query_row_map carries
        # each compacted row's PADDED global row index (rank blocks leave
        # gaps — parallel/multiproc.GlobalMetadata) so gathers/scatters
        # land on the true score rows.
        row_map = getattr(metadata, "query_row_map", None)
        idx = np.zeros((Q, D), dtype=np.int64)
        valid = np.zeros((Q, D), dtype=bool)
        for q in range(Q):
            c = sizes[q]
            rows = np.arange(qb[q], qb[q + 1])
            idx[q, :c] = rows if row_map is None else row_map[rows]
            valid[q, :c] = True
        self._pad_idx = idx
        self._valid = valid
        # scatter target covers every PADDED row when mapped
        self._out_rows = int(num_data) if row_map is None \
            else int(len(metadata.label))
        self._label_padded = np.where(valid, self.label[idx], 0.0) \
            .astype(np.float32)
        self._qsizes = sizes

    def _unpad(self, padded: jnp.ndarray) -> jnp.ndarray:
        """Scatter padded [Q, D] values back to flat [n] row order."""
        flat_idx = jnp.asarray(self._pad_idx.reshape(-1))
        vals = padded.reshape(-1)
        mask = jnp.asarray(self._valid.reshape(-1))
        out = jnp.zeros((self._out_rows,), jnp.float32)
        safe_idx = jnp.where(mask, flat_idx, 0)
        return out.at[safe_idx].add(jnp.where(mask, vals, 0.0))


class LambdarankNDCG(RankingObjective):
    """Pairwise lambdas weighted by |ΔNDCG|
    (ref: rank_objective.hpp:96-277)."""

    name = "lambdarank"

    def __init__(self, config):
        super().__init__(config)
        self.sigmoid = float(config.sigmoid)
        if self.sigmoid <= 0.0:
            log.fatal("Sigmoid param %f should be greater than zero",
                      self.sigmoid)
        self.norm = bool(config.lambdarank_norm)
        self.truncation_level = int(config.lambdarank_truncation_level)
        self.label_gain = dcg.default_label_gain(config.label_gain)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        dcg.check_label(self.label, len(self.label_gain))
        # inverse max DCG per query (ref: rank_objective.hpp:124-135)
        inv = np.zeros(self.num_queries)
        for q in range(self.num_queries):
            s, e = self.query_boundaries[q], self.query_boundaries[q + 1]
            m = dcg.max_dcg_at_k(self.truncation_level, self.label[s:e],
                                 self.label_gain)
            inv[q] = 1.0 / m if m > 0 else 0.0
        self._inv_max_dcg = jnp.asarray(inv.astype(np.float32))
        self._labels_j = jnp.asarray(self._label_padded)
        self._valid_j = jnp.asarray(self._valid)
        self._gain_table = jnp.asarray(self.label_gain.astype(np.float32))
        self._disc = jnp.asarray(
            dcg.discounts(self.max_docs).astype(np.float32))
        self._weight_j = (jnp.asarray(self.weight)
                          if self.weight is not None else None)
        self._grad_fn = self._build_grad_fn()

    def _build_grad_fn(self):
        D = self.max_docs
        trunc = self.truncation_level
        sig = self.sigmoid
        norm = self.norm
        gain_table = self._gain_table
        disc = self._disc

        def per_query(y, s, valid, inv_max_dcg):
            """Lambdas/hessians for one padded query
            (ref: rank_objective.hpp:139-230 GetGradientsForOneQuery)."""
            neg_inf = jnp.float32(-jnp.inf)
            s_masked = jnp.where(valid, s, neg_inf)
            order = jnp.argsort(-s_masked, stable=True)  # positions -> doc
            ys = y[order]
            ss = s_masked[order]
            ok = valid[order] & jnp.isfinite(ss)
            n_ok = jnp.sum(ok.astype(jnp.int32))
            # best/worst scores (ref: :158-166 — worst skips one kMinScore)
            best = ss[0]
            worst_i = jnp.maximum(n_ok - 1, 0)
            worst = ss[worst_i]

            gains = gain_table[ys.astype(jnp.int32)]
            pos = jnp.arange(D)
            # pair mask: i < j, i under truncation, both valid, labels differ
            mi = pos[:, None]
            mj = pos[None, :]
            pair = ((mi < mj) & (mi < trunc)
                    & ok[:, None] & ok[None, :]
                    & (ys[:, None] != ys[None, :]))

            hi_is_i = ys[:, None] > ys[None, :]
            ds = jnp.where(hi_is_i, ss[:, None] - ss[None, :],
                           ss[None, :] - ss[:, None])
            dcg_gap = jnp.abs(gains[:, None] - gains[None, :])
            paired_disc = jnp.abs(disc[:, None] - disc[None, :])
            delta = dcg_gap * paired_disc * inv_max_dcg
            if norm:
                delta = jnp.where(best != worst,
                                  delta / (0.01 + jnp.abs(ds)), delta)
            p = 1.0 / (1.0 + jnp.exp(sig * ds))      # GetSigmoid(ds)
            p_hess = p * (1.0 - p) * (sig * sig) * delta
            p_lambda = -sig * delta * p              # (ref: :207-210)
            p_lambda = jnp.where(pair, p_lambda, 0.0)
            p_hess = jnp.where(pair, p_hess, 0.0)

            # high gets +p_lambda, low gets -p_lambda; hess adds to both
            # (pair (i,j) stored once at [i,j]; role decided by hi_is_i)
            contrib_i = jnp.where(hi_is_i, p_lambda, -p_lambda)
            contrib_j = jnp.where(hi_is_i, -p_lambda, p_lambda)
            lam_sorted = (jnp.sum(contrib_i, axis=1)
                          + jnp.sum(contrib_j, axis=0))
            hess_sorted = jnp.sum(p_hess, axis=1) + jnp.sum(p_hess, axis=0)
            sum_lambdas = -2.0 * jnp.sum(p_lambda)
            if norm:
                factor = jnp.where(
                    sum_lambdas > 0,
                    jnp.log2(1.0 + sum_lambdas) / jnp.maximum(sum_lambdas,
                                                              K_EPSILON),
                    1.0)
                lam_sorted = lam_sorted * factor
                hess_sorted = hess_sorted * factor
            # unsort back to doc positions
            lam = jnp.zeros((D,), jnp.float32).at[order].set(lam_sorted)
            hes = jnp.zeros((D,), jnp.float32).at[order].set(hess_sorted)
            return lam, hes

        vq = jax.vmap(per_query)

        @jax.jit
        def grad_fn(score_padded, labels, valid, inv_max_dcg):
            return vq(labels, score_padded, valid, inv_max_dcg)

        return grad_fn

    def get_gradients(self, score):
        s = score[0]  # [n]
        s_padded = s[jnp.asarray(self._pad_idx)]
        lam, hes = self._grad_fn(s_padded, self._labels_j, self._valid_j,
                                 self._inv_max_dcg)
        g = self._unpad(lam)[None, :]
        h = self._unpad(hes)[None, :]
        if self._weight_j is not None:
            w = self._weight_j[None, :]
            g, h = g * w, h * w
        return g, h

    def to_string(self):
        return self.name

    @property
    def need_accurate_prediction(self):
        return False


class RankXENDCG(RankingObjective):
    """XE_NDCG listwise objective [arxiv.org/abs/1911.09798]
    (ref: rank_objective.hpp:284-363)."""

    name = "rank_xendcg"

    def __init__(self, config):
        super().__init__(config)
        self.seed = int(config.objective_seed)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self._labels_j = jnp.asarray(self._label_padded)
        self._valid_j = jnp.asarray(self._valid)
        self._weight_j = (jnp.asarray(self.weight)
                          if self.weight is not None else None)
        self._rng_key = jax.random.PRNGKey(self.seed)
        self._grad_fn = self._build_grad_fn()

    def _build_grad_fn(self):
        def per_query(y, s, valid, gumbel_u):
            neg_inf = jnp.float32(-jnp.inf)
            sm = jnp.where(valid, s, neg_inf)
            # softmax over valid docs (ref: :315 Common::Softmax)
            rho = jax.nn.softmax(sm)
            rho = jnp.where(valid, rho, 0.0)
            # Phi(l, u) = 2^l - u (ref: :355-357)
            params = jnp.where(valid, jnp.exp2(y) - gumbel_u, 0.0)
            inv_denom = 1.0 / jnp.maximum(K_EPSILON, jnp.sum(params))
            # first order (ref: :332-339)
            term1 = -params * inv_denom + rho
            lam = term1
            one_m_rho = jnp.maximum(1.0 - rho, K_EPSILON)
            params1 = jnp.where(valid, term1 / one_m_rho, 0.0)
            sum_l1 = jnp.sum(params1)
            # second order (ref: :341-348)
            term2 = rho * (sum_l1 - params1)
            lam = lam + term2
            params2 = jnp.where(valid, term2 / one_m_rho, 0.0)
            sum_l2 = jnp.sum(params2)
            # third order (ref: :349-352)
            lam = lam + rho * (sum_l2 - params2)
            hes = rho * (1.0 - rho)
            n_ok = jnp.sum(valid.astype(jnp.int32))
            lam = jnp.where((n_ok > 1) & valid, lam, 0.0)
            hes = jnp.where((n_ok > 1) & valid, hes, 0.0)
            return lam, hes

        vq = jax.vmap(per_query)

        @jax.jit
        def grad_fn(score_padded, labels, valid, u):
            return vq(labels, score_padded, valid, u)

        return grad_fn

    def get_gradients(self, score):
        s = score[0]
        s_padded = s[jnp.asarray(self._pad_idx)]
        self._rng_key, sub = jax.random.split(self._rng_key)
        u = jax.random.uniform(sub, self._labels_j.shape)
        lam, hes = self._grad_fn(s_padded, self._labels_j, self._valid_j, u)
        g = self._unpad(lam)[None, :]
        h = self._unpad(hes)[None, :]
        if self._weight_j is not None:
            w = self._weight_j[None, :]
            g, h = g * w, h * w
        return g, h

    def to_string(self):
        return self.name

    @property
    def need_accurate_prediction(self):
        return False
