"""Binary classification objective.

TPU-native analog of ref: src/objective/binary_objective.hpp (BinaryLogloss).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..utils import log
from .base import K_EPSILON, ObjectiveFunction


class BinaryLogloss(ObjectiveFunction):
    """Sigmoid logloss with is_unbalance / scale_pos_weight
    (ref: binary_objective.hpp:21-222)."""

    name = "binary"

    def __init__(self, config):
        super().__init__(config)
        self.sigmoid = float(config.sigmoid)
        if self.sigmoid <= 0.0:
            log.fatal("Sigmoid parameter %f should be greater than zero",
                      self.sigmoid)
        self.is_unbalance = bool(config.is_unbalance)
        self.scale_pos_weight = float(config.scale_pos_weight)
        if self.is_unbalance and abs(self.scale_pos_weight - 1.0) > 1e-6:
            log.fatal("Cannot set is_unbalance and scale_pos_weight at the "
                      "same time")
        self.need_train = True
        self.num_pos_data = 0

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        is_pos = self.label > 0
        cnt_pos = int(np.sum(is_pos))
        cnt_neg = num_data - cnt_pos
        self.num_pos_data = cnt_pos
        self.need_train = not (cnt_pos == 0 or cnt_neg == 0)
        if not self.need_train:
            log.warning("Contains only one class")
        log.info("Number of positive: %d, number of negative: %d",
                 cnt_pos, cnt_neg)
        # label weights (ref: binary_objective.hpp:88-103)
        w_neg, w_pos = 1.0, 1.0
        if self.is_unbalance and cnt_pos > 0 and cnt_neg > 0:
            if cnt_pos > cnt_neg:
                w_neg = cnt_pos / cnt_neg
            else:
                w_pos = cnt_neg / cnt_pos
        w_pos *= self.scale_pos_weight
        self._is_pos = is_pos
        # ±1 labels and per-row class weight, folded with row weights
        self._label_val = jnp.asarray(np.where(is_pos, 1.0, -1.0)
                                      .astype(np.float32))
        lw = np.where(is_pos, w_pos, w_neg).astype(np.float32)
        if self.weight is not None:
            lw = lw * self.weight
        self._label_weight = jnp.asarray(lw)

    def get_gradients(self, score):
        return self.gradients_from(score, self.gradient_operands())

    def gradient_operands(self):
        return (self._label_val, self._label_weight)

    def gradients_from(self, score, operands):
        # ref: binary_objective.hpp:107-136
        if not self.need_train:
            return jnp.zeros_like(score), jnp.zeros_like(score)
        label_val, label_weight = operands
        lv = label_val[None, :]
        lw = label_weight[None, :]
        response = -lv * self.sigmoid / (1.0 + jnp.exp(lv * self.sigmoid
                                                       * score))
        abs_resp = jnp.abs(response)
        grad = response * lw
        hess = abs_resp * (self.sigmoid - abs_resp) * lw
        return grad, hess

    def epilogue_spec(self):
        if not self.need_train:
            return None
        return ("binary", (self._label_val, self._label_weight),
                self.sigmoid)

    def boost_from_score(self, class_id):
        # ref: binary_objective.hpp:139-163
        if self.weight is not None:
            suml = float(np.sum(self._is_pos * self.weight))
            sumw = float(np.sum(self.weight))
        else:
            suml = float(np.sum(self._is_pos))
            sumw = float(self.num_data)
        pavg = min(max(suml / sumw, K_EPSILON), 1.0 - K_EPSILON)
        initscore = np.log(pavg / (1.0 - pavg)) / self.sigmoid
        log.info("[%s:BoostFromScore]: pavg=%f -> initscore=%f",
                 self.name, pavg, initscore)
        return float(initscore)

    def convert_output(self, raw):
        return 1.0 / (1.0 + np.exp(-self.sigmoid * raw))

    def convert_output_jnp(self, raw):
        return 1.0 / (1.0 + jnp.exp(-self.sigmoid * raw))

    def to_string(self):
        return f"{self.name} sigmoid:{self.sigmoid:g}"

    def class_need_train(self, class_id):
        return self.need_train

    @property
    def need_accurate_prediction(self):
        return False
