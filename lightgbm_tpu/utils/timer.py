"""Named-section timing + profiler integration.

Behavioral analog of the reference's TIMETAG-gated section timer
(ref: include/LightGBM/utils/common.h:978 Timer, :1042 FunctionTimer —
Start/Stop accumulate per-name wall time, printed once at shutdown).
Disabled timers are no-ops, so instrumentation can stay in the hot
driver paths permanently like the reference's.

Enable with env ``LIGHTGBM_TPU_TIMETAG=1`` (the analog of compiling the
reference with -DTIMETAG) or ``global_timer.enable()``. On-device work is
asynchronous under JAX, so sections measure DISPATCH time unless
``sync=True`` is passed, which blocks on the given arrays first — the
honest way to attribute device time to a section.

``profiler_trace`` wraps ``jax.profiler.trace`` for XLA-level traces
viewable in TensorBoard/Perfetto — the deep-dive path the reference
lacks (SURVEY §5: profiling gap).
"""
from __future__ import annotations

import atexit
import contextlib
import os
import threading
import time
from typing import Dict

from . import log


class Timer:
    """Accumulates wall-clock per named section (thread-safe)."""

    def __init__(self, enabled: bool = False):
        self._enabled = enabled
        self._lock = threading.Lock()
        self._acc: Dict[str, float] = {}
        self._counts: Dict[str, int] = {}
        self._tls = threading.local()

    # ------------------------------------------------------------------
    def enable(self) -> None:
        self._enabled = True

    def disable(self) -> None:
        self._enabled = False

    @property
    def enabled(self) -> bool:
        return self._enabled

    def reset(self) -> None:
        with self._lock:
            self._acc.clear()
            self._counts.clear()

    # ------------------------------------------------------------------
    def start(self, name: str) -> None:
        if not self._enabled:
            return
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = {}
        stack[name] = time.perf_counter()

    def stop(self, name: str) -> None:
        if not self._enabled:
            return
        stack = getattr(self._tls, "stack", {})
        t0 = stack.pop(name, None)
        if t0 is None:
            return
        dt = time.perf_counter() - t0
        with self._lock:
            self._acc[name] = self._acc.get(name, 0.0) + dt
            self._counts[name] = self._counts.get(name, 0) + 1

    @contextlib.contextmanager
    def section(self, name: str, sync=None):
        """Time a block. ``sync`` = array/pytree to block on before
        closing the section (attributes asynchronous device work here)."""
        self.start(name)
        try:
            yield
        finally:
            if self._enabled and sync is not None:
                import jax
                jax.block_until_ready(sync)
            self.stop(name)

    # ------------------------------------------------------------------
    def stats(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._acc)

    def print(self) -> None:
        """(ref: common.h:1011 Timer::Print — '%s costs: %f' per name,
        name-ordered)"""
        if not self._acc:
            return
        for name in sorted(self._acc):
            log.info("%s costs: %f seconds (%d calls)", name,
                     self._acc[name], self._counts.get(name, 0))


global_timer = Timer(enabled=bool(int(
    os.environ.get("LIGHTGBM_TPU_TIMETAG", "0") or "0")))


@atexit.register
def _print_at_exit() -> None:  # ref: common.h:988 ~Timer() { Print(); }
    if global_timer.enabled:
        global_timer.print()


@contextlib.contextmanager
def profiler_trace(log_dir: str):
    """XLA-level trace via jax.profiler (TensorBoard/Perfetto viewable)."""
    import jax
    with jax.profiler.trace(log_dir):
        yield
