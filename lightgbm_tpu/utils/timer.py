"""Named-section timing + profiler integration.

Behavioral analog of the reference's TIMETAG-gated section timer
(ref: include/LightGBM/utils/common.h:978 Timer, :1042 FunctionTimer —
Start/Stop accumulate per-name wall time, printed once at shutdown).
Disabled timers are no-ops, so instrumentation can stay in the hot
driver paths permanently like the reference's.

Enable with env ``LIGHTGBM_TPU_TIMETAG=1`` (the analog of compiling the
reference with -DTIMETAG) or ``global_timer.enable()``. On-device work is
asynchronous under JAX, so sections measure DISPATCH time unless
``sync=True`` is passed, which blocks on the given arrays first — the
honest way to attribute device time to a section.

``profiler_trace`` wraps ``jax.profiler.trace`` for XLA-level traces
viewable in TensorBoard/Perfetto — the deep-dive path the reference
lacks (SURVEY §5: profiling gap). The training loop exposes the same
trace via the ``profile_dir`` config key (docs/Observability.md).
"""
from __future__ import annotations

import atexit
import contextlib
import os
import threading
import time
from typing import Dict, NamedTuple

from . import log


class SectionStat(NamedTuple):
    """Accumulated cost of one named section."""
    total: float
    count: int


class Timer:
    """Accumulates wall-clock per named section (thread-safe)."""

    def __init__(self, enabled: bool = False):
        self._enabled = enabled
        self._lock = threading.Lock()
        self._acc: Dict[str, float] = {}
        self._counts: Dict[str, int] = {}
        self._tls = threading.local()
        # bumped by reset(): invalidates every thread's open-start stack,
        # so a section started before reset() cannot leak a stale start
        # time into the next run
        self._gen = 0

    # ------------------------------------------------------------------
    def enable(self) -> None:
        self._enabled = True

    def disable(self) -> None:
        self._enabled = False

    @property
    def enabled(self) -> bool:
        return self._enabled

    def reset(self) -> None:
        with self._lock:
            self._acc.clear()
            self._counts.clear()
            self._gen += 1

    # ------------------------------------------------------------------
    def _stack(self) -> Dict[str, float]:
        """This thread's open-start stack, discarded when a reset() has
        happened since it was last touched."""
        tls = self._tls
        if getattr(tls, "gen", None) != self._gen:
            tls.stack = {}
            tls.gen = self._gen
        return tls.stack

    def start(self, name: str) -> None:
        if not self._enabled:
            return
        self._stack()[name] = time.perf_counter()

    def stop(self, name: str) -> None:
        if not self._enabled:
            return
        t0 = self._stack().pop(name, None)
        if t0 is None:
            return
        self.add(name, time.perf_counter() - t0)

    def add(self, name: str, seconds: float) -> None:
        """Accumulate an externally-measured duration (used by callers
        that time once and feed both this timer and the telemetry
        registry)."""
        if not self._enabled:
            return
        with self._lock:
            self._acc[name] = self._acc.get(name, 0.0) + seconds
            self._counts[name] = self._counts.get(name, 0) + 1

    @contextlib.contextmanager
    def section(self, name: str, sync=None):
        """Time a block. ``sync`` = array/pytree to block on before
        closing the section (attributes asynchronous device work here)."""
        self.start(name)
        try:
            yield
        finally:
            if self._enabled and sync is not None:
                import jax
                jax.block_until_ready(sync)
            self.stop(name)

    # ------------------------------------------------------------------
    def stats(self) -> Dict[str, SectionStat]:
        """Per-section (total_seconds, call_count)."""
        with self._lock:
            return {name: SectionStat(self._acc[name],
                                      self._counts.get(name, 0))
                    for name in self._acc}

    def print(self) -> None:
        """(ref: common.h:1011 Timer::Print — '%s costs: %f' per name;
        costliest first so the hot section tops the report)"""
        if not self._acc:
            return
        for name in sorted(self._acc, key=self._acc.get, reverse=True):
            log.info("%s costs: %f seconds (%d calls)", name,
                     self._acc[name], self._counts.get(name, 0))


global_timer = Timer(enabled=bool(int(
    os.environ.get("LIGHTGBM_TPU_TIMETAG", "0") or "0")))


@atexit.register
def _print_at_exit() -> None:  # ref: common.h:988 ~Timer() { Print(); }
    if global_timer.enabled:
        global_timer.print()


@contextlib.contextmanager
def profiler_trace(log_dir: str):
    """XLA-level trace via jax.profiler (TensorBoard/Perfetto viewable)."""
    import jax
    with jax.profiler.trace(log_dir):
        yield
