"""Logging for lightgbm_tpu.

TPU-native analog of the reference logger (ref: include/LightGBM/utils/log.h:71-170):
leveled logging (Fatal/Warning/Info/Debug) with a pluggable callback so host
applications (and the Python `register_logger` API, ref: python-package
lightgbm/basic.py:48) can redirect output.
"""
from __future__ import annotations

import sys
from enum import IntEnum
from typing import Callable, Optional


class LogLevel(IntEnum):
    FATAL = -1
    WARNING = 0
    INFO = 1
    DEBUG = 2


class LightGBMError(Exception):
    """Raised on fatal errors (analog of the reference's Log::Fatal throw)."""


_log_level: LogLevel = LogLevel.INFO
_log_callback: Optional[Callable[[str], None]] = None


def set_log_level(level: LogLevel) -> None:
    global _log_level
    _log_level = LogLevel(level)


def get_log_level() -> LogLevel:
    return _log_level


def register_logger(callback: Optional[Callable[[str], None]]) -> None:
    """Redirect log output through ``callback`` (None restores stderr)."""
    global _log_callback
    _log_callback = callback


def _emit(msg: str) -> None:
    if _log_callback is not None:
        _log_callback(msg)
    else:
        print(msg, file=sys.stderr, flush=True)


def debug(fmt: str, *args) -> None:
    if _log_level >= LogLevel.DEBUG:
        _emit("[LightGBM-TPU] [Debug] " + (fmt % args if args else fmt))


def info(fmt: str, *args) -> None:
    if _log_level >= LogLevel.INFO:
        _emit("[LightGBM-TPU] [Info] " + (fmt % args if args else fmt))


def warning(fmt: str, *args) -> None:
    if _log_level >= LogLevel.WARNING:
        _emit("[LightGBM-TPU] [Warning] " + (fmt % args if args else fmt))


def fatal(fmt: str, *args) -> None:
    msg = fmt % args if args else fmt
    raise LightGBMError(msg)


def check(cond: bool, msg: str = "check failed") -> None:
    """Analog of the reference CHECK_* macros (ref: utils/log.h:30-68)."""
    if not cond:
        fatal(msg)
