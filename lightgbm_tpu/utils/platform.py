"""Deterministic JAX platform selection.

TPU-terminal environments may register their platform plugin in a way
that outranks the ``JAX_PLATFORMS`` env var (observed: the env var is
silently ignored and backend bring-up hangs forever when the TPU is
unreachable). Every process entry point that must honor the env var —
the CLI, the embedded-interpreter C ABI, the bench harness — calls this
ONE helper before the first backend touch.
"""
from __future__ import annotations

import os


def apply_compilation_cache(config) -> None:
    """Point JAX's persistent XLA compilation cache at
    ``compilation_cache_dir`` (a plain config key, so it works from the
    CLI, config files and the Python API alike). Applied at booster init
    — before the first trace — so repeated runs with the same shapes and
    params deserialize the fused training step instead of recompiling
    it. No-op when the key is unset; never fatal (an unwritable cache
    dir must not kill training)."""
    path = str(getattr(config, "compilation_cache_dir", "") or "")
    if not path:
        return
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        # the default 1 s floor skips most per-tree growers; the user
        # asking for a cache dir wants the repeated-run speedup, so
        # cache everything that isn't trivially cheap
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.1)
    except Exception as e:
        from . import log
        log.warning("compilation_cache_dir=%s could not be applied: %s",
                    path, e)


def pin_jax_platforms() -> None:
    """Apply ``JAX_PLATFORMS`` through jax.config, which is honored even
    where the env var is not. No-op when the env var is unset or jax is
    unavailable.

    Conflict rule — CPU wins. Two parties can have set jax_platforms
    before we run: an embedding host program (e.g. a test harness
    calling jax.config.update("jax_platforms", "cpu")) or the TPU
    runtime's own plugin (which both exports JAX_PLATFORMS and may set
    the config programmatically at interpreter startup). We cannot tell
    them apart, but the safe resolution is directional: a CPU request —
    from either the env var or the existing config — always prevails,
    because pinning to CPU never hangs, while dragging a CPU-pinned
    process onto an unreachable accelerator blocks backend bring-up
    forever."""
    plat = os.environ.get("JAX_PLATFORMS")
    if not plat:
        return
    try:
        import jax

        current = getattr(jax.config, "jax_platforms", None)
        # "cpu first" is the only configuration that counts as a host's
        # explicit CPU pin; the TPU runtime's own hook sets
        # "<accel>,cpu" (accelerator preferred, cpu fallback), which an
        # env request must still override
        if current and current != plat \
                and str(current).split(",")[0] == "cpu":
            # the host already forced CPU; never override that — but say
            # so: a silently-dropped env request cost two rounds of
            # debugging in the other direction
            if plat.split(",")[0] != "cpu":
                import sys
                print(f"[LightGBM-TPU] [Info] JAX_PLATFORMS={plat} "
                      f"ignored: the process already pinned "
                      f"jax_platforms={current} (CPU-first wins; see "
                      f"utils/platform.py)", file=sys.stderr, flush=True)
            return
        jax.config.update("jax_platforms", plat)
    except Exception:
        pass
