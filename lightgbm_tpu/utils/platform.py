"""Deterministic JAX platform selection.

TPU-terminal environments may register their platform plugin in a way
that outranks the ``JAX_PLATFORMS`` env var (observed: the env var is
silently ignored and backend bring-up hangs forever when the TPU is
unreachable). Every process entry point that must honor the env var —
the CLI, the embedded-interpreter C ABI, the bench harness — calls this
ONE helper before the first backend touch.
"""
from __future__ import annotations

import os


def pin_jax_platforms() -> None:
    """Apply ``JAX_PLATFORMS`` through jax.config, which is honored even
    where the env var is not. No-op when the env var is unset, when jax
    is unavailable — or when the embedding program already picked a
    DIFFERENT platform programmatically (the TPU runtime exports
    JAX_PLATFORMS itself, so blindly re-applying the env var would
    clobber an explicit jax.config.update("jax_platforms", "cpu") made
    by a host process and hang on an unreachable device)."""
    plat = os.environ.get("JAX_PLATFORMS")
    if not plat:
        return
    try:
        import jax

        current = getattr(jax.config, "jax_platforms", None)
        if current and current != plat:
            return
        jax.config.update("jax_platforms", plat)
    except Exception:
        pass
