"""DCG/NDCG helpers shared by the lambdarank objective and rank metrics.

TPU-native analog of ref: src/metric/dcg_calculator.cpp (DCGCalculator).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from . import log

K_MAX_POSITION = 10000


def default_label_gain(label_gain: Optional[Sequence[float]]) -> np.ndarray:
    """label_gain[i] = 2^i - 1 (ref: dcg_calculator.cpp:33)."""
    if label_gain:
        return np.asarray(label_gain, dtype=np.float64)
    return np.array([0.0] + [float((1 << i) - 1) for i in range(1, 31)])


def discounts(n: int) -> np.ndarray:
    """discount[i] = 1/log2(2+i) (ref: dcg_calculator.cpp:49)."""
    return 1.0 / np.log2(2.0 + np.arange(n, dtype=np.float64))


def check_label(label: np.ndarray, num_gains: int) -> None:
    # ref: dcg_calculator.cpp CheckLabel — integral labels within gain table
    li = label.astype(np.int64)
    if np.any(np.abs(label - li) > 1e-9) or label.min() < 0:
        log.fatal("NDCG labels must be non-negative integers")
    if li.max() >= num_gains:
        log.fatal("Label %d is larger than the size of label_gain (%d)",
                  int(li.max()), num_gains)


def max_dcg_at_k(k: int, label: np.ndarray,
                 label_gain: np.ndarray) -> float:
    """Ideal DCG@k — greedy from the top label (ref: dcg_calculator.cpp:55
    CalMaxDCGAtK)."""
    n = len(label)
    k = min(k, n)
    sorted_gain = np.sort(label_gain[label.astype(np.int64)])[::-1]
    return float(np.sum(sorted_gain[:k] * discounts(k)))


def dcg_at_k(ks: Sequence[int], label: np.ndarray, score: np.ndarray,
             label_gain: np.ndarray) -> List[float]:
    """DCG at each k for one query, docs ranked by score descending
    (ref: dcg_calculator.cpp CalDCG; stable sort matches reference)."""
    order = np.argsort(-score, kind="stable")
    gains = label_gain[label.astype(np.int64)[order]]
    n = len(label)
    disc = discounts(n)
    cum = np.cumsum(gains * disc)
    return [float(cum[min(k, n) - 1]) if n > 0 else 0.0 for k in ks]
