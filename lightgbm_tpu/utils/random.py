"""Reference-parity pseudo-random streams.

The reference drives every sampling decision (bagging membership, by-tree
and by-node column subsets, ...) off one small LCG
(ref: include/LightGBM/utils/random.h:18 Random — x = 214013*x + 2531011
mod 2^32, int16 draws from bits 16..30) plus a per-1024-row-block
generator array for bagging (ref: src/boosting/gbdt.cpp:804-808,
gbdt.h:536). Round 1 used np.RandomState, which made deterministic
subset-level parity with the reference impossible (VERDICT weak #9);
these classes reproduce the reference streams draw-for-draw.

The per-block bagging draw matrix is computed closed-form: the k-step LCG
jump is x_k = A_k * x0 + C_k (mod 2^32) with A_k = a^k and
C_k = c * (a^{k-1} + ... + 1), so one [block_size, n_blocks] broadcast
yields every row's draw without a Python loop.
"""
from __future__ import annotations

from typing import List

import numpy as np

_A = np.uint32(214013)
_C = np.uint32(2531011)


def round_int(x: float) -> int:
    """(ref: utils/common.h RoundInt — floor(x + 0.5))"""
    return int(np.floor(x + 0.5))


class Random:
    """Scalar LCG stream (ref: utils/random.h:18). Plain-int arithmetic
    masked to 32 bits — numpy scalar uint ops warn on wraparound."""

    def __init__(self, seed: int = 123456789):
        self.x = int(seed) & 0xFFFFFFFF

    def _step(self) -> int:
        self.x = (214013 * self.x + 2531011) & 0xFFFFFFFF
        return self.x

    def rand_int16(self) -> int:
        return (self._step() >> 16) & 0x7FFF

    def rand_int32(self) -> int:
        return self._step() & 0x7FFFFFFF

    def next_short(self, lo: int, hi: int) -> int:
        return self.rand_int16() % (hi - lo) + lo

    def next_int(self, lo: int, hi: int) -> int:
        return self.rand_int32() % (hi - lo) + lo

    def next_float(self) -> float:
        # float32 division like the reference's float arithmetic
        return float(np.float32(self.rand_int16()) / np.float32(32768.0))

    def sample(self, n: int, k: int) -> List[int]:
        """K ordered samples from {0..N-1} (ref: random.h:67 Sample —
        probability walk for large K, Floyd's set insertion otherwise)."""
        out: List[int] = []
        if k > n or k <= 0:
            return out
        if k == n:
            return list(range(n))
        if k > 1 and k > (n / np.log2(k)):
            for i in range(n):
                prob = (k - len(out)) / float(n - i)
                if self.next_float() < prob:
                    out.append(i)
            return out
        chosen = set()
        for r in range(n - k, n):
            v = self.next_int(0, r + 1)
            if v in chosen:
                chosen.add(r)
            else:
                chosen.add(v)
        return sorted(chosen)


class BlockBaggingStreams:
    """Vectorized per-block bagging generators: block i of 1024 rows owns
    an independent LCG seeded ``bagging_seed + i`` whose stream persists
    across iterations, each row consuming exactly one draw per bagging
    round (ref: gbdt.cpp:192 BaggingHelper / :804 ResetBaggingConfig)."""

    BLOCK = 1024  # ref: gbdt.h:536 bagging_rand_block_

    def __init__(self, seed: int, num_data: int):
        self.num_data = num_data
        nb = (num_data + self.BLOCK - 1) // self.BLOCK
        self.state = np.asarray(
            (int(seed) + np.arange(nb, dtype=np.int64)) & 0xFFFFFFFF,
            np.uint32)
        # closed-form k-step jump tables A_k, C_k for k = 1..BLOCK
        # (python-int arithmetic to avoid numpy scalar overflow warnings)
        a = np.empty(self.BLOCK + 1, np.uint32)
        c = np.empty(self.BLOCK + 1, np.uint32)
        ai, ci = 1, 0
        a[0], c[0] = ai, ci
        for kk in range(1, self.BLOCK + 1):
            ai = (ai * 214013) & 0xFFFFFFFF
            ci = (ci * 214013 + 2531011) & 0xFFFFFFFF
            a[kk], c[kk] = ai, ci
        self._jump_a, self._jump_c = a, c
        # per-block row counts (the last block may be partial)
        cnt = np.full(nb, self.BLOCK, np.int64)
        if num_data % self.BLOCK:
            cnt[-1] = num_data % self.BLOCK
        self._cnt = cnt

    def next_floats(self) -> np.ndarray:
        """[num_data] float32 draw per row for one bagging round, row r
        served by stream r // 1024 in row order."""
        # draws[k, b] uses state after k+1 steps of block b
        a = self._jump_a[1:, None]            # [BLOCK, 1]
        c = self._jump_c[1:, None]
        X = a * self.state[None, :] + c       # uint32 wraps
        draws = ((X >> np.uint32(16)) & np.uint32(0x7FFF)).astype(
            np.float32) / np.float32(32768.0)
        # advance each block by the number of rows it served
        self.state = (self._jump_a[self._cnt] * self.state
                      + self._jump_c[self._cnt])
        out = draws.T.reshape(-1)[:self.num_data]
        return out
