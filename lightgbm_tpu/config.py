"""Declarative parameter system.

TPU-native analog of the reference config layer (ref: include/LightGBM/config.h,
src/io/config.cpp:16,45,193 and the generated src/io/config_auto.cpp).  The
reference keeps one source of truth — parameter name, aliases, type, check and
doc — in header comments and code-generates the alias table / setters
(helpers/parameter_generator.py).  Here the same single source of truth is the
``_PARAMS`` registry below; alias resolution, type coercion and range checks are
driven from it at runtime.

Unknown parameters are kept and forwarded with a warning, matching the
reference's behavior of passing unrecognized keys through (config.cpp:193).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from .utils import log

__all__ = ["Config", "PARAM_ALIASES", "param_docs"]


@dataclasses.dataclass(frozen=True)
class _Param:
    name: str
    ptype: type  # int, float, bool, str, list
    default: Any
    aliases: Tuple[str, ...] = ()
    check: Optional[Tuple[str, float]] = None  # (op, bound): ">", ">=", "<", "<="
    check2: Optional[Tuple[str, float]] = None
    desc: str = ""


def _p(name, ptype, default, aliases=(), check=None, check2=None, desc=""):
    return _Param(name, ptype, default, tuple(aliases), check, check2, desc)


# One row per parameter; mirrors the surface of the reference Config struct
# (ref: include/LightGBM/config.h:139-1029).  Grouped as in Parameters.rst.
_PARAMS: List[_Param] = [
    # ---- Core parameters ----
    _p("task", str, "train", ("task_type",), desc="train, predict, convert_model, refit"),
    _p("objective", str, "regression",
       ("objective_type", "app", "application", "loss"),
       desc="objective name (regression, binary, multiclass, lambdarank, ...)"),
    _p("boosting", str, "gbdt", ("boosting_type", "boost"),
       desc="gbdt, rf, dart, goss"),
    _p("data", str, "", ("train", "train_data", "train_data_file", "data_filename"),
       desc="path of training data (CLI)"),
    _p("valid", list, [], ("test", "valid_data", "valid_data_file", "test_data",
                           "test_data_file", "valid_filenames"),
       desc="paths of validation data (CLI)"),
    _p("num_iterations", int, 100,
       ("num_iteration", "n_iter", "num_tree", "num_trees", "num_round",
        "num_rounds", "nrounds", "num_boost_round", "n_estimators", "max_iter"),
       check=(">=", 0)),
    _p("learning_rate", float, 0.1, ("shrinkage_rate", "eta"), check=(">", 0.0)),
    _p("num_leaves", int, 31, ("num_leaf", "max_leaves", "max_leaf", "max_leaf_nodes"),
       check=(">", 1), check2=("<=", 131072)),
    _p("tree_learner", str, "serial",
       ("tree", "tree_type", "tree_learner_type"),
       desc="serial, feature, data, voting"),
    _p("num_threads", int, 0, ("num_thread", "nthread", "nthreads", "n_jobs"),
       desc="unused on TPU (XLA owns threading); kept for API parity"),
    _p("device_type", str, "tpu", ("device",), desc="tpu or cpu (cpu = XLA on host)"),
    _p("seed", int, 0, ("random_seed", "random_state"),
       desc="master seed deriving data_random_seed etc."),
    _p("deterministic", bool, False),
    # ---- Learning control ----
    _p("force_col_wise", bool, False),
    _p("force_row_wise", bool, False),
    _p("histogram_pool_size", float, -1.0, ("hist_pool_size",)),
    _p("max_depth", int, -1, desc="<=0 means no limit"),
    _p("min_data_in_leaf", int, 20,
       ("min_data_per_leaf", "min_data", "min_child_samples", "min_samples_leaf"),
       check=(">=", 0)),
    _p("min_sum_hessian_in_leaf", float, 1e-3,
       ("min_sum_hessian_per_leaf", "min_sum_hessian", "min_hessian",
        "min_child_weight"), check=(">=", 0.0)),
    _p("bagging_fraction", float, 1.0, ("sub_row", "subsample", "bagging"),
       check=(">", 0.0), check2=("<=", 1.0)),
    _p("pos_bagging_fraction", float, 1.0,
       ("pos_sub_row", "pos_subsample", "pos_bagging"),
       check=(">", 0.0), check2=("<=", 1.0)),
    _p("neg_bagging_fraction", float, 1.0,
       ("neg_sub_row", "neg_subsample", "neg_bagging"),
       check=(">", 0.0), check2=("<=", 1.0)),
    _p("bagging_freq", int, 0, ("subsample_freq",)),
    _p("bagging_seed", int, 3, ("bagging_fraction_seed",)),
    _p("feature_fraction", float, 1.0,
       ("sub_feature", "colsample_bytree"), check=(">", 0.0), check2=("<=", 1.0)),
    _p("feature_fraction_bynode", float, 1.0,
       ("sub_feature_bynode", "colsample_bynode"),
       check=(">", 0.0), check2=("<=", 1.0)),
    _p("feature_fraction_seed", int, 2),
    _p("extra_trees", bool, False, ("extra_tree",)),
    _p("extra_seed", int, 6),
    _p("early_stopping_round", int, 0,
       ("early_stopping_rounds", "early_stopping", "n_iter_no_change")),
    _p("first_metric_only", bool, False),
    _p("max_delta_step", float, 0.0, ("max_tree_output", "max_leaf_output")),
    _p("lambda_l1", float, 0.0, ("reg_alpha", "l1_regularization"), check=(">=", 0.0)),
    _p("lambda_l2", float, 0.0, ("reg_lambda", "lambda", "l2_regularization"),
       check=(">=", 0.0)),
    _p("linear_lambda", float, 0.0, check=(">=", 0.0)),
    _p("min_gain_to_split", float, 0.0, ("min_split_gain",), check=(">=", 0.0)),
    _p("drop_rate", float, 0.1, ("rate_drop",), check=(">=", 0.0), check2=("<=", 1.0)),
    _p("max_drop", int, 50),
    _p("skip_drop", float, 0.5, check=(">=", 0.0), check2=("<=", 1.0)),
    _p("xgboost_dart_mode", bool, False),
    _p("uniform_drop", bool, False),
    _p("drop_seed", int, 4),
    _p("top_rate", float, 0.2, check=(">=", 0.0), check2=("<=", 1.0),
       desc="GOSS: keep-ratio of large-gradient rows"),
    _p("other_rate", float, 0.1, check=(">=", 0.0), check2=("<=", 1.0),
       desc="GOSS: sample-ratio of small-gradient rows"),
    _p("min_data_per_group", int, 100, check=(">", 0)),
    _p("max_cat_threshold", int, 32, check=(">", 0)),
    _p("cat_l2", float, 10.0, check=(">=", 0.0)),
    _p("cat_smooth", float, 10.0, check=(">=", 0.0)),
    _p("max_cat_to_onehot", int, 4, check=(">", 0)),
    _p("top_k", int, 20, ("topk",), check=(">", 0),
       desc="voting-parallel: per-shard feature proposals"),
    _p("monotone_constraints", list, [], ("mc", "monotone_constraint")),
    _p("monotone_constraints_method", str, "basic",
       ("monotone_constraining_method", "mc_method"),
       desc="basic, intermediate, advanced"),
    _p("monotone_penalty", float, 0.0, ("monotone_splits_penalty", "ms_penalty",
                                        "mc_penalty"), check=(">=", 0.0)),
    _p("feature_contri", list, [], ("feature_contrib", "fc", "fp", "feature_penalty")),
    _p("forcedsplits_filename", str, "", ("fs", "forced_splits_filename",
                                          "forced_splits_file", "forced_splits")),
    _p("refit_decay_rate", float, 0.9, check=(">=", 0.0), check2=("<=", 1.0)),
    _p("cegb_tradeoff", float, 1.0, check=(">=", 0.0)),
    _p("cegb_penalty_split", float, 0.0, check=(">=", 0.0)),
    _p("cegb_penalty_feature_lazy", list, []),
    _p("cegb_penalty_feature_coupled", list, []),
    _p("path_smooth", float, 0.0, check=(">=", 0.0)),
    _p("interaction_constraints", list, []),
    _p("verbosity", int, 1, ("verbose",)),
    _p("input_model", str, "", ("model_input", "model_in")),
    _p("output_model", str, "LightGBM_model.txt", ("model_output", "model_out")),
    _p("saved_feature_importance_type", int, 0),
    _p("snapshot_freq", int, -1, ("save_period",)),
    # ---- Linear tree ----
    _p("linear_tree", bool, False, ("linear_trees",)),
    # ---- Dataset parameters ----
    _p("max_bin", int, 255, ("max_bins",), check=(">", 1)),
    _p("max_bin_by_feature", list, []),
    _p("min_data_in_bin", int, 3, check=(">", 0)),
    _p("bin_construct_sample_cnt", int, 200000, ("subsample_for_bin",), check=(">", 0)),
    _p("data_random_seed", int, 1, ("data_seed",)),
    _p("is_enable_sparse", bool, True, ("is_sparse", "enable_sparse", "sparse")),
    _p("enable_bundle", bool, True, ("is_enable_bundle", "bundle")),
    _p("use_missing", bool, True),
    _p("zero_as_missing", bool, False),
    _p("feature_pre_filter", bool, True),
    _p("pre_partition", bool, False, ("is_pre_partition",)),
    _p("two_round", bool, False, ("two_round_loading", "use_two_round_loading"),
       desc="stream file-based dataset construction in bounded chunks "
            "(ingest/): pass 1 collects the binning sample, pass 2 "
            "parses -> bins -> packs per chunk, so peak host RSS is "
            "O(ingest_chunk_rows) instead of O(shard) — the trained "
            "model is bit-identical to the monolithic load "
            "(docs/Data.md). With save_binary, the packed chunks "
            "stream straight into the binary cache artifact and the "
            "parsed shard never exists in RAM at once"),
    _p("ingest_chunk_rows", int, 65536, ("ingest_chunk_size",),
       check=(">", 0),
       desc="rows per streaming-ingest chunk (parse/bin/pack and "
            "host->device prefetch granularity). Setting it explicitly "
            "also OPTS IN to chunked ingest for file loads, like "
            "two_round=true"),
    _p("ingest_prefetch", bool, True,
       desc="double-buffered host->device transfer of streamed/"
            "mmap-cached bin matrices: the next chunk's host read "
            "overlaps the in-flight copy, at most two chunks live on "
            "host (ingest.max_live_chunks gauge), host stall time in "
            "prefetch.host_wait_ms. Off = one-shot jnp.asarray upload"),
    _p("header", bool, False, ("has_header",)),
    _p("label_column", str, "", ("label",)),
    _p("weight_column", str, "", ("weight",)),
    _p("group_column", str, "", ("group", "group_id", "query_column", "query",
                                 "query_id")),
    _p("ignore_column", str, "", ("ignore_feature", "blacklist")),
    _p("categorical_feature", list, [], ("cat_feature", "categorical_column",
                                         "cat_column")),
    _p("forcedbins_filename", str, ""),
    _p("save_binary", bool, False, ("is_save_binary", "is_save_binary_file"),
       desc="maintain a binary dataset cache next to a file-based "
            "training input (<data>.bin, per-rank shards under the "
            "multiproc launcher): written after construction (or "
            "streamed during it with two_round), and LOADED instead of "
            "the text file on later constructs when the source "
            "fingerprint (size/mtime/dataset params) still matches — "
            "cache-hit startup skips parsing and binning entirely "
            "(docs/Data.md). cli.py task=save_binary writes the same "
            "artifact explicitly"),
    _p("precise_float_parser", bool, False),
    # ---- Predict parameters ----
    _p("start_iteration_predict", int, 0),
    _p("num_iteration_predict", int, -1),
    _p("predict_raw_score", bool, False, ("is_predict_raw_score", "predict_rawscore",
                                          "raw_score")),
    _p("predict_leaf_index", bool, False, ("is_predict_leaf_index", "leaf_index")),
    _p("predict_contrib", bool, False, ("is_predict_contrib", "contrib")),
    _p("predict_disable_shape_check", bool, False),
    _p("pred_device_min_work", int, 2_000_000,
       ("predict_device_min_work",), check=(">=", 0),
       desc="minimum rows x trees before Booster.predict routes a batch "
            "through the device predictor (stacked trees + jit scan) "
            "instead of the exact float64 host walk; 0 forces the device "
            "path, a huge value forces the host walk — the deterministic "
            "switch the serving/parity tests use. Serving "
            "(lightgbm_tpu.serve) always uses the device path when the "
            "model is representable"),
    _p("pred_early_stop", bool, False),
    _p("pred_early_stop_freq", int, 10),
    _p("pred_early_stop_margin", float, 10.0),
    _p("output_result", str, "LightGBM_predict_result.txt",
       ("predict_result", "prediction_result", "predict_name", "pred_name",
        "name_pred")),
    # ---- Convert parameters ----
    _p("convert_model_language", str, ""),
    _p("convert_model", str, "gbdt_prediction.cpp", ("convert_model_file",)),
    # ---- Objective parameters ----
    _p("objective_seed", int, 5),
    _p("num_class", int, 1, ("num_classes",), check=(">", 0)),
    _p("is_unbalance", bool, False, ("unbalance", "unbalanced_sets")),
    _p("scale_pos_weight", float, 1.0, check=(">", 0.0)),
    _p("sigmoid", float, 1.0, check=(">", 0.0)),
    _p("boost_from_average", bool, True),
    _p("reg_sqrt", bool, False),
    _p("alpha", float, 0.9, check=(">", 0.0)),
    _p("fair_c", float, 1.0, check=(">", 0.0)),
    _p("poisson_max_delta_step", float, 0.7, check=(">", 0.0)),
    _p("tweedie_variance_power", float, 1.5, check=(">=", 1.0), check2=("<", 2.0)),
    _p("lambdarank_truncation_level", int, 30, check=(">", 0)),
    _p("lambdarank_norm", bool, True),
    _p("label_gain", list, []),
    # ---- Metric parameters ----
    _p("metric", list, [], ("metrics", "metric_types")),
    _p("metric_freq", int, 1, ("output_freq",), check=(">", 0)),
    _p("is_provide_training_metric", bool, False,
       ("training_metric", "is_training_metric", "train_metric")),
    _p("eval_at", list, [1, 2, 3, 4, 5], ("ndcg_eval_at", "ndcg_at", "map_eval_at",
                                          "map_at")),
    _p("multi_error_top_k", int, 1, check=(">", 0)),
    _p("auc_mu_weights", list, []),
    # ---- Network (distributed) parameters ----
    # On TPU these select mesh behavior rather than socket/MPI endpoints
    # (ref: config.h:983-1006; src/network/*).
    _p("num_machines", int, 1, ("num_machine",), check=(">", 0)),
    _p("local_listen_port", int, 12400, ("local_port", "port"),
       desc="unused on TPU (XLA owns transport); kept for API parity"),
    _p("time_out", int, 120, check=(">", 0)),
    _p("machine_list_filename", str, "", ("machine_list_file", "machine_list",
                                          "mlist")),
    _p("machines", str, "", ("workers", "nodes")),
    # ---- GPU (reference) → TPU parameters ----
    _p("gpu_platform_id", int, -1),
    _p("gpu_device_id", int, -1),
    _p("gpu_use_dp", bool, False,
       desc="use float64 histogram accumulation (parity mode)"),
    _p("num_gpu", int, 1, check=(">", 0)),
    # ---- TPU-specific ----
    _p("grow_policy", str, "auto",
       desc="auto, leafwise (exact LightGBM semantics), depthwise "
            "(frontier-batched, fastest on TPU)"),
    _p("tpu_histogram_impl", str, "auto",
       desc="auto, segment (XLA segment-sum), onehot (one-hot matmul), "
            "pallas (Pallas kernel)"),
    _p("tpu_engine", str, "auto",
       desc="auto, fused (fused route+histogram level kernel, fastest), "
            "frontier (round-1 Pallas path), xla (no Pallas)"),
    _p("tpu_hist_precision", str, "bf16x2",
       desc="histogram input precision: bf16x2 (hi/lo split, fp32-grade, "
            "default) or bf16 (fastest)"),
    _p("tpu_enable_bundle", bool, True,
       desc="exclusive feature bundling (sparse mutually-exclusive "
            "features share histogram columns) on the fused and depthwise "
            "growers; engages only when it reduces the column count, and "
            "requires enable_bundle too (the reference's switch)"),
    _p("tpu_extra_levels", int, 3, check=(">=", 0),
       desc="extra fused-level passes after the pow2 frontier levels so "
            "skewed trees can spend the remaining leaf budget"),
    _p("tpu_max_bundle_bins", int, 256, check=(">", 1),
       desc="bin capacity per EFB bundle column for sparse-built "
            "datasets (columns fill toward this cap, bounding the "
            "uniform-width padding of the fused kernel layout)"),
    _p("tpu_quantized_grad", int, 0, ("tpu_quant_grad",),
       check=(">=", 0), check2=("<=", 16),
       desc="quantized gradient histograms on the fused engine: 16 or 8 "
            "= stochastic-rounded fixed-point grad/hess under a "
            "per-iteration global scale, integer MXU accumulation "
            "(int8 channels, exact int32 sums) with one f32 rescale "
            "before the split search — halves the one-hot scratch and "
            "gh stream the histogram kernel's floor is made of "
            "(docs/Performance.md 'Histogram plane'; accuracy-curve "
            "A/B-gated). 0 = off (f32-grade bf16x2 path, the default). "
            "Requires tpu_engine=fused; other engines degrade with a "
            "structured event"),
    _p("tpu_adaptive_bins", bool, False,
       desc="adaptive per-feature bin widths in the fused kernel "
            "layout: each feature's slab is sized to ITS effective bin "
            "count (pow2, packed densely into the 128-lane quantum) "
            "instead of padding every feature to the global pow2 "
            "max_bin — shrinks the one-hot scratch and histogram "
            "accumulator on heterogeneous-cardinality data. "
            "BIT-IDENTICAL models to the padded layout (A/B-tested): "
            "the packed layout is a pure re-indexing with the row tile "
            "held at the padded formula. Off under EFB bundling and "
            "voting-parallel (their layouts own the flat axis)"),
    _p("tpu_gain_screening", bool, False,
       desc="EMA-FS gain screening (arxiv 2606.26337): maintain a "
            "per-feature EMA of realized split gains (in the megastep "
            "scan carry on the fast path) and restrict each tree's "
            "split search to the top tpu_screening_keep_ratio features "
            "by EMA, composed with the feature_fraction mask; "
            "screened-out features' one-hot slabs are zeroed in the "
            "fused kernel. Warmup and periodic exploration rounds keep "
            "the mask open so late-blooming features re-enter "
            "(statistical-parity A/B-gated; EMA state rides resilience "
            "checkpoints). Requires tpu_engine=fused"),
    _p("tpu_screening_warmup", int, 10, check=(">=", 0),
       desc="iterations before gain screening narrows the mask (all "
            "features stay eligible while the gain EMA warms up)"),
    _p("tpu_screening_keep_ratio", float, 0.5,
       check=(">", 0.0), check2=("<=", 1.0),
       desc="fraction of features kept by gain screening outside "
            "exploration rounds (top-k by gain EMA, ties kept)"),
    _p("tpu_screening_explore_period", int, 8, check=(">=", 0),
       desc="every Nth iteration is an exploration round with the full "
            "feature set eligible, so screened-out features can realize "
            "gains and re-enter; 0 = never explore after warmup"),
    _p("tpu_screening_ema_alpha", float, 0.9,
       check=(">=", 0.0), check2=("<", 1.0),
       desc="gain-EMA decay: ema = alpha * ema + (1 - alpha) * "
            "realized split gains of the iteration's trees"),
    _p("tpu_fast_path", bool, True,
       desc="allow the pipelined fast path (device trees drained in "
            "batches); off = synchronous per-iteration host bookkeeping "
            "— bit-comparable across engines/modes, used by debugging "
            "and A/B tests"),
    _p("tpu_fused_epilogue", bool, True,
       desc="fuse final-level routing + score update + gradients + next "
            "root histogram into one kernel pass on the pipelined fast "
            "path (objectives with a kernel closed form: binary, l2)"),
    _p("tpu_megastep", bool, True,
       desc="chain up to tpu_megastep_iters boosting iterations inside "
            "ONE jit (lax.scan over the fused tree-growing step; "
            "gradients, bagging weights, tree growth, score and "
            "valid-score updates all stay on device) when the driver "
            "loop permits multi-iteration steps (engine.train / CLI "
            "train); off = one dispatch per iteration on the fast path. "
            "Off-TPU (interpret-mode fused) the default does not engage "
            "— set the key explicitly to opt in; there is no dispatch "
            "latency to amortize there"),
    _p("tpu_megastep_iters", int, 32, check=(">", 1),
       desc="max boosting iterations fused into one megastep dispatch "
            "(capped by the pipeline drain batch, the num_iterations "
            "horizon and the current bagging round's window)"),
    _p("tpu_mp_megastep", bool, True,
       desc="let multi-process (multi-chip pod) training ride the "
            "dispatch-amortized fast path and megastep: the shard_map-"
            "wrapped fused growers run inside the scan over the global "
            "ICI/DCN mesh, split sync and the voting exchange stay "
            "in-trace XLA collectives, and host collectives (health "
            "audit, checkpoints) fire only at drain boundaries. Off = "
            "multi-process runs evict to the synchronous per-iteration "
            "driver (pre-round-12 behavior, A/B switch)"),
    _p("tpu_traced_eval", bool, True,
       desc="evaluate the built-in metrics ON DEVICE inside the "
            "megastep scan (metric/traced.py) so lgb.train with eval "
            "sets + early_stopping/log_evaluation/record_evaluation/"
            "snapshots keeps the dispatch-amortized fast path; the "
            "drain replays those callbacks against the stacked "
            "per-iteration metric matrix, and a scan-carried early-stop "
            "flag keeps the drained model bit-identical to the "
            "synchronous driver's. Off = built-in callbacks evict to "
            "the per-iteration loop (pre-round-8 behavior, A/B switch)"),
    _p("tpu_rows_per_shard_pad", int, 8,
       desc="pad row count to a multiple of this per mesh shard"),
    _p("mesh_axis_data", str, "data", desc="mesh axis name for row sharding"),
    _p("mesh_axis_feature", str, "feature",
       desc="mesh axis name for feature sharding"),
    _p("compilation_cache_dir", str, "",
       ("jax_compilation_cache_dir", "xla_cache_dir"),
       desc="directory for JAX's persistent XLA compilation cache: "
            "repeated runs (same shapes/params) skip recompiling the "
            "fused training step — applied to jax.config at booster "
            "init, before the first trace"),
    # ---- Observability (docs/Observability.md) ----
    _p("telemetry_out", str, "", ("telemetry_output", "telemetry_file"),
       desc="path: stream structured JSONL telemetry (per-iteration "
            "section times, collective traffic, compile and degradation "
            "events); multi-process ranks write <path>.rank<r>, rank 0 "
            "the bare path. Time attribution follows "
            "telemetry_granularity — only granularity=section forces "
            "the synchronous per-iteration driver"),
    _p("telemetry_granularity", str, "batch",
       ("telemetry_level",),
       desc="time-attribution granularity when telemetry is on: 'batch' "
            "(default — training keeps the pipelined/megastep fast path; "
            "wall time and dispatch counts attributed per drained batch), "
            "'iteration' (fast path with one sync per iteration; whole-"
            "iteration wall times, no per-section split), 'section' "
            "(synchronous driver with honestly-synced per-section times "
            "— the pre-round-5 behavior; trace_out and "
            "health_check_period imply this)"),
    _p("profile_dir", str, "", ("profiler_dir", "profile_log_dir"),
       desc="directory: capture a jax.profiler trace of the training "
            "loop (TensorBoard/Perfetto viewable)"),
    _p("profile_start_iteration", int, 0, check=(">=", 0),
       desc="first boosting iteration covered by the profile_dir trace"),
    _p("profile_num_iterations", int, -1,
       desc="iterations covered by the profile_dir trace; <0 = until "
            "training ends"),
    _p("trace_out", str, "", ("trace_output", "trace_file"),
       desc="path: export a Perfetto/Chrome-trace JSON timeline of the "
            "training run — one track per rank, spans for the driver "
            "sections (boosting/histogram_split/tree_materialize/"
            "score_update), collectives, XLA compiles and health "
            "checks; loadable in chrome://tracing or ui.perfetto.dev. "
            "Implies telemetry (synchronous driver); multi-process runs "
            "merge every rank's spans into rank 0's file"),
    _p("health_check_period", int, 0, ("health_check_freq",),
       check=(">=", 0),
       desc="every N iterations hash the model state (leaf values + "
            "split params) and allgather per-rank section times, "
            "emitting rank_divergence events when ranks disagree and "
            "straggler events when section-time skew exceeds "
            "health_skew_threshold; 0 = off. Implies telemetry "
            "(synchronous driver)"),
    _p("health_skew_threshold", float, 2.0,
       ("straggler_skew_threshold",), check=(">", 1.0),
       desc="max/median per-section time ratio across ranks at or above "
            "which the health auditor emits a straggler event"),
    _p("metrics_port", int, 0, ("prometheus_port", "openmetrics_port"),
       check=(">=", 0),
       desc="serve the LIVE telemetry registry as an OpenMetrics/"
            "Prometheus endpoint on http://127.0.0.1:<port>/metrics "
            "(stdlib http.server on a daemon thread; counters, gauges, "
            "timing summaries and dist quantiles with rank/run_id "
            "labels). Multi-process ranks bind <port>+<rank>; rank 0 "
            "additionally appends the fleet counter series fed by the "
            "health auditor's existing allgather. A port in use falls "
            "back to an ephemeral port with a structured "
            "metrics_exporter event. 0 = off. Implies telemetry "
            "(batch granularity — the fast path is kept)"),
    _p("memory_watermarks", bool, True,
       ("memory_watermark", "mem_watermarks"),
       desc="when telemetry is enabled, gauge every local device's "
            "bytes_in_use / peak_bytes_in_use / bytes_limit — plus "
            "bytes_reserved / peak_bytes_reserved and a derived "
            "free-space fragmentation ratio where the backend's "
            "allocator reports them — (mem.d<id>.* gauges, the "
            "exporter's HBM-headroom series) at megastep drain and "
            "serving dispatch boundaries; backends without allocator "
            "stats (CPU) degrade to a no-op"),
    _p("run_report_out", str, "", ("run_report", "report_out"),
       desc="path: write the consolidated, schema-versioned run report "
            "(run_report.json + rendered <path>.md) at finalize — "
            "dispatch/compile counters with per-iteration derivations, "
            "every megastep_evicted / degrade reason fired, the "
            "device-time cost ledger, collective traffic, memory "
            "watermarks and checkpoint/recovery events in ONE "
            "comparable artifact (scripts/run_diff.py diffs two of "
            "them). Multi-process: rank 0 writes the report with a "
            "per-rank section aggregated over the existing finalize "
            "allgather. Implies telemetry (batch granularity); the "
            "same report is served live from GET /report when "
            "metrics_port is set"),
    _p("cost_ledger", str, "hlo", ("cost_analysis_mode",),
       desc="device-time cost ledger mode (obs/cost.py): 'hlo' "
            "(default — analyze each fresh executable signature "
            "[megastep chunks, fast step, serve buckets] with the "
            "client-side HLO cost model, no second compile), "
            "'compiled' (post-optimization compiled.cost_analysis(); "
            "pays a second backend compile unless "
            "compilation_cache_dir is armed), 'off'. Active only while "
            "telemetry is enabled; feeds cost.flops_per_iter / "
            "cost.hlo_bytes_per_iter / cost.achieved_fraction gauges "
            "and one cost_ledger record per drained batch"),
    _p("perf_db", str, "", ("perf_database", "perfdb"),
       desc="path to the append-only, shape-keyed performance database "
            "(obs/perfdb.py, JSONL). Every profile window that closes "
            "(profile_dir config window or POST /profile) is parsed by "
            "the roofline plane (obs/kernelstats.py) and its joined "
            "executables append one measured sample each — keyed by "
            "(signature, kind, shape class, backend, quant bits, "
            "packed layout, world size) — so measured device times "
            "accumulate across runs into the tuning cache "
            "scripts/perfdb_query.py and run_diff --perf-db read. "
            "Appends are atomic (single O_APPEND write); concurrent "
            "runs may share one file. Empty (default) disables the "
            "perfdb write; the roofline record and gauges are emitted "
            "either way whenever a window closes under telemetry"),
    _p("drift_profile", bool, True, ("data_profile", "drift_monitor"),
       desc="capture a compact DataProfile of the training distribution "
            "at dataset finalize (per-feature bin-occupancy histograms "
            "over the packed bins, missing rates, label/score "
            "distribution, mappers digest, row count) and embed it — "
            "with the model's provenance record — in the serialized "
            "model artifact and in resilience checkpoints, so any "
            "loaded booster carries its training distribution. Also "
            "the master switch for the ingest mapper-drift monitor and "
            "the serving drift monitor (both degrade structurally when "
            "a model has no embedded profile: one drift_unavailable "
            "event, never an exception). Default ON"),
    _p("drift_psi_threshold", float, 0.2, ("psi_threshold",),
       check=(">", 0.0),
       desc="serving drift monitor: PSI level at or below which a "
            "feature/score distribution counts as stable; evaluations "
            "with max PSI above it arm the hysteresis counter toward a "
            "drift_alert event (0.2 is the conventional "
            "investigate-shift PSI rule of thumb)"),
    _p("drift_eval_rows", int, 512, ("drift_eval_period_rows",),
       check=(">=", 1),
       desc="serving drift monitor: minimum accumulated request rows "
            "between PSI evaluations — evaluation runs on the "
            "micro-batcher's post-batch flush hook, off the request "
            "latency path and with zero extra device dispatches"),
    _p("drift_hysteresis", int, 2, ("drift_alert_hysteresis",),
       check=(">=", 1),
       desc="serving drift monitor: consecutive over-threshold "
            "evaluations required before a drift_alert fires; the "
            "alert then latches until an evaluation drops back under "
            "the threshold, so one sustained distribution shift "
            "raises exactly one alert"),
    _p("drift_mapper_threshold", float, 0.02,
       ("mapper_drift_threshold",), check=(">=", 0.0),
       desc="ingest drift monitor: per-chunk fraction of values "
            "outside the frozen mappers' training range (numeric "
            "out-of-range mass + categorical new-category rate) at or "
            "above which the chunk is flagged in the mapper_drift "
            "event — the rebuild-vs-append trigger for continuous "
            "learning"),
    # ---- SLO plane (docs/Observability.md §14) ----
    _p("slo_enabled", bool, False, ("enable_slo",),
       desc="arm the SloEngine with the built-in objective catalog "
            "(serve latency p99, shed ratio, lane/worker liveness, "
            "shadow divergence, model age, drift ceiling, training "
            "liveness, straggler skew, checkpoint age, prefetch "
            "starvation, scrape staleness). The evaluator is host-side "
            "and dispatch-neutral: it reads telemetry snapshots on a "
            "daemon ticker and never touches device arrays "
            "(counter-asserted in bench like the profile control)"),
    _p("slo_config", str, "", ("slo_objectives",),
       desc="path to a JSON objective spec file ({'objectives': "
            "[{id, target, hysteresis, ...}]}); entries matching a "
            "built-in id override it, new ids must carry a known "
            "'kind'. Setting this implies slo_enabled"),
    _p("slo_tick_period_s", float, 5.0, ("slo_period_s",),
       check=(">=", 0.0),
       desc="SLO evaluation cadence in seconds for the daemon ticker; "
            "0 disables the thread — the engine then evaluates only at "
            "the driver's drain boundaries (training) or on explicit "
            "step() calls (tests/bench)"),
    _p("slo_readyz_gating", bool, False, (),
       desc="let /readyz report 503 while a PAGE-severity serving "
            "alert is firing, so a load balancer drains a replica that "
            "is alive but violating its latency/liveness objectives. "
            "Default OFF: alerting observes, readiness gates only on "
            "structural state (warmup/rollover/wedge)"),
    # ---- Serving admission control (docs/Serving.md) ----
    _p("serve_max_queue_rows", int, 0, ("serve_queue_rows",),
       check=(">=", 0),
       desc="admission control: max TOTAL rows queued in the "
            "PredictionService micro-batcher; a submit that would "
            "overflow raises a structured ServeRejected (reason, "
            "retry_after_ms hint from the measured drain rate) "
            "synchronously instead of growing the backlog without "
            "bound. 0 = unbounded (the pre-overload-hardening "
            "behavior). PredictionService(max_queue_rows=) overrides"),
    _p("serve_max_queue_requests", int, 0, ("serve_queue_requests",),
       check=(">=", 0),
       desc="admission control: max queued REQUESTS in the "
            "micro-batcher (companion bound to serve_max_queue_rows "
            "for single-row traffic). 0 = unbounded. "
            "PredictionService(max_queue_requests=) overrides"),
    _p("serve_default_deadline_ms", float, 0.0, ("serve_deadline_ms",),
       check=(">=", 0.0),
       desc="service-level default request deadline: a queued request "
            "older than this is SHED AT DEQUEUE with "
            "ServeDeadlineExceeded — before any device work is spent "
            "on it, never after. 0 = no deadline; submit(deadline_ms=) "
            "overrides per request. "
            "PredictionService(default_deadline_ms=) overrides"),
    _p("serve_target_p99_ms", float, 0.0, ("serve_p99_target_ms",),
       check=(">=", 0.0),
       desc="arm the adaptive admission controller: drives the "
            "micro-batcher's max_delay_ms, its batch-row cap (bucket "
            "selection — smaller warmed power-of-two buckets under "
            "pressure, zero fresh compiles) and a shed watermark under "
            "the hard queue cap from the live serve.latency_ms p99 "
            "ring, with consecutive-evaluation hysteresis so it "
            "cannot flap. 0 = off (serving behavior unchanged). "
            "PredictionService(target_p99_ms=) overrides"),
    _p("serve_devices", int, 0, ("serve_n_devices",), check=(">=", 0),
       desc="serving fleet width: replicate each hot model's packed "
            "tree tensors onto this many local devices, each with its "
            "own dispatch queue + worker lane; the micro-batcher "
            "routes each micro-batch to the least-loaded replica and "
            "spills to the coldest lane before shedding. Per-device "
            "LRU/budget residency and atomic all-replica rollover "
            "apply, and predict_bulk shard-maps giant batches row-wise "
            "over the fleet. 0 = all local devices; 1 = the "
            "single-device pre-fleet serving plane (every legacy "
            "contract byte-identical). "
            "PredictionService(serve_devices=) overrides"),
    _p("serve_routing", str, "least_loaded", (),
       desc="fleet request routing across the per-device dispatch "
            "lanes: 'least_loaded' scores each lane by queued + "
            "in-flight rows weighted by its measured per-row dispatch "
            "EWMA (all-idle ties rotate round-robin so every device "
            "warms and stays measurable); 'round_robin' ignores load "
            "entirely. Only meaningful when the serving fleet has more "
            "than one device"),
    # ---- Resilience (docs/Reliability.md) ----
    _p("checkpoint_dir", str, "", ("checkpoint_path",),
       desc="directory for resumable training checkpoints "
            "(resilience/): per-rank atomic write-then-rename files "
            "under ckpt_<iteration>/ with a manifest (rank, iteration, "
            "model-state hash), written by a background thread at "
            "megastep drain boundaries / every checkpoint_period "
            "iterations; empty = checkpointing off"),
    _p("checkpoint_period", int, 0, ("checkpoint_freq",), check=(">=", 0),
       desc="checkpoint at least every N boosting iterations (0 = off). "
            "On the fast path the write lands at the next drain "
            "boundary at or past N, so checkpointing never adds a "
            "device dispatch; a crashed multi-chip run resumes from the "
            "newest rank-consistent checkpoint with at most N "
            "iterations of lost work"),
    _p("checkpoint_keep", int, 2, check=(">=", 1),
       desc="complete checkpoints retained per rank (>= 2 keeps the "
            "previous one valid while the next is being written — the "
            "double-buffer invariant)"),
    _p("resume", str, "", ("resume_from",),
       desc="resume training from a checkpoint: a concrete "
            "ckpt_<iteration> directory or a checkpoint_dir root (the "
            "newest complete hash-consistent checkpoint is selected). "
            "CLI: task=train resume=<path>; API: "
            "engine.train(resume_from=...). The resumed run's "
            "serialized model is bit-identical to an uninterrupted run "
            "with the same params/seed"),
    _p("health_auto_resync", bool, True,
       desc="on a rank_divergence health finding, re-sync the diverged "
            "rank's model state from rank 0's hash-verified "
            "serialization (score carries fixed up in place) instead of "
            "only logging; emits a structured 'recovery' event and "
            "disables itself for the run if a repair fails to converge"),
    _p("health_checkpoint_on_straggler", bool, False,
       desc="force an immediate checkpoint when the health auditor "
            "flags a straggler past health_skew_threshold (a limping "
            "rank often precedes a dead one; keeps the launcher's "
            "restart point fresh)"),
    _p("collective_timeout", float, 0.0, ("collective_timeout_s",),
       check=(">=", 0.0),
       desc="seconds before a host-plane collective (multiproc "
            "allgathers, health audits) degrades a hung peer to a "
            "structured CollectiveError instead of deadlocking the "
            "cohort; 0 = off. Set it in the params passed to the "
            "launcher so a wedged rank turns into a respawn, not a "
            "hang; size it above the worst-case first-iteration "
            "compile stall"),
    _p("collective_retries", int, 2, check=(">=", 0),
       desc="bounded retries for host collectives that raise transport "
            "errors (timeouts are never retried — the pairing is lost)"),
    _p("restart_max_retries", int, 2, check=(">=", 0),
       desc="launcher (parallel.train_distributed): cohort respawns "
            "after a rank failure before giving up"),
    _p("restart_backoff", float, 1.0, check=(">=", 0.0),
       desc="launcher: base seconds of exponential backoff between "
            "cohort respawns (base * 2^attempt)"),
]

_BY_NAME: Dict[str, _Param] = {p.name: p for p in _PARAMS}


def param_default(name: str) -> Any:
    """Registered default of one parameter — the single source of truth
    for constructor knobs that mirror config keys (PredictionService's
    serve_* admission-control defaults) without paying a full Config
    construction (and its global log-level side effect) per lookup."""
    return _BY_NAME[name].default

PARAM_ALIASES: Dict[str, str] = {}
for _param in _PARAMS:
    for _a in _param.aliases:
        PARAM_ALIASES[_a] = _param.name

_OBJECTIVE_ALIASES = {
    "regression": "regression", "regression_l2": "regression", "l2": "regression",
    "mean_squared_error": "regression", "mse": "regression",
    "l2_root": "regression", "root_mean_squared_error": "regression",
    "rmse": "regression",
    "regression_l1": "regression_l1", "l1": "regression_l1",
    "mean_absolute_error": "regression_l1", "mae": "regression_l1",
    "huber": "huber", "fair": "fair", "poisson": "poisson",
    "quantile": "quantile", "mape": "mape",
    "mean_absolute_percentage_error": "mape",
    "gamma": "gamma", "tweedie": "tweedie",
    "binary": "binary",
    "multiclass": "multiclass", "softmax": "multiclass",
    "multiclassova": "multiclassova", "multiclass_ova": "multiclassova",
    "ova": "multiclassova", "ovr": "multiclassova",
    "cross_entropy": "cross_entropy", "xentropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda", "xentlambda": "cross_entropy_lambda",
    "lambdarank": "lambdarank",
    "rank_xendcg": "rank_xendcg", "xendcg": "rank_xendcg", "xe_ndcg": "rank_xendcg",
    "xe_ndcg_mart": "rank_xendcg", "xendcg_mart": "rank_xendcg",
    "none": "none", "null": "none", "custom": "none", "na": "none",
}


def _coerce(p: _Param, value: Any) -> Any:
    if p.ptype is bool:
        if isinstance(value, str):
            return value.lower() in ("true", "1", "+", "yes")
        return bool(value)
    if p.ptype is int:
        return int(float(value)) if isinstance(value, str) else int(value)
    if p.ptype is float:
        return float(value)
    if p.ptype is list:
        if isinstance(value, str):
            if not value:
                return []
            return [_auto_num(v) for v in value.split(",")]
        if isinstance(value, (list, tuple)):
            return list(value)
        return [value]
    return str(value)


def _auto_num(s: str) -> Any:
    s = s.strip()
    try:
        f = float(s)
        return int(f) if f == int(f) and "." not in s and "e" not in s.lower() else f
    except ValueError:
        return s


def _check(p: _Param, value: Any) -> None:
    for chk in (p.check, p.check2):
        if chk is None or not isinstance(value, (int, float)):
            continue
        op, bound = chk
        ok = {"<": value < bound, "<=": value <= bound,
              ">": value > bound, ">=": value >= bound}[op]
        if not ok:
            log.fatal("Parameter %s should be %s %s; got %s", p.name, op, bound, value)


class Config:
    """Resolved training configuration.

    Usage: ``cfg = Config({"num_leaves": 63, "eta": 0.05})``; attribute access
    returns resolved values (``cfg.learning_rate == 0.05``).
    """

    def __init__(self, params: Optional[Dict[str, Any]] = None):
        # copy list defaults so in-place mutation can't corrupt the registry
        self._values: Dict[str, Any] = {
            p.name: (list(p.default) if p.ptype is list else p.default)
            for p in _PARAMS}
        self.unknown: Dict[str, Any] = {}
        self._user_set: set = set()
        if params:
            self.update(params)
        else:
            self._post_process()

    # -- alias resolution (ref: config.cpp:45 KeyAliasTransform) --
    @staticmethod
    def resolve_key(key: str) -> str:
        key = key.strip().replace("-", "_")
        return PARAM_ALIASES.get(key, key)

    def update(self, params: Dict[str, Any]) -> None:
        for raw_key, value in params.items():
            key = self.resolve_key(raw_key)
            if value is None:
                continue
            p = _BY_NAME.get(key)
            if p is None:
                self.unknown[key] = value
                continue
            v = _coerce(p, value)
            _check(p, v)
            self._values[key] = v
            self._user_set.add(key)
        self._post_process()

    def _post_process(self) -> None:
        # Objective alias resolution + derived flags
        # (ref: config.cpp:193 Config::Set derived is_parallel etc.)
        obj = str(self._values["objective"]).lower()
        self._values["objective"] = _OBJECTIVE_ALIASES.get(obj, obj)
        tl = self._values["tree_learner"]
        tl_alias = {"serial": "serial", "feature": "feature",
                    "feature_parallel": "feature", "data": "data",
                    "data_parallel": "data", "voting": "voting",
                    "voting_parallel": "voting"}
        self._values["tree_learner"] = tl_alias.get(tl, tl)
        self.is_parallel = self._values["tree_learner"] != "serial"
        self.is_data_based_parallel = self._values["tree_learner"] in ("data", "voting")
        if self._values["verbosity"] < 0:
            log.set_log_level(log.LogLevel.WARNING if self._values["verbosity"] == -1
                              else log.LogLevel.FATAL)
        elif self._values["verbosity"] == 0:
            log.set_log_level(log.LogLevel.WARNING)
        elif self._values["verbosity"] == 1:
            log.set_log_level(log.LogLevel.INFO)
        else:
            log.set_log_level(log.LogLevel.DEBUG)

    def was_set(self, key: str) -> bool:
        return self.resolve_key(key) in self._user_set

    def __getattr__(self, name: str) -> Any:
        values = self.__dict__.get("_values")
        if values is not None and name in values:
            return values[name]
        raise AttributeError(name)

    def __getitem__(self, name: str) -> Any:
        return self._values[self.resolve_key(name)]

    def set(self, name: str, value: Any) -> None:
        self.update({name: value})

    def to_dict(self) -> Dict[str, Any]:
        d = dict(self._values)
        d.update(self.unknown)
        return d

    @staticmethod
    def kv2map(args: List[str]) -> Dict[str, str]:
        """Parse CLI ``k=v`` tokens (ref: config.cpp:16 KV2Map)."""
        out: Dict[str, str] = {}
        for arg in args:
            if "=" not in arg:
                continue
            k, v = arg.split("=", 1)
            out[k.strip()] = v.strip()
        return out


def param_docs() -> str:
    """Render parameter documentation (analog of generated Parameters.rst)."""
    lines = []
    for p in _PARAMS:
        alias = f" (aliases: {', '.join(p.aliases)})" if p.aliases else ""
        chk = ""
        if p.check:
            chk = f", constraint: {p.check[0]} {p.check[1]}"
        lines.append(f"- ``{p.name}``{alias}: {p.ptype.__name__}, "
                     f"default={p.default!r}{chk}. {p.desc}")
    return "\n".join(lines)
