"""OpenMetrics/Prometheus HTTP exporter for the live Telemetry registry.

Everything the registry records — counters, gauges, timing
distributions, ``dist()`` quantile rings — becomes scrapeable from the
RUNNING process the moment ``metrics_port=<p>`` is set (config/CLI key
for training, constructor knob for ``PredictionService``): a stdlib
``http.server`` on a daemon thread renders a fresh snapshot per GET, so
dashboards watch a multi-chip train job or a serving fleet live instead
of post-hoc JSONL archaeology.

Exposition (docs/Observability.md §10):

- counters  → ``lgbm_<name>_total``   (OpenMetrics ``counter``)
- gauges    → ``lgbm_<name>``         (``gauge``; per-device memory
  lands here as ``lgbm_mem_d<id>_bytes_in_use`` etc.)
- timings   → ``lgbm_<name>_seconds`` (``summary``: ``_count``/``_sum``
  plus ``_min``/``_max`` gauges)
- dists     → ``lgbm_<name>``         (``summary`` with ``quantile``
  labels 0.5/0.95/0.99 off the bounded sample ring)

Every series carries ``rank`` and ``run_id`` labels.  Endpoints:
``/metrics`` (the local registry; on rank 0 the fleet counter series —
fed by the health auditor's existing allgather, zero new collectives —
are appended with their origin rank's label), ``/healthz`` (liveness)
and ``/readyz`` (readiness: 503 until the owner's ``ready_check``
passes — a PredictionService is ready only after ``warmup()`` compiled
its buckets and flips unready during a rollover swap window, so
external load balancers can drain correctly; exporters without a check
report ready).

Port discipline: under the multiproc launcher each rank binds
``metrics_port + rank``.  A port already in use degrades to an
ephemeral port with a structured ``metrics_exporter`` event (never an
exception into training), so two boosters in one process — or a test
runner racing itself — cannot crash a run over a TCP bind.

Control plane (docs/Observability.md §12): beyond the scrape path the
exporter is the RUNNING job's control surface —

- ``GET /snapshot`` — the FULL registry snapshot (counters, gauges,
  timings, dists, event + finding rings) as JSON; the on-demand deep
  view ``/metrics`` deliberately omits;
- ``POST /profile?iters=N[&dir=...]`` — arm a bounded ``jax.profiler``
  window that the driver opens at its next megastep drain boundary
  (iteration edge on the sync driver) and closes N iterations later at
  the following boundary.  Arming while a window is armed, open, or a
  ``profile_dir`` config window is pending answers 409 (overlap
  refusal); arming never dispatches — the driver only reads a flag at
  sync points it already owns, which is the counter-asserted
  dispatch-neutrality contract ``bench.py --micro`` gates;
- ``GET /report`` — the consolidated run report (obs/report.py) built
  from the live registry, same schema as the ``run_report_out``
  artifact;
- ``GET /alerts`` — the SLO plane's alert view (obs/slo.py): active
  alerts, per-objective status, burn rates and the recent transition
  history; 404 until an SloEngine is armed (``slo_enabled`` /
  ``slo_config``);
- ``GET /roofline`` — the roofline plane's latest measured view
  (obs/kernelstats.py): per-executable device times joined to their
  analytic cost entries, join coverage, top kernels.  404 until a
  profile window has closed and parsed (arm one with
  ``POST /profile``).

``/metrics`` bodies are cached for ``cache_ttl`` (~1 s): a tight
external scrape loop re-reads the cached rendering instead of
contending the training/serving worker threads on the registry lock;
``/snapshot`` and ``/report`` are on-demand and never cached.
"""
from __future__ import annotations

import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

from ..utils import log

CONTENT_TYPE = ("application/openmetrics-text; version=1.0.0; "
                "charset=utf-8")
_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
_QUANTILES = (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99"))


def _metric_name(name: str, prefix: str = "lgbm_") -> str:
    out = prefix + _NAME_RE.sub("_", str(name))
    if out[0].isdigit():
        out = "_" + out
    return out


def _fmt_labels(labels: Dict[str, Any]) -> str:
    if not labels:
        return ""
    parts = []
    for k in sorted(labels):
        v = str(labels[k]).replace("\\", r"\\").replace('"', r'\"') \
            .replace("\n", r"\n")
        parts.append(f'{k}="{v}"')
    return "{" + ",".join(parts) + "}"


def _num(v: Any) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def build_info_labels() -> Dict[str, Any]:
    """Deploy-identifying labels for the ``lgbm_build_info`` series:
    package version, jax version and active backend.  Cheap host
    lookups, computed once per exporter."""
    info: Dict[str, Any] = {}
    try:
        from .. import __version__
        info["version"] = __version__
    except Exception:
        info["version"] = "unknown"
    try:
        import jax
        info["jax_version"] = getattr(jax, "__version__", "unknown")
        info["backend"] = jax.default_backend()
    except Exception:
        info.setdefault("jax_version", "unknown")
        info.setdefault("backend", "unknown")
    return info


def render_openmetrics(snapshot: Dict[str, Any],
                       labels: Optional[Dict[str, Any]] = None,
                       fleet: Optional[List[Dict[str, Any]]] = None,
                       build_info: Optional[Dict[str, Any]] = None
                       ) -> str:
    """Registry snapshot (Telemetry.snapshot schema) → OpenMetrics
    exposition text.  ``fleet`` entries (``{"rank": r, "counters":
    {...}}``) add per-rank counter series under the same families —
    the aggregated view rank 0 serves for the whole cohort.
    ``build_info`` labels add a constant ``lgbm_build_info 1`` series
    so scrapes are joinable across deploys."""
    labels = dict(labels or {})
    lines: List[str] = []
    local_rank = labels.get("rank")

    counters = snapshot.get("counters", {})
    fleet = [e for e in (fleet or [])
             if isinstance(e.get("counters"), dict)
             and e.get("rank") != local_rank]
    fleet_names = {n for e in fleet for n in e["counters"]}
    for name in sorted(set(counters) | fleet_names):
        m = _metric_name(name)
        lines.append(f"# TYPE {m} counter")
        if name in counters:
            lines.append(f"{m}_total{_fmt_labels(labels)} "
                         f"{_num(counters[name])}")
        for e in fleet:
            if name in e["counters"]:
                lab = dict(labels, rank=e.get("rank"))
                lab.pop("run_id", None)   # peers' run ids aren't ours
                lines.append(f"{m}_total{_fmt_labels(lab)} "
                             f"{_num(e['counters'][name])}")

    for name, v in sorted(snapshot.get("gauges", {}).items()):
        m = _metric_name(name)
        lines.append(f"# TYPE {m} gauge")
        lines.append(f"{m}{_fmt_labels(labels)} {_num(v)}")

    for name, t in sorted(snapshot.get("timings", {}).items()):
        m = _metric_name(name) + "_seconds"
        lines.append(f"# TYPE {m} summary")
        lines.append(f"{m}_count{_fmt_labels(labels)} "
                     f"{_num(t.get('count', 0))}")
        lines.append(f"{m}_sum{_fmt_labels(labels)} "
                     f"{_num(t.get('total', 0.0))}")
        for stat in ("min", "max"):
            if stat in t and t[stat] not in (float("inf"),):
                g = m + "_" + stat
                lines.append(f"# TYPE {g} gauge")
                lines.append(f"{g}{_fmt_labels(labels)} {_num(t[stat])}")

    for name, d in sorted(snapshot.get("dists", {}).items()):
        m = _metric_name(name)
        lines.append(f"# TYPE {m} summary")
        # quantile series render only off a non-empty sample ring: a
        # fresh distribution (count 0, or a drained ring) exposes
        # count/sum alone — a scraper must never see NaN quantiles
        if d.get("count", 0) > 0:
            for qlabel, key in _QUANTILES:
                if key in d:
                    lab = dict(labels, quantile=qlabel)
                    lines.append(f"{m}{_fmt_labels(lab)} {_num(d[key])}")
        lines.append(f"{m}_count{_fmt_labels(labels)} "
                     f"{_num(d.get('count', 0))}")
        if "sum" in d:
            lines.append(f"{m}_sum{_fmt_labels(labels)} "
                         f"{_num(d['sum'])}")

    if build_info:
        lab = dict(labels)
        lab.update(build_info)
        lines.append("# TYPE lgbm_build_info gauge")
        lines.append(f"lgbm_build_info{_fmt_labels(lab)} 1")

    lines.append("# EOF")
    return "\n".join(lines) + "\n"


class ProfileControl:
    """Thread-safe handoff of on-demand profiling requests between the
    HTTP control plane (exporter daemon threads) and the training
    driver (which polls at drain boundaries / iteration edges — the
    sync points it already owns, so an armed-but-idle request costs
    zero device dispatches).

    State machine: idle -> armed (``arm``) -> busy (driver ``take``
    opens the window) -> idle (``done`` when the window closes).
    ``arm`` refuses overlap: a second request while armed or busy —
    or while the owner's ``conflict_check`` reports a pending
    ``profile_dir`` config window — returns ``(False, reason)``.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._armed: Optional[Dict[str, Any]] = None
        self._busy = False
        # owner-installed () -> Optional[str]: non-None names a
        # conflicting profiling source (e.g. the profile_dir window)
        self.conflict_check = None

    def arm(self, iters: int, log_dir: str = ""
            ) -> Tuple[bool, str, Dict[str, Any]]:
        iters = int(iters)
        if iters <= 0:
            return False, "iters must be >= 1", {}
        chk = self.conflict_check
        conflict = None
        if chk is not None:
            try:
                conflict = chk()
            except Exception:
                conflict = None
        with self._lock:
            if self._armed is not None:
                return False, "profile window already armed", {}
            if self._busy:
                return False, "profile window already open", {}
            if conflict:
                return False, conflict, {}
            # no default dir is minted HERE: a request armed against a
            # finished job (no boundary ever fires — the bench's
            # armed-but-untriggered leg does this on purpose) must not
            # leak a directory per POST; the driver mkdtemps when the
            # window actually opens and reports it on the
            # profile_window open/closed events
            req = {"iters": iters, "dir": str(log_dir or ""),
                   "armed_ts": time.time()}
            self._armed = req
            return True, "armed", dict(req)

    def take(self) -> Optional[Dict[str, Any]]:
        """Driver side: claim the armed request (marks the control busy
        until ``done``)."""
        with self._lock:
            req, self._armed = self._armed, None
            if req is not None:
                self._busy = True
            return req

    def done(self) -> None:
        with self._lock:
            self._busy = False

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {"armed": dict(self._armed) if self._armed else None,
                    "open": self._busy}


class _Handler(BaseHTTPRequestHandler):
    # the exporter must never block a scrape behind a slow peer
    timeout = 10
    exporter: "MetricsExporter" = None   # class attr set per server

    def _send(self, code: int, body: bytes,
              ctype: str = "text/plain") -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, obj: Any) -> None:
        self._send(code, (json.dumps(obj, default=str) + "\n")
                   .encode("utf-8"), "application/json")

    def do_GET(self) -> None:  # noqa: N802 (http.server API)
        path = self.path.split("?", 1)[0]
        if path in ("/metrics", "/", "/metrics/"):
            try:
                body = self.exporter.render_cached().encode("utf-8")
            except Exception as e:   # a scrape bug must not kill serving
                self.send_error(500, str(e)[:200])
                return
            self._send(200, body, CONTENT_TYPE)
        elif path == "/snapshot":
            # the FULL registry view (incl. event/finding rings) as
            # JSON — on demand only, so the deep copy under the lock is
            # an operator's choice, never a scrape loop's side effect
            try:
                snap = self.exporter.telemetry.snapshot()
                snap["run_id"] = self.exporter.telemetry.run_id
                snap["profile"] = (
                    self.exporter.profile_control.status()
                    if self.exporter.profile_control is not None
                    else None)
            except Exception as e:
                self.send_error(500, str(e)[:200])
                return
            self._send_json(200, snap)
        elif path == "/report":
            fn = self.exporter.report_fn
            if fn is None:
                self._send_json(404, {"error": "no report source "
                                               "attached"})
                return
            try:
                rep = fn()
            except Exception as e:
                self.send_error(500, str(e)[:200])
                return
            self._send_json(200, rep)
        elif path == "/alerts":
            fn = self.exporter.alerts_fn
            if fn is None:
                self._send_json(404, {"error": "no slo engine attached "
                                               "(set slo_enabled or "
                                               "slo_config)"})
                return
            try:
                payload = fn()
            except Exception as e:
                self.send_error(500, str(e)[:200])
                return
            self._send_json(200, payload)
        elif path == "/roofline":
            fn = self.exporter.roofline_fn
            if fn is None:
                self._send_json(404, {"error": "no roofline source "
                                               "attached"})
                return
            try:
                payload = fn()
            except Exception as e:
                self.send_error(500, str(e)[:200])
                return
            if payload is None:
                self._send_json(404, {"error": "no profile window "
                                               "parsed yet (arm one "
                                               "with POST /profile)"})
                return
            self._send_json(200, payload)
        elif path == "/healthz":
            body = b"ok\n"
            self.send_response(200)
            self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif path == "/readyz":
            # readiness is distinct from liveness: a serving process is
            # alive the moment the exporter binds, but an external load
            # balancer must not route to it until warmup() compiled the
            # buckets — and must drain it during a rollover swap window.
            # Exporters without a ready_check (training) report ready.
            chk = self.exporter.ready_check
            try:
                ok, reason = (True, "ready") if chk is None else chk()
            except Exception as e:    # a probe bug reads as unready
                ok, reason = False, f"ready_check failed: {e}"
            body = (str(reason) + "\n").encode("utf-8")
            self.send_response(200 if ok else 503)
            self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self.send_error(404)

    def do_POST(self) -> None:  # noqa: N802 (http.server API)
        path, _, query = self.path.partition("?")
        if path != "/profile":
            self.send_error(404)
            return
        ctl = self.exporter.profile_control
        if ctl is None:
            self._send_json(404, {"error": "no profile control attached "
                                           "(serving exporters and bare "
                                           "registries do not profile)"})
            return
        from urllib.parse import parse_qs
        qs = parse_qs(query, keep_blank_values=True)
        params = {k: v[-1] for k, v in qs.items()}
        try:
            iters = int(params.get("iters", "1"))
        except ValueError:
            self._send_json(400, {"error": "iters must be an integer"})
            return
        ok, reason, req = ctl.arm(iters, params.get("dir", ""))
        tel = self.exporter.telemetry
        if not ok:
            # overlap refusal is a first-class, structured outcome: the
            # 409 carries the reason and the registry records it
            tel.event("profile_window", state="refused", reason=reason,
                      iters=iters)
            self._send_json(409, {"armed": False, "reason": reason})
            return
        tel.event("profile_window", state="armed", iters=req["iters"],
                  dir=req["dir"])
        self._send_json(200, {"armed": True, "iters": req["iters"],
                              "dir": req["dir"]})

    def log_message(self, fmt, *args) -> None:   # silence per-scrape spam
        pass


class MetricsExporter:
    """Daemon-thread OpenMetrics endpoint over one Telemetry registry."""

    def __init__(self, telemetry, port: int, host: str = "127.0.0.1",
                 extra_labels: Optional[Dict[str, Any]] = None,
                 ready_check=None, profile_control=None, report_fn=None,
                 alerts_fn=None, roofline_fn=None,
                 cache_ttl: float = 1.0):
        self.telemetry = telemetry
        self.requested_port = int(port)
        self.host = host
        self.extra_labels = dict(extra_labels or {})
        # () -> (ok, reason) readiness probe behind GET /readyz; None =
        # always ready (liveness == readiness, the training exporter)
        self.ready_check = ready_check
        # control-plane hooks: the on-demand profiling handoff (POST
        # /profile — training drivers install one) and the run-report
        # source (GET /report)
        self.profile_control = profile_control
        self.report_fn = report_fn
        # the SLO plane's alert view (GET /alerts) — an SloEngine's
        # alerts_payload when one is armed, else 404
        self.alerts_fn = alerts_fn
        # the roofline plane's latest parsed window (GET /roofline) —
        # None until a profile window closes, then the kernelstats
        # join_cost record of the most recent one
        self.roofline_fn = roofline_fn
        self.build_info = build_info_labels()
        # /metrics body cache: a tight external scrape loop re-reads
        # the cached rendering for cache_ttl seconds instead of
        # re-snapshotting the registry under its lock per request
        self.cache_ttl = float(cache_ttl)
        self.cache_hits = 0
        self._cache_lock = threading.Lock()
        self._cache_body: Optional[str] = None
        self._cache_ts = 0.0
        self.port: Optional[int] = None
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    def render(self) -> str:
        tel = self.telemetry
        labels = {"rank": tel.rank, "run_id": tel.run_id}
        labels.update(self.extra_labels)
        fleet = tel.fleet_counters() if tel.rank == 0 else None
        # scrape-staleness feed for the SLO plane: the gauge records
        # when /metrics last produced a fresh body (TTL-cached re-reads
        # don't move it, which bounds its resolution at cache_ttl)
        tel.gauge("export.last_scrape_ts", tel.wall_now())
        # the events-free view: a scrape must not deep-copy the event
        # rings under the registry lock (metrics_snapshot docstring)
        return render_openmetrics(tel.metrics_snapshot(), labels, fleet,
                                  build_info=self.build_info)

    def render_cached(self) -> str:
        """The /metrics serving path: one fresh render per ``cache_ttl``
        window, shared by every scraper that lands inside it.  The TTL
        bounds staleness at ~1 s — negligible against the 15 s scrape
        intervals time-series stores use, and the price of making a
        scrape storm contention-free."""
        ttl = self.cache_ttl
        if ttl <= 0:
            return self.render()
        now = time.monotonic()
        with self._cache_lock:
            if self._cache_body is not None \
                    and now - self._cache_ts < ttl:
                self.cache_hits += 1
                return self._cache_body
        body = self.render()
        with self._cache_lock:
            self._cache_body = body
            self._cache_ts = time.monotonic()
        return body

    @property
    def url(self) -> Optional[str]:
        if self.port is None:
            return None
        return f"http://{self.host}:{self.port}/metrics"

    # ------------------------------------------------------------------
    def start(self) -> int:
        """Bind and serve; returns the ACTUAL port.  A port in use
        degrades to an ephemeral bind with a structured
        ``metrics_exporter`` event — observability must never be the
        reason a training run dies on a TCP race."""
        if self._httpd is not None:
            return self.port
        fallback = False
        try:
            httpd = ThreadingHTTPServer((self.host, self.requested_port),
                                        self._handler_class())
        except OSError as e:
            fallback = True
            reason = f"{type(e).__name__}: {e}"
            try:
                httpd = ThreadingHTTPServer((self.host, 0),
                                            self._handler_class())
            except OSError as e2:   # no bindable port at all: degrade off
                log.warning("metrics exporter could not bind %s:%d (%s) "
                            "nor an ephemeral port (%s); exporter off",
                            self.host, self.requested_port, e, e2)
                self.telemetry.event(
                    "metrics_exporter", port=None,
                    requested_port=self.requested_port,
                    fallback=True, error=f"{type(e2).__name__}: {e2}")
                return -1
        httpd.daemon_threads = True
        self._httpd = httpd
        self.port = int(httpd.server_address[1])
        self._thread = threading.Thread(
            target=httpd.serve_forever, name="lgbm-metrics-exporter",
            daemon=True)
        self._thread.start()
        ev = {"port": self.port, "requested_port": self.requested_port,
              "fallback": fallback}
        if fallback:
            ev["error"] = reason
            log.warning("metrics port %d in use; exporter fell back to "
                        "%s:%d", self.requested_port, self.host,
                        self.port)
        self.telemetry.event("metrics_exporter", **ev)
        log.info("OpenMetrics endpoint: %s", self.url)
        return self.port

    def _handler_class(self):
        # one handler subclass per exporter so concurrent exporters
        # (training + serving in one process) don't share state
        return type("_BoundHandler", (_Handler,), {"exporter": self})

    def stop(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is None:
            return
        try:
            httpd.shutdown()
            httpd.server_close()
        except Exception:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


def scrape(url: str, timeout: float = 5.0) -> Tuple[str, str]:
    """Convenience GET (tests, bench, obs_tail --scrape): returns
    ``(content_type, body)``."""
    from urllib.request import urlopen
    with urlopen(url, timeout=timeout) as resp:
        return (resp.headers.get("Content-Type", ""),
                resp.read().decode("utf-8"))


def post(url: str, timeout: float = 5.0) -> Tuple[int, Dict[str, Any]]:
    """Convenience POST against the control endpoints (tests, bench):
    returns ``(status, parsed JSON body)`` — a 4xx answer (e.g. the 409
    overlap refusal) is a RESULT here, not an exception."""
    from urllib.error import HTTPError
    from urllib.request import Request, urlopen
    req = Request(url, data=b"", method="POST")
    try:
        with urlopen(req, timeout=timeout) as resp:
            return (resp.status,
                    json.loads(resp.read().decode("utf-8")))
    except HTTPError as e:
        try:
            return e.code, json.loads(e.read().decode("utf-8"))
        except Exception:
            return e.code, {}
