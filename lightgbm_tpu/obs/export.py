"""OpenMetrics/Prometheus HTTP exporter for the live Telemetry registry.

Everything the registry records — counters, gauges, timing
distributions, ``dist()`` quantile rings — becomes scrapeable from the
RUNNING process the moment ``metrics_port=<p>`` is set (config/CLI key
for training, constructor knob for ``PredictionService``): a stdlib
``http.server`` on a daemon thread renders a fresh snapshot per GET, so
dashboards watch a multi-chip train job or a serving fleet live instead
of post-hoc JSONL archaeology.

Exposition (docs/Observability.md §10):

- counters  → ``lgbm_<name>_total``   (OpenMetrics ``counter``)
- gauges    → ``lgbm_<name>``         (``gauge``; per-device memory
  lands here as ``lgbm_mem_d<id>_bytes_in_use`` etc.)
- timings   → ``lgbm_<name>_seconds`` (``summary``: ``_count``/``_sum``
  plus ``_min``/``_max`` gauges)
- dists     → ``lgbm_<name>``         (``summary`` with ``quantile``
  labels 0.5/0.95/0.99 off the bounded sample ring)

Every series carries ``rank`` and ``run_id`` labels.  Endpoints:
``/metrics`` (the local registry; on rank 0 the fleet counter series —
fed by the health auditor's existing allgather, zero new collectives —
are appended with their origin rank's label), ``/healthz`` (liveness)
and ``/readyz`` (readiness: 503 until the owner's ``ready_check``
passes — a PredictionService is ready only after ``warmup()`` compiled
its buckets and flips unready during a rollover swap window, so
external load balancers can drain correctly; exporters without a check
report ready).

Port discipline: under the multiproc launcher each rank binds
``metrics_port + rank``.  A port already in use degrades to an
ephemeral port with a structured ``metrics_exporter`` event (never an
exception into training), so two boosters in one process — or a test
runner racing itself — cannot crash a run over a TCP bind.
"""
from __future__ import annotations

import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

from ..utils import log

CONTENT_TYPE = ("application/openmetrics-text; version=1.0.0; "
                "charset=utf-8")
_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
_QUANTILES = (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99"))


def _metric_name(name: str, prefix: str = "lgbm_") -> str:
    out = prefix + _NAME_RE.sub("_", str(name))
    if out[0].isdigit():
        out = "_" + out
    return out


def _fmt_labels(labels: Dict[str, Any]) -> str:
    if not labels:
        return ""
    parts = []
    for k in sorted(labels):
        v = str(labels[k]).replace("\\", r"\\").replace('"', r'\"') \
            .replace("\n", r"\n")
        parts.append(f'{k}="{v}"')
    return "{" + ",".join(parts) + "}"


def _num(v: Any) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render_openmetrics(snapshot: Dict[str, Any],
                       labels: Optional[Dict[str, Any]] = None,
                       fleet: Optional[List[Dict[str, Any]]] = None
                       ) -> str:
    """Registry snapshot (Telemetry.snapshot schema) → OpenMetrics
    exposition text.  ``fleet`` entries (``{"rank": r, "counters":
    {...}}``) add per-rank counter series under the same families —
    the aggregated view rank 0 serves for the whole cohort."""
    labels = dict(labels or {})
    lines: List[str] = []
    local_rank = labels.get("rank")

    counters = snapshot.get("counters", {})
    fleet = [e for e in (fleet or [])
             if isinstance(e.get("counters"), dict)
             and e.get("rank") != local_rank]
    fleet_names = {n for e in fleet for n in e["counters"]}
    for name in sorted(set(counters) | fleet_names):
        m = _metric_name(name)
        lines.append(f"# TYPE {m} counter")
        if name in counters:
            lines.append(f"{m}_total{_fmt_labels(labels)} "
                         f"{_num(counters[name])}")
        for e in fleet:
            if name in e["counters"]:
                lab = dict(labels, rank=e.get("rank"))
                lab.pop("run_id", None)   # peers' run ids aren't ours
                lines.append(f"{m}_total{_fmt_labels(lab)} "
                             f"{_num(e['counters'][name])}")

    for name, v in sorted(snapshot.get("gauges", {}).items()):
        m = _metric_name(name)
        lines.append(f"# TYPE {m} gauge")
        lines.append(f"{m}{_fmt_labels(labels)} {_num(v)}")

    for name, t in sorted(snapshot.get("timings", {}).items()):
        m = _metric_name(name) + "_seconds"
        lines.append(f"# TYPE {m} summary")
        lines.append(f"{m}_count{_fmt_labels(labels)} "
                     f"{_num(t.get('count', 0))}")
        lines.append(f"{m}_sum{_fmt_labels(labels)} "
                     f"{_num(t.get('total', 0.0))}")
        for stat in ("min", "max"):
            if stat in t and t[stat] not in (float("inf"),):
                g = m + "_" + stat
                lines.append(f"# TYPE {g} gauge")
                lines.append(f"{g}{_fmt_labels(labels)} {_num(t[stat])}")

    for name, d in sorted(snapshot.get("dists", {}).items()):
        m = _metric_name(name)
        lines.append(f"# TYPE {m} summary")
        for qlabel, key in _QUANTILES:
            if key in d:
                lab = dict(labels, quantile=qlabel)
                lines.append(f"{m}{_fmt_labels(lab)} {_num(d[key])}")
        lines.append(f"{m}_count{_fmt_labels(labels)} "
                     f"{_num(d.get('count', 0))}")
        if "sum" in d:
            lines.append(f"{m}_sum{_fmt_labels(labels)} "
                         f"{_num(d['sum'])}")

    lines.append("# EOF")
    return "\n".join(lines) + "\n"


class _Handler(BaseHTTPRequestHandler):
    # the exporter must never block a scrape behind a slow peer
    timeout = 10
    exporter: "MetricsExporter" = None   # class attr set per server

    def do_GET(self) -> None:  # noqa: N802 (http.server API)
        path = self.path.split("?", 1)[0]
        if path in ("/metrics", "/", "/metrics/"):
            try:
                body = self.exporter.render().encode("utf-8")
            except Exception as e:   # a scrape bug must not kill serving
                self.send_error(500, str(e)[:200])
                return
            self.send_response(200)
            self.send_header("Content-Type", CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif path == "/healthz":
            body = b"ok\n"
            self.send_response(200)
            self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif path == "/readyz":
            # readiness is distinct from liveness: a serving process is
            # alive the moment the exporter binds, but an external load
            # balancer must not route to it until warmup() compiled the
            # buckets — and must drain it during a rollover swap window.
            # Exporters without a ready_check (training) report ready.
            chk = self.exporter.ready_check
            try:
                ok, reason = (True, "ready") if chk is None else chk()
            except Exception as e:    # a probe bug reads as unready
                ok, reason = False, f"ready_check failed: {e}"
            body = (str(reason) + "\n").encode("utf-8")
            self.send_response(200 if ok else 503)
            self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self.send_error(404)

    def log_message(self, fmt, *args) -> None:   # silence per-scrape spam
        pass


class MetricsExporter:
    """Daemon-thread OpenMetrics endpoint over one Telemetry registry."""

    def __init__(self, telemetry, port: int, host: str = "127.0.0.1",
                 extra_labels: Optional[Dict[str, Any]] = None,
                 ready_check=None):
        self.telemetry = telemetry
        self.requested_port = int(port)
        self.host = host
        self.extra_labels = dict(extra_labels or {})
        # () -> (ok, reason) readiness probe behind GET /readyz; None =
        # always ready (liveness == readiness, the training exporter)
        self.ready_check = ready_check
        self.port: Optional[int] = None
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    def render(self) -> str:
        tel = self.telemetry
        labels = {"rank": tel.rank, "run_id": tel.run_id}
        labels.update(self.extra_labels)
        fleet = tel.fleet_counters() if tel.rank == 0 else None
        # the events-free view: a scrape must not deep-copy the event
        # rings under the registry lock (metrics_snapshot docstring)
        return render_openmetrics(tel.metrics_snapshot(), labels, fleet)

    @property
    def url(self) -> Optional[str]:
        if self.port is None:
            return None
        return f"http://{self.host}:{self.port}/metrics"

    # ------------------------------------------------------------------
    def start(self) -> int:
        """Bind and serve; returns the ACTUAL port.  A port in use
        degrades to an ephemeral bind with a structured
        ``metrics_exporter`` event — observability must never be the
        reason a training run dies on a TCP race."""
        if self._httpd is not None:
            return self.port
        fallback = False
        try:
            httpd = ThreadingHTTPServer((self.host, self.requested_port),
                                        self._handler_class())
        except OSError as e:
            fallback = True
            reason = f"{type(e).__name__}: {e}"
            try:
                httpd = ThreadingHTTPServer((self.host, 0),
                                            self._handler_class())
            except OSError as e2:   # no bindable port at all: degrade off
                log.warning("metrics exporter could not bind %s:%d (%s) "
                            "nor an ephemeral port (%s); exporter off",
                            self.host, self.requested_port, e, e2)
                self.telemetry.event(
                    "metrics_exporter", port=None,
                    requested_port=self.requested_port,
                    fallback=True, error=f"{type(e2).__name__}: {e2}")
                return -1
        httpd.daemon_threads = True
        self._httpd = httpd
        self.port = int(httpd.server_address[1])
        self._thread = threading.Thread(
            target=httpd.serve_forever, name="lgbm-metrics-exporter",
            daemon=True)
        self._thread.start()
        ev = {"port": self.port, "requested_port": self.requested_port,
              "fallback": fallback}
        if fallback:
            ev["error"] = reason
            log.warning("metrics port %d in use; exporter fell back to "
                        "%s:%d", self.requested_port, self.host,
                        self.port)
        self.telemetry.event("metrics_exporter", **ev)
        log.info("OpenMetrics endpoint: %s", self.url)
        return self.port

    def _handler_class(self):
        # one handler subclass per exporter so concurrent exporters
        # (training + serving in one process) don't share state
        return type("_BoundHandler", (_Handler,), {"exporter": self})

    def stop(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is None:
            return
        try:
            httpd.shutdown()
            httpd.server_close()
        except Exception:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


def scrape(url: str, timeout: float = 5.0) -> Tuple[str, str]:
    """Convenience GET (tests, bench, obs_tail --scrape): returns
    ``(content_type, body)``."""
    from urllib.request import urlopen
    with urlopen(url, timeout=timeout) as resp:
        return (resp.headers.get("Content-Type", ""),
                resp.read().decode("utf-8"))
