"""Perfetto/Chrome-trace exporter for the telemetry span stream.

``trace_out=<path>`` turns span collection on in the registry; at
finalize the driver drains every rank's spans (an ``allgather_json``
under multi-process — span volume is a handful per iteration, bounded by
the span ring) and rank 0 writes ONE Chrome-trace JSON:

- one *process* track per rank (``pid == rank``, named ``rank <r>``),
  the timeline view GPU GBDT systems credit for their per-phase wins
  (PAPERS.md: "GPU-acceleration for Large-scale Tree Boosting");
- within a rank, threads (tids) group the span kinds: ``train`` holds
  the per-iteration span with the driver sections
  (boosting/histogram_split/tree_materialize/score_update/...) nested
  inside it, ``collectives`` holds host-allgather spans and in-jit psum
  estimate instants, ``compile`` holds XLA compile phases, ``health``
  holds the auditor's check spans;
- spans are ``ph: "X"`` complete events (ts/dur in microseconds);
  zero-duration records render as ``ph: "i"`` instants.

The output loads directly in ``chrome://tracing`` or
https://ui.perfetto.dev (see docs/Observability.md for how to read it).
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List

# stable tid assignment so every rank's tracks line up in the viewer
# ("serve" carries the per-request spans of obs/reqtrace.py)
_TRACK_ORDER = ("train", "collectives", "compile", "health", "serve")


def chrome_trace_events(per_rank_spans: List[List[Dict[str, Any]]]
                        ) -> List[Dict[str, Any]]:
    """Span dicts (registry schema: name/ts/dur/rank/track/iter/args) ->
    Chrome-trace event list, one pid per rank with named thread tracks."""
    events: List[Dict[str, Any]] = []
    for spans in per_rank_spans:
        if not spans:
            continue
        rank = int(spans[0].get("rank", 0))
        pid = rank
        events.append({"ph": "M", "pid": pid, "tid": 0,
                       "name": "process_name",
                       "args": {"name": f"rank {rank}"}})
        events.append({"ph": "M", "pid": pid, "tid": 0,
                       "name": "process_sort_index",
                       "args": {"sort_index": rank}})
        tids: Dict[str, int] = {}
        for s in spans:
            track = str(s.get("track", "train"))
            if track not in tids:
                tids[track] = (_TRACK_ORDER.index(track)
                               if track in _TRACK_ORDER
                               else len(_TRACK_ORDER)
                               + sum(t not in _TRACK_ORDER for t in tids))
                events.append({"ph": "M", "pid": pid, "tid": tids[track],
                               "name": "thread_name",
                               "args": {"name": track}})
                events.append({"ph": "M", "pid": pid, "tid": tids[track],
                               "name": "thread_sort_index",
                               "args": {"sort_index": tids[track]}})
        for s in spans:
            track = str(s.get("track", "train"))
            dur_us = float(s.get("dur", 0.0)) * 1e6
            args = dict(s.get("args") or {})
            if "iter" in s:
                args["iter"] = s["iter"]
            ev: Dict[str, Any] = {"name": str(s["name"]), "cat": track,
                                  "pid": pid, "tid": tids[track],
                                  "ts": float(s["ts"]) * 1e6}
            if dur_us > 0:
                ev["ph"] = "X"
                ev["dur"] = dur_us
            else:
                ev["ph"] = "i"
                ev["s"] = "t"
            if args:
                ev["args"] = args
            events.append(ev)
    return events


def write_trace(path: str, per_rank_spans: List[List[Dict[str, Any]]]
                ) -> str:
    """Write the Chrome-trace JSON atomically (a crash mid-dump must not
    leave a half-written file where a loadable trace was promised)."""
    doc = {"traceEvents": chrome_trace_events(per_rank_spans),
           "displayTimeUnit": "ms"}
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(doc, fh, separators=(",", ":"))
    os.replace(tmp, path)
    return path
