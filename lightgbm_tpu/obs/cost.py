"""Device-time cost ledger: analytic FLOP/byte accounting per executable.

Fourteen PRs of instrumentation measure *when* the device is busy
(dispatch counters, batch wall times, compile events) but not *what the
work is worth*: answering "how many FLOPs and HBM bytes does one
boosting iteration actually move, and what fraction does the analytic
histogram model (``hist.*`` gauges, ops/layout.hist_plane_bytes)
account for?" still required hand-joining JSONL sinks.  The ledger
closes that gap in the spirit of the accelerator cost models of
arxiv 2011.02022 and the whole-loop-on-device accounting of
arxiv 1706.08359:

- **per-executable analysis** — every fresh jit signature the drivers
  detect (megastep chunks, the per-iteration fast step, serving
  buckets) is queued here with its *abstract* operand shapes
  (``jax.ShapeDtypeStruct`` — never live buffers, so donation cannot
  invalidate the queue) and analyzed lazily OFF the dispatch path via
  ``fn.lower(...)``: ``cost_ledger="hlo"`` (default) reads
  ``Lowered.cost_analysis()`` (client-side HLO analysis, no second XLA
  compile), ``"compiled"`` reads ``lowered.compile().cost_analysis()``
  (the post-optimization executable numbers the ISSUE names — pays a
  second backend compile unless the persistent compilation cache is
  armed via ``compilation_cache_dir``);
- **per-iteration attribution** — one ``cost_ledger`` JSONL record per
  drained batch joins the executable analysis (scaled by the chunk
  length it covers) with the batch's measured wall time, the measured
  in-trace collective payload (ops/collectives.py) and the analytic
  ``hist.bytes_per_iter`` plane model, and gauges
  ``cost.flops_per_iter`` / ``cost.hlo_bytes_per_iter`` /
  ``cost.achieved_fraction`` for the exporter;
- **ground truth for the analytic model** — ``achieved_fraction`` is
  ``hist.bytes_per_iter / cost.hlo_bytes_per_iter``: the share of the
  executable's total HLO byte traffic the PR-14 analytic histogram
  model accounts for.  A layout change that moves the fraction without
  touching either model is a real attribution shift, not noise.

Honesty caveat (documented in docs/Observability.md §12): HLO cost
analysis prices custom calls (the Pallas histogram kernel) at their
operand traffic, not their internal loops — the ``hist.*`` analytic
model is the complementary in-kernel view, which is exactly why the
ledger reports both sides instead of pretending one is ground truth.

Every entry point is exception-safe and a no-op on a disabled registry:
a cost model must never be the reason a training run dies.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from ..utils import log

MODES = ("off", "hlo", "compiled")


def tree_avals(tree):
    """Pytree of arrays -> pytree of ShapeDtypeStructs (non-array leaves
    pass through).  Shape/dtype metadata stays readable even on donated
    (deleted) device buffers, so this is safe to call after dispatch."""
    import jax

    def conv(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return jax.ShapeDtypeStruct(x.shape, x.dtype)
        return x

    return jax.tree_util.tree_map(conv, tree)


def _merge_analysis(ca: Any) -> Dict[str, float]:
    """Normalize cost_analysis output: newer jax returns one dict,
    older backends a list of per-computation dicts — sum the families
    we report."""
    if isinstance(ca, dict):
        parts: List[Dict[str, Any]] = [ca]
    elif isinstance(ca, (list, tuple)):
        parts = [p for p in ca if isinstance(p, dict)]
    else:
        parts = []
    out = {"flops": 0.0, "bytes_accessed": 0.0, "transcendentals": 0.0}
    for p in parts:
        out["flops"] += float(p.get("flops", 0.0) or 0.0)
        out["bytes_accessed"] += float(p.get("bytes accessed", 0.0) or 0.0)
        out["transcendentals"] += float(p.get("transcendentals", 0.0)
                                        or 0.0)
    return out


def analyze_jit(fn, args, kwargs=None, mode: str = "hlo"
                ) -> Optional[Dict[str, float]]:
    """Cost-analyze one jitted callable against abstract args.  Returns
    ``{"flops", "bytes_accessed", "transcendentals"}`` or None when the
    backend/API cannot answer (never raises)."""
    if mode == "off":
        return None
    try:
        lowered = fn.lower(*args, **(kwargs or {}))
        if mode == "compiled":
            ca = lowered.compile().cost_analysis()
        else:
            ca = lowered.cost_analysis()
        return _merge_analysis(ca)
    except Exception as e:     # the ledger is advisory, training is not
        log.debug("cost analysis failed: %s", e)
        return None


class CostLedger:
    """Per-run executable cost bookkeeping over one Telemetry registry.

    ``note()`` is cheap (aval capture + queue append) and safe on the
    dispatch path; ``flush()`` runs the deferred analyses and is meant
    for host-sync points (megastep drain, serve warmup/post-batch);
    ``ledger_record()`` emits the per-drained-batch join.
    """

    #: executable kinds that drive the per-iteration training gauges
    TRAIN_KINDS = ("megastep", "fast_step")

    def __init__(self, tel, mode: str = "hlo"):
        self.tel = tel
        self.mode = mode if mode in MODES else "hlo"
        self._lock = threading.Lock()
        self._pending: List[Dict[str, Any]] = []
        # newest analyzed entry per kind (the megastep re-chunks near
        # horizon tails; the latest signature is the active schedule)
        self._by_kind: Dict[str, Dict[str, Any]] = {}
        self._analyzed: Dict[str, Dict[str, Any]] = {}

    @property
    def enabled(self) -> bool:
        return self.mode != "off" and self.tel is not None \
            and self.tel.enabled

    # ------------------------------------------------------------------
    def note(self, fn, args, signature: str, kind: str, scale: int = 1,
             kwargs=None, operand_bytes: int = 0, **extra: Any) -> None:
        """Queue a fresh executable signature for deferred analysis.
        ``scale`` is how many iterations (training) or rows (serving)
        one call of the executable covers."""
        if not self.enabled:
            return
        try:
            avals = tree_avals(args)
            kw_avals = tree_avals(kwargs) if kwargs else None
        except Exception as e:
            log.debug("cost aval capture failed: %s", e)
            return
        with self._lock:
            if signature in self._analyzed:
                return
            self._pending.append({
                "fn": fn, "args": avals, "kwargs": kw_avals,
                "signature": str(signature), "kind": str(kind),
                "scale": max(1, int(scale)),
                "operand_bytes": int(operand_bytes), "extra": extra})

    def flush(self) -> None:
        """Run deferred analyses (host-sync points only: fn.lower costs
        a retrace).  Emits one ``cost_executable`` event per signature —
        the record that joins against ``compile_executable`` by
        signature string."""
        if not self.enabled:
            return
        with self._lock:
            pending, self._pending = self._pending, []
        for ent in pending:
            ca = analyze_jit(ent["fn"], ent["args"], ent["kwargs"],
                             self.mode)
            if ca is None:
                self.tel.inc("cost.analysis_failed")
                continue
            rec = {"signature": ent["signature"], "kind": ent["kind"],
                   "scale": ent["scale"],
                   "operand_bytes": ent["operand_bytes"],
                   "flops": ca["flops"],
                   "hlo_bytes": ca["bytes_accessed"],
                   "transcendentals": ca["transcendentals"],
                   "mode": self.mode}
            with self._lock:
                self._analyzed[ent["signature"]] = rec
                self._by_kind[ent["kind"]] = rec
            self.tel.inc("cost.executables")
            self.tel.event("cost_executable", **dict(rec, **ent["extra"]))

    # ------------------------------------------------------------------
    def active_train_entry(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            for kind in self.TRAIN_KINDS:
                if kind in self._by_kind:
                    return dict(self._by_kind[kind])
        return None

    def entry(self, kind: str) -> Optional[Dict[str, Any]]:
        """Newest analyzed entry of one kind (None before any flush)."""
        with self._lock:
            ent = self._by_kind.get(kind)
            return dict(ent) if ent else None

    @property
    def has_pending(self) -> bool:
        with self._lock:
            return bool(self._pending)

    def entries(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(v) for v in self._analyzed.values()]

    def ledger_record(self, it0: int, iterations: int,
                      wall_s: Optional[float] = None,
                      hist_bytes_per_iter: Optional[float] = None,
                      coll_bytes_per_iter: Optional[float] = None
                      ) -> Optional[Dict[str, Any]]:
        """One per-drained-batch join: the active executable's analytic
        FLOPs/bytes scaled per iteration, the measured wall, the
        measured collective payload and the analytic histogram plane
        model — plus the ``cost.*`` gauges the exporter scrapes."""
        if not self.enabled:
            return None
        self.flush()
        ent = self.active_train_entry()
        if ent is None or iterations <= 0:
            return None
        tel = self.tel
        flops_it = ent["flops"] / ent["scale"]
        bytes_it = ent["hlo_bytes"] / ent["scale"]
        tel.gauge("cost.flops_per_iter", flops_it)
        tel.gauge("cost.hlo_bytes_per_iter", bytes_it)
        rec: Dict[str, Any] = {
            "iterations": int(iterations),
            "kind": ent["kind"], "signature": ent["signature"],
            "mode": ent["mode"],
            "flops_per_iter": flops_it,
            "hlo_bytes_per_iter": bytes_it,
            "operand_bytes": ent["operand_bytes"],
        }
        if wall_s is not None and wall_s > 0:
            sec_it = wall_s / iterations
            rec["sec_per_iter"] = round(sec_it, 6)
            rec["achieved_flops_per_s"] = flops_it / sec_it
            rec["achieved_bytes_per_s"] = bytes_it / sec_it
        if coll_bytes_per_iter is not None:
            rec["coll_bytes_per_iter"] = float(coll_bytes_per_iter)
        if hist_bytes_per_iter is not None and hist_bytes_per_iter > 0 \
                and bytes_it > 0:
            frac = float(hist_bytes_per_iter) / bytes_it
            rec["hist_bytes_per_iter"] = float(hist_bytes_per_iter)
            rec["achieved_fraction"] = frac
            tel.gauge("cost.achieved_fraction", frac)
        tel.event("cost_ledger", iteration=int(it0), **rec)
        return rec
