"""Measured kernel-time attribution from ``jax.profiler`` traces.

The cost ledger (obs/cost.py) prices every executable with *analytic*
HLO flops/bytes; nothing in the system was a measured per-kernel device
time — the number ROADMAP item 5's measured-cost autotuner actually
needs.  This module closes that gap without any new dependency: the
profile windows the drivers already own (the ``profile_dir`` config
window and ``POST /profile`` on the exporter) write Chrome-trace
``*.trace.json.gz`` artifacts under
``<dir>/plugins/profile/<ts>/<host>.trace.json.gz``, and everything in
a Chrome trace is plain gzip + JSON — stdlib territory.

Three layers:

- ``parse_profile_dir(dir)`` — find and parse every trace file under a
  profile directory into one stats dict: anchor spans (the
  ``megastep`` / ``fast_step`` step annotations and the serving
  ``serve_bucket`` annotation the drivers emit), per-kernel device
  durations off the runtime threads/device lanes, and per-anchor
  attribution by time-interval containment (busy time is the interval
  UNION, so overlapping kernels are not double-counted; the raw sum
  minus the union is reported as ``overlap_us``).  Malformed input —
  truncated gzip, empty file, JSON without ``traceEvents`` — lands in
  the ``errors`` list, NEVER an exception: the parser runs inside the
  training driver's window-close hook.
- ``join_cost(stats, cost_entries, compile_entries)`` — JOIN the
  measured anchors to the analytic ``cost_executable`` records by
  executable kind (the anchor names ARE the ledger kinds, which is the
  whole reason the annotations exist: the jit function names
  ``step``/``step_ext`` are ambiguous between megastep and fast-step)
  and to ``compile_executable`` records by signature.  Every joined
  executable gets a measured ``device_time_us_per_dispatch`` (from
  per-kernel device events when the backend emits them, else the
  anchor's host span — labeled by ``timing_source``),
  ``achieved_flops_per_s`` / ``achieved_bytes_per_s`` (analytic work
  over measured device time) and a measured ``measured_fraction``
  (device-busy occupancy of the anchor's host span — the measured
  complement to the ledger's analytic ``cost.achieved_fraction``).
  ``join_coverage`` is the dispatch-weighted fraction of anchors that
  joined: unjoinable signatures report coverage < 1.0, never raise.
- ``roofline_from_dir(...)`` — the one-call convenience the drivers,
  ``scripts/profile.py summarize`` and the tests use.

docs/Observability.md §15 documents the join semantics and the
``roofline`` record this feeds.
"""
from __future__ import annotations

import gzip
import json
import os
from typing import Any, Dict, List, Optional, Tuple

SCHEMA = "lightgbm_tpu.kernelstats/1"

#: anchor span names the drivers annotate — one per executable kind the
#: cost ledger knows (boosting/gbdt.py megastep + fast-step dispatch,
#: serve/engine.py bucket dispatch)
ANCHOR_KINDS = ("megastep", "fast_step", "serve_bucket")

#: runtime/bookkeeping event-name prefixes that are NOT device kernels:
#: executor scaffolding, host<->device transfers, python-side frames.
#: Everything else on a non-python thread (or a "/device:" lane in a
#: TPU trace) counts as measured kernel time.
_RUNTIME_PREFIXES = (
    "TfrtCpu", "Thunk", "ThreadpoolListener", "ParseArguments",
    "ExecuteHelper", "PjitFunction", "$", "XlaModule", "XlaComputation",
    "BufferFromHost", "CopyToHost", "CopyFromHost", "TransferFrom",
    "TransferTo", "Memcpy", "infeed", "outfeed", "Stream #",
    "program_interpreter", "RunAsync", "EnqueueWork", "H2D", "D2H",
    "TaskDispatcher",   # llvm-codegen work dispatch (compile, not run)
)

_TRACE_SUFFIXES = (".trace.json.gz", ".trace.json")


def _base(name: str) -> str:
    return name.split("[", 1)[0].split("#", 1)[0].strip()


def trace_files(root: str) -> List[str]:
    """All Chrome-trace artifacts under a profile dir (sorted for
    deterministic multi-file merges)."""
    out: List[str] = []
    for r, _, fs in os.walk(root):
        for f in fs:
            if f.endswith(_TRACE_SUFFIXES):
                out.append(os.path.join(r, f))
    return sorted(out)


def dir_stats(root: str) -> Tuple[int, int]:
    """(file count, total bytes) under a profile dir — the
    ``profile.trace_files`` / ``profile.trace_bytes`` gauges, so an
    empty or truncated capture is observable instead of silently
    parsing to zero kernels."""
    files = bytes_ = 0
    try:
        for r, _, fs in os.walk(root):
            for f in fs:
                files += 1
                try:
                    bytes_ += os.path.getsize(os.path.join(r, f))
                except OSError:
                    pass
    except OSError:
        pass
    return files, bytes_


def parse_trace_file(path: str) -> Dict[str, Any]:
    """One trace file -> ``{"events": [...], "error": None|str}``.
    Never raises: a truncated gzip or non-JSON body is an ``error``
    string, an empty event list parses clean."""
    try:
        if path.endswith(".gz"):
            with gzip.open(path, "rb") as fh:
                raw = fh.read()
        else:
            with open(path, "rb") as fh:
                raw = fh.read()
    except (OSError, EOFError, gzip.BadGzipFile) as e:
        return {"events": [], "error": f"{os.path.basename(path)}: "
                                       f"{type(e).__name__}: {e}"}
    if not raw.strip():
        return {"events": [], "error": f"{os.path.basename(path)}: "
                                       "empty trace"}
    try:
        doc = json.loads(raw)
    except (ValueError, UnicodeDecodeError) as e:
        return {"events": [], "error": f"{os.path.basename(path)}: "
                                       f"not JSON: {e}"}
    if not isinstance(doc, dict) or not isinstance(
            doc.get("traceEvents"), list):
        return {"events": [], "error": f"{os.path.basename(path)}: "
                                       "no traceEvents"}
    return {"events": doc["traceEvents"], "error": None}


def _merged_union_us(spans: List[Tuple[float, float]]) -> float:
    """Total covered time of possibly-overlapping (start, end) spans."""
    total = 0.0
    end = None
    for s, e in sorted(spans):
        if e <= s:
            continue
        if end is None or s >= end:
            total += e - s
            end = e
        elif e > end:
            total += e - end
            end = e
    return total


def parse_profile_dir(root: str) -> Dict[str, Any]:
    """Parse every trace artifact under ``root`` into one measured-time
    stats dict (schema above).  Timestamps/durations are Chrome-trace
    microseconds.  Never raises."""
    files = trace_files(root)
    n_files, n_bytes = dir_stats(root)
    stats: Dict[str, Any] = {
        "schema": SCHEMA, "dir": str(root),
        "trace_files": len(files), "dir_files": n_files,
        "trace_bytes": n_bytes, "parsed_files": 0, "errors": [],
        "events": 0,
        "anchors": {}, "kernels": {}, "by_kind": {},
        "unattributed_time_us": 0.0,
    }
    anchor_spans: Dict[str, List[Tuple[float, float]]] = {}
    kind_kernel_spans: Dict[str, List[Tuple[float, float]]] = {}
    for path in files:
        parsed = parse_trace_file(path)
        if parsed["error"]:
            stats["errors"].append(parsed["error"])
            continue
        stats["parsed_files"] += 1
        events = parsed["events"]
        # first pass: pid/tid naming metadata (kernel classification
        # needs to know which threads are python and which pids are
        # device lanes) AND the anchor spans — traceEvents carry no
        # ordering guarantee, so kernels emitted before their anchor in
        # the stream must still attribute
        proc_names: Dict[Any, str] = {}
        thread_names: Dict[Tuple[Any, Any], str] = {}
        for ev in events:
            if not isinstance(ev, dict):
                continue
            if ev.get("ph") == "M":
                args = ev.get("args") or {}
                if ev.get("name") == "process_name":
                    proc_names[ev.get("pid")] = str(args.get("name", ""))
                elif ev.get("name") == "thread_name":
                    thread_names[(ev.get("pid"), ev.get("tid"))] = \
                        str(args.get("name", ""))
                continue
            if ev.get("ph") != "X":
                continue
            base = _base(str(ev.get("name", "")))
            if base not in ANCHOR_KINDS:
                continue
            try:
                ts = float(ev.get("ts", 0.0))
                dur = float(ev.get("dur", 0.0))
            except (TypeError, ValueError):
                continue
            a = stats["anchors"].setdefault(
                base, {"dispatches": 0, "host_time_us": 0.0})
            a["dispatches"] += 1
            a["host_time_us"] += dur
            anchor_spans.setdefault(base, []).append((ts, ts + dur))
        # second pass: kernel events, attributed to the collected spans
        for ev in events:
            if not isinstance(ev, dict) or ev.get("ph") != "X":
                continue
            name = str(ev.get("name", ""))
            try:
                ts = float(ev.get("ts", 0.0))
                dur = float(ev.get("dur", 0.0))
            except (TypeError, ValueError):
                continue
            stats["events"] += 1
            if _base(name) in ANCHOR_KINDS:
                continue          # counted in the first pass
            if dur <= 0:
                continue
            tname = thread_names.get((ev.get("pid"), ev.get("tid")), "")
            pname = proc_names.get(ev.get("pid"), "")
            device_lane = "/device:" in pname
            if not device_lane and tname.lower().startswith("python"):
                continue          # host frames, not device work
            if any(name.startswith(p) for p in _RUNTIME_PREFIXES):
                continue          # runtime scaffolding / transfers
            k = stats["kernels"].setdefault(
                name, {"count": 0, "time_us": 0.0})
            k["count"] += 1
            k["time_us"] += dur
            mid = ts + dur / 2.0
            owner = None
            for kind, spans in anchor_spans.items():
                if any(s <= mid < e for s, e in spans):
                    owner = kind
                    break
            if owner is None:
                stats["unattributed_time_us"] += dur
                continue
            bk = stats["by_kind"].setdefault(
                owner, {"device_time_us": 0.0, "kernel_time_us": 0.0,
                        "overlap_us": 0.0, "kernels": {}})
            bk["kernel_time_us"] += dur
            kk = bk["kernels"].setdefault(
                name, {"count": 0, "time_us": 0.0})
            kk["count"] += 1
            kk["time_us"] += dur
            kind_kernel_spans.setdefault(owner, []).append(
                (ts, ts + dur))
    for kind, spans in kind_kernel_spans.items():
        bk = stats["by_kind"][kind]
        bk["device_time_us"] = _merged_union_us(spans)
        bk["overlap_us"] = max(
            0.0, bk["kernel_time_us"] - bk["device_time_us"])
    return stats


def _top_kernels(kernels: Dict[str, Dict[str, Any]], top: int
                 ) -> List[Dict[str, Any]]:
    rows = [{"name": n, "count": int(k["count"]),
             "time_us": round(float(k["time_us"]), 3)}
            for n, k in kernels.items()]
    rows.sort(key=lambda r: (-r["time_us"], r["name"]))
    return rows[:top]


def join_cost(stats: Dict[str, Any],
              cost_entries: Optional[List[Dict[str, Any]]] = None,
              compile_entries: Optional[List[Dict[str, Any]]] = None,
              top: int = 8) -> Dict[str, Any]:
    """Measured stats x analytic ledger -> the roofline record.

    Anchors join ``cost_executable`` entries by executable kind (newest
    entry per kind wins, matching CostLedger's active-schedule rule)
    and ``compile_executable`` records by the joined signature.  An
    anchor with no matching cost entry stays in the table unjoined and
    drags ``join_coverage`` below 1.0 — reported, never raised."""
    by_kind: Dict[str, Dict[str, Any]] = {}
    for ent in cost_entries or []:
        if isinstance(ent, dict) and ent.get("kind"):
            by_kind[str(ent["kind"])] = ent
    compile_by_sig: Dict[str, Dict[str, Any]] = {}
    for ent in compile_entries or []:
        if isinstance(ent, dict) and ent.get("signature"):
            compile_by_sig[str(ent["signature"])] = ent
    executables: List[Dict[str, Any]] = []
    total_disp = joined_disp = 0
    total_device_us = 0.0
    for kind in sorted(stats.get("anchors", {})):
        a = stats["anchors"][kind]
        bk = stats.get("by_kind", {}).get(kind, {})
        disp = int(a.get("dispatches", 0))
        total_disp += disp
        device_us = float(bk.get("device_time_us", 0.0))
        host_us = float(a.get("host_time_us", 0.0))
        total_device_us += device_us
        ent = by_kind.get(kind)
        # timing source: per-kernel device events when the backend
        # emits them (TPU lanes), else the anchor's host span — the CPU
        # runtime executes a jitted executable without per-op trace
        # events, and a labeled host-span measurement beats a zero
        timed_us = device_us if device_us > 0 else host_us
        row: Dict[str, Any] = {
            "kind": kind,
            "signature": str(ent["signature"]) if ent else None,
            "joined": ent is not None,
            "dispatches": disp,
            "device_time_us": round(device_us, 3),
            "host_time_us": round(host_us, 3),
            "kernel_time_us": round(
                float(bk.get("kernel_time_us", 0.0)), 3),
            "overlap_us": round(float(bk.get("overlap_us", 0.0)), 3),
            "timing_source": ("kernels" if device_us > 0
                              else "host_span"),
            "device_time_us_per_dispatch": round(
                timed_us / disp, 3) if disp and timed_us > 0 else None,
            "measured_fraction": round(
                device_us / host_us, 6) if host_us > 0 else None,
            "top_kernels": _top_kernels(bk.get("kernels", {}), top),
        }
        if ent is not None:
            joined_disp += disp
            row["scale"] = int(ent.get("scale", 1))
            row["flops"] = float(ent.get("flops", 0.0))
            row["hlo_bytes"] = float(ent.get("hlo_bytes", 0.0))
            if timed_us > 0 and disp > 0:
                per_disp_s = timed_us / disp * 1e-6
                row["achieved_flops_per_s"] = row["flops"] / per_disp_s
                row["achieved_bytes_per_s"] = \
                    row["hlo_bytes"] / per_disp_s
            comp = compile_by_sig.get(row["signature"])
            if comp is not None:
                row["compile_ms"] = comp.get("compile_ms")
                row["operand_bytes"] = comp.get("operand_bytes")
        executables.append(row)
    executables.sort(key=lambda r: -r["device_time_us"])
    return {
        "schema": SCHEMA,
        "dir": stats.get("dir"),
        "join_coverage": round(
            joined_disp / total_disp, 6) if total_disp else 0.0,
        "anchor_dispatches": total_disp,
        "joined_executables": sum(1 for r in executables
                                  if r["joined"]),
        "executables": executables,
        "kernels": _top_kernels(stats.get("kernels", {}), top),
        "total_device_time_us": round(total_device_us, 3),
        "unattributed_time_us": round(
            float(stats.get("unattributed_time_us", 0.0)), 3),
        "trace_files": int(stats.get("trace_files", 0)),
        "trace_bytes": int(stats.get("trace_bytes", 0)),
        "parsed_files": int(stats.get("parsed_files", 0)),
        "parse_errors": len(stats.get("errors", [])),
        "errors": list(stats.get("errors", []))[:8],
    }


def roofline_from_dir(root: str,
                      cost_entries: Optional[List[Dict[str, Any]]] = None,
                      compile_entries: Optional[
                          List[Dict[str, Any]]] = None,
                      top: int = 8) -> Dict[str, Any]:
    """Parse + join in one call — the window-close hook, the ``profile.py
    summarize`` subcommand and the e2e tests all go through here."""
    return join_cost(parse_profile_dir(root), cost_entries,
                     compile_entries, top=top)


def cost_entries_from_events(events: List[Dict[str, Any]]
                             ) -> Tuple[List[Dict[str, Any]],
                                        List[Dict[str, Any]]]:
    """Split a JSONL/event-ring record stream into the
    (cost_executable, compile_executable) entry lists ``join_cost``
    consumes — for joining a trace against a ``telemetry_out`` sink
    after the fact (``profile.py summarize --telemetry``)."""
    cost = [e for e in events if isinstance(e, dict)
            and e.get("event") == "cost_executable"]
    compiles = [e for e in events if isinstance(e, dict)
                and e.get("event") == "compile_executable"]
    return cost, compiles
