"""jax.monitoring bridge + per-device memory accounting.

JAX reports compile phases through ``jax.monitoring`` duration events
(``/jax/core/compile/jaxpr_trace_duration``,
``.../jaxpr_to_mlir_module_duration``, ``.../backend_compile_duration``).
A single process-wide listener is installed on first attach and fans the
events out to every live, enabled :class:`Telemetry` — so per-booster
registries see the compiles their iterations trigger (a recompile
mid-training is exactly the kind of cliff PROFILE.md says one-off timing
scripts keep missing).  Whatever identity kwargs the monitoring API
passes (``fun_name`` on newer jax) ride along on the compile record.

Memory accounting covers EVERY local device, not just device 0: a
multi-chip host where one device's allocator is near its limit while
device 0 idles is precisely the failure per-device gauges exist to
show.  ``memory_watermarks`` snapshots ``bytes_in_use`` /
``peak_bytes_in_use`` / ``bytes_limit`` into per-device registry gauges
at the driver's natural sync points (megastep drain, serving dispatch)
so the OpenMetrics exporter can expose live HBM headroom.
"""
from __future__ import annotations

import threading
import weakref
from typing import Dict, Optional

_COMPILE_PREFIX = "/jax/core/compile"

_lock = threading.Lock()
_installed = False
_active: "weakref.WeakSet" = weakref.WeakSet()

# backends whose devices report no allocator stats (CPU, interpret)
# answer None once and are never re-queried: the watermark hook sits on
# the serving dispatch path, where a per-batch jax.local_devices() walk
# that can only ever return None is pure overhead
_mem_unsupported = False


def attach(tel) -> None:
    """Subscribe a Telemetry instance to compile events (idempotent)."""
    global _installed
    with _lock:
        _active.add(tel)
        if _installed:
            return
        try:
            from jax import monitoring
            monitoring.register_event_duration_secs_listener(_on_duration)
        except Exception:  # monitoring API unavailable: degrade silently
            pass
        _installed = True


def detach(tel) -> None:
    with _lock:
        _active.discard(tel)


# parameter names of compile_event / span that a monitoring kwarg must
# never shadow — a colliding key would raise TypeError INSIDE jax's
# compile path and kill the jit that triggered the listener
_RESERVED_ATTRS = frozenset(
    {"phase", "seconds", "name", "track", "iteration", "wall_start",
     "event", "duration"})


def _on_duration(event: str, duration: float, **kwargs) -> None:
    if not event.startswith(_COMPILE_PREFIX):
        return
    # short phase name: "backend_compile_duration" etc.
    phase = event.rsplit("/", 1)[-1]
    # only plain scalar identity attrs survive — the record must stay
    # JSON- and trace-serializable whatever jax adds to the callback
    attrs = {k: v for k, v in kwargs.items()
             if isinstance(v, (str, int, float, bool))
             and k not in _RESERVED_ATTRS}
    for tel in list(_active):
        if tel.enabled:
            try:
                tel.compile_event(phase, float(duration), **attrs)
            except Exception:
                # a telemetry bug must never propagate out of the
                # monitoring listener into the XLA compile it observes
                pass


_STAT_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
              "largest_alloc_size")


def device_memory_stats() -> Optional[Dict[int, dict]]:
    """Allocator stats of EVERY local device, keyed by device id
    (``{0: {"bytes_in_use": ..., ...}, 1: {...}}``).  Backends whose
    devices report nothing (CPU, interpret) return None — cleanly, and
    cached so repeated polling costs one attribute check."""
    global _mem_unsupported
    if _mem_unsupported:
        return None
    try:
        import jax
        devices = jax.local_devices()
    except Exception:
        return None
    out: Dict[int, dict] = {}
    for d in devices:
        try:
            ms = d.memory_stats()
        except Exception:
            ms = None
        if not ms:
            continue
        ent = {key: int(ms[key]) for key in _STAT_KEYS if key in ms}
        if ent:
            out[int(getattr(d, "id", len(out)))] = ent
    if not out:
        _mem_unsupported = True
        return None
    return out


def memory_watermarks(tel, where: str = "") -> Optional[Dict[int, dict]]:
    """Gauge every local device's live and peak allocator bytes into the
    registry (``mem.d<id>.bytes_in_use`` / ``.peak_bytes_in_use`` /
    ``.bytes_limit``) and count the observation under
    ``mem.watermarks.<where>``.  Called at megastep drain and serving
    dispatch boundaries — the two places the allocator's peak actually
    moves — so the exporter's HBM-headroom gauges track the run live.
    Returns the per-device stats (None where unsupported)."""
    if tel is None or not tel.enabled:
        return None
    stats = device_memory_stats()
    if not stats:
        return None
    for did, ent in stats.items():
        for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            if key in ent:
                tel.gauge(f"mem.d{did}.{key}", ent[key])
    if where:
        tel.inc("mem.watermarks." + where)
    return stats
