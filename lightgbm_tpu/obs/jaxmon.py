"""jax.monitoring bridge + device memory stats.

JAX reports compile phases through ``jax.monitoring`` duration events
(``/jax/core/compile/jaxpr_trace_duration``,
``.../jaxpr_to_mlir_module_duration``, ``.../backend_compile_duration``).
A single process-wide listener is installed on first attach and fans the
events out to every live, enabled :class:`Telemetry` — so per-booster
registries see the compiles their iterations trigger (a recompile
mid-training is exactly the kind of cliff PROFILE.md says one-off timing
scripts keep missing).
"""
from __future__ import annotations

import threading
import weakref
from typing import Optional

_COMPILE_PREFIX = "/jax/core/compile"

_lock = threading.Lock()
_installed = False
_active: "weakref.WeakSet" = weakref.WeakSet()


def attach(tel) -> None:
    """Subscribe a Telemetry instance to compile events (idempotent)."""
    global _installed
    with _lock:
        _active.add(tel)
        if _installed:
            return
        try:
            from jax import monitoring
            monitoring.register_event_duration_secs_listener(_on_duration)
        except Exception:  # monitoring API unavailable: degrade silently
            pass
        _installed = True


def detach(tel) -> None:
    with _lock:
        _active.discard(tel)


def _on_duration(event: str, duration: float, **kwargs) -> None:
    if not event.startswith(_COMPILE_PREFIX):
        return
    # short phase name: "backend_compile_duration" etc.
    phase = event.rsplit("/", 1)[-1]
    for tel in list(_active):
        if tel.enabled:
            tel.compile_event(phase, float(duration))


def device_memory_stats() -> Optional[dict]:
    """Allocator stats of the first local device ({} keys vary by
    backend; TPU/GPU report bytes_in_use etc., CPU returns None)."""
    try:
        import jax
        ms = jax.local_devices()[0].memory_stats()
    except Exception:
        return None
    if not ms:
        return None
    out = {}
    for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                "largest_alloc_size"):
        if key in ms:
            out[key] = int(ms[key])
    return out or None
