"""jax.monitoring bridge + per-device memory accounting.

JAX reports compile phases through ``jax.monitoring`` duration events
(``/jax/core/compile/jaxpr_trace_duration``,
``.../jaxpr_to_mlir_module_duration``, ``.../backend_compile_duration``).
A single process-wide listener is installed on first attach and fans the
events out to every live, enabled :class:`Telemetry` — so per-booster
registries see the compiles their iterations trigger (a recompile
mid-training is exactly the kind of cliff PROFILE.md says one-off timing
scripts keep missing).  Whatever identity kwargs the monitoring API
passes (``fun_name`` on newer jax) ride along on the compile record.

Memory accounting covers EVERY local device, not just device 0: a
multi-chip host where one device's allocator is near its limit while
device 0 idles is precisely the failure per-device gauges exist to
show.  ``memory_watermarks`` snapshots ``bytes_in_use`` /
``peak_bytes_in_use`` / ``bytes_limit`` into per-device registry gauges
at the driver's natural sync points (megastep drain, serving dispatch)
so the OpenMetrics exporter can expose live HBM headroom.
"""
from __future__ import annotations

import threading
import weakref
from typing import Dict, Optional

_COMPILE_PREFIX = "/jax/core/compile"

_lock = threading.Lock()
_installed = False
_active: "weakref.WeakSet" = weakref.WeakSet()

# backends whose devices report no allocator stats (CPU, interpret)
# answer None once and are never re-queried: the watermark hook sits on
# the serving dispatch path, where a per-batch jax.local_devices() walk
# that can only ever return None is pure overhead
_mem_unsupported = False


def attach(tel) -> None:
    """Subscribe a Telemetry instance to compile events (idempotent)."""
    global _installed
    with _lock:
        _active.add(tel)
        if _installed:
            return
        try:
            from jax import monitoring
            monitoring.register_event_duration_secs_listener(_on_duration)
        except Exception:  # monitoring API unavailable: degrade silently
            pass
        _installed = True


def detach(tel) -> None:
    with _lock:
        _active.discard(tel)


# parameter names of compile_event / span that a monitoring kwarg must
# never shadow — a colliding key would raise TypeError INSIDE jax's
# compile path and kill the jit that triggered the listener
_RESERVED_ATTRS = frozenset(
    {"phase", "seconds", "name", "track", "iteration", "wall_start",
     "event", "duration"})


def _on_duration(event: str, duration: float, **kwargs) -> None:
    if not event.startswith(_COMPILE_PREFIX):
        return
    # short phase name: "backend_compile_duration" etc.
    phase = event.rsplit("/", 1)[-1]
    # only plain scalar identity attrs survive — the record must stay
    # JSON- and trace-serializable whatever jax adds to the callback
    attrs = {k: v for k, v in kwargs.items()
             if isinstance(v, (str, int, float, bool))
             and k not in _RESERVED_ATTRS}
    for tel in list(_active):
        if tel.enabled:
            try:
                tel.compile_event(phase, float(duration), **attrs)
            except Exception:
                # a telemetry bug must never propagate out of the
                # monitoring listener into the XLA compile it observes
                pass


_STAT_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
              "largest_alloc_size", "bytes_reserved",
              "peak_bytes_reserved", "largest_free_block_bytes")

#: stats exported as per-device gauges by memory_watermarks (the
#: reserved-bytes pair only exists where the backend's allocator
#: reports it — TPU/GPU BFC allocators do, CPU does not; absent keys
#: are simply absent from the gauges, never zero-filled)
_GAUGE_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
               "bytes_reserved", "peak_bytes_reserved")


def device_memory_stats() -> Optional[Dict[int, dict]]:
    """Allocator stats of EVERY local device, keyed by device id
    (``{0: {"bytes_in_use": ..., ...}, 1: {...}}``).  Backends whose
    devices report nothing (CPU, interpret) return None — cleanly, and
    cached so repeated polling costs one attribute check."""
    global _mem_unsupported
    if _mem_unsupported:
        return None
    try:
        import jax
        devices = jax.local_devices()
    except Exception:
        return None
    out: Dict[int, dict] = {}
    for d in devices:
        try:
            ms = d.memory_stats()
        except Exception:
            ms = None
        if not ms:
            continue
        ent = {key: int(ms[key]) for key in _STAT_KEYS if key in ms}
        if ent:
            out[int(getattr(d, "id", len(out)))] = ent
    if not out:
        _mem_unsupported = True
        return None
    return out


def memory_watermarks(tel, where: str = "") -> Optional[Dict[int, dict]]:
    """Gauge every local device's live and peak allocator bytes into the
    registry (``mem.d<id>.bytes_in_use`` / ``.peak_bytes_in_use`` /
    ``.bytes_limit``) and count the observation under
    ``mem.watermarks.<where>``.  Called at megastep drain and serving
    dispatch boundaries — the two places the allocator's peak actually
    moves — so the exporter's HBM-headroom gauges track the run live.
    Returns the per-device stats (None where unsupported)."""
    if tel is None or not tel.enabled:
        return None
    stats = device_memory_stats()
    if not stats:
        return None
    for did, ent in stats.items():
        for key in _GAUGE_KEYS:
            if key in ent:
                tel.gauge(f"mem.d{did}.{key}", ent[key])
        frag = fragmentation(ent)
        if frag is not None:
            ent["fragmentation"] = frag
            tel.gauge(f"mem.d{did}.fragmentation", frag)
    if where:
        tel.inc("mem.watermarks." + where)
    return stats


def fragmentation(ent: dict) -> Optional[float]:
    """Free-space fragmentation of one device's allocator: the share of
    free pool bytes NOT reachable as a single contiguous block
    (``1 - largest_free_block / free``).  0 = one perfect free block;
    approaching 1 = free space is shattered and a large histogram
    buffer may OOM despite headroom.  ``largest_free_block_bytes``
    describes the allocator's RESERVED pool, so where the allocator
    reports ``bytes_reserved`` (a growing BFC pool) the free
    denominator is ``bytes_reserved - bytes_in_use`` — dividing by the
    whole unreserved limit would read a barely-grown pool as ~100%
    fragmented while most of HBM is freely allocatable.  None where
    the backend reports no block/limit stats (CPU)."""
    try:
        in_use = int(ent["bytes_in_use"])
        largest = int(ent["largest_free_block_bytes"])
        pool = int(ent.get("bytes_reserved", ent["bytes_limit"]))
    except (KeyError, TypeError, ValueError):
        return None
    free = pool - in_use
    if free <= 0:
        return 0.0
    return max(0.0, min(1.0, 1.0 - largest / free))
