"""Drift & lineage plane: training data profiles, divergence math and
the serving drift monitor.

Three cooperating pieces (ROADMAP item 2's observability prerequisites):

- **DataProfile** — a compact, JSON-canonical snapshot of the training
  distribution captured at dataset finalize: per-feature bin-occupancy
  histograms (one ``np.bincount`` over the packed bins the dataset
  already holds — no re-binning), missing rates, the label
  distribution, the frozen ``mappers_digest`` and row count, plus (for
  numeric features) the bin upper bounds so a RAW-variant serving
  engine can host-bin float inputs against the same edges.  The profile
  rides the model artifact (``io/model_io.py`` appends a
  ``data_profile:`` block after ``end of parameters``) and checkpoint
  payloads, so any loaded booster carries its training distribution.
  Serialization is byte-stable: :func:`canonical_json` of a profile
  that round-trips through save/load re-emits the identical bytes.

- **PSI / JS divergence** — :func:`psi` and :func:`js_divergence` with
  epsilon smoothing, defined for every degenerate shape the monitors
  meet in production: empty reference bins, single-bin features,
  all-missing columns, zero-count current windows.

- **DriftMonitor** — the serving-side accumulator+evaluator.  The
  micro-batcher feeds it host-side from the ALREADY-ENCODED batch
  (zero extra device dispatches; the 1.0 dispatches/request and
  0-recompile serving contracts are counter-asserted in CI), and a
  periodic evaluation computes per-feature PSI against the resident
  model's profile with consecutive-evaluation hysteresis so one
  sustained excursion raises exactly one ``drift_alert``.

Provenance (:func:`build_provenance`) is the lineage half: source
fingerprint, params digest, parent checkpoint hash, training ``run_id``
and profile digest, riding the same artifact/checkpoint channels and
chained through ``rollover()`` into ``serve_rollover`` events.
"""
from __future__ import annotations

import hashlib
import json
import threading
from typing import Any, Dict, List, Optional

import numpy as np

PROFILE_SCHEMA = "lightgbm_tpu.data_profile/1"
PROVENANCE_SCHEMA = "lightgbm_tpu.provenance/1"

# smoothing mass added to every bin before normalizing: keeps the PSI
# log terms finite when a bin is empty on either side (the standard
# industry treatment; the exact value only matters for bins with no
# reference mass, where any finite penalty is a modeling choice)
PSI_EPS = 1e-4

# label / score distributions use fixed-size quantile sketches
_SCORE_BINS = 16

# histograms are COARSENED to at most this many contiguous groups
# before the PSI compare: under the null (no drift) the PSI estimate's
# expectation is ~ (groups-1) * (1/N_ref + 1/N_cur), so comparing the
# raw 63-255 training bins directly would read pure sampling noise as
# drift at any practical eval window.  8 groups keeps the null
# expectation well under the 0.2 alert threshold from a few hundred
# rows while a real location/scale shift still moves whole groups.
_PSI_GROUPS = 8


def coarsen(counts, groups: int = _PSI_GROUPS) -> np.ndarray:
    """Sum contiguous histogram bins down to at most ``groups`` —
    the noise-control step in front of every PSI comparison."""
    c = np.asarray(counts, np.float64).ravel()
    if c.size <= groups:
        return c
    starts = np.linspace(0, c.size, groups + 1).astype(int)[:-1]
    return np.add.reduceat(c, starts)


# ---------------------------------------------------------------------
# divergence math
def _smooth_norm(counts, eps: float) -> np.ndarray:
    c = np.asarray(counts, np.float64).ravel()
    if c.size == 0:
        return c
    c = np.maximum(c, 0.0) + eps
    return c / c.sum()


def psi(ref_counts, cur_counts, eps: float = PSI_EPS) -> float:
    """Population Stability Index between two count vectors.

    ``sum((p_i - q_i) * ln(p_i / q_i))`` over smoothed, normalized
    bins.  Degenerate shapes are defined, not exceptional: mismatched
    lengths compare over the shorter prefix padded with empty bins,
    a single-bin feature is identically 0 (both normalize to [1.0]),
    and an empty/zero vector on either side yields a finite value via
    the smoothing mass.
    """
    r = np.asarray(ref_counts, np.float64).ravel()
    c = np.asarray(cur_counts, np.float64).ravel()
    n = max(r.size, c.size)
    if n == 0:
        return 0.0
    if r.size < n:
        r = np.concatenate([r, np.zeros(n - r.size)])
    if c.size < n:
        c = np.concatenate([c, np.zeros(n - c.size)])
    p, q = _smooth_norm(r, eps), _smooth_norm(c, eps)
    return float(np.sum((p - q) * np.log(p / q)))


def js_divergence(ref_counts, cur_counts, eps: float = PSI_EPS) -> float:
    """Jensen-Shannon divergence (natural log; bounded by ln 2) with
    the same smoothing/shape conventions as :func:`psi`."""
    r = np.asarray(ref_counts, np.float64).ravel()
    c = np.asarray(cur_counts, np.float64).ravel()
    n = max(r.size, c.size)
    if n == 0:
        return 0.0
    if r.size < n:
        r = np.concatenate([r, np.zeros(n - r.size)])
    if c.size < n:
        c = np.concatenate([c, np.zeros(n - c.size)])
    p, q = _smooth_norm(r, eps), _smooth_norm(c, eps)
    m = 0.5 * (p + q)
    kl = lambda a, b: float(np.sum(a * np.log(a / b)))  # noqa: E731
    return 0.5 * kl(p, m) + 0.5 * kl(q, m)


# ---------------------------------------------------------------------
# canonical serialization (the byte-stability contract)
def _jsonable(x: Any) -> Any:
    """Plain-python view of numpy scalars/arrays so the canonical dump
    is independent of who built the object (fresh bincount vs a parsed
    round trip)."""
    if isinstance(x, dict):
        return {str(k): _jsonable(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_jsonable(v) for v in x]
    if isinstance(x, np.ndarray):
        return [_jsonable(v) for v in x.tolist()]
    if isinstance(x, (np.integer,)):
        return int(x)
    if isinstance(x, (np.floating,)):
        return float(x)
    return x


def canonical_json(obj: Any) -> str:
    """Sorted-key, separator-minimal JSON — dumping a parsed dump
    reproduces the identical bytes (floats use Python's shortest
    round-trip repr, which json both emits and parses exactly)."""
    return json.dumps(_jsonable(obj), sort_keys=True,
                      separators=(",", ":"), allow_nan=False)


def profile_digest(profile: Optional[Dict[str, Any]]) -> str:
    if not profile:
        return ""
    return hashlib.sha256(canonical_json(profile).encode()).hexdigest()


# ---------------------------------------------------------------------
# training reference profile
def _quantile_sketch(values: np.ndarray, bins: int = _SCORE_BINS
                     ) -> Dict[str, Any]:
    """Fixed-size histogram of a 1-D float sample: interior quantile
    edges (deduplicated — a constant sample degrades to one bin) and
    the counts of ``searchsorted`` against them.  Comparable across
    samples because the EDGES ride the profile."""
    v = np.asarray(values, np.float64).ravel()
    v = v[np.isfinite(v)]
    if v.size == 0:
        return {"edges": [], "counts": [], "count": 0}
    qs = np.quantile(v, np.linspace(0.0, 1.0, bins + 1)[1:-1])
    edges = np.unique(qs)
    counts = np.bincount(np.searchsorted(edges, v, side="right"),
                         minlength=edges.size + 1)
    return {"edges": [float(e) for e in edges],
            "counts": [int(c) for c in counts],
            "count": int(v.size),
            "mean": float(v.mean()), "std": float(v.std())}


def sketch_counts(sketch: Dict[str, Any], values: np.ndarray
                  ) -> np.ndarray:
    """Histogram ``values`` against a stored sketch's edges."""
    edges = np.asarray(sketch.get("edges", []), np.float64)
    v = np.asarray(values, np.float64).ravel()
    v = v[np.isfinite(v)]
    return np.bincount(np.searchsorted(edges, v, side="right"),
                       minlength=edges.size + 1)


def build_profile(ds) -> Dict[str, Any]:
    """Capture a :data:`PROFILE_SCHEMA` DataProfile from a finalized
    ``TpuDataset`` (packed bins + frozen mappers present).  One
    ``np.bincount`` per used feature over columns that already exist —
    no re-binning, no device work."""
    from ..binning import mappers_digest

    bins = ds.bins
    n = int(bins.shape[0]) if bins is not None else 0
    # sparse-EFB datasets hold BUNDLE columns, not per-feature columns:
    # per-feature histograms are structurally unavailable — emit empty
    # counts (the monitor skips empty references) but keep the label /
    # missing-rate / digest parts of the profile
    bundled = getattr(ds, "prebundled", None) is not None
    features: List[Dict[str, Any]] = []
    for k, j in enumerate(ds.used_features):
        nb = int(ds.num_bin_per_feat[k])
        if n and not bundled:
            counts = np.bincount(np.asarray(bins[:, k], np.int64),
                                 minlength=nb)[:nb]
        else:
            counts = np.zeros(0, np.int64)
        mapper = ds.mappers[j]
        mtype = mapper.missing_type_str()
        if mtype == "NaN" and counts.size:
            miss = int(counts[nb - 1])
        elif mtype == "Zero" \
                and 0 <= int(mapper.default_bin) < counts.size:
            miss = int(counts[int(mapper.default_bin)])
        else:
            miss = 0
        feat = {
            "index": int(j),
            "num_bin": nb,
            "counts": [int(c) for c in counts],
            "missing_rate": float(miss) / n if n else 0.0,
            "categorical": bool(ds.is_categorical[k]),
        }
        if not feat["categorical"] \
                and getattr(mapper, "bin_upper_bound", None) is not None:
            # numeric edges let a raw-variant engine host-bin floats
            # against the training layout; +-inf edges are dropped
            # (allow_nan=False canonical JSON) — searchsorted against
            # the finite interior edges reproduces the same bins
            feat["edges"] = [float(b) for b in mapper.bin_upper_bound
                             if np.isfinite(b)]
        features.append(feat)

    label = getattr(ds.metadata, "label", None)
    profile = {
        "schema": PROFILE_SCHEMA,
        "rows": n,
        "mappers_digest": mappers_digest(ds.mappers),
        "features": features,
        "label": _quantile_sketch(label) if label is not None
        else {"edges": [], "counts": [], "count": 0},
    }
    return profile


def add_score_distribution(profile: Optional[Dict[str, Any]],
                           scores) -> None:
    """Attach the final training-score (margin) distribution — called
    at training finalize, where the drained scores are already on host
    fetch path (no extra dispatch)."""
    if not profile:
        return
    profile["score"] = _quantile_sketch(np.asarray(scores))


# ---------------------------------------------------------------------
# provenance (the lineage record)
def build_provenance(*, run_id: str = "", params_digest: str = "",
                     source: str = "", parent_checkpoint: str = "",
                     profile: Optional[Dict[str, Any]] = None
                     ) -> Dict[str, Any]:
    """When ``run_id`` is not supplied it is CONTENT-DERIVED — a digest
    of (params digest, source fingerprint, profile digest) — so two
    identical trainings serialize byte-identical model artifacts (the
    repo's rerun-determinism contract; reference model strings carry no
    per-run entropy either).  For the same reason the record holds no
    wall-clock timestamp, and ``parent_checkpoint`` stays OUT of the
    derivation: a resumed run is the same training run, so restore can
    chain the checkpoint hash without changing the run identity.
    Per-run wall-clock identity lives in the telemetry stream / run
    report, and model age is tracked from rollover time at serving."""
    pdig = profile_digest(profile)
    if not run_id:
        seed = canonical_json({"params": str(params_digest),
                               "source": str(source), "profile": pdig})
        run_id = "r" + hashlib.sha256(seed.encode()).hexdigest()[:16]
    return {
        "schema": PROVENANCE_SCHEMA,
        "run_id": str(run_id),
        "params_digest": str(params_digest),
        "source": str(source),
        "parent_checkpoint": str(parent_checkpoint),
        "profile_digest": pdig,
    }


def source_fingerprint(data, profile: Optional[Dict[str, Any]] = None
                       ) -> str:
    """Content fingerprint of the training data.  Given a profile the
    identity is rows x features + the frozen mappers digest — stable
    across ingestion paths (in-memory array, pushed rows, binary-cache
    reload, streamed file), which the model-string parity contracts
    require: the same data must serialize the same artifact no matter
    how it arrived.  Path+mtime metadata would break that (and goes
    stale on copy); it belongs to the ingest cache-hit layer
    (``ingest.cache.source_fingerprint``), not the model artifact.
    Without a profile, fall back to the container description."""
    if profile:
        return (f"data:{int(profile.get('rows', 0))}x"
                f"{len(profile.get('features', []))}:"
                f"m{str(profile.get('mappers_digest', ''))[:12]}")
    try:
        import os
        if isinstance(data, str):
            st = os.stat(data)
            return f"file:{os.path.abspath(data)}:{st.st_size}:" \
                   f"{int(st.st_mtime)}"
        shape = getattr(data, "shape", None)
        if shape is not None:
            return "array:" + "x".join(str(int(s)) for s in shape)
    except Exception:
        pass
    return f"object:{type(data).__name__}"


# ---------------------------------------------------------------------
# serving drift monitor
class DriftMonitor:
    """Host-side drift accumulator for one resident serving engine.

    ``accumulate``/``accumulate_raw``/``accumulate_scores`` are called
    by the serving engine on batches it ALREADY encoded/predicted (the
    zero-extra-dispatch invariant); ``evaluate`` is called by the
    micro-batcher's post-batch flush hook — off the request latency
    path — and returns a result dict once enough rows accumulated
    since the last evaluation, else ``None``.

    Hysteresis: an alert arms only after ``hysteresis`` CONSECUTIVE
    evaluations with ``psi_max`` over the threshold, fires once, and
    cannot re-fire until the excursion fully clears (an evaluation back
    under the threshold).  One sustained shift -> exactly one
    ``drift_alert``.
    """

    def __init__(self, profile: Dict[str, Any], *,
                 psi_threshold: float = 0.2, eval_rows: int = 512,
                 hysteresis: int = 2):
        self.profile = profile
        self.psi_threshold = float(psi_threshold)
        self.eval_rows = max(1, int(eval_rows))
        self.hysteresis = max(1, int(hysteresis))
        feats = profile.get("features", [])
        self._ref = [np.asarray(f.get("counts", []), np.float64)
                     for f in feats]
        self._idx = [int(f.get("index", i)) for i, f in enumerate(feats)]
        self._edges = [np.asarray(f.get("edges", []), np.float64)
                       if not f.get("categorical") else None
                       for f in feats]
        self._counts = [np.zeros(max(1, r.size), np.int64)
                        for r in self._ref]
        self._score_ref = profile.get("score") or {}
        self._score_counts = np.zeros(
            len(self._score_ref.get("counts", [])) or 1, np.int64)
        self._rows = 0
        self._rows_since_eval = 0
        self._over = 0
        self._latched = False
        self.alerts = 0
        self.evaluations = 0
        self.last: Dict[str, Any] = {}
        self._lock = threading.Lock()

    # -------------------------------------------------- accumulation
    def accumulate(self, enc: np.ndarray) -> None:
        """Binned rows (``[rows, F]`` integer bin indices — the binned
        serving variant's encode output)."""
        enc = np.asarray(enc)
        if enc.ndim != 2 or enc.shape[0] == 0:
            return
        with self._lock:
            for k, ref in enumerate(self._ref):
                if k >= enc.shape[1]:
                    break
                nb = self._counts[k].size
                col = np.clip(np.asarray(enc[:, k], np.int64), 0, nb - 1)
                self._counts[k] += np.bincount(col, minlength=nb)
            self._rows += int(enc.shape[0])
            self._rows_since_eval += int(enc.shape[0])

    def accumulate_raw(self, X: np.ndarray) -> None:
        """Float rows (the raw serving variant): host-bin numeric
        features against the profile's stored edges.  Categorical
        features (no edges in the profile) are skipped — their PSI is
        simply not monitored on raw engines."""
        X = np.asarray(X, np.float64)
        if X.ndim != 2 or X.shape[0] == 0:
            return
        with self._lock:
            for k, edges in enumerate(self._edges):
                if edges is None or self._idx[k] >= X.shape[1]:
                    continue
                col = X[:, self._idx[k]]
                col = col[np.isfinite(col)]
                nb = self._counts[k].size
                b = np.clip(np.searchsorted(edges, col, side="left"),
                            0, nb - 1)
                self._counts[k] += np.bincount(b, minlength=nb)
            self._rows += int(X.shape[0])
            self._rows_since_eval += int(X.shape[0])

    def accumulate_scores(self, raw) -> None:
        if not self._score_ref.get("counts"):
            return
        c = sketch_counts(self._score_ref, np.asarray(raw))
        with self._lock:
            n = min(c.size, self._score_counts.size)
            self._score_counts[:n] += c[:n]

    # -------------------------------------------------- evaluation
    def evaluate(self, force: bool = False) -> Optional[Dict[str, Any]]:
        with self._lock:
            if not force and self._rows_since_eval < self.eval_rows:
                return None
            if self._rows == 0:
                return None
            per_feat = {self._idx[k]: psi(coarsen(ref),
                                          coarsen(self._counts[k]))
                        for k, ref in enumerate(self._ref) if ref.size}
            score_psi = psi(coarsen(self._score_ref.get("counts", [])),
                            coarsen(self._score_counts)) \
                if self._score_ref.get("counts") else 0.0
            psi_max = max(list(per_feat.values()) + [score_psi], default=0.0)
            over = psi_max > self.psi_threshold
            if over:
                self._over += 1
            else:
                self._over = 0
                self._latched = False
            alert = False
            if self._over >= self.hysteresis and not self._latched:
                self._latched = True
                self.alerts += 1
                alert = True
            self.evaluations += 1
            self._rows_since_eval = 0
            self.last = {"psi": per_feat, "score_psi": score_psi,
                         "psi_max": psi_max, "rows": self._rows,
                         "alert": alert, "over_count": self._over}
            return dict(self.last)


# ---------------------------------------------------------------------
# ingest-side mapper drift (per-chunk, against the frozen mappers)
def chunk_mapper_drift(mappers, used_features, Xf: np.ndarray
                       ) -> Dict[str, Any]:
    """Diff one raw ingest chunk against the frozen mappers: fraction
    of finite values outside a numeric mapper's [min, max] training
    range, and the unseen-category rate for categorical mappers.
    Pure numpy over the chunk the pipeline already holds."""
    from ..binning import mapper_drift_counts

    out = new_cat = total = 0
    worst_rate, worst_feat = 0.0, -1
    for j in used_features:
        if j >= Xf.shape[1]:
            continue
        o, nc, n = mapper_drift_counts(mappers[j], Xf[:, j])
        out += o
        new_cat += nc
        total += n
        rate = (o + nc) / n if n else 0.0
        if rate > worst_rate:
            worst_rate, worst_feat = rate, int(j)
    return {"rows": int(Xf.shape[0]),
            "out_of_range": int(out), "new_categories": int(new_cat),
            "values": int(total),
            "out_of_range_rate": out / total if total else 0.0,
            "new_category_rate": new_cat / total if total else 0.0,
            "worst_feature": worst_feat,
            "worst_rate": round(worst_rate, 6)}
