"""Request-scoped serving traces.

A serving request is invisible today between ``PredictionService
.submit()`` and its future resolving: the batcher coalesces it, the
engine buckets and dispatches it, and nothing ties the pieces back to
THE request an operator is debugging.  This module supplies the thread
of identity:

- :func:`mint_trace_id` — 16-hex-char id stamped on the request at
  ``submit()`` (also exposed as ``future.trace_id`` so callers can
  quote it in their own logs);
- a worker-thread batch context (:func:`begin_batch` /
  :func:`annotate` / :func:`end_batch`) the engine annotates from
  INSIDE the dispatch (bucket size, device dispatch wall,
  compile-on-this-call, host-walk degradation) without the batcher and
  engine knowing each other's internals;
- :func:`emit_access` — exactly one structured ``serve_access`` JSONL
  record per request (trace_id, model_id, rows, queue_ms, batch_ms,
  dispatch_ms, bucket, degraded) plus a Perfetto span on the ``serve``
  track whose ``trace_id`` arg matches the record, so the JSONL line
  and the timeline view are two projections of the same request.

The batch context is a plain thread-local: the micro-batcher owns ONE
worker thread, and the engine's dispatch runs inside it — no locking,
and a second service in the same process gets its own worker and its
own context.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Dict, Optional

_tls = threading.local()


def mint_trace_id() -> str:
    """16 hex chars of OS entropy — unique per request, short enough to
    grep."""
    return os.urandom(8).hex()


# ------------------------------------------------------- batch context
def begin_batch(model_id: str,
                device: Optional[int] = None) -> Dict[str, Any]:
    ctx = {"model_id": str(model_id), "bucket": None,
           "dispatch_ms": 0.0, "dispatches": 0, "compiles": 0,
           "degraded": False, "model_version": None,
           # fleet lane index (None on a single-device batcher): which
           # device replica served this batch — the serve_access field
           # the fleet: summary's per-device request share reads
           "device": device}
    _tls.batch = ctx
    return ctx


def current() -> Optional[Dict[str, Any]]:
    return getattr(_tls, "batch", None)


def begin_shadow() -> None:
    """Suppress annotate() while a rollover candidate scores mirrored
    traffic on the worker thread: the shadow engine's dispatch facts
    (dispatch_ms, bucket, model_version) must not overwrite the LIVE
    request's context — the live response came from the serving
    engine, and its trace must say so."""
    _tls.shadow = True


def end_shadow() -> None:
    _tls.shadow = False


def annotate(**attrs: Any) -> None:
    """Merge engine-side facts into the open batch context (no-op when
    no batch is open — the engine also serves ``Booster.predict`` style
    direct calls that carry no request identity — or while a shadow
    engine is scoring mirrored traffic)."""
    if getattr(_tls, "shadow", False):
        return
    ctx = current()
    if ctx is None:
        return
    for k, v in attrs.items():
        if k in ("dispatch_ms", "dispatches", "compiles"):
            ctx[k] = ctx.get(k, 0) + v      # accumulate across chunks
        else:
            ctx[k] = v


def end_batch() -> Dict[str, Any]:
    ctx = current() or {}
    _tls.batch = None
    return ctx


# ------------------------------------------------------------ emission
def emit_access(tel, req, ctx: Dict[str, Any], queue_ms: float,
                batch_ms: float, done_wall: float) -> None:
    """One ``serve_access`` record + one ``serve``-track span for one
    finished request.  ``req`` is the batcher's request (trace_id,
    model_id, rows, wall-clock submit); ``ctx`` is the engine-annotated
    batch context shared by the request's batch."""
    if tel is None or not tel.enabled:
        return
    bucket = ctx.get("bucket")
    degraded = bool(ctx.get("degraded", False))
    dispatch_ms = round(float(ctx.get("dispatch_ms", 0.0)), 3)
    extra = {}
    if ctx.get("error"):
        extra["error"] = str(ctx["error"])   # failed requests trace too
    if ctx.get("model_version"):
        # rollover attribution: which packed model state produced THIS
        # response (the rollover-under-load test's exactly-one-version
        # contract reads this field)
        extra["model_version"] = str(ctx["model_version"])
    if ctx.get("shadow_divergence") is not None:
        extra["shadow_divergence"] = float(ctx["shadow_divergence"])
    if ctx.get("device") is not None:
        extra["device"] = int(ctx["device"])
    tel.inc("serve.access_records")
    tel.event("serve_access", trace_id=req.trace_id,
              model_id=req.model_id, rows=int(req.rows),
              queue_ms=round(float(queue_ms), 3),
              batch_ms=round(float(batch_ms), 3),
              dispatch_ms=dispatch_ms,
              bucket=None if bucket is None else int(bucket),
              degraded=degraded, **extra)
    tel.span("request", req.w_submit, max(0.0, done_wall - req.w_submit),
             track="serve", trace_id=req.trace_id,
             model_id=req.model_id, rows=int(req.rows),
             queue_ms=round(float(queue_ms), 3),
             dispatch_ms=dispatch_ms,
             bucket=None if bucket is None else int(bucket),
             degraded=degraded)
